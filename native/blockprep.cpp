// fabric-tpu native host path: whole-block transaction preparation.
//
// One C call turns a block's envelope list into flat arrays: protobuf
// wire-format field extraction down the envelope -> payload -> header
// -> transaction -> action -> endorsement chain, SHA-256 digest lanes
// (creator payload digest, per-endorsement prp||endorser digest, txid
// binding), identity deduplication, and DER signature staging (via
// batchprep.cpp's Montgomery batch inversion). This is the host-side
// 90% that round 3 measured between the wire and the device
// (fabric_tpu/core/txvalidator.py phase 1 + the provider's per-item
// staging loop) executed natively in one pass.
//
// Reference analog: `core/committer/txvalidator/v20/validator.go`
// spreads this across goroutines (per-tx proto unmarshals +
// per-signature crypto); here the whole block is one call so the TPU
// dispatch sees ready-made operand arrays.
//
// SEMANTICS CONTRACT (differential-tested): this parser is
// *optimistic*. It fully decides a transaction only when the envelope
// chain parses CLEANLY: every field is a known number with the
// expected wire type, singular fields appear once, strings are valid
// UTF-8, nested messages that upb would parse eagerly parse here too.
// Anything else returns BP_NEEDS_PYTHON for that tx and the Python
// validator (the semantic oracle) decides it — so adversarial or
// non-canonical encodings cost fallback time, never correctness.
//
// Build: compiled together with batchprep.cpp into libbatchprep.so
// (fabric_tpu/native/__init__.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

#if defined(__x86_64__) || defined(__i386__)
#include <cpuid.h>
#include <immintrin.h>
#define FTPU_X86 1
#endif

// from batchprep.cpp
extern "C" void ftpu_batch_prep_ptrs(const uint8_t *const *ptrs,
                                     const int32_t *lens, int32_t n,
                                     uint8_t *r_out, uint8_t *rpn_out,
                                     uint8_t *w_out, uint8_t *ok_out);

namespace {

// ---------------- SHA-256 (FIPS 180-4) ----------------

const uint32_t SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
    0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
    0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
    0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
    0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
    0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
    0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
    0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
    0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t x, int k) {
    return (x >> k) | (x << (32 - k));
}

#ifdef FTPU_X86
// SHA-NI block transform (Intel SHA extensions): ~10x the scalar
// schedule. Selected at runtime via __builtin_cpu_supports; the
// digest-lane workload (payload + prp||endorser hashing) is the
// single biggest native cost without it.
__attribute__((target("sha,sse4.1")))
void sha256_transform_ni(uint32_t state[8], const uint8_t *data,
                         size_t nblocks) {
    const __m128i MASK = _mm_set_epi64x(
        (long long)0x0c0d0e0f08090a0bULL,
        (long long)0x0405060700010203ULL);
    __m128i TMP = _mm_loadu_si128((const __m128i *)&state[0]);
    __m128i STATE1 = _mm_loadu_si128((const __m128i *)&state[4]);
    TMP = _mm_shuffle_epi32(TMP, 0xB1);          // CDAB
    STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);    // EFGH
    __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);  // ABEF
    STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);       // CDGH

    while (nblocks--) {
        __m128i ABEF_SAVE = STATE0, CDGH_SAVE = STATE1;
        __m128i MSG, MSG0, MSG1, MSG2, MSG3;

        MSG0 = _mm_shuffle_epi8(
            _mm_loadu_si128((const __m128i *)(data + 0)), MASK);
        MSG = _mm_add_epi32(MSG0, _mm_set_epi64x(
            (long long)0xE9B5DBA5B5C0FBCFULL,
            (long long)0x71374491428A2F98ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

        MSG1 = _mm_shuffle_epi8(
            _mm_loadu_si128((const __m128i *)(data + 16)), MASK);
        MSG = _mm_add_epi32(MSG1, _mm_set_epi64x(
            (long long)0xAB1C5ED5923F82A4ULL,
            (long long)0x59F111F13956C25BULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

        MSG2 = _mm_shuffle_epi8(
            _mm_loadu_si128((const __m128i *)(data + 32)), MASK);
        MSG = _mm_add_epi32(MSG2, _mm_set_epi64x(
            (long long)0x550C7DC3243185BEULL,
            (long long)0x12835B01D807AA98ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

        MSG3 = _mm_shuffle_epi8(
            _mm_loadu_si128((const __m128i *)(data + 48)), MASK);
        MSG = _mm_add_epi32(MSG3, _mm_set_epi64x(
            (long long)0xC19BF1749BDC06A7ULL,
            (long long)0x80DEB1FE72BE5D74ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        __m128i TMP4 = _mm_alignr_epi8(MSG3, MSG2, 4);
        MSG0 = _mm_add_epi32(MSG0, TMP4);
        MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

        MSG = _mm_add_epi32(MSG0, _mm_set_epi64x(
            (long long)0x240CA1CC0FC19DC6ULL,
            (long long)0xEFBE4786E49B69C1ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG0, MSG3, 4);
        MSG1 = _mm_add_epi32(MSG1, TMP4);
        MSG1 = _mm_sha256msg2_epu32(MSG1, MSG0);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);

        MSG = _mm_add_epi32(MSG1, _mm_set_epi64x(
            (long long)0x76F988DA5CB0A9DCULL,
            (long long)0x4A7484AA2DE92C6FULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG1, MSG0, 4);
        MSG2 = _mm_add_epi32(MSG2, TMP4);
        MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

        MSG = _mm_add_epi32(MSG2, _mm_set_epi64x(
            (long long)0xBF597FC7B00327C8ULL,
            (long long)0xA831C66D983E5152ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG2, MSG1, 4);
        MSG3 = _mm_add_epi32(MSG3, TMP4);
        MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

        MSG = _mm_add_epi32(MSG3, _mm_set_epi64x(
            (long long)0x1429296706CA6351ULL,
            (long long)0xD5A79147C6E00BF3ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG3, MSG2, 4);
        MSG0 = _mm_add_epi32(MSG0, TMP4);
        MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

        MSG = _mm_add_epi32(MSG0, _mm_set_epi64x(
            (long long)0x53380D134D2C6DFCULL,
            (long long)0x2E1B213827B70A85ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG0, MSG3, 4);
        MSG1 = _mm_add_epi32(MSG1, TMP4);
        MSG1 = _mm_sha256msg2_epu32(MSG1, MSG0);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);

        MSG = _mm_add_epi32(MSG1, _mm_set_epi64x(
            (long long)0x92722C8581C2C92EULL,
            (long long)0x766A0ABB650A7354ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG1, MSG0, 4);
        MSG2 = _mm_add_epi32(MSG2, TMP4);
        MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

        MSG = _mm_add_epi32(MSG2, _mm_set_epi64x(
            (long long)0xC76C51A3C24B8B70ULL,
            (long long)0xA81A664BA2BFE8A1ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG2, MSG1, 4);
        MSG3 = _mm_add_epi32(MSG3, TMP4);
        MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

        MSG = _mm_add_epi32(MSG3, _mm_set_epi64x(
            (long long)0x106AA070F40E3585ULL,
            (long long)0xD6990624D192E819ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG3, MSG2, 4);
        MSG0 = _mm_add_epi32(MSG0, TMP4);
        MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

        MSG = _mm_add_epi32(MSG0, _mm_set_epi64x(
            (long long)0x34B0BCB52748774CULL,
            (long long)0x1E376C0819A4C116ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG0, MSG3, 4);
        MSG1 = _mm_add_epi32(MSG1, TMP4);
        MSG1 = _mm_sha256msg2_epu32(MSG1, MSG0);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);

        MSG = _mm_add_epi32(MSG1, _mm_set_epi64x(
            (long long)0x682E6FF35B9CCA4FULL,
            (long long)0x4ED8AA4A391C0CB3ULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG1, MSG0, 4);
        MSG2 = _mm_add_epi32(MSG2, TMP4);
        MSG2 = _mm_sha256msg2_epu32(MSG2, MSG1);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

        MSG = _mm_add_epi32(MSG2, _mm_set_epi64x(
            (long long)0x8CC7020884C87814ULL,
            (long long)0x78A5636F748F82EEULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        TMP4 = _mm_alignr_epi8(MSG2, MSG1, 4);
        MSG3 = _mm_add_epi32(MSG3, TMP4);
        MSG3 = _mm_sha256msg2_epu32(MSG3, MSG2);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

        MSG = _mm_add_epi32(MSG3, _mm_set_epi64x(
            (long long)0xC67178F2BEF9A3F7ULL,
            (long long)0xA4506CEB90BEFFFAULL));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

        STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
        STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
        data += 64;
    }

    TMP = _mm_shuffle_epi32(STATE0, 0x1B);       // FEBA
    STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);    // DCHG
    STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0); // DCBA
    STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);    // HGFE
    _mm_storeu_si128((__m128i *)&state[0], STATE0);
    _mm_storeu_si128((__m128i *)&state[4], STATE1);
}

bool sha_ni_supported() {
    // __builtin_cpu_supports("sha") only exists on gcc >= 11; probe
    // CPUID directly (leaf 7 EBX bit 29 = SHA-NI, leaf 1 ECX bit 19 =
    // SSE4.1) so the library still builds on older toolchains —
    // without this the WHOLE native prep layer silently fell back to
    // Python on gcc 10 hosts
    unsigned int eax, ebx, ecx, edx;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx))
        return false;
    if (!(ebx & (1u << 29)))
        return false;
    if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx))
        return false;
    return (ecx & (1u << 19)) != 0;
}
#else
bool sha_ni_supported() { return false; }
void sha256_transform_ni(uint32_t *, const uint8_t *, size_t) {}
#endif

const bool USE_SHA_NI = sha_ni_supported();

struct Sha256 {
    uint32_t h[8];
    uint8_t buf[64];
    uint64_t total;
    size_t fill;

    void init() {
        h[0] = 0x6a09e667; h[1] = 0xbb67ae85; h[2] = 0x3c6ef372;
        h[3] = 0xa54ff53a; h[4] = 0x510e527f; h[5] = 0x9b05688c;
        h[6] = 0x1f83d9ab; h[7] = 0x5be0cd19;
        total = 0;
        fill = 0;
    }

    void transform(const uint8_t *p) {
        uint32_t w[64];
        for (int i = 0; i < 16; ++i)
            w[i] = (uint32_t)p[4 * i] << 24 |
                   (uint32_t)p[4 * i + 1] << 16 |
                   (uint32_t)p[4 * i + 2] << 8 | p[4 * i + 3];
        for (int i = 16; i < 64; ++i) {
            uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^
                          (w[i - 15] >> 3);
            uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^
                          (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4],
                 f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 64; ++i) {
            uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + SHA_K[i] + w[i];
            uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + mj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void transform_blocks(const uint8_t *p, size_t k) {
        if (USE_SHA_NI) {
            sha256_transform_ni(h, p, k);
            return;
        }
        while (k--) {
            transform(p);
            p += 64;
        }
    }

    void update(const uint8_t *p, size_t n) {
        total += n;
        if (fill) {
            size_t take = 64 - fill;
            if (take > n) take = n;
            memcpy(buf + fill, p, take);
            fill += take;
            p += take;
            n -= take;
            if (fill == 64) {
                transform_blocks(buf, 1);
                fill = 0;
            }
        }
        if (n >= 64) {
            size_t k = n / 64;
            transform_blocks(p, k);
            p += k * 64;
            n -= k * 64;
        }
        if (n) {
            memcpy(buf, p, n);
            fill = n;
        }
    }

    void final(uint8_t out[32]) {
        uint64_t bits = total * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t z = 0;
        while (fill != 56) update(&z, 1);
        uint8_t lenb[8];
        for (int i = 0; i < 8; ++i)
            lenb[i] = (uint8_t)(bits >> (56 - 8 * i));
        update(lenb, 8);
        for (int i = 0; i < 8; ++i) {
            out[4 * i] = (uint8_t)(h[i] >> 24);
            out[4 * i + 1] = (uint8_t)(h[i] >> 16);
            out[4 * i + 2] = (uint8_t)(h[i] >> 8);
            out[4 * i + 3] = (uint8_t)h[i];
        }
    }
};

void sha256_one(const uint8_t *p, size_t n, uint8_t out[32]) {
    Sha256 s;
    s.init();
    s.update(p, n);
    s.final(out);
}

// ---------------- protobuf wire scanning ----------------

struct Slice {
    const uint8_t *p;
    int64_t n;
};

const Slice NIL = {nullptr, 0};

// <= 10 bytes, canonical 64-bit range (10th byte must be 0x01 or the
// encoding exceeds 64 bits -> not clean)
bool read_varint(const Slice &in, int64_t &pos, uint64_t &val) {
    uint64_t v = 0;
    int shift = 0;
    for (int i = 0; i < 10; ++i) {
        if (pos >= in.n) return false;
        uint8_t b = in.p[pos++];
        if (i == 9 && b > 0x01) return false;
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            val = v;
            return true;
        }
        shift += 7;
    }
    return false;
}

bool read_len_delim(const Slice &in, int64_t &pos, Slice &out) {
    uint64_t len;
    if (!read_varint(in, pos, len)) return false;
    if (len > (uint64_t)(in.n - pos)) return false;
    out.p = in.p + pos;
    out.n = (int64_t)len;
    pos += (int64_t)len;
    return true;
}

// strict UTF-8 (what upb enforces on proto3 string fields): no
// overlongs, no surrogates, max U+10FFFF
bool valid_utf8(const Slice &s) {
    int64_t i = 0;
    while (i < s.n) {
        uint8_t c = s.p[i];
        if (c < 0x80) {
            ++i;
        } else if ((c & 0xE0) == 0xC0) {
            if (i + 1 >= s.n || (s.p[i + 1] & 0xC0) != 0x80) return false;
            if (c < 0xC2) return false;  // overlong
            i += 2;
        } else if ((c & 0xF0) == 0xE0) {
            if (i + 2 >= s.n || (s.p[i + 1] & 0xC0) != 0x80 ||
                (s.p[i + 2] & 0xC0) != 0x80)
                return false;
            uint32_t cp = ((uint32_t)(c & 0x0F) << 12) |
                          ((uint32_t)(s.p[i + 1] & 0x3F) << 6) |
                          (s.p[i + 2] & 0x3F);
            if (cp < 0x800 || (cp >= 0xD800 && cp <= 0xDFFF))
                return false;
            i += 3;
        } else if ((c & 0xF8) == 0xF0) {
            if (i + 3 >= s.n || (s.p[i + 1] & 0xC0) != 0x80 ||
                (s.p[i + 2] & 0xC0) != 0x80 ||
                (s.p[i + 3] & 0xC0) != 0x80)
                return false;
            uint32_t cp = ((uint32_t)(c & 0x07) << 18) |
                          ((uint32_t)(s.p[i + 1] & 0x3F) << 12) |
                          ((uint32_t)(s.p[i + 2] & 0x3F) << 6) |
                          (s.p[i + 3] & 0x3F);
            if (cp < 0x10000 || cp > 0x10FFFF) return false;
            i += 4;
        } else {
            return false;
        }
    }
    return true;
}

// Generic clean scan of a message whose fields are all singular.
// kinds[f] for f in 1..maxf: 'v' varint, 'l' length-delimited,
// 's' length-delimited UTF-8 string, 0 = unknown (fail).
// Returns 1 on clean parse; slices/ints indexed by field number.
int scan_msg(const Slice &in, const char *kinds, int maxf,
             Slice *slices, uint64_t *ints) {
    uint32_t seen = 0;
    int64_t pos = 0;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        if (f < 1 || f > (uint64_t)maxf) return 0;
        char k = kinds[f];
        if (k == 0) return 0;
        if (seen & (1u << f)) return 0;
        seen |= 1u << f;
        if (k == 'v') {
            uint64_t v;
            if (wt != 0 || !read_varint(in, pos, v)) return 0;
            if (ints) ints[f] = v;
        } else {  // 'l' or 's'
            Slice s;
            if (wt != 2 || !read_len_delim(in, pos, s)) return 0;
            if (k == 's' && !valid_utf8(s)) return 0;
            if (slices) slices[f] = s;
        }
    }
    return 1;
}

// ---- message shapes (field kinds indexed by field number) ----
// fabric_tpu/protos/common.proto, transaction.proto, proposal.proto

const char K_ENVELOPE[] = {0, 'l', 'l'};                  // payload, sig
const char K_PAYLOAD[] = {0, 'l', 'l'};                   // header(msg), data
const char K_HEADER[] = {0, 'l', 'l'};                    // chdr, shdr
// type, version, timestamp, channel_id, tx_id, epoch, ext, tls_hash
const char K_CHANNEL_HDR[] = {0, 'v', 'v', 'v', 's', 's', 'v', 'l', 'l'};
const char K_SIG_HDR[] = {0, 'l', 'l'};                   // creator, nonce
const char K_TX_ACTION[] = {0, 'l', 'l'};                 // header, payload
const char K_CAP[] = {0, 'l', 'l'};          // cc_proposal_payload, action(msg)
const char K_ENDORSEMENT[] = {0, 'l', 'l'};               // endorser, sig
const char K_PRP[] = {0, 'l', 'l'};                       // hash, extension
const char K_CC_ACTION[] = {0, 'l', 'l', 'l', 'l'};  // results, events, resp, id
const char K_RESPONSE[] = {0, 'v', 's', 'l'};         // status, message, payload
const char K_CHAINCODE_ID[] = {0, 's', 's', 's'};     // name, version, path

// ---- rwset scanning (fabric_tpu/protos/rwset.proto) ----
//
// Mirrors what the VSCC's extract_write_info touches: upb eagerly
// parses TxReadWriteSet / NsReadWriteSet / CollectionHashedReadWriteSet
// when ChaincodeAction.results is unmarshaled; the per-ns KVRWSet bytes
// are parsed only for the tx's own chaincode namespace. rw_mode output:
//   1 = clean + PLAIN: only non-delete public writes in the matching
//       namespace, no metadata writes, no collections — the written
//       keys are fully captured in the flat key table.
//   2 = clean + RICH: parses fine but has features (deletes, metadata,
//       collections, >MAX_K keys) the Python path must walk.
//   3 = NOT clean: the Python parser decides (and may reject).

const int MAX_K = 16;            // plain written keys per tx

const char K_VERSION[] = {0, 'v', 'v'};
const char K_KVWRITE[] = {0, 's', 'v', 'l'};
const char K_MERKLE[] = {0, 'v', 'v', 0};   // field 3 repeated, custom

int scan_kvread(const Slice &in) {
    int64_t pos = 0;
    bool seen1 = false, seen2 = false;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        Slice s;
        if (f == 1 && wt == 2) {
            if (seen1) return 0;
            seen1 = true;
            if (!read_len_delim(in, pos, s) || !valid_utf8(s)) return 0;
        } else if (f == 2 && wt == 2) {
            if (seen2) return 0;
            seen2 = true;
            if (!read_len_delim(in, pos, s)) return 0;
            if (!scan_msg(s, K_VERSION, 2, nullptr, nullptr)) return 0;
        } else {
            return 0;
        }
    }
    return 1;
}

int scan_query_reads(const Slice &in) {
    int64_t pos = 0;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        if ((tag >> 3) != 1 || (tag & 7) != 2) return 0;
        Slice s;
        if (!read_len_delim(in, pos, s)) return 0;
        if (!scan_kvread(s)) return 0;
    }
    return 1;
}

int scan_merkle(const Slice &in) {
    int64_t pos = 0;
    bool seen1 = false, seen2 = false;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        if (f == 1 && wt == 0) {
            if (seen1) return 0;
            seen1 = true;
            uint64_t v;
            if (!read_varint(in, pos, v)) return 0;
        } else if (f == 2 && wt == 0) {
            if (seen2) return 0;
            seen2 = true;
            uint64_t v;
            if (!read_varint(in, pos, v)) return 0;
        } else if (f == 3 && wt == 2) {
            Slice s;
            if (!read_len_delim(in, pos, s)) return 0;
        } else {
            return 0;
        }
    }
    return 1;
}

int scan_range_query(const Slice &in) {
    int64_t pos = 0;
    uint32_t seen = 0;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        if (f < 1 || f > 5) return 0;
        if (seen & (1u << f)) return 0;
        seen |= 1u << f;
        if (f == 3) {
            uint64_t v;
            if (wt != 0 || !read_varint(in, pos, v)) return 0;
            continue;
        }
        Slice s;
        if (wt != 2 || !read_len_delim(in, pos, s)) return 0;
        if (f <= 2 && !valid_utf8(s)) return 0;
        if (f == 4 && !scan_query_reads(s)) return 0;
        if (f == 5 && !scan_merkle(s)) return 0;
    }
    return 1;
}

// KVMetadataWrite / KVMetadataWriteHash: key/key_hash + entries
int scan_metadata_write(const Slice &in, bool key_is_string) {
    int64_t pos = 0;
    bool seen1 = false;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        Slice s;
        if (wt != 2 || f < 1 || f > 2) return 0;
        if (f == 1) {
            if (seen1) return 0;
            seen1 = true;
            if (!read_len_delim(in, pos, s)) return 0;
            if (key_is_string && !valid_utf8(s)) return 0;
        } else {
            if (!read_len_delim(in, pos, s)) return 0;
            // KVMetadataEntry {name=1 string, value=2 bytes}
            const char K_ENTRY[] = {0, 's', 'l'};
            if (!scan_msg(s, K_ENTRY, 2, nullptr, nullptr)) return 0;
        }
    }
    return 1;
}

// KVRWSet for the matching namespace. Collects plain write keys;
// flags rich features.
int scan_kvrwset(const Slice &in, std::vector<Slice> &keys,
                 bool &rich) {
    int64_t pos = 0;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        if (wt != 2 || f < 1 || f > 4) return 0;
        Slice s;
        if (!read_len_delim(in, pos, s)) return 0;
        if (f == 1) {
            if (!scan_kvread(s)) return 0;
        } else if (f == 2) {
            if (!scan_range_query(s)) return 0;
        } else if (f == 3) {
            Slice ws[4] = {NIL, NIL, NIL, NIL};
            uint64_t wi[4] = {0};
            if (!scan_msg(s, K_KVWRITE, 3, ws, wi)) return 0;
            if (wi[2] != 0) rich = true;   // is_delete -> vp_updates
            keys.push_back(ws[1]);
            if ((int)keys.size() > MAX_K) rich = true;
        } else {
            if (!scan_metadata_write(s, true)) return 0;
            rich = true;                   // metadata writes
        }
    }
    return 1;
}

// HashedRWSet (collections of the matching namespace): cleanliness
// only — any hashed content at all is rich.
int scan_hashed_rwset(const Slice &in) {
    int64_t pos = 0;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        if (wt != 2 || f < 1 || f > 3) return 0;
        Slice s;
        if (!read_len_delim(in, pos, s)) return 0;
        if (f == 1) {
            // KVReadHash {key_hash bytes, version msg}
            int64_t p2 = 0;
            bool sk = false, sv = false;
            while (p2 < s.n) {
                uint64_t t2;
                if (!read_varint(s, p2, t2)) return 0;
                uint64_t f2 = t2 >> 3;
                Slice s2;
                if ((t2 & 7) != 2 || f2 < 1 || f2 > 2) return 0;
                if (!read_len_delim(s, p2, s2)) return 0;
                if (f2 == 1) {
                    if (sk) return 0;
                    sk = true;
                } else {
                    if (sv) return 0;
                    sv = true;
                    if (!scan_msg(s2, K_VERSION, 2, nullptr, nullptr))
                        return 0;
                }
            }
        } else if (f == 2) {
            const char K_WH[] = {0, 'l', 'v', 'l'};
            if (!scan_msg(s, K_WH, 3, nullptr, nullptr)) return 0;
        } else {
            if (!scan_metadata_write(s, false)) return 0;
        }
    }
    return 1;
}

// ChaincodeAction.results: returns rw_mode (1 plain / 2 rich / 3 not
// clean) and fills `keys` for plain txs.
int scan_results(const Slice &results, const Slice &ccname,
                 std::vector<Slice> &keys) {
    bool rich = false;
    int64_t pos = 0;
    bool seen_dm = false;
    while (pos < results.n) {
        uint64_t tag;
        if (!read_varint(results, pos, tag)) return 3;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        if (f == 1 && wt == 0) {
            if (seen_dm) return 3;
            seen_dm = true;
            uint64_t v;
            if (!read_varint(results, pos, v)) return 3;
        } else if (f == 2 && wt == 2) {
            Slice nsrw;
            if (!read_len_delim(results, pos, nsrw)) return 3;
            // NsReadWriteSet {namespace=1 s, rwset=2 l, colls=3 rep}
            Slice ns = NIL, kvr = NIL;
            std::vector<Slice> colls;
            int64_t p2 = 0;
            bool s1 = false, s2 = false;
            while (p2 < nsrw.n) {
                uint64_t t2;
                if (!read_varint(nsrw, p2, t2)) return 3;
                uint64_t f2 = t2 >> 3;
                Slice sl;
                if ((t2 & 7) != 2 || f2 < 1 || f2 > 3) return 3;
                if (!read_len_delim(nsrw, p2, sl)) return 3;
                if (f2 == 1) {
                    if (s1) return 3;
                    s1 = true;
                    if (!valid_utf8(sl)) return 3;
                    ns = sl;
                } else if (f2 == 2) {
                    if (s2) return 3;
                    s2 = true;
                    kvr = sl;
                } else {
                    // CollectionHashedReadWriteSet {1 s, 2 l, 3 l}
                    const char K_COLL[] = {0, 's', 'l', 'l'};
                    Slice cf[4] = {NIL, NIL, NIL, NIL};
                    if (!scan_msg(sl, K_COLL, 3, cf, nullptr)) return 3;
                    colls.push_back(cf[2]);
                }
            }
            bool match = ns.n == ccname.n &&
                         (ns.n == 0 ||
                          memcmp(ns.p, ccname.p, (size_t)ns.n) == 0);
            if (!match) continue;
            if (!scan_kvrwset(kvr, keys, rich)) return 3;
            for (const Slice &c : colls) {
                if (!scan_hashed_rwset(c)) return 3;
                rich = true;   // any collection content -> python walk
            }
            if (!colls.empty()) rich = true;
        } else {
            return 3;
        }
    }
    return rich ? 2 : 1;
}

// Transaction: repeated actions (field 1). Each action must scan
// cleanly (upb parses every nested TransactionAction eagerly); only
// action[0]'s contents are used downstream (validator semantics).
int scan_transaction(const Slice &in, Slice &action0, int64_t &count) {
    count = 0;
    int64_t pos = 0;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        if ((tag >> 3) != 1 || (tag & 7) != 2) return 0;
        Slice a;
        if (!read_len_delim(in, pos, a)) return 0;
        if (!scan_msg(a, K_TX_ACTION, 2, nullptr, nullptr)) return 0;
        if (count == 0) action0 = a;
        ++count;
    }
    return 1;
}

// ChaincodeEndorsedAction: prp (1, bytes), repeated endorsements (2).
int scan_endorsed_action(const Slice &in, Slice &prp,
                         std::vector<Slice> &endorsers,
                         std::vector<Slice> &esigs) {
    prp = NIL;
    bool seen_prp = false;
    int64_t pos = 0;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return 0;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        if (wt != 2) return 0;
        if (f == 1) {
            if (seen_prp) return 0;
            seen_prp = true;
            if (!read_len_delim(in, pos, prp)) return 0;
        } else if (f == 2) {
            Slice e;
            if (!read_len_delim(in, pos, e)) return 0;
            Slice fs[3] = {NIL, NIL, NIL};
            if (!scan_msg(e, K_ENDORSEMENT, 2, fs, nullptr)) return 0;
            endorsers.push_back(fs[1]);
            esigs.push_back(fs[2]);
        } else {
            return 0;
        }
    }
    return 1;
}

// ---------------- status codes ----------------

enum {
    BP_OK_ENDORSER = 0,
    BP_OK_CONFIG = 1,
    BP_NEEDS_PYTHON = 2,
    BP_FAIL_BASE = 100,  // + TxValidationCode
};

// TxValidationCode values (fabric_tpu/protos/transaction.proto)
enum {
    TVC_NIL_ENVELOPE = 1,
    TVC_BAD_COMMON_HEADER = 3,
    TVC_INVALID_ENDORSER = 5,
    TVC_UNSUPPORTED_TX_PAYLOAD = 7,
    TVC_BAD_PROPOSAL_TXID = 8,
    TVC_NIL_TXACTION = 16,
    TVC_BAD_CHANNEL_HEADER = 20,
};

enum {  // common.HeaderType
    HDR_CONFIG = 1,
    HDR_ENDORSER_TRANSACTION = 3,
};

// ---------------- per-tx parse ----------------

struct TxOut {
    int32_t status = BP_NEEDS_PYTHON;
    Slice creator = NIL, csig = NIL, payload = NIL;
    Slice txid = NIL, config = NIL, ccname = NIL, results = NIL;
    Slice prp = NIL;
    uint8_t payload_digest[32] = {0};
    std::vector<Slice> e_ident, e_sig;
    uint64_t creator_hash = 0;
    std::vector<uint64_t> e_hash;
    int32_t rw_mode = 0;
    std::vector<Slice> rw_keys;
};

uint64_t fnv1a(const Slice &s) {
    uint64_t h = 1469598103934665603ull;
    for (int64_t i = 0; i < s.n; ++i) {
        h ^= s.p[i];
        h *= 1099511628211ull;
    }
    return h;
}

const char HEXD[] = "0123456789abcdef";

void parse_tx(const Slice &env, const Slice &channel_id, int32_t max_e,
              TxOut &out) {
    Slice fs[3] = {NIL, NIL, NIL};
    if (!scan_msg(env, K_ENVELOPE, 2, fs, nullptr)) return;  // needs py
    Slice payload = fs[1], sig = fs[2];
    if (payload.n == 0) {
        out.status = BP_FAIL_BASE + TVC_NIL_ENVELOPE;
        return;
    }
    Slice pf[3] = {NIL, NIL, NIL};
    if (!scan_msg(payload, K_PAYLOAD, 2, pf, nullptr)) return;
    Slice header = pf[1], data = pf[2];
    Slice hf[3] = {NIL, NIL, NIL};
    if (!scan_msg(header, K_HEADER, 2, hf, nullptr)) return;
    Slice chf[9] = {NIL, NIL, NIL, NIL, NIL, NIL, NIL, NIL, NIL};
    uint64_t chi[9] = {0};
    if (!scan_msg(hf[1], K_CHANNEL_HDR, 8, chf, chi)) return;
    Slice shf[3] = {NIL, NIL, NIL};
    if (!scan_msg(hf[2], K_SIG_HDR, 2, shf, nullptr)) return;
    int64_t ch_type = (int64_t)(int32_t)chi[1];  // int32 varint
    Slice ch_channel = chf[4], ch_txid = chf[5];
    Slice creator = shf[1], nonce = shf[2];

    // decided structurally from here on (mirrors
    // core/msgvalidation.check_envelope order exactly)
    if (ch_channel.n != channel_id.n ||
        (ch_channel.n &&
         memcmp(ch_channel.p, channel_id.p, ch_channel.n) != 0)) {
        out.status = BP_FAIL_BASE + TVC_BAD_CHANNEL_HEADER;
        return;
    }
    if (creator.n == 0 || nonce.n == 0) {
        out.status = BP_FAIL_BASE + TVC_BAD_COMMON_HEADER;
        return;
    }
    out.creator = creator;
    out.csig = sig;
    out.payload = payload;
    out.txid = ch_txid;

    // creator identity interning must also cover txs that FAIL later
    // stages natively (empty prp / missing chaincode id): in reference
    // order those txs still pass the creator-identity check and claim
    // their txid before INVALID_ENDORSER_TRANSACTION is assigned
    out.creator_hash = fnv1a(creator);

    if (ch_type == HDR_CONFIG) {
        out.config = data;
        // a zero-length Payload.data is still a parseable (empty)
        // ConfigEnvelope downstream; keep parity with python by
        // pointing config at the data slice either way
        sha256_one(payload.p, (size_t)payload.n, out.payload_digest);
        out.status = BP_OK_CONFIG;
        return;
    }
    if (ch_type != HDR_ENDORSER_TRANSACTION) {
        out.status = BP_FAIL_BASE + TVC_UNSUPPORTED_TX_PAYLOAD;
        return;
    }

    // txid binding: hex(sha256(nonce || creator)) must equal tx_id
    uint8_t tid[32];
    {
        Sha256 s;
        s.init();
        s.update(nonce.p, (size_t)nonce.n);
        s.update(creator.p, (size_t)creator.n);
        s.final(tid);
    }
    bool tid_ok = ch_txid.n == 64;
    for (int i = 0; tid_ok && i < 32; ++i) {
        if (ch_txid.p[2 * i] != HEXD[tid[i] >> 4] ||
            ch_txid.p[2 * i + 1] != HEXD[tid[i] & 0xF])
            tid_ok = false;
    }
    if (!tid_ok) {
        out.status = BP_FAIL_BASE + TVC_BAD_PROPOSAL_TXID;
        return;
    }

    Slice action0;
    int64_t n_actions;
    if (!scan_transaction(data, action0, n_actions)) return;
    if (n_actions == 0) {
        out.status = BP_FAIL_BASE + TVC_NIL_TXACTION;
        return;
    }
    Slice af[3] = {NIL, NIL, NIL};
    if (!scan_msg(action0, K_TX_ACTION, 2, af, nullptr)) return;
    // ChaincodeActionPayload (upb parses the nested endorsed action
    // + endorsements eagerly; mirror that before deciding anything)
    Slice capf[3] = {NIL, NIL, NIL};
    if (!scan_msg(af[2], K_CAP, 2, capf, nullptr)) return;
    Slice prp;
    std::vector<Slice> endorsers, esigs;
    if (!scan_endorsed_action(capf[2], prp, endorsers, esigs)) return;
    if ((int32_t)endorsers.size() > max_e) return;  // rare: python path
    if (prp.n == 0) {
        // "no proposal response payload"
        out.status = BP_FAIL_BASE + TVC_INVALID_ENDORSER;
        return;
    }
    Slice prpf[3] = {NIL, NIL, NIL};
    if (!scan_msg(prp, K_PRP, 2, prpf, nullptr)) return;
    Slice ccaf[5] = {NIL, NIL, NIL, NIL, NIL};
    if (!scan_msg(prpf[2], K_CC_ACTION, 4, ccaf, nullptr)) return;
    // nested Response + ChaincodeID must parse (upb eagerness)
    if (!scan_msg(ccaf[3], K_RESPONSE, 3, nullptr, nullptr)) return;
    Slice cidf[4] = {NIL, NIL, NIL, NIL};
    if (!scan_msg(ccaf[4], K_CHAINCODE_ID, 3, cidf, nullptr)) return;
    if (cidf[1].n == 0) {
        // "no chaincode id in chaincode action"
        out.status = BP_FAIL_BASE + TVC_INVALID_ENDORSER;
        return;
    }

    out.ccname = cidf[1];
    out.results = ccaf[1];
    out.prp = prp;
    out.rw_mode = scan_results(ccaf[1], cidf[1], out.rw_keys);
    out.e_ident = std::move(endorsers);
    out.e_sig = std::move(esigs);

    // digest lanes: creator signs the payload bytes; each endorser
    // signs prp || endorser (msp/identities.go:170 semantics, hashed
    // host-side exactly as the sw provider would)
    sha256_one(payload.p, (size_t)payload.n, out.payload_digest);
    out.e_hash.resize(out.e_ident.size());
    for (size_t j = 0; j < out.e_ident.size(); ++j)
        out.e_hash[j] = fnv1a(out.e_ident[j]);
    out.status = BP_OK_ENDORSER;
}

// endorsement digests are computed in the parallel phase too, but need
// the shared output buffer; kept separate from parse_tx
void endorse_digests(const TxOut &t, uint8_t *e_digest, int32_t max_e,
                     int64_t tx_index) {
    for (size_t j = 0; j < t.e_ident.size(); ++j) {
        Sha256 s;
        s.init();
        s.update(t.prp.p, (size_t)t.prp.n);
        s.update(t.e_ident[j].p, (size_t)t.e_ident[j].n);
        s.final(e_digest + (tx_index * max_e + (int64_t)j) * 32);
    }
}

// serial, deterministic identity dedup over precomputed hashes
struct Dedup {
    struct Entry {
        uint64_t h;
        Slice s;
        int32_t id;
    };
    std::vector<std::vector<Entry>> buckets;
    int32_t next_id = 0;

    Dedup() : buckets(1024) {}

    // returns the id; *is_new set when this call created it
    int32_t intern(const Slice &s, uint64_t h, bool *is_new) {
        *is_new = false;
        auto &b = buckets[h & 1023];
        for (const auto &e : b) {
            if (e.h == h && e.s.n == s.n &&
                (s.n == 0 ||
                 memcmp(e.s.p, s.p, (size_t)s.n) == 0))
                return e.id;
        }
        b.push_back({h, s, next_id});
        *is_new = true;
        return next_id++;
    }
};

void parallel_for(int64_t n, int nthreads,
                  const std::function<void(int64_t, int64_t)> &fn) {
    if (nthreads <= 1 || n < 64) {
        fn(0, n);
        return;
    }
    std::vector<std::thread> ts;
    int64_t chunk = (n + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; ++t) {
        int64_t lo = t * chunk, hi = lo + chunk;
        if (lo >= n) break;
        if (hi > n) hi = n;
        ts.emplace_back([&fn, lo, hi] { fn(lo, hi); });
    }
    for (auto &t : ts) t.join();
}

int env_threads() {
    const char *e = getenv("FTPU_NATIVE_THREADS");
    if (e && *e) {
        int v = atoi(e);
        if (v >= 1) return v;
    }
    unsigned hc = std::thread::hardware_concurrency();
    if (hc == 0) hc = 1;
    return (int)(hc > 8 ? 8 : hc);
}

}  // namespace

extern "C" {

// One call per block. Inputs: per-envelope pointers + lengths, the
// expected channel id, and max endorsements per tx the flat tables
// hold (beyond it: BP_NEEDS_PYTHON). All offsets in the output arrays
// are LOCAL to that tx's envelope buffer. Identity ids (creator_uid /
// e_uid) index the deduplicated identity table (uid_env, uid_off,
// uid_len — env index + local offset), -1 where absent.
//
// Signature staging (r/rpn/w/ok, 32-byte big-endian scalars) is
// filled for the creator signature ([n,32]) and each endorsement
// ([n,max_e,32]) via the Montgomery batch-inversion path.
//
// Returns the number of unique identities (>= 0), or -1 on invalid
// arguments.
int32_t ftpu_block_prep(
    const uint8_t *const *envs, const int64_t *env_lens, int32_t n,
    const uint8_t *channel_id, int32_t channel_id_len, int32_t max_e,
    // per-tx
    int32_t *status, int64_t *creator_off, int32_t *creator_len,
    int32_t *creator_uid, int64_t *csig_off, int32_t *csig_len,
    uint8_t *payload_digest,                       // [n,32]
    int64_t *txid_off, int32_t *txid_len,          // [n]
    int64_t *config_off, int32_t *config_len,      // [n]
    int64_t *ccname_off, int32_t *ccname_len,      // [n]
    int64_t *results_off, int32_t *results_len,    // [n]
    int64_t *prp_off, int32_t *prp_len,            // [n]
    int32_t *rw_mode, int32_t *rw_nkeys,           // [n]
    int64_t *rw_key_off, int32_t *rw_key_len,      // [n,MAX_K]
    int32_t *e_count,                              // [n]
    int64_t *e_ident_off, int32_t *e_ident_len,    // [n,max_e]
    int32_t *e_uid,                                // [n,max_e]
    int64_t *e_sig_off, int32_t *e_sig_len,        // [n,max_e]
    uint8_t *e_digest,                             // [n,max_e,32]
    // signature staging
    uint8_t *c_r, uint8_t *c_rpn, uint8_t *c_w, uint8_t *c_ok,  // [n,32]/[n]
    uint8_t *e_r, uint8_t *e_rpn, uint8_t *e_w, uint8_t *e_okf, // [n,max_e,..]
    // unique identity table, capacity n*(max_e+1)
    int32_t *uid_env, int64_t *uid_off, int32_t *uid_len) {
    if (n < 0 || max_e <= 0 || max_e > 64) return -1;
    std::vector<TxOut> txs(n);
    Slice chan = {channel_id, channel_id_len};

    parallel_for(n, env_threads(), [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) {
            Slice env = {envs[i], env_lens[i]};
            parse_tx(env, chan, max_e, txs[i]);
            endorse_digests(txs[i], e_digest, max_e, i);
        }
    });

    // serial phase: dedup identities, flatten offsets, stage sig lanes
    Dedup dd;
    std::vector<const uint8_t *> sig_ptrs;
    std::vector<int32_t> sig_lens, sig_lane;  // lane: tx*(max_e+1)+slot
    auto loc = [&](int64_t i, const Slice &s, int64_t *off_a,
                   int32_t *len_a, int64_t idx) {
        off_a[idx] = s.p ? (int64_t)(s.p - envs[i]) : 0;
        len_a[idx] = (int32_t)s.n;
    };
    for (int64_t i = 0; i < n; ++i) {
        TxOut &t = txs[i];
        status[i] = t.status;
        creator_uid[i] = -1;
        e_count[i] = 0;
        loc(i, t.creator, creator_off, creator_len, i);
        loc(i, t.csig, csig_off, csig_len, i);
        loc(i, t.txid, txid_off, txid_len, i);
        loc(i, t.config, config_off, config_len, i);
        loc(i, t.ccname, ccname_off, ccname_len, i);
        loc(i, t.results, results_off, results_len, i);
        loc(i, t.prp, prp_off, prp_len, i);
        rw_mode[i] = t.rw_mode;
        int32_t nk = t.rw_mode == 1 ? (int32_t)t.rw_keys.size() : 0;
        rw_nkeys[i] = nk;
        for (int32_t kk = 0; kk < nk; ++kk)
            loc(i, t.rw_keys[kk], rw_key_off, rw_key_len,
                i * MAX_K + kk);
        memcpy(payload_digest + 32 * i, t.payload_digest, 32);
        bool ok_status = t.status == BP_OK_ENDORSER ||
                         t.status == BP_OK_CONFIG;
        // native-decided extract failures still intern their creator:
        // the Python phase needs the identity-validity check (which
        // precedes the txid claim) for those txs too
        bool claimer = t.status ==
                       BP_FAIL_BASE + TVC_INVALID_ENDORSER;
        if (!ok_status && !claimer) continue;
        bool fresh;
        int32_t cu = dd.intern(t.creator, t.creator_hash, &fresh);
        creator_uid[i] = cu;
        if (fresh) {
            uid_env[cu] = (int32_t)i;
            uid_off[cu] = t.creator.p - envs[i];
            uid_len[cu] = (int32_t)t.creator.n;
        }
        if (!ok_status) continue;   // no signature lanes for claimers
        sig_ptrs.push_back(t.csig.p);
        sig_lens.push_back((int32_t)t.csig.n);
        sig_lane.push_back((int32_t)(i * (max_e + 1)));
        e_count[i] = (int32_t)t.e_ident.size();
        for (size_t j = 0; j < t.e_ident.size(); ++j) {
            int64_t fj = i * max_e + (int64_t)j;
            loc(i, t.e_ident[j], e_ident_off, e_ident_len, fj);
            loc(i, t.e_sig[j], e_sig_off, e_sig_len, fj);
            int32_t u = dd.intern(t.e_ident[j], t.e_hash[j], &fresh);
            e_uid[fj] = u;
            if (fresh) {
                uid_env[u] = (int32_t)i;
                uid_off[u] = t.e_ident[j].p - envs[i];
                uid_len[u] = (int32_t)t.e_ident[j].n;
            }
            sig_ptrs.push_back(t.e_sig[j].p);
            sig_lens.push_back((int32_t)t.e_sig[j].n);
            sig_lane.push_back((int32_t)(i * (max_e + 1) + 1 + j));
        }
    }

    // DER parse + low-S gates + batched s^-1 for every live signature
    int32_t m = (int32_t)sig_ptrs.size();
    if (m > 0) {
        std::vector<uint8_t> r(m * 32), rpn(m * 32), w(m * 32), ok(m);
        ftpu_batch_prep_ptrs(sig_ptrs.data(), sig_lens.data(), m,
                             r.data(), rpn.data(), w.data(), ok.data());
        for (int32_t s = 0; s < m; ++s) {
            int32_t lane = sig_lane[s];
            int64_t tx = lane / (max_e + 1);
            int32_t slot = lane % (max_e + 1);
            if (slot == 0) {
                memcpy(c_r + 32 * tx, r.data() + 32 * s, 32);
                memcpy(c_rpn + 32 * tx, rpn.data() + 32 * s, 32);
                memcpy(c_w + 32 * tx, w.data() + 32 * s, 32);
                c_ok[tx] = ok[s];
            } else {
                int64_t fj = tx * max_e + (slot - 1);
                memcpy(e_r + 32 * fj, r.data() + 32 * s, 32);
                memcpy(e_rpn + 32 * fj, rpn.data() + 32 * s, 32);
                memcpy(e_w + 32 * fj, w.data() + 32 * s, 32);
                e_okf[fj] = ok[s];
            }
        }
    }
    return dd.next_id;
}

// standalone SHA-256 (differential tests vs hashlib)
void ftpu_sha256(const uint8_t *p, int64_t n, uint8_t *out32) {
    sha256_one(p, (size_t)n, out32);
}

// ---- tolerant txid scan (block-store indexing) ----
//
// The block store needs ONLY ChannelHeader.tx_id per envelope
// (Envelope.payload -> Payload.header -> Header.channel_header ->
// field 5). Unlike the strict clean-scan above (which routes unusual
// encodings to Python for VALIDATION), indexing must accept anything
// the Python protobuf parser accepts: unknown fields are skipped,
// repeated occurrences take the last value (proto3 merge semantics).
// Returns per-envelope txid offset/len; len = -1 means this envelope
// needs the Python fallback parse, len = 0 means cleanly parsed with
// no txid (skip, matching `if not ch.tx_id` in _index_block).
// Reference analog: blockindex.go indexBlock extracting txids via
// protoutil.GetOrComputeTxIDFromEnvelope.

// 1 found, 0 absent (clean), -1 malformed / needs-Python.
// bail_on_repeat: for embedded MESSAGE fields protobuf merge is
// concatenation, not last-wins — a repeated occurrence must route to
// the Python parser rather than silently dropping the first
// occurrence's contents. String fields (tx_id itself) keep proto3
// last-wins, which IS the Python semantics.
static int32_t walk_one(const Slice &in, uint64_t field, Slice &out,
                        bool bail_on_repeat) {
    int64_t pos = 0;
    int32_t found = 0;
    while (pos < in.n) {
        uint64_t tag;
        if (!read_varint(in, pos, tag)) return -1;
        uint64_t f = tag >> 3;
        uint32_t wt = (uint32_t)(tag & 7);
        if (wt == 2) {
            Slice s;
            if (!read_len_delim(in, pos, s)) return -1;
            if (f == field) {
                if (found && bail_on_repeat) return -1;
                out = s;          // last occurrence wins (string)
                found = 1;
            }
        } else if (wt == 0) {
            uint64_t v;
            if (!read_varint(in, pos, v)) return -1;
        } else if (wt == 5) {
            if (pos + 4 > in.n) return -1;
            pos += 4;
        } else if (wt == 1) {
            if (pos + 8 > in.n) return -1;
            pos += 8;
        } else {
            return -1;            // groups/reserved: Python decides
        }
    }
    return found;
}

void ftpu_txid_scan(const uint8_t *const *envs, const int64_t *lens,
                    int64_t n, int64_t *txid_off, int32_t *txid_len) {
    parallel_for(n, env_threads(), [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i) {
            txid_off[i] = 0;
            txid_len[i] = -1;
            Slice env = {envs[i], lens[i]};
            Slice payload = NIL, header = NIL, chdr = NIL, txid = NIL;
            if (walk_one(env, 1, payload, true) != 1) {
                // no payload: Python would fail the same way, but let
                // it decide (it may still skip gracefully)
                continue;
            }
            if (walk_one(payload, 1, header, true) != 1) continue;
            if (walk_one(header, 1, chdr, true) != 1) continue;
            int32_t got = walk_one(chdr, 5, txid, false);
            if (got < 0) continue;
            if (got == 0 || !valid_utf8(txid)) {
                if (got == 1) continue;       // bad utf8: Python path
                txid_len[i] = 0;              // cleanly absent
                continue;
            }
            txid_off[i] = (int64_t)(txid.p - envs[i]);
            txid_len[i] = (int32_t)txid.n;
        }
    });
}

// standalone UTF-8 validator (differential tests vs upb)
int32_t ftpu_utf8_valid(const uint8_t *p, int64_t n) {
    Slice s = {p, n};
    return valid_utf8(s) ? 1 : 0;
}

}  // extern "C"
