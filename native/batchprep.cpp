// fabric-tpu native host path: batched ECDSA verify preparation.
//
// The TPU provider's CPU-side hot loop (fabric_tpu/bccsp/tpu.py
// _verify_batch_device): per signature — strict DER parse, positivity,
// low-S policy, scalar range checks, w = s^-1 mod n, r+n overflow
// probe — executed here over the whole batch in one C call. Semantics
// mirror fabric_tpu/bccsp/utils.py (unmarshal_signature/is_low_s),
// which in turn mirrors the reference's bccsp/utils/ecdsa.go:41-90;
// differential tests (tests/test_native.py) pin byte-identical
// accept/reject and identical scalar outputs against the Python path.
//
// Build: g++ -O2 -shared -fPIC -o libbatchprep.so batchprep.cpp
// (tools/build_native.py; loaded via ctypes — no pybind11 needed).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ---- u256 little-endian 4x64 limbs ----

struct U256 {
    uint64_t v[4];
};

const U256 ZERO = {{0, 0, 0, 0}};

// P-256 group order n and field prime p (big-endian constants folded
// to limbs).
const U256 N = {{0xF3B9CAC2FC632551ULL, 0xBCE6FAADA7179E84ULL,
                 0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFF00000000ULL}};
const U256 P = {{0xFFFFFFFFFFFFFFFFULL, 0x00000000FFFFFFFFULL,
                 0x0000000000000000ULL, 0xFFFFFFFF00000001ULL}};
// n >> 1 (the low-S boundary)
const U256 HALF_N = {{0x79DCE5617E3192A8ULL, 0xDE737D56D38BCF42ULL,
                      0x7FFFFFFFFFFFFFFFULL, 0x7FFFFFFF80000000ULL}};

int cmp(const U256 &a, const U256 &b) {
    for (int i = 3; i >= 0; --i) {
        if (a.v[i] < b.v[i]) return -1;
        if (a.v[i] > b.v[i]) return 1;
    }
    return 0;
}

bool is_zero(const U256 &a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

// a += b; returns carry-out
uint64_t add(U256 &a, const U256 &b) {
    unsigned __int128 c = 0;
    for (int i = 0; i < 4; ++i) {
        c += (unsigned __int128)a.v[i] + b.v[i];
        a.v[i] = (uint64_t)c;
        c >>= 64;
    }
    return (uint64_t)c;
}

// a -= b (assumes a >= b)
void sub(U256 &a, const U256 &b) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
        unsigned __int128 d =
            (unsigned __int128)a.v[i] - b.v[i] - borrow;
        a.v[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

// a >>= 1 with carry_in as the new top bit
void shr1(U256 &a, uint64_t carry_in) {
    for (int i = 0; i < 4; ++i) {
        uint64_t next = (i < 3) ? (a.v[i + 1] & 1) : carry_in;
        a.v[i] = (a.v[i] >> 1) | (next << 63);
    }
}

// u = u/2 mod n (n odd)
void halve_mod(U256 &u) {
    if (u.v[0] & 1) {
        uint64_t c = add(u, N);
        shr1(u, c);
    } else {
        shr1(u, 0);
    }
}

// a = (a - b) mod n, both < n
void sub_mod(U256 &a, const U256 &b) {
    if (cmp(a, b) >= 0) {
        sub(a, b);
    } else {
        // a + n - b: the carry out of a+n cancels against b > a
        add(a, N);
        sub(a, b);
    }
}

// ---- Montgomery arithmetic mod n (R = 2^256) ----
// N0INV = -n^-1 mod 2^64; RR = R^2 mod n (both precomputed offline)
const uint64_t N0INV = 0xccd1c8aaee00bc4fULL;
const U256 RR = {{0x83244c95be79eea2ULL, 0x4699799c49bd6fa6ULL,
                  0x2845b2392b6bec59ULL, 0x66e12d94f3d95620ULL}};
const U256 ONE_U = {{1, 0, 0, 0}};

// out = a*b*R^-1 mod n (CIOS)
void mont_mul(const U256 &a, const U256 &b, U256 &out) {
    uint64_t t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; ++i) {
        unsigned __int128 c = 0;
        for (int j = 0; j < 4; ++j) {
            c += (unsigned __int128)t[j] +
                 (unsigned __int128)a.v[i] * b.v[j];
            t[j] = (uint64_t)c;
            c >>= 64;
        }
        c += t[4];
        t[4] = (uint64_t)c;
        t[5] = (uint64_t)(c >> 64);

        uint64_t m = t[0] * N0INV;
        c = (unsigned __int128)t[0] + (unsigned __int128)m * N.v[0];
        c >>= 64;
        for (int j = 1; j < 4; ++j) {
            c += (unsigned __int128)t[j] +
                 (unsigned __int128)m * N.v[j];
            t[j - 1] = (uint64_t)c;
            c >>= 64;
        }
        c += t[4];
        t[3] = (uint64_t)c;
        t[4] = t[5] + (uint64_t)(c >> 64);
        t[5] = 0;
    }
    U256 res = {{t[0], t[1], t[2], t[3]}};
    if (t[4] || cmp(res, N) >= 0) sub(res, N);
    out = res;
}

// out = in^-1 mod n via binary extended GCD; in must be in (0, n)
void modinv(const U256 &in, U256 &out) {
    U256 a = in, b = N;
    U256 u = {{1, 0, 0, 0}}, w = ZERO;
    while (!is_zero(a)) {
        while (!(a.v[0] & 1)) {
            shr1(a, 0);
            halve_mod(u);
        }
        while (!(b.v[0] & 1)) {
            shr1(b, 0);
            halve_mod(w);
        }
        if (cmp(a, b) >= 0) {
            sub(a, b);
            sub_mod(u, w);
        } else {
            sub(b, a);
            sub_mod(w, u);
        }
    }
    out = w;  // gcd in b == 1 for prime n
}

void store_be(const U256 &a, uint8_t *out32) {
    for (int i = 0; i < 4; ++i) {
        uint64_t limb = a.v[3 - i];
        for (int j = 0; j < 8; ++j)
            out32[i * 8 + j] = (uint8_t)(limb >> (56 - 8 * j));
    }
}

// ---- DER parsing (exact mirror of utils.py _parse_len/_parse_int) ----

struct Parser {
    const uint8_t *raw;
    int32_t len;
    int32_t off;
    bool bad;

    uint8_t byte() { return raw[off]; }
    bool avail(int32_t k) const { return off + k <= len; }
};

// definite length; false on format error
bool parse_len(Parser &p, int64_t &out) {
    if (p.off >= p.len) return false;
    uint8_t b = p.raw[p.off];
    if (b < 0x80) {
        out = b;
        p.off += 1;
        return true;
    }
    int nbytes = b & 0x7F;
    if (nbytes == 0 || nbytes > 4) return false;
    if (p.off + 1 + nbytes > p.len) return false;
    if (p.raw[p.off + 1] == 0) return false;  // superfluous zeros
    int64_t val = 0;
    for (int i = 0; i < nbytes; ++i)
        val = (val << 8) | p.raw[p.off + 1 + i];
    if (val < 0x80) return false;             // non-minimal form
    p.off += 1 + nbytes;
    out = val;
    return true;
}

// INTEGER -> (value as U256 if it fits in 32 bytes, ok flags).
// Returns false on malformed DER; *fits=false when positive but wider
// than 256 bits (caller rejects: >= n anyway); *nonpos=true for
// negative or zero values.
bool parse_int(Parser &p, U256 &out, bool &fits, bool &nonpos) {
    if (p.off >= p.len || p.raw[p.off] != 0x02) return false;
    p.off += 1;
    int64_t length;
    if (!parse_len(p, length)) return false;
    if (length == 0) return false;
    if (p.off + length > p.len) return false;
    const uint8_t *content = p.raw + p.off;
    if (length > 1) {
        if (content[0] == 0x00 && content[1] < 0x80) return false;
        if (content[0] == 0xFF && content[1] >= 0x80) return false;
    }
    nonpos = false;
    fits = true;
    out = ZERO;
    if (content[0] & 0x80) {
        nonpos = true;  // negative (two's complement sign bit)
        p.off += length;
        return true;
    }
    const uint8_t *mag = content;
    int64_t mlen = length;
    while (mlen > 0 && mag[0] == 0x00) {
        ++mag;
        --mlen;
    }
    if (mlen == 0) {
        nonpos = true;  // value == 0
        p.off += length;
        return true;
    }
    if (mlen > 32) {
        fits = false;
        p.off += length;
        return true;
    }
    for (int64_t i = 0; i < mlen; ++i) {
        int64_t bit_index = (mlen - 1 - i);
        out.v[bit_index / 8] |=
            (uint64_t)mag[i] << (8 * (bit_index % 8));
    }
    p.off += length;
    return true;
}

}  // namespace

// Parse + policy gates + r/rpn staging; s returned for the caller to
// invert (singly or via the batched Montgomery trick).
int prep_parse(const uint8_t *der, int32_t der_len, uint8_t *r_out,
               uint8_t *rpn_out, U256 &s_out) {
    Parser p{der, der_len, 0, false};
    if (der_len <= 0 || der[0] != 0x30) return 0;
    p.off = 1;
    int64_t seq_len;
    if (!parse_len(p, seq_len)) return 0;
    if (p.off + seq_len > p.len) return 0;
    int64_t end = p.off + seq_len;
    U256 r, s;
    bool fits_r, nonpos_r, fits_s, nonpos_s;
    if (!parse_int(p, r, fits_r, nonpos_r)) return 0;
    if (!parse_int(p, s, fits_s, nonpos_s)) return 0;
    if (p.off != end) return 0;  // trailing data inside sequence
    // (bytes after `end` tolerated — Go asn1 `rest` semantics)
    if (nonpos_r || nonpos_s) return 0;
    // low-S policy, then scalar range (mirrors check_signature + the
    // provider's r/s < n gate; !fits => >= n)
    if (!fits_s || cmp(s, HALF_N) > 0) return 0;
    if (!fits_r || cmp(r, N) >= 0 || is_zero(r)) return 0;
    if (cmp(s, N) >= 0 || is_zero(s)) return 0;

    U256 rpn = r;
    uint64_t carry = add(rpn, N);
    // r+n used only if it stays below the field prime p (no carry and
    // < p); else fall back to r (tpu.py: rpn = r+N if r+N < P else r)
    if (carry || cmp(rpn, P) >= 0) rpn = r;
    store_be(r, r_out);
    store_be(rpn, rpn_out);
    s_out = s;
    return 1;
}

extern "C" {

// One signature: parse + policy gates + scalar prep.
// Returns 1 and fills r/rpn/w (32-byte big-endian each) on acceptance.
int ftpu_prep_one(const uint8_t *der, int32_t der_len, uint8_t *r_out,
                  uint8_t *rpn_out, uint8_t *w_out) {
    U256 s;
    if (!prep_parse(der, der_len, r_out, rpn_out, s)) return 0;
    U256 w;
    modinv(s, w);
    store_be(w, w_out);
    return 1;
}

// Batch driver over a pointer table (one entry per signature; nullptr
// or len<=0 rejects the lane). The s^-1 mod n for the whole batch
// costs ONE binary-GCD inversion via Montgomery's batch-inversion
// trick (prefix products; ~5 Montgomery muls per accepted signature
// instead of a ~15us GCD each).
void ftpu_batch_prep_ptrs(const uint8_t *const *ptrs,
                          const int32_t *lens, int32_t n,
                          uint8_t *r_out, uint8_t *rpn_out,
                          uint8_t *w_out, uint8_t *ok_out) {
    std::vector<U256> s_mont(n), prefix(n);
    std::vector<int32_t> live(n);
    int32_t k = 0;
    for (int32_t i = 0; i < n; ++i) {
        U256 s;
        ok_out[i] = ptrs[i] != nullptr && (uint8_t)prep_parse(
            ptrs[i], lens[i], r_out + 32 * i, rpn_out + 32 * i, s);
        if (!ok_out[i]) continue;
        mont_mul(s, RR, s_mont[k]);        // to Montgomery domain
        if (k == 0) prefix[0] = s_mont[0];
        else mont_mul(prefix[k - 1], s_mont[k], prefix[k]);
        live[k] = i;
        ++k;
    }
    if (k == 0) return;
    // invert the full prefix product: one real inversion
    U256 pf, ipf, acc;
    mont_mul(prefix[k - 1], ONE_U, pf);    // out of Montgomery domain
    modinv(pf, ipf);
    mont_mul(ipf, RR, acc);                // back into the domain
    for (int32_t j = k - 1; j >= 0; --j) {
        U256 inv_j, w;
        if (j > 0) mont_mul(acc, prefix[j - 1], inv_j);
        else inv_j = acc;
        mont_mul(inv_j, ONE_U, w);         // out of Montgomery domain
        store_be(w, w_out + 32 * live[j]);
        U256 next;
        mont_mul(acc, s_mont[j], next);
        acc = next;
    }
}

// Contiguous-blob variant (the original ctypes entry point).
void ftpu_batch_prep(const uint8_t *blob, const int32_t *offs,
                     const int32_t *lens, int32_t n, uint8_t *r_out,
                     uint8_t *rpn_out, uint8_t *w_out,
                     uint8_t *ok_out) {
    std::vector<const uint8_t *> ptrs(n);
    for (int32_t i = 0; i < n; ++i) ptrs[i] = blob + offs[i];
    ftpu_batch_prep_ptrs(ptrs.data(), lens, n, r_out, rpn_out, w_out,
                         ok_out);
}

}  // extern "C"
