"""BASELINE config 3: block validation through the REAL tx pipeline.

Stands up an in-process 2-org network with a single-node etcdraft
orderer (real RaftChain: WAL, ready loop, block signing), endorses
`ntxs` transactions through the gateway (2 endorsements + 1 creator
signature each), orders them into one block, then times the peer-side
block pipeline — `Channel.process_block` = TxValidator (batched
verify) → pvt-data gather → kvledger commit — for BOTH a TPU-provider
peer and a sw-provider peer over the SAME ordered block.

Reference analog: `integration/e2e/e2e_test.go`; the timings mirror
"Validated block [n] in Tms" (`validator.go:262`) and the commit
breakdown (`kv_ledger.go:673-681`). Used by bench.py (BENCH_E2E=1) to
emit the `pipeline` section of the headline JSON.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def run(tpu_csp, ntxs: int = 1024, endorsements: int = 2) -> dict:
    from fabric_tpu.bccsp.sw import SWProvider
    from fabric_tpu.core.chaincode import Chaincode, ChaincodeDefinition
    from fabric_tpu.core.chaincode import shim
    from fabric_tpu.internal import cryptogen
    from fabric_tpu.internal.configtxgen import (
        genesis_block,
        new_channel_group,
    )
    from fabric_tpu.msp import msp_config_from_dir
    from fabric_tpu.msp.mspimpl import X509MSP
    from fabric_tpu.orderer import raft as raft_mod
    from fabric_tpu.orderer.broadcast import BroadcastHandler
    from fabric_tpu.orderer.cluster import LocalClusterNetwork
    from fabric_tpu.orderer.multichannel import Registrar
    from fabric_tpu.peer import Peer
    from fabric_tpu.peer.gateway import Gateway
    from fabric_tpu.protos import transaction as txpb

    channel = "benchchannel"
    orderer_ep = "orderer0.example.com:7050"
    root = tempfile.mkdtemp(prefix="bench_e2e_")
    cdir = os.path.join(root, "crypto")
    # reuse crypto material across runs (beside the warm Q tables):
    # deterministic org keys mean the TPU-filtered orderer's persisted
    # tables match on the next run — restart-warm ordering instead of
    # a per-run table build
    warm_dir = os.environ.get(
        "BENCH_WARM_DIR",
        os.path.expanduser("~/.cache/fabric_tpu_warmkeys"))
    crypto_cache = os.path.join(warm_dir, "pipeline_crypto")
    import shutil
    if os.path.isdir(crypto_cache):
        shutil.copytree(crypto_cache, cdir)
        org1 = os.path.join(cdir, "peerOrganizations",
                            "org1.example.com")
        org2 = os.path.join(cdir, "peerOrganizations",
                            "org2.example.com")
        ordo = os.path.join(cdir, "ordererOrganizations",
                            "example.com")
    else:
        org1 = cryptogen.generate_org(cdir, "org1.example.com",
                                      n_peers=1, n_users=1)
        org2 = cryptogen.generate_org(cdir, "org2.example.com",
                                      n_peers=1, n_users=1)
        ordo = cryptogen.generate_org(cdir, "example.com",
                                      orderer_org=True)
        try:
            shutil.copytree(cdir, crypto_cache + ".tmp")
            os.replace(crypto_cache + ".tmp", crypto_cache)
        except Exception:                 # noqa: BLE001
            pass                          # cache miss next run; fine
    sw_csp = SWProvider()

    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "etcdraft",
            "Addresses": [orderer_ep],
            # long timeout: submission of a full 10k-tx block takes
            # seconds; the cutter must cut on COUNT (one block), not
            # mid-submission timeouts
            "BatchTimeout": "30s",
            # bytes limits sized so MaxMessageCount governs: the point
            # is ONE ntxs-transaction block through the validator
            # (config 3's shape), not the blockcutter's byte policy
            "BatchSize": {"MaxMessageCount": ntxs,
                          "PreferredMaxBytes": 1 << 30,
                          "AbsoluteMaxBytes": 1 << 30},
            "Raft": {"Consenters": [
                {"Host": orderer_ep.split(":")[0], "Port": 7050}]},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": [orderer_ep]}],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(channel, new_channel_group(profile))

    def local_msp(msp_dir, mspid):
        m = X509MSP(sw_csp)
        m.setup(msp_config_from_dir(msp_dir, mspid, csp=sw_csp))
        return m

    # ---- single-node raft ordering service ----
    net = LocalClusterNetwork()
    transport = net.register(orderer_ep)
    orderer_msp = local_msp(
        os.path.join(ordo, "orderers", "orderer0.example.com", "msp"),
        "OrdererMSP")
    # Two ordering services are measured: this one (sw filter — the
    # reference configuration) and, below, a TPU-filtered twin over
    # the same genesis. Both ride the WINDOWED ingest (one sig-filter
    # verify_batch + one consenter enqueue per 512-envelope window —
    # process_normal_msgs).
    registrar = Registrar(
        os.path.join(root, "orderer"),
        orderer_msp.get_default_signing_identity(), sw_csp,
        {"etcdraft": raft_mod.consenter(transport,
                                        tick_interval_s=0.03,
                                        election_tick=8)})
    registrar.join(genesis)
    broadcast = BroadcastHandler(registrar)

    class KV(Chaincode):
        def init(self, stub):
            return shim.success()

        def invoke(self, stub):
            fn, params = stub.get_function_and_parameters()
            stub.put_state(params[0], params[1].encode())
            return shim.success()

    # ---- two validating peers: TPU provider vs sw provider ----
    peers = {}
    for org_name, org_dir, mspid, csp in (
            ("org1", org1, "Org1MSP", tpu_csp),
            ("org2", org2, "Org2MSP", sw_csp)):
        msp = local_msp(
            os.path.join(org_dir, "peers",
                         f"peer0.{org_name}.example.com", "msp"), mspid)
        peer = Peer(os.path.join(root, f"peer_{org_name}"), msp, csp)
        peer.join_channel(genesis)
        peer.chaincode_support.register("bench", KV())
        peer.channel(channel).define_chaincode(
            ChaincodeDefinition(name="bench"))
        peers[org_name] = peer

    user_msp = local_msp(
        os.path.join(org1, "users", "User1@org1.example.com", "msp"),
        "Org1MSP")
    gw = Gateway(peers["org1"], broadcast,
                 user_msp.get_default_signing_identity())

    endorsing = list(peers.values())[:endorsements]

    print("pipeline: network up; endorsing", flush=True,
          file=sys.stderr)
    # ---- endorse everything first (CPU signing work, untimed) ----
    t0 = time.perf_counter()
    envs = [gw.endorse(channel, "bench",
                       [b"put", f"k{i}".encode(), f"v{i}".encode()],
                       endorsing_peers=endorsing)[0]
            for i in range(ntxs)]
    endorse_s = time.perf_counter() - t0

    print(f"pipeline: endorsed {ntxs} in {endorse_s:.1f}s; ordering",
          flush=True, file=sys.stderr)
    # ---- order through raft into one block ----
    # submission goes through the batched windowed ingest — the same
    # path the BroadcastStream gRPC handler drives (one sig-filter
    # verify_batch + one consenter enqueue per window)
    from fabric_tpu.protos import common as cpb

    def order_envs(bcast, reg, stall_s: float = 150.0):
        t0 = time.perf_counter()
        window = 512
        pos = 0
        deadline0 = time.monotonic() + 60
        while pos < len(envs):
            batch = envs[pos:pos + window]
            resps = bcast.process_messages(batch)
            ok = 0
            for resp in resps:
                if resp.status == cpb.Status.SUCCESS:
                    ok += 1
                elif resp.status == cpb.Status.SERVICE_UNAVAILABLE:
                    # raft still electing: retry the unaccepted tail
                    break
                else:
                    # permanent rejection (BAD_REQUEST/FORBIDDEN/...):
                    # retrying cannot help — fail fast with the info
                    raise RuntimeError(
                        f"broadcast rejected: {resp.status} "
                        f"{resp.info}")
            pos += ok
            if ok == 0:
                if time.monotonic() > deadline0:
                    raise RuntimeError("broadcast unavailable for 60s")
                time.sleep(0.05)
        ch = reg.get_chain(channel)
        deadline = time.monotonic() + stall_s
        while True:
            blks = [ch.ledger.block_store.get_block_by_number(n)
                    for n in range(1, ch.ledger.height)]
            done = (all(b is not None for b in blks) and
                    sum(len(b.data.data) for b in blks
                        if b is not None) >= ntxs)
            if done:
                return time.perf_counter() - t0, blks
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"ordering stalled at height {ch.ledger.height}")
            time.sleep(0.05)

    order_s, blocks = order_envs(broadcast, registrar)

    # ---- the SAME block ordered by a TPU-FILTERED orderer ----
    # a second single-node ordering service over the same genesis,
    # BCCSP = the TPU provider: the windowed sig filter verifies each
    # 512-envelope window on device. With crypto material and Q-table
    # bytes persisted across runs, its per-key-set table restores from
    # disk (warm restart) instead of rebuilding — the round-4 blocker.
    # Timed warm-included; round-4 kept the sw filter here and the
    # TPU-filter number was only a commit-message claim.
    order_tpu_s = None
    try:
        # the orderer's own provider pads every 512-envelope window to
        # the 4096-lane bucket the parent AOT-compiled: no fresh
        # device compiles inside the ordering timer (the padded lanes
        # are premasked; device time is ~flat in lane count here)
        from fabric_tpu.bccsp import factory as _bf
        orderer_csp = _bf.new_bccsp(_bf.FactoryOpts.from_config({
            "Default": "TPU",
            "TPU": {"MinBatch": 16, "BucketFloor": 4096,
                    "Chunk": 32768, "WarmKeysDir": warm_dir}}))
        net2 = LocalClusterNetwork()
        transport2 = net2.register(orderer_ep)
        registrar2 = Registrar(
            os.path.join(root, "orderer_tpu"),
            orderer_msp.get_default_signing_identity(), orderer_csp,
            {"etcdraft": raft_mod.consenter(transport2,
                                            tick_interval_s=0.03,
                                            election_tick=8)})
        registrar2.join(genesis)
        broadcast2 = BroadcastHandler(registrar2)
        # generous stall budget: a first-ever run may pay one K=1
        # pipeline compile + the creator-set table restore inside the
        # timer (both cached/persisted for every later run)
        order_tpu_s, _blocks2 = order_envs(broadcast2, registrar2,
                                           stall_s=900.0)
        registrar2.halt()
        transport2.close()
    except Exception as e:                # noqa: BLE001
        print(f"pipeline: tpu-filtered ordering failed: {e}",
              flush=True, file=sys.stderr)
    data_blocks = [b for b in blocks if b.data.data]
    nsigs = ntxs * (endorsements + 1)

    print(f"pipeline: ordered in {order_s:.1f}s; validating", flush=True,
          file=sys.stderr)
    # ---- peer-side pipeline: validate (repeatable) + commit (once) ----
    out: dict = {
        "ntxs": ntxs, "endorsements_per_tx": endorsements,
        "signatures": nsigs, "endorse_s": round(endorse_s, 2),
        "order_raft_s": round(order_s, 2),
        "order_tx_per_s": round(ntxs / order_s, 1),
        "blocks": len(data_blocks),
    }
    if order_tpu_s is not None:
        out["order_raft_tpu_filter_s"] = round(order_tpu_s, 2)
        out["order_tpu_filter_tx_per_s"] = round(ntxs / order_tpu_s, 1)
    for org_name, peer in peers.items():
        ch = peer.channel(channel)
        label = "tpu_peer" if org_name == "org1" else "sw_peer"
        # warm (compiles on the tpu peer), then best-of-3 validation
        for b in data_blocks:
            flags = ch.validator.validate(b)
            assert all(f == txpb.TxValidationCode.VALID for f in flags), \
                f"{label}: invalid flags {set(flags)}"
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for b in data_blocks:
                ch.validator.validate(b)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        t0 = time.perf_counter()
        for b in data_blocks:
            codes = ch.process_block(b)
            assert all(c == txpb.TxValidationCode.VALID for c in codes)
        commit_s = time.perf_counter() - t0
        out[label] = {
            "validate_s": round(best, 4),
            "validate_tx_per_s": round(ntxs / best, 1),
            "validate_sigs_per_s": round(nsigs / best, 1),
            "process_block_s": round(commit_s, 4),
            "commit_tx_per_s": round(ntxs / commit_s, 1),
        }
    registrar.halt()
    transport.close()
    return out


# ---------------------------------------------------------------------------
# Round-10 batched ordering: the wheel-free stub-seam harness.
#
# `run()` above exercises the REAL x509/MSP/channel-config stack and
# therefore needs the 'cryptography' wheel (cert generation); on hosts
# without it the ordering bottleneck would go unmeasured. The helpers
# below rebuild the same single-node etcdraft ordering service with
# ONLY those wheel-bound layers stubbed: real P-256 envelope
# signatures (pure-python backend), the real batched StandardChannel
# sig-filter over the provider's AdmissionWindow, the real
# blockcutter, RaftChain/RaftNode/WAL, BlockWriteStage and BlockWriter
# (signed blocks, batched self-verify). tests/test_order_pipeline.py
# drives the same harness deterministically.
# ---------------------------------------------------------------------------


def make_order_client(channel: str = "orderbench"):
    """Creator-side material for the stub ordering service: one REAL
    P-256 keypair, a protoutil-compatible signer, and an envelope
    factory. Pass the same client to twin services so an identical
    envelope stream can be replayed through both (bit-identity
    checks compare the resulting block streams)."""
    import hashlib
    import types

    from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem
    from fabric_tpu.bccsp.sw import SWProvider
    from fabric_tpu.protos import common as cpb
    from fabric_tpu.protoutil import protoutil as pu

    sw = SWProvider()
    key = sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
    pub = key.public_key()
    creator = b"order-bench-client"

    class _Signer:
        def serialize(self):
            return creator

        def sign(self, msg: bytes) -> bytes:
            return sw.sign(key, hashlib.sha256(msg).digest())

        def verify_item(self, msg: bytes, sig: bytes) -> VerifyItem:
            return VerifyItem(key=pub, signature=sig, message=msg)

    signer = _Signer()

    def envelope(i: int, payload: bytes = None) -> cpb.Envelope:
        ch = pu.make_channel_header(
            cpb.HeaderType.ENDORSER_TRANSACTION, channel,
            tx_id=f"obench{i}")
        sh = pu.create_signature_header(creator, pu.random_nonce())
        return pu.sign_or_panic(signer, pu.make_payload(
            ch, sh, payload if payload is not None
            else f"tx{i}".encode()))

    return types.SimpleNamespace(channel=channel, sw=sw, key=key,
                                 pub=pub, creator=creator,
                                 signer=signer, envelope=envelope)


def make_order_support(root: str, client=None, csp=None,
                       channel: str = "orderbench",
                       block_txs: int = 64,
                       batch_timeout_s: float = 30.0,
                       endpoints=("orderer0.example.com:7050",),
                       on_config=None):
    """A wheel-free `ChainSupport` twin: real OrdererLedger (block
    store + raft WAL keyspaces), real blockcutter, real BlockWriter
    (signed blocks, batched self-verify through `csp`), real
    StandardChannel whose batched sig-filter rides the provider's
    AdmissionWindow, and a real SignaturePolicy — only the
    x509/MSP/channel-config layers are replaced by a stub bundle whose
    consenter set is `endpoints`. A committed config block bumps the
    stub's config sequence (so later stale-seq envelopes exercise the
    batched revalidation path) and calls `on_config(support, block)` —
    the reconfiguration seam: mutate `support.orderer_config` there
    (e.g. rotate consenter certs). The returned support's `.chain` is
    None until a RaftChain is attached (see `make_order_service`)."""
    import hashlib
    import types

    from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem
    from fabric_tpu.bccsp.admission import AdmissionWindow
    from fabric_tpu.common.policies.cauthdsl import SignaturePolicy
    from fabric_tpu.orderer import blockcutter
    from fabric_tpu.orderer.blockwriter import BlockWriter
    from fabric_tpu.orderer.msgprocessor import StandardChannel
    from fabric_tpu.orderer.multichannel import OrdererLedger
    from fabric_tpu.protos import common as cpb
    from fabric_tpu.protos import configtx as ctxpb
    from fabric_tpu.protos import policies as polpb
    from fabric_tpu.protoutil import protoutil as pu

    if client is None:
        client = make_order_client(channel)
    sw = client.sw
    provider = csp if csp is not None else sw
    ingress = AdmissionWindow.shared(provider)

    # one orderer signing key per RIG, parked on the shared client:
    # snapshot catch-up verifies pulled-block signatures against the
    # block SOURCE, so every consenter of a multi-node bench cluster
    # must sign under the same (stub) orderer identity
    okey = getattr(client, "_bench_orderer_key", None)
    if okey is None:
        okey = sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
        try:
            client._bench_orderer_key = okey
        except Exception:
            pass
    opub = okey.public_key()

    class _OrdererSigner:
        def serialize(self):
            return b"order-bench-orderer"

        def sign(self, msg: bytes) -> bytes:
            return sw.sign(okey, hashlib.sha256(msg).digest())

        def verify_item(self, msg: bytes, sig: bytes) -> VerifyItem:
            return VerifyItem(key=opub, signature=sig, message=msg)

    class _Identity:
        def mspid(self):
            return "BenchMSP"

        def satisfies_principal(self, principal):
            return None

        def verify_item(self, msg: bytes, sig: bytes) -> VerifyItem:
            return VerifyItem(key=client.pub, signature=sig,
                              message=msg)

    class _Deserializer:
        def deserialize_identity(self, raw: bytes):
            if raw != client.creator:
                raise ValueError("unknown creator")
            return _Identity()

    def consensus_metadata(cert_suffix: bytes = b"") -> bytes:
        meta = ctxpb.ConsensusMetadata()
        for ep in endpoints:
            host, port = ep.rsplit(":", 1)
            c = meta.consenters.add()
            c.host, c.port = host, int(port)
            c.client_tls_cert = (b"stub-cert-" + ep.encode() +
                                 cert_suffix)
        return pu.marshal(meta)

    pol_env = polpb.SignaturePolicyEnvelope()
    pol_env.rule.signed_by = 0
    pol_env.identities.add()
    policy = SignaturePolicy(pol_env, _Deserializer(), ingress)

    class _PolicyManager:
        def get_policy(self, name):
            return policy

    orderer_cfg = types.SimpleNamespace(
        consensus_type="etcdraft",
        consensus_state=0,
        consensus_metadata=consensus_metadata(),
        consensus_metadata_fn=consensus_metadata,
        batch_size=types.SimpleNamespace(
            max_message_count=block_txs,
            absolute_max_bytes=1 << 30,
            preferred_max_bytes=1 << 20),
        batch_timeout_s=batch_timeout_s)
    bundle = types.SimpleNamespace(orderer=orderer_cfg,
                                   policy_manager=_PolicyManager())

    signer = _OrdererSigner()
    ledger = OrdererLedger(os.path.join(root, "ledger"))
    if ledger.height == 0:
        # deterministic stub genesis (twin services must agree on the
        # prev-hash of block 1): zeroed timestamp, empty nonce, no
        # signature — is_config_block only reads the channel header
        ch = pu.make_channel_header(cpb.HeaderType.CONFIG, channel)
        ch.timestamp = 0
        sh = pu.create_signature_header(signer.serialize(), b"")
        genesis = pu.new_block(0, b"")
        genesis.data.data.append(pu.marshal(cpb.Envelope(
            payload=pu.marshal(pu.make_payload(ch, sh,
                                               b"stub-genesis")))))
        genesis.header.data_hash = pu.block_data_hash(genesis.data)
        ledger.add_block(genesis)

    class _StubSupport:
        """ChainSupport duck-type over the stub bundle."""

        def __init__(self):
            self.channel_id = channel
            self.ledger = ledger
            self.signer = signer
            self.client = client
            self.orderer_config = orderer_cfg
            self.on_config = on_config
            self.chain = None
            self._sequence = 0
            self._last_config = 0
            self.cutter = blockcutter.Receiver(self._batch_config)
            self.writer = BlockWriter(
                ledger, signer,
                last_block=ledger.get_block(ledger.height - 1),
                csp=provider)
            self.ingress_csp = ingress
            self.processor = StandardChannel(channel, self)

        def bundle(self):
            return bundle

        def configtx_validator(self):
            return self   # duck-type: only .sequence() is consulted

        def sequence(self) -> int:
            return self._sequence

        @property
        def csp(self):
            return provider

        def _batch_config(self):
            bs = self.orderer_config.batch_size
            return blockcutter.BatchConfig(
                max_message_count=bs.max_message_count,
                absolute_max_bytes=bs.absolute_max_bytes,
                preferred_max_bytes=bs.preferred_max_bytes)

        @property
        def batch_timeout_s(self) -> float:
            return self.orderer_config.batch_timeout_s

        def write_block(self, block, consenter_metadata=b"") -> None:
            self.writer.write_block(
                block, consenter_metadata,
                last_config_number=self._last_config)

        def write_blocks(self, blocks,
                         consenter_metadata=b"") -> None:
            self.writer.write_blocks(
                blocks, consenter_metadata,
                last_config_number=self._last_config)

        def write_config_block(self, block,
                               consenter_metadata=b"") -> None:
            self.writer.write_block(
                block, consenter_metadata,
                last_config_number=block.header.number)
            self._last_config = block.header.number
            self._sequence += 1
            if self.on_config is not None:
                self.on_config(self, block)

        def verify_onboarded_span(self, blocks) -> tuple:
            """Snapshot catch-up verification over the stub MSP:
            numbering from the ledger tip, data-hash, prev-hash
            linkage, and every block signature against the rig's
            shared orderer identity in ONE batched dispatch (the stub
            has a single orderer principal, so the full policy
            re-derivation of the real ChainSupport collapses to
            that key)."""
            from fabric_tpu.orderer.onboarding import VerificationError
            height = self.ledger.height
            prev = None
            if height:
                prev = pu.block_header_hash(
                    self.ledger.get_block(height - 1).header)
            evals, items = [], []
            error = None
            for i, b in enumerate(blocks):
                number = height + i
                try:
                    if b.header.number != number:
                        raise VerificationError(
                            b.header.number,
                            f"out of order (expected {number})")
                    if b.header.data_hash != \
                            pu.block_data_hash(b.data):
                        raise VerificationError(
                            number, "data hash mismatch")
                    if prev is not None and \
                            b.header.previous_hash != prev:
                        raise VerificationError(
                            number, "previous-hash linkage broken")
                    lo, n = len(items), 0
                    if number > 0:
                        signed = pu.block_signature_set(b)
                        if not signed:
                            raise VerificationError(
                                number, "unsigned block")
                        for sd in signed:
                            if sd.identity != signer.serialize():
                                raise VerificationError(
                                    number, "unknown block signer")
                            items.append(signer.verify_item(
                                sd.data, sd.signature))
                        n = len(signed)
                except Exception as e:
                    error = e if isinstance(e, VerificationError) \
                        else VerificationError(number, str(e))
                    break
                evals.append((number, lo, n))
                prev = pu.block_header_hash(b.header)
            ok = provider.verify_batch(items) if items else []
            n_valid = 0
            for number, lo, n in evals:
                if not all(ok[lo:lo + n]):
                    error = VerificationError(
                        number, "block signature invalid")
                    break
                n_valid += 1
            return n_valid, error

        def commit_onboarded_block(self, block) -> None:
            """Commit one VERIFIED pulled block verbatim (it keeps the
            source's signatures) and resync the writer's tip."""
            if block.header.number != self.ledger.height:
                raise ValueError(
                    f"onboarding block {block.header.number} out of "
                    f"order (height {self.ledger.height})")
            self.ledger.add_block(block)
            self.writer.resync(block)
            if pu.is_config_block(block):
                self._last_config = block.header.number

        def close(self):
            self.ledger.close()

    return _StubSupport()


def make_order_service(root: str, client=None, csp=None,
                       channel: str = "orderbench",
                       block_txs: int = 64,
                       batch_timeout_s: float = 30.0,
                       endpoint: str = "orderer0.example.com:7050",
                       endpoints=None, net=None,
                       write_pipeline=None, start: bool = True,
                       tick_interval_s: float = 0.02,
                       election_tick: int = 8, on_config=None,
                       transport_wrap=None):
    """A raft ordering service over `make_order_support`: single-node
    by default, multi-consenter when `net` + `endpoints` are shared
    across calls. `start=False` leaves the ready loop unstarted so
    tests can drive the chain deterministically (tick/elect, feed
    `_process_order_window`, `_drain_ready`). `close(flush=False)` is
    crash-equivalent: the write stage is abandoned, committed-but-
    unwritten entries stay in the raft WAL and replay on the next
    service built over the same `root`."""
    import types

    from fabric_tpu.orderer.broadcast import BroadcastHandler
    from fabric_tpu.orderer.cluster import LocalClusterNetwork
    from fabric_tpu.orderer.raft.chain import RaftChain

    if net is None:
        net = LocalClusterNetwork()
    eps = tuple(endpoints) if endpoints else (endpoint,)
    support = make_order_support(
        root, client=client, csp=csp, channel=channel,
        block_txs=block_txs, batch_timeout_s=batch_timeout_s,
        endpoints=eps, on_config=on_config)
    transport = net.register(endpoint)
    if transport_wrap is not None:
        # round 15: the chaos seam — e.g. NetChaos.wrap_cluster puts
        # this consenter's outbound links under seeded network chaos
        transport = transport_wrap(transport)
    chain = RaftChain(support, transport,
                      tick_interval_s=tick_interval_s,
                      election_tick=election_tick,
                      write_pipeline=write_pipeline)
    support.chain = chain

    class _Registrar:
        def get_chain(self, cid):
            return support if cid == channel else None

    broadcast = BroadcastHandler(_Registrar())
    if start:
        chain.start()

    def close(flush: bool = True) -> None:
        try:
            if flush:
                chain.halt()
            else:
                # crash-sim: stop the loop without flushing the write
                # stage; its worker may be wedged mid-span — unwritten
                # blocks replay from the WAL at the next start
                chain._halted.set()
                try:
                    chain._events.put_nowait(None)
                except Exception:     # noqa: BLE001
                    pass
                if chain._thread is not None:
                    chain._thread.join(timeout=5)
        finally:
            try:
                transport.close()
            except Exception:         # noqa: BLE001
                pass
            support.close()

    return types.SimpleNamespace(support=support, chain=chain,
                                 transport=transport, net=net,
                                 broadcast=broadcast,
                                 client=support.client, close=close)


def _stage_tail(stage: str, which: str):
    """Rounded stage-quantile lookup shared by the bench rigs (the
    `*_p50_s`/`*_p99_s` stage-line fields)."""
    from fabric_tpu.common import tracing
    return tracing.stage_quantile(stage, which, ndigits=6)


def order_pipeline_run(csp=None, ntxs: int = 1024,
                       window: int = 256,
                       block_txs: int = 256,
                       trace_path: str = None) -> dict:
    """ISSUE 7 scenario: the batched raft ordering pipeline, wheel-free
    (stub x509/MSP seam, pure-python P-256 when the OpenSSL wheel is
    absent) so the bounded default bench can always report the
    ordering bottleneck. Stands up a REAL single-node etcdraft
    ordering service (WAL, ready loop, admission window, block-write
    stage, signed blocks), broadcasts `ntxs` creator-signed envelopes
    through the windowed ingest, and times `order_raft_s` from first
    submission to every block durable. The `order_vs_validate` ratio
    divides that by a peer-validation equivalent — ONE batched
    `verify_batch` over the same `ntxs` signatures on the same
    provider — so the driver sees how far ordering still trails
    validation (ROADMAP item 2's ~2x target), independent of how fast
    this host's crypto backend happens to be."""
    import shutil

    from fabric_tpu.bccsp import VerifyItem
    from fabric_tpu.common import clustertrace, tracing
    from fabric_tpu.protos import common as cpb

    root = tempfile.mkdtemp(prefix="bench_order_")
    svc = None
    commit_pipe = None
    try:
        # start from a clean recorder: this run's dump and stage
        # quantiles should describe THIS run, not earlier bench
        # sections sharing the process
        tracing.reset()
        clustertrace.reset()
        svc = make_order_service(root, csp=csp, block_txs=block_txs,
                                 batch_timeout_s=30.0)
        client = svc.client

        # ---- creator-signed envelopes (CPU signing, untimed):
        # `ntxs` for the timed run + one extra block's worth for the
        # untimed lifecycle PROBE below, so the timed denominator is
        # unchanged vs earlier rounds ----
        t0 = time.perf_counter()
        envs = [client.envelope(i) for i in range(ntxs + block_txs)]
        probe_envs, envs = envs[:block_txs], envs[block_txs:]
        sign_s = time.perf_counter() - t0

        # wait out the single-node election so the timed run measures
        # ordering, not retry sleeps
        deadline0 = time.monotonic() + 60
        while svc.chain.node.leader_id != svc.chain.node_id:
            if time.monotonic() > deadline0:
                raise RuntimeError("no raft leader after 60s")
            time.sleep(0.01)

        def pump(run, stop_deadline):
            """Broadcast `run` under per-window ingress spans (the
            broadcast_stream seam's round-14 shape: each window's
            trace context propagates into the order events)."""
            pos = 0
            while pos < len(run):
                with tracing.span("ingress.batch",
                                  envelopes=min(window,
                                                len(run) - pos)) as c:
                    if c is not None:
                        # first-ingress birth stamp (round 18): the
                        # e2e_commit_seconds observation at the
                        # commit leg measures from here
                        clustertrace.note_birth(c.trace_id)
                    resps = svc.broadcast.process_messages(
                        run[pos:pos + window])
                ok = 0
                for resp in resps:
                    if resp.status == cpb.Status.SUCCESS:
                        ok += 1
                    elif resp.status == \
                            cpb.Status.SERVICE_UNAVAILABLE:
                        break   # leadership wobble: retry tail
                    else:
                        raise RuntimeError(f"broadcast rejected: "
                                           f"{resp.status} "
                                           f"{resp.info}")
                pos += ok
                if ok == 0:
                    if time.monotonic() > stop_deadline:
                        raise RuntimeError(
                            "broadcast unavailable for 60s")
                    time.sleep(0.02)
            return c

        ledger = svc.support.ledger

        def wait_txs(want, deadline_s=600):
            deadline = time.monotonic() + deadline_s
            while True:
                blks = [ledger.get_block(n)
                        for n in range(1, ledger.height)]
                got = sum(len(b.data.data) for b in blks
                          if b is not None)
                if got >= want and all(b is not None for b in blks):
                    return blks
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"ordering stalled: {got}/{want} at height "
                        f"{ledger.height}")
                time.sleep(0.02)

        # ---- the lifecycle probe (untimed): ONE full block pushed
        # through ingress->order->write alone, so its trace_id links
        # a transaction end to end deterministically (the acceptance
        # trace); the commit pipeline below re-attaches the same
        # context for its validate/commit spans ----
        probe_ctx = pump(probe_envs, deadline0)
        wait_txs(len(probe_envs))
        probe_trace_id = probe_ctx.trace_id if probe_ctx else None

        # ---- the timed ordering run ----
        t0 = time.perf_counter()
        pump(envs, time.monotonic() + 60)
        blocks = wait_txs(len(probe_envs) + ntxs)
        order_s = time.perf_counter() - t0

        # ---- the peer-validation equivalent on the SAME provider ----
        provider = svc.support.csp
        items = [VerifyItem(key=client.pub, signature=e.signature,
                            message=e.payload) for e in envs]
        provider.verify_batch(items[:min(64, ntxs)])   # warm
        t0 = time.perf_counter()
        ok = provider.verify_batch(items)
        validate_s = max(time.perf_counter() - t0, 1e-9)
        if not all(ok):
            raise RuntimeError("validate-equivalent rejected lanes")

        # ---- validate+commit the ORDERED stream through the REAL
        # CommitPipeline (round 14): its commit.validate /
        # commit.commit spans complete the lifecycle — the probe
        # block submits under the probe's trace context, so one
        # trace_id now links ingress -> order.window -> order.propose
        # -> order.consensus -> order.write -> commit.validate ->
        # commit.commit in the dumped trace ----
        from fabric_tpu.core.commitpipeline import CommitPipeline
        from fabric_tpu.core.txvalidator import ValidationResult
        from fabric_tpu.protos import transaction as txpb
        from fabric_tpu.protoutil import protoutil as pu

        class _Validator:
            """Batched creator-signature verify per block on the same
            provider (the device-bound stage), deferred-publication
            contract matching the real TxValidator."""

            def validate_ahead(self, block, known_txids=None):
                v0 = time.perf_counter()
                vitems = []
                for env_bytes in block.data.data:
                    env = pu.unmarshal_envelope(env_bytes)
                    vitems.append(VerifyItem(key=client.pub,
                                             signature=env.signature,
                                             message=env.payload))
                vok = provider.verify_batch(vitems)
                codes = [txpb.TxValidationCode.VALID if o else
                         txpb.TxValidationCode.BAD_CREATOR_SIGNATURE
                         for o in vok]
                return ValidationResult(
                    codes=codes, n_items=len(vitems),
                    duration_s=time.perf_counter() - v0)

            def publish_validation(self, block, result):
                while len(block.metadata.metadata) <= \
                        cpb.BlockMetadataIndex.TRANSACTIONS_FILTER:
                    block.metadata.metadata.append(b"")
                block.metadata.metadata[
                    cpb.BlockMetadataIndex.TRANSACTIONS_FILTER] = \
                    bytes(result.codes)

            def validate(self, block):
                result = self.validate_ahead(block)
                self.publish_validation(block, result)
                return result.codes

        class _BlockStore:
            @staticmethod
            def block_tx_ids(block):
                out = []
                for env_bytes in block.data.data:
                    try:
                        env = pu.unmarshal_envelope(env_bytes)
                        payload = pu.get_payload(env)
                        out.append(pu.get_channel_header(
                            payload).tx_id)
                    except Exception:       # noqa: BLE001
                        out.append("")
                return out

        class _PeerLedger:
            def __init__(self):
                self.height = 1             # "genesis committed"
                self.block_store = _BlockStore()

        class _PeerChan:
            channel_id = client.channel

            def __init__(self):
                self.ledger = _PeerLedger()
                self.validator = _Validator()
                self.committed: list = []

            def commit_validated(self, block, codes, rwsets=None,
                                 tx_ids=None):
                if not all(c == txpb.TxValidationCode.VALID
                           for c in codes):
                    raise RuntimeError(
                        f"ordered block [{block.header.number}] "
                        f"failed creator-signature validation")
                self.committed.append(block.header.number)
                self.ledger.height = block.header.number + 1
                return list(codes)

            def process_block(self, block):
                codes = self.validator.validate(block)
                return self.commit_validated(block, codes)

        chan = _PeerChan()
        # round 18: the commit leg IS the peer node of this rig —
        # naming it gives the probe trace a second node track (the
        # orderer's chain loop already records under its endpoint)
        commit_pipe = CommitPipeline(
            chan, depth=1, node_id="peer0.example.com:7051")
        t0 = time.perf_counter()
        for i, blk in enumerate(blocks, start=1):
            # every block submits under the carrier the block writer
            # registered (round 18 — the deliver-feeder shape); the
            # probe block's carrier descends from the probe ingress
            # span, so its validate/commit spans keep the lifecycle
            # trace_id exactly as before
            carrier = clustertrace.block_carrier(client.channel,
                                                 blk.header.number)
            if carrier is None and blk.header.number == 1:
                with tracing.attached(probe_ctx):
                    commit_pipe.submit(i, block=blk)
            else:
                with clustertrace.resumed(
                        carrier, link="deliver:orderbench",
                        node="peer0.example.com:7051"):
                    commit_pipe.submit(i, block=blk)
        commit_pipe.drain(timeout=600)
        commit_leg_s = time.perf_counter() - t0
        if len(chan.committed) != len(blocks):
            raise RuntimeError(
                f"commit leg short: {len(chan.committed)}/"
                f"{len(blocks)} blocks")

        # ---- stage tails + the lifecycle trace dump ----
        pq = _stage_tail

        if trace_path is None:
            trace_path = os.environ.get("BENCH_TRACE_SIDECAR",
                                        "bench_trace.json")
        trace_file = None
        linked = []
        if trace_path:
            try:
                trace_file = tracing.dump("bench_full_pipeline",
                                          path=trace_path)
            except Exception:               # noqa: BLE001
                trace_file = None
        nodes: list = []
        if probe_trace_id:
            linked = tracing.trace_stages(probe_trace_id)
            # round-18 contract: the probe's trace must CROSS nodes —
            # the orderer's chain-loop track plus the commit leg's
            # peer track at minimum
            nodes = tracing.trace_nodes(probe_trace_id)
            assert len(nodes) >= 2, \
                f"probe trace stayed on one node: {nodes}"

        # round-18 e2e finality tails (birth -> commit on the peer
        # leg); an explicit marker when tracing is off or nothing
        # carried a birth, so the smoke gate can tell "didn't run"
        # from "lost its fields"
        e2e_p50 = _stage_tail("e2e.commit", "p50_s")
        e2e_p99 = _stage_tail("e2e.commit", "p99_s")

        stats = svc.chain.order_pipeline_stats()
        win = getattr(svc.support.ingress_csp, "stats", {})
        return {
            "ntxs": ntxs, "window": window, "block_txs": block_txs,
            "blocks": len(blocks) - 1,      # probe block excluded
            "sign_s": round(sign_s, 2),
            "order_raft_s": round(order_s, 3),
            "order_tx_per_s": round(ntxs / order_s, 1),
            "validate_equiv_s": round(validate_s, 4),
            "order_vs_validate": round(order_s / validate_s, 2),
            "commit_leg_s": round(commit_leg_s, 3),
            "batch_fill": stats.get("fill"),
            "windows": stats.get("windows"),
            "blocks_proposed": stats.get("blocks_proposed"),
            "blocks_written": stats.get("blocks_written"),
            "write_overlap_ratio": round(
                stats.get("overlap_ratio") or 0.0, 4),
            "steps_coalesced": stats.get("steps_coalesced"),
            "demotions": stats.get("demotions"),
            "ingress_window_dispatches": win.get("window_dispatches"),
            "ingress_window_callers": win.get("window_callers"),
            "filter_backend": type(provider).__name__,
            # round-14 per-stage tails (the means above hide these)
            "order_window_p50_s": pq("order.window", "p50_s"),
            "order_window_p99_s": pq("order.window", "p99_s"),
            "order_propose_p50_s": pq("order.propose", "p50_s"),
            "order_propose_p99_s": pq("order.propose", "p99_s"),
            "order_consensus_p50_s": pq("order.consensus", "p50_s"),
            "order_consensus_p99_s": pq("order.consensus", "p99_s"),
            "order_write_p50_s": pq("order.write", "p50_s"),
            "order_write_p99_s": pq("order.write", "p99_s"),
            "validate_p50_s": pq("commit.validate", "p50_s"),
            "validate_p99_s": pq("commit.validate", "p99_s"),
            "commit_p50_s": pq("commit.commit", "p50_s"),
            "commit_p99_s": pq("commit.commit", "p99_s"),
            "trace_file": trace_file,
            "probe_trace_id": probe_trace_id,
            "trace_linked_stages": ",".join(linked) or None,
            "trace_nodes": ",".join(nodes) or None,
            **({"e2e_commit_p50_s": e2e_p50,
                "e2e_commit_p99_s": e2e_p99}
               if e2e_p50 is not None else
               {"e2e_skipped": "tracing off or no birth-stamped "
                               "commits"}),
        }
    finally:
        if commit_pipe is not None:
            try:
                commit_pipe.stop()
            except Exception:             # noqa: BLE001
                pass
        if svc is not None:
            try:
                svc.close(flush=True)
            except Exception:         # noqa: BLE001
                pass
        shutil.rmtree(root, ignore_errors=True)


def cluster_trace_run(consenters: int = 3, ntxs: int = 24,
                      block_txs: int = 8, window: int = 12,
                      slo_target_s: float = 1.0,
                      deadline_s: float = 120.0) -> dict:
    """ISSUE 15 acceptance rig: a wheel-free in-process 3-consenter +
    2-peer run that produces ONE merged Chrome trace in which a single
    probe transaction's trace_id links ingress -> raft consensus hops
    -> block write -> gossip/deliver -> commit.validate/commit.commit
    on BOTH peers.

    Topology: `consenters` raft orderers over one LocalClusterNetwork
    (wire carriers framed into consensus/submit payloads); peer0 feeds
    its CommitPipeline from a REAL `common/deliver.DeliverHandler`
    block stream off the leader; peer1 receives the same blocks over
    the gossip `LocalNetwork` (a relay reads a FOLLOWER's deliver
    stream and re-gossips under the resumed carrier). Two
    OperationsServers front the shared recorder; the merge is pulled
    over HTTP via `/debug/trace/cluster?trace_id=` (peer fetch + clock
    alignment + span-id dedup all exercised), and
    `e2e_commit_seconds`/`hop_seconds` + `components.slo` are read off
    the REAL /metrics and /healthz surfaces."""
    import shutil
    import threading
    import types
    import urllib.request

    from fabric_tpu.common import clustertrace, tracing
    from fabric_tpu.common import metrics as metrics_mod
    from fabric_tpu.common.deliver import DeliverHandler
    from fabric_tpu.core.commitpipeline import CommitPipeline
    from fabric_tpu.core.txvalidator import ValidationResult
    from fabric_tpu.gossip.transport import LocalNetwork
    from fabric_tpu.node.operations import OperationsServer
    from fabric_tpu.orderer.cluster import LocalClusterNetwork
    from fabric_tpu.peer.deliverclient import seek_envelope
    from fabric_tpu.protos import common as cpb
    from fabric_tpu.protos import transaction as txpb
    from fabric_tpu.protoutil import protoutil as pu

    if not tracing.enabled():
        return {"skipped": "FTPU_TRACE=0"}

    root = tempfile.mkdtemp(prefix="bench_ctrace_")
    t_run0 = time.perf_counter()
    deadline = time.monotonic() + deadline_s
    eps = [f"orderer{i}.example.com:{7050 + i}"
           for i in range(consenters)]
    peer_eps = ["peer0.example.com:7051", "peer1.example.com:7052"]
    svcs: dict = {}
    pipes: list = []
    ops_servers: list = []
    gossip_net = None
    try:
        tracing.reset()
        clustertrace.reset()
        provider = metrics_mod.PrometheusProvider()
        tracing.bind_metrics(provider)   # + e2e/hop histograms
        clustertrace.configure_slo(slo_target_s)

        net = LocalClusterNetwork()
        client = make_order_client()
        for i, ep in enumerate(eps):
            svcs[ep] = make_order_service(
                os.path.join(root, f"o{i}"), client=client,
                endpoint=ep, endpoints=eps, net=net,
                block_txs=block_txs, batch_timeout_s=0.1,
                tick_interval_s=0.01, election_tick=8)

        def leader_ep():
            from fabric_tpu.orderer.raft.core import LEADER
            for ep, s in svcs.items():
                if s.chain.node.state == LEADER:
                    return ep
            return None

        while leader_ep() is None:
            if time.monotonic() > deadline:
                raise RuntimeError("no raft leader")
            time.sleep(0.005)
        lead = svcs[leader_ep()]

        # ---- the probe block + steady traffic, birth-stamped ----
        envs = [client.envelope(i) for i in range(block_txs + ntxs)]
        probe_envs, rest = envs[:block_txs], envs[block_txs:]

        def pump(run):
            pos = 0
            ctx = None
            while pos < len(run):
                with tracing.span(
                        "ingress.batch",
                        envelopes=min(window, len(run) - pos)) as c:
                    if c is not None:
                        clustertrace.note_birth(c.trace_id)
                        ctx = c
                    resps = lead.broadcast.process_messages(
                        run[pos:pos + window])
                ok = sum(1 for r in resps
                         if r.status == cpb.Status.SUCCESS)
                pos += ok
                if ok == 0:
                    if time.monotonic() > deadline:
                        raise RuntimeError("broadcast stalled")
                    time.sleep(0.02)
            return ctx

        probe_ctx = pump(probe_envs)
        probe_trace_id = probe_ctx.trace_id
        pump(rest)

        # every consenter durably holds every block
        want_txs = len(envs)
        while True:
            heights = [s.support.ledger.height for s in svcs.values()]
            got = 0
            if len(set(heights)) == 1 and heights[0] > 1:
                blks = [lead.support.ledger.get_block(n)
                        for n in range(1, heights[0])]
                if all(b is not None for b in blks):
                    got = sum(len(b.data.data) for b in blks)
                    if got >= want_txs:
                        break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"cluster never converged: {heights} ({got}/"
                    f"{want_txs} txs)")
            time.sleep(0.02)
        height = heights[0]

        # ---- the two peers ----
        class _Validator:
            def validate_ahead(self, block, known_txids=None):
                v0 = time.perf_counter()
                n = len(block.data.data)
                return ValidationResult(
                    codes=[txpb.TxValidationCode.VALID] * n,
                    n_items=n,
                    duration_s=time.perf_counter() - v0)

            def publish_validation(self, block, result):
                while len(block.metadata.metadata) <= \
                        cpb.BlockMetadataIndex.TRANSACTIONS_FILTER:
                    block.metadata.metadata.append(b"")
                block.metadata.metadata[
                    cpb.BlockMetadataIndex.TRANSACTIONS_FILTER] = \
                    bytes(result.codes)

            def validate(self, block):
                result = self.validate_ahead(block)
                self.publish_validation(block, result)
                return result.codes

        class _BlockStore:
            @staticmethod
            def block_tx_ids(block):
                return [""] * len(block.data.data)

        class _PeerChan:
            channel_id = client.channel

            def __init__(self):
                self.ledger = types.SimpleNamespace(
                    height=1, block_store=_BlockStore())
                self.validator = _Validator()
                self.committed: list = []

            def commit_validated(self, block, codes, rwsets=None,
                                 tx_ids=None):
                self.committed.append(block.header.number)
                self.ledger.height = block.header.number + 1
                return list(codes)

            def process_block(self, block):
                codes = self.validator.validate(block)
                return self.commit_validated(block, codes)

        chans = [_PeerChan() for _ in peer_eps]
        pipes = [CommitPipeline(chan, depth=1, node_id=pep)
                 for chan, pep in zip(chans, peer_eps)]

        # peer0: the REAL DeliverHandler block stream off the leader
        deliver = DeliverHandler(
            lambda cid: lead.support
            if cid == client.channel else None)
        seek = seek_envelope(client.channel, 1, client.signer,
                             stop=height - 1)
        errors: list = []

        def deliver_feeder():
            try:
                for resp in deliver.handle(seek):
                    if resp.WhichOneof("type") != "block":
                        break
                    blk = resp.block
                    carrier = clustertrace.block_carrier(
                        client.channel, blk.header.number)
                    with clustertrace.resumed(
                            carrier,
                            link=f"deliver:{lead.transport.endpoint}",
                            node=peer_eps[0]):
                        pipes[0].submit(blk.header.number, block=blk)
            except Exception as e:   # noqa: BLE001 — surfaced below
                errors.append(f"deliver feeder: {e}")

        # peer1: blocks re-gossiped over the gossip fabric by a relay
        # reading a FOLLOWER's deliver stream (carrier captured at the
        # relay's resumed ambient, re-extracted at peer1's transport
        # drain)
        gossip_net = LocalNetwork()
        relay_t = gossip_net.register("relay.example.com:7060")
        peer1_t = gossip_net.register(peer_eps[1])

        def on_gossip(sender, raw):
            # runs on peer1's drain thread UNDER the resumed carrier
            blk = cpb.Block()
            blk.ParseFromString(raw)
            clustertrace.register_block(client.channel,
                                        blk.header.number)
            with clustertrace.resumed(
                    clustertrace.block_carrier(client.channel,
                                               blk.header.number),
                    link=f"gossip:{sender}", node=peer_eps[1]):
                pipes[1].submit(blk.header.number, block=blk)

        peer1_t.set_handler(on_gossip)
        follower = next(s for ep, s in svcs.items()
                        if s is not lead)
        fol_deliver = DeliverHandler(
            lambda cid: follower.support
            if cid == client.channel else None)

        def gossip_relay():
            try:
                for resp in fol_deliver.handle(seek):
                    if resp.WhichOneof("type") != "block":
                        break
                    blk = resp.block
                    carrier = clustertrace.block_carrier(
                        client.channel, blk.header.number)
                    with clustertrace.resumed(
                            carrier, link="deliver:follower",
                            node="relay.example.com:7060"):
                        relay_t.send(peer_eps[1],
                                     blk.SerializeToString())
            except Exception as e:   # noqa: BLE001 — surfaced below
                errors.append(f"gossip relay: {e}")

        threads = [threading.Thread(target=deliver_feeder,
                                    name="ctrace-deliver"),
                   threading.Thread(target=gossip_relay,
                                    name="ctrace-relay")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=max(5.0, deadline - time.monotonic()))
        if errors:
            raise RuntimeError("; ".join(errors))
        # the gossip leg submits from peer1's ASYNC drain thread:
        # pipeline.drain() only covers already-submitted blocks, so
        # wait for every commit to actually land before asserting
        while not all(len(c.committed) >= height - 1
                      for c in chans):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"peer commits stalled: "
                    f"{[len(c.committed) for c in chans]}/"
                    f"{height - 1}")
            time.sleep(0.01)
        for p in pipes:
            p.drain(timeout=max(5.0, deadline - time.monotonic()))
        for chan in chans:
            assert len(chan.committed) == height - 1, \
                (chan.committed, height)

        # ---- the operations surfaces ----
        ops_a = OperationsServer(metrics_provider=provider)
        ops_a.register_checker("slo", clustertrace.slo_health)
        ops_b = OperationsServer()
        ops_a.set_trace_peers([ops_b.address])
        ops_a.start()
        ops_b.start()
        ops_servers = [ops_a, ops_b]

        def get_json(addr, path):
            with urllib.request.urlopen(f"http://{addr}{path}",
                                        timeout=10) as r:
                return json.load(r)

        merged = get_json(ops_a.address,
                          f"/debug/trace/cluster?trace_id="
                          f"{probe_trace_id}")
        probe_events = [e for e in merged["traceEvents"]
                        if e.get("ph") != "M"]
        assert probe_events, "merged cluster trace is empty"
        assert all(e["args"]["trace_id"] == probe_trace_id
                   for e in probe_events), "trace_id filter leaked"
        stages = {e["name"] for e in probe_events}
        for want in ("ingress.batch", "hop.recv", "order.write",
                     "commit.validate", "commit.commit"):
            assert want in stages, \
                f"probe trace lacks {want!r}: {sorted(stages)}"
        nodes = {e["args"].get("node") for e in probe_events} - {None}
        commit_nodes = {e["args"].get("node") for e in probe_events
                        if e["name"] == "commit.commit"}
        assert set(peer_eps) <= commit_nodes, \
            f"probe did not commit on both peers: {commit_nodes}"
        hop_nodes = {e["args"].get("node") for e in probe_events
                     if e["name"] == "hop.recv"} - {None}
        assert any(n in hop_nodes for n in eps), \
            f"no consensus hop resumed on a consenter: {hop_nodes}"

        with urllib.request.urlopen(
                f"http://{ops_a.address}/metrics", timeout=10) as r:
            metrics_text = r.read().decode()
        assert "e2e_commit_seconds" in metrics_text, \
            "e2e_commit_seconds not rendered on /metrics"
        assert "hop_seconds" in metrics_text, \
            "hop_seconds not rendered on /metrics"
        healthz = get_json(ops_a.address, "/healthz")
        slo_state = (healthz.get("components") or {}).get("slo")
        assert slo_state is not None, healthz

        pq = _stage_tail
        return {
            "consenters": consenters,
            "peers": len(peer_eps),
            "ntxs": want_txs,
            "blocks": height - 1,
            "probe_trace_id": probe_trace_id,
            "merged_events": len(probe_events),
            "trace_nodes": ",".join(sorted(nodes)),
            "commit_nodes": ",".join(sorted(commit_nodes)),
            "linked_stages": ",".join(sorted(stages)),
            "residual_skew_s": merged["ftpu"]["cluster"][
                "residual_skew_s_observed"],
            "e2e_commit_p50_s": pq("e2e.commit", "p50_s"),
            "e2e_commit_p99_s": pq("e2e.commit", "p99_s"),
            "slo_health": slo_state,
            "slo_target_s": slo_target_s,
            "run_s": round(time.perf_counter() - t_run0, 2),
        }
    finally:
        for p in pipes:
            try:
                p.stop()
            except Exception:         # noqa: BLE001
                pass
        for s in svcs.values():
            try:
                s.close(flush=True)
            except Exception:         # noqa: BLE001
                pass
        if gossip_net is not None:
            for ep in list(gossip_net.endpoints()):
                try:
                    gossip_net._nodes[ep].close()
                except Exception:     # noqa: BLE001
                    pass
        for o in ops_servers:
            try:
                o.stop()
            except Exception:         # noqa: BLE001
                pass
        clustertrace.configure_slo(None)
        shutil.rmtree(root, ignore_errors=True)


def overload_run(producers: int = 4, ntxs_per_producer: int = 300,
                 window: int = 24, block_txs: int = 32,
                 budget_s: float = 0.35,
                 events_cap: int = 48) -> dict:
    """ISSUE 9 soak scenario: drive the REAL single-node raft ordering
    service (threaded ready loop, admission window, write stage,
    signed blocks) with MORE offered load than it can drain —
    `producers` threads each broadcasting creator-signed envelopes
    through `BroadcastHandler.process_messages` under a tight ambient
    `Deadline` (`budget_s`) against a deliberately small raft event
    queue (`events_cap` windows) — and assert the round-12 overload
    contract:

      * bounded: every registered overload queue's max_depth stayed
        within its capacity (no unbounded growth anywhere);
      * shed, not stalled: over-capacity load was refused as clean
        per-envelope SERVICE_UNAVAILABLE, counted per stage, and no
        producer ever blocked past its deadline budget;
      * nothing half-applied: every ACCEPTED (SUCCESS) envelope
        commits exactly once, every committed envelope was accepted,
        and the committed stream replayed through a fresh SEQUENTIAL
        (write_pipeline=False) oracle service is bit-identical;
      * live throughout: the ledger kept advancing and the run
        finished inside its wall budget (the soak script adds
        FTPU_LOCKCHECK=1 on top for the no-deadlock claim).

    Chaos faults ride in from FTPU_FAULTS exactly like every other
    regime (tools/soak_check.sh arms order.propose delays + raft.step
    errors), so shed accounting and demotion machinery are exercised
    TOGETHER."""
    import shutil
    import threading

    from fabric_tpu.common import overload
    from fabric_tpu.protos import common as cpb
    from fabric_tpu.protoutil.protoutil import marshal as pu_marshal

    os.environ["FTPU_RAFT_EVENTS_CAP"] = str(events_cap)
    root = tempfile.mkdtemp(prefix="bench_overload_")
    svc = None
    oracle = None
    try:
        svc = make_order_service(os.path.join(root, "hot"),
                                 block_txs=block_txs,
                                 batch_timeout_s=0.2)
        client = svc.client

        deadline0 = time.monotonic() + 60
        while svc.chain.node.leader_id != svc.chain.node_id:
            if time.monotonic() > deadline0:
                raise RuntimeError("no raft leader after 60s")
            time.sleep(0.01)

        # pre-sign everything (CPU signing is untimed setup)
        all_envs = [[client.envelope(p * 1_000_000 + i)
                     for i in range(ntxs_per_producer)]
                    for p in range(producers)]

        accepted: list[list[bytes]] = [[] for _ in range(producers)]
        shed_counts = [0] * producers
        max_call_s = [0.0] * producers
        errors: list = []

        def producer(p: int) -> None:
            envs = all_envs[p]
            pos = 0
            while pos < len(envs):
                batch = envs[pos:pos + window]
                pos += len(batch)
                t0 = time.perf_counter()
                try:
                    with overload.Deadline.after(budget_s).applied():
                        resps = svc.broadcast.process_messages(batch)
                except Exception as e:      # noqa: BLE001
                    errors.append(f"producer {p}: {e!r}")
                    return
                dt = time.perf_counter() - t0
                if dt > max_call_s[p]:
                    max_call_s[p] = dt
                for env, resp in zip(batch, resps):
                    if resp.status == cpb.Status.SUCCESS:
                        accepted[p].append(pu_marshal(env))
                    elif resp.status == \
                            cpb.Status.SERVICE_UNAVAILABLE:
                        shed_counts[p] += 1
                    else:
                        errors.append(
                            f"producer {p}: unexpected status "
                            f"{resp.status} {resp.info}")
                        return

        t_run0 = time.perf_counter()
        threads = [threading.Thread(target=producer, args=(p,),
                                    name=f"overload-producer-{p}")
                   for p in range(producers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        offered_s = time.perf_counter() - t_run0
        if errors:
            raise RuntimeError("; ".join(errors[:3]))

        n_accepted = sum(len(a) for a in accepted)
        n_shed = sum(shed_counts)
        n_offered = producers * ntxs_per_producer

        # ---- drain: every accepted envelope must land ----
        # incremental read (high-water block cursor): re-reading the
        # whole ledger every poll tick is O(blocks^2) and starves the
        # single-core pipeline being drained
        ledger = svc.support.ledger
        accepted_set = {e for a in accepted for e in a}
        drain_deadline = time.monotonic() + 300
        committed: list = []
        next_block = 1
        while True:
            while next_block < ledger.height:
                b = ledger.get_block(next_block)
                if b is None:       # still in the write stage
                    break
                committed.extend(bytes(d) for d in b.data.data)
                next_block += 1
            if len(committed) >= n_accepted:
                break
            if time.monotonic() > drain_deadline:
                raise RuntimeError(
                    f"overload drain stalled: {len(committed)}/"
                    f"{n_accepted} committed")
            time.sleep(0.05)
        n_blocks = next_block - 1
        drain_s = time.perf_counter() - t_run0 - offered_s

        # exactly-once: accepted == committed as multisets (and since
        # accepted envelopes are globally unique, set+len suffice)
        assert len(committed) == n_accepted, \
            (len(committed), n_accepted)
        assert set(committed) == accepted_set, \
            "committed stream diverged from the accepted set"

        # snapshot the overload stages NOW: the oracle service below
        # re-registers same-named queues (raft.events.<channel>) and
        # would shadow the hot run's readings
        stages = overload.stage_stats()

        # ---- sequential-oracle replay, bit-identical ----
        # SAME client (keys + creator): the oracle must accept the
        # exact committed bytes, and a fresh client's sig filter
        # would rightly reject them
        oracle = make_order_service(os.path.join(root, "oracle"),
                                    client=client,
                                    block_txs=block_txs,
                                    batch_timeout_s=0.2,
                                    write_pipeline=False,
                                    endpoint="oracle0.example.com:7050",
                                    endpoints=(
                                        "oracle0.example.com:7050",))
        odl = time.monotonic() + 60
        while oracle.chain.node.leader_id != oracle.chain.node_id:
            if time.monotonic() > odl:
                raise RuntimeError("oracle: no raft leader")
            time.sleep(0.01)
        pos = 0
        committed_envs = [cpb.Envelope.FromString(raw)
                          for raw in committed]
        while pos < len(committed_envs):
            resps = oracle.broadcast.process_messages(
                committed_envs[pos:pos + window])
            ok = sum(1 for r in resps
                     if r.status == cpb.Status.SUCCESS)
            if ok == 0:
                raise RuntimeError("oracle rejected the committed "
                                   "stream")
            pos += ok
        olg = oracle.support.ledger
        odeadline = time.monotonic() + 300
        ocommitted: list = []
        onext = 1
        while True:
            while onext < olg.height:
                b = olg.get_block(onext)
                if b is None:
                    break
                ocommitted.extend(bytes(d) for d in b.data.data)
                onext += 1
            if len(ocommitted) >= len(committed):
                break
            if time.monotonic() > odeadline:
                raise RuntimeError("oracle drain stalled")
            time.sleep(0.05)
        assert ocommitted == committed, \
            "sequential-oracle envelope stream diverged bit-wise"

        # the oracle's creator signed the SAME key: its envelopes ARE
        # the committed bytes, so equality above is bit-identity of
        # everything the overloaded path committed

        # ---- bounded-depth + per-stage shed accounting ----
        depth_violations = {
            name: s for name, s in stages.items()
            if s.get("capacity", 0) > 0
            and s.get("max_depth", 0) > s["capacity"]}
        assert not depth_violations, \
            f"queue depth exceeded its bound: {depth_violations}"
        stage_sheds = {name: int(s.get("sheds", 0))
                       for name, s in stages.items()
                       if s.get("sheds")}

        opstats = svc.chain.order_pipeline_stats()
        committed_rate = (len(committed) /
                          max(offered_s + drain_s, 1e-9))
        offered_rate = n_offered / max(offered_s, 1e-9)
        return {
            "producers": producers,
            "offered": n_offered,
            "accepted": n_accepted,
            "client_shed": n_shed,
            "offered_per_s": round(offered_rate, 1),
            "committed_per_s": round(committed_rate, 1),
            "overcapacity_ratio": round(
                offered_rate / max(committed_rate, 1e-9), 2),
            "max_producer_call_s": round(max(max_call_s), 3),
            "budget_s": budget_s,
            "events_cap": events_cap,
            "stage_sheds": stage_sheds,
            "queue_max_depths": {
                name: s.get("max_depth", 0)
                for name, s in stages.items()
                if s.get("capacity", 0) > 0},
            "demotions": opstats.get("demotions"),
            "blocks": n_blocks,
            "accepted_commit_exact_once": True,
            "oracle_bit_identical": True,
            "run_s": round(offered_s + drain_s, 2),
        }
    finally:
        os.environ.pop("FTPU_RAFT_EVENTS_CAP", None)
        for s in (svc, oracle):
            if s is not None:
                try:
                    s.close(flush=True)
                except Exception:     # noqa: BLE001
                    pass
        shutil.rmtree(root, ignore_errors=True)


def _scheme_mix_run(n_items: int = 96, n_keys: int = 24,
                    hot_keys: int = 4, hot_frac: float = 0.8,
                    ed_items: int = 24, bls_items: int = 4,
                    invalid_frac: float = 0.1,
                    seed: int = 5) -> dict:
    """The Caliper-style scenario-mix side workload of the round-19
    serving rig: ONE mixed batch through a fresh `AdmissionWindow` —
    P-256 endorsement checks under a hot-key vs long-tail key
    distribution (`hot_frac` of items signed by `hot_keys` keys, the
    rest spread over the tail), an Ed25519 MSP slice, a (small — the
    wheel-free pairing costs ~0.25s/verify) BLS consenter slice, and
    an adversarial invalid-signature mix. Every valid item must
    verify, every corrupted one must be refused — the mixed batch
    exercises the window's scheme router + span splitter exactly the
    way a mixed-tenant serving plane would."""
    import hashlib
    import random

    from fabric_tpu.bccsp import (BLSKeyGenOpts, ECDSAKeyGenOpts,
                                  Ed25519KeyGenOpts, VerifyItem)
    from fabric_tpu.bccsp.admission import AdmissionWindow
    from fabric_tpu.bccsp.sw import SWProvider

    rng = random.Random(seed)
    sw = SWProvider()
    window = AdmissionWindow.shared(sw)
    ec_keys = [sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
               for _ in range(n_keys)]
    ed_keys = [sw.key_gen(Ed25519KeyGenOpts(ephemeral=True))
               for _ in range(max(2, hot_keys))]
    bls_keys = [sw.key_gen(BLSKeyGenOpts(ephemeral=True))
                for _ in range(2)]

    items, want, schemes = [], [], []
    key_picks = {"hot": 0, "tail": 0}
    t_sign0 = time.perf_counter()
    for i in range(n_items + ed_items + bls_items):
        msg = f"scheme-mix item {i}".encode()
        if i < n_items:
            if rng.random() < hot_frac:
                key = ec_keys[rng.randrange(hot_keys)]
                key_picks["hot"] += 1
            else:
                key = ec_keys[hot_keys +
                              rng.randrange(n_keys - hot_keys)]
                key_picks["tail"] += 1
            sig = sw.sign(key, hashlib.sha256(msg).digest())
            schemes.append("p256")
        elif i < n_items + ed_items:
            key = ed_keys[rng.randrange(len(ed_keys))]
            sig = sw.sign(key, msg)   # message-based scheme
            schemes.append("ed25519")
        else:
            key = bls_keys[rng.randrange(len(bls_keys))]
            sig = sw.sign(key, msg)
            schemes.append("bls12381")
        ok = rng.random() >= invalid_frac
        if not ok:
            # wrong-message signature: well-formed, must verify False
            bad = msg + b"#tampered"
            if schemes[-1] == "p256":
                sig = sw.sign(key, hashlib.sha256(bad).digest())
            else:
                sig = sw.sign(key, bad)
        items.append(VerifyItem(key=key.public_key(), signature=sig,
                                message=msg))
        want.append(ok)
    sign_s = time.perf_counter() - t_sign0

    t0 = time.perf_counter()
    got = window.verify_batch(items)
    verify_s = time.perf_counter() - t0
    mismatches = [i for i, (g, w) in enumerate(zip(got, want))
                  if bool(g) != w]
    assert not mismatches, \
        (f"scheme-mix verdict mismatch at {mismatches[:5]} "
         f"(schemes {[schemes[i] for i in mismatches[:5]]})")
    return {
        "items": len(items),
        "schemes": {s: schemes.count(s)
                    for s in ("p256", "ed25519", "bls12381")},
        "key_distribution": key_picks,
        "invalid_refused": sum(1 for w in want if not w),
        "sign_s": round(sign_s, 3),
        "verify_s": round(verify_s, 3),
        "verify_per_s": round(len(items) / max(verify_s, 1e-9), 1),
        "q16_table_cache": "skipped (sw provider)",
        "all_verdicts_exact": True,
    }


def adaptive_serving_run(consenters: int = 3, workers: int = 6,
                         ntxs: int = 2400, invalid: int = 48,
                         clients: int = 20000,
                         block_txs: int = 1,
                         slo_target_s: float = 1.5,
                         events_cap: int = 256,
                         interval_s: float = 0.25,
                         warmup_frac: float = 0.25,
                         seed: int = 11,
                         drop_rate: float = 0.02,
                         dup_rate: float = 0.01,
                         reorder_rate: float = 0.02,
                         reorder_window: int = 4,
                         flap_ceiling: int = 6,
                         adjust_ceiling: int = 250,
                         scheme_mix: bool = True,
                         deadline_s: float = 600.0) -> dict:
    """ISSUE 19 acceptance rig: the closed-loop serving benchmark that
    pits the ADAPTIVE admission control plane against the same rig
    with static knobs, and reports **max sustainable tx/s at a held
    p99 commit SLO**.

    Topology per phase (built fresh twice, identical except for the
    controller): a 3-consenter raft ordering cluster with every
    inter-consenter link under seeded network chaos, plus two peers
    fed post-load from DISTINCT consenters (peer0 off the leader's
    deliver stream, peer1 off a follower's) through real
    CommitPipelines. `workers` closed-loop clients — multiplexing
    `clients` simulated client identities (the tx payload carries the
    client id) — submit pre-signed P-256 envelopes one at a time
    under the live ingress deadline budget, with `invalid`
    corrupted-signature envelopes interleaved (they must be refused,
    never committed). `block_txs=1` makes the signed-block writer the
    genuine serving bottleneck (~5ms sign+self-verify per block on
    the wheel-free provider), so offered load really does exceed
    drain capacity and the static phase exhibits bufferbloat: the
    raft events queue absorbs the excess and commit p99 blows through
    the SLO. A watcher thread stamps every commit against its submit
    time and feeds `clustertrace.slo()` live — the burn signal the
    controller (adaptive phase only) closes the loop on, shrinking
    queue capacities and deadline budgets until latency is bounded by
    shallow queues instead of deep ones.

    Methodology (Caliper-style): per phase, p99 and throughput are
    computed over the steady window — commits whose SUBMIT fell after
    `warmup_frac` of the load wall (the warmup covers the
    controller's reaction time in the adaptive phase and the
    queue-growth ramp in the static one); `slo_held` is steady-window
    p99 <= target; `max_sustainable_tx_s` is the adaptive phase's
    steady-window committed rate. `adaptive_beats_static` per the
    acceptance bar: the adaptive phase holds the SLO AND (the static
    phase burns it OR adaptive sustained more tx/s). Controller
    adjustments are bounded: reversals <= `flap_ceiling`, total moves
    <= `adjust_ceiling`. The adaptive phase's committed stream must
    replay bit-identically through a fresh sequential oracle, and
    accepted == committed exactly-once in BOTH phases."""
    import gc
    import shutil
    import threading
    import types

    from fabric_tpu.common import (adaptive, clustertrace, netchaos,
                                   overload, tracing)
    from fabric_tpu.common import metrics as metrics_mod
    from fabric_tpu.common.deliver import DeliverHandler
    from fabric_tpu.core.commitpipeline import CommitPipeline
    from fabric_tpu.core.txvalidator import ValidationResult
    from fabric_tpu.orderer.cluster import LocalClusterNetwork
    from fabric_tpu.peer.deliverclient import seek_envelope
    from fabric_tpu.protos import common as cpb
    from fabric_tpu.protos import transaction as txpb
    from fabric_tpu.protoutil.protoutil import marshal as pu_marshal

    if not adaptive.enabled():
        return {"skipped": "FTPU_ADAPTIVE disabled"}

    root = tempfile.mkdtemp(prefix="bench_adaptive_")
    t_run0 = time.perf_counter()
    deadline = time.monotonic() + deadline_s
    peer_eps = ["peer0.example.com:7051", "peer1.example.com:7052"]
    client = make_order_client(channel="adaptbench")

    # ---- pre-signed envelope pool (untimed setup, shared by both
    # phases — each phase runs over a fresh ledger, so identical tx
    # ids never meet). The payload carries the simulated client id:
    # `workers` threads multiplex `clients` logical clients, the
    # closed-loop Caliper shape.
    pool = []                     # (envelope, marshalled, valid)
    for i in range(ntxs):
        env = client.envelope(
            i, payload=f"c{i % clients}:tx{i}".encode())
        pool.append((env, pu_marshal(env), True))
    inv_step = max(1, ntxs // max(1, invalid))
    for j in range(invalid):
        env = client.envelope(
            ntxs + j, payload=f"c{j % clients}:bad{j}".encode())
        # adversarial mix: a WELL-FORMED signature over the wrong
        # bytes — it must fail verification cleanly (a malformed
        # encoding would test the parser, not the policy)
        env.signature = client.signer.sign(
            env.payload + b"#tampered")
        # interleave the adversarial mix evenly through the stream
        pool.insert(min(len(pool), j * inv_step + inv_step // 2),
                    (env, pu_marshal(env), False))
    invalid_raws = {raw for _e, raw, ok in pool if not ok}

    class _Validator:
        def validate_ahead(self, block, known_txids=None):
            v0 = time.perf_counter()
            n = len(block.data.data)
            return ValidationResult(
                codes=[txpb.TxValidationCode.VALID] * n,
                n_items=n,
                duration_s=time.perf_counter() - v0)

        def publish_validation(self, block, result):
            while len(block.metadata.metadata) <= \
                    cpb.BlockMetadataIndex.TRANSACTIONS_FILTER:
                block.metadata.metadata.append(b"")
            block.metadata.metadata[
                cpb.BlockMetadataIndex.TRANSACTIONS_FILTER] = \
                bytes(result.codes)

        def validate(self, block):
            result = self.validate_ahead(block)
            self.publish_validation(block, result)
            return result.codes

    class _BlockStore:
        @staticmethod
        def block_tx_ids(block):
            return [""] * len(block.data.data)

    class _PeerChan:
        channel_id = client.channel

        def __init__(self):
            self.ledger = types.SimpleNamespace(
                height=1, block_store=_BlockStore())
            self.validator = _Validator()
            self.committed: list = []

        def commit_validated(self, block, codes, rwsets=None,
                             tx_ids=None):
            self.committed.append(block.header.number)
            self.ledger.height = block.header.number + 1
            return list(codes)

        def process_block(self, block):
            codes = self.validator.validate(block)
            return self.commit_validated(block, codes)

    def run_phase(name: str, with_controller: bool) -> dict:
        eps = [f"orderer{i}.{name}.example.com:{7050 + i}"
               for i in range(consenters)]
        tracing.reset()
        clustertrace.reset()
        adaptive.reset()
        gc.collect()
        provider = metrics_mod.PrometheusProvider()
        tracing.bind_metrics(provider)
        clustertrace.configure_slo(slo_target_s)
        chaos = netchaos.NetChaos(seed=seed)
        chaos.set_policy(netchaos.LinkPolicy(
            drop_rate=drop_rate, dup_rate=dup_rate,
            reorder_rate=reorder_rate,
            reorder_window=reorder_window))
        net = LocalClusterNetwork()
        svcs: dict = {}
        pipes: list = []
        ctl = None
        os.environ["FTPU_RAFT_EVENTS_CAP"] = str(events_cap)
        try:
            for i, ep in enumerate(eps):
                svcs[ep] = make_order_service(
                    os.path.join(root, name, f"o{i}"),
                    client=client, channel=client.channel,
                    endpoint=ep, endpoints=eps,
                    net=net, block_txs=block_txs,
                    batch_timeout_s=0.1,
                    # the leader's loop stalls up to ~events_cap x
                    # 5ms in writer backpressure under overload; the
                    # election timeout must ride it out or a healthy
                    # leader gets deposed mid-burn
                    tick_interval_s=0.02, election_tick=200,
                    transport_wrap=chaos.wrap_cluster)
        finally:
            os.environ.pop("FTPU_RAFT_EVENTS_CAP", None)
        try:
            def leader_ep():
                from fabric_tpu.orderer.raft.core import LEADER
                for ep, s in svcs.items():
                    if s.chain.node.state == LEADER:
                        return ep
                return None

            while leader_ep() is None:
                if time.monotonic() > deadline:
                    raise RuntimeError(f"{name}: no raft leader")
                time.sleep(0.005)
            lead = svcs[leader_ep()]

            if with_controller:
                # the shared AdmissionWindow is cached per provider
                # and registered its span knob when the STATIC phase
                # built it; adaptive.reset() cleared the registry, so
                # re-park the knob for this phase's controller
                from fabric_tpu.bccsp.admission import \
                    AdmissionWindow
                win = AdmissionWindow.shared(client.sw)
                if "bccsp.admission.span" not in adaptive.knobs():
                    adaptive.register_attr_knob(
                        win, "max_window_items",
                        "bccsp.admission.span",
                        floor=16, ceiling=win._SPAN_CAP)
                ctl = adaptive.start_controller(
                    metrics_provider=provider,
                    interval_s=interval_s)
                if ctl is None:
                    raise RuntimeError(
                        "adaptive controller failed to start")

            # ---- closed-loop load ----
            slices = [pool[w::workers] for w in range(workers)]
            submit_t: dict = {}
            sub_lock = threading.Lock()
            accepted: list = [[] for _ in range(workers)]
            shed = [0] * workers
            rejected = [0] * workers
            errors: list = []
            committed: list = []
            n_target = [None]      # set once workers finish
            stop_watch = threading.Event()
            lat: list = []         # (submit_t, commit_t, latency_s)

            def worker(w: int) -> None:
                for env, raw, _ok in slices[w]:
                    now = time.perf_counter()
                    with sub_lock:
                        submit_t[raw] = now
                    try:
                        budget = overload.ingress_budget_s()
                        with overload.Deadline.after(
                                budget).applied():
                            resp = lead.broadcast.process_messages(
                                [env])[0]
                    except Exception as e:  # noqa: BLE001
                        errors.append(f"{name} worker {w}: {e!r}")
                        return
                    if resp.status == cpb.Status.SUCCESS:
                        accepted[w].append(raw)
                    else:
                        with sub_lock:
                            submit_t.pop(raw, None)
                        if resp.status == \
                                cpb.Status.SERVICE_UNAVAILABLE:
                            shed[w] += 1
                        else:
                            rejected[w] += 1

            def watcher() -> None:
                ledger = lead.support.ledger
                next_block = 1
                slo = clustertrace.slo()
                while True:
                    advanced = True
                    while advanced:
                        advanced = False
                        while next_block < ledger.height:
                            b = ledger.get_block(next_block)
                            if b is None:
                                break
                            now = time.perf_counter()
                            for d in b.data.data:
                                raw = bytes(d)
                                with sub_lock:
                                    st = submit_t.get(raw)
                                if st is not None:
                                    lsec = now - st
                                    slo.observe(lsec)
                                    lat.append((st, now, lsec))
                                committed.append(raw)
                            next_block += 1
                            advanced = True
                    if stop_watch.is_set():
                        return
                    if n_target[0] is not None and \
                            len(committed) >= n_target[0]:
                        return
                    time.sleep(0.02)

            t_load0 = time.perf_counter()
            wthreads = [threading.Thread(
                target=worker, args=(w,),
                name=f"adaptive-client-{w}")
                for w in range(workers)]
            watch = threading.Thread(target=watcher,
                                     name="adaptive-watcher")
            watch.start()
            for t in wthreads:
                t.start()
            for t in wthreads:
                t.join(timeout=max(5.0,
                                   deadline - time.monotonic()))
            if errors:
                raise RuntimeError("; ".join(errors[:3]))
            n_accepted = sum(len(a) for a in accepted)
            n_target[0] = n_accepted
            watch.join(timeout=max(5.0,
                                   deadline - time.monotonic()))
            if watch.is_alive():
                stop_watch.set()
                watch.join(timeout=5.0)
                raise RuntimeError(
                    f"{name}: drain stalled at "
                    f"{len(committed)}/{n_accepted}")
            load_s = time.perf_counter() - t_load0

            # ---- exactly-once + adversarial-mix accounting ----
            accepted_set = {raw for a in accepted for raw in a}
            assert len(committed) == n_accepted, \
                (name, len(committed), n_accepted)
            assert set(committed) == accepted_set, \
                f"{name}: committed stream diverged from accepted"
            assert not (invalid_raws & set(committed)), \
                f"{name}: an invalid-signature envelope committed"
            n_rejected = sum(rejected)
            assert n_rejected <= invalid, (name, n_rejected)

            # ---- steady-window latency + throughput ----
            cut = t_load0 + warmup_frac * load_s
            steady = [x for x in lat if x[0] >= cut] or lat
            lats = sorted(x[2] for x in steady)
            p99 = lats[int(0.99 * (len(lats) - 1))] if lats else 0.0
            p50 = lats[len(lats) // 2] if lats else 0.0
            span0 = min(x[0] for x in steady) if steady else cut
            span1 = max(x[1] for x in steady) if steady else cut
            tx_s = len(steady) / max(span1 - span0, 1e-9)

            stages = overload.stage_stats()
            stage_sheds = {n: int(s.get("sheds", 0))
                           for n, s in stages.items()
                           if s.get("sheds")}
            # the raft events queues carry a FORCED control-plane
            # lane (consensus steps, bounded at 4x the data-plane
            # capacity) — their depth bound is 5x; everything else
            # must honor its configured capacity exactly
            depth_violations = {
                n: s for n, s in stages.items()
                if s.get("capacity", 0) > 0
                and s.get("max_depth", 0) > s["capacity"] *
                (5 if s.get("forced") else 1)}
            assert not depth_violations, \
                f"{name}: depth bound broken: {depth_violations}"

            # ---- both peers commit the full chain, fed from
            # DISTINCT consenters ----
            while True:
                heights = [s.support.ledger.height
                           for s in svcs.values()]
                if len(set(heights)) == 1:
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"{name}: consenters never converged "
                        f"{heights}")
                time.sleep(0.02)
            height = heights[0]
            chans = [_PeerChan() for _ in peer_eps]
            pipes = [CommitPipeline(chan, depth=1, node_id=pep)
                     for chan, pep in zip(chans, peer_eps)]
            follower = next(s for s in svcs.values()
                            if s is not lead)
            feed_errors: list = []

            def feed(src, pipe, pep):
                try:
                    handler = DeliverHandler(
                        lambda cid: src.support
                        if cid == client.channel else None)
                    seek = seek_envelope(client.channel, 1,
                                         client.signer,
                                         stop=height - 1)
                    for resp in handler.handle(seek):
                        if resp.WhichOneof("type") != "block":
                            break
                        blk = resp.block
                        carrier = clustertrace.block_carrier(
                            client.channel, blk.header.number)
                        with clustertrace.resumed(
                                carrier,
                                link=f"deliver:"
                                     f"{src.transport.endpoint}",
                                node=pep):
                            pipe.submit(blk.header.number,
                                        block=blk)
                except Exception as e:  # noqa: BLE001 — surfaced below
                    feed_errors.append(f"{pep}: {e}")

            fthreads = [
                threading.Thread(target=feed,
                                 args=(src, pipe, pep),
                                 name=f"adaptive-feed-{pep}")
                for src, pipe, pep in zip((lead, follower), pipes,
                                          peer_eps)]
            for t in fthreads:
                t.start()
            for t in fthreads:
                t.join(timeout=max(5.0,
                                   deadline - time.monotonic()))
            if feed_errors:
                raise RuntimeError("; ".join(feed_errors))
            for p in pipes:
                p.drain(timeout=max(5.0,
                                    deadline - time.monotonic()))
            for chan in chans:
                assert len(chan.committed) == height - 1, \
                    (name, len(chan.committed), height - 1)

            out = {
                "offered": len(pool),
                "accepted": n_accepted,
                "shed": sum(shed),
                "rejected_invalid": n_rejected,
                "committed": len(committed),
                "blocks": height - 1,
                "peer_commits": [len(c.committed) for c in chans],
                "load_s": round(load_s, 2),
                "steady_obs": len(steady),
                "commit_p50_s": round(p50, 3),
                "commit_p99_s": round(p99, 3),
                "tx_s": round(tx_s, 1),
                "slo_held": bool(p99 <= slo_target_s),
                "slo_over_target": clustertrace.slo().stats[
                    "over_target"],
                "stage_sheds": stage_sheds,
                "chaos": {k: chaos.stats[k]
                          for k in ("sent", "dropped", "duplicated",
                                    "reordered")},
            }
            if ctl is not None:
                ctl_stats = dict(ctl.stats)
                out["controller"] = ctl_stats
                out["knobs_final"] = {
                    n: k.value()
                    for n, k in sorted(adaptive.knobs().items())}
                rendered = provider.render() \
                    if hasattr(provider, "render") else ""
                out["adaptive_metrics_rendered"] = bool(
                    ctl_stats.get("moves", 0) == 0 or
                    "adaptive_knob_value" in rendered)
            return out, committed
        finally:
            stop_w = locals().get("stop_watch")
            if stop_w is not None:
                stop_w.set()
            if ctl is not None:
                adaptive.stop_controller()
            for p in pipes:
                try:
                    p.stop()
                except Exception:     # noqa: BLE001
                    pass
            for s in svcs.values():
                try:
                    s.close(flush=True)
                except Exception:     # noqa: BLE001
                    pass
            chaos.close()
            clustertrace.configure_slo(None)

    oracle = None
    try:
        static_res, _static_committed = run_phase("static", False)
        adaptive_res, committed = run_phase("adaptive", True)

        # ---- sequential-oracle replay of the ADAPTIVE phase's
        # committed stream (same client: the oracle must accept the
        # exact committed bytes) ----
        oracle = make_order_service(
            os.path.join(root, "oracle"), client=client,
            channel=client.channel,
            block_txs=64, batch_timeout_s=0.2,
            write_pipeline=False,
            endpoint="oracle0.example.com:7050",
            endpoints=("oracle0.example.com:7050",))
        odl = time.monotonic() + 60
        while oracle.chain.node.leader_id != oracle.chain.node_id:
            if time.monotonic() > odl:
                raise RuntimeError("oracle: no raft leader")
            time.sleep(0.01)
        committed_envs = [cpb.Envelope.FromString(raw)
                          for raw in committed]
        pos = 0
        while pos < len(committed_envs):
            resps = oracle.broadcast.process_messages(
                committed_envs[pos:pos + 64])
            ok = sum(1 for r in resps
                     if r.status == cpb.Status.SUCCESS)
            if ok == 0:
                raise RuntimeError(
                    "oracle rejected the committed stream")
            pos += ok
        olg = oracle.support.ledger
        ocommitted: list = []
        onext = 1
        while len(ocommitted) < len(committed):
            while onext < olg.height:
                b = olg.get_block(onext)
                if b is None:
                    break
                ocommitted.extend(bytes(d) for d in b.data.data)
                onext += 1
            if time.monotonic() > deadline:
                raise RuntimeError("oracle drain stalled")
            time.sleep(0.02)
        assert ocommitted == committed, \
            "sequential-oracle envelope stream diverged bit-wise"

        ctl_stats = adaptive_res.get("controller", {})
        moves = int(ctl_stats.get("moves", 0))
        reversals = int(ctl_stats.get("reversals", 0))
        no_flap = (reversals <= flap_ceiling and
                   moves <= adjust_ceiling)
        beats = bool(
            adaptive_res["slo_held"] and
            (not static_res["slo_held"] or
             adaptive_res["tx_s"] > static_res["tx_s"]))
        res = {
            "consenters": consenters,
            "peers": len(peer_eps),
            "workers": workers,
            "clients_simulated": clients,
            "ntxs_per_phase": len(pool),
            "invalid_per_phase": invalid,
            "block_txs": block_txs,
            "events_cap": events_cap,
            "slo_target_s": slo_target_s,
            "warmup_frac": warmup_frac,
            "static": static_res,
            "adaptive": adaptive_res,
            "max_sustainable_tx_s": adaptive_res["tx_s"],
            "slo_held": adaptive_res["slo_held"],
            "adaptive_beats_static": beats,
            "controller_moves": moves,
            "controller_reversals": reversals,
            "flap_ceiling": flap_ceiling,
            "adjust_ceiling": adjust_ceiling,
            "no_flap": no_flap,
            "accepted_commit_exact_once": True,
            "oracle_bit_identical": True,
        }
        if scheme_mix:
            try:
                res["scheme_mix"] = _scheme_mix_run()
            except Exception as e:    # noqa: BLE001
                res["scheme_mix"] = {
                    "error": f"{type(e).__name__}: {e}"}
        res["run_s"] = round(time.perf_counter() - t_run0, 2)
        return res
    finally:
        if oracle is not None:
            try:
                oracle.close(flush=True)
            except Exception:         # noqa: BLE001
                pass
        from fabric_tpu.common import adaptive as _ad
        _ad.reset()
        shutil.rmtree(root, ignore_errors=True)


def failover_run(consenters: int = 3, producers: int = 2,
                 ntxs_per_producer: int = 60, window: int = 12,
                 block_txs: int = 8, seed: int = 7,
                 drop_rate: float = 0.10, dup_rate: float = 0.05,
                 reorder_rate: float = 0.10, reorder_window: int = 4,
                 kill_after: float = 0.35,
                 partition_s: float = 0.3,
                 reelect_bound_s: float = 30.0) -> dict:
    """ISSUE 13 soak: a 3-consenter raft ordering cluster with every
    inter-consenter link under seeded network chaos (drop + duplicate
    + bounded reorder, `common/netchaos.py`), the LEADER killed
    crash-equivalently mid-load, and — after re-election — one
    surviving follower partitioned and healed. The claims:

      * ordering recovers within a bounded re-election window
        (`failover_reelect_s` < `reelect_bound_s`), attributable via
        `raft.leader_change` tracing instants and a parseable
        flight-recorder auto-dump;
      * the survivors' committed block streams are BYTE-IDENTICAL
        (numbers, prev-hash linkage, data hashes, envelope bytes);
      * exactly-once: no envelope commits twice, and every ACCEPTED
        (SUCCESS-acked) envelope commits — acks lost with the dead
        leader are reconciled by resubmission AFTER quiescence, the
        real client protocol;
      * the committed stream replays bit-identically through a fresh
        sequential oracle service (the PR-9 oracle-replay check).

    Chaos decisions are seeded (`seed`) so a failing run reproduces."""
    import shutil
    import threading

    from fabric_tpu.common import netchaos, tracing
    from fabric_tpu.protos import common as cpb
    from fabric_tpu.protoutil.protoutil import marshal as pu_marshal

    from fabric_tpu.common import clustertrace

    root = tempfile.mkdtemp(prefix="bench_failover_")
    dump_dir = os.path.join(root, "traces")
    chaos = netchaos.NetChaos(seed=seed)
    chaos.set_policy(netchaos.LinkPolicy(
        drop_rate=drop_rate, dup_rate=dup_rate,
        reorder_rate=reorder_rate, reorder_window=reorder_window))
    eps = [f"orderer{i}.example.com:{7050 + i}"
           for i in range(consenters)]
    svcs: dict = {}
    oracle = None
    t_run0 = time.perf_counter()
    try:
        tracing.reset()
        # the birth/block-carrier registries are keyed by (channel,
        # number) on the SHARED default channel: an earlier bench
        # section's first-wins registrations would otherwise shadow
        # this one's
        clustertrace.reset()
        tracing.configure(dump_dir=dump_dir)
        from fabric_tpu.orderer.cluster import LocalClusterNetwork
        net = LocalClusterNetwork()
        client = make_order_client()
        for i, ep in enumerate(eps):
            svcs[ep] = make_order_service(
                os.path.join(root, f"o{i}"), client=client,
                endpoint=ep, endpoints=eps, net=net,
                block_txs=block_txs, batch_timeout_s=0.1,
                tick_interval_s=0.01, election_tick=8,
                transport_wrap=chaos.wrap_cluster)
        alive = dict(svcs)

        def current_leader(services=None):
            from fabric_tpu.orderer.raft.core import LEADER
            for ep, s in (services or alive).items():
                if s.chain.node.state == LEADER:
                    return ep
            return None

        def wait_leader(bound_s, services=None):
            deadline = time.monotonic() + bound_s
            while time.monotonic() < deadline:
                ep = current_leader(services)
                if ep is not None:
                    return ep
                time.sleep(0.005)
            raise RuntimeError(f"no raft leader inside {bound_s}s")

        wait_leader(60.0)

        # pre-sign every envelope (untimed CPU setup); globally unique
        all_envs = [[client.envelope(p * 1_000_000 + i)
                     for i in range(ntxs_per_producer)]
                    for p in range(producers)]
        n_offered = producers * ntxs_per_producer

        accepted_lock = threading.Lock()
        accepted: set = set()          # marshaled envelope bytes
        unknown: set = set()           # outcome lost with a dying node
        shed = [0]
        errors: list = []

        def producer(p: int) -> None:
            envs = all_envs[p]
            pos = 0
            rotation = 0
            deadline = time.monotonic() + 180
            while pos < len(envs):
                if time.monotonic() > deadline:
                    errors.append(f"producer {p}: offered-load "
                                  f"deadline at {pos}/{len(envs)}")
                    return
                targets = list(alive.values())
                svc = targets[(p + rotation) % len(targets)]
                batch = envs[pos:pos + window]
                try:
                    resps = svc.broadcast.process_messages(batch)
                except Exception:   # noqa: BLE001 — a dying node mid-call:
                    # outcome UNKNOWN (it may have enqueued a prefix);
                    # reconciliation decides after quiescence
                    with accepted_lock:
                        unknown.update(pu_marshal(e) for e in batch)
                    pos += len(batch)
                    rotation += 1
                    continue
                ok = 0
                for resp in resps:
                    if resp.status == cpb.Status.SUCCESS:
                        ok += 1
                    elif resp.status == cpb.Status.SERVICE_UNAVAILABLE:
                        shed[0] += 1
                        break       # election wobble: retry the tail
                    else:
                        errors.append(f"producer {p}: {resp.status} "
                                      f"{resp.info}")
                        return
                with accepted_lock:
                    accepted.update(pu_marshal(e)
                                    for e in batch[:ok])
                pos += ok
                if ok == 0:
                    rotation += 1
                    time.sleep(0.02)

        threads = [threading.Thread(target=producer, args=(p,),
                                    name=f"failover-producer-{p}")
                   for p in range(producers)]
        for t in threads:
            t.start()

        # ---- the kill: wait for part of the load, then crash the
        # leader (no flush — its unwritten blocks die with it) ----
        kill_threshold = int(kill_after * n_offered)
        deadline = time.monotonic() + 120
        while True:
            with accepted_lock:
                n_acc = len(accepted)
            if n_acc >= kill_threshold:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"load never reached the kill threshold "
                    f"({n_acc}/{kill_threshold}; errors={errors[:2]})")
            time.sleep(0.005)
        victim_ep = wait_leader(30.0)
        victim = alive[victim_ep]
        # rebind (never mutate) the shared dict: producer threads are
        # mid-iteration over it without a lock, and a pop() here would
        # kill one with 'dictionary changed size' OUTSIDE its
        # try/except — silently weakening the offered load
        alive = {ep: s for ep, s in alive.items()
                 if ep != victim_ep}
        t_kill = time.monotonic()
        victim.close(flush=False)
        new_leader_ep = wait_leader(reelect_bound_s, services=alive)
        reelect_s = time.monotonic() - t_kill

        # ---- one partition-and-heal on a surviving follower ----
        follower_eps = [ep for ep in alive if ep != new_leader_ep]
        if follower_eps and partition_s > 0:
            chaos.partition([follower_eps[0]],
                            heal_after_s=partition_s)

        for t in threads:
            t.join(timeout=240)
        if errors:
            raise RuntimeError("; ".join(errors[:3]))

        # ---- quiesce: survivor streams equal and stable ----
        def read_stream(svc, timeout_s: float = 10.0):
            """Fully-readable committed stream: `height` can advance
            a beat before the row is visible to this reader thread
            (async write stage) — retry until every block reads."""
            lg = svc.support.ledger
            rd = time.monotonic() + timeout_s
            while True:
                h = lg.height
                out = []
                for n in range(h):
                    b = lg.get_block(n)
                    if b is None:
                        break
                    out.append(b)
                if len(out) == h or time.monotonic() > rd:
                    return out
                time.sleep(0.01)

        def survivor_streams():
            return {ep: read_stream(s) for ep, s in alive.items()}

        # stability is detected on the CHEAP height signal (monotonic;
        # a full read_stream per 50ms poll would proto-decode every
        # block of every survivor hundreds of times) — the full
        # visibility-retrying reads happen once afterwards
        deadline = time.monotonic() + 240
        stable_since = None
        last_sig = None
        while True:
            sig = tuple(s.support.ledger.height
                        for s in alive.values())
            now = time.monotonic()
            if sig != last_sig or len(set(sig)) != 1:
                last_sig, stable_since = sig, now
            elif now - stable_since >= 1.0:
                break
            if now > deadline:
                raise RuntimeError(f"survivors never quiesced: {sig}")
            time.sleep(0.05)

        # ---- reconcile: resubmit accepted/unknown envelopes the dead
        # leader lost, then re-quiesce ----
        def committed_envs():
            streams = survivor_streams()
            ref = streams[new_leader_ep]
            return [bytes(d) for b in ref[1:] for d in b.data.data]

        committed = committed_envs()
        cset = set(committed)
        with accepted_lock:
            tracked = set(accepted) | set(unknown)
        missing = (set(accepted) - cset) | (set(unknown) - cset)
        resubmitted = len(missing)
        if missing:
            leader_svc = alive[wait_leader(30.0, services=alive)]
            todo = [cpb.Envelope.FromString(raw)
                    for raw in sorted(missing)]
            pos = 0
            deadline = time.monotonic() + 120
            while pos < len(todo):
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"reconciliation stalled at {pos}/{len(todo)}")
                resps = leader_svc.broadcast.process_messages(
                    todo[pos:pos + window])
                ok = sum(1 for r in resps
                         if r.status == cpb.Status.SUCCESS)
                pos += ok
                if ok == 0:
                    time.sleep(0.02)
            with accepted_lock:
                accepted.update(pu_marshal(e) for e in todo)
            deadline = time.monotonic() + 240
            last_hs = None
            while True:
                hs = tuple(s.support.ledger.height
                           for s in alive.values())
                if hs != last_hs:
                    # re-read (and re-decode) the chain only when the
                    # cheap height signal moved
                    committed = committed_envs()
                    last_hs = hs
                if set(committed) >= set(accepted) and \
                        len(set(hs)) == 1:
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError("resubmitted envelopes never "
                                       "all committed")
                time.sleep(0.05)

        # ---- the contract ----
        dup_count = len(committed) - len(set(committed))
        assert dup_count == 0, \
            f"{dup_count} envelope(s) committed more than once"
        with accepted_lock:
            lost = set(accepted) - set(committed)
        assert not lost, f"{len(lost)} accepted envelope(s) lost"
        stray = set(committed) - tracked - set(accepted)
        assert not stray, \
            f"{len(stray)} committed envelope(s) never offered"

        streams = survivor_streams()
        ref_ep, ref = next(iter(streams.items()))
        for ep, st in streams.items():
            assert len(st) == len(ref), (ep, len(st), len(ref))
            for x, y in zip(ref, st):
                assert (x.header.number == y.header.number and
                        x.header.previous_hash ==
                        y.header.previous_hash and
                        x.header.data_hash == y.header.data_hash and
                        list(x.data.data) == list(y.data.data)), \
                    f"survivor streams diverge at block " \
                    f"{x.header.number} ({ref_ep} vs {ep})"

        # ---- failover attribution: instants + parseable auto-dump ----
        leader_changes = sum(
            1 for e in tracing.snapshot()
            if e[0] == "i" and e[1] == "raft.leader_change")
        assert leader_changes >= consenters + 1, leader_changes

        # round-18 contract: with wire-carrier propagation the
        # ordering traces CROSS consenters — the leader's windows
        # must show resumed consensus hops on other nodes' tracks
        # even under chaos (dup/reorder forward carriers, drops just
        # lose hops)
        multi_node_traces = 0
        if tracing.enabled():
            trace_node_sets: dict = {}
            for e in tracing.snapshot():
                if e[2] is not None and e[10] is not None:
                    trace_node_sets.setdefault(e[2], set()).add(e[10])
            multi_node_traces = sum(
                1 for s in trace_node_sets.values() if len(s) >= 2)
            assert multi_node_traces > 0, \
                "no trace crossed a consenter boundary"
        tracing.wait_dumps()
        dump_path = None
        if os.path.isdir(dump_dir):
            dumps = sorted(
                f for f in os.listdir(dump_dir)
                if "leader_change" in f and f.endswith(".json"))
            if dumps:
                dump_path = os.path.join(dump_dir, dumps[-1])
                with open(dump_path, encoding="utf-8") as f:
                    doc = json.load(f)
                assert doc.get("traceEvents"), "empty failover dump"
        assert dump_path is not None, \
            "no leader_change flight-recorder dump was written"

        # ---- sequential-oracle replay, bit-identical ----
        oracle = make_order_service(
            os.path.join(root, "oracle"), client=client,
            block_txs=block_txs, batch_timeout_s=0.1,
            write_pipeline=False,
            endpoint="oracle0.example.com:7050",
            endpoints=("oracle0.example.com:7050",))
        odl = time.monotonic() + 60
        while oracle.chain.node.leader_id != oracle.chain.node_id:
            if time.monotonic() > odl:
                raise RuntimeError("oracle: no raft leader")
            time.sleep(0.01)
        committed_objs = [cpb.Envelope.FromString(raw)
                          for raw in committed]
        pos = 0
        odl = time.monotonic() + 240
        while pos < len(committed_objs):
            resps = oracle.broadcast.process_messages(
                committed_objs[pos:pos + window])
            ok = sum(1 for r in resps
                     if r.status == cpb.Status.SUCCESS)
            if ok == 0 and time.monotonic() > odl:
                raise RuntimeError("oracle rejected the committed "
                                   "stream")
            pos += ok
            if ok == 0:
                time.sleep(0.02)
        olg = oracle.support.ledger
        ocommitted: list = []
        onext = 1
        odl = time.monotonic() + 240
        while len(ocommitted) < len(committed):
            while onext < olg.height:
                b = olg.get_block(onext)
                if b is None:
                    break
                ocommitted.extend(bytes(d) for d in b.data.data)
                onext += 1
            if time.monotonic() > odl:
                raise RuntimeError("oracle drain stalled")
            time.sleep(0.02)
        assert ocommitted == committed, \
            "oracle envelope stream diverged bit-wise"

        with accepted_lock:
            n_accepted = len(accepted)
        return {
            "consenters": consenters,
            "offered": n_offered,
            "accepted": n_accepted,
            "unknown_outcome": len(unknown),
            "client_shed": shed[0],
            "resubmitted": resubmitted,
            "committed": len(committed),
            "duplicates": 0,
            "reelect_s": round(reelect_s, 3),
            "reelect_bound_s": reelect_bound_s,
            "leader_changes": leader_changes,
            "killed_leader": victim_ep,
            "survivor_streams_identical": True,
            "accepted_commit_exact_once": True,
            "oracle_bit_identical": True,
            "multi_node_traces": multi_node_traces,
            "trace_dump": dump_path,
            "chaos_dropped": chaos.stats["dropped"],
            "chaos_duplicated": chaos.stats["duplicated"],
            "chaos_reordered": chaos.stats["reordered"],
            "chaos_partitioned": chaos.stats["partitioned"],
            "chaos_heals": chaos.stats["heals"],
            "run_s": round(time.perf_counter() - t_run0, 2),
        }
    finally:
        for s in list(svcs.values()) + ([oracle] if oracle else []):
            try:
                s.close(flush=False)
            except Exception:     # noqa: BLE001
                pass
        chaos.close()
        tracing.configure(
            dump_dir=os.environ.get("FTPU_TRACE_DUMP_DIR", ""))
        shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# Round-15 crash-point recovery matrix: subprocess children.
#
# The harness (tests/test_net_chaos.py) runs these as KILLED AND
# RESTARTED real processes: run 1 arms a `crash`-mode fault at one
# durable-write seam (raft.wal_append / order.block_write /
# onboarding.commit) via FTPU_FAULTS and dies mid-stream (os._exit
# 137, a power loss at the seam); run 2 reopens the same root, replays
# from the WAL/ledger, reports the replayed stream's per-block digests,
# pumps whatever payloads are still missing, and asserts exactly-once;
# run 3 reopens again and must report the IDENTICAL digests (restart
# replay is deterministic and bit-identical).
# ---------------------------------------------------------------------------


def _block_digest(block) -> str:
    """Digest over EVERYTHING durable — header, envelope bytes AND
    metadata (a restart replays stored bytes, it never re-signs, so
    bit-identity across reopen includes each block's signature)."""
    import hashlib

    h = hashlib.sha256()
    h.update(block.header.number.to_bytes(8, "big"))
    h.update(bytes(block.header.previous_hash))
    h.update(bytes(block.header.data_hash))
    for d in block.data.data:
        h.update(len(d).to_bytes(4, "big"))
        h.update(bytes(d))
    for m in block.metadata.metadata:
        h.update(len(m).to_bytes(4, "big"))
        h.update(bytes(m))
    return h.hexdigest()


def crash_matrix_order_child(root: str, ntxs: int = 16,
                             block_txs: int = 4) -> dict:
    """One crash-matrix cell over the raft ordering service: open (or
    reopen) the service at `root`, report the REPLAYED stream, then
    pump every payload of range(ntxs) not yet committed — one block's
    worth at a time, waiting each out, so the WAL-append / block-write
    seams are crossed once per batch and an armed crash fault lands
    mid-stream deterministically."""
    from fabric_tpu.protos import common as cpb
    from fabric_tpu.protoutil import protoutil as pu

    svc = make_order_service(root, block_txs=block_txs,
                             batch_timeout_s=0.05,
                             tick_interval_s=0.01)
    try:
        ledger = svc.support.ledger
        client = svc.client

        def stream():
            # a block can be committed-but-mid-append in the write
            # stage: read the contiguous written prefix only
            out = []
            for n in range(ledger.height):
                b = ledger.get_block(n)
                if b is None:
                    break
                out.append(b)
            return out

        def payload_counts():
            counts: dict = {}
            for b in stream()[1:]:
                for raw in b.data.data:
                    env = pu.unmarshal_envelope(bytes(raw))
                    data = bytes(pu.get_payload(env).data)
                    counts[data] = counts.get(data, 0) + 1
            return counts

        replay_digests = [_block_digest(b) for b in stream()]

        deadline = time.monotonic() + 60
        while svc.chain.node.leader_id != svc.chain.node_id:
            if time.monotonic() > deadline:
                raise RuntimeError("no raft leader after 60s")
            time.sleep(0.005)

        want = {f"tx{i}".encode(): i for i in range(ntxs)}
        have = payload_counts()
        missing = [i for data, i in sorted(want.items(),
                                           key=lambda kv: kv[1])
                   if data not in have]
        pumped = 0
        for lo in range(0, len(missing), block_txs):
            batch = [client.envelope(i)
                     for i in missing[lo:lo + block_txs]]
            pos = 0
            deadline = time.monotonic() + 60
            while pos < len(batch):
                resps = svc.broadcast.process_messages(batch[pos:])
                pos += sum(1 for r in resps
                           if r.status == cpb.Status.SUCCESS)
                if time.monotonic() > deadline:
                    raise RuntimeError("pump stalled")
                if pos < len(batch):
                    time.sleep(0.01)
            pumped += len(batch)
            # wait THIS batch durable before the next: one admission
            # window -> one WAL append -> one block write per batch
            deadline = time.monotonic() + 60
            while sum(payload_counts().get(
                    f"tx{i}".encode(), 0)
                    for i in missing[lo:lo + block_txs]) < len(batch):
                if time.monotonic() > deadline:
                    raise RuntimeError("batch never committed")
                time.sleep(0.01)

        counts = payload_counts()
        exact_once = (sorted(counts) == sorted(want) and
                      all(v == 1 for v in counts.values()))
        final = stream()
        return {
            "replay_height": len(replay_digests),
            "replay_digests": replay_digests,
            "height": len(final),
            "block_digests": [_block_digest(b) for b in final],
            "payloads_exact_once": exact_once,
            "pumped": pumped,
            "ntxs": ntxs,
        }
    finally:
        svc.close(flush=True)


def crash_matrix_onboard_child(root: str, nblocks: int = 9) -> dict:
    """The onboarding-commit crash-matrix cell: replicate a
    deterministic stub-signed chain (the test_onboarding seam shape)
    into a DURABLE OrdererLedger through the real ChainReplicator —
    `onboarding.commit=crash:1:k` kills the process at the k-th
    commit; the rerun must resume from the durable prefix and finish
    with a replica bit-identical to the source."""
    import hashlib
    from types import SimpleNamespace

    from fabric_tpu.orderer import onboarding as onb
    from fabric_tpu.orderer.multichannel import OrdererLedger
    from fabric_tpu.common.backoff import FullJitterBackoff
    from fabric_tpu.protos import common as cpb
    from fabric_tpu.protos import configtx as ctxpb
    from fabric_tpu.protoutil import protoutil as pu

    channel = "crashonb"
    signer = b"orderer-a"

    def sign(ident: bytes, msg: bytes) -> bytes:
        return hashlib.sha256(b"stubsig|" + ident + b"|" + msg) \
            .digest()

    class _Csp:
        def verify_batch(self, items):
            return [sig == sign(ident, msg)
                    for ident, msg, sig in items]

    class _Prepared:
        def __init__(self, signed):
            self.items = [(sd.identity, sd.data, sd.signature)
                          for sd in signed]
            self._signed = signed

        def finish(self, ok):
            for sd, o in zip(self._signed, ok):
                if o and sd.identity == signer:
                    return
            raise RuntimeError("no valid orderer signature")

    class _Policy:
        def prepare(self, signed):
            return _Prepared(signed)

    meta = ctxpb.ConsensusMetadata()
    c = meta.consenters.add()
    c.host, c.port = "src.example.com", 7050
    bundle = SimpleNamespace(
        csp=_Csp(),
        policy_manager=SimpleNamespace(
            get_policy=lambda path: _Policy()),
        orderer=SimpleNamespace(
            consensus_metadata=meta.SerializeToString(
                deterministic=True)))

    # deterministic source chain: both the crashed and the resumed
    # child regenerate the identical bytes
    blocks = []
    prev = b""
    for i in range(nblocks):
        block = pu.new_block(i, prev)
        block.data.data.append(b"onb-payload-%d" % i)
        block.header.data_hash = pu.block_data_hash(block.data)
        md = cpb.Metadata()
        md.value = pu.encode_last_config(0)
        if i > 0:
            ms = md.signatures.add()
            ms.signature_header = pu.marshal(
                pu.create_signature_header(signer, b"n" * 24))
            ms.signature = sign(
                signer, md.value + ms.signature_header +
                pu.block_header_bytes(block.header))
        block.metadata.metadata[
            cpb.BlockMetadataIndex.SIGNATURES] = pu.marshal(md)
        blocks.append(block)
        prev = pu.block_header_hash(block.header)

    class _Transport:
        endpoint = "joiner.example.com:0"

        def pull_blocks(self, ep, cid, start, end):
            return [b for b in blocks
                    if start <= b.header.number < end]

    ledger = OrdererLedger(os.path.join(root, "replica"))
    try:
        class _LedgerSink:
            def height(self):
                return ledger.height

            def tip_hash(self):
                if ledger.height == 0:
                    return None
                return pu.block_header_hash(
                    ledger.get_block(ledger.height - 1).header)

            def verify(self, span):
                n, bundle_after, err = onb.verify_block_span(
                    channel, span, self.height(), self.tip_hash(),
                    bundle)
                return n, err

            def commit(self, block):
                ledger.add_block(block)

        replay_digests = [_block_digest(ledger.get_block(n))
                          for n in range(ledger.height)]
        rep = onb.ChainReplicator(
            channel, _Transport(),
            consenters_fn=lambda: ["src.example.com:7050"],
            sink=_LedgerSink(), batch=3,
            backoff=FullJitterBackoff(0.001, 0.01))
        rep.run(target_height=nblocks, max_wall_s=60.0)

        replica = [ledger.get_block(n) for n in range(ledger.height)]
        source_digests = [_block_digest(b) for b in blocks]
        replica_digests = [_block_digest(b) for b in replica]
        return {
            "replay_height": len(replay_digests),
            "replay_digests": replay_digests,
            "height": len(replica),
            "block_digests": replica_digests,
            "source_digests": source_digests,
            "matches_source": replica_digests == source_digests,
            "replay_is_source_prefix": replay_digests ==
            source_digests[:len(replay_digests)],
        }
    finally:
        ledger.close()


def _have_openssl_cp() -> bool:
    try:
        from fabric_tpu.bccsp._crypto_compat import HAVE_CRYPTOGRAPHY
        return bool(HAVE_CRYPTOGRAPHY)
    except Exception:                     # noqa: BLE001
        return False


def commit_pipeline_run(n_blocks: int = 6, ntxs: int = 24) -> dict:
    """ISSUE 4 scenario: sequential vs depth-1 overlapped intake on a
    synthetic multi-block stream — REAL per-tx signature verification
    (stage A, batched through the BCCSP seam; pure-python P-256 when
    the OpenSSL wheel is absent) against REAL KVLedger commits (stage
    B), wheel-free so the bounded default bench can always run it.
    Reports both wall clocks and the pipeline's measured overlap."""
    import hashlib
    import tempfile

    from fabric_tpu import protoutil as pu
    from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem
    from fabric_tpu.bccsp.sw import SWProvider
    from fabric_tpu.core.commitpipeline import CommitPipeline
    from fabric_tpu.core.committer import LedgerCommitter
    from fabric_tpu.core.txvalidator import ValidationResult
    from fabric_tpu.ledger import KVLedger
    from fabric_tpu.ledger.kvdb import DBHandle, KVStore
    from fabric_tpu.ledger.kvledger import extract_tx_rwset
    from fabric_tpu.ledger.statedb import StateDB
    from fabric_tpu.ledger.txmgr import TxSimulator
    from fabric_tpu.protos import common as cpb, proposal as proppb
    from fabric_tpu.protos import transaction as txpb

    from fabric_tpu.common import tracing

    channel = "cpbench"
    root = tempfile.mkdtemp(prefix="bench_cp_")
    seq = piped = pipeline = None
    scratch_kv = None
    try:
        # clean stage reservoirs + carrier registries: this run's
        # validate/commit tails must describe THIS rig, not earlier
        # bench sections
        tracing.reset()
        from fabric_tpu.common import clustertrace
        clustertrace.reset()
        sw = SWProvider()
        key = sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
        pub = key.public_key()

        class Signer:
            def serialize(self):
                return b"bench-client"

            def sign(self, msg):
                return sw.sign(key, hashlib.sha256(msg).digest())

        # ---- build the stream once (signing is untimed setup) ----
        scratch_kv = KVStore(os.path.join(root, "scratch.db"))
        scratch = StateDB(DBHandle(scratch_kv, "s"))

        def tx_env(i):
            sim = TxSimulator(scratch, "sim")
            sim.put_state("bench", f"k{i}", f"v{i}".encode())
            results = pu.marshal(sim.get_tx_simulation_results())
            prop, _tx_id = pu.create_proposal(channel, "bench",
                                              [b"invoke"],
                                              creator=b"bench-client")
            presp = pu.create_proposal_response(
                pu.marshal(prop), results, b"", proppb.Response(status=200),
                proppb.ChaincodeID(name="bench"), Signer())
            return pu.marshal(pu.create_signed_tx(prop, [presp], Signer()))

        ch_hdr = pu.make_channel_header(cpb.HeaderType.CONFIG, channel)
        sh = pu.create_signature_header(b"orderer", pu.random_nonce())
        genesis = pu.new_block(0, b"")
        genesis.data.data.append(pu.marshal(cpb.Envelope(
            payload=pu.marshal(pu.make_payload(ch_hdr, sh, b"cfg")))))
        genesis.header.data_hash = pu.block_data_hash(genesis.data)
        blocks = [genesis]
        n = 0
        for _ in range(n_blocks):
            blk = pu.new_block(blocks[-1].header.number + 1,
                               pu.block_header_hash(blocks[-1].header))
            for _t in range(ntxs):
                blk.data.data.append(tx_env(n))
                n += 1
            blk.header.data_hash = pu.block_data_hash(blk.data)
            blocks.append(blk)
        stream = [b.SerializeToString() for b in blocks]

        class Validator:
            """One batched signature verify per block (the device-bound
            stage); verdicts + deferred-publication contract match the
            real TxValidator."""

            def validate_ahead(self, block, known_txids=None):
                t0 = time.perf_counter()
                items = []
                for env_bytes in block.data.data:
                    env = pu.unmarshal_envelope(env_bytes)
                    items.append(VerifyItem(key=pub,
                                            signature=env.signature,
                                            message=env.payload))
                ok = sw.verify_batch(items) if block.header.number else \
                    [True] * len(items)
                codes = [txpb.TxValidationCode.VALID if o else
                         txpb.TxValidationCode.BAD_CREATOR_SIGNATURE
                         for o in ok]
                return ValidationResult(
                    codes=codes, n_items=len(items),
                    duration_s=time.perf_counter() - t0)

            def publish_validation(self, block, result):
                while len(block.metadata.metadata) <= \
                        cpb.BlockMetadataIndex.TRANSACTIONS_FILTER:
                    block.metadata.metadata.append(b"")
                block.metadata.metadata[
                    cpb.BlockMetadataIndex.TRANSACTIONS_FILTER] = \
                    bytes(result.codes)

            def validate(self, block):
                result = self.validate_ahead(block)
                self.publish_validation(block, result)
                return result.codes

        class Chan:
            def __init__(self, name):
                self.ledger = KVLedger(channel, os.path.join(root, name))
                self.channel_id = channel
                self.validator = Validator()
                self.committer = LedgerCommitter(self.ledger)

            def commit_validated(self, block, codes, rwsets=None,
                                 tx_ids=None):
                return self.committer.commit(block, codes, rwsets=rwsets)

            def process_block(self, block):
                codes = self.validator.validate(block)
                rwsets = [extract_tx_rwset(e) for e in block.data.data]
                return self.commit_validated(block, codes, rwsets=rwsets)

        def parse(raw):
            blk = cpb.Block()
            blk.ParseFromString(raw)
            return blk

        # ---- sequential twin ----
        seq = Chan("seq")
        seq.ledger.initialize_from_genesis(parse(stream[0]))
        t0 = time.perf_counter()
        for raw in stream[1:]:
            seq.process_block(parse(raw))
        sequential_s = time.perf_counter() - t0

        # ---- depth-1 overlapped twin ----
        piped = Chan("piped")
        piped.ledger.initialize_from_genesis(parse(stream[0]))
        pipeline = CommitPipeline(piped, depth=1)
        t0 = time.perf_counter()
        try:
            for i, raw in enumerate(stream[1:], start=1):
                pipeline.submit(i, raw=raw)
            pipeline.drain(timeout=600)
        finally:
            stats = dict(pipeline.stats)
            overlap = pipeline.overlap_ratio
        pipelined_s = time.perf_counter() - t0

        assert piped.ledger.commit_hash == seq.ledger.commit_hash, \
            "pipelined commit hash diverged from sequential"
        pq = _stage_tail

        return {
            "blocks": n_blocks, "txs_per_block": ntxs,
            "sequential_s": round(sequential_s, 4),
            "pipelined_s": round(pipelined_s, 4),
            # round-14 per-block stage tails from the pipelined twin
            "cp_validate_p50_s": pq("commit.validate", "p50_s"),
            "cp_validate_p99_s": pq("commit.validate", "p99_s"),
            "cp_commit_p50_s": pq("commit.commit", "p50_s"),
            "cp_commit_p99_s": pq("commit.commit", "p99_s"),
            "speedup": round(sequential_s / pipelined_s, 3)
            if pipelined_s else None,
            "overlap_ratio": round(overlap, 4),
            "validate_s": round(stats["validate_s"], 4),
            "commit_s": round(stats["commit_s"], 4),
            "barriers": stats["barriers"],
            "fallbacks": stats["fallbacks"],
            "commit_hash_match": True,
            # on wheel-less 1-core hosts stage A is pure-python P-256
            # and HOLDS the GIL, so measured overlap shows as
            # contention, not speedup; device/native stage A (TPU comb
            # kernel, native DER parse) releases it and the same
            # overlap buys wall clock
            "stage_a_backend": "sw-pure-python"
            if not _have_openssl_cp() else "sw-openssl",
        }
    finally:
        # this runs on EVERY default bench invocation now: close both
        # twins and drop the temp trees even when an assert fires
        import shutil
        if pipeline is not None:
            pipeline.stop()
        for chan in (seq, piped):
            if chan is not None:
                try:
                    chan.ledger.close()
                except Exception:     # noqa: BLE001
                    pass
        try:
            scratch_kv.close()
        except Exception:             # noqa: BLE001
            pass
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    if len(sys.argv) > 1 and sys.argv[1] == "failover":
        # the round-15 leader-kill soak (tools/soak_check.sh): same
        # lockcheck discipline as the overload regime
        from fabric_tpu.common import lockcheck
        if os.environ.get(lockcheck.ENV_VAR):
            lockcheck.install(
                raise_on_violation=os.environ.get(
                    lockcheck.ENV_VAR) == "raise")
        out = failover_run(
            producers=int(os.environ.get("SOAK_PRODUCERS", "2")),
            ntxs_per_producer=int(os.environ.get("SOAK_TXS", "60")),
            seed=int(os.environ.get("SOAK_SEED", "7")),
            drop_rate=float(os.environ.get("SOAK_DROP_RATE", "0.10")),
            reelect_bound_s=float(os.environ.get(
                "SOAK_REELECT_BOUND_S", "30")))
        san = lockcheck.sanitizer()
        out["lockcheck_violations"] = (
            len(san.violations()) if san is not None else None)
        print(json.dumps(out))
        if san is not None and san.violations():
            print(san.report(), file=sys.stderr)
            sys.exit(3)
        sys.exit(0)

    if len(sys.argv) > 1 and sys.argv[1] == "clustertrace":
        # the round-18 cross-node tracing acceptance rig: 3 consenters
        # + 2 peers, ONE merged Chrome trace over /debug/trace/cluster
        out = cluster_trace_run(
            ntxs=int(os.environ.get("CTRACE_TXS", "24")),
            block_txs=int(os.environ.get("CTRACE_BLOCK_TXS", "8")),
            slo_target_s=float(os.environ.get("CTRACE_SLO_S", "1.0")))
        print(json.dumps(out))
        sys.exit(0)

    if len(sys.argv) > 1 and sys.argv[1] == "crashchild":
        # one crash-matrix cell (tests/test_net_chaos.py drives this
        # as a killed-and-restarted subprocess; the crash fault itself
        # rides in via FTPU_FAULTS)
        mode, root = sys.argv[2], sys.argv[3]
        if mode == "order":
            out = crash_matrix_order_child(
                root,
                ntxs=int(os.environ.get("CRASH_NTXS", "16")),
                block_txs=int(os.environ.get("CRASH_BLOCK_TXS", "4")))
        elif mode == "onboard":
            out = crash_matrix_onboard_child(
                root,
                nblocks=int(os.environ.get("CRASH_NBLOCKS", "9")))
        else:
            print(f"unknown crashchild mode {mode!r}",
                  file=sys.stderr)
            sys.exit(2)
        print(json.dumps(out))
        sys.exit(0)

    if len(sys.argv) > 1 and sys.argv[1] == "adaptive":
        # the round-19 closed-loop serving soak (tools/soak_check.sh):
        # adaptive-vs-static phases, max sustainable tx/s at a held
        # p99 commit SLO. Same lockcheck discipline as the other
        # regimes — armed BEFORE the fabric_tpu imports.
        from fabric_tpu.common import lockcheck
        if os.environ.get(lockcheck.ENV_VAR):
            lockcheck.install(
                raise_on_violation=os.environ.get(
                    lockcheck.ENV_VAR) == "raise")
        out = adaptive_serving_run(
            workers=int(os.environ.get("SOAK_WORKERS", "6")),
            ntxs=int(os.environ.get("SOAK_TXS", "2400")),
            invalid=int(os.environ.get("SOAK_INVALID", "48")),
            slo_target_s=float(os.environ.get("SOAK_SLO_S", "1.5")),
            events_cap=int(os.environ.get("SOAK_EVENTS_CAP", "256")),
            interval_s=float(os.environ.get(
                "SOAK_ADAPT_INTERVAL_S", "0.25")),
            seed=int(os.environ.get("SOAK_SEED", "11")),
            drop_rate=float(os.environ.get("SOAK_DROP_RATE", "0.02")))
        san = lockcheck.sanitizer()
        out["lockcheck_violations"] = (
            len(san.violations()) if san is not None else None)
        print(json.dumps(out))
        if san is not None and san.violations():
            print(san.report(), file=sys.stderr)
            sys.exit(3)
        sys.exit(0)

    if len(sys.argv) > 1 and sys.argv[1] == "overload":
        # the round-12 soak regime (tools/soak_check.sh): arm the
        # lock-order sanitizer FIRST when requested — locks are
        # tracked from creation, so the patch must precede the
        # fabric_tpu imports the run pulls in
        from fabric_tpu.common import lockcheck
        if os.environ.get(lockcheck.ENV_VAR):
            lockcheck.install(
                raise_on_violation=os.environ.get(
                    lockcheck.ENV_VAR) == "raise")
        out = overload_run(
            producers=int(os.environ.get("SOAK_PRODUCERS", "4")),
            ntxs_per_producer=int(os.environ.get("SOAK_TXS", "300")),
            budget_s=float(os.environ.get("SOAK_BUDGET_S", "0.35")),
            events_cap=int(os.environ.get("SOAK_EVENTS_CAP", "48")))
        san = lockcheck.sanitizer()
        out["lockcheck_violations"] = (
            len(san.violations()) if san is not None else None)
        print(json.dumps(out))
        if san is not None and san.violations():
            print(san.report(), file=sys.stderr)
            sys.exit(3)
        sys.exit(0)

    from fabric_tpu.bccsp import factory
    from fabric_tpu.common import jaxenv

    jaxenv.enable_compilation_cache()
    prov = factory.new_bccsp(factory.FactoryOpts.from_config(
        {"Default": "TPU", "TPU": {"MinBatch": 16}}))
    print(json.dumps(run(prov, ntxs=int(
        os.environ.get("BENCH_E2E_TXS", "1024")))))
