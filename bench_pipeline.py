"""BASELINE config 3: block validation through the REAL tx pipeline.

Stands up an in-process 2-org network with a single-node etcdraft
orderer (real RaftChain: WAL, ready loop, block signing), endorses
`ntxs` transactions through the gateway (2 endorsements + 1 creator
signature each), orders them into one block, then times the peer-side
block pipeline — `Channel.process_block` = TxValidator (batched
verify) → pvt-data gather → kvledger commit — for BOTH a TPU-provider
peer and a sw-provider peer over the SAME ordered block.

Reference analog: `integration/e2e/e2e_test.go`; the timings mirror
"Validated block [n] in Tms" (`validator.go:262`) and the commit
breakdown (`kv_ledger.go:673-681`). Used by bench.py (BENCH_E2E=1) to
emit the `pipeline` section of the headline JSON.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def run(tpu_csp, ntxs: int = 1024, endorsements: int = 2) -> dict:
    from fabric_tpu.bccsp.sw import SWProvider
    from fabric_tpu.core.chaincode import Chaincode, ChaincodeDefinition
    from fabric_tpu.core.chaincode import shim
    from fabric_tpu.internal import cryptogen
    from fabric_tpu.internal.configtxgen import (
        genesis_block,
        new_channel_group,
    )
    from fabric_tpu.msp import msp_config_from_dir
    from fabric_tpu.msp.mspimpl import X509MSP
    from fabric_tpu.orderer import raft as raft_mod
    from fabric_tpu.orderer.broadcast import BroadcastHandler
    from fabric_tpu.orderer.cluster import LocalClusterNetwork
    from fabric_tpu.orderer.multichannel import Registrar
    from fabric_tpu.peer import Peer
    from fabric_tpu.peer.gateway import Gateway
    from fabric_tpu.protos import transaction as txpb

    channel = "benchchannel"
    orderer_ep = "orderer0.example.com:7050"
    root = tempfile.mkdtemp(prefix="bench_e2e_")
    cdir = os.path.join(root, "crypto")
    # reuse crypto material across runs (beside the warm Q tables):
    # deterministic org keys mean the TPU-filtered orderer's persisted
    # tables match on the next run — restart-warm ordering instead of
    # a per-run table build
    warm_dir = os.environ.get(
        "BENCH_WARM_DIR",
        os.path.expanduser("~/.cache/fabric_tpu_warmkeys"))
    crypto_cache = os.path.join(warm_dir, "pipeline_crypto")
    import shutil
    if os.path.isdir(crypto_cache):
        shutil.copytree(crypto_cache, cdir)
        org1 = os.path.join(cdir, "peerOrganizations",
                            "org1.example.com")
        org2 = os.path.join(cdir, "peerOrganizations",
                            "org2.example.com")
        ordo = os.path.join(cdir, "ordererOrganizations",
                            "example.com")
    else:
        org1 = cryptogen.generate_org(cdir, "org1.example.com",
                                      n_peers=1, n_users=1)
        org2 = cryptogen.generate_org(cdir, "org2.example.com",
                                      n_peers=1, n_users=1)
        ordo = cryptogen.generate_org(cdir, "example.com",
                                      orderer_org=True)
        try:
            shutil.copytree(cdir, crypto_cache + ".tmp")
            os.replace(crypto_cache + ".tmp", crypto_cache)
        except Exception:                 # noqa: BLE001
            pass                          # cache miss next run; fine
    sw_csp = SWProvider()

    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "etcdraft",
            "Addresses": [orderer_ep],
            # long timeout: submission of a full 10k-tx block takes
            # seconds; the cutter must cut on COUNT (one block), not
            # mid-submission timeouts
            "BatchTimeout": "30s",
            # bytes limits sized so MaxMessageCount governs: the point
            # is ONE ntxs-transaction block through the validator
            # (config 3's shape), not the blockcutter's byte policy
            "BatchSize": {"MaxMessageCount": ntxs,
                          "PreferredMaxBytes": 1 << 30,
                          "AbsoluteMaxBytes": 1 << 30},
            "Raft": {"Consenters": [
                {"Host": orderer_ep.split(":")[0], "Port": 7050}]},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": [orderer_ep]}],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(channel, new_channel_group(profile))

    def local_msp(msp_dir, mspid):
        m = X509MSP(sw_csp)
        m.setup(msp_config_from_dir(msp_dir, mspid, csp=sw_csp))
        return m

    # ---- single-node raft ordering service ----
    net = LocalClusterNetwork()
    transport = net.register(orderer_ep)
    orderer_msp = local_msp(
        os.path.join(ordo, "orderers", "orderer0.example.com", "msp"),
        "OrdererMSP")
    # Two ordering services are measured: this one (sw filter — the
    # reference configuration) and, below, a TPU-filtered twin over
    # the same genesis. Both ride the WINDOWED ingest (one sig-filter
    # verify_batch + one consenter enqueue per 512-envelope window —
    # process_normal_msgs).
    registrar = Registrar(
        os.path.join(root, "orderer"),
        orderer_msp.get_default_signing_identity(), sw_csp,
        {"etcdraft": raft_mod.consenter(transport,
                                        tick_interval_s=0.03,
                                        election_tick=8)})
    registrar.join(genesis)
    broadcast = BroadcastHandler(registrar)

    class KV(Chaincode):
        def init(self, stub):
            return shim.success()

        def invoke(self, stub):
            fn, params = stub.get_function_and_parameters()
            stub.put_state(params[0], params[1].encode())
            return shim.success()

    # ---- two validating peers: TPU provider vs sw provider ----
    peers = {}
    for org_name, org_dir, mspid, csp in (
            ("org1", org1, "Org1MSP", tpu_csp),
            ("org2", org2, "Org2MSP", sw_csp)):
        msp = local_msp(
            os.path.join(org_dir, "peers",
                         f"peer0.{org_name}.example.com", "msp"), mspid)
        peer = Peer(os.path.join(root, f"peer_{org_name}"), msp, csp)
        peer.join_channel(genesis)
        peer.chaincode_support.register("bench", KV())
        peer.channel(channel).define_chaincode(
            ChaincodeDefinition(name="bench"))
        peers[org_name] = peer

    user_msp = local_msp(
        os.path.join(org1, "users", "User1@org1.example.com", "msp"),
        "Org1MSP")
    gw = Gateway(peers["org1"], broadcast,
                 user_msp.get_default_signing_identity())

    endorsing = list(peers.values())[:endorsements]

    print("pipeline: network up; endorsing", flush=True,
          file=sys.stderr)
    # ---- endorse everything first (CPU signing work, untimed) ----
    t0 = time.perf_counter()
    envs = [gw.endorse(channel, "bench",
                       [b"put", f"k{i}".encode(), f"v{i}".encode()],
                       endorsing_peers=endorsing)[0]
            for i in range(ntxs)]
    endorse_s = time.perf_counter() - t0

    print(f"pipeline: endorsed {ntxs} in {endorse_s:.1f}s; ordering",
          flush=True, file=sys.stderr)
    # ---- order through raft into one block ----
    # submission goes through the batched windowed ingest — the same
    # path the BroadcastStream gRPC handler drives (one sig-filter
    # verify_batch + one consenter enqueue per window)
    from fabric_tpu.protos import common as cpb

    def order_envs(bcast, reg, stall_s: float = 150.0):
        t0 = time.perf_counter()
        window = 512
        pos = 0
        deadline0 = time.monotonic() + 60
        while pos < len(envs):
            batch = envs[pos:pos + window]
            resps = bcast.process_messages(batch)
            ok = 0
            for resp in resps:
                if resp.status == cpb.Status.SUCCESS:
                    ok += 1
                elif resp.status == cpb.Status.SERVICE_UNAVAILABLE:
                    # raft still electing: retry the unaccepted tail
                    break
                else:
                    # permanent rejection (BAD_REQUEST/FORBIDDEN/...):
                    # retrying cannot help — fail fast with the info
                    raise RuntimeError(
                        f"broadcast rejected: {resp.status} "
                        f"{resp.info}")
            pos += ok
            if ok == 0:
                if time.monotonic() > deadline0:
                    raise RuntimeError("broadcast unavailable for 60s")
                time.sleep(0.05)
        ch = reg.get_chain(channel)
        deadline = time.monotonic() + stall_s
        while True:
            blks = [ch.ledger.block_store.get_block_by_number(n)
                    for n in range(1, ch.ledger.height)]
            done = (all(b is not None for b in blks) and
                    sum(len(b.data.data) for b in blks
                        if b is not None) >= ntxs)
            if done:
                return time.perf_counter() - t0, blks
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"ordering stalled at height {ch.ledger.height}")
            time.sleep(0.05)

    order_s, blocks = order_envs(broadcast, registrar)

    # ---- the SAME block ordered by a TPU-FILTERED orderer ----
    # a second single-node ordering service over the same genesis,
    # BCCSP = the TPU provider: the windowed sig filter verifies each
    # 512-envelope window on device. With crypto material and Q-table
    # bytes persisted across runs, its per-key-set table restores from
    # disk (warm restart) instead of rebuilding — the round-4 blocker.
    # Timed warm-included; round-4 kept the sw filter here and the
    # TPU-filter number was only a commit-message claim.
    order_tpu_s = None
    try:
        # the orderer's own provider pads every 512-envelope window to
        # the 4096-lane bucket the parent AOT-compiled: no fresh
        # device compiles inside the ordering timer (the padded lanes
        # are premasked; device time is ~flat in lane count here)
        from fabric_tpu.bccsp import factory as _bf
        orderer_csp = _bf.new_bccsp(_bf.FactoryOpts.from_config({
            "Default": "TPU",
            "TPU": {"MinBatch": 16, "BucketFloor": 4096,
                    "Chunk": 32768, "WarmKeysDir": warm_dir}}))
        net2 = LocalClusterNetwork()
        transport2 = net2.register(orderer_ep)
        registrar2 = Registrar(
            os.path.join(root, "orderer_tpu"),
            orderer_msp.get_default_signing_identity(), orderer_csp,
            {"etcdraft": raft_mod.consenter(transport2,
                                            tick_interval_s=0.03,
                                            election_tick=8)})
        registrar2.join(genesis)
        broadcast2 = BroadcastHandler(registrar2)
        # generous stall budget: a first-ever run may pay one K=1
        # pipeline compile + the creator-set table restore inside the
        # timer (both cached/persisted for every later run)
        order_tpu_s, _blocks2 = order_envs(broadcast2, registrar2,
                                           stall_s=900.0)
        registrar2.halt()
        transport2.close()
    except Exception as e:                # noqa: BLE001
        print(f"pipeline: tpu-filtered ordering failed: {e}",
              flush=True, file=sys.stderr)
    data_blocks = [b for b in blocks if b.data.data]
    nsigs = ntxs * (endorsements + 1)

    print(f"pipeline: ordered in {order_s:.1f}s; validating", flush=True,
          file=sys.stderr)
    # ---- peer-side pipeline: validate (repeatable) + commit (once) ----
    out: dict = {
        "ntxs": ntxs, "endorsements_per_tx": endorsements,
        "signatures": nsigs, "endorse_s": round(endorse_s, 2),
        "order_raft_s": round(order_s, 2),
        "order_tx_per_s": round(ntxs / order_s, 1),
        "blocks": len(data_blocks),
    }
    if order_tpu_s is not None:
        out["order_raft_tpu_filter_s"] = round(order_tpu_s, 2)
        out["order_tpu_filter_tx_per_s"] = round(ntxs / order_tpu_s, 1)
    for org_name, peer in peers.items():
        ch = peer.channel(channel)
        label = "tpu_peer" if org_name == "org1" else "sw_peer"
        # warm (compiles on the tpu peer), then best-of-3 validation
        for b in data_blocks:
            flags = ch.validator.validate(b)
            assert all(f == txpb.TxValidationCode.VALID for f in flags), \
                f"{label}: invalid flags {set(flags)}"
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for b in data_blocks:
                ch.validator.validate(b)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        t0 = time.perf_counter()
        for b in data_blocks:
            codes = ch.process_block(b)
            assert all(c == txpb.TxValidationCode.VALID for c in codes)
        commit_s = time.perf_counter() - t0
        out[label] = {
            "validate_s": round(best, 4),
            "validate_tx_per_s": round(ntxs / best, 1),
            "validate_sigs_per_s": round(nsigs / best, 1),
            "process_block_s": round(commit_s, 4),
            "commit_tx_per_s": round(ntxs / commit_s, 1),
        }
    registrar.halt()
    transport.close()
    return out


if __name__ == "__main__":
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fabric_tpu.bccsp import factory
    from fabric_tpu.common import jaxenv

    jaxenv.enable_compilation_cache()
    prov = factory.new_bccsp(factory.FactoryOpts.from_config(
        {"Default": "TPU", "TPU": {"MinBatch": 16}}))
    print(json.dumps(run(prov, ntxs=int(
        os.environ.get("BENCH_E2E_TXS", "1024")))))
