"""BASELINE config 3: block validation through the REAL tx pipeline.

Stands up an in-process 2-org network with a single-node etcdraft
orderer (real RaftChain: WAL, ready loop, block signing), endorses
`ntxs` transactions through the gateway (2 endorsements + 1 creator
signature each), orders them into one block, then times the peer-side
block pipeline — `Channel.process_block` = TxValidator (batched
verify) → pvt-data gather → kvledger commit — for BOTH a TPU-provider
peer and a sw-provider peer over the SAME ordered block.

Reference analog: `integration/e2e/e2e_test.go`; the timings mirror
"Validated block [n] in Tms" (`validator.go:262`) and the commit
breakdown (`kv_ledger.go:673-681`). Used by bench.py (BENCH_E2E=1) to
emit the `pipeline` section of the headline JSON.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def run(tpu_csp, ntxs: int = 1024, endorsements: int = 2) -> dict:
    from fabric_tpu.bccsp.sw import SWProvider
    from fabric_tpu.core.chaincode import Chaincode, ChaincodeDefinition
    from fabric_tpu.core.chaincode import shim
    from fabric_tpu.internal import cryptogen
    from fabric_tpu.internal.configtxgen import (
        genesis_block,
        new_channel_group,
    )
    from fabric_tpu.msp import msp_config_from_dir
    from fabric_tpu.msp.mspimpl import X509MSP
    from fabric_tpu.orderer import raft as raft_mod
    from fabric_tpu.orderer.broadcast import BroadcastHandler
    from fabric_tpu.orderer.cluster import LocalClusterNetwork
    from fabric_tpu.orderer.multichannel import Registrar
    from fabric_tpu.peer import Peer
    from fabric_tpu.peer.gateway import Gateway
    from fabric_tpu.protos import transaction as txpb

    channel = "benchchannel"
    orderer_ep = "orderer0.example.com:7050"
    root = tempfile.mkdtemp(prefix="bench_e2e_")
    cdir = os.path.join(root, "crypto")
    # reuse crypto material across runs (beside the warm Q tables):
    # deterministic org keys mean the TPU-filtered orderer's persisted
    # tables match on the next run — restart-warm ordering instead of
    # a per-run table build
    warm_dir = os.environ.get(
        "BENCH_WARM_DIR",
        os.path.expanduser("~/.cache/fabric_tpu_warmkeys"))
    crypto_cache = os.path.join(warm_dir, "pipeline_crypto")
    import shutil
    if os.path.isdir(crypto_cache):
        shutil.copytree(crypto_cache, cdir)
        org1 = os.path.join(cdir, "peerOrganizations",
                            "org1.example.com")
        org2 = os.path.join(cdir, "peerOrganizations",
                            "org2.example.com")
        ordo = os.path.join(cdir, "ordererOrganizations",
                            "example.com")
    else:
        org1 = cryptogen.generate_org(cdir, "org1.example.com",
                                      n_peers=1, n_users=1)
        org2 = cryptogen.generate_org(cdir, "org2.example.com",
                                      n_peers=1, n_users=1)
        ordo = cryptogen.generate_org(cdir, "example.com",
                                      orderer_org=True)
        try:
            shutil.copytree(cdir, crypto_cache + ".tmp")
            os.replace(crypto_cache + ".tmp", crypto_cache)
        except Exception:                 # noqa: BLE001
            pass                          # cache miss next run; fine
    sw_csp = SWProvider()

    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "etcdraft",
            "Addresses": [orderer_ep],
            # long timeout: submission of a full 10k-tx block takes
            # seconds; the cutter must cut on COUNT (one block), not
            # mid-submission timeouts
            "BatchTimeout": "30s",
            # bytes limits sized so MaxMessageCount governs: the point
            # is ONE ntxs-transaction block through the validator
            # (config 3's shape), not the blockcutter's byte policy
            "BatchSize": {"MaxMessageCount": ntxs,
                          "PreferredMaxBytes": 1 << 30,
                          "AbsoluteMaxBytes": 1 << 30},
            "Raft": {"Consenters": [
                {"Host": orderer_ep.split(":")[0], "Port": 7050}]},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": [orderer_ep]}],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(channel, new_channel_group(profile))

    def local_msp(msp_dir, mspid):
        m = X509MSP(sw_csp)
        m.setup(msp_config_from_dir(msp_dir, mspid, csp=sw_csp))
        return m

    # ---- single-node raft ordering service ----
    net = LocalClusterNetwork()
    transport = net.register(orderer_ep)
    orderer_msp = local_msp(
        os.path.join(ordo, "orderers", "orderer0.example.com", "msp"),
        "OrdererMSP")
    # Two ordering services are measured: this one (sw filter — the
    # reference configuration) and, below, a TPU-filtered twin over
    # the same genesis. Both ride the WINDOWED ingest (one sig-filter
    # verify_batch + one consenter enqueue per 512-envelope window —
    # process_normal_msgs).
    registrar = Registrar(
        os.path.join(root, "orderer"),
        orderer_msp.get_default_signing_identity(), sw_csp,
        {"etcdraft": raft_mod.consenter(transport,
                                        tick_interval_s=0.03,
                                        election_tick=8)})
    registrar.join(genesis)
    broadcast = BroadcastHandler(registrar)

    class KV(Chaincode):
        def init(self, stub):
            return shim.success()

        def invoke(self, stub):
            fn, params = stub.get_function_and_parameters()
            stub.put_state(params[0], params[1].encode())
            return shim.success()

    # ---- two validating peers: TPU provider vs sw provider ----
    peers = {}
    for org_name, org_dir, mspid, csp in (
            ("org1", org1, "Org1MSP", tpu_csp),
            ("org2", org2, "Org2MSP", sw_csp)):
        msp = local_msp(
            os.path.join(org_dir, "peers",
                         f"peer0.{org_name}.example.com", "msp"), mspid)
        peer = Peer(os.path.join(root, f"peer_{org_name}"), msp, csp)
        peer.join_channel(genesis)
        peer.chaincode_support.register("bench", KV())
        peer.channel(channel).define_chaincode(
            ChaincodeDefinition(name="bench"))
        peers[org_name] = peer

    user_msp = local_msp(
        os.path.join(org1, "users", "User1@org1.example.com", "msp"),
        "Org1MSP")
    gw = Gateway(peers["org1"], broadcast,
                 user_msp.get_default_signing_identity())

    endorsing = list(peers.values())[:endorsements]

    print("pipeline: network up; endorsing", flush=True,
          file=sys.stderr)
    # ---- endorse everything first (CPU signing work, untimed) ----
    t0 = time.perf_counter()
    envs = [gw.endorse(channel, "bench",
                       [b"put", f"k{i}".encode(), f"v{i}".encode()],
                       endorsing_peers=endorsing)[0]
            for i in range(ntxs)]
    endorse_s = time.perf_counter() - t0

    print(f"pipeline: endorsed {ntxs} in {endorse_s:.1f}s; ordering",
          flush=True, file=sys.stderr)
    # ---- order through raft into one block ----
    # submission goes through the batched windowed ingest — the same
    # path the BroadcastStream gRPC handler drives (one sig-filter
    # verify_batch + one consenter enqueue per window)
    from fabric_tpu.protos import common as cpb

    def order_envs(bcast, reg, stall_s: float = 150.0):
        t0 = time.perf_counter()
        window = 512
        pos = 0
        deadline0 = time.monotonic() + 60
        while pos < len(envs):
            batch = envs[pos:pos + window]
            resps = bcast.process_messages(batch)
            ok = 0
            for resp in resps:
                if resp.status == cpb.Status.SUCCESS:
                    ok += 1
                elif resp.status == cpb.Status.SERVICE_UNAVAILABLE:
                    # raft still electing: retry the unaccepted tail
                    break
                else:
                    # permanent rejection (BAD_REQUEST/FORBIDDEN/...):
                    # retrying cannot help — fail fast with the info
                    raise RuntimeError(
                        f"broadcast rejected: {resp.status} "
                        f"{resp.info}")
            pos += ok
            if ok == 0:
                if time.monotonic() > deadline0:
                    raise RuntimeError("broadcast unavailable for 60s")
                time.sleep(0.05)
        ch = reg.get_chain(channel)
        deadline = time.monotonic() + stall_s
        while True:
            blks = [ch.ledger.block_store.get_block_by_number(n)
                    for n in range(1, ch.ledger.height)]
            done = (all(b is not None for b in blks) and
                    sum(len(b.data.data) for b in blks
                        if b is not None) >= ntxs)
            if done:
                return time.perf_counter() - t0, blks
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"ordering stalled at height {ch.ledger.height}")
            time.sleep(0.05)

    order_s, blocks = order_envs(broadcast, registrar)

    # ---- the SAME block ordered by a TPU-FILTERED orderer ----
    # a second single-node ordering service over the same genesis,
    # BCCSP = the TPU provider: the windowed sig filter verifies each
    # 512-envelope window on device. With crypto material and Q-table
    # bytes persisted across runs, its per-key-set table restores from
    # disk (warm restart) instead of rebuilding — the round-4 blocker.
    # Timed warm-included; round-4 kept the sw filter here and the
    # TPU-filter number was only a commit-message claim.
    order_tpu_s = None
    try:
        # the orderer's own provider pads every 512-envelope window to
        # the 4096-lane bucket the parent AOT-compiled: no fresh
        # device compiles inside the ordering timer (the padded lanes
        # are premasked; device time is ~flat in lane count here)
        from fabric_tpu.bccsp import factory as _bf
        orderer_csp = _bf.new_bccsp(_bf.FactoryOpts.from_config({
            "Default": "TPU",
            "TPU": {"MinBatch": 16, "BucketFloor": 4096,
                    "Chunk": 32768, "WarmKeysDir": warm_dir}}))
        net2 = LocalClusterNetwork()
        transport2 = net2.register(orderer_ep)
        registrar2 = Registrar(
            os.path.join(root, "orderer_tpu"),
            orderer_msp.get_default_signing_identity(), orderer_csp,
            {"etcdraft": raft_mod.consenter(transport2,
                                            tick_interval_s=0.03,
                                            election_tick=8)})
        registrar2.join(genesis)
        broadcast2 = BroadcastHandler(registrar2)
        # generous stall budget: a first-ever run may pay one K=1
        # pipeline compile + the creator-set table restore inside the
        # timer (both cached/persisted for every later run)
        order_tpu_s, _blocks2 = order_envs(broadcast2, registrar2,
                                           stall_s=900.0)
        registrar2.halt()
        transport2.close()
    except Exception as e:                # noqa: BLE001
        print(f"pipeline: tpu-filtered ordering failed: {e}",
              flush=True, file=sys.stderr)
    data_blocks = [b for b in blocks if b.data.data]
    nsigs = ntxs * (endorsements + 1)

    print(f"pipeline: ordered in {order_s:.1f}s; validating", flush=True,
          file=sys.stderr)
    # ---- peer-side pipeline: validate (repeatable) + commit (once) ----
    out: dict = {
        "ntxs": ntxs, "endorsements_per_tx": endorsements,
        "signatures": nsigs, "endorse_s": round(endorse_s, 2),
        "order_raft_s": round(order_s, 2),
        "order_tx_per_s": round(ntxs / order_s, 1),
        "blocks": len(data_blocks),
    }
    if order_tpu_s is not None:
        out["order_raft_tpu_filter_s"] = round(order_tpu_s, 2)
        out["order_tpu_filter_tx_per_s"] = round(ntxs / order_tpu_s, 1)
    for org_name, peer in peers.items():
        ch = peer.channel(channel)
        label = "tpu_peer" if org_name == "org1" else "sw_peer"
        # warm (compiles on the tpu peer), then best-of-3 validation
        for b in data_blocks:
            flags = ch.validator.validate(b)
            assert all(f == txpb.TxValidationCode.VALID for f in flags), \
                f"{label}: invalid flags {set(flags)}"
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for b in data_blocks:
                ch.validator.validate(b)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        t0 = time.perf_counter()
        for b in data_blocks:
            codes = ch.process_block(b)
            assert all(c == txpb.TxValidationCode.VALID for c in codes)
        commit_s = time.perf_counter() - t0
        out[label] = {
            "validate_s": round(best, 4),
            "validate_tx_per_s": round(ntxs / best, 1),
            "validate_sigs_per_s": round(nsigs / best, 1),
            "process_block_s": round(commit_s, 4),
            "commit_tx_per_s": round(ntxs / commit_s, 1),
        }
    registrar.halt()
    transport.close()
    return out


def _have_openssl_cp() -> bool:
    try:
        from fabric_tpu.bccsp._crypto_compat import HAVE_CRYPTOGRAPHY
        return bool(HAVE_CRYPTOGRAPHY)
    except Exception:                     # noqa: BLE001
        return False


def commit_pipeline_run(n_blocks: int = 6, ntxs: int = 24) -> dict:
    """ISSUE 4 scenario: sequential vs depth-1 overlapped intake on a
    synthetic multi-block stream — REAL per-tx signature verification
    (stage A, batched through the BCCSP seam; pure-python P-256 when
    the OpenSSL wheel is absent) against REAL KVLedger commits (stage
    B), wheel-free so the bounded default bench can always run it.
    Reports both wall clocks and the pipeline's measured overlap."""
    import hashlib
    import tempfile

    from fabric_tpu import protoutil as pu
    from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem
    from fabric_tpu.bccsp.sw import SWProvider
    from fabric_tpu.core.commitpipeline import CommitPipeline
    from fabric_tpu.core.committer import LedgerCommitter
    from fabric_tpu.core.txvalidator import ValidationResult
    from fabric_tpu.ledger import KVLedger
    from fabric_tpu.ledger.kvdb import DBHandle, KVStore
    from fabric_tpu.ledger.kvledger import extract_tx_rwset
    from fabric_tpu.ledger.statedb import StateDB
    from fabric_tpu.ledger.txmgr import TxSimulator
    from fabric_tpu.protos import common as cpb, proposal as proppb
    from fabric_tpu.protos import transaction as txpb

    channel = "cpbench"
    root = tempfile.mkdtemp(prefix="bench_cp_")
    seq = piped = pipeline = None
    scratch_kv = None
    try:
        sw = SWProvider()
        key = sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
        pub = key.public_key()

        class Signer:
            def serialize(self):
                return b"bench-client"

            def sign(self, msg):
                return sw.sign(key, hashlib.sha256(msg).digest())

        # ---- build the stream once (signing is untimed setup) ----
        scratch_kv = KVStore(os.path.join(root, "scratch.db"))
        scratch = StateDB(DBHandle(scratch_kv, "s"))

        def tx_env(i):
            sim = TxSimulator(scratch, "sim")
            sim.put_state("bench", f"k{i}", f"v{i}".encode())
            results = pu.marshal(sim.get_tx_simulation_results())
            prop, _tx_id = pu.create_proposal(channel, "bench",
                                              [b"invoke"],
                                              creator=b"bench-client")
            presp = pu.create_proposal_response(
                pu.marshal(prop), results, b"", proppb.Response(status=200),
                proppb.ChaincodeID(name="bench"), Signer())
            return pu.marshal(pu.create_signed_tx(prop, [presp], Signer()))

        ch_hdr = pu.make_channel_header(cpb.HeaderType.CONFIG, channel)
        sh = pu.create_signature_header(b"orderer", pu.random_nonce())
        genesis = pu.new_block(0, b"")
        genesis.data.data.append(pu.marshal(cpb.Envelope(
            payload=pu.marshal(pu.make_payload(ch_hdr, sh, b"cfg")))))
        genesis.header.data_hash = pu.block_data_hash(genesis.data)
        blocks = [genesis]
        n = 0
        for _ in range(n_blocks):
            blk = pu.new_block(blocks[-1].header.number + 1,
                               pu.block_header_hash(blocks[-1].header))
            for _t in range(ntxs):
                blk.data.data.append(tx_env(n))
                n += 1
            blk.header.data_hash = pu.block_data_hash(blk.data)
            blocks.append(blk)
        stream = [b.SerializeToString() for b in blocks]

        class Validator:
            """One batched signature verify per block (the device-bound
            stage); verdicts + deferred-publication contract match the
            real TxValidator."""

            def validate_ahead(self, block, known_txids=None):
                t0 = time.perf_counter()
                items = []
                for env_bytes in block.data.data:
                    env = pu.unmarshal_envelope(env_bytes)
                    items.append(VerifyItem(key=pub,
                                            signature=env.signature,
                                            message=env.payload))
                ok = sw.verify_batch(items) if block.header.number else \
                    [True] * len(items)
                codes = [txpb.TxValidationCode.VALID if o else
                         txpb.TxValidationCode.BAD_CREATOR_SIGNATURE
                         for o in ok]
                return ValidationResult(
                    codes=codes, n_items=len(items),
                    duration_s=time.perf_counter() - t0)

            def publish_validation(self, block, result):
                while len(block.metadata.metadata) <= \
                        cpb.BlockMetadataIndex.TRANSACTIONS_FILTER:
                    block.metadata.metadata.append(b"")
                block.metadata.metadata[
                    cpb.BlockMetadataIndex.TRANSACTIONS_FILTER] = \
                    bytes(result.codes)

            def validate(self, block):
                result = self.validate_ahead(block)
                self.publish_validation(block, result)
                return result.codes

        class Chan:
            def __init__(self, name):
                self.ledger = KVLedger(channel, os.path.join(root, name))
                self.channel_id = channel
                self.validator = Validator()
                self.committer = LedgerCommitter(self.ledger)

            def commit_validated(self, block, codes, rwsets=None,
                                 tx_ids=None):
                return self.committer.commit(block, codes, rwsets=rwsets)

            def process_block(self, block):
                codes = self.validator.validate(block)
                rwsets = [extract_tx_rwset(e) for e in block.data.data]
                return self.commit_validated(block, codes, rwsets=rwsets)

        def parse(raw):
            blk = cpb.Block()
            blk.ParseFromString(raw)
            return blk

        # ---- sequential twin ----
        seq = Chan("seq")
        seq.ledger.initialize_from_genesis(parse(stream[0]))
        t0 = time.perf_counter()
        for raw in stream[1:]:
            seq.process_block(parse(raw))
        sequential_s = time.perf_counter() - t0

        # ---- depth-1 overlapped twin ----
        piped = Chan("piped")
        piped.ledger.initialize_from_genesis(parse(stream[0]))
        pipeline = CommitPipeline(piped, depth=1)
        t0 = time.perf_counter()
        try:
            for i, raw in enumerate(stream[1:], start=1):
                pipeline.submit(i, raw=raw)
            pipeline.drain(timeout=600)
        finally:
            stats = dict(pipeline.stats)
            overlap = pipeline.overlap_ratio
        pipelined_s = time.perf_counter() - t0

        assert piped.ledger.commit_hash == seq.ledger.commit_hash, \
            "pipelined commit hash diverged from sequential"
        return {
            "blocks": n_blocks, "txs_per_block": ntxs,
            "sequential_s": round(sequential_s, 4),
            "pipelined_s": round(pipelined_s, 4),
            "speedup": round(sequential_s / pipelined_s, 3)
            if pipelined_s else None,
            "overlap_ratio": round(overlap, 4),
            "validate_s": round(stats["validate_s"], 4),
            "commit_s": round(stats["commit_s"], 4),
            "barriers": stats["barriers"],
            "fallbacks": stats["fallbacks"],
            "commit_hash_match": True,
            # on wheel-less 1-core hosts stage A is pure-python P-256
            # and HOLDS the GIL, so measured overlap shows as
            # contention, not speedup; device/native stage A (TPU comb
            # kernel, native DER parse) releases it and the same
            # overlap buys wall clock
            "stage_a_backend": "sw-pure-python"
            if not _have_openssl_cp() else "sw-openssl",
        }
    finally:
        # this runs on EVERY default bench invocation now: close both
        # twins and drop the temp trees even when an assert fires
        import shutil
        if pipeline is not None:
            pipeline.stop()
        for chan in (seq, piped):
            if chan is not None:
                try:
                    chan.ledger.close()
                except Exception:     # noqa: BLE001
                    pass
        try:
            scratch_kv.close()
        except Exception:             # noqa: BLE001
            pass
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fabric_tpu.bccsp import factory
    from fabric_tpu.common import jaxenv

    jaxenv.enable_compilation_cache()
    prov = factory.new_bccsp(factory.FactoryOpts.from_config(
        {"Default": "TPU", "TPU": {"MinBatch": 16}}))
    print(json.dumps(run(prov, ntxs=int(
        os.environ.get("BENCH_E2E_TXS", "1024")))))
