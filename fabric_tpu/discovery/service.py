"""Service discovery: membership, config and endorsement descriptors.

Rebuild of `discovery/{service.go:63,support/,endorsement/}`: clients
send a signed Request; the peer authenticates it against the channel's
Readers policy (with a result cache keyed on the identity —
`discovery/auth` cache), then answers from gossip membership, the
channel config bundle, and endorsement-policy analysis
(`endorsement.go:84,160` → layouts via common/policies/inquire).
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Optional

from fabric_tpu.common.policies import inquire
from fabric_tpu.common.policies import policy as papi
from fabric_tpu.protos import discovery as dpb, policies as polpb
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("discovery")

_AUTH_CACHE_MAX = 1000


class DiscoveryService:
    def __init__(self, peer, gossip_service):
        self._peer = peer
        self._gossip = gossip_service
        self._auth_cache: dict[tuple[str, bytes], bool] = {}
        self._lock = threading.Lock()

    # -- entry point (the gRPC service calls this) --

    def process(self, signed: dpb.SignedRequest) -> dpb.Response:
        req = dpb.Request()
        resp = dpb.Response()
        try:
            req.ParseFromString(signed.payload)
        except Exception:
            r = resp.results.add()
            r.error.content = "malformed request"
            return resp
        for query in req.queries:
            result = resp.results.add()
            try:
                self._one_query(query, req.authentication, signed,
                                result)
            except Exception as e:
                logger.exception("discovery query failed")
                result.error.content = str(e)
        return resp

    def _one_query(self, query: dpb.Query, identity: bytes,
                   signed: dpb.SignedRequest,
                   result: dpb.QueryResult) -> None:
        channel = self._peer.channel(query.channel)
        if channel is None:
            result.error.content = f"channel {query.channel} not found"
            return
        if not self._authorized(channel, identity, signed):
            result.error.content = "access denied"
            return
        which = query.WhichOneof("query")
        if which == "peer_query":
            self._peers_of(query.channel, result.members)
        elif which == "config_query":
            self._config_of(channel, result.config_result)
        elif which == "cc_query":
            for interest in query.cc_query.interests:
                self._endorsement_descriptor(
                    channel, query.channel, interest,
                    result.cc_query_res.descriptors.add())
        else:
            result.error.content = "empty query"

    # -- auth (reference discovery/auth cache) --

    def _authorized(self, channel, identity: bytes,
                    signed: dpb.SignedRequest) -> bool:
        bundle = channel.bundle()
        key = (channel.channel_id,
               hashlib.sha256(identity + signed.signature).digest())
        with self._lock:
            cached = self._auth_cache.get(key)
        if cached is not None:
            return cached
        ok = False
        try:
            policy = bundle.policy_manager.get_policy(
                "/Channel/Application/Readers")
            policy.evaluate_signed_data([pu.SignedData(
                data=signed.payload, identity=identity,
                signature=signed.signature)])
            ok = True
        except papi.PolicyError:
            ok = False
        with self._lock:
            if len(self._auth_cache) > _AUTH_CACHE_MAX:
                self._auth_cache.clear()
            self._auth_cache[key] = ok
        return ok

    # -- membership (gossip-fed) --

    def _discovered_peers(self, channel_id: str
                          ) -> list[dpb.DiscoveredPeer]:
        out = []
        gchannel = self._gossip.node.channel(channel_id)
        if gchannel is None:
            return out
        heights = gchannel.heights()
        # self
        me = dpb.DiscoveredPeer(
            msp_id=self._gossip.node.org_id,
            endpoint=self._gossip.node.endpoint,
            identity=self._gossip.node.identity,
            ledger_height=self._peer.channel(channel_id).height)
        me.chaincodes.extend(
            self._peer.chaincode_support.registered())
        out.append(me)
        for m in gchannel.members():
            org = self._gossip._org_of_identity(m.identity) \
                if m.identity else None
            if org is None:
                continue
            dp = dpb.DiscoveredPeer(
                msp_id=org, endpoint=m.member.endpoint,
                identity=m.identity,
                ledger_height=heights.get(
                    bytes(m.member.pki_id), 0))
            out.append(dp)
        return out

    def _peers_of(self, channel_id: str,
                  result: dpb.PeerMembershipResult) -> None:
        for dp in self._discovered_peers(channel_id):
            result.peers.add().CopyFrom(dp)

    # -- config --

    def _config_of(self, channel, result: dpb.ConfigResult) -> None:
        from fabric_tpu.protos import configtx as ctxpb
        bundle = channel.bundle()
        root = bundle.config.channel_group
        for section in ("Application", "Orderer"):
            group = root.groups.get(section)
            if group is None:
                continue
            for org_name, og in group.groups.items():
                val = og.values.get("MSP")
                if val is None:
                    continue
                mv = ctxpb.MSPValue()
                mv.ParseFromString(val.value)
                result.msps[org_name] = mv.config
        result.orderer_endpoints.extend(
            bundle.channel.orderer_addresses)
        if bundle.orderer is not None:
            for org in bundle.orderer.orgs.values():
                for ep in org.endpoints:
                    if ep not in result.orderer_endpoints:
                        result.orderer_endpoints.append(ep)

    # -- endorsement descriptors --

    def chaincode_layouts(self, channel, cc_name: str
                          ) -> list[dict[str, int]]:
        """Layouts satisfying the chaincode's endorsement policy."""
        definition = channel.chaincode_definition(cc_name)
        envelope: Optional[polpb.SignaturePolicyEnvelope] = None
        if definition is not None and definition.endorsement_policy:
            app = polpb.ApplicationPolicy()
            app.ParseFromString(definition.endorsement_policy)
            if app.WhichOneof("type") == "signature_policy":
                envelope = app.signature_policy
            else:
                envelope = self._channel_policy_envelope(
                    channel, app.channel_config_policy_reference)
        else:
            envelope = self._channel_policy_envelope(
                channel, "/Channel/Application/Endorsement")
        if envelope is None:
            return []
        return inquire.layouts_from_envelope(envelope)

    def _channel_policy_envelope(self, channel, path: str
                                 ) -> Optional[polpb.SignaturePolicyEnvelope]:
        """Resolve a config policy path to a signature policy; an
        ImplicitMeta over org sub-policies is lowered to OutOf(k,
        member-of-each-org) like the reference's policy mapping."""
        bundle = channel.bundle()
        if bundle.application is None:
            return None
        orgs = sorted(org.mspid
                      for org in bundle.application.orgs.values())
        n = self._implicit_meta_n(bundle, path, len(orgs))
        env = polpb.SignaturePolicyEnvelope(version=0)
        sub_rules = []
        for i, org in enumerate(orgs):
            p = env.identities.add(
                classification=polpb.MSPPrincipal.ROLE)
            role = polpb.MSPRole(msp_identifier=org,
                                 role=polpb.MSPRole.MEMBER)
            p.principal = role.SerializeToString()
            sp = polpb.SignaturePolicy(signed_by=i)
            sub_rules.append(sp)
        env.rule.n_out_of.n = max(n, 1)
        for sp in sub_rules:
            env.rule.n_out_of.rules.add().CopyFrom(sp)
        return env

    @staticmethod
    def _implicit_meta_n(bundle, path: str, n_orgs: int) -> int:
        """How many org sub-policy satisfactions the referenced
        ImplicitMeta policy needs."""
        rule = polpb.ImplicitMetaPolicy.MAJORITY
        try:
            name = path.rsplit("/", 1)[1]
            group = bundle.config.channel_group.groups["Application"]
            pol = group.policies[name].policy
            if pol.type == polpb.Policy.IMPLICIT_META:
                imp = polpb.ImplicitMetaPolicy()
                imp.ParseFromString(pol.value)
                rule = imp.rule
        except Exception as e:
            logger.warning("discovery: implicit-meta policy lookup "
                           "for %r failed (%s); assuming MAJORITY",
                           path, e)
        if rule == polpb.ImplicitMetaPolicy.ANY:
            return 1
        if rule == polpb.ImplicitMetaPolicy.ALL:
            return n_orgs
        return n_orgs // 2 + 1

    def _endorsement_descriptor(self, channel, channel_id: str,
                                interest: dpb.ChaincodeInterest,
                                desc: dpb.EndorsementDescriptor) -> None:
        names = [c.name for c in interest.chaincodes] or [""]
        desc.chaincode = names[0]
        # cc2cc interest: intersect layouts by merging requirements —
        # here: layouts of the FIRST cc filtered to orgs that satisfy
        # every cc's policy (reference combines principal sets)
        layouts = self.chaincode_layouts(channel, names[0])
        peers = self._discovered_peers(channel_id)
        by_org: dict[str, list[dpb.DiscoveredPeer]] = {}
        for dp in peers:
            by_org.setdefault(dp.msp_id, []).append(dp)
        kept = []
        for layout in layouts:
            if all(len(by_org.get(org, ())) >= qty
                   for org, qty in layout.items()):
                kept.append(layout)
        for layout in kept:
            pl = desc.layouts.add()
            for org, qty in sorted(layout.items()):
                pl.quantities_by_org[org] = qty
            for org in layout:
                if org not in desc.endorsers_by_org:
                    group = desc.endorsers_by_org[org]
                    for dp in by_org[org]:
                        group.peers.add().CopyFrom(dp)
