from fabric_tpu.discovery.service import DiscoveryService  # noqa: F401
