"""Verified orderer onboarding: crash-safe, fault-tolerant chain
replication with source failover.

Rebuild of `orderer/common/onboarding/onboarding.go` +
`orderer/common/cluster/replication.go` with the block verification of
`cluster/util.go:202` VerifyBlocks: a joining (or lagging) orderer
pulls the channel's chain from ANY available consenter, failing over
between endpoints with full-jitter backoff (a source that dies
mid-transfer is excluded after repeated failures and re-admitted after
a cooldown), verifies every pulled block — header data-hash, previous
hash linkage, and the block signature against the channel's
`/Channel/Orderer/BlockValidation` policy through the batched BCCSP
seam — re-deriving the governing config from embedded config blocks as
the chain advances (the reference updates its verifier the same way),
and commits through the crash-safe block store so a kill at any point
resumes from the last durable block (the verified prefix is never
re-pulled; a forged or truncated suffix is never accepted).

State machine: discover → pull → verify → commit → (promote) → done.
Fault points for chaos runs: `cluster.pull`, `cluster.verify`,
`onboarding.commit` (common/faults.py); crash-fault injection for the
nwo kill-mid-catch-up test via FTPU_CRASH_ONBOARD_AT_HEIGHT.

Trust model for bootstrap (join from a non-genesis config block): the
operator-supplied join block is TRUSTED (it arrives over the
authenticated admin API). Its config seeds signature verification; the
pulled chain must hash-anchor to it — the block at the join height must
hash-equal the join block, so a source serving a different chain (fork,
wrong channel, forged prefix) is rejected and failed over. Pulled
genesis blocks are unsigned and only anchored transitively; history
before the join block is re-verified under the configs embedded in the
pulled chain, exactly like the reference's VerifyBlocks.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Optional, Sequence

from fabric_tpu.common import faults
from fabric_tpu.common import metrics as _m
from fabric_tpu.common.backoff import FullJitterBackoff
from fabric_tpu.common.channelconfig import Bundle
from fabric_tpu.internal.configtxgen.genesis import config_from_block
from fabric_tpu.protos import common, configtx as ctxpb
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("orderer.onboarding")

BLOCK_VALIDATION_POLICY = "/Channel/Orderer/BlockValidation"

# env hook for the nwo kill-mid-catch-up test: die (exit 43) right
# before committing the block with this number, leaving the verified
# prefix durable (the restart must resume, not re-pull)
CRASH_ENV = "FTPU_CRASH_ONBOARD_AT_HEIGHT"

ONBOARDING_STATE = _m.GaugeOpts(
    namespace="onboarding", name="state",
    help="The onboarding/replication state of the node on the channel:"
         " 1 for the current state (idle, discover, pull, verify, "
         "commit, promote, done, failed), 0 otherwise.",
    label_names=("channel", "state"))
ONBOARDING_BLOCKS_PULLED = _m.CounterOpts(
    namespace="onboarding", name="blocks_pulled_total",
    help="The number of blocks pulled from fellow consenters, "
         "verified, and committed by the onboarding replicator.",
    label_names=("channel",))
ONBOARDING_VERIFY_FAILURES = _m.CounterOpts(
    namespace="onboarding", name="verify_failures_total",
    help="The number of pulled block spans rejected by verification "
         "(bad data hash, broken previous-hash linkage, signature "
         "that does not satisfy the BlockValidation policy, or a "
         "chain that fails to anchor to the join block).",
    label_names=("channel",))
ONBOARDING_SOURCE_FAILOVERS = _m.CounterOpts(
    namespace="onboarding", name="source_failovers_total",
    help="The number of mid-stream source switches: the consenter "
         "being pulled from died or served bad blocks, and "
         "replication resumed from another consenter at the last "
         "committed height.",
    label_names=("channel",))

STATES = ("idle", "discover", "pull", "verify", "commit", "promote",
          "done", "failed")


class OnboardingError(Exception):
    """Replication could not complete (sources exhausted, halted, or
    deadline passed). The committed prefix stays durable; a retry or
    restart resumes from it."""


class VerificationError(OnboardingError):
    """A pulled block failed verification and was NOT committed."""

    def __init__(self, number: int, reason: str):
        super().__init__(f"block {number}: {reason}")
        self.number = number


class ChainAnchorError(VerificationError):
    """The pulled chain does not contain the trusted join block: the
    block at the join height hashes differently (fork, wrong channel,
    or forged prefix)."""


def consenter_endpoints(bundle) -> list[str]:
    """host:port of every consenter in the channel config's consensus
    metadata (the discovery half of onboarding: who can be pulled
    from)."""
    meta = ctxpb.ConsensusMetadata()
    meta.ParseFromString(bundle.orderer.consensus_metadata)
    return [f"{c.host}:{c.port}" for c in meta.consenters]


def bundle_from_config_block(channel_id: str, block: common.Block,
                             csp) -> Bundle:
    return Bundle(channel_id, config_from_block(block), csp)


def verify_block_span(channel_id: str, blocks: Sequence[common.Block],
                      start_height: int, prev_hash: Optional[bytes],
                      bundle: Bundle
                      ) -> tuple[int, Optional[Bundle],
                                 Optional[Exception]]:
    """Verify a contiguous span of pulled blocks (reference:
    `cluster/util.go:202` VerifyBlocks): numbering from `start_height`,
    data-hash integrity, previous-hash linkage (against `prev_hash`
    for the first block when known), and every block's signature set
    against the CURRENT config's BlockValidation policy — where
    "current" advances through config blocks embedded in the span, as
    the reference's verifier update does. Signatures are checked in
    ONE batched BCCSP dispatch for the whole span.

    Returns (valid_prefix_len, bundle_in_force_after_prefix, error):
    the first `valid_prefix_len` blocks are safe to commit; `error`
    explains why the prefix stopped short of the whole span (None when
    everything verified). Never raises: a verification failure is data
    about the SOURCE, not an exceptional program state.
    """
    csp = bundle.csp
    evals: list = []   # (block, prep|None, lo, n, bundle_after|None)
    items: list = []
    cur = bundle
    error: Optional[Exception] = None
    for i, b in enumerate(blocks):
        number = start_height + i
        try:
            if b.header.number != number:
                raise VerificationError(
                    b.header.number,
                    f"out of order (expected {number})")
            if b.header.data_hash != pu.block_data_hash(b.data):
                raise VerificationError(number, "data hash mismatch")
            if prev_hash is not None and \
                    b.header.previous_hash != prev_hash:
                raise VerificationError(
                    number, "previous-hash linkage broken")
            prep = None
            if number > 0:
                # the genesis block carries no signatures (nothing
                # existed to sign it); everything later must satisfy
                # the orderer policy of the config in force
                signed = pu.block_signature_set(b)
                policy = cur.policy_manager.get_policy(
                    BLOCK_VALIDATION_POLICY)
                try:
                    prep = policy.prepare(signed)
                except Exception:
                    # policy type without two-phase support: verify
                    # inline (its own csp still batches within the set)
                    policy.evaluate_signed_data(signed)
                    prep = None
            nxt = None
            if pu.is_config_block(b):
                nxt = bundle_from_config_block(channel_id, b, csp)
                cur = nxt
        except Exception as e:
            error = e if isinstance(e, VerificationError) else \
                VerificationError(number, str(e))
            break
        if prep is not None:
            evals.append((b, prep, len(items), len(prep.items), nxt))
            items.extend(prep.items)
        else:
            evals.append((b, None, 0, 0, nxt))
        prev_hash = pu.block_header_hash(b.header)

    ok = csp.verify_batch(items) if items else []
    n_valid = 0
    final_bundle = bundle
    for b, prep, lo, n, nxt in evals:
        if prep is not None:
            try:
                prep.finish(ok[lo:lo + n])
            except Exception as e:
                error = VerificationError(
                    b.header.number,
                    f"BlockValidation policy rejected signatures: {e}")
                break
        n_valid += 1
        if nxt is not None:
            final_bundle = nxt
    return n_valid, final_bundle, error


class SourceSelector:
    """Per-endpoint failover policy: round-robin over the consenter
    set, excluding an endpoint after `exclude_after` consecutive
    failures and re-admitting it (clean slate) once `cooldown_s` has
    served. When EVERY endpoint is excluded, the one whose cooldown
    expires soonest is offered anyway — liveness beats politeness; a
    3-node cluster that flapped must not wedge a joining orderer."""

    def __init__(self, exclude_after: int = 3, cooldown_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.exclude_after = exclude_after
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._order: list[str] = []
        self._failures: dict[str, int] = {}
        self._excluded_until: dict[str, float] = {}
        self._rr = 0

    def update(self, endpoints: Sequence[str]) -> None:
        """Refresh the candidate set (the consenter set can change as
        config blocks commit mid-replication)."""
        for ep in endpoints:
            if ep not in self._order:
                self._order.append(ep)
        live = set(endpoints)
        for ep in list(self._order):
            if ep not in live:
                self._order.remove(ep)
                self._failures.pop(ep, None)
                self._excluded_until.pop(ep, None)

    def admitted(self, ep: str) -> bool:
        until = self._excluded_until.get(ep)
        if until is None:
            return ep in self._order
        if self._clock() >= until:
            del self._excluded_until[ep]
            self._failures[ep] = 0
            return True
        return False

    def pick(self) -> Optional[str]:
        if not self._order:
            return None
        n = len(self._order)
        for i in range(n):
            ep = self._order[(self._rr + i) % n]
            if self.admitted(ep):
                self._rr = (self._rr + i + 1) % n
                return ep
        if not self._excluded_until:
            return None
        return min(self._excluded_until,
                   key=self._excluded_until.get)

    def report_failure(self, ep: str) -> bool:
        """Returns True when this failure EXCLUDED the endpoint."""
        f = self._failures.get(ep, 0) + 1
        self._failures[ep] = f
        if f >= self.exclude_after and ep not in self._excluded_until:
            self._excluded_until[ep] = self._clock() + self.cooldown_s
            logger.warning("source %s excluded for %.1fs after %d "
                           "consecutive failures", ep, self.cooldown_s,
                           f)
            return True
        return False

    def report_success(self, ep: str) -> None:
        self._failures[ep] = 0
        self._excluded_until.pop(ep, None)


class SupportSink:
    """Replication target for a channel that already has a
    ChainSupport (follower tracking, raft snapshot catch-up): verify
    against the support's live bundle, commit through its
    onboarded-block path (ledger append + writer resync + config
    re-apply)."""

    def __init__(self, support):
        self._support = support

    def height(self) -> int:
        return self._support.ledger.height

    def tip_hash(self) -> Optional[bytes]:
        h = self._support.ledger.height
        if h == 0:
            return None
        return pu.block_header_hash(
            self._support.ledger.get_block(h - 1).header)

    def verify(self, blocks) -> tuple[int, Optional[Exception]]:
        return self._support.verify_onboarded_span(blocks)

    def commit(self, block: common.Block) -> None:
        self._support.commit_onboarded_block(block)


class BootstrapSink:
    """Replication target for a channel being BOOTSTRAPPED from a
    non-genesis join block: no ChainSupport exists yet; blocks go
    straight into the (crash-safe) orderer ledger. The trusted join
    block seeds signature verification and anchors the pulled chain at
    its height."""

    def __init__(self, channel_id: str, ledger, join_block: common.Block,
                 csp):
        self._channel = channel_id
        self._ledger = ledger
        self._csp = csp
        self.anchor_number = join_block.header.number
        self._anchor_hash = pu.block_header_hash(join_block.header)
        # backward hash binding: expected[h] is the REQUIRED header
        # hash of block h, derived by walking previous_hash links down
        # from the trusted join block (attest()). `_bind_floor` is the
        # lowest height already walked; nothing below the anchor may
        # commit until its expected hash is known (32 bytes/block of
        # memory, pruned as blocks commit).
        self._expected: dict[int, bytes] = {
            self.anchor_number: self._anchor_hash}
        if self.anchor_number > 0:
            self._expected[self.anchor_number - 1] = \
                bytes(join_block.header.previous_hash)
        self._bind_floor = self.anchor_number
        self._bundle = bundle_from_config_block(channel_id, join_block,
                                                csp)
        # VERIFICATION follows the chain's historical configs;
        # DISCOVERY must not: a config block from years ago lists
        # since-retired consenter endpoints, and adopting it for
        # source selection would wedge replication on dead addresses.
        # Discovery starts from the trusted join block's consenter set
        # and only moves FORWARD (configs past the join height).
        self._discovery_bundle = self._bundle
        # resume after a crash: the last config block already COMMITTED
        # (verified) governs verification from here on — including the
        # genesis config, exactly as a fresh run would have adopted it
        # through verify_block_span's config advancement
        h = ledger.height
        if h:
            tip = ledger.get_block(h - 1)
            idx = tip.header.number if pu.is_config_block(tip) else \
                pu.get_last_config_index(tip)
            cfg = ledger.get_block(idx)
            if cfg is not None and pu.is_config_block(cfg):
                resumed = bundle_from_config_block(
                    self._channel, cfg, csp)
                self._bundle = resumed
                if idx > self.anchor_number:
                    self._discovery_bundle = resumed

    @property
    def bundle(self) -> Bundle:
        """The config governing source DISCOVERY: the trusted join
        block's, superseded only by config blocks past the join
        height (never by historical ones — see __init__)."""
        return self._discovery_bundle

    def height(self) -> int:
        return self._ledger.height

    def tip_hash(self) -> Optional[bytes]:
        h = self._ledger.height
        if h == 0:
            return None
        return pu.block_header_hash(
            self._ledger.get_block(h - 1).header)

    def attest(self, fetch_range) -> None:
        """Source attestation + anchor binding, called by the
        replicator BEFORE the first span is pulled from a source.

        Two jobs: (1) the source must serve a block at the trusted
        join height that hash-equals the join block — a fork / wrong
        channel / forged chain is rejected at first contact; (2) the
        previous-hash chain is walked BACKWARD from the join block
        down to the committed tip, pinning the required header hash of
        every sub-anchor height. Forward verification alone can't
        protect those heights: it (correctly, like the reference)
        adopts configs embedded in the pulled chain, so a fully
        self-consistent forged prefix would otherwise verify — even an
        adaptive source that answers this probe honestly and forges
        only span pulls is caught, because every forward block below
        the anchor must match its pinned hash (see verify()).

        The walk costs one extra pass over the un-replicated range
        (hashes only are retained); an interrupted walk resumes where
        it stopped when the next source attests. `fetch_range(a, b)`
        returns the source's blocks [a, b)."""
        got = list(fetch_range(self.anchor_number,
                               self.anchor_number + 1))
        if not got or got[0].header.number != self.anchor_number:
            raise OnboardingError(
                f"source has no block at the join height "
                f"{self.anchor_number} (stale or truncated)")
        if pu.block_header_hash(got[0].header) != self._anchor_hash:
            raise ChainAnchorError(
                self.anchor_number,
                "source's chain does not contain the join block")
        target = self._ledger.height
        while self._bind_floor > target:
            lo = max(target, self._bind_floor - 64)
            span = {b.header.number: b
                    for b in fetch_range(lo, self._bind_floor)}
            for num in range(self._bind_floor - 1, lo - 1, -1):
                b = span.get(num)
                if b is None:
                    raise OnboardingError(
                        f"source missing block {num} during anchor "
                        "binding")
                if pu.block_header_hash(b.header) != \
                        self._expected[num]:
                    raise ChainAnchorError(
                        num, "block does not back-chain to the join "
                             "block")
                if num > 0:
                    self._expected[num - 1] = \
                        bytes(b.header.previous_hash)
            self._bind_floor = lo
        # resume consistency: the already-committed tip must itself
        # back-chain to the anchor (it always does for prefixes this
        # sink committed; anything else is disk tampering)
        if target > 0 and self._bind_floor == target:
            tip = self.tip_hash()
            exp = self._expected.get(target - 1)
            if exp is not None and tip != exp:
                raise ChainAnchorError(
                    target - 1,
                    "committed prefix does not back-chain to the "
                    "join block")

    def verify(self, blocks) -> tuple[int, Optional[Exception]]:
        n_valid, bundle_after, err = verify_block_span(
            self._channel, blocks, self._ledger.height,
            self.tip_hash(), self._bundle)
        # anchor binding: every block at or below the join height must
        # hash-match the pin derived by attest()'s backward walk (the
        # join block itself included). A mismatch means the source is
        # serving a different chain (fork, wrong channel, forged
        # prefix) — reject the WHOLE span, nothing from such a source
        # may touch the ledger
        for b in blocks[:n_valid]:
            exp = self._expected.get(b.header.number)
            if exp is not None and \
                    pu.block_header_hash(b.header) != exp:
                return 0, ChainAnchorError(
                    b.header.number,
                    "pulled block does not anchor to the join block")
            if b.header.number <= self.anchor_number and exp is None:
                # unbound sub-anchor height: attest() has not walked
                # this far yet (it always has for admitted sources —
                # this is a belt-and-braces guard)
                return 0, ChainAnchorError(
                    b.header.number,
                    "block below the join height has no anchor "
                    "binding")
        return n_valid, err

    def commit(self, block: common.Block) -> None:
        self._ledger.add_block(block)
        # the pin has served its purpose; keep memory bounded
        self._expected.pop(block.header.number, None)
        if pu.is_config_block(block) and block.header.number > 0:
            adopted = bundle_from_config_block(
                self._channel, block, self._csp)
            self._bundle = adopted
            if block.header.number > self.anchor_number:
                self._discovery_bundle = adopted


# ftpu-check: allow-lockset(single-threaded engine: run/step execute on
# the one onboarding or tracking thread that owns the instance)
class ChainReplicator:
    """The pull → verify → commit engine. One instance per channel per
    process; both the bootstrap path (registrar join from a config
    block) and the tracking paths (follower chain, raft snapshot
    catch-up) drive it with different sinks."""

    def __init__(self, channel_id: str, transport, consenters_fn,
                 sink, selector: Optional[SourceSelector] = None,
                 backoff: Optional[FullJitterBackoff] = None,
                 batch: int = 20, metrics_provider=None,
                 on_state: Optional[Callable[[str], None]] = None):
        """`consenters_fn()` returns the channel's current consenter
        endpoints (the replicator drops this node's own endpoint);
        `sink` provides height()/tip_hash()/verify(blocks)/commit(b).
        """
        self._channel = channel_id
        self._transport = transport
        self._consenters_fn = consenters_fn
        self._sink = sink
        self.selector = selector or SourceSelector()
        self.backoff = backoff or FullJitterBackoff(0.05, 5.0)
        self._batch = batch
        self._on_state = on_state
        self._source: Optional[str] = None
        # set when the source we were progressing with is lost: the
        # next endpoint to make progress decides whether an actual
        # FAILOVER happened (different source) or the same one
        # recovered
        self._failed_over_from: Optional[str] = None
        # sources that passed the sink's attestation (chain identity
        # never changes, so once is enough per endpoint)
        self._attested: set[str] = set()
        self.state = "idle"
        provider = metrics_provider or _m.DisabledProvider()
        lbl = ("channel", channel_id)
        self._m_state = provider.new_gauge(ONBOARDING_STATE)
        self._m_pulled = provider.new_counter(
            ONBOARDING_BLOCKS_PULLED).with_labels(*lbl)
        self._m_verify_fail = provider.new_counter(
            ONBOARDING_VERIFY_FAILURES).with_labels(*lbl)
        self._m_failovers = provider.new_counter(
            ONBOARDING_SOURCE_FAILOVERS).with_labels(*lbl)
        self._set_state("idle")

    # -- state surface (metrics gauge + /healthz callback) --

    def _set_state(self, state: str) -> None:
        self.state = state
        for s in STATES:
            self._m_state.with_labels(
                "channel", self._channel, "state", s).set(
                1 if s == state else 0)
        if self._on_state is not None:
            try:
                self._on_state(state)
            except Exception:
                logger.debug("[%s] on_state callback failed",
                             self._channel)

    # -- failure bookkeeping --

    def _note_failure(self, ep: str, kind: str, exc) -> None:
        logger.warning("[%s] %s from source %s failed: %s",
                       self._channel, kind, ep, exc)
        self.selector.report_failure(ep)
        if ep == self._source:
            # mid-stream loss of the source we were progressing with;
            # whether this becomes a FAILOVER (vs. the same source
            # recovering) is decided when progress resumes
            self._source = None
            self._failed_over_from = ep

    # -- one replication round --

    def step(self, at_tip_ok: bool = False) -> int:
        """Pull once from one source, verify the span, commit the
        valid prefix. Returns the number of blocks committed. All
        transport/verification trouble is absorbed into the selector
        and backoff state — callers loop, they don't catch.

        `at_tip_ok` is tracking mode (follower at the live tip): an
        empty pull means the chain is quiescent, not that the source
        is stale."""
        self._set_state("discover")
        own = self._transport.endpoint
        eps = [ep for ep in self._consenters_fn() if ep != own]
        self.selector.update(eps)
        ep = self._source if (
            self._source is not None and
            self.selector.admitted(self._source)) else None
        if ep is None:
            ep = self.selector.pick()
        if ep is None:
            self._set_state("pull")
            return 0
        height = self._sink.height()
        self._set_state("pull")
        attest = getattr(self._sink, "attest", None)
        if attest is not None and ep not in self._attested:
            try:
                faults.check("cluster.pull")
                attest(lambda lo, hi: self._transport.pull_blocks(
                    ep, self._channel, lo, hi))
            except Exception as e:
                self._note_failure(ep, "attest", e)
                if isinstance(e, VerificationError):
                    self._m_verify_fail.add(1)
                return 0
            self._attested.add(ep)
        try:
            faults.check("cluster.pull")
            blocks = list(self._transport.pull_blocks(
                ep, self._channel, height, height + self._batch))
        except Exception as e:
            self._note_failure(ep, "pull", e)
            return 0
        # tolerate sources that include already-committed history;
        # what matters is the contiguous run from our height
        blocks = [b for b in blocks if b.header.number >= height]
        if not blocks or blocks[0].header.number != height:
            if at_tip_ok and not blocks:
                self.selector.report_success(ep)
                self._source = ep
            else:
                self._note_failure(
                    ep, "pull",
                    f"no block at height {height} (stale or truncated "
                    "source)")
            return 0

        self._set_state("verify")
        err: Optional[Exception] = None
        try:
            faults.check("cluster.verify")
            n_valid, err = self._sink.verify(blocks)
        except Exception as e:
            n_valid, err = 0, e
        if n_valid < len(blocks):
            self._m_verify_fail.add(1)

        committed = 0
        try:
            crash_at = int(os.environ.get(CRASH_ENV, ""))
        except ValueError:
            crash_at = None
        self._set_state("commit")
        for b in blocks[:n_valid]:
            if crash_at is not None and \
                    b.header.number == crash_at:
                logger.critical(
                    "%s=%d: dying before committing block %d",
                    CRASH_ENV, crash_at, b.header.number)
                os._exit(43)
            try:
                faults.check("onboarding.commit")
                self._sink.commit(b)
            except Exception as e:
                # commit trouble is OURS (disk, injected fault) — the
                # durable prefix stands; do NOT blame the source. The
                # driving loop backs off on zero-progress rounds, so
                # no counter advance here (it would double-step the
                # exponent per incident)
                logger.warning("[%s] commit of block %d failed: %s",
                               self._channel, b.header.number, e)
                return committed
            committed += 1
            self._m_pulled.add(1)
        if committed:
            self.backoff.reset()
            self.selector.report_success(ep)
            if self._failed_over_from is not None:
                if ep != self._failed_over_from:
                    # replication actually RESUMED on another
                    # consenter from the last committed height — the
                    # event the metric's help text describes
                    self._m_failovers.add(1)
                self._failed_over_from = None
            self._source = ep
        if err is not None:
            # the source served a span whose tail failed verification:
            # nothing beyond the valid prefix was committed; fail over
            self._note_failure(ep, "verify", err)
        return committed

    # -- driving loops --

    def run(self, target_height: int, stop=None,
            max_wall_s: Optional[float] = None) -> None:
        """Catch-up mode: replicate until the sink holds
        `target_height` blocks. Raises OnboardingError on halt or
        deadline — the committed prefix stays durable either way."""
        deadline = (time.monotonic() + max_wall_s
                    if max_wall_s is not None else None)
        while self._sink.height() < target_height:
            if stop is not None and stop.is_set():
                self._set_state("failed")
                raise OnboardingError(
                    f"[{self._channel}] replication halted at height "
                    f"{self._sink.height()}/{target_height}")
            if deadline is not None and time.monotonic() > deadline:
                self._set_state("failed")
                raise OnboardingError(
                    f"[{self._channel}] replication deadline passed at "
                    f"height {self._sink.height()}/{target_height}")
            if self.step(at_tip_ok=False) == 0:
                delay = self.backoff.next()
                if stop is not None:
                    stop.wait(delay)
                else:
                    time.sleep(delay)
        self._set_state("done")

    def poll_once(self) -> int:
        """Tracking mode (follower chain): one round; a quiescent tip
        is healthy, transport/verification failures rotate sources."""
        return self.step(at_tip_ok=True)
