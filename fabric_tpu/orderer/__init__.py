"""Ordering service (reference: `orderer/` — SURVEY.md §2.8)."""
