"""Solo consenter: single-node ordering for dev/test.

Rebuild of `orderer/consensus/solo/consensus.go` — one goroutine
(thread) drains a message queue through the blockcutter, arming the
batch timer while messages are pending; config messages flush pending
and get their own block. Production deployments use raft
(`fabric_tpu/orderer/raft`), exactly as in the reference.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Optional

from fabric_tpu.common import overload
from fabric_tpu.protos import common
from fabric_tpu.orderer.msgprocessor import (
    CONFIG, CONFIG_UPDATE, MsgProcessorError, classify,
)
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("orderer.solo")


@dataclass
class _Msg:
    env: common.Envelope
    config_seq: int
    is_config: bool


class SoloChain:
    """consensus.Chain (reference: `orderer/consensus/consensus.go`).

    The message queue is a bounded SheddingQueue (round 12): a full
    queue bounds the broadcast handler's wait by the caller's deadline
    budget and then sheds with a retryable OverloadError (surfaced as
    SERVICE_UNAVAILABLE) — even the dev/test consenter must not hang
    ingress forever."""

    def __init__(self, support):
        self._support = support
        self._queue = overload.SheddingQueue(
            f"solo.events.{support.channel_id}", maxsize=1000)
        self._halted = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- Chain interface --

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"solo-{self._support.channel_id}",
            daemon=True)
        self._thread.start()

    def halt(self) -> None:
        self._halted.set()
        self._queue.put_forced(None)  # wake the loop (bound-exempt)
        if self._thread is not None:
            self._thread.join(timeout=5)

    def order(self, env: common.Envelope, config_seq: int) -> None:
        """Normal message (reference solo `Order`)."""
        self._enqueue(_Msg(env, config_seq, is_config=False))

    def order_batch(self, envs_seqs) -> int:
        """A broadcast ingest window as one queue item (see
        RaftChain.order_batch). Returns the accepted count (all of
        them — the local queue cannot partially fail)."""
        if self._halted.is_set():
            raise MsgProcessorError("chain is halted")
        self._queue.put([_Msg(env, seq, is_config=False)
                         for env, seq in envs_seqs])
        return len(envs_seqs)

    def configure(self, env: common.Envelope, config_seq: int) -> None:
        """Config message — already wrapped by the msgprocessor."""
        self._enqueue(_Msg(env, config_seq, is_config=True))

    def _enqueue(self, msg: _Msg) -> None:
        if self._halted.is_set():
            raise MsgProcessorError("chain is halted")
        self._queue.put(msg)

    def errored(self) -> bool:
        return self._halted.is_set()

    # -- the loop (reference solo main for/select) --

    def _run(self) -> None:
        support = self._support
        timer_deadline: Optional[float] = None
        while not self._halted.is_set():
            timeout = None
            if timer_deadline is not None:
                timeout = max(0.0, timer_deadline - time.monotonic())
            try:
                msg = self._queue.get(timeout=timeout)
            except queue.Empty:
                # batch timer fired
                timer_deadline = None
                batch = support.cutter.cut()
                if batch:
                    block = support.create_next_block(batch)
                    support.write_block(block)
                continue
            if msg is None:
                break
            try:
                for m in (msg if isinstance(msg, list) else [msg]):
                    if m.is_config:
                        timer_deadline = self._process_config(
                            m, timer_deadline)
                    else:
                        timer_deadline = self._process_normal(
                            m, timer_deadline)
            except MsgProcessorError as e:
                logger.warning("[%s] message dropped during ordering: "
                               "%s", support.channel_id, e)
            except Exception:
                logger.exception("[%s] consenter error",
                                 support.channel_id)

    def _process_normal(self, msg: _Msg, timer_deadline):
        support = self._support
        if msg.config_seq < support.sequence():
            # config changed since broadcast validated it: revalidate
            support.processor.process_normal_msg(msg.env)
        batches, pending = support.cutter.ordered(msg.env)
        for batch in batches:
            block = support.create_next_block(batch)
            support.write_block(block)
        if not pending:
            return None
        if timer_deadline is None:
            return time.monotonic() + support.batch_timeout_s
        return timer_deadline

    def _process_config(self, msg: _Msg, timer_deadline):
        support = self._support
        env = msg.env
        if msg.config_seq < support.sequence():
            env, _seq = support.processor.process_config_msg(env)
        batch = support.cutter.cut()
        if batch:
            support.write_block(support.create_next_block(batch))
        block = support.create_next_block([env])
        support.write_config_block(block)
        return None


def consenter(support) -> SoloChain:
    """Factory for the registrar's consenter map."""
    return SoloChain(support)
