from fabric_tpu.orderer.raft.chain import RaftChain, consenter  # noqa: F401
from fabric_tpu.orderer.raft.core import RaftNode, Ready  # noqa: F401
from fabric_tpu.orderer.raft.storage import RaftStorage  # noqa: F401
