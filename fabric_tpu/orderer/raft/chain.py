"""Raft consenter chain: ordering via replicated consensus.

Rebuild of `orderer/consensus/etcdraft/chain.go` (`Order:388`,
`Submit:529`, `run:599`, `propose:930`, `writeBlock:857`): the elected
raft leader drains submitted envelopes through the blockcutter, creates
blocks with a local block creator (decoupled from the block writer —
in-flight blocks are not yet written), and proposes the serialized
block as a raft entry; every consenter writes committed entries through
its own BlockWriter (each orderer signs the blocks it stores). Config
blocks reconfigure the chain and, when the consenter set changed,
trigger a raft membership change; a consenter that finds itself removed
halts (the reference's eviction suspector, `eviction.go`). A follower
that receives a raft snapshot pulls the missing blocks from a fellow
consenter and verifies their signatures before appending
(`blockpuller.go` + `cluster/util.go VerifyBlocks`).

Raft node IDs are the first 8 bytes of SHA-256(endpoint) — stable
across membership changes without coordination (the reference persists
an id↔consenter table in the block metadata instead).

Round 10 rebuilt the hot path batch-first (the discipline that fixed
verify in rounds 6/9, applied to ordering): each drained ready-loop
tick becomes ONE admission window — stale envelopes revalidated in one
batched msgprocessor pass, the whole window fed through the cutter,
and every cut batch proposed through `RaftNode.propose_batch` (one WAL
append, one replication fan-out). Committed blocks are signed and
written off-loop by `pipeline.BlockWriteStage`, so block-cutting of
window N+1 overlaps consensus on block N and the write of block N−1;
config blocks, membership changes, log compaction and catch-up drain
the stage first, and any stage failure demotes to the sequential path
and heals through `_replay_committed` (crash-equivalent, bit-identical
block stream).
"""

from __future__ import annotations

import hashlib
import logging
import os
import queue
import threading
import time
from typing import Optional

from fabric_tpu.common import (adaptive, clustertrace, faults,
                               overload, tracing)
from fabric_tpu.common.hotpath import hot_path
from fabric_tpu.orderer.msgprocessor import MsgProcessorError
from fabric_tpu.orderer.raft.core import LEADER, RaftNode
from fabric_tpu.orderer.raft.pipeline import (
    BlockWriteStage, OrderWriteError,
)
from fabric_tpu.orderer.raft.storage import RaftStorage
from fabric_tpu.protos import common, orderer as opb
from fabric_tpu.protos import configtx as ctxpb, raft as rpb
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("orderer.raft.chain")

COMPACT_EVERY = 64   # entries between raft-log compactions

# round 19: default pacing bound on proposed-but-unapplied raft
# entries. The event queue and the write stage bound their own depths,
# but nothing bounded the CONSENSUS segment between them — a leader
# cuts and proposes instantly, so sustained overload parks thousands
# of blocks inside replication and every commit inherits that standing
# queue (classic bufferbloat: tightening the other knobs cannot drain
# a backlog that lives between them). The generous default keeps the
# gate invisible in normal operation; the adaptive controller shrinks
# the live cap under SLO burn so end-to-end latency becomes
# inflight x per-block cost instead of backlog x per-block cost.
MAX_INFLIGHT_BLOCKS = 4096

from fabric_tpu.common import metrics as _m  # noqa: E402

IS_LEADER = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft", name="is_leader",
    help="The leadership status of this node on the channel: 1 if it "
         "is the raft leader, 0 otherwise.", label_names=("channel",))
LEADER_CHANGES = _m.CounterOpts(
    namespace="consensus", subsystem="etcdraft", name="leader_changes",
    help="The number of leader changes observed since process start.",
    label_names=("channel",))
COMMITTED_BLOCK_NUMBER = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft",
    name="committed_block_number",
    help="The number of the latest block committed through raft.",
    label_names=("channel",))
PROPOSAL_FAILURES = _m.CounterOpts(
    namespace="consensus", subsystem="etcdraft",
    name="proposal_failures",
    help="The number of proposal failures on the leader (cut blocks "
         "that could not be proposed to raft).",
    label_names=("channel",))
CLUSTER_SIZE = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft", name="cluster_size",
    help="The number of consenters in the channel's raft cluster.",
    label_names=("channel",))
SNAPSHOT_BLOCK_NUMBER = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft",
    name="snapshot_block_number",
    help="The block number of the latest raft snapshot (log "
         "compaction point).", label_names=("channel",))
NORMAL_PROPOSALS_RECEIVED = _m.CounterOpts(
    namespace="consensus", subsystem="etcdraft",
    name="normal_proposals_received",
    help="The number of normal (non-config) proposals received by "
         "this node.", label_names=("channel",))
ACTIVE_NODES = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft",
    name="active_nodes",
    help="The number of consenters this node has heard from within "
         "the last few election timeouts (itself included).",
    label_names=("channel",))
DATA_PERSIST_DURATION = _m.HistogramOpts(
    namespace="consensus", subsystem="etcdraft",
    name="data_persist_duration",
    help="The time to persist raft log entries and hard state to "
         "the WAL in seconds.", label_names=("channel",))
CONFIG_PROPOSALS_RECEIVED = _m.CounterOpts(
    namespace="consensus", subsystem="etcdraft",
    name="config_proposals_received",
    help="The number of config proposals received by this node.",
    label_names=("channel",))


class RaftMetrics:
    """Reference: `orderer/consensus/etcdraft/metrics.go`."""

    def __init__(self, provider=None, channel: str = ""):
        provider = provider or _m.DisabledProvider()
        lbl = ("channel", channel)
        self.is_leader = provider.new_gauge(
            IS_LEADER).with_labels(*lbl)
        self.leader_changes = provider.new_counter(
            LEADER_CHANGES).with_labels(*lbl)
        self.committed_block_number = provider.new_gauge(
            COMMITTED_BLOCK_NUMBER).with_labels(*lbl)
        self.proposal_failures = provider.new_counter(
            PROPOSAL_FAILURES).with_labels(*lbl)
        self.cluster_size = provider.new_gauge(
            CLUSTER_SIZE).with_labels(*lbl)
        self.snapshot_block_number = provider.new_gauge(
            SNAPSHOT_BLOCK_NUMBER).with_labels(*lbl)
        self.normal_proposals = provider.new_counter(
            NORMAL_PROPOSALS_RECEIVED).with_labels(*lbl)
        self.config_proposals = provider.new_counter(
            CONFIG_PROPOSALS_RECEIVED).with_labels(*lbl)
        self.active_nodes = provider.new_gauge(
            ACTIVE_NODES).with_labels(*lbl)
        self.data_persist_duration = provider.new_histogram(
            DATA_PERSIST_DURATION).with_labels(*lbl)


def endpoint_id(endpoint: str) -> int:
    """Stable 63-bit raft node id for a consenter endpoint."""
    h = hashlib.sha256(endpoint.encode()).digest()
    return int.from_bytes(h[:8], "big") & 0x7FFFFFFFFFFFFFFF


def parse_consenters(metadata: bytes) -> dict[int, str]:
    meta = ctxpb.ConsensusMetadata()
    meta.ParseFromString(metadata)
    out = {}
    for c in meta.consenters:
        ep = f"{c.host}:{c.port}"
        out[endpoint_id(ep)] = ep
    return out


def parse_consenter_certs(metadata: bytes) -> dict[str, bytes]:
    """endpoint -> client TLS cert PEM from the channel's consenter
    set (reference etcdraft Consenter.client_tls_cert) — the identity
    table cluster-RPC callers are authenticated against."""
    meta = ctxpb.ConsensusMetadata()
    meta.ParseFromString(metadata)
    return {f"{c.host}:{c.port}": bytes(c.client_tls_cert)
            for c in meta.consenters}


class _TimedStorage:
    """RaftStorage proxy timing the WAL writes (append + hard state)
    into consensus_etcdraft_data_persist_duration."""

    def __init__(self, inner, observe):
        self._inner = inner
        self._observe = observe

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def append(self, entries) -> None:
        t0 = time.perf_counter()
        self._inner.append(entries)
        self._observe(time.perf_counter() - t0)

    def set_hard_state(self, term, voted_for, commit) -> None:
        t0 = time.perf_counter()
        self._inner.set_hard_state(term, voted_for, commit)
        self._observe(time.perf_counter() - t0)


class _BlockCreator:
    """In-flight block assembly, decoupled from the writer (reference:
    etcdraft/blockcreator.go)."""

    def __init__(self, number: int, prev_hash: bytes):
        self.number = number
        self.prev_hash = prev_hash

    def create(self, envelopes) -> common.Block:
        block = pu.new_block(self.number, self.prev_hash)
        for env in envelopes:
            block.data.data.append(pu.marshal(env))
        block.header.data_hash = pu.block_data_hash(block.data)
        self.number += 1
        self.prev_hash = pu.block_header_hash(block.header)
        return block


class _ProposalGate:
    """Admission-edge pacing for the consensus pipeline (round 19).

    Depth is entries the leader has proposed but not yet applied
    (`last_index - applied_index`): the segment of the ordering path
    that had no bound of its own. `admit` blocks the submitting
    broadcast worker while the pipeline is at capacity — honest
    backpressure, bounded by the caller's deadline budget exactly like
    `SheddingQueue.put` — then sheds with a retryable OverloadError.
    The live cap is a registered adaptive knob; the registered-stage
    readings (`raft.inflight.<channel>.<node>`) feed the controller's
    overload signal like any other stage."""

    _POLL_S = 0.005   # applied_index advances off-thread; no condvar

    def __init__(self, chain: "RaftChain",
                 cap: int = MAX_INFLIGHT_BLOCKS):
        self._chain = chain
        self.cap = cap
        self._lock = threading.Lock()
        self.stats = {"puts": 0, "sheds": 0}
        self._last_shed_t: Optional[float] = None
        self._shed_rate = overload.ShedRateWindow()
        self._name = (f"raft.inflight.{chain._support.channel_id}"
                      f".{chain.node_id}")
        overload.register_stage(self._name, self)

    def depth(self) -> int:
        node = self._chain.node
        return max(0, node.last_index() - node.applied_index)

    def overload_stats(self) -> dict:
        # no max_depth: the gate paces ADMISSION, it is not a hard
        # bound — one already-admitted window may cut several blocks
        # past the cap, which is overshoot, not a leak
        with self._lock:
            return {
                "depth": self.depth(),
                "capacity": self.cap,
                "sheds": self.stats["sheds"],
                "puts": self.stats["puts"],
                "last_shed_t": self._last_shed_t,
                "shed_rate": self._shed_rate.rate(),
            }

    def admit(self) -> None:
        """Wait for consensus-pipeline headroom, up to the ambient
        deadline budget; shed (retryable) past it."""
        cap = int(self.cap or 0)
        if cap <= 0 or self.depth() < cap:
            with self._lock:
                self.stats["puts"] += 1
            return
        budget = overload.Deadline.remaining_or(
            overload.default_enqueue_budget_s())
        deadline = time.monotonic() + max(0.0, budget)
        while self.depth() >= int(self.cap or cap):
            if time.monotonic() >= deadline or \
                    self._chain._halted.is_set():
                with self._lock:
                    self.stats["sheds"] += 1
                    self._last_shed_t = time.monotonic()
                    self._shed_rate.note()
                tracing.note_shed(self._name)
                raise overload.OverloadError(
                    self._name,
                    f"consensus pipeline at {self.depth()} inflight "
                    f"entries (cap {int(self.cap)}) past the deadline "
                    f"budget")
            time.sleep(self._POLL_S)
        with self._lock:
            self.stats["puts"] += 1


# ftpu-check: allow-lockset(raft actor: state mutates only on the _run
# loop; public submit/configure enqueue onto the internally-locked queue)
class RaftChain:
    """consensus.Chain over the raft core."""

    def __init__(self, support, transport, tick_interval_s: float = 0.1,
                 election_tick: int = 10, heartbeat_tick: int = 1,
                 metrics_provider=None,
                 write_pipeline: Optional[bool] = None):
        self._support = support
        self._transport = transport
        self.endpoint = transport.endpoint
        self._tick_s = tick_interval_s
        self.metrics = RaftMetrics(metrics_provider,
                                   channel=support.channel_id)
        self._last_leader = None   # soft_leader sentinel: None = no leader
        # failover attribution (round 15): the FIRST election of a
        # chain is startup, every later change is a failover — only
        # those auto-dump the flight recorder
        self._seen_leader = False
        self._send_warned: dict[str, float] = {}

        self._consenters = parse_consenters(
            support.bundle().orderer.consensus_metadata)
        if not self._consenters:
            raise ValueError(f"[{support.channel_id}] raft requires a "
                             "consenter set in the channel config")
        self.node_id = endpoint_id(self.endpoint)
        if self.node_id not in self._consenters:
            raise ValueError(f"{self.endpoint} is not a consenter on "
                             f"{support.channel_id}")

        storage = _TimedStorage(
            RaftStorage(support.ledger.db_handle("raft")),
            self.metrics.data_persist_duration.observe)
        self.node = RaftNode(self.node_id,
                             list(self._consenters),
                             storage,
                             election_tick=election_tick,
                             heartbeat_tick=heartbeat_tick)
        self._storage = storage
        # liveness view for the active_nodes gauge: ids we heard from
        # recently (updated on inbound raft traffic, decayed on tick)
        self._peer_seen: dict[int, float] = {}
        self._active_window_s = (3 * election_tick *
                                 max(tick_interval_s, 1e-3))
        self.metrics.active_nodes.set(1)
        # round 12: the consenter event queue is a bounded SHEDDING
        # queue — a full queue bounds the producer's wait by the
        # caller's deadline budget and then sheds with a retryable
        # OverloadError (surfaced as SERVICE_UNAVAILABLE), instead of
        # hanging the broadcast handler forever. The starting bound
        # resolves through overload.raft_events_cap()
        # (FTPU_RAFT_EVENTS_CAP > Operations.Overload.RaftEventsCap >
        # 4096, round 19); the live capacity is a registered adaptive
        # knob — the controller shrinks it under SLO burn so ordering
        # load sheds at the admission edge instead of queueing into
        # the commit p99, and restores it in calm.
        self._events = overload.SheddingQueue(
            f"raft.events.{support.channel_id}",
            maxsize=max(1, overload.raft_events_cap()))
        adaptive.register_queue_capacity(
            self._events,
            name=(f"raft.events.{support.channel_id}"
                  f".{self.node_id}.capacity"),
            floor=max(4, self._events.maxsize // 32))
        self._halted = threading.Event()
        # round 19: proposal pacing — see _ProposalGate. The cap is an
        # adaptive knob: invisible at the default, tightened under SLO
        # burn so commit latency tracks inflight depth, not backlog.
        self._proposal_gate = _ProposalGate(self)
        adaptive.register_attr_knob(
            self._proposal_gate, "cap",
            f"raft.inflight.{support.channel_id}.{self.node_id}.cap",
            floor=max(2, MAX_INFLIGHT_BLOCKS // 1024),
            ceiling=MAX_INFLIGHT_BLOCKS)
        self._thread: Optional[threading.Thread] = None
        self._creator: Optional[_BlockCreator] = None
        self._timer_deadline: Optional[float] = None
        self._applied_since_compact = 0
        self._metrics_provider = metrics_provider
        self._replicator = None   # lazy: built on first catch-up
        self.metrics.cluster_size.set(len(self._consenters))
        # round-10 ordering-pipeline accounting (read by
        # profiling.publish_order_stats and the bench)
        self.order_stats = {
            "windows": 0, "envelopes": 0, "blocks_proposed": 0,
            "propose_s": 0.0, "consensus_s": 0.0,
            "last_fill": 0, "last_propose_s": 0.0,
            "last_consensus_s": 0.0,
            "steps_coalesced": 0, "demotions": 0,
        }
        # block number -> (propose perf_counter, trace context):
        # consumed at commit time for the consensus-latency span
        self._proposed_at: dict[int, tuple] = {}
        # the most recent propose's trace context (round 18): entry-
        # bearing consensus sends in _drain_ready attach it so the
        # raft replication hop carries the ordering trace across
        # consenters (heartbeats stay unparented)
        self._last_order_ctx = None
        # raft-loop busy window, read by the write stage's overlap
        # accounting: (busy-since or None, last closed busy interval)
        self._loop_busy_since: Optional[float] = None
        self._loop_window: tuple[float, float] = (0.0, 0.0)
        self._write_stage: Optional[BlockWriteStage] = None
        self._replay_committed()
        if write_pipeline is None:
            write_pipeline = os.environ.get(
                "FTPU_ORDER_PIPELINE", "1") != "0"
        if write_pipeline:
            self._write_stage = BlockWriteStage(
                support, loop_activity=self._loop_activity,
                node_id=self.endpoint)
        transport.set_channel_auth(
            support.channel_id,
            parse_consenter_certs(
                support.bundle().orderer.consensus_metadata))
        transport.set_handler(support.channel_id, self)

    # -- restart replay: committed-but-unwritten entries --

    def _replay_committed(self) -> None:
        height = self._support.ledger.height
        for e in self._storage.entries(self._storage.first_index(),
                                       self.node.commit_index + 1):
            if e.type != rpb.Entry.NORMAL or not e.data:
                continue
            block = common.Block()
            try:
                block.ParseFromString(e.data)
            except Exception:
                continue
            if block.header.number == height:
                self._write_committed_block(block)
                height = self._support.ledger.height

    # ------------------------------------------------------------------
    # Chain interface (what broadcast + registrar call)
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run,
            name=f"raft-{self._support.channel_id}-{self.node_id % 997}",
            daemon=True)
        self._thread.start()

    def halt(self) -> None:
        self._halted.set()
        # the halt sentinel is a control item: bound-exempt, so a
        # full event queue can never make halt() hang or lose the wake
        self._events.put_forced(None)
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._write_stage is not None:
            # flush, don't abandon: committed blocks the stage still
            # holds would otherwise only reappear at the next restart's
            # replay (a clean halt should leave the ledger at the tip)
            try:
                self._write_stage.stop(flush=True)
            except Exception as e:
                logger.warning("[%s] halt: flushing write stage "
                               "failed: %s", self._support.channel_id,
                               e)
        try:
            self._transport.remove_handler(self._support.channel_id)
        except Exception as e:
            logger.warning("[%s] halt: removing transport handler "
                           "failed: %s", self._support.channel_id, e)

    def errored(self) -> bool:
        return self._halted.is_set()

    def force_tick(self) -> None:
        """Inject one immediate protocol tick through the event queue
        (round 16). The raft core is tick-driven by design — the
        protocol's whole clock is `node.tick()` — so a caller that
        needs retransmission/election timers to advance on ITS
        cadence (chaos tests healing dropped steps, a loaded box
        where the wall-clock tick thread starves) enqueues ticks
        instead of sleeping out wall margins. The tick runs on the
        loop's own thread like any event (no new locking), rides
        put_forced (a full queue cannot drop the clock), and a
        heartbeat-refreshed follower never times out from it — this
        accelerates the protocol uniformly, exactly like a shorter
        tick_interval_s."""
        self._events.put_forced(("tick",))

    def order(self, env: common.Envelope, config_seq: int) -> None:
        """Single-envelope Order folds through the SAME batch
        admission window as the bulk path: under load, the ready loop
        drains a run of these into one window — a burst of unary
        submitters never pays per-envelope consensus-event latency."""
        self.order_batch([(env, config_seq)])

    def order_batch(self, envs_seqs) -> int:
        """A whole ingest window as ONE event: the broadcast layer's
        batched filter hands the accepted run here, so the consenter
        loop wakes once per window instead of once per envelope (on a
        busy single-core host the per-envelope queue handoff was the
        ordering floor — reference chain.go Order enqueues per
        message). Returns how many LEADING envelopes were accepted —
        a follower forwarding to the leader can fail mid-window, and
        the already-forwarded prefix must not be reported as failed
        (the client would re-order it on retry)."""
        self.metrics.normal_proposals.add(len(envs_seqs))
        if self._halted.is_set():
            raise MsgProcessorError("chain is halted")
        leader = self.node.leader_id
        if leader == self.node_id:
            self._proposal_gate.admit()
            self._events.put(("order_batch", envs_seqs,
                              tracing.capture()))
            return len(envs_seqs)
        accepted = 0
        for env, seq in envs_seqs:
            try:
                self._submit_forward(env, seq)
            except MsgProcessorError:
                if accepted == 0:
                    raise
                return accepted
            accepted += 1
        return accepted

    def configure(self, env: common.Envelope, config_seq: int) -> None:
        self._submit(env, config_seq, is_config=True)

    def _submit(self, env: common.Envelope, config_seq: int,
                is_config: bool) -> None:
        (self.metrics.config_proposals if is_config
         else self.metrics.normal_proposals).add(1)
        if self._halted.is_set():
            raise MsgProcessorError("chain is halted")
        leader = self.node.leader_id
        if leader == self.node_id:
            self._events.put(("order", env, config_seq, is_config,
                              tracing.capture()))
            return
        self._submit_forward(env, config_seq)

    def _submit_forward(self, env: common.Envelope,
                        config_seq: int) -> None:
        """Forward to the current raft leader (reference Submit RPC)."""
        leader = self.node.leader_id
        if leader == 0:
            raise MsgProcessorError(
                f"[{self._support.channel_id}] no raft leader")
        target = self._consenters.get(leader)
        if target is None:
            raise MsgProcessorError(f"unknown raft leader {leader}")
        resp = self._transport.submit(target,
                                      self._support.channel_id,
                                      pu.marshal(env), config_seq)
        if resp.status != common.Status.SUCCESS:
            raise MsgProcessorError(
                f"leader {target} rejected submission: {resp.info}")

    # ------------------------------------------------------------------
    # cluster handler interface (transport calls these)
    # ------------------------------------------------------------------

    def on_consensus(self, sender: str, payload: bytes) -> None:
        if self._halted.is_set():
            return
        msg = rpb.RaftMessage()
        try:
            msg.ParseFromString(payload)
        except Exception:
            return
        # round 19: consensus steps are CONTROL-PLANE traffic and ride
        # PAST the data-plane bound (put_forced) — a queue full of
        # order submissions must never starve acks and heartbeats, or
        # sustained admission pressure deposes a healthy leader and
        # the whole channel livelocks (the serving soak exposed
        # exactly this). The lane is still bounded: past 4x the
        # data-plane capacity the step is dropped (raft retransmission
        # recovers INTERNAL protocol loss — counted in `drops`, not
        # `sheds`, which keeps meaning client-visible refusals).
        if self._events.qsize() < 4 * self._events.maxsize:
            self._events.put_forced(("step", msg))
        else:
            self._events.note_drop()
            logger.warning("[%s] raft event queue flooded; step "
                           "message dropped",
                           self._support.channel_id)

    def on_submit(self, env_bytes: bytes,
                  config_seq: int = 0) -> opb.SubmitResponse:
        channel = self._support.channel_id
        if self.node.leader_id != self.node_id:
            return opb.SubmitResponse(
                channel=channel, status=common.Status.SERVICE_UNAVAILABLE,
                info="not the leader")
        try:
            env = pu.unmarshal_envelope(env_bytes)
            # classify config-ness here; carry the ORIGIN's validation
            # sequence so _process_order re-runs the msgprocessor when
            # the forwarder validated under a stale channel config
            # (reference chain.go Submit/Order last_validation_seq).
            # The default 0 is conservative: unknown origin sequence
            # means the leader always re-validates.
            payload = pu.get_payload(env)
            ch = pu.get_channel_header(payload)
            is_config = ch.type in (common.HeaderType.CONFIG,
                                    common.HeaderType.ORDERER_TRANSACTION)
            if not is_config:   # config traffic is never paced
                self._proposal_gate.admit()
            self._events.put(("order", env, config_seq, is_config,
                              tracing.capture()))
        except overload.OverloadError as e:
            # full event queue past the deadline budget: backpressure
            # to the FORWARDER, which surfaces it to its client as a
            # retryable SERVICE_UNAVAILABLE (never a hung Submit RPC)
            return opb.SubmitResponse(
                channel=channel,
                status=common.Status.SERVICE_UNAVAILABLE, info=str(e))
        except Exception as e:
            return opb.SubmitResponse(channel=channel,
                                      status=common.Status.BAD_REQUEST,
                                      info=str(e))
        return opb.SubmitResponse(channel=channel,
                                  status=common.Status.SUCCESS)

    def serve_blocks(self, start: int, end: int) -> list[common.Block]:
        out = []
        for num in range(start, min(end, self._support.ledger.height)):
            b = self._support.ledger.get_block(num)
            if b is None:
                break
            out.append(b)
        return out

    # ------------------------------------------------------------------
    # main loop (reference chain.go run:599)
    # ------------------------------------------------------------------

    def _handle_event(self, ev, now: float) -> None:
        """One drained non-ordering event (`order`/`order_batch` never
        reach here — `_run` folds them into the tick's admission
        window). A failing raft step is a DROPPED message (raft's
        retransmission recovers it), never a reason to abort the rest
        of the drain's events; `raft.step` is the chaos point that
        models message loss/corruption."""
        if ev[0] == "step":
            try:
                faults.check("raft.step")
                self._peer_seen[ev[1].from_] = now
                self.node.step(ev[1])
            except Exception:
                logger.exception("[%s] raft step failed; message "
                                 "dropped", self._support.channel_id)
        elif ev[0] == "tick":
            # a force_tick() injection: one protocol tick on the
            # loop's own thread, independent of the wall-clock
            # cadence (see force_tick below)
            self.node.tick()

    def _coalesce_steps(self, evs: list) -> list:
        """Merge superseded CONSECUTIVE step messages from the same
        sender before stepping the state machine: an entry-less
        APPEND/HEARTBEAT only resets the election clock and advances
        the commit index — both carried (monotonically) by the newer
        message of the run; a non-reject APPEND_RESP is an ack the
        leader folds in with a monotonic max, so only the run's
        highest ack matters. Entry-bearing APPENDs, votes, rejections
        and cross-sender interleavings are never dropped — raft's own
        retransmission covers any ack a drop skipped."""
        out: list = []
        dropped = 0
        for ev in evs:
            if ev[0] == "step" and out and out[-1][0] == "step":
                prev, cur = out[-1][1], ev[1]
                if cur.from_ == prev.from_ and \
                        cur.term == prev.term and \
                        cur.type == prev.type:
                    if cur.type in (rpb.RaftMessage.APPEND,
                                    rpb.RaftMessage.HEARTBEAT) and \
                            not prev.entries and not cur.entries and \
                            cur.commit >= prev.commit and \
                            cur.prev_log_index >= prev.prev_log_index:
                        out[-1] = ev
                        dropped += 1
                        continue
                    if cur.type == rpb.RaftMessage.APPEND_RESP and \
                            not prev.reject and not cur.reject and \
                            cur.last_log_index >= prev.last_log_index:
                        out[-1] = ev
                        dropped += 1
                        continue
            out.append(ev)
        if dropped:
            self.order_stats["steps_coalesced"] += dropped
        return out

    def _loop_activity(self):
        """The write stage's overlap probe: is the raft loop busy now,
        and what was its last busy interval (perf_counter clock)."""
        return self._loop_busy_since, self._loop_window

    def _run(self) -> None:
        # cross-node trace attribution (round 18): everything this
        # loop records — order window/propose/consensus spans, leader-
        # change instants — belongs to THIS consenter's track
        tracing.set_node(self.endpoint)
        next_tick = time.monotonic() + self._tick_s
        while not self._halted.is_set():
            now = time.monotonic()
            deadline = next_tick
            if self._timer_deadline is not None:
                deadline = min(deadline, self._timer_deadline)
            try:
                ev = self._events.get(timeout=max(0.0, deadline - now))
            except queue.Empty:
                ev = ()
            if ev is None:
                break
            # drain everything already queued: one wakeup handles the
            # whole backlog, then ONE ready() pass flushes the
            # accumulated side effects (avoids per-event thread
            # handoffs when a producer is streaming submissions)
            evs = [ev] if ev else []
            while len(evs) < 4096:
                try:
                    nxt = self._events.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._halted.set()
                    break
                evs.append(nxt)
            self._loop_busy_since = time.perf_counter()
            try:
                now = time.monotonic()
                # the drained backlog becomes ONE admission window:
                # steps coalesce, submissions merge into a single
                # cutter/propose pass (preserving arrival order across
                # the config-message run breaks)
                window: list = []
                for ev in self._coalesce_steps(evs):
                    if ev[0] == "order":
                        window.append((ev[1], ev[2], ev[3],
                                       ev[4] if len(ev) > 4 else None))
                    elif ev[0] == "order_batch":
                        ctx = ev[2] if len(ev) > 2 else None
                        window.extend((env, seq, False, ctx)
                                      for env, seq in ev[1])
                    else:
                        self._handle_event(ev, now)
                if window:
                    self._process_order_window(window)
                if now >= next_tick:
                    self.node.tick()
                    next_tick = now + self._tick_s
                    horizon = now - self._active_window_s
                    self.metrics.active_nodes.set(
                        1 + sum(1 for nid, ts in
                                self._peer_seen.items()
                                if ts >= horizon and
                                nid in self._consenters))
                    if self._write_stage is not None:
                        try:
                            self._write_stage.check_error()
                        except OrderWriteError:
                            self._demote_write_stage()
                if self._timer_deadline is not None and \
                        now >= self._timer_deadline:
                    self._timer_deadline = None
                    self._cut_and_propose(self._support.cutter.cut())
                self._drain_ready()
            except Exception:
                logger.exception("[%s] raft chain loop error",
                                 self._support.channel_id)
            finally:
                end = time.perf_counter()
                self._loop_window = (self._loop_busy_since or end, end)
                self._loop_busy_since = None

    def _drain_ready(self) -> None:
        ready = self.node.ready()
        if ready.soft_leader != self._last_leader:
            # count only elections of a real node: X→None (leader
            # lost) must not double-count the following None→Y
            if ready.soft_leader is not None:
                self.metrics.leader_changes.add(1)
            # every leadership transition is a tracing landmark; a
            # REAL failover (a leader was already known) additionally
            # auto-dumps the flight recorder so the events leading to
            # it are attributable post-hoc (rate-limited, async)
            tracing.instant(
                "raft.leader_change",
                channel=self._support.channel_id,
                leader=ready.soft_leader or 0,
                prev=self._last_leader or 0,
                term=self.node.term)
            if self._seen_leader:
                tracing.auto_dump("leader_change")
            if ready.soft_leader is not None:
                self._seen_leader = True
            self._last_leader = ready.soft_leader
            self.metrics.is_leader.set(
                1 if ready.soft_leader == self.node_id else 0)
        for msg in ready.messages:
            target = self._consenters.get(msg.to)
            if target is None:
                continue
            try:
                # entry-bearing sends ride the last propose's trace
                # (round 18): the transport injects the ambient
                # carrier, so the remote consenter resumes the
                # ordering trace for exactly the replication hops —
                # attached(None) is a passthrough for heartbeats
                with tracing.attached(
                        self._last_order_ctx if msg.entries
                        else None):
                    self._transport.send_consensus(
                        target, self._support.channel_id,
                        msg.SerializeToString())
            except Exception as e:   # noqa: BLE001 — one dead peer must
                # not abort the rest of the drain: the transport RAISES
                # on unregistered endpoints (round 15), and a leader
                # heartbeating a killed consenter would otherwise drop
                # every later message of this ready batch. Rate-limit
                # the warn — this fires every heartbeat tick while the
                # peer stays gone.
                now = time.monotonic()
                if now - self._send_warned.get(target, 0.0) > 5.0:
                    self._send_warned[target] = now
                    logger.warning("[%s] consensus send to %s failed "
                                   "(suppressing repeats 5s): %s",
                                   self._support.channel_id, target, e)
        for entry in ready.committed_entries:
            self._apply(entry)
        if ready.soft_leader != self.node_id and self._creator:
            # deposed: in-flight blocks die with the old term
            self._creator = None
            self._timer_deadline = None
            self._proposed_at.clear()
            self._last_order_ctx = None

    # -- leader-side ordering (the admission window) --

    def _process_order_window(self, window) -> None:
        """One drained ready-loop tick's submissions as ONE ordering
        pass: stale envelopes revalidate in a single batched
        msgprocessor run (one device-batched sig-filter dispatch), the
        whole window streams through the blockcutter, and every cut
        batch rides one `_propose_batch` (one WAL append). Config
        messages break the run — they flush pending work and get their
        own block, preserving intra-channel arrival order exactly like
        the per-envelope path.

        Round 14: the whole pass runs under an `order.window` span
        attached to the window's first traced envelope (the ingress
        span's context, carried across the event queue), so propose /
        consensus / write spans downstream share its trace_id."""
        # normalize legacy 3-tuple items (tests and older callers
        # drive this entry directly without a trace context)
        window = [w if len(w) > 3 else (w[0], w[1], w[2], None)
                  for w in window]
        wctx = next((c for _env, _seq, _cfg, c in window
                     if c is not None), None)
        with tracing.span("order.window", parent=wctx,
                          envelopes=len(window)):
            self._run_order_window(window)

    def _run_order_window(self, window) -> None:
        support = self._support
        if self.node.state != LEADER:
            # deposed between submit and processing: re-route
            for env, seq, is_config, _ctx in window:
                try:
                    self._submit(env, seq, is_config)
                except MsgProcessorError as e:
                    logger.warning("[%s] dropped message during leader "
                                   "change: %s", support.channel_id, e)
            return
        t0 = time.perf_counter()
        run: list = []          # (env, config_seq) normal-message run
        batches: list = []      # cut batches awaiting one proposal

        def flush_run() -> None:
            nonlocal run
            if not run:
                return
            for env in self._revalidate_run(run):
                cut, _pending = support.cutter.ordered(env)
                batches.extend(cut)
            run = []

        for env, seq, is_config, _ctx in window:
            if is_config:
                flush_run()
                # propose everything cut so far FIRST: the config
                # block must land after the normal traffic that
                # preceded it in the window
                self._propose_batch(batches)
                batches = []
                try:
                    self._process_config(env, seq)
                except MsgProcessorError as e:
                    logger.warning("[%s] message dropped during "
                                   "ordering: %s", support.channel_id,
                                   e)
            else:
                run.append((env, seq))
        flush_run()
        self._propose_batch(batches)
        if support.cutter.pending_count:
            if self._timer_deadline is None:
                self._timer_deadline = (
                    time.monotonic() + support.batch_timeout_s)
        else:
            self._timer_deadline = None
        dt = time.perf_counter() - t0
        self.order_stats["windows"] += 1
        self.order_stats["envelopes"] += len(window)
        self.order_stats["propose_s"] += dt
        self.order_stats["last_propose_s"] = dt

    def _revalidate_run(self, run) -> list:
        """Envelopes validated by broadcast under a since-changed
        channel config must re-run the msgprocessor (reference
        chain.go Order last_validation_seq) — here in ONE batched pass
        for the window's whole stale set instead of per message.
        Returns the envelopes still accepted, in order; rejected ones
        are dropped with a warning (the per-envelope path's
        behavior)."""
        support = self._support
        seq_now = support.sequence()
        stale = [i for i, (_env, seq) in enumerate(run)
                 if seq < seq_now]
        if not stale:
            return [env for env, _seq in run]
        results = support.processor.process_normal_msgs(
            [run[i][0] for i in stale])
        dropped = set()
        for i, (_seq, err) in zip(stale, results):
            if err is not None:
                dropped.add(i)
                logger.warning("[%s] message dropped during ordering: "
                               "%s", support.channel_id, err)
        return [env for i, (env, _seq) in enumerate(run)
                if i not in dropped]

    def _process_config(self, env: common.Envelope,
                        config_seq: int) -> None:
        support = self._support
        if config_seq < support.sequence():
            env, _ = support.processor.process_config_msg(env)
        self._cut_and_propose(support.cutter.cut())
        self._timer_deadline = None
        self._propose_batch([[env]])

    def _cut_and_propose(self, batch) -> None:
        if batch:
            self._propose_batch([list(batch)])

    @hot_path
    @tracing.traced("order.propose")
    def _propose_batch(self, batches) -> None:
        """The batched-propose span: every batch the admission window
        cut becomes one raft entry, ALL entries appended through one
        `_TimedStorage` WAL write and replicated in one fan-out
        (`RaftNode.propose_batch`). The `order.propose` chaos point
        guards the span — a fault fires BEFORE any state mutates and
        demotes the window to the per-block sequential path, so a
        batching failure can never lose envelopes."""
        batches = [list(b) for b in batches if b]
        if not batches:
            return
        try:
            faults.check("order.propose")
            if self._creator is None:
                self._creator = self._creator_from_tail()
            blocks = [self._creator.create(b) for b in batches]
            n = self.node.propose_batch(
                [b.SerializeToString() for b in blocks])
        except Exception:
            logger.warning(
                "[%s] batched propose failed; demoting this window to "
                "sequential per-block proposes",
                self._support.channel_id, exc_info=True)
            self.order_stats["demotions"] += 1
            # the batched creator may have advanced past blocks that
            # were never proposed: rebuild from the raft-log tail
            self._creator = None
            for batch in batches:
                try:
                    self._propose_block(batch)
                except Exception:   # noqa: BLE001 — a storage error mid-
                    # demotion (failing WAL) must not abort the rest of
                    # the window or escape into the ready loop: this
                    # block is DROPPED exactly like a deposed leader's
                    # (clients track commitment via deliver and
                    # retransmit), the remaining batches still propose
                    logger.warning(
                        "[%s] sequential propose failed; block of %d "
                        "envelope(s) dropped", self._support.channel_id,
                        len(batch), exc_info=True)
                    self.metrics.proposal_failures.add(1)
                    self._creator = None
            return
        if n < len(blocks):
            logger.warning("[%s] %d proposal(s) dropped (not leader)",
                           self._support.channel_id, len(blocks) - n)
            self.metrics.proposal_failures.add(len(blocks) - n)
            self._creator = None
        now = time.perf_counter()
        pctx = tracing.capture()
        self._last_order_ctx = pctx
        for block in blocks[:n]:
            self._proposed_at[block.header.number] = (now, pctx)
        self.order_stats["blocks_proposed"] += n
        if n:
            self.order_stats["last_fill"] = len(batches[n - 1])

    def _propose_block(self, envelopes) -> None:
        """Sequential per-block propose — the pre-round-10 path, kept
        as the demotion target of `_propose_batch`."""
        if self._creator is None:
            self._creator = self._creator_from_tail()
        block = self._creator.create(envelopes)
        ok = self.node.propose(block.SerializeToString())
        if not ok:
            logger.warning("[%s] proposal dropped (not leader)",
                           self._support.channel_id)
            self.metrics.proposal_failures.add(1)
            self._creator = None
            return
        self._last_order_ctx = tracing.capture()
        self._proposed_at[block.header.number] = (
            time.perf_counter(), self._last_order_ctx)
        self.order_stats["blocks_proposed"] += 1
        self.order_stats["last_fill"] = len(envelopes)

    def _creator_from_tail(self) -> _BlockCreator:
        """New leader: continue after the last block in the raft log
        (it will commit under this term), else after the ledger tip."""
        for e in reversed(self._storage.entries(
                self._storage.first_index(),
                self.node.last_index() + 1)):
            if e.type != rpb.Entry.NORMAL or not e.data:
                continue
            try:
                block = common.Block()
                block.ParseFromString(e.data)
            except Exception:
                continue
            return _BlockCreator(block.header.number + 1,
                                 pu.block_header_hash(block.header))
        tip = self._support.ledger.get_block(
            self._support.ledger.height - 1)
        return _BlockCreator(tip.header.number + 1,
                             pu.block_header_hash(tip.header))

    # -- apply (every consenter) --

    def _apply(self, entry: rpb.Entry) -> None:
        if entry.type == rpb.Entry.CONF_CHANGE:
            # reconfiguration barrier: membership changes must observe
            # the durable ledger tip
            self._drain_write_stage("membership change")
            self._after_conf_change()
            return
        if not entry.data:
            return
        block = common.Block()
        try:
            block.ParseFromString(entry.data)
        except Exception:
            logger.warning("[%s] undecodable raft entry %d",
                           self._support.channel_id, entry.index)
            return
        rec = self._proposed_at.pop(block.header.number, None)
        pctx = None
        if rec is not None:
            t0, pctx = rec
            t1 = time.perf_counter()
            dt = t1 - t0
            self.order_stats["consensus_s"] += dt
            self.order_stats["last_consensus_s"] = dt
            # propose->commit replication latency as a complete span
            # under the proposing window's trace (leader only — a
            # follower never proposed, so it has no t0 to anchor)
            pctx = tracing.observe_span(
                "order.consensus", t0, t1, parent=pctx,
                block=block.header.number) or pctx
        height = self._support.ledger.height
        if self._write_stage is not None:
            # blocks the write stage holds count as written: a
            # re-applied entry for one is a duplicate, not a gap
            height = self._write_stage.effective_tip(height)
        if block.header.number < height:
            return  # duplicate (replay)
        if block.header.number > height:
            # the replicator appends to the ledger directly: it must
            # see the durable tip, not race the async writer
            self._drain_write_stage("snapshot catch-up")
            self._catch_up(self._support.ledger.height,
                           block.header.number)
            if self._support.ledger.height != block.header.number:
                logger.error("[%s] catch-up to %d failed (at %d)",
                             self._support.channel_id,
                             block.header.number,
                             self._support.ledger.height)
                return
        with tracing.attached(pctx):
            self._write_committed_block(block)
        self._applied_since_compact += 1
        if self._applied_since_compact >= COMPACT_EVERY:
            # compaction barrier: an entry compacted away while its
            # block is still in flight would be unrecoverable after a
            # crash — drain first; on a stall, just postpone (the next
            # applied entry retries)
            if self._write_stage is not None:
                try:
                    if not self._write_stage.drain(timeout=5.0):
                        return
                except OrderWriteError:
                    self._demote_write_stage()
            self._applied_since_compact = 0
            self.node.compact(self.node.applied_index,
                              self._support.ledger.height)
            self.metrics.snapshot_block_number.set(
                self._support.ledger.height - 1)

    def _write_committed_block(self, block: common.Block) -> None:
        self.metrics.committed_block_number.set(block.header.number)
        support = self._support
        # pin the block's trace carrier (round 18): blocks travel by
        # value and must stay bit-identical across replay, so the
        # carrier lives in a side registry keyed (channel, number) —
        # the gossip/deliver commit seams resume it on the peers.
        # Ambient here is the proposing window's context (re-attached
        # at _apply on the leader); a follower has none and registers
        # nothing — its deliver readers fall back to a fresh trace.
        clustertrace.register_block(support.channel_id,
                                    block.header.number)
        if pu.is_config_block(block):
            # config barrier: the reconfiguration below (and the
            # bundle the NEXT message validates under) must observe
            # every earlier block durably written
            self._drain_write_stage("config block")
            if block.header.number < support.ledger.height:
                # the barrier demoted: _replay_committed already wrote
                # this very entry (and ran the reconfiguration)
                # through the sequential path — writing it again
                # would be an out-of-order append
                return
            support.write_config_block(block)
            self._reconfigure()
        elif self._write_stage is not None:
            try:
                self._write_stage.submit(block)
            except OrderWriteError:
                # demotion replays committed-but-unwritten entries —
                # including this one — through the sequential path
                self._demote_write_stage()
        else:
            support.write_block(block)

    def _drain_write_stage(self, reason: str,
                           timeout: float = 30.0) -> None:
        """Barrier: wait for the write stage to reach the submitted
        tip. A sticky error or a stall demotes to sequential writes
        (which replays the gap from the raft log)."""
        if self._write_stage is None:
            return
        try:
            if self._write_stage.drain(timeout=timeout):
                return
            logger.warning("[%s] write stage stalled at a %s barrier; "
                           "demoting to sequential writes",
                           self._support.channel_id, reason)
        except OrderWriteError:
            pass
        self._demote_write_stage()

    def _demote_write_stage(self) -> None:
        """Stage failure → the sequential path: stop the worker
        (crash-equivalent for anything it still held) and heal the
        ledger gap from the raft log, exactly like a restart would.
        No envelope is lost — every affected block's entry is still in
        the WAL."""
        stage, self._write_stage = self._write_stage, None
        if stage is None:
            return
        logger.warning("[%s] block-write pipeline demoted to the "
                       "sequential path", self._support.channel_id)
        self.order_stats["demotions"] += 1
        try:
            stage.stop(flush=False)
        except Exception as e:   # noqa: BLE001 — demotion must complete
            logger.warning("[%s] stopping failed write stage: %s",
                           self._support.channel_id, e)
        # the replay below appends through the same BlockWriter the
        # worker uses — never run both concurrently. A worker wedged
        # in a device dispatch is bounded by the provider's breaker
        # deadline (round 1), and the sequential path would block the
        # loop on that same write anyway, so this wait terminates.
        while stage.alive():
            logger.warning("[%s] write worker still mid-span; waiting "
                           "before the sequential replay",
                           self._support.channel_id)
            stage.join(timeout=10.0)
        self._replay_committed()

    def _reconfigure(self) -> None:
        """A config block committed: adopt the (possibly) new consenter
        set; the leader drives the raft membership change."""
        meta = self._support.bundle().orderer.consensus_metadata
        new = parse_consenters(meta)
        if not new:
            return
        # refresh the caller-auth table even when the endpoint set is
        # unchanged: a config update may rotate a consenter's client
        # TLS cert in place
        self._transport.set_channel_auth(self._support.channel_id,
                                         parse_consenter_certs(meta))
        if new == self._consenters:
            return
        logger.info("[%s] consenter set change: %s -> %s",
                    self._support.channel_id,
                    sorted(self._consenters.values()),
                    sorted(new.values()))
        self._consenters = new
        self.metrics.cluster_size.set(len(new))
        if self.node.state == LEADER:
            self.node.propose_conf_change(list(new))

    def _after_conf_change(self) -> None:
        if self.node_id not in self.node.peers:
            logger.warning("[%s] this consenter was evicted; halting "
                           "chain (deliver keeps serving)",
                           self._support.channel_id)
            threading.Thread(target=self.halt, daemon=True).start()

    def order_pipeline_stats(self) -> dict:
        """Merged ordering-pipeline readings. The `fill`/`propose_s`/
        `consensus_s`/`write_s`/`overlap_ratio` keys feed the
        canonical `orderer_batch_*` gauges through
        `profiling.publish_order_stats`; the counters ride along for
        the bench and tests."""
        s = self.order_stats
        out = {
            "fill": s["last_fill"],
            "propose_s": s["last_propose_s"],
            "consensus_s": s["last_consensus_s"],
            "write_s": 0.0,
            "overlap_ratio": 0.0,
            "windows": s["windows"],
            "envelopes": s["envelopes"],
            "blocks_proposed": s["blocks_proposed"],
            "propose_total_s": s["propose_s"],
            "consensus_total_s": s["consensus_s"],
            "steps_coalesced": s["steps_coalesced"],
            "demotions": s["demotions"],
        }
        stage = self._write_stage
        if stage is not None:
            out["write_s"] = stage.stats["last_write_s"]
            out["write_total_s"] = stage.stats["write_s"]
            out["blocks_written"] = stage.stats["written"]
            out["write_spans"] = stage.stats["spans"]
            out["overlap_ratio"] = stage.overlap_ratio
        return out

    # -- snapshot catch-up (reference blockpuller.go) --

    def _catch_up(self, start: int, end: int) -> None:
        """A raft snapshot points past our ledger: pull the gap through
        the onboarding replicator — verified blocks, source failover,
        full-jitter backoff — instead of a single fixed source
        (reference blockpuller.go over cluster/replication.go)."""
        from fabric_tpu.orderer import onboarding as onb
        if self._replicator is None:
            self._replicator = onb.ChainReplicator(
                self._support.channel_id, self._transport,
                consenters_fn=lambda: [
                    ep for _nid, ep in sorted(self._consenters.items())],
                sink=onb.SupportSink(self._support),
                metrics_provider=self._metrics_provider)
        try:
            # bounded: this runs on the raft event-loop thread, and an
            # unfinished catch-up is retried when the next committed
            # entry arrives
            self._replicator.run(target_height=end, stop=self._halted,
                                 max_wall_s=15.0)
        except onb.OnboardingError as e:
            logger.warning("[%s] snapshot catch-up incomplete: %s",
                           self._support.channel_id, e)


def consenter(transport, tick_interval_s: float = 0.1,
              election_tick: int = 10, metrics_provider=None,
              write_pipeline: Optional[bool] = None):
    """Factory-of-factories for the registrar's consenter map:
    `{"etcdraft": raft.consenter(transport)}`. An orderer outside the
    channel's consenter set comes up as a FOLLOWER (onboarding mode)
    instead — the reference registrar's SwitchFollowerToChain seam."""
    def factory(support):
        consenters = parse_consenters(
            support.bundle().orderer.consensus_metadata)
        if endpoint_id(transport.endpoint) not in consenters:
            from fabric_tpu.orderer.raft.follower import FollowerChain
            logger.info("[%s] %s not in consenter set: starting as "
                        "follower", support.channel_id,
                        transport.endpoint)
            return FollowerChain(
                support, transport,
                on_became_consenter=getattr(
                    support, "on_became_consenter", None),
                metrics_provider=metrics_provider)
        return RaftChain(support, transport,
                         tick_interval_s=tick_interval_s,
                         election_tick=election_tick,
                         metrics_provider=metrics_provider,
                         write_pipeline=write_pipeline)
    return factory
