"""Raft consenter chain: ordering via replicated consensus.

Rebuild of `orderer/consensus/etcdraft/chain.go` (`Order:388`,
`Submit:529`, `run:599`, `propose:930`, `writeBlock:857`): the elected
raft leader drains submitted envelopes through the blockcutter, creates
blocks with a local block creator (decoupled from the block writer —
in-flight blocks are not yet written), and proposes the serialized
block as a raft entry; every consenter writes committed entries through
its own BlockWriter (each orderer signs the blocks it stores). Config
blocks reconfigure the chain and, when the consenter set changed,
trigger a raft membership change; a consenter that finds itself removed
halts (the reference's eviction suspector, `eviction.go`). A follower
that receives a raft snapshot pulls the missing blocks from a fellow
consenter and verifies their signatures before appending
(`blockpuller.go` + `cluster/util.go VerifyBlocks`).

Raft node IDs are the first 8 bytes of SHA-256(endpoint) — stable
across membership changes without coordination (the reference persists
an id↔consenter table in the block metadata instead).
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time
from typing import Optional

from fabric_tpu.common import faults
from fabric_tpu.orderer.msgprocessor import MsgProcessorError
from fabric_tpu.orderer.raft.core import LEADER, RaftNode
from fabric_tpu.orderer.raft.storage import RaftStorage
from fabric_tpu.protos import common, orderer as opb
from fabric_tpu.protos import configtx as ctxpb, raft as rpb
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("orderer.raft.chain")

COMPACT_EVERY = 64   # entries between raft-log compactions

from fabric_tpu.common import metrics as _m  # noqa: E402

IS_LEADER = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft", name="is_leader",
    help="The leadership status of this node on the channel: 1 if it "
         "is the raft leader, 0 otherwise.", label_names=("channel",))
LEADER_CHANGES = _m.CounterOpts(
    namespace="consensus", subsystem="etcdraft", name="leader_changes",
    help="The number of leader changes observed since process start.",
    label_names=("channel",))
COMMITTED_BLOCK_NUMBER = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft",
    name="committed_block_number",
    help="The number of the latest block committed through raft.",
    label_names=("channel",))
PROPOSAL_FAILURES = _m.CounterOpts(
    namespace="consensus", subsystem="etcdraft",
    name="proposal_failures",
    help="The number of proposal failures on the leader (cut blocks "
         "that could not be proposed to raft).",
    label_names=("channel",))
CLUSTER_SIZE = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft", name="cluster_size",
    help="The number of consenters in the channel's raft cluster.",
    label_names=("channel",))
SNAPSHOT_BLOCK_NUMBER = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft",
    name="snapshot_block_number",
    help="The block number of the latest raft snapshot (log "
         "compaction point).", label_names=("channel",))
NORMAL_PROPOSALS_RECEIVED = _m.CounterOpts(
    namespace="consensus", subsystem="etcdraft",
    name="normal_proposals_received",
    help="The number of normal (non-config) proposals received by "
         "this node.", label_names=("channel",))
ACTIVE_NODES = _m.GaugeOpts(
    namespace="consensus", subsystem="etcdraft",
    name="active_nodes",
    help="The number of consenters this node has heard from within "
         "the last few election timeouts (itself included).",
    label_names=("channel",))
DATA_PERSIST_DURATION = _m.HistogramOpts(
    namespace="consensus", subsystem="etcdraft",
    name="data_persist_duration",
    help="The time to persist raft log entries and hard state to "
         "the WAL in seconds.", label_names=("channel",))
CONFIG_PROPOSALS_RECEIVED = _m.CounterOpts(
    namespace="consensus", subsystem="etcdraft",
    name="config_proposals_received",
    help="The number of config proposals received by this node.",
    label_names=("channel",))


class RaftMetrics:
    """Reference: `orderer/consensus/etcdraft/metrics.go`."""

    def __init__(self, provider=None, channel: str = ""):
        provider = provider or _m.DisabledProvider()
        lbl = ("channel", channel)
        self.is_leader = provider.new_gauge(
            IS_LEADER).with_labels(*lbl)
        self.leader_changes = provider.new_counter(
            LEADER_CHANGES).with_labels(*lbl)
        self.committed_block_number = provider.new_gauge(
            COMMITTED_BLOCK_NUMBER).with_labels(*lbl)
        self.proposal_failures = provider.new_counter(
            PROPOSAL_FAILURES).with_labels(*lbl)
        self.cluster_size = provider.new_gauge(
            CLUSTER_SIZE).with_labels(*lbl)
        self.snapshot_block_number = provider.new_gauge(
            SNAPSHOT_BLOCK_NUMBER).with_labels(*lbl)
        self.normal_proposals = provider.new_counter(
            NORMAL_PROPOSALS_RECEIVED).with_labels(*lbl)
        self.config_proposals = provider.new_counter(
            CONFIG_PROPOSALS_RECEIVED).with_labels(*lbl)
        self.active_nodes = provider.new_gauge(
            ACTIVE_NODES).with_labels(*lbl)
        self.data_persist_duration = provider.new_histogram(
            DATA_PERSIST_DURATION).with_labels(*lbl)


def endpoint_id(endpoint: str) -> int:
    """Stable 63-bit raft node id for a consenter endpoint."""
    h = hashlib.sha256(endpoint.encode()).digest()
    return int.from_bytes(h[:8], "big") & 0x7FFFFFFFFFFFFFFF


def parse_consenters(metadata: bytes) -> dict[int, str]:
    meta = ctxpb.ConsensusMetadata()
    meta.ParseFromString(metadata)
    out = {}
    for c in meta.consenters:
        ep = f"{c.host}:{c.port}"
        out[endpoint_id(ep)] = ep
    return out


def parse_consenter_certs(metadata: bytes) -> dict[str, bytes]:
    """endpoint -> client TLS cert PEM from the channel's consenter
    set (reference etcdraft Consenter.client_tls_cert) — the identity
    table cluster-RPC callers are authenticated against."""
    meta = ctxpb.ConsensusMetadata()
    meta.ParseFromString(metadata)
    return {f"{c.host}:{c.port}": bytes(c.client_tls_cert)
            for c in meta.consenters}


class _TimedStorage:
    """RaftStorage proxy timing the WAL writes (append + hard state)
    into consensus_etcdraft_data_persist_duration."""

    def __init__(self, inner, observe):
        self._inner = inner
        self._observe = observe

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def append(self, entries) -> None:
        t0 = time.perf_counter()
        self._inner.append(entries)
        self._observe(time.perf_counter() - t0)

    def set_hard_state(self, term, voted_for, commit) -> None:
        t0 = time.perf_counter()
        self._inner.set_hard_state(term, voted_for, commit)
        self._observe(time.perf_counter() - t0)


class _BlockCreator:
    """In-flight block assembly, decoupled from the writer (reference:
    etcdraft/blockcreator.go)."""

    def __init__(self, number: int, prev_hash: bytes):
        self.number = number
        self.prev_hash = prev_hash

    def create(self, envelopes) -> common.Block:
        block = pu.new_block(self.number, self.prev_hash)
        for env in envelopes:
            block.data.data.append(pu.marshal(env))
        block.header.data_hash = pu.block_data_hash(block.data)
        self.number += 1
        self.prev_hash = pu.block_header_hash(block.header)
        return block


class RaftChain:
    """consensus.Chain over the raft core."""

    def __init__(self, support, transport, tick_interval_s: float = 0.1,
                 election_tick: int = 10, heartbeat_tick: int = 1,
                 metrics_provider=None):
        self._support = support
        self._transport = transport
        self.endpoint = transport.endpoint
        self._tick_s = tick_interval_s
        self.metrics = RaftMetrics(metrics_provider,
                                   channel=support.channel_id)
        self._last_leader = None   # soft_leader sentinel: None = no leader

        self._consenters = parse_consenters(
            support.bundle().orderer.consensus_metadata)
        if not self._consenters:
            raise ValueError(f"[{support.channel_id}] raft requires a "
                             "consenter set in the channel config")
        self.node_id = endpoint_id(self.endpoint)
        if self.node_id not in self._consenters:
            raise ValueError(f"{self.endpoint} is not a consenter on "
                             f"{support.channel_id}")

        storage = _TimedStorage(
            RaftStorage(support.ledger.db_handle("raft")),
            self.metrics.data_persist_duration.observe)
        self.node = RaftNode(self.node_id,
                             list(self._consenters),
                             storage,
                             election_tick=election_tick,
                             heartbeat_tick=heartbeat_tick)
        self._storage = storage
        # liveness view for the active_nodes gauge: ids we heard from
        # recently (updated on inbound raft traffic, decayed on tick)
        self._peer_seen: dict[int, float] = {}
        self._active_window_s = (3 * election_tick *
                                 max(tick_interval_s, 1e-3))
        self.metrics.active_nodes.set(1)
        self._events: queue.Queue = queue.Queue(maxsize=4096)
        self._halted = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._creator: Optional[_BlockCreator] = None
        self._timer_deadline: Optional[float] = None
        self._applied_since_compact = 0
        self._metrics_provider = metrics_provider
        self._replicator = None   # lazy: built on first catch-up
        self.metrics.cluster_size.set(len(self._consenters))
        self._replay_committed()
        transport.set_channel_auth(
            support.channel_id,
            parse_consenter_certs(
                support.bundle().orderer.consensus_metadata))
        transport.set_handler(support.channel_id, self)

    # -- restart replay: committed-but-unwritten entries --

    def _replay_committed(self) -> None:
        height = self._support.ledger.height
        for e in self._storage.entries(self._storage.first_index(),
                                       self.node.commit_index + 1):
            if e.type != rpb.Entry.NORMAL or not e.data:
                continue
            block = common.Block()
            try:
                block.ParseFromString(e.data)
            except Exception:
                continue
            if block.header.number == height:
                self._write_committed_block(block)
                height = self._support.ledger.height

    # ------------------------------------------------------------------
    # Chain interface (what broadcast + registrar call)
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run,
            name=f"raft-{self._support.channel_id}-{self.node_id % 997}",
            daemon=True)
        self._thread.start()

    def halt(self) -> None:
        self._halted.set()
        try:
            self._events.put_nowait(None)
        except queue.Full:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self._transport.remove_handler(self._support.channel_id)
        except Exception as e:
            logger.warning("[%s] halt: removing transport handler "
                           "failed: %s", self._support.channel_id, e)

    def errored(self) -> bool:
        return self._halted.is_set()

    def order(self, env: common.Envelope, config_seq: int) -> None:
        self._submit(env, config_seq, is_config=False)

    def order_batch(self, envs_seqs) -> int:
        """A whole ingest window as ONE event: the broadcast layer's
        batched filter hands the accepted run here, so the consenter
        loop wakes once per window instead of once per envelope (on a
        busy single-core host the per-envelope queue handoff was the
        ordering floor — reference chain.go Order enqueues per
        message). Returns how many LEADING envelopes were accepted —
        a follower forwarding to the leader can fail mid-window, and
        the already-forwarded prefix must not be reported as failed
        (the client would re-order it on retry)."""
        self.metrics.normal_proposals.add(len(envs_seqs))
        if self._halted.is_set():
            raise MsgProcessorError("chain is halted")
        leader = self.node.leader_id
        if leader == self.node_id:
            self._events.put(("order_batch", envs_seqs))
            return len(envs_seqs)
        accepted = 0
        for env, seq in envs_seqs:
            try:
                self._submit_forward(env, seq)
            except MsgProcessorError:
                if accepted == 0:
                    raise
                return accepted
            accepted += 1
        return accepted

    def configure(self, env: common.Envelope, config_seq: int) -> None:
        self._submit(env, config_seq, is_config=True)

    def _submit(self, env: common.Envelope, config_seq: int,
                is_config: bool) -> None:
        (self.metrics.config_proposals if is_config
         else self.metrics.normal_proposals).add(1)
        if self._halted.is_set():
            raise MsgProcessorError("chain is halted")
        leader = self.node.leader_id
        if leader == self.node_id:
            self._events.put(("order", env, config_seq, is_config))
            return
        self._submit_forward(env, config_seq)

    def _submit_forward(self, env: common.Envelope,
                        config_seq: int) -> None:
        """Forward to the current raft leader (reference Submit RPC)."""
        leader = self.node.leader_id
        if leader == 0:
            raise MsgProcessorError(
                f"[{self._support.channel_id}] no raft leader")
        target = self._consenters.get(leader)
        if target is None:
            raise MsgProcessorError(f"unknown raft leader {leader}")
        resp = self._transport.submit(target,
                                      self._support.channel_id,
                                      pu.marshal(env), config_seq)
        if resp.status != common.Status.SUCCESS:
            raise MsgProcessorError(
                f"leader {target} rejected submission: {resp.info}")

    # ------------------------------------------------------------------
    # cluster handler interface (transport calls these)
    # ------------------------------------------------------------------

    def on_consensus(self, sender: str, payload: bytes) -> None:
        if self._halted.is_set():
            return
        msg = rpb.RaftMessage()
        try:
            msg.ParseFromString(payload)
        except Exception:
            return
        try:
            self._events.put_nowait(("step", msg))
        except queue.Full:
            logger.warning("[%s] raft event queue full",
                           self._support.channel_id)

    def on_submit(self, env_bytes: bytes,
                  config_seq: int = 0) -> opb.SubmitResponse:
        channel = self._support.channel_id
        if self.node.leader_id != self.node_id:
            return opb.SubmitResponse(
                channel=channel, status=common.Status.SERVICE_UNAVAILABLE,
                info="not the leader")
        try:
            env = pu.unmarshal_envelope(env_bytes)
            # classify config-ness here; carry the ORIGIN's validation
            # sequence so _process_order re-runs the msgprocessor when
            # the forwarder validated under a stale channel config
            # (reference chain.go Submit/Order last_validation_seq).
            # The default 0 is conservative: unknown origin sequence
            # means the leader always re-validates.
            payload = pu.get_payload(env)
            ch = pu.get_channel_header(payload)
            is_config = ch.type in (common.HeaderType.CONFIG,
                                    common.HeaderType.ORDERER_TRANSACTION)
            self._events.put(("order", env, config_seq, is_config))
        except Exception as e:
            return opb.SubmitResponse(channel=channel,
                                      status=common.Status.BAD_REQUEST,
                                      info=str(e))
        return opb.SubmitResponse(channel=channel,
                                  status=common.Status.SUCCESS)

    def serve_blocks(self, start: int, end: int) -> list[common.Block]:
        out = []
        for num in range(start, min(end, self._support.ledger.height)):
            b = self._support.ledger.get_block(num)
            if b is None:
                break
            out.append(b)
        return out

    # ------------------------------------------------------------------
    # main loop (reference chain.go run:599)
    # ------------------------------------------------------------------

    def _handle_event(self, ev, now: float) -> None:
        """One drained event. A failing raft step is a DROPPED message
        (raft's retransmission recovers it), never a reason to abort
        the rest of the drain's events; `raft.step` is the chaos point
        that models message loss/corruption."""
        if ev[0] == "step":
            try:
                faults.check("raft.step")
                self._peer_seen[ev[1].from_] = now
                self.node.step(ev[1])
            except Exception:
                logger.exception("[%s] raft step failed; message "
                                 "dropped", self._support.channel_id)
        elif ev[0] == "order":
            self._process_order(ev[1], ev[2], ev[3])
        elif ev[0] == "order_batch":
            for env, seq in ev[1]:
                self._process_order(env, seq, False)

    def _run(self) -> None:
        next_tick = time.monotonic() + self._tick_s
        while not self._halted.is_set():
            now = time.monotonic()
            deadline = next_tick
            if self._timer_deadline is not None:
                deadline = min(deadline, self._timer_deadline)
            try:
                ev = self._events.get(timeout=max(0.0, deadline - now))
            except queue.Empty:
                ev = ()
            if ev is None:
                break
            # drain everything already queued: one wakeup handles the
            # whole backlog, then ONE ready() pass flushes the
            # accumulated side effects (avoids per-event thread
            # handoffs when a producer is streaming submissions)
            evs = [ev] if ev else []
            while len(evs) < 4096:
                try:
                    nxt = self._events.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._halted.set()
                    break
                evs.append(nxt)
            try:
                now = time.monotonic()
                for ev in evs:
                    self._handle_event(ev, now)
                if now >= next_tick:
                    self.node.tick()
                    next_tick = now + self._tick_s
                    horizon = now - self._active_window_s
                    self.metrics.active_nodes.set(
                        1 + sum(1 for nid, ts in
                                self._peer_seen.items()
                                if ts >= horizon and
                                nid in self._consenters))
                if self._timer_deadline is not None and \
                        now >= self._timer_deadline:
                    self._timer_deadline = None
                    self._cut_and_propose(self._support.cutter.cut())
                self._drain_ready()
            except Exception:
                logger.exception("[%s] raft chain loop error",
                                 self._support.channel_id)

    def _drain_ready(self) -> None:
        ready = self.node.ready()
        if ready.soft_leader != self._last_leader:
            # count only elections of a real node: X→None (leader
            # lost) must not double-count the following None→Y
            if ready.soft_leader is not None:
                self.metrics.leader_changes.add(1)
            self._last_leader = ready.soft_leader
            self.metrics.is_leader.set(
                1 if ready.soft_leader == self.node_id else 0)
        for msg in ready.messages:
            target = self._consenters.get(msg.to)
            if target is not None:
                self._transport.send_consensus(
                    target, self._support.channel_id,
                    msg.SerializeToString())
        for entry in ready.committed_entries:
            self._apply(entry)
        if ready.soft_leader != self.node_id and self._creator:
            # deposed: in-flight blocks die with the old term
            self._creator = None
            self._timer_deadline = None

    # -- leader-side ordering --

    def _process_order(self, env: common.Envelope, config_seq: int,
                       is_config: bool) -> None:
        support = self._support
        if self.node.state != LEADER:
            # deposed between submit and processing: re-route
            try:
                self._submit(env, config_seq, is_config)
            except MsgProcessorError as e:
                logger.warning("[%s] dropped message during leader "
                               "change: %s", support.channel_id, e)
            return
        try:
            if is_config:
                if config_seq < support.sequence():
                    env, _ = support.processor.process_config_msg(env)
                self._cut_and_propose(support.cutter.cut())
                self._timer_deadline = None
                self._propose_block([env])
            else:
                if config_seq < support.sequence():
                    support.processor.process_normal_msg(env)
                batches, pending = support.cutter.ordered(env)
                for batch in batches:
                    self._cut_and_propose(batch)
                if pending:
                    if self._timer_deadline is None:
                        self._timer_deadline = (
                            time.monotonic() + support.batch_timeout_s)
                else:
                    self._timer_deadline = None
        except MsgProcessorError as e:
            logger.warning("[%s] message dropped during ordering: %s",
                           support.channel_id, e)

    def _cut_and_propose(self, batch) -> None:
        if batch:
            self._propose_block(list(batch))

    def _propose_block(self, envelopes) -> None:
        if self._creator is None:
            self._creator = self._creator_from_tail()
        block = self._creator.create(envelopes)
        ok = self.node.propose(block.SerializeToString())
        if not ok:
            logger.warning("[%s] proposal dropped (not leader)",
                           self._support.channel_id)
            self.metrics.proposal_failures.add(1)
            self._creator = None

    def _creator_from_tail(self) -> _BlockCreator:
        """New leader: continue after the last block in the raft log
        (it will commit under this term), else after the ledger tip."""
        for e in reversed(self._storage.entries(
                self._storage.first_index(),
                self.node.last_index() + 1)):
            if e.type != rpb.Entry.NORMAL or not e.data:
                continue
            try:
                block = common.Block()
                block.ParseFromString(e.data)
            except Exception:
                continue
            return _BlockCreator(block.header.number + 1,
                                 pu.block_header_hash(block.header))
        tip = self._support.ledger.get_block(
            self._support.ledger.height - 1)
        return _BlockCreator(tip.header.number + 1,
                             pu.block_header_hash(tip.header))

    # -- apply (every consenter) --

    def _apply(self, entry: rpb.Entry) -> None:
        if entry.type == rpb.Entry.CONF_CHANGE:
            self._after_conf_change()
            return
        if not entry.data:
            return
        block = common.Block()
        try:
            block.ParseFromString(entry.data)
        except Exception:
            logger.warning("[%s] undecodable raft entry %d",
                           self._support.channel_id, entry.index)
            return
        height = self._support.ledger.height
        if block.header.number < height:
            return  # duplicate (replay)
        if block.header.number > height:
            self._catch_up(height, block.header.number)
            if self._support.ledger.height != block.header.number:
                logger.error("[%s] catch-up to %d failed (at %d)",
                             self._support.channel_id,
                             block.header.number,
                             self._support.ledger.height)
                return
        self._write_committed_block(block)
        self._applied_since_compact += 1
        if self._applied_since_compact >= COMPACT_EVERY:
            self._applied_since_compact = 0
            self.node.compact(self.node.applied_index,
                              self._support.ledger.height)
            self.metrics.snapshot_block_number.set(
                self._support.ledger.height - 1)

    def _write_committed_block(self, block: common.Block) -> None:
        self.metrics.committed_block_number.set(block.header.number)
        support = self._support
        if pu.is_config_block(block):
            support.write_config_block(block)
            self._reconfigure()
        else:
            support.write_block(block)

    def _reconfigure(self) -> None:
        """A config block committed: adopt the (possibly) new consenter
        set; the leader drives the raft membership change."""
        meta = self._support.bundle().orderer.consensus_metadata
        new = parse_consenters(meta)
        if not new:
            return
        # refresh the caller-auth table even when the endpoint set is
        # unchanged: a config update may rotate a consenter's client
        # TLS cert in place
        self._transport.set_channel_auth(self._support.channel_id,
                                         parse_consenter_certs(meta))
        if new == self._consenters:
            return
        logger.info("[%s] consenter set change: %s -> %s",
                    self._support.channel_id,
                    sorted(self._consenters.values()),
                    sorted(new.values()))
        self._consenters = new
        self.metrics.cluster_size.set(len(new))
        if self.node.state == LEADER:
            self.node.propose_conf_change(list(new))

    def _after_conf_change(self) -> None:
        if self.node_id not in self.node.peers:
            logger.warning("[%s] this consenter was evicted; halting "
                           "chain (deliver keeps serving)",
                           self._support.channel_id)
            threading.Thread(target=self.halt, daemon=True).start()

    # -- snapshot catch-up (reference blockpuller.go) --

    def _catch_up(self, start: int, end: int) -> None:
        """A raft snapshot points past our ledger: pull the gap through
        the onboarding replicator — verified blocks, source failover,
        full-jitter backoff — instead of a single fixed source
        (reference blockpuller.go over cluster/replication.go)."""
        from fabric_tpu.orderer import onboarding as onb
        if self._replicator is None:
            self._replicator = onb.ChainReplicator(
                self._support.channel_id, self._transport,
                consenters_fn=lambda: [
                    ep for _nid, ep in sorted(self._consenters.items())],
                sink=onb.SupportSink(self._support),
                metrics_provider=self._metrics_provider)
        try:
            # bounded: this runs on the raft event-loop thread, and an
            # unfinished catch-up is retried when the next committed
            # entry arrives
            self._replicator.run(target_height=end, stop=self._halted,
                                 max_wall_s=15.0)
        except onb.OnboardingError as e:
            logger.warning("[%s] snapshot catch-up incomplete: %s",
                           self._support.channel_id, e)


def consenter(transport, tick_interval_s: float = 0.1,
              election_tick: int = 10, metrics_provider=None):
    """Factory-of-factories for the registrar's consenter map:
    `{"etcdraft": raft.consenter(transport)}`. An orderer outside the
    channel's consenter set comes up as a FOLLOWER (onboarding mode)
    instead — the reference registrar's SwitchFollowerToChain seam."""
    def factory(support):
        consenters = parse_consenters(
            support.bundle().orderer.consensus_metadata)
        if endpoint_id(transport.endpoint) not in consenters:
            from fabric_tpu.orderer.raft.follower import FollowerChain
            logger.info("[%s] %s not in consenter set: starting as "
                        "follower", support.channel_id,
                        transport.endpoint)
            return FollowerChain(
                support, transport,
                on_became_consenter=getattr(
                    support, "on_became_consenter", None),
                metrics_provider=metrics_provider)
        return RaftChain(support, transport,
                         tick_interval_s=tick_interval_s,
                         election_tick=election_tick,
                         metrics_provider=metrics_provider)
    return factory
