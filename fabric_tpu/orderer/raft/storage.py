"""Raft persistence: hard state, log entries, snapshot metadata.

The role of etcd WAL + snapshot files in the reference
(`orderer/consensus/etcdraft/storage.go`): everything raft must not
forget across a crash — (term, voted_for, commit), the entry log, and
the latest compaction point — lands in the channel's embedded ordered
KV store (crash-safe WAL-mode SQLite, same engine as the ledger
indexes) before the state machine acts on it.
"""

from __future__ import annotations

import struct
from typing import Optional

from fabric_tpu.common import faults
from fabric_tpu.ledger.kvdb import DBHandle
from fabric_tpu.protos import raft as rpb

_HARD = b"h"          # term, voted_for, commit
_ENTRY = b"e"         # e + pack(index) -> Entry
_SNAP = b"s"          # SnapshotMeta


def _ek(index: int) -> bytes:
    return _ENTRY + struct.pack(">Q", index)


class RaftStorage:
    def __init__(self, db: DBHandle):
        self._db = db
        self._last: Optional[int] = None
        self._first: Optional[int] = None

    # -- hard state --

    def hard_state(self) -> tuple[int, int, int]:
        raw = self._db.get(_HARD)
        if raw is None:
            return 0, 0, 0
        return struct.unpack(">QQQ", raw)

    def set_hard_state(self, term: int, voted_for: int,
                       commit: int) -> None:
        self._db.put(_HARD, struct.pack(">QQQ", term, voted_for,
                                        commit))

    # -- log --

    def first_index(self) -> int:
        """Index of the first entry still in the log (after the
        snapshot point); snapshot.last_index + 1."""
        if self._first is None:
            meta = self.snapshot_meta()
            self._first = meta.last_index + 1
        return self._first

    def last_index(self) -> int:
        if self._last is None:
            self._last = self.snapshot_meta().last_index
            for k, _v in self._db.iterate(start=_ENTRY,
                                          end=_ENTRY + b"\xff"):
                idx = struct.unpack(">Q", k[1:])[0]
                if idx > self._last:
                    self._last = idx
        return self._last

    def term_of(self, index: int) -> int:
        if index == 0:
            return 0
        meta = self.snapshot_meta()
        if index == meta.last_index:
            return meta.last_term
        raw = self._db.get(_ek(index))
        if raw is None:
            return 0
        e = rpb.Entry()
        e.ParseFromString(raw)
        return e.term

    def entries(self, lo: int, hi: int) -> list[rpb.Entry]:
        """[lo, hi) — silently clipped to what exists."""
        out = []
        for _k, v in self._db.iterate(start=_ek(lo), end=_ek(hi)):
            e = rpb.Entry()
            e.ParseFromString(v)
            out.append(e)
        return out

    def append(self, entries: list[rpb.Entry]) -> None:
        # the WAL-append seam of the crash-point recovery matrix:
        # crash mode dies HERE — before the atomic batch write — so a
        # restart must reconstruct from what the previous appends made
        # durable; error mode models a failing disk (the chain drops
        # the step / demotes the propose)
        faults.check("raft.wal_append")
        batch = self._db.new_batch()
        for e in entries:
            batch.put(_ek(e.index),
                      e.SerializeToString(deterministic=True))
        self._db.write_batch(batch)
        if entries:
            self._last = max(self._last or 0, entries[-1].index)

    def truncate_from(self, index: int) -> None:
        """Drop entries >= index (conflict resolution)."""
        batch = self._db.new_batch()
        for k, _v in self._db.iterate(start=_ek(index),
                                      end=_ENTRY + b"\xff"):
            batch.delete(k)
        if batch.ops:
            self._db.write_batch(batch)
        self._last = None

    # -- snapshot / compaction --

    def snapshot_meta(self) -> rpb.SnapshotMeta:
        raw = self._db.get(_SNAP)
        meta = rpb.SnapshotMeta()
        if raw is not None:
            meta.ParseFromString(raw)
        return meta

    def compact(self, upto_index: int, block_height: int,
                conf: rpb.ConfState) -> None:
        """Make `upto_index` the new snapshot point and drop the prefix."""
        if upto_index < self.first_index():
            return
        term = self.term_of(upto_index)
        meta = rpb.SnapshotMeta(last_index=upto_index, last_term=term,
                                block_height=block_height)
        meta.conf.CopyFrom(conf)
        batch = self._db.new_batch()
        batch.put(_SNAP, meta.SerializeToString(deterministic=True))
        for k, _v in self._db.iterate(start=_ENTRY,
                                      end=_ek(upto_index + 1)):
            batch.delete(k)
        self._db.write_batch(batch)
        self._first = upto_index + 1

    def install_snapshot(self, meta: rpb.SnapshotMeta) -> None:
        """Follower side: adopt a leader snapshot position wholesale;
        the entire local log is superseded."""
        batch = self._db.new_batch()
        batch.put(_SNAP, meta.SerializeToString(deterministic=True))
        for k, _v in self._db.iterate(start=_ENTRY,
                                      end=_ENTRY + b"\xff"):
            batch.delete(k)
        self._db.write_batch(batch)
        self._first = meta.last_index + 1
        self._last = meta.last_index
