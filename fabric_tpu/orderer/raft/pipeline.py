"""Pipelined block writing for the raft ordering path.

The consenter's apply path was strictly sequential per block: the raft
event loop signed the block, appended it to the block store, notified
deliver waiters, and only then touched the next event — so consensus
on block N and block-cutting of batch N+1 idled behind the
sign+store-append of block N−1. `BlockWriteStage` is the ordering-side
analog of the peer's `CommitPipeline` (core/commitpipeline.py, round
7): committed NORMAL blocks are handed to a dedicated write worker,
and the raft loop goes straight back to draining its admission window.

  stage CUT        (raft loop)   admission window → blockcutter →
                                 batched raft proposal
  stage CONSENSUS  (raft loop)   replication / commit, event-driven
  stage WRITE      (this worker) sign + metadata + block-store append
                                 + deliver notification, in block order

Correctness barriers are explicit and live in the CHAIN (chain.py):

  * config blocks and raft membership changes drain this stage before
    they are applied — reconfiguration must observe the durable tip;
  * log compaction drains first — a compacted entry whose block was
    never written would be unrecoverable after a crash;
  * snapshot catch-up drains first — the replicator appends directly.

No early side effects: a block enters this stage only AFTER its entry
committed in raft, and the entry stays in the raft log until it is
durably written (the chain defers compaction past it) — a crash
between propose(N+1) and write(N) replays bit-identically through
`RaftChain._replay_committed`, exactly like a crash on the sequential
path. Any write failure is sticky: the chain demotes to the
sequential write path and heals the gap through the same replay.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from fabric_tpu.common import adaptive, faults, overload, tracing

logger = logging.getLogger("orderer.raft.pipeline")

# default bound on committed-but-unwritten blocks the stage may hold;
# a writer that cannot keep even this much headroom is stalled, and
# the chain's demotion machinery (sequential writes + WAL replay) is
# the correct response — not unbounded memory growth
MAX_PENDING = 128


class OrderWriteError(Exception):
    """A pipelined block write failed; `number` is the first block of
    the failing span. Recovery is the sequential path's: demote and
    replay committed-but-unwritten entries from the raft log."""

    def __init__(self, number: int, cause: BaseException):
        super().__init__(f"pipelined write of block [{number}] failed: "
                         f"{cause}")
        self.number = number
        self.cause = cause


class BlockWriteStage:
    """Ordered, asynchronous sign+write worker for one channel.

    `support` duck-type: `write_block(block)` and (optionally)
    `write_blocks(blocks)` — the batched span writer that signs the
    whole span and self-checks the produced signatures through the
    BCCSP seam in one device dispatch. `loop_activity()` (optional)
    returns `(busy_since_or_None, (t0, t1) last busy window)` of the
    raft event loop, for the overlap accounting. The `stats` readings
    surface as the canonical `orderer_batch_{write_s,overlap_ratio}`
    gauges through `profiling.publish_order_stats`."""

    def __init__(self, support,
                 loop_activity: Optional[Callable] = None,
                 max_pending: int = MAX_PENDING,
                 node_id: Optional[str] = None):
        self._support = support
        self._node_id = node_id      # trace-track attribution
        self._cond = threading.Condition()
        self._pending: list = []
        self._max_pending = max_pending
        self._submitted_tip: Optional[int] = None
        self._written_tip: Optional[int] = None
        self._error: Optional[OrderWriteError] = None
        self._stop = threading.Event()
        self._loop_activity = loop_activity
        self.stats = {
            "written": 0, "spans": 0, "sheds": 0,
            "write_s": 0.0, "overlap_s": 0.0, "last_write_s": 0.0,
        }
        self._last_shed_t: Optional[float] = None
        self._shed_rate = overload.ShedRateWindow()
        overload.register_stage(
            f"order.write.{support.channel_id}", self)
        # round 19: the pending-span bound is an adaptive knob —
        # tightening it propagates writer backpressure to the
        # admission edge sooner (shallower queues, shorter commit
        # tail); the ceiling is the configured bound.
        knob_scope = f"{support.channel_id}.{node_id}" if node_id \
            else support.channel_id
        adaptive.register_attr_knob(
            self, "_max_pending",
            f"order.write.{knob_scope}.max_pending",
            floor=max(1, max_pending // 32), ceiling=max_pending)
        self._thread = threading.Thread(
            target=self._write_loop,
            name=f"order-write-{support.channel_id}", daemon=True)
        self._thread.start()

    def overload_stats(self) -> dict:
        """Overload-registry protocol: pending committed blocks are
        the stage's queue depth; a submit that timed out (and demoted
        the chain) is its shed."""
        with self._cond:
            return {
                "depth": len(self._pending),
                "capacity": self._max_pending,
                "sheds": self.stats["sheds"],
                "puts": self.stats["written"] + len(self._pending),
                "last_shed_t": self._last_shed_t,
                "shed_rate": self._shed_rate.rate(),
            }

    # -- raft-loop API --

    def submit(self, block) -> None:
        """Enqueue the next committed block (in block order). Raises
        the sticky error if an earlier span failed — the caller then
        demotes to the sequential path.

        Bounded (round 12): with `max_pending` blocks already held,
        the raft loop waits for the writer — honest backpressure that
        propagates to the admission edge (the event queue fills, the
        broadcast clients see SERVICE_UNAVAILABLE) — but only up to
        the deadline budget. A writer stalled past that is a failed
        stage: OrderWriteError, and the chain demotes + replays from
        the WAL. A committed block is NEVER dropped here — shedding
        happens at admission, not after consensus."""
        budget = overload.Deadline.remaining_or(
            overload.default_enqueue_budget_s())
        deadline = time.monotonic() + max(0.0, budget)
        with self._cond:
            if self._error is not None:
                raise self._error
            while len(self._pending) >= self._max_pending and \
                    self._error is None and not self._stop.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.stats["sheds"] += 1
                    self._last_shed_t = time.monotonic()
                    self._shed_rate.note()
                    tracing.note_shed(
                        f"order.write.{self._support.channel_id}")
                    raise OrderWriteError(
                        block.header.number,
                        overload.OverloadError(
                            f"order.write.{self._support.channel_id}",
                            f"write stage full at "
                            f"{self._max_pending} blocks past the "
                            f"deadline budget"))
                self._cond.wait(timeout=remaining)
            if self._error is not None:
                raise self._error
            # the ambient context (the proposing window's, re-attached
            # by the raft loop at _apply) rides with the block so the
            # async write span keeps the transaction's trace_id
            self._pending.append((block, tracing.capture()))
            self._submitted_tip = block.header.number
            self._cond.notify_all()

    def effective_tip(self, ledger_height: int) -> int:
        """The chain's working height: the ledger tip plus every block
        already accepted by this stage (the raft loop must treat an
        in-flight block as written — a re-applied entry for it is a
        duplicate, not a gap)."""
        with self._cond:
            if self._submitted_tip is not None:
                return max(ledger_height, self._submitted_tip + 1)
            return ledger_height

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until every submitted block is durably written; the
        chain's barrier before config blocks, membership changes, log
        compaction and catch-up. Returns False on timeout (the caller
        skips the optional work or demotes); raises the sticky error
        if a span failed."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cond:
            while (self._pending or
                   (self._submitted_tip is not None and
                    self._written_tip != self._submitted_tip)) and \
                    self._error is None and not self._stop.is_set():
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=0.2 if remaining is None
                                else min(0.2, remaining))
            if self._error is not None:
                raise self._error
            return True

    def check_error(self) -> None:
        """Non-blocking sticky-error probe (the raft loop polls this
        once per tick so a failed span demotes promptly, not at the
        next config barrier)."""
        with self._cond:
            if self._error is not None:
                raise self._error

    def stop(self, flush: bool = True, timeout: float = 5.0) -> None:
        """Flush (best effort) and join the worker. `flush=False` is
        crash-equivalent: unwritten blocks stay in the raft log and
        replay at the next start."""
        if flush:
            try:
                if not self.drain(timeout=timeout):
                    logger.warning(
                        "[%s] halt: write-stage drain timed out with "
                        "blocks still unwritten — they stay in the "
                        "raft log and replay at the next start",
                        self._support.channel_id)
            except OrderWriteError:
                pass
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            logger.warning(
                "[%s] halt: write worker still mid-span after %.1fs; "
                "its unwritten blocks replay at the next start",
                self._support.channel_id, timeout)

    def alive(self) -> bool:
        """Whether the worker thread is still running (after a
        `stop(flush=False)` whose join timed out, the chain must not
        replay sequentially until this goes False)."""
        return self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    @property
    def overlap_ratio(self) -> float:
        return (self.stats["overlap_s"] / self.stats["write_s"]
                if self.stats["write_s"] else 0.0)

    # -- the worker --

    def _write_loop(self) -> None:
        # the async writer records order.write spans on its own
        # thread: bind them to the owning consenter's trace track
        tracing.set_node(self._node_id)
        while not self._stop.is_set():
            with self._cond:
                while (not self._pending or self._error is not None) \
                        and not self._stop.is_set():
                    self._cond.wait(timeout=0.2)
                if self._stop.is_set():
                    return
                # take everything queued: the whole run becomes ONE
                # batched sign+verify span through the BCCSP seam
                pending, self._pending = self._pending, []
                self._cond.notify_all()   # wake a backpressured submit
            run = [b for b, _ctx in pending]
            rctx = next((c for _b, c in pending if c is not None),
                        None)
            t0 = time.perf_counter()
            try:
                # the block-write seam of the crash-point recovery
                # matrix: crash mode kills the consenter between raft
                # commit and the durable block append (the committed
                # entries replay from the WAL at restart); error mode
                # is a sticky stage failure -> the chain demotes
                faults.check("order.block_write")
                with tracing.span("order.write", parent=rctx,
                                  blocks=len(run),
                                  first=run[0].header.number,
                                  last=run[-1].header.number):
                    write_blocks = getattr(self._support,
                                           "write_blocks", None)
                    if write_blocks is not None and len(run) > 1:
                        write_blocks(run)
                    else:
                        for block in run:
                            self._support.write_block(block)
            except Exception as e:   # noqa: BLE001 — sticky, chain demotes
                logger.exception(
                    "[%s] pipelined write of blocks [%d..%d] failed; "
                    "the chain will demote to sequential writes and "
                    "replay from the raft log",
                    self._support.channel_id, run[0].header.number,
                    run[-1].header.number)
                with self._cond:
                    if self._error is None:
                        self._error = OrderWriteError(
                            run[0].header.number, e)
                    self._cond.notify_all()
                continue
            t1 = time.perf_counter()
            with self._cond:
                self._written_tip = run[-1].header.number
                self._cond.notify_all()
            self.stats["written"] += len(run)
            self.stats["spans"] += 1
            self.stats["write_s"] += t1 - t0
            self.stats["last_write_s"] = t1 - t0
            self.stats["overlap_s"] += self._overlap(t0, t1)

    def _overlap(self, t0: float, t1: float) -> float:
        """How much of the write window [t0, t1] ran while the raft
        loop was busy (cutting the next window / stepping consensus) —
        the time this stage actually hid."""
        if self._loop_activity is None:
            return 0.0
        try:
            active_since, window = self._loop_activity()
        except Exception:   # noqa: BLE001 — accounting must never kill writes
            return 0.0
        overlap = 0.0
        if active_since is not None:
            overlap += max(0.0, t1 - max(t0, active_since))
        ws, we = window
        if we > ws:
            overlap += max(0.0, min(t1, we) - max(t0, ws))
        return min(overlap, t1 - t0)
