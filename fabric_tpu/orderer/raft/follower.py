"""Follower chain: serve a channel without being a consenter.

Rebuild of `orderer/common/follower/follower_chain.go` + the onboarding
flow (`orderer/common/onboarding/onboarding.go`): an orderer that joins
a channel whose consenter set does not include it pulls blocks from the
consenters through the onboarding replicator — every block verified
(`cluster/util.go VerifyBlocks` semantics via the batched BCCSP seam),
sources failed over with full-jitter backoff when one dies mid-stream —
keeps its ledger current for Deliver clients, and, when a committed
config block adds it to the consenter set, triggers promotion: the
registrar swaps this follower for a consenter chain over the same
support (reference registrar.SwitchFollowerToChain).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from fabric_tpu.orderer.msgprocessor import MsgProcessorError
from fabric_tpu.orderer.onboarding import (
    ChainReplicator,
    SupportSink,
    consenter_endpoints,
)
from fabric_tpu.orderer.raft.chain import parse_consenters

logger = logging.getLogger("orderer.follower")


class FollowerChain:
    def __init__(self, support, transport,
                 poll_interval_s: float = 0.3,
                 on_became_consenter: Optional[Callable] = None,
                 metrics_provider=None):
        self._support = support
        self._transport = transport
        self._interval = poll_interval_s
        self._on_promote = on_became_consenter
        self._halted = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._replicator = ChainReplicator(
            support.channel_id, transport,
            consenters_fn=lambda: consenter_endpoints(support.bundle()),
            sink=SupportSink(support),
            metrics_provider=metrics_provider)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run,
            name=f"follower-{self._support.channel_id}", daemon=True)
        self._thread.start()

    def halt(self) -> None:
        self._halted.set()
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=5)

    def errored(self) -> bool:
        return self._halted.is_set()

    def order(self, env, config_seq) -> None:
        raise MsgProcessorError(
            f"[{self._support.channel_id}] this orderer is a follower; "
            "submit to a consenter")

    configure = order

    # -- the pull loop --

    def _run(self) -> None:
        while not self._halted.wait(self._interval):
            try:
                self._replicator.poll_once()
                if self._am_consenter():
                    logger.info("[%s] %s is now in the consenter set; "
                                "halting follower for promotion",
                                self._support.channel_id,
                                self._transport.endpoint)
                    self._halted.set()
                    if self._on_promote is not None:
                        self._on_promote()
                    return
            except Exception:
                logger.exception("[%s] follower pull failed",
                                 self._support.channel_id)

    def _consenters(self) -> dict[int, str]:
        return parse_consenters(
            self._support.bundle().orderer.consensus_metadata)

    def _am_consenter(self) -> bool:
        return self._transport.endpoint in \
            self._consenters().values()


def follower_factory(transport, on_became_consenter=None):
    def factory(support) -> FollowerChain:
        return FollowerChain(support, transport,
                             on_became_consenter=on_became_consenter)
    return factory
