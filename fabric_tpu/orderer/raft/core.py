"""Raft consensus state machine — pure, deterministic, IO-free.

The role of etcd/raft in the reference (`orderer/consensus/etcdraft`
vendors go.etcd.io/etcd/raft): leader election with randomized
timeouts + pre-vote, log replication with consistency checks and fast
conflict backtracking, majority commit (current-term rule), and
configuration changes. Mirrors etcd's architecture: the node is driven
by `tick()` / `step(msg)` / `propose(data)` and emits side effects only
through `ready()` — (messages to send, entries to persist, entries to
apply) — which the chain layer (`chain.py`) executes. Determinism makes
the protocol unit-testable without threads or clocks
(`tests/test_raft.py` drives whole clusters synchronously).
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass, field
from typing import Optional

from fabric_tpu.common.backoff import FullJitterBackoff
from fabric_tpu.protos import raft as rpb

logger = logging.getLogger("orderer.raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


@dataclass
class Ready:
    messages: list = field(default_factory=list)       # RaftMessage out
    entries_to_persist: list = field(default_factory=list)  # new log tail
    committed_entries: list = field(default_factory=list)   # apply these
    hard_state_changed: bool = False
    soft_leader: Optional[int] = None


# ftpu-check: allow-lockset(raft actor: every method runs on the owning
# RaftChain._run loop; cross-thread input arrives via the event queue)
class RaftNode:
    """One consenter's raft state. `storage` provides the persisted
    log + hard state (term, voted_for) — see storage.py."""

    def __init__(self, node_id: int, peers: list[int], storage,
                 election_tick: int = 10, heartbeat_tick: int = 1,
                 pre_vote: bool = True):
        self.id = node_id
        self.peers = sorted(set(peers) | {node_id})
        self._storage = storage
        self.election_tick = election_tick
        self.heartbeat_tick = heartbeat_tick
        self.pre_vote = pre_vote

        hs = storage.hard_state()
        self.term: int = hs[0]
        self.voted_for: int = hs[1]
        self.commit_index: int = hs[2]
        self.applied_index: int = self.commit_index

        self.state = FOLLOWER
        self.leader_id: int = 0
        self._elapsed = 0
        # Deterministic per-node election jitter, RE-DRAWN per round
        # (round 15): the old fixed node-id spread made colliding
        # timeouts collide FOREVER — two candidates under a lossy
        # link could split every election. A node-id-seeded PRNG
        # keeps the core deterministic (same node, same sequence)
        # while consecutive failed campaigns draw from a widening,
        # bounded window under the common/backoff.py full-jitter
        # discipline — the bounded re-election guarantee: the worst
        # timeout is election_tick + the backoff cap (3x), and any
        # sign of a live leader resets the spread.
        self._rng = random.Random(0x9E3779B9 ^ (node_id & 0xFFFFFFFF))
        self._elect_backoff = FullJitterBackoff(
            base_s=2.0, max_s=float(3 * election_tick),
            draw=self._rng.uniform)
        self._timeout = election_tick + int(
            self._rng.uniform(0, election_tick))
        self._votes: dict[int, bool] = {}
        self._pre_votes: dict[int, bool] = {}

        # leader volatile state
        self.next_index: dict[int, int] = {}
        self.match_index: dict[int, int] = {}

        self._ready = Ready()
        self._apply_upto(self.commit_index, replay=True)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def ready(self) -> Ready:
        """Drain pending side effects (etcd Ready pattern)."""
        r, self._ready = self._ready, Ready()
        r.soft_leader = self.leader_id if self.leader_id else None
        return r

    def tick(self) -> None:
        self._elapsed += 1
        if self.state == LEADER:
            if self._elapsed >= self.heartbeat_tick:
                self._elapsed = 0
                self._broadcast_append(heartbeat_only=False)
        elif self._elapsed >= self._timeout:
            self._elapsed = 0
            self._campaign()

    def propose(self, data: bytes,
                etype: int = rpb.Entry.NORMAL) -> bool:
        return self.propose_batch([data], etype=etype) == 1

    def propose_batch(self, datas,
                      etype: int = rpb.Entry.NORMAL) -> int:
        """Append a RUN of proposals as one log operation: all entries
        share ONE storage.append (one WAL write through _TimedStorage)
        and ONE replication fan-out, instead of a per-proposal
        append+broadcast (the ordering floor under load — each block of
        a busy admission window used to pay its own fsync and its own
        APPEND round). Returns how many entries were accepted: 0 when
        not leader, else all of them (the append is atomic)."""
        if self.state != LEADER or not datas:
            return 0
        index = self.last_index()
        entries = [rpb.Entry(index=index + i + 1, term=self.term,
                             type=etype, data=data)
                   for i, data in enumerate(datas)]
        self._storage.append(entries)
        self._ready.entries_to_persist.extend(entries)
        self.match_index[self.id] = entries[-1].index
        if len(self.peers) == 1:
            self._maybe_commit()
        else:
            self._broadcast_append()
        return len(entries)

    def propose_conf_change(self, voters: list[int]) -> bool:
        cs = rpb.ConfState(voters=sorted(voters))
        return self.propose(cs.SerializeToString(),
                            etype=rpb.Entry.CONF_CHANGE)

    def step(self, msg: rpb.RaftMessage) -> None:
        if msg.term > self.term:
            if msg.type == rpb.RaftMessage.PRE_VOTE_RESP and msg.reject:
                # a peer at a higher term refused us: adopt the term so
                # the next campaign can actually win (etcd behavior)
                self._become_follower(msg.term, 0)
                return
            if msg.type not in (rpb.RaftMessage.PRE_VOTE,
                                rpb.RaftMessage.PRE_VOTE_RESP):
                leader = msg.from_ if msg.type in (
                    rpb.RaftMessage.APPEND,
                    rpb.RaftMessage.HEARTBEAT,
                    rpb.RaftMessage.SNAPSHOT) else 0
                self._become_follower(msg.term, leader)
        elif msg.term < self.term:
            if msg.type in (rpb.RaftMessage.VOTE,
                            rpb.RaftMessage.PRE_VOTE):
                self._send(msg.from_, self._vote_resp(
                    msg.type, granted=False))
            return

        t = msg.type
        if t == rpb.RaftMessage.PRE_VOTE:
            self._handle_pre_vote(msg)
        elif t == rpb.RaftMessage.PRE_VOTE_RESP:
            self._handle_pre_vote_resp(msg)
        elif t == rpb.RaftMessage.VOTE:
            self._handle_vote(msg)
        elif t == rpb.RaftMessage.VOTE_RESP:
            self._handle_vote_resp(msg)
        elif t in (rpb.RaftMessage.APPEND, rpb.RaftMessage.HEARTBEAT):
            self._handle_append(msg)
        elif t == rpb.RaftMessage.APPEND_RESP:
            self._handle_append_resp(msg)
        elif t == rpb.RaftMessage.SNAPSHOT:
            self._handle_snapshot(msg)

    def advance_applied(self, index: int) -> None:
        self.applied_index = max(self.applied_index, index)

    # ------------------------------------------------------------------
    # log helpers
    # ------------------------------------------------------------------

    def last_index(self) -> int:
        return self._storage.last_index()

    def last_term(self) -> int:
        return self._storage.term_of(self.last_index())

    def _log_up_to_date(self, idx: int, term: int) -> bool:
        lt, li = self.last_term(), self.last_index()
        return (term, idx) >= (lt, li)

    # ------------------------------------------------------------------
    # elections
    # ------------------------------------------------------------------

    def _campaign(self) -> None:
        if self.id not in self.peers:
            return  # removed from the cluster
        # re-draw the next election timeout with full jitter over a
        # widening (bounded) window: repeated split/failed campaigns
        # de-synchronize instead of colliding again
        self._timeout = self.election_tick + 1 + int(
            self._elect_backoff.next())
        if len(self.peers) == 1:
            self._become_leader(self.term + 1)
            return
        if self.pre_vote:
            self.state = CANDIDATE
            self._pre_votes = {self.id: True}
            for p in self._others():
                m = self._base(p, rpb.RaftMessage.PRE_VOTE)
                m.term = self.term + 1
                m.last_log_index = self.last_index()
                m.last_log_term = self.last_term()
                self._send(p, m)
        else:
            self._start_real_election()

    def _start_real_election(self) -> None:
        self.state = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self.leader_id = 0
        self._persist_hard_state()
        self._votes = {self.id: True}
        if self._quorum(self._votes):
            self._become_leader(self.term)
            return
        for p in self._others():
            m = self._base(p, rpb.RaftMessage.VOTE)
            m.last_log_index = self.last_index()
            m.last_log_term = self.last_term()
            self._send(p, m)

    def _handle_pre_vote(self, msg: rpb.RaftMessage) -> None:
        # grant iff we'd vote in that term: no live leader heard
        # recently AND candidate log is current
        granted = (msg.term > self.term and
                   self._log_up_to_date(msg.last_log_index,
                                        msg.last_log_term) and
                   (self.leader_id == 0 or
                    self._elapsed >= self.election_tick))
        resp = self._vote_resp(rpb.RaftMessage.PRE_VOTE, granted)
        resp.term = msg.term
        self._send(msg.from_, resp)

    def _handle_pre_vote_resp(self, msg: rpb.RaftMessage) -> None:
        if self.state != CANDIDATE:
            return
        self._pre_votes[msg.from_] = not msg.reject
        if self._quorum({k: v for k, v in self._pre_votes.items()
                         if v}):
            self._start_real_election()

    def _handle_vote(self, msg: rpb.RaftMessage) -> None:
        can_vote = (self.voted_for in (0, msg.from_) and
                    self.leader_id == 0)
        granted = can_vote and self._log_up_to_date(
            msg.last_log_index, msg.last_log_term)
        if granted:
            self.voted_for = msg.from_
            self._elapsed = 0
            self._persist_hard_state()
        self._send(msg.from_,
                   self._vote_resp(rpb.RaftMessage.VOTE, granted))

    def _handle_vote_resp(self, msg: rpb.RaftMessage) -> None:
        if self.state != CANDIDATE:
            return
        if not msg.reject:
            self._votes[msg.from_] = True
        if self._quorum(self._votes):
            self._become_leader(self.term)

    def _vote_resp(self, req_type: int, granted: bool
                   ) -> rpb.RaftMessage:
        resp_type = rpb.RaftMessage.VOTE_RESP \
            if req_type == rpb.RaftMessage.VOTE \
            else rpb.RaftMessage.PRE_VOTE_RESP
        m = rpb.RaftMessage(type=resp_type, from_=self.id,
                            term=self.term)
        m.reject = not granted
        return m

    # ------------------------------------------------------------------
    # role transitions
    # ------------------------------------------------------------------

    def _become_follower(self, term: int, leader: int) -> None:
        changed = term != self.term
        self.state = FOLLOWER
        self.term = term
        if changed:
            self.voted_for = 0
        self.leader_id = leader
        self._elapsed = 0
        if leader:
            # progress: a live leader exists — the next outage starts
            # from the base election window, not this one's ceiling
            self._reset_election_jitter()
        if changed:
            self._persist_hard_state()

    def _reset_election_jitter(self) -> None:
        if self._elect_backoff.failures:
            self._elect_backoff.reset()
            self._timeout = self.election_tick + int(
                self._rng.uniform(0, self.election_tick))

    def _become_leader(self, term: int) -> None:
        self.state = LEADER
        self.term = term
        self.leader_id = self.id
        self._elapsed = 0
        self._reset_election_jitter()
        last = self.last_index()
        self.next_index = {p: last + 1 for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        logger.info("raft node %d became leader at term %d", self.id,
                    term)
        if last > self.commit_index:
            # an uncommitted predecessor tail: commit it NOW by
            # appending an empty own-term entry (etcd appends one at
            # every term start; doing it only when a tail exists
            # keeps quiet elections index-stable). Raft forbids
            # counting replicas of old-term entries toward commit, so
            # without this the tail — blocks accepted by the dead
            # leader — would sit unwritten until the next client
            # proposal happens to arrive.
            e = rpb.Entry(index=last + 1, term=self.term,
                          type=rpb.Entry.NORMAL, data=b"")
            self._storage.append([e])
            self._ready.entries_to_persist.append(e)
        self.match_index[self.id] = self.last_index()
        self._broadcast_append()
        if len(self.peers) == 1:
            self._maybe_commit()

    # ------------------------------------------------------------------
    # replication
    # ------------------------------------------------------------------

    def _broadcast_append(self, heartbeat_only: bool = False) -> None:
        for p in self._others():
            self._send_append(p)

    def _send_append(self, peer: int) -> None:
        nxt = self.next_index.get(peer, self.last_index() + 1)
        first = self._storage.first_index()
        if nxt < first:
            # follower is behind our compacted log → snapshot
            meta = self._storage.snapshot_meta()
            m = self._base(peer, rpb.RaftMessage.SNAPSHOT)
            m.snapshot.CopyFrom(meta)
            self._send(peer, m)
            return
        prev = nxt - 1
        m = self._base(peer, rpb.RaftMessage.APPEND)
        m.prev_log_index = prev
        m.prev_log_term = self._storage.term_of(prev)
        m.commit = self.commit_index
        for e in self._storage.entries(nxt, nxt + 64):
            m.entries.add().CopyFrom(e)
        self._send(peer, m)

    def _handle_append(self, msg: rpb.RaftMessage) -> None:
        self._elapsed = 0
        if self.state != FOLLOWER:
            self._become_follower(msg.term, msg.from_)
        self.leader_id = msg.from_
        self._reset_election_jitter()

        resp = self._base(msg.from_, rpb.RaftMessage.APPEND_RESP)
        prev = msg.prev_log_index
        if prev < self.commit_index:
            # A STALE append — delayed, duplicated or reordered by the
            # network — entirely below our commit point. Committed
            # entries are immutable and known to match the leader's
            # log, so ack the commit index and touch NOTHING (etcd
            # MsgApp handling). Without this guard the conflict scan
            # below would see term_of()==0 for compacted indexes and
            # truncate_from() a compacted index — deleting the whole
            # LIVE log on a message that carries no new information.
            resp.last_log_index = self.commit_index
            self._send(msg.from_, resp)
            return
        if prev > self.last_index() or \
                (prev >= self._storage.first_index() - 1 and
                 self._storage.term_of(prev) != msg.prev_log_term):
            resp.reject = True
            resp.reject_hint = min(self.last_index(), prev)
            self._send(msg.from_, resp)
            return
        new_entries = []
        for e in msg.entries:
            if e.index <= self.last_index():
                if self._storage.term_of(e.index) == e.term:
                    continue  # already have it
                self._storage.truncate_from(e.index)
            new_entries.append(e)
        if new_entries:
            self._storage.append(new_entries)
            self._ready.entries_to_persist.extend(new_entries)
        last_new = msg.prev_log_index + len(msg.entries)
        if msg.commit > self.commit_index:
            self._set_commit(min(msg.commit, last_new if msg.entries
                                 else self.last_index()))
        # Ack the highest index KNOWN to match the leader's log (etcd
        # MsgAppResp semantics), never our raw last_index(): a stale
        # divergent tail from an old term must not inflate the leader's
        # match_index, or it could commit entries never replicated to a
        # majority (ledger fork after failover).
        resp.last_log_index = last_new
        self._send(msg.from_, resp)

    def _handle_append_resp(self, msg: rpb.RaftMessage) -> None:
        if self.state != LEADER:
            return
        peer = msg.from_
        if msg.reject:
            # fast backtrack to the follower's hint
            self.next_index[peer] = max(
                1, min(msg.reject_hint + 1,
                       self.next_index.get(peer, 1) - 1))
            self._send_append(peer)
            return
        # last_log_index is the follower's confirmed-match position
        # (prev + len(entries) of the APPEND it acked); monotonic max
        # guards against stale reordered acks only.
        self.match_index[peer] = max(self.match_index.get(peer, 0),
                                     msg.last_log_index)
        self.next_index[peer] = max(self.next_index.get(peer, 1),
                                    self.match_index[peer] + 1)
        self._maybe_commit()
        if self.next_index[peer] <= self.last_index():
            self._send_append(peer)

    def _maybe_commit(self) -> None:
        matches = sorted((self.match_index.get(p, 0)
                          for p in self.peers), reverse=True)
        n = matches[len(self.peers) // 2]  # majority floor
        if n > self.commit_index and \
                self._storage.term_of(n) == self.term:
            self._set_commit(n)
            # propagate the new commit index promptly
            for p in self._others():
                self._send_append(p)

    def _set_commit(self, index: int) -> None:
        if index <= self.commit_index:
            return
        self.commit_index = index
        self._persist_hard_state()
        self._apply_upto(index)

    def _apply_upto(self, index: int, replay: bool = False) -> None:
        start = self.applied_index + 1
        if replay:
            return  # replay is the chain layer's job at restart
        for e in self._storage.entries(start, index + 1):
            self._ready.committed_entries.append(e)
            self.applied_index = e.index
            if e.type == rpb.Entry.CONF_CHANGE:
                self._apply_conf_change(e)

    def _apply_conf_change(self, entry: rpb.Entry) -> None:
        cs = rpb.ConfState()
        cs.ParseFromString(entry.data)
        self.peers = sorted(cs.voters)
        logger.info("raft node %d: voters now %s", self.id, self.peers)
        if self.state == LEADER:
            for p in self.peers:
                self.next_index.setdefault(p, self.last_index() + 1)
                self.match_index.setdefault(p, 0)

    # -- snapshots (block-pull catch-up, chain layer completes it) --

    def _handle_snapshot(self, msg: rpb.RaftMessage) -> None:
        self._elapsed = 0
        self.leader_id = msg.from_
        meta = msg.snapshot
        if meta.last_index <= self.commit_index:
            # stale/duplicate snapshot (reordered, or our ack was
            # dropped): ACK the current position anyway — silence
            # here leaves the leader's next_index below our first
            # index forever, and it would re-send this snapshot on
            # every heartbeat (a livelock the drop+dup chaos surfaces
            # immediately)
            resp = self._base(msg.from_, rpb.RaftMessage.APPEND_RESP)
            resp.last_log_index = self.commit_index
            self._send(msg.from_, resp)
            return
        # accept the snapshot position; the chain pulls blocks
        self._storage.install_snapshot(meta)
        self.commit_index = meta.last_index
        self.applied_index = meta.last_index
        self.peers = sorted(meta.conf.voters) or self.peers
        self._persist_hard_state()
        self._ready.committed_entries.append(
            rpb.Entry(index=meta.last_index, term=meta.last_term,
                      type=rpb.Entry.NORMAL, data=b""))
        resp = self._base(msg.from_, rpb.RaftMessage.APPEND_RESP)
        resp.last_log_index = meta.last_index  # matched through snapshot
        self._send(msg.from_, resp)

    def compact(self, upto_index: int, block_height: int) -> None:
        """Truncate the applied prefix (chain calls this periodically —
        reference: snapshot_interval_size)."""
        self._storage.compact(min(upto_index, self.applied_index),
                              block_height,
                              rpb.ConfState(voters=self.peers))

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------

    def _others(self):
        return [p for p in self.peers if p != self.id]

    def _quorum(self, votes: dict) -> bool:
        return len([v for v in votes.values() if v]) > \
            len(self.peers) // 2

    def _base(self, to: int, mtype: int) -> rpb.RaftMessage:
        return rpb.RaftMessage(type=mtype, from_=self.id, to=to,
                               term=self.term)

    def _send(self, to: int, msg: rpb.RaftMessage) -> None:
        msg.to = to
        self._ready.messages.append(msg)

    def _persist_hard_state(self) -> None:
        self._storage.set_hard_state(self.term, self.voted_for,
                                     self.commit_index)
        self._ready.hard_state_changed = True
