"""Block assembly + signing on the ordering side.

Rebuild of `orderer/common/multichannel/blockwriter.go`:
`CreateNextBlock:67` (hash-chain a batch of envelopes into a block) and
`WriteBlock:168` → `commitBlock:197` → `addBlockSignature:208` (the
orderer signs (metadata.value ‖ sig_header ‖ block_header_bytes) and
stores the signature in the SIGNATURES metadata slot — exactly what the
peer's `VerifyBlock` / `block_signature_set` checks).

Round 10 adds the batched span path (`write_blocks`): the write
pipeline hands a run of committed blocks here, every block is signed,
and the produced metadata signatures are re-verified in ONE batched
dispatch through the BCCSP provider seam before anything touches the
store — the orderer's own signatures ride the same device batch path
(breaker + sw fallback included, round 1) that peer validation uses,
and a corrupted signer or warm-table can never append a block the
peers would reject.
"""

from __future__ import annotations

import logging
import threading

from fabric_tpu.protos import common
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("orderer.blockwriter")


class BlockWriter:
    def __init__(self, block_store, signer, last_block=None, csp=None):
        """`block_store` is an append-only store exposing
        `add_block(block)` + `get_block_by_number`; `signer` the
        orderer's signing identity; `csp` (optional) the provider the
        batched span path self-verifies produced block signatures
        through."""
        self._store = block_store
        self._signer = signer
        self._csp = csp
        self._last = last_block
        self._lock = threading.Lock()

    @property
    def last_block(self):
        return self._last

    def resync(self, last_block: common.Block) -> None:
        """Adopt an externally appended block (catch-up/onboarding) as
        the new chain tip."""
        with self._lock:
            self._last = last_block

    def create_next_block(self, envelopes) -> common.Block:
        """Reference: `CreateNextBlock:67`."""
        with self._lock:
            if self._last is None:
                prev_hash = b""
                number = 0
            else:
                prev_hash = pu.block_header_hash(self._last.header)
                number = self._last.header.number + 1
        block = pu.new_block(number, prev_hash)
        for env in envelopes:
            block.data.data.append(pu.marshal(env))
        block.header.data_hash = pu.block_data_hash(block.data)
        return block

    def write_block(self, block: common.Block,
                    consenter_metadata: bytes = b"",
                    last_config_number: int = 0) -> None:
        """Reference: `WriteBlock:168` + `commitBlock:197`. Signs, then
        appends to the block store; `self._last` only advances on
        success so a store failure cannot fork the hash chain.
        `last_config_number` rides in Metadata.value (the reference's
        OrdererBlockMetadata.LastConfig) so restarts and onboarding can
        find the governing config block without a scan."""
        with self._lock:
            if self._last is not None and \
                    block.header.number != self._last.header.number + 1:
                raise ValueError(
                    f"writing block {block.header.number} out of order "
                    f"(last {self._last.header.number})")
            self._add_metadata(block, consenter_metadata,
                               last_config_number)
            self._store.add_block(block)
            self._last = block

    def write_blocks(self, blocks,
                     consenter_metadata: bytes = b"",
                     last_config_number: int = 0) -> None:
        """The batched span path (the write pipeline's entry): sign
        every block of a contiguous committed run, self-verify ALL the
        produced metadata signatures in one `csp.verify_batch`
        dispatch (when a provider was wired — the TPU path's breaker/
        fallback semantics apply unchanged), then append the span.
        Nothing touches the store until the whole span's signatures
        check out — a bad signature surfaces as an error the pipeline
        demotes on, never as an appended block peers would reject."""
        blocks = list(blocks)
        if not blocks:
            return
        with self._lock:
            expect = None if self._last is None \
                else self._last.header.number + 1
            signed: list = []
            for block in blocks:
                if expect is not None and \
                        block.header.number != expect:
                    raise ValueError(
                        f"writing block {block.header.number} out of "
                        f"order (expected {expect})")
                signed.append(self._add_metadata(
                    block, consenter_metadata, last_config_number))
                expect = block.header.number + 1
        # verify OUTSIDE the lock: the batched check may be a device
        # dispatch, and a lock held across one is exactly what the
        # round-8 sanitizer exists to catch
        self._self_verify(blocks, signed)
        with self._lock:
            if self._last is not None and \
                    blocks[0].header.number != \
                    self._last.header.number + 1:
                raise ValueError(
                    f"writing block {blocks[0].header.number} out of "
                    f"order (last {self._last.header.number})")
            for block in blocks:
                self._store.add_block(block)
                self._last = block

    def _self_verify(self, blocks, signed) -> None:
        """One batched provider dispatch over the span's fresh block
        signatures (skipped without a csp, or for a signer that cannot
        express verification items).

        When the orderer's cluster identity is BLS (round-11 scheme
        dispatch), the span's k signatures aggregate to ONE 96-byte G1
        point and ONE `csp.verify_aggregate` pairing check replaces k
        verify lanes — the consensus-aggregation shape from the
        EdDSA/BLS committee measurement (PAPERS.md, 2302.00418). A
        failed aggregate falls through to the per-signature batch for
        block-level attribution, so the error below still names the
        offending block numbers."""
        verify_item = getattr(self._signer, "verify_item", None)
        if self._csp is None or verify_item is None:
            return
        items = [verify_item(msg, sig) for msg, sig in signed]
        agg_verify = getattr(self._csp, "verify_aggregate", None)
        if agg_verify is not None and items and all(
                getattr(it.key, "scheme", None) == "bls12381"
                for it in items):
            from fabric_tpu.bccsp.sw import bls_aggregate_signatures
            try:
                agg_sig = bls_aggregate_signatures(
                    [it.signature for it in items])
                if agg_verify([it.key for it in items],
                              [it.message for it in items], agg_sig):
                    return
            except NotImplementedError:
                logger.warning("csp has no aggregate scheme; "
                               "verifying the BLS span per-signature")
            except ValueError:
                # a signer emitting non-G1 bytes must land on the
                # per-signature pass below (which rejects with block
                # attribution), not crash the span write
                logger.warning("BLS span signatures failed to "
                               "aggregate; verifying per-signature",
                               exc_info=True)
            # aggregate rejected (or unsupported): the per-signature
            # pass below attributes the failure to specific blocks
        ok = self._csp.verify_batch(items)
        if not all(ok):
            bad = [b.header.number
                   for b, good in zip(blocks, ok) if not good]
            raise ValueError(
                f"self-verification of fresh block signature(s) "
                f"{bad} failed — refusing to append a span peers "
                f"would reject")

    def _add_metadata(self, block: common.Block,
                      consenter_metadata: bytes,
                      last_config_number: int) -> tuple[bytes, bytes]:
        """Reference: `addBlockSignature:208` — the signed payload is
        (metadata.value ‖ signature_header ‖ block_header_bytes).
        Returns (signed_bytes, signature) so the batched span path can
        re-verify the whole run in one provider dispatch."""
        sig_header = pu.create_signature_header(
            self._signer.serialize(), pu.random_nonce())
        md = common.Metadata()
        md.value = pu.encode_last_config(last_config_number)
        ms = md.signatures.add()
        ms.signature_header = pu.marshal(sig_header)
        signed_bytes = (md.value + ms.signature_header +
                        pu.block_header_bytes(block.header))
        ms.signature = self._signer.sign(signed_bytes)
        block.metadata.metadata[
            common.BlockMetadataIndex.SIGNATURES] = pu.marshal(md)
        block.metadata.metadata[
            common.BlockMetadataIndex.ORDERER] = consenter_metadata
        # the slot must exist even on the ordering side (reference
        # writes an all-zero filter; peers overwrite at validation)
        n = len(block.data.data)
        block.metadata.metadata[
            common.BlockMetadataIndex.TRANSACTIONS_FILTER] = bytes(n)
        return signed_bytes, ms.signature
