"""Per-channel message processors for Broadcast ingest.

Rebuild of `orderer/common/msgprocessor/` — classification
(`standardchannel.go:54-170` ClassifyMsg / ProcessNormalMsg /
ProcessConfigUpdateMsg), the rule set (empty-reject, size filter,
signature filter) and config-update processing through the configtx
validator. System-channel machinery is deliberately absent: this
framework is channel-participation-native (the reference's 2.x
direction).
"""

from __future__ import annotations

import logging
from typing import Callable

from fabric_tpu.protos import common, configtx as ctxpb
from fabric_tpu.protoutil import protoutil as pu
from fabric_tpu.common.policies import policy as papi

logger = logging.getLogger("orderer.msgprocessor")


class MsgProcessorError(Exception):
    pass


class PermissionDenied(MsgProcessorError):
    pass


# message classes (reference: msgprocessor.Classification)
NORMAL = 0
CONFIG_UPDATE = 1
CONFIG = 2

# ConsensusType.state values (configtx.proto)
STATE_NORMAL = 0
STATE_MAINTENANCE = 1


def classify(ch: common.ChannelHeader) -> int:
    """Reference: `standardchannel.go:82` ClassifyMsg."""
    if ch.type == common.HeaderType.CONFIG_UPDATE:
        return CONFIG_UPDATE
    if ch.type == common.HeaderType.CONFIG:
        return CONFIG
    return NORMAL


class StandardChannel:
    """One channel's ingest processor. `support` must expose:
    - `bundle()` → current channelconfig Bundle,
    - `configtx_validator()` → configtx.Validator,
    - `signer` → the orderer's signing identity (for wrapping config
      envelopes).
    """

    def __init__(self, channel_id: str, support):
        self._channel_id = channel_id
        self._support = support

    # -- rules (reference: msgprocessor/{emptyrejectrule,sizefilter,
    #    sigfilter}.go) --

    def _apply_filters(self, env: common.Envelope,
                       policy_name: str) -> None:
        if not env.payload:
            raise MsgProcessorError("message payload is empty")
        bundle = self._support.bundle()
        max_bytes = bundle.orderer.batch_size.absolute_max_bytes
        if env.ByteSize() > max_bytes:
            raise MsgProcessorError(
                f"message larger than absolute_max_bytes ({max_bytes})")
        try:
            policy = bundle.policy_manager.get_policy(policy_name)
        except papi.PolicyError as e:
            raise PermissionDenied(f"no policy {policy_name}: {e}")
        try:
            policy.evaluate_signed_data(pu.envelope_as_signed_data(env))
        except papi.PolicyError as e:
            raise PermissionDenied(
                f"{policy_name} policy rejected message: {e}")

    # -- maintenance mode (reference: msgprocessor/maintenancefilter.go) --

    def _consensus_state(self) -> int:
        return getattr(self._support.bundle().orderer,
                       "consensus_state", STATE_NORMAL)

    def _check_maintenance_normal(self) -> None:
        """Reference maintenancefilter.go Apply: while the channel is in
        maintenance, normal transactions are rejected — only config
        updates (the migration itself) may be ordered."""
        if self._consensus_state() == STATE_MAINTENANCE:
            raise MsgProcessorError(
                "normal transactions are rejected during maintenance")

    def _check_maintenance_config(self, current: ctxpb.Config,
                                  proposed: ctxpb.Config) -> None:
        """Gate ConsensusType changes on maintenance mode (reference:
        maintenancefilter.go — the consensus-type migration state
        machine):
          * NORMAL → NORMAL: the consensus type must not change.
          * NORMAL → MAINTENANCE / MAINTENANCE → NORMAL: the update may
            change NOTHING except ConsensusType.state.
          * MAINTENANCE → MAINTENANCE: type/metadata may change (the
            migration step itself).
        """
        from fabric_tpu.common.channelconfig.bundle import (
            CONSENSUS_TYPE_KEY, ORDERER,
        )

        def ct_of(cfg: ctxpb.Config) -> ctxpb.ConsensusType:
            grp = cfg.channel_group.groups[ORDERER]
            ct = ctxpb.ConsensusType()
            ct.ParseFromString(grp.values[CONSENSUS_TYPE_KEY].value)
            return ct

        try:
            cur, nxt = ct_of(current), ct_of(proposed)
        except Exception as e:
            raise MsgProcessorError(
                f"config update drops the ConsensusType value: {e}")
        if cur.state == STATE_NORMAL and nxt.state == STATE_NORMAL:
            if nxt.type != cur.type:
                raise MsgProcessorError(
                    f"attempted to change consensus type from "
                    f"{cur.type} to {nxt.type} outside of maintenance "
                    f"mode")
            return
        if cur.state != nxt.state:
            # entry/exit must change ONLY ConsensusType.state
            a, b = ctxpb.Config(), ctxpb.Config()
            a.CopyFrom(current)
            b.CopyFrom(proposed)
            for cfg in (a, b):
                grp = cfg.channel_group.groups[ORDERER]
                grp.values[CONSENSUS_TYPE_KEY].value = b""
                grp.values[CONSENSUS_TYPE_KEY].ClearField("mod_policy")
            # version bumps accompany any value change; normalize them
            a.sequence = 0
            b.sequence = 0
            grp_a = a.channel_group.groups[ORDERER]
            grp_b = b.channel_group.groups[ORDERER]
            grp_a.values[CONSENSUS_TYPE_KEY].version = 0
            grp_b.values[CONSENSUS_TYPE_KEY].version = 0
            if pu.marshal(a) != pu.marshal(b):
                direction = "entry to" \
                    if nxt.state == STATE_MAINTENANCE else "exit from"
                raise MsgProcessorError(
                    f"config update for {direction} maintenance mode "
                    f"may change only ConsensusType.state")
            direction = "entering" if nxt.state == STATE_MAINTENANCE \
                else "exiting"
            if nxt.type != cur.type:
                raise MsgProcessorError(
                    f"cannot change consensus type while {direction} "
                    f"maintenance mode")
            if nxt.metadata != cur.metadata:
                raise MsgProcessorError(
                    f"cannot change consensus metadata while "
                    f"{direction} maintenance mode")

    def process_normal_msg(self, env: common.Envelope) -> int:
        """Reference `ProcessNormalMsg:100`: capture the config
        sequence FIRST, then filter — if a config change races the
        filters, the stale (lower) sequence forces the consenter to
        revalidate (standardchannel.go takes Sequence() before
        Apply for exactly this reason)."""
        seq, err = self.process_normal_msgs([env])[0]
        if err is not None:
            raise err
        return seq

    def process_normal_msgs(self, envs) -> list:
        """Batched ProcessNormalMsg over an ingest window: the
        signature-filter evaluations of the whole window share ONE
        `csp.verify_batch` (on the TPU provider, one device dispatch),
        where the reference verifies each Broadcast message's
        signature individually (`sigfilter.go` under `broadcast.go:72`).
        Per-envelope outcome: (config_seq, None) or (None, error) —
        acceptance per envelope is unchanged, only the crypto is
        batched."""
        seq = self._support.configtx_validator().sequence()
        bundle = self._support.bundle()
        max_bytes = bundle.orderer.batch_size.absolute_max_bytes
        try:
            policy = bundle.policy_manager.get_policy("/Channel/Writers")
        except papi.PolicyError as e:
            err = PermissionDenied(f"no policy /Channel/Writers: {e}")
            return [(None, err)] * len(envs)
        # prefer the provider's micro-batched admission window
        # (bccsp/admission.py): concurrent ingest windows — and the
        # single-envelope path — coalesce into one device dispatch
        csp = getattr(self._support, "ingress_csp", None)
        if csp is None:
            csp = getattr(self._support, "csp", None)
        out: list = [None] * len(envs)
        prepared: list = []           # (env index, prepared policy eval)
        items: list = []
        for i, env in enumerate(envs):
            try:
                self._check_maintenance_normal()
                if not env.payload:
                    raise MsgProcessorError("message payload is empty")
                if env.ByteSize() > max_bytes:
                    raise MsgProcessorError(
                        f"message larger than absolute_max_bytes "
                        f"({max_bytes})")
                sd = pu.envelope_as_signed_data(env)
                prep = None
                if csp is not None and hasattr(policy, "prepare"):
                    try:
                        prep = policy.prepare(sd)
                    except Exception:
                        prep = None    # no two-phase support: inline
                if prep is not None:
                    prepared.append((i, prep, len(items),
                                     len(prep.items)))
                    items.extend(prep.items)
                else:
                    # policy type without two-phase support: evaluate
                    # inline (its own csp still batches within the set)
                    try:
                        policy.evaluate_signed_data(sd)
                    except papi.PolicyError as e:
                        raise PermissionDenied(
                            f"/Channel/Writers policy rejected "
                            f"message: {e}")
                    out[i] = (seq, None)
            except MsgProcessorError as e:
                out[i] = (None, e)
            except Exception as e:
                out[i] = (None, MsgProcessorError(str(e)))
        if items:
            ok = csp.verify_batch(items)
        else:
            ok = []
        for i, prep, lo, n_items in prepared:
            try:
                prep.finish(ok[lo:lo + n_items])
                out[i] = (seq, None)
            except papi.PolicyError as e:
                out[i] = (None, PermissionDenied(
                    f"/Channel/Writers policy rejected message: {e}"))
            except Exception as e:
                out[i] = (None, MsgProcessorError(str(e)))
        return out

    def process_config_update_msg(self, env: common.Envelope
                                  ) -> tuple[common.Envelope, int]:
        """Reference `ProcessConfigUpdateMsg:116`: validate the update
        against the current config + policies, wrap the resulting
        ConfigEnvelope in a signed CONFIG envelope ready for ordering.
        Sequence is captured before the filters (same race rationale as
        process_normal_msg)."""
        seq = self._support.configtx_validator().sequence()
        self._apply_filters(env, "/Channel/Writers")
        payload = pu.get_payload(env)
        update_env = ctxpb.ConfigUpdateEnvelope()
        try:
            update_env.ParseFromString(payload.data)
        except Exception as e:
            raise MsgProcessorError(f"bad config update envelope: {e}")
        validator = self._support.configtx_validator()
        new_config = validator.propose_config_update(update_env)
        self._check_maintenance_config(validator.config, new_config)

        cfg_env = ctxpb.ConfigEnvelope()
        cfg_env.config.CopyFrom(new_config)
        cfg_env.last_update = pu.marshal(env)

        signer = self._support.signer
        ch = pu.make_channel_header(common.HeaderType.CONFIG,
                                    self._channel_id)
        sh = pu.create_signature_header(signer.serialize(),
                                        pu.random_nonce())
        wrapped = pu.make_payload(ch, sh, pu.marshal(cfg_env))
        signed = pu.sign_or_panic(signer, wrapped)
        return signed, seq

    def process_config_msg(self, env: common.Envelope
                           ) -> tuple[common.Envelope, int]:
        """Reference `ProcessConfigMsg:155`: a CONFIG envelope arriving
        on Broadcast is unwrapped to its original update and
        re-processed (defends against forged config envelopes)."""
        payload = pu.get_payload(env)
        cfg_env = ctxpb.ConfigEnvelope()
        try:
            cfg_env.ParseFromString(payload.data)
        except Exception as e:
            raise MsgProcessorError(f"bad config envelope: {e}")
        if not cfg_env.last_update:
            raise MsgProcessorError(
                "config envelope has no embedded update")
        return self.process_config_update_msg(
            pu.unmarshal_envelope(cfg_env.last_update))
