"""Per-channel message processors for Broadcast ingest.

Rebuild of `orderer/common/msgprocessor/` — classification
(`standardchannel.go:54-170` ClassifyMsg / ProcessNormalMsg /
ProcessConfigUpdateMsg), the rule set (empty-reject, size filter,
signature filter) and config-update processing through the configtx
validator. System-channel machinery is deliberately absent: this
framework is channel-participation-native (the reference's 2.x
direction).
"""

from __future__ import annotations

import logging
from typing import Callable

from fabric_tpu.protos import common, configtx as ctxpb
from fabric_tpu.protoutil import protoutil as pu
from fabric_tpu.common.policies import policy as papi

logger = logging.getLogger("orderer.msgprocessor")


class MsgProcessorError(Exception):
    pass


class PermissionDenied(MsgProcessorError):
    pass


# message classes (reference: msgprocessor.Classification)
NORMAL = 0
CONFIG_UPDATE = 1
CONFIG = 2


def classify(ch: common.ChannelHeader) -> int:
    """Reference: `standardchannel.go:82` ClassifyMsg."""
    if ch.type == common.HeaderType.CONFIG_UPDATE:
        return CONFIG_UPDATE
    if ch.type == common.HeaderType.CONFIG:
        return CONFIG
    return NORMAL


class StandardChannel:
    """One channel's ingest processor. `support` must expose:
    - `bundle()` → current channelconfig Bundle,
    - `configtx_validator()` → configtx.Validator,
    - `signer` → the orderer's signing identity (for wrapping config
      envelopes).
    """

    def __init__(self, channel_id: str, support):
        self._channel_id = channel_id
        self._support = support

    # -- rules (reference: msgprocessor/{emptyrejectrule,sizefilter,
    #    sigfilter}.go) --

    def _apply_filters(self, env: common.Envelope,
                       policy_name: str) -> None:
        if not env.payload:
            raise MsgProcessorError("message payload is empty")
        bundle = self._support.bundle()
        max_bytes = bundle.orderer.batch_size.absolute_max_bytes
        if len(pu.marshal(env)) > max_bytes:
            raise MsgProcessorError(
                f"message larger than absolute_max_bytes ({max_bytes})")
        try:
            policy = bundle.policy_manager.get_policy(policy_name)
        except papi.PolicyError as e:
            raise PermissionDenied(f"no policy {policy_name}: {e}")
        try:
            policy.evaluate_signed_data(pu.envelope_as_signed_data(env))
        except papi.PolicyError as e:
            raise PermissionDenied(
                f"{policy_name} policy rejected message: {e}")

    def process_normal_msg(self, env: common.Envelope) -> int:
        """Reference `ProcessNormalMsg:100`: capture the config
        sequence FIRST, then filter — if a config change races the
        filters, the stale (lower) sequence forces the consenter to
        revalidate (standardchannel.go takes Sequence() before
        Apply for exactly this reason)."""
        seq = self._support.configtx_validator().sequence()
        self._apply_filters(env, "/Channel/Writers")
        return seq

    def process_config_update_msg(self, env: common.Envelope
                                  ) -> tuple[common.Envelope, int]:
        """Reference `ProcessConfigUpdateMsg:116`: validate the update
        against the current config + policies, wrap the resulting
        ConfigEnvelope in a signed CONFIG envelope ready for ordering.
        Sequence is captured before the filters (same race rationale as
        process_normal_msg)."""
        seq = self._support.configtx_validator().sequence()
        self._apply_filters(env, "/Channel/Writers")
        payload = pu.get_payload(env)
        update_env = ctxpb.ConfigUpdateEnvelope()
        try:
            update_env.ParseFromString(payload.data)
        except Exception as e:
            raise MsgProcessorError(f"bad config update envelope: {e}")
        validator = self._support.configtx_validator()
        new_config = validator.propose_config_update(update_env)

        cfg_env = ctxpb.ConfigEnvelope()
        cfg_env.config.CopyFrom(new_config)
        cfg_env.last_update = pu.marshal(env)

        signer = self._support.signer
        ch = pu.make_channel_header(common.HeaderType.CONFIG,
                                    self._channel_id)
        sh = pu.create_signature_header(signer.serialize(),
                                        pu.random_nonce())
        wrapped = pu.make_payload(ch, sh, pu.marshal(cfg_env))
        signed = pu.sign_or_panic(signer, wrapped)
        return signed, seq

    def process_config_msg(self, env: common.Envelope
                           ) -> tuple[common.Envelope, int]:
        """Reference `ProcessConfigMsg:155`: a CONFIG envelope arriving
        on Broadcast is unwrapped to its original update and
        re-processed (defends against forged config envelopes)."""
        payload = pu.get_payload(env)
        cfg_env = ctxpb.ConfigEnvelope()
        try:
            cfg_env.ParseFromString(payload.data)
        except Exception as e:
            raise MsgProcessorError(f"bad config envelope: {e}")
        if not cfg_env.last_update:
            raise MsgProcessorError(
                "config envelope has no embedded update")
        return self.process_config_update_msg(
            pu.unmarshal_envelope(cfg_env.last_update))
