"""Crash-tolerant file repository for channel-participation artifacts.

Rebuild of `orderer/common/filerepo/filerepo.go`: a directory of
`<name>.<suffix>` files where Save is write-to-`<file>~tmp`, fsync,
atomic rename — so a reader never observes a torn file — and
construction sweeps leftover `~tmp` files from a crash mid-save.
The orderer uses one repo for join blocks: a join is durable in the
repo BEFORE the channel's ledger exists, and the registrar resumes
interrupted joins at startup (multichannel.Registrar.__init__).
"""

from __future__ import annotations

import os
import re
from typing import Optional

_TMP = "~tmp"
_NAME_RE = re.compile(r"^[a-zA-Z0-9.-]+$")


class FileRepoError(Exception):
    pass


class FileRepo:
    """One artifact kind (suffix) in one directory."""

    def __init__(self, base_dir: str, suffix: str = "join"):
        if not suffix or "." in suffix or "/" in suffix:
            raise FileRepoError(f"invalid suffix {suffix!r}")
        self._dir = os.path.join(base_dir, suffix)
        self._suffix = "." + suffix
        os.makedirs(self._dir, exist_ok=True)
        # a crash mid-save leaves only a ~tmp file; sweep it so a
        # half-written artifact can never be read back
        for name in os.listdir(self._dir):
            if name.endswith(_TMP):
                try:
                    os.remove(os.path.join(self._dir, name))
                except OSError:
                    pass
        self._fsync_dir()

    def _fsync_dir(self) -> None:
        """Make the rename/unlink itself durable (POSIX requires the
        directory fsync, not just the file's)."""
        try:
            fd = os.open(self._dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def _path(self, name: str) -> str:
        if not _NAME_RE.match(name):
            raise FileRepoError(f"invalid artifact name {name!r}")
        return os.path.join(self._dir, name + self._suffix)

    def save(self, name: str, content: bytes) -> None:
        """Atomic create-or-replace: tmp + fsync + rename + dir fsync
        (reference filerepo.Save semantics)."""
        path = self._path(name)
        tmp = path + _TMP
        with open(tmp, "wb") as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._fsync_dir()

    def read(self, name: str) -> Optional[bytes]:
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def remove(self, name: str) -> None:
        """Idempotent (reference Remove tolerates missing files)."""
        try:
            os.remove(self._path(name))
        except FileNotFoundError:
            return
        self._fsync_dir()

    def list(self) -> list[str]:
        """Artifact names (without suffix), sorted."""
        out = []
        for fname in os.listdir(self._dir):
            if fname.endswith(self._suffix) and not fname.endswith(_TMP):
                out.append(fname[: -len(self._suffix)])
        return sorted(out)
