"""Block cutter: batch envelopes by count / bytes / timeout.

Rebuild of `orderer/common/blockcutter/blockcutter.go:69` (Ordered):
returns zero, one, or two batches per message plus a "pending" flag the
chain uses to arm its batch timer. Timeout itself lives in the
consenter (solo/raft), exactly like the reference.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

from fabric_tpu.common import metrics as _m
from fabric_tpu.protos import common
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("blockcutter")

BLOCK_FILL_DURATION = _m.HistogramOpts(
    namespace="blockcutter", name="block_fill_duration",
    help="The time from first transaction enqueueing to the block "
         "being cut in seconds.", label_names=("channel",))


@dataclass
class BatchConfig:
    """Orderer.BatchSize from channel config (reference:
    configtx.yaml Orderer.BatchSize)."""
    max_message_count: int = 500
    absolute_max_bytes: int = 10 * 1024 * 1024
    preferred_max_bytes: int = 2 * 1024 * 1024


class Receiver:
    def __init__(self, config_source, metrics_provider=None,
                 channel: str = ""):
        """`config_source()` returns the current BatchConfig — config
        can change between blocks (reference fetches
        sharedConfigFetcher.OrdererConfig() per call)."""
        self._config_source = config_source
        self._pending: list[common.Envelope] = []
        self._pending_bytes = 0
        self._first_enqueued: float | None = None
        provider = metrics_provider or _m.DisabledProvider()
        self._fill_duration = provider.new_histogram(
            BLOCK_FILL_DURATION).with_labels("channel", channel)

    def ordered(self, env: common.Envelope
                ) -> tuple[list[list[common.Envelope]], bool]:
        """Reference `Ordered`: returns (batches, pending). An oversize
        message is cut into its own batch; a message that would
        overflow preferred_max_bytes first flushes the pending batch."""
        cfg = self._config_source()
        msg_bytes = len(pu.marshal(env))
        batches: list[list[common.Envelope]] = []

        if msg_bytes > cfg.preferred_max_bytes:
            logger.debug("message (%dB) larger than preferred (%dB): "
                         "isolating", msg_bytes, cfg.preferred_max_bytes)
            if self._pending:
                batches.append(self._cut())
            batches.append([env])
            return batches, False

        if self._pending_bytes + msg_bytes > cfg.preferred_max_bytes:
            batches.append(self._cut())

        self._pending.append(env)
        if self._first_enqueued is None:
            self._first_enqueued = time.perf_counter()
        self._pending_bytes += msg_bytes
        if len(self._pending) >= cfg.max_message_count:
            batches.append(self._cut())
        return batches, bool(self._pending)

    def cut(self) -> list[common.Envelope]:
        """Flush pending (timer fired or config message arrived)."""
        return self._cut() if self._pending else []

    def _cut(self) -> list[common.Envelope]:
        batch = self._pending
        if self._first_enqueued is not None:
            self._fill_duration.observe(
                time.perf_counter() - self._first_enqueued)
            self._first_enqueued = None
        self._pending = []
        self._pending_bytes = 0
        return batch

    @property
    def pending_count(self) -> int:
        return len(self._pending)
