"""Multichannel registrar: one ordering chain per channel.

Rebuild of `orderer/common/multichannel/registrar.go:97` — channel
registry, channel creation from a join-block (channel-participation
style, no system channel: the reference's 2.x direction), per-channel
`ChainSupport` binding together config bundle, configtx validator,
msgprocessor, blockcutter, blockwriter and the consenter chain.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
from typing import Callable, Optional

from fabric_tpu.protos import common, configtx as ctxpb
from fabric_tpu.protoutil import protoutil as pu
from fabric_tpu.common.channelconfig import Bundle
from fabric_tpu.common.configtx import Validator as ConfigTxValidator
from fabric_tpu.internal.configtxgen import genesis as genesis_mod
from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.orderer import blockcutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.orderer.msgprocessor import StandardChannel

logger = logging.getLogger("orderer.multichannel")

from fabric_tpu.common import metrics as _m  # noqa: E402

PARTICIPATION_STATUS = _m.GaugeOpts(
    namespace="participation", name="status",
    help="The channel participation status of the node on the "
         "channel: 1 for the current status (active, onboarding, "
         "failed), 0 otherwise.", label_names=("channel", "status"))
PARTICIPATION_RELATION = _m.GaugeOpts(
    namespace="participation", name="consensus_relation",
    help="The consensus relation of the node on the channel: 1 for "
         "the current relation (consenter, follower, other), 0 "
         "otherwise.", label_names=("channel", "relation"))


class OrdererLedger:
    """The ordering side keeps only the block chain (no state DB) —
    reference: orderer uses blkstorage directly
    (`orderer/common/server/main.go` createLedgerFactory). A condition
    variable lets Deliver block until the next block arrives."""

    def __init__(self, ledger_dir: str):
        os.makedirs(ledger_dir, exist_ok=True)
        self._kv = KVStore(os.path.join(ledger_dir, "index.db"))
        self.block_store = BlockStore(ledger_dir,
                                      DBHandle(self._kv, "blkindex"))
        self._cond = threading.Condition()

    @property
    def height(self) -> int:
        return self.block_store.height

    def add_block(self, block: common.Block) -> None:
        self.block_store.add_block(block)
        with self._cond:
            self._cond.notify_all()

    def get_block(self, number: int) -> Optional[common.Block]:
        return self.block_store.get_block_by_number(number)

    def db_handle(self, name: str) -> DBHandle:
        """A named keyspace in the channel's KV store (raft WAL etc.)."""
        return DBHandle(self._kv, name)

    def wait_for_block(self, number: int,
                       timeout: Optional[float] = None) -> bool:
        """Block until height > number (i.e. block `number` exists)."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self.block_store.height > number, timeout)

    def close(self) -> None:
        self.block_store.close()
        self._kv.close()




class ChainSupport:
    """Everything one channel's chain needs (reference:
    `multichannel/chainsupport.go`). The msgprocessor's `support`
    duck-type (bundle()/configtx_validator()/signer) is satisfied
    here."""

    def __init__(self, channel_id: str, ledger: OrdererLedger,
                 signer, csp, consenter_factory,
                 metrics_provider=None, on_became_consenter=None):
        self.channel_id = channel_id
        self.ledger = ledger
        self.signer = signer
        self._csp = csp
        self._metrics_provider = metrics_provider
        # promotion hook: a FollowerChain that finds this orderer in
        # the consenter set calls this (the registrar wires it to
        # switch_follower_to_chain); consulted by the consenter factory
        self.on_became_consenter = on_became_consenter
        self._lock = threading.Lock()
        self._bundle: Optional[Bundle] = None
        self._validator: Optional[ConfigTxValidator] = None

        height = ledger.height
        if height == 0:
            raise ValueError("chain support requires a bootstrapped "
                             "ledger (join-block first)")
        last = ledger.get_block(height - 1)
        cfg_block = last if pu.is_config_block(last) else \
            ledger.get_block(pu.get_last_config_index(last))
        self._apply_config_block(cfg_block)
        self._last_config_number = cfg_block.header.number

        self.cutter = blockcutter.Receiver(
            self._batch_config, metrics_provider=metrics_provider,
            channel=channel_id)
        self.writer = BlockWriter(ledger, signer, last_block=last,
                                  csp=csp)
        # broadcast-ingress signature checks ride the session
        # provider's micro-batched admission window: a storm of
        # single-envelope submitters coalesces into full device
        # batches (bccsp/admission.py) — every channel on this node
        # shares the provider's one window
        from fabric_tpu.bccsp.admission import AdmissionWindow
        self.ingress_csp = AdmissionWindow.shared(csp) \
            if csp is not None else None
        self.processor = StandardChannel(channel_id, self)
        self.chain = consenter_factory(self)
        logger.info("[%s] chain support up at height %d "
                    "(consensus=%s)", channel_id, height,
                    self.bundle().orderer.consensus_type)

    # -- config plumbing --

    def _apply_config_block(self, block: common.Block) -> None:
        env = pu.extract_envelope(block, 0)
        payload = pu.get_payload(env)
        ch = pu.get_channel_header(payload)
        if ch.type != common.HeaderType.CONFIG:
            raise ValueError(f"block {block.header.number} is not a "
                             "config block")
        if ch.channel_id != self.channel_id:
            raise ValueError("config block is for channel "
                             f"{ch.channel_id!r}")
        cfg_env = ctxpb.ConfigEnvelope()
        cfg_env.ParseFromString(payload.data)
        bundle = Bundle(self.channel_id, cfg_env.config, self._csp)
        if bundle.orderer is None:
            raise ValueError("config lacks an Orderer section")
        with self._lock:
            self._bundle = bundle
            self._validator = ConfigTxValidator(
                self.channel_id, cfg_env.config,
                bundle.policy_manager)
        logger.info("[%s] config now at sequence %d",
                    self.channel_id, self._validator.sequence())

    @property
    def csp(self):
        """The orderer's crypto provider — the batched sig-filter
        (msgprocessor.process_normal_msgs) dispatches through it."""
        return self._csp

    def bundle(self) -> Bundle:
        with self._lock:
            return self._bundle

    def configtx_validator(self) -> ConfigTxValidator:
        with self._lock:
            return self._validator

    def sequence(self) -> int:
        return self.configtx_validator().sequence()

    def _batch_config(self) -> blockcutter.BatchConfig:
        bs = self.bundle().orderer.batch_size
        return blockcutter.BatchConfig(
            max_message_count=bs.max_message_count,
            absolute_max_bytes=bs.absolute_max_bytes,
            preferred_max_bytes=bs.preferred_max_bytes)

    @property
    def batch_timeout_s(self) -> float:
        return self.bundle().orderer.batch_timeout_s

    # -- what consenter chains call to emit blocks --

    def create_next_block(self, envelopes) -> common.Block:
        return self.writer.create_next_block(envelopes)

    def write_block(self, block: common.Block,
                    consenter_metadata: bytes = b"") -> None:
        self.writer.write_block(
            block, consenter_metadata,
            last_config_number=self._last_config_number)

    def write_blocks(self, blocks,
                     consenter_metadata: bytes = b"") -> None:
        """A contiguous committed span in one batched sign+verify pass
        (the raft write pipeline's fast path; see
        BlockWriter.write_blocks). Callers guarantee no config block
        rides in the span — those go through write_config_block."""
        self.writer.write_blocks(
            blocks, consenter_metadata,
            last_config_number=self._last_config_number)

    def write_config_block(self, block: common.Block,
                           consenter_metadata: bytes = b"") -> None:
        """A committed config block reconfigures the chain before the
        next message is processed (reference:
        `chainsupport.go` WriteConfigBlock)."""
        self.writer.write_block(
            block, consenter_metadata,
            last_config_number=block.header.number)
        self._last_config_number = block.header.number
        self._apply_config_block(block)

    def verify_onboarded_span(self, blocks) -> tuple:
        """Verify a contiguous span of pulled blocks against this
        channel's live config (reference
        `orderer/common/cluster/util.go:202` VerifyBlocks): numbering
        from the ledger tip, data-hash, previous-hash linkage, and one
        BATCHED BCCSP dispatch for every block signature in the span,
        re-deriving the policy across embedded config blocks. Returns
        (valid_prefix_len, error) — see onboarding.verify_block_span.
        """
        from fabric_tpu.orderer.onboarding import verify_block_span
        height = self.ledger.height
        prev_hash = None
        if height:
            prev_hash = pu.block_header_hash(
                self.ledger.get_block(height - 1).header)
        n_valid, _bundle, err = verify_block_span(
            self.channel_id, blocks, height, prev_hash, self.bundle())
        return n_valid, err

    def commit_onboarded_block(self, block: common.Block) -> None:
        """Commit one VERIFIED pulled block: append verbatim (it keeps
        the source's signatures), resync the writer, and adopt an
        embedded config."""
        if block.header.number != self.ledger.height:
            raise ValueError(
                f"onboarding block {block.header.number} out of order "
                f"(height {self.ledger.height})")
        self.ledger.add_block(block)
        self.writer.resync(block)
        if pu.is_config_block(block):
            self._last_config_number = block.header.number
            self._apply_config_block(block)

    def halt(self) -> None:
        self.chain.halt()


class Registrar:
    """Channel registry (reference: `registrar.go:97` NewRegistrar +
    Initialize). Channels come into being via `join` (channel
    participation, `orderer/common/channelparticipation`) and are
    restored from disk on restart."""

    def __init__(self, root_dir: str, signer, csp,
                 consenters: dict[str, Callable],
                 metrics_provider=None, cluster_transport=None):
        self._root = root_dir
        self._signer = signer
        self._csp = csp
        self._consenters = dict(consenters)
        self._chains: dict[str, ChainSupport] = {}
        self._onboarding: set[str] = set()   # joins replicating now
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # cluster fabric for ONBOARDING pulls (join from a non-genesis
        # config block); without one, only genesis joins are possible
        self._cluster_transport = cluster_transport
        # channel -> replication state, surfaced on /healthz; mutated
        # from onboarding/promotion threads — always via
        # _note_onboarding (under _lock)
        self.onboarding_status: dict[str, str] = {}
        self._metrics_provider = metrics_provider or \
            _m.DisabledProvider()
        self._part_status = self._metrics_provider.new_gauge(
            PARTICIPATION_STATUS)
        self._part_relation = self._metrics_provider.new_gauge(
            PARTICIPATION_RELATION)
        os.makedirs(root_dir, exist_ok=True)
        # crash-tolerant join-block repo (reference
        # orderer/common/filerepo/filerepo.go): a join is durable here
        # BEFORE the channel ledger exists, so a crash mid-join resumes
        # below instead of losing the operator's request
        from fabric_tpu.orderer.filerepo import FileRepo
        self._joinrepo = FileRepo(os.path.join(root_dir, "pendingops"),
                                  "join")
        # pending joins first: a channel with a NON-genesis artifact
        # was crashed mid-ONBOARDING — it must resume through the
        # onboarding path (which keeps hash-anchoring the pulled chain
        # to the operator-supplied join block), never through a plain
        # restore that would forget the anchor
        pending: dict[str, common.Block] = {}
        for channel_id in self._joinrepo.list():
            try:
                block = common.Block()
                block.ParseFromString(self._joinrepo.read(channel_id))
                pending[channel_id] = block
            except Exception:
                logger.exception("unreadable pending-join artifact "
                                 "for %s (kept)", channel_id)
        for channel_id in sorted(os.listdir(root_dir)):
            if channel_id == "pendingops":
                continue
            if not os.path.isdir(os.path.join(root_dir, channel_id)):
                continue
            blk = pending.get(channel_id)
            if blk is not None and blk.header.number > 0:
                continue        # resumed below via onboarding
            try:
                self._restore(channel_id)
            except Exception:
                logger.exception("failed to restore channel %s",
                                 channel_id)
        for channel_id, block in sorted(pending.items()):
            if channel_id in self._chains:
                # crashed after the ledger append but before the
                # artifact removal: the channel restored above
                self._joinrepo.remove(channel_id)
                continue
            logger.info("resuming interrupted join of channel %s "
                        "from the pending-join repo", channel_id)
            if block.header.number == 0:
                try:
                    self.join(block)
                except Exception:
                    logger.exception("could not resume join of "
                                     "channel %s (artifact kept for "
                                     "retry)", channel_id)
            else:
                # onboarding resume replicates from the network; run
                # it in the background so startup (and the channels
                # restored above) aren't held hostage to dead sources
                threading.Thread(
                    target=self._resume_onboarding,
                    args=(channel_id, block), daemon=True,
                    name=f"onboard-{channel_id}").start()

    def _resume_onboarding(self, channel_id: str,
                           block: common.Block) -> None:
        try:
            self.join(block)
        except Exception:
            logger.exception("could not resume onboarding of channel "
                             "%s (durable prefix + artifact kept for "
                             "retry)", channel_id)

    def _consenter_factory(self):
        def factory(support: ChainSupport):
            ctype = support.bundle().orderer.consensus_type
            maker = self._consenters.get(ctype)
            if maker is None:
                raise ValueError(f"no consenter for type {ctype!r}")
            return maker(support)
        return factory

    def _set_participation(self, channel_id: str, support) -> None:
        """Channel-participation gauges (reference:
        `orderer/common/channelparticipation` info endpoint exposes the
        same status/relation pair)."""
        follower = type(support.chain).__name__ == "FollowerChain"
        status = "onboarding" if follower else "active"
        relation = "follower" if follower else "consenter"
        for s in ("active", "onboarding", "failed"):
            self._part_status.with_labels(
                "channel", channel_id, "status", s).set(
                1 if s == status else 0)
        for r in ("consenter", "follower", "other"):
            self._part_relation.with_labels(
                "channel", channel_id, "relation", r).set(
                1 if r == relation else 0)

    def _promotion_hook(self, channel_id: str) -> Callable:
        def hook() -> None:
            self.switch_follower_to_chain(channel_id)
        return hook

    def switch_follower_to_chain(self, channel_id: str) -> None:
        """Promotion (reference registrar.SwitchFollowerToChain): a
        committed config block added this orderer to the channel's
        consenter set; replace the follower chain with a consenter
        chain over the SAME support. Runs on its own thread — the
        trigger fires from inside the follower's loop."""
        def _go() -> None:
            if self._stop.is_set():
                return
            with self._lock:
                support = self._chains.get(channel_id)
            if support is None:
                return
            try:
                support.chain.halt()
            except Exception:
                logger.exception("[%s] halting follower for promotion "
                                 "failed", channel_id)
            try:
                # swap + start under the lock, re-checking halt: the
                # registrar's halt() snapshots chains under the same
                # lock, so a promotion either lands BEFORE the
                # snapshot (and gets halted with everything else) or
                # observes _stop and never starts the new chain
                with self._lock:
                    if self._stop.is_set() or \
                            self._chains.get(channel_id) is not support:
                        return
                    support.chain = self._consenter_factory()(support)
                    support.chain.start()
            except Exception:
                logger.exception("[%s] promotion to consenter failed",
                                 channel_id)
                return
            self._set_participation(channel_id, support)
            self._note_onboarding(channel_id, None)
            logger.info("[%s] follower promoted to consenter",
                        channel_id)
        threading.Thread(target=_go, daemon=True,
                         name=f"promote-{channel_id}").start()

    def _restore(self, channel_id: str) -> None:
        ledger = OrdererLedger(os.path.join(self._root, channel_id))
        if ledger.height == 0:
            ledger.close()
            return
        try:
            support = ChainSupport(channel_id, ledger, self._signer,
                                   self._csp,
                                   self._consenter_factory(),
                                   metrics_provider=self._metrics_provider,
                                   on_became_consenter=self._promotion_hook(
                                       channel_id))
        except Exception:
            ledger.close()
            raise
        with self._lock:
            self._chains[channel_id] = support
        support.chain.start()
        self._set_participation(channel_id, support)

    def join(self, join_block: common.Block) -> ChainSupport:
        """Channel participation join (reference:
        `registrar.go` JoinChannel / `channelparticipation`): bootstrap
        the channel's ledger from a genesis (join) block."""
        env = pu.extract_envelope(join_block, 0)
        ch = pu.get_channel_header(pu.get_payload(env))
        channel_id = ch.channel_id
        if join_block.header.number != 0:
            return self._join_onboarding(channel_id, join_block)
        with self._lock:
            if channel_id in self._chains or \
                    channel_id in self._onboarding:
                raise ValueError(f"channel {channel_id} already exists")
            # validate the join block BEFORE anything touches disk:
            # a rejected join must leave no trace so it can be retried
            # (same contract as ledgermgmt.create's marker protocol)
            bundle = Bundle(channel_id,
                            genesis_mod.config_from_block(join_block),
                            self._csp)
            if bundle.orderer is None:
                raise ValueError("join block config lacks an Orderer "
                                 "section")
            # the join becomes DURABLE here, before any ledger state
            # exists: a crash at any later point is resumed from this
            # artifact at startup (write-tmp-fsync-rename discipline —
            # reference orderer/common/filerepo + registrar JoinChannel)
            self._joinrepo.save(channel_id, pu.marshal(join_block))
            if os.environ.get("FTPU_CRASH_AFTER_JOIN_SAVE") == "1":
                # crash-fault injection for the nwo kill-during-join
                # test: die with the join saved but no ledger created
                logger.critical("FTPU_CRASH_AFTER_JOIN_SAVE: aborting")
                os._exit(41)
            channel_dir = os.path.join(self._root, channel_id)
            # only a join that CREATES the ledger may clean it up on
            # failure; a pre-existing dir (e.g. startup _restore failed
            # and the operator retries) holds a chain we must not wipe
            created = not os.path.isdir(channel_dir)
            ledger = OrdererLedger(channel_dir)
            try:
                if ledger.height == 0:
                    ledger.add_block(join_block)
                support = ChainSupport(channel_id, ledger, self._signer,
                                       self._csp,
                                       self._consenter_factory(),
                                       metrics_provider=self._metrics_provider,
                                       on_became_consenter=self._promotion_hook(
                                           channel_id))
            except Exception:
                ledger.close()
                if created:
                    shutil.rmtree(channel_dir, ignore_errors=True)
                    self._joinrepo.remove(channel_id)
                raise
            self._chains[channel_id] = support
            # the ledger now holds the join block durably; the pending
            # artifact has served its purpose
            self._joinrepo.remove(channel_id)
        support.chain.start()
        self._set_participation(channel_id, support)
        return support

    def _join_onboarding(self, channel_id: str,
                         join_block: common.Block) -> ChainSupport:
        """Join from a LATER config block (reference
        `orderer/common/onboarding/onboarding.go` + registrar
        JoinChannel with a non-genesis block): replicate the chain up
        through the join block from the channel's consenters —
        verifying every block, failing over between sources — then
        come up as a follower (or consenter, if the join config
        already names this orderer). The join artifact plus the
        crash-safe block store make a kill at ANY point resumable: the
        restart re-enters here (or _restore, once a block is durable)
        and replication continues from the last committed height."""
        from fabric_tpu.orderer import onboarding as onb
        with self._lock:
            if channel_id in self._chains or \
                    channel_id in self._onboarding:
                raise ValueError(f"channel {channel_id} already exists")
            if self._cluster_transport is None:
                raise ValueError(
                    f"cannot onboard channel {channel_id}: joining "
                    "from a non-genesis config block requires a "
                    "cluster transport to pull the chain from")
            # validate BEFORE anything touches disk (same contract as
            # the genesis path: a rejected join leaves no trace)
            bundle = Bundle(channel_id,
                            genesis_mod.config_from_block(join_block),
                            self._csp)
            if bundle.orderer is None:
                raise ValueError("join block config lacks an Orderer "
                                 "section")
            self._joinrepo.save(channel_id, pu.marshal(join_block))
            # reserve the name: replication happens OUTSIDE the lock
            # (it can take minutes — the registrar must keep serving
            # get_chain for every other channel meanwhile)
            self._onboarding.add(channel_id)
        channel_dir = os.path.join(self._root, channel_id)
        created = not os.path.isdir(channel_dir)
        ledger = None
        try:
            ledger = OrdererLedger(channel_dir)
            sink = onb.BootstrapSink(channel_id, ledger, join_block,
                                     self._csp)
            replicator = onb.ChainReplicator(
                channel_id, self._cluster_transport,
                consenters_fn=lambda: onb.consenter_endpoints(
                    sink.bundle),
                sink=sink,
                metrics_provider=self._metrics_provider,
                on_state=lambda st: self._note_onboarding(channel_id,
                                                          st))
            replicator.run(
                target_height=join_block.header.number + 1,
                stop=self._stop,
                max_wall_s=float(os.environ.get(
                    "FTPU_ONBOARD_JOIN_TIMEOUT_S", "120")))
            with self._lock:
                support = ChainSupport(
                    channel_id, ledger, self._signer, self._csp,
                    self._consenter_factory(),
                    metrics_provider=self._metrics_provider,
                    on_became_consenter=self._promotion_hook(
                        channel_id))
                self._chains[channel_id] = support
                self._joinrepo.remove(channel_id)
                self.onboarding_status.pop(channel_id, None)
        except Exception:
            progressed = ledger is not None and ledger.height > 0
            if ledger is not None:
                ledger.close()
            if created and not progressed:
                # nothing replicated: leave no trace, allow retry
                shutil.rmtree(channel_dir, ignore_errors=True)
                self._joinrepo.remove(channel_id)
                self._note_onboarding(channel_id, None)
            else:
                # keep the durable verified prefix AND the join
                # artifact: a restart or retried join resumes here
                self._note_onboarding(channel_id, "failed")
            raise
        finally:
            with self._lock:
                self._onboarding.discard(channel_id)
        support.chain.start()
        self._set_participation(channel_id, support)
        return support

    def remove(self, channel_id: str) -> None:
        """Channel-participation remove: halt the chain and delete the
        channel's ledger (reference registrar.RemoveChannel)."""
        with self._lock:
            support = self._chains.pop(channel_id, None)
            self._joinrepo.remove(channel_id)
        if support is not None:
            support.halt()
            support.ledger.close()
            shutil.rmtree(os.path.join(self._root, channel_id),
                          ignore_errors=True)

    def get_chain(self, channel_id: str) -> Optional[ChainSupport]:
        with self._lock:
            return self._chains.get(channel_id)

    def channel_list(self) -> list[str]:
        with self._lock:
            return sorted(self._chains)

    def _note_onboarding(self, channel_id: str,
                         state: Optional[str]) -> None:
        """Single mutation point for onboarding_status outside held-
        lock regions: the dict is written from the onboarding and
        promotion threads and read by /healthz, so every write takes
        _lock (None removes the entry)."""
        with self._lock:
            if state is None:
                self.onboarding_status.pop(channel_id, None)
            else:
                self.onboarding_status[channel_id] = state

    def onboarding_health(self) -> Optional[str]:
        """Aggregate replication state for /healthz `components`:
        "chan1:pull chan2:verify", or None when nothing is
        onboarding."""
        with self._lock:
            snap = dict(self.onboarding_status)
        if not snap:
            return None
        return " ".join(f"{ch}:{st}" for ch, st in sorted(snap.items()))

    def halt(self) -> None:
        self._stop.set()
        with self._lock:
            chains = list(self._chains.values())
        for c in chains:
            c.halt()
            c.ledger.close()
