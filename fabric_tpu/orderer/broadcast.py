"""Broadcast ingest: classify → process → order.

Rebuild of `orderer/common/broadcast/broadcast.go:66,135`
(Handle/ProcessMessage): each envelope is classified, run through the
channel's msgprocessor (filters + config processing), then handed to
the consenter chain via Order/Configure.
"""

from __future__ import annotations

import logging
import time

from fabric_tpu.common import metrics as _m
from fabric_tpu.common.overload import OverloadError
from fabric_tpu.protos import common, orderer as ordpb
from fabric_tpu.protoutil import protoutil as pu
from fabric_tpu.orderer import msgprocessor

logger = logging.getLogger("orderer.broadcast")

VALIDATE_DURATION = _m.HistogramOpts(
    namespace="broadcast", name="validate_duration",
    help="The time to validate a broadcast transaction through the "
         "channel's message processor.",
    label_names=("channel", "type", "status"))
ENQUEUE_DURATION = _m.HistogramOpts(
    namespace="broadcast", name="enqueue_duration",
    help="The time to enqueue a validated transaction into the "
         "consenter chain.", label_names=("channel", "type", "status"))
PROCESSED_COUNT = _m.CounterOpts(
    namespace="broadcast", name="processed_count",
    help="The number of broadcast transactions processed.",
    label_names=("channel", "type", "status"))


class BroadcastMetrics:
    """Reference: `orderer/common/broadcast/metrics.go`."""

    def __init__(self, provider=None):
        provider = provider or _m.DisabledProvider()
        self.validate_duration = provider.new_histogram(
            VALIDATE_DURATION)
        self.enqueue_duration = provider.new_histogram(
            ENQUEUE_DURATION)
        self.processed_count = provider.new_counter(PROCESSED_COUNT)


class BroadcastHandler:
    def __init__(self, registrar, metrics: BroadcastMetrics = None):
        self._registrar = registrar
        self.metrics = metrics or BroadcastMetrics()

    def _observe(self, hist_or_counter, channel: str, kind: str,
                 status: int, dur: float = None) -> None:
        inst = hist_or_counter.with_labels(
            "channel", channel, "type", kind,
            "status", common.Status.Name(status))
        inst.observe(dur) if dur is not None else inst.add(1)

    def process_messages(self, envs) -> list:
        """Batched ingest over a window of envelopes: responses are 1:1
        and in order, but consecutive NORMAL envelopes on the same
        channel share one msgprocessor pass (ONE batched
        signature-filter verify) and one consenter enqueue
        (`chain.order_batch`). Config-class envelopes break the run and
        process individually, preserving intra-channel order. The gRPC
        Broadcast stream drains its inbound window through this entry."""
        out: list = [None] * len(envs)
        run: list = []                # (orig index, env)
        run_channel: str = ""
        run_support = None

        def flush():
            nonlocal run, run_support
            if not run:
                return
            idxs = [i for i, _ in run]
            batch = [e for _, e in run]
            for i, resp in zip(idxs, self._process_normal_run(
                    run_channel, run_support, batch)):
                out[i] = resp
            run = []
            run_support = None

        for i, env in enumerate(envs):
            try:
                ch = pu.get_channel_header(pu.get_payload(env))
            except Exception:
                ch = None
            if (ch is None or not ch.channel_id or
                    msgprocessor.classify(ch) != msgprocessor.NORMAL):
                flush()
                out[i] = self.process_message(env)
                continue
            support = self._registrar.get_chain(ch.channel_id)
            if run and (ch.channel_id != run_channel or
                        support is not run_support):
                flush()
            run_channel = ch.channel_id
            run_support = support
            run.append((i, env))
        flush()
        return out

    def _process_normal_run(self, cid: str, support, batch
                            ) -> list:
        """One NORMAL-message run on one channel: batched filters, then
        one enqueue."""
        if support is None:
            # metric coverage must match the unary process_message path
            # (round-4 advisor: the two ingest paths disagreed here)
            for _ in batch:
                self._observe(self.metrics.processed_count, cid,
                              "normal", common.Status.NOT_FOUND)
            return [ordpb.BroadcastResponse(
                status=common.Status.NOT_FOUND,
                info=f"channel {cid} not found")] * len(batch)
        if support.chain.errored():
            resp = ordpb.BroadcastResponse(
                status=common.Status.SERVICE_UNAVAILABLE,
                info="consenter is in an errored state")
            for _ in batch:
                self._observe(self.metrics.processed_count, cid,
                              "normal", resp.status)
            return [resp] * len(batch)

        t0 = time.perf_counter()
        try:
            results = support.processor.process_normal_msgs(batch)
        except OverloadError as e:
            # the batched sig-filter verify was shed (admission-window
            # deadline): the whole run is refused retryably — nothing
            # was enqueued, nothing half-applied
            resp = ordpb.BroadcastResponse(
                status=common.Status.SERVICE_UNAVAILABLE, info=str(e))
            for _ in batch:
                self._observe(self.metrics.processed_count, cid,
                              "normal", resp.status)
            return [resp] * len(batch)
        vdur = (time.perf_counter() - t0) / max(len(batch), 1)
        responses: list = [None] * len(batch)
        accepted: list = []
        for j, (env, (seq, err)) in enumerate(zip(batch, results)):
            if err is None:
                self._observe(self.metrics.validate_duration, cid,
                              "normal", common.Status.SUCCESS, vdur)
                accepted.append((j, env, seq))
                continue
            status = (common.Status.FORBIDDEN
                      if isinstance(err, msgprocessor.PermissionDenied)
                      else common.Status.BAD_REQUEST)
            self._observe(self.metrics.validate_duration, cid,
                          "normal", status, vdur)
            self._observe(self.metrics.processed_count, cid, "normal",
                          status)
            responses[j] = ordpb.BroadcastResponse(status=status,
                                                   info=str(err))
        if accepted:
            t1 = time.perf_counter()
            n_ok = 0
            status, info = common.Status.SUCCESS, ""
            try:
                order_batch = getattr(support.chain, "order_batch",
                                      None)
                if order_batch is not None:
                    n_ok = order_batch([(env, seq)
                                        for _, env, seq in accepted])
                else:
                    for _, env, seq in accepted:
                        support.chain.order(env, seq)
                        n_ok += 1
            except (msgprocessor.MsgProcessorError, OverloadError) as e:
                # MsgProcessorError: transient leadership/halt;
                # OverloadError: the consenter event queue shed past
                # the deadline budget — both retryable, same contract
                status, info = common.Status.SERVICE_UNAVAILABLE, str(e)
            except Exception as e:
                logger.exception("[%s] broadcast failure", cid)
                status, info = common.Status.INTERNAL_SERVER_ERROR, \
                    str(e)
            edur = (time.perf_counter() - t1) / len(accepted)
            if n_ok < len(accepted) and \
                    status == common.Status.SUCCESS:
                status = common.Status.SERVICE_UNAVAILABLE
                info = "leader changed mid-window"
            # a follower forwarding mid-window can deliver a prefix:
            # report those truthfully as SUCCESS, only the rest failed
            for pos, (j, _, _) in enumerate(accepted):
                st = common.Status.SUCCESS if pos < n_ok else status
                inf = "" if pos < n_ok else info
                self._observe(self.metrics.enqueue_duration, cid,
                              "normal", st, edur)
                self._observe(self.metrics.processed_count, cid,
                              "normal", st)
                responses[j] = ordpb.BroadcastResponse(status=st,
                                                       info=inf)
        return responses

    def process_message(self, env: common.Envelope
                        ) -> ordpb.BroadcastResponse:
        """One envelope in, one status out (the gRPC stream layer maps
        this 1:1 — reference broadcast.go Handle loop)."""

        def reject(channel: str, status: int,
                   info: str) -> ordpb.BroadcastResponse:
            # pre-classification rejections count too — a storm of
            # NOT_FOUND/BAD_REQUEST traffic must be visible in
            # broadcast_processed_count (reference records these)
            self._observe(self.metrics.processed_count, channel,
                          "unknown", status)
            return ordpb.BroadcastResponse(status=status, info=info)

        try:
            ch = pu.get_channel_header(pu.get_payload(env))
        except Exception as e:
            return reject("", common.Status.BAD_REQUEST,
                          f"malformed envelope: {e}")
        if not ch.channel_id:
            return reject("", common.Status.BAD_REQUEST,
                          "empty channel id")
        support = self._registrar.get_chain(ch.channel_id)
        if support is None:
            if msgprocessor.classify(ch) == msgprocessor.CONFIG_UPDATE:
                # the reference's system channel would treat this as
                # channel CREATION (msgprocessor/systemchannel.go);
                # this orderer is system-channel-free (the Fabric 3.x
                # direction) — surface the supported path explicitly
                # instead of a bare not-found
                return reject(
                    ch.channel_id, common.Status.NOT_FOUND,
                    f"channel {ch.channel_id} does not exist, and "
                    "channel creation via broadcast config update "
                    "requires a system channel, which this orderer "
                    "does not serve; create the channel through the "
                    "participation API (osnadmin channel join)")
            return reject(ch.channel_id, common.Status.NOT_FOUND,
                          f"channel {ch.channel_id} not found")
        if support.chain.errored():
            return reject(ch.channel_id,
                          common.Status.SERVICE_UNAVAILABLE,
                          "consenter is in an errored state")

        kind = msgprocessor.classify(ch)
        kname = "config" if kind != msgprocessor.NORMAL else "normal"
        cid = ch.channel_id

        def done(status: int, info: str = "",
                 enqueue_t0: float = None) -> ordpb.BroadcastResponse:
            if enqueue_t0 is not None:
                self._observe(self.metrics.enqueue_duration, cid, kname,
                              status, time.perf_counter() - enqueue_t0)
            self._observe(self.metrics.processed_count, cid, kname,
                          status)
            return ordpb.BroadcastResponse(status=status, info=info)

        t0 = time.perf_counter()
        try:
            if kind == msgprocessor.NORMAL:
                seq = support.processor.process_normal_msg(env)
                to_order, configure = env, False
            elif kind == msgprocessor.CONFIG_UPDATE:
                to_order, seq = \
                    support.processor.process_config_update_msg(env)
                configure = True
            else:
                to_order, seq = \
                    support.processor.process_config_msg(env)
                configure = True
        except msgprocessor.PermissionDenied as e:
            self._observe(self.metrics.validate_duration, cid, kname,
                          common.Status.FORBIDDEN,
                          time.perf_counter() - t0)
            return done(common.Status.FORBIDDEN, str(e))
        except OverloadError as e:
            # shed in the sig-filter's admission window: retryable
            self._observe(self.metrics.validate_duration, cid, kname,
                          common.Status.SERVICE_UNAVAILABLE,
                          time.perf_counter() - t0)
            return done(common.Status.SERVICE_UNAVAILABLE, str(e))
        except msgprocessor.MsgProcessorError as e:
            self._observe(self.metrics.validate_duration, cid, kname,
                          common.Status.BAD_REQUEST,
                          time.perf_counter() - t0)
            return done(common.Status.BAD_REQUEST, str(e))
        except Exception as e:
            logger.exception("[%s] broadcast validation failure", cid)
            self._observe(self.metrics.validate_duration, cid, kname,
                          common.Status.INTERNAL_SERVER_ERROR,
                          time.perf_counter() - t0)
            return done(common.Status.INTERNAL_SERVER_ERROR, str(e))
        self._observe(self.metrics.validate_duration, cid, kname,
                      common.Status.SUCCESS, time.perf_counter() - t0)

        t1 = time.perf_counter()
        try:
            if configure:
                support.chain.configure(to_order, seq)
            else:
                support.chain.order(to_order, seq)
        except (msgprocessor.MsgProcessorError, OverloadError) as e:
            # enqueue-side rejections are transient leadership/halt/
            # overload conditions (no leader yet, halted mid-reconfig,
            # forward refused, event queue shed past the deadline
            # budget) — clients should back off and retry (reference:
            # Order on a halted/leaderless chain → SERVICE_UNAVAILABLE)
            return done(common.Status.SERVICE_UNAVAILABLE, str(e),
                        enqueue_t0=t1)
        except Exception as e:
            logger.exception("[%s] broadcast failure", cid)
            return done(common.Status.INTERNAL_SERVER_ERROR, str(e),
                        enqueue_t0=t1)
        return done(common.Status.SUCCESS, enqueue_t0=t1)
