"""Broadcast ingest: classify → process → order.

Rebuild of `orderer/common/broadcast/broadcast.go:66,135`
(Handle/ProcessMessage): each envelope is classified, run through the
channel's msgprocessor (filters + config processing), then handed to
the consenter chain via Order/Configure.
"""

from __future__ import annotations

import logging

from fabric_tpu.protos import common, orderer as ordpb
from fabric_tpu.protoutil import protoutil as pu
from fabric_tpu.orderer import msgprocessor

logger = logging.getLogger("orderer.broadcast")


class BroadcastHandler:
    def __init__(self, registrar):
        self._registrar = registrar

    def process_message(self, env: common.Envelope
                        ) -> ordpb.BroadcastResponse:
        """One envelope in, one status out (the gRPC stream layer maps
        this 1:1 — reference broadcast.go Handle loop)."""
        try:
            ch = pu.get_channel_header(pu.get_payload(env))
        except Exception as e:
            return ordpb.BroadcastResponse(
                status=common.Status.BAD_REQUEST,
                info=f"malformed envelope: {e}")
        if not ch.channel_id:
            return ordpb.BroadcastResponse(
                status=common.Status.BAD_REQUEST,
                info="empty channel id")
        support = self._registrar.get_chain(ch.channel_id)
        if support is None:
            return ordpb.BroadcastResponse(
                status=common.Status.NOT_FOUND,
                info=f"channel {ch.channel_id} not found")
        if support.chain.errored():
            return ordpb.BroadcastResponse(
                status=common.Status.SERVICE_UNAVAILABLE,
                info="consenter is in an errored state")

        kind = msgprocessor.classify(ch)
        try:
            if kind == msgprocessor.NORMAL:
                seq = support.processor.process_normal_msg(env)
                support.chain.order(env, seq)
            else:
                if kind == msgprocessor.CONFIG_UPDATE:
                    wrapped, seq = \
                        support.processor.process_config_update_msg(env)
                else:
                    wrapped, seq = \
                        support.processor.process_config_msg(env)
                support.chain.configure(wrapped, seq)
        except msgprocessor.PermissionDenied as e:
            return ordpb.BroadcastResponse(
                status=common.Status.FORBIDDEN, info=str(e))
        except msgprocessor.MsgProcessorError as e:
            return ordpb.BroadcastResponse(
                status=common.Status.BAD_REQUEST, info=str(e))
        except Exception as e:
            logger.exception("[%s] broadcast failure", ch.channel_id)
            return ordpb.BroadcastResponse(
                status=common.Status.INTERNAL_SERVER_ERROR, info=str(e))
        return ordpb.BroadcastResponse(status=common.Status.SUCCESS)
