"""Orderer-to-orderer cluster communication seam.

Rebuild of `orderer/common/cluster/{comm.go,service.go,rpc.go}`: the
Step RPC carries two payload kinds — SubmitRequest (follower forwards a
tx to the leader) and ConsensusRequest (raft messages) — plus the
block-pulling used for catch-up/onboarding
(`orderer/common/cluster/{replication,deliver}.go`, which the reference
implements over the Deliver API). The interface is transport-agnostic:
`LocalClusterNetwork` is the in-process fabric; the gRPC fabric
(fabric_tpu/comm) exposes the same surface.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Optional

from fabric_tpu.common import clustertrace, tracing
from fabric_tpu.protos import common, orderer as opb

logger = logging.getLogger("orderer.cluster")


class ClusterTransport:
    """What a consenter chain needs from the cluster fabric."""

    endpoint: str

    def send_consensus(self, target: str, channel: str,
                       payload: bytes) -> None:
        raise NotImplementedError

    def submit(self, target: str, channel: str, env_bytes: bytes,
               config_seq: int = 0) -> opb.SubmitResponse:
        """Forward an envelope to the leader. `config_seq` is the
        channel-config sequence the ORIGIN validated the message under
        (reference SubmitRequest.last_validation_seq): the leader
        re-validates when its own sequence is newer."""
        raise NotImplementedError

    def pull_blocks(self, target: str, channel: str, start: int,
                    end: int) -> list[common.Block]:
        raise NotImplementedError

    def set_handler(self, channel: str, handler) -> None:
        """handler duck-type: on_consensus(sender, payload_bytes),
        on_submit(env_bytes, config_seq) -> SubmitResponse,
        serve_blocks(start, end) -> list[Block]."""
        raise NotImplementedError

    def set_channel_auth(self, channel: str,
                         client_certs: dict[str, bytes]) -> None:
        """Register {consenter endpoint -> client TLS cert PEM} for a
        channel so the inbound half can authenticate cluster callers
        (reference: `orderer/common/cluster/comm.go` binds the mTLS
        client cert to the channel's consenter set). Transports without
        a network boundary (in-process) need no enforcement."""

    def close(self) -> None:
        raise NotImplementedError


class LocalClusterTransport(ClusterTransport):
    def __init__(self, network: "LocalClusterNetwork", endpoint: str):
        self.endpoint = endpoint
        self._net = network
        self._handlers: dict[str, object] = {}
        self._inbox: queue.Queue = queue.Queue(maxsize=4096)
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._drain, name=f"cluster-{endpoint}", daemon=True)
        self._thread.start()

    def set_handler(self, channel: str, handler) -> None:
        self._handlers[channel] = handler

    def remove_handler(self, channel: str) -> None:
        self._handlers.pop(channel, None)

    # -- outbound (round 18: every cross-node send carries the wire
    # trace carrier — framed into the opaque payloads, side-band on
    # the argument-only pull RPC) --

    def send_consensus(self, target: str, channel: str,
                       payload: bytes) -> None:
        self._net.route_consensus(self.endpoint, target, channel,
                                  clustertrace.inject(payload))

    def submit(self, target: str, channel: str, env_bytes: bytes,
               config_seq: int = 0) -> opb.SubmitResponse:
        return self._net.route_submit(self.endpoint, target, channel,
                                      clustertrace.inject(env_bytes),
                                      config_seq)

    def pull_blocks(self, target: str, channel: str, start: int,
                    end: int) -> list[common.Block]:
        return self._net.route_pull(
            self.endpoint, target, channel, start, end,
            carrier=clustertrace.capture_carrier())

    # -- inbound (async consensus path only; submit/pull are RPCs) --

    def enqueue_consensus(self, sender: str, channel: str,
                          payload: bytes) -> None:
        try:
            self._inbox.put_nowait((sender, channel, payload))
        except queue.Full:
            logger.warning("[%s] cluster inbox full; dropping raft msg",
                           self.endpoint)

    def _drain(self) -> None:
        # extraction seam (round 18): the remote worker resumes the
        # SENDER's span tree under this node's id — a raft APPEND
        # carries its proposing window's trace across the hop instead
        # of opening an orphan (or no) trace here
        tracing.set_node(self.endpoint)
        while not self._closed.is_set():
            try:
                sender, channel, payload = self._inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            handler = self._handlers.get(channel)
            if handler is None:
                continue
            payload, carrier = clustertrace.extract(payload)
            try:
                with clustertrace.resumed(
                        carrier, link=f"{sender}>{self.endpoint}",
                        node=self.endpoint):
                    handler.on_consensus(sender, payload)
            except Exception:
                logger.exception("[%s] consensus handler failed",
                                 self.endpoint)

    def handle_submit(self, channel: str, env_bytes: bytes,
                      config_seq: int = 0) -> opb.SubmitResponse:
        handler = self._handlers.get(channel)
        env_bytes, carrier = clustertrace.extract(env_bytes)
        if handler is None:
            return opb.SubmitResponse(
                channel=channel,
                status=common.Status.NOT_FOUND,
                info=f"channel {channel} not served here")
        with clustertrace.resumed(carrier,
                                  link=f"submit>{self.endpoint}",
                                  node=self.endpoint):
            return handler.on_submit(env_bytes, config_seq)

    def handle_pull(self, channel: str, start: int, end: int,
                    carrier=None) -> list[common.Block]:
        handler = self._handlers.get(channel)
        if handler is None:
            return []
        with clustertrace.resumed(carrier,
                                  link=f"pull>{self.endpoint}",
                                  node=self.endpoint):
            return handler.serve_blocks(start, end)

    def close(self) -> None:
        self._closed.set()
        self._net.unregister(self.endpoint)
        self._thread.join(timeout=2)


class LocalClusterNetwork:
    """In-proc cluster fabric with partitions (crash-fault tests)."""

    def __init__(self):
        self._nodes: dict[str, LocalClusterTransport] = {}
        self._lock = threading.Lock()
        self._down: set[str] = set()
        self._partitions: set[frozenset] = set()

    def register(self, endpoint: str) -> LocalClusterTransport:
        t = LocalClusterTransport(self, endpoint)
        with self._lock:
            self._nodes[endpoint] = t
            self._down.discard(endpoint)
        return t

    def unregister(self, endpoint: str) -> None:
        with self._lock:
            self._nodes.pop(endpoint, None)

    # fault injection
    def take_down(self, endpoint: str) -> None:
        with self._lock:
            self._down.add(endpoint)

    def bring_up(self, endpoint: str) -> None:
        with self._lock:
            self._down.discard(endpoint)

    def partition(self, a: str, b: str) -> None:
        with self._lock:
            self._partitions.add(frozenset((a, b)))

    def heal(self) -> None:
        with self._lock:
            self._partitions.clear()

    def _reachable(self, sender: str, target: str) -> Optional[
            LocalClusterTransport]:
        with self._lock:
            if sender in self._down or target in self._down:
                return None
            if frozenset((sender, target)) in self._partitions:
                return None
            return self._nodes.get(target)

    def route_consensus(self, sender: str, target: str, channel: str,
                        payload: bytes) -> None:
        node = self._reachable(sender, target)
        if node is not None:
            node.enqueue_consensus(sender, channel, payload)
            return
        with self._lock:
            known = target in self._nodes
        if not known:
            # an UNREGISTERED/removed endpoint is a dead address, not
            # transient loss: raise like the submit/pull paths do
            # (PR-3 rule — cluster transports RAISE on unreachable),
            # so a caller holding a stale consenter table hears about
            # it instead of silently heartbeating a ghost. A node
            # that is merely down/partitioned still drops silently:
            # that is network loss, and raft retransmission owns it.
            raise ConnectionError(
                f"{target} unreachable from {sender}: not registered")

    def route_submit(self, sender: str, target: str, channel: str,
                     env_bytes: bytes,
                     config_seq: int = 0) -> opb.SubmitResponse:
        node = self._reachable(sender, target)
        if node is None:
            return opb.SubmitResponse(
                channel=channel,
                status=common.Status.SERVICE_UNAVAILABLE,
                info=f"{target} unreachable")
        return node.handle_submit(channel, env_bytes, config_seq)

    def route_pull(self, sender: str, target: str, channel: str,
                   start: int, end: int,
                   carrier=None) -> list[common.Block]:
        node = self._reachable(sender, target)
        if node is None:
            # a dead source must be DISTINGUISHABLE from one that has
            # no blocks to serve: the onboarding replicator fails over
            # on transport errors but treats an empty result at the
            # tip as quiescence
            raise ConnectionError(f"{target} unreachable from {sender}")
        return node.handle_pull(channel, start, end, carrier=carrier)
