"""Channel participation admin API (system-channel-less operation).

Rebuild of `orderer/common/channelparticipation/` — the operator API
behind `osnadmin channel {join,list,remove}`: join a channel from a
config block (genesis, or a later config block → onboarding/follower
mode), list channels with their consensus relation and height, remove
a channel. The HTTP surface rides on the operations server
(fabric_tpu/node); this module is the transport-free core.
"""

from __future__ import annotations

import logging

from fabric_tpu.protos import common, orderer as opb
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("orderer.channelparticipation")


class ParticipationError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class ChannelParticipation:
    def __init__(self, registrar):
        self._registrar = registrar

    def join(self, config_block_bytes: bytes) -> opb.ChannelInfo:
        try:
            block = common.Block()
            block.ParseFromString(config_block_bytes)
        except Exception as e:
            raise ParticipationError(400, f"invalid config block: {e}")
        if not pu.is_config_block(block):
            raise ParticipationError(
                400, "the submitted block is not a config block")
        try:
            support = self._registrar.join(block)
        except ValueError as e:
            msg = str(e)
            status = 405 if "already exists" in msg else 400
            raise ParticipationError(status, msg)
        return self.info(support.channel_id)

    def list(self) -> opb.ChannelList:
        out = opb.ChannelList()
        for name in self._registrar.channel_list():
            out.channels.append(self.info(name))
        return out

    def info(self, channel_id: str) -> opb.ChannelInfo:
        support = self._registrar.get_chain(channel_id)
        if support is None:
            raise ParticipationError(
                404, f"channel {channel_id} does not exist")
        relation = "consenter"
        chain = support.chain
        if type(chain).__name__ == "FollowerChain":
            relation = "follower"
        return opb.ChannelInfo(
            name=channel_id,
            consensus_relation=relation,
            status="active" if not chain.errored() else "inactive",
            height=support.ledger.height)

    def remove(self, channel_id: str) -> None:
        if self._registrar.get_chain(channel_id) is None:
            raise ParticipationError(
                404, f"channel {channel_id} does not exist")
        self._registrar.remove(channel_id)
        logger.info("channel %s removed", channel_id)
