"""Offline operator commands on a STOPPED peer's ledger data.

Rebuild of `internal/peer/node/{reset,rollback,rebuild_dbs,unjoin}.go`:
  rebuild_dbs  drop the derived DBs (state/history/pvt bookkeeping);
               the next start replays them from the block store
  rollback     truncate a channel to a target height, then drop the
               derived DBs so replay reconstructs exactly that prefix
  reset        rollback every channel to height 1 (genesis only)
  unjoin       remove a channel's ledger entirely

All of these refuse to run while the data dir looks live is the
operator's responsibility (the reference takes a file lock; a stopped
process is assumed here).
"""

from __future__ import annotations

import os
import shutil

from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.ledger.kvdb import DBHandle, KVStore

logger = must_get_logger("nodeops")

# keyspaces derived from the block store (rebuilt by replay on start)
_DERIVED = ("statedb", "historydb", "confighist", "pvtstore",
            "blkindex")
# droppable + rebuilt by replay: dropping statedb resets the savepoint,
# so the next open replays every block — re-running MVCC, history and
# the state listeners (which rebuild confighist)
_REBUILD_ONLY = ("statedb", "historydb", "confighist")


def _channels(ledger_root: str) -> list[str]:
    if not os.path.isdir(ledger_root):
        return []
    return [d for d in sorted(os.listdir(ledger_root))
            if os.path.isdir(os.path.join(ledger_root, d, "chains"))]


def _drop_keyspaces(kv: KVStore, names) -> None:
    for name in names:
        db = DBHandle(kv, name)
        batch = db.new_batch()
        for k, _v in db.iterate():
            batch.delete(k)
        if batch.ops:
            db.write_batch(batch)


def rebuild_dbs(ledger_root: str) -> list[str]:
    """Drop state+history everywhere; keep blocks + committed pvt
    cleartext (reference rebuild-dbs keeps pvtdata store too)."""
    done = []
    for channel in _channels(ledger_root):
        path = os.path.join(ledger_root, channel, "index.db")
        kv = KVStore(path)
        _drop_keyspaces(kv, _REBUILD_ONLY)
        kv.close()
        done.append(channel)
        logger.info("dropped derived DBs for %s", channel)
    return done


def upgrade_dbs(ledger_root: str) -> list[str]:
    """Migrate ledgers whose derived DBs were written by an older
    binary (reference: `internal/peer/node/upgrade_dbs.go`): drop the
    format-bound keyspaces and stamp the current data format; the next
    `peer node start` replays them from the block store in the new
    encoding. Ledgers already at the current format are untouched."""
    from fabric_tpu.ledger.kvledger import KVLedger

    done = []
    for channel in _channels(ledger_root):
        path = os.path.join(ledger_root, channel, "index.db")
        kv = KVStore(path)
        meta = DBHandle(kv, "ledgermeta")
        fmt = meta.get(b"datafmt") or b"1.0"
        if fmt == KVLedger.DATA_FORMAT:
            kv.close()
            logger.info("%s already at data format %s", channel,
                        fmt.decode())
            continue
        # a snapshot-bootstrapped channel has no blocks before the
        # boundary — dropping its statedb would destroy state that can
        # NEVER be replayed locally (rollback() guards the same edge)
        store = BlockStore(os.path.join(ledger_root, channel),
                           DBHandle(kv, "blkindex"))
        first = store.first_block
        store.close()
        if first > 0:
            kv.close()
            logger.warning(
                "%s was bootstrapped from a snapshot (first local "
                "block %d): cannot upgrade in place — unjoin and "
                "re-join from a snapshot taken by an upgraded peer",
                channel, first)
            continue
        _drop_keyspaces(kv, _REBUILD_ONLY)
        meta.put(b"datafmt", KVLedger.DATA_FORMAT)
        kv.close()
        done.append(channel)
        logger.info("upgraded %s: %s -> %s (derived DBs dropped for "
                    "replay)", channel, fmt.decode(),
                    KVLedger.DATA_FORMAT.decode())
    return done


def rollback(ledger_root: str, channel: str, target_height: int) -> None:
    """Truncate `channel` to `target_height` blocks; derived DBs are
    dropped for full replay (includes the pvt store: cleartext above
    the target must not survive)."""
    path = os.path.join(ledger_root, channel)
    if not os.path.isdir(path):
        raise ValueError(f"channel {channel!r} does not exist")
    kv = KVStore(os.path.join(path, "index.db"))
    store = BlockStore(path, DBHandle(kv, "blkindex"))
    if target_height >= store.height:
        store.close()
        kv.close()
        raise ValueError(
            f"target height {target_height} >= current "
            f"{store.height}")
    if store.first_block > 0 and target_height <= store.first_block:
        store.close()
        kv.close()
        raise ValueError("cannot roll back past the snapshot boundary")
    store.truncate_to(target_height)
    store.close()
    _drop_keyspaces(kv, ("statedb", "historydb", "snapshotreq"))
    # pvt cleartext below the target must SURVIVE (replay re-applies it
    # from the pvt store; it cannot be refetched from blocks) — prune
    # only entries at/above the target
    import struct
    pvtdb = DBHandle(kv, "pvtstore")
    batch = pvtdb.new_batch()
    for k, _v in pvtdb.iterate():
        tag = k[:1]
        if tag in (b"d", b"m"):
            (block_num,) = struct.unpack_from(">Q", k, 1)
            if block_num >= target_height:
                batch.delete(k)
        elif tag == b"e":
            _exp, written = struct.unpack_from(">QQ", k, 1)
            if written >= target_height:
                batch.delete(k)
    if batch.ops:
        pvtdb.write_batch(batch)
    kv.close()
    logger.info("rolled %s back to height %d", channel, target_height)


def reset(ledger_root: str) -> list[str]:
    """Every channel back to its genesis block (reference reset.go)."""
    done = []
    for channel in _channels(ledger_root):
        try:
            rollback(ledger_root, channel, 1)
            done.append(channel)
        except ValueError as e:
            logger.warning("reset skipped %s: %s", channel, e)
    return done


_PAUSED = "_paused"


def pause(ledger_root: str, channel: str) -> None:
    """Mark a channel paused on a stopped peer: the next start skips it
    entirely (reference: `internal/peer/node/pause.go`)."""
    path = os.path.join(ledger_root, channel)
    if not os.path.isdir(path):
        raise ValueError(f"channel {channel!r} does not exist")
    with open(os.path.join(path, _PAUSED), "w"):
        pass
    logger.info("paused %s", channel)


def resume(ledger_root: str, channel: str) -> None:
    """Reference: `internal/peer/node/resume.go`."""
    marker = os.path.join(ledger_root, channel, _PAUSED)
    if not os.path.exists(marker):
        raise ValueError(f"channel {channel!r} is not paused")
    os.remove(marker)
    logger.info("resumed %s", channel)


def is_paused(ledger_root: str, channel: str) -> bool:
    return os.path.exists(os.path.join(ledger_root, channel, _PAUSED))


def unjoin(ledger_root: str, channel: str) -> None:
    path = os.path.join(ledger_root, channel)
    if not os.path.isdir(path):
        raise ValueError(f"channel {channel!r} does not exist")
    shutil.rmtree(path)
    logger.info("unjoined %s", channel)
