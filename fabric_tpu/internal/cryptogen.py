"""Test/dev crypto material generator.

Rebuild of `internal/cryptogen/` (`ca/`, `csp/`, `msp/` generators +
the cobra CLI): emits the canonical MSP directory layout for orgs,
their nodes, and users —

    <out>/peerOrganizations/<domain>/
        ca/ca.<domain>-cert.pem, ca-key.pem
        msp/cacerts/…                    (org-level verification MSP)
        peers/<peer>.<domain>/msp/{cacerts,signcerts,keystore}
        users/{Admin,User1…}@<domain>/msp/…

NodeOU classification is on by default: node certs carry OU=peer /
OU=orderer, user certs OU=client / OU=admin, and each MSP dir gets a
config.yaml enabling NodeOUs — mirroring cryptogen's output.
"""

from __future__ import annotations

import datetime
import os

# gated import: without the `cryptography` wheel this module still
# imports; cert GENERATION then raises MissingCryptographyError at
# call time (there is no honest pure-python x509 builder)
from fabric_tpu.bccsp._crypto_compat import (
    NameOID,
    ec,
    hashes,
    serialization,
    x509,
)

_NOT_BEFORE = datetime.datetime(2020, 1, 1)
_NOT_AFTER = datetime.datetime(2099, 1, 1)

_NODE_OU_CONFIG = """NodeOUs:
  Enable: true
  ClientOUIdentifier:
    OrganizationalUnitIdentifier: client
  PeerOUIdentifier:
    OrganizationalUnitIdentifier: peer
  AdminOUIdentifier:
    OrganizationalUnitIdentifier: admin
  OrdererOUIdentifier:
    OrganizationalUnitIdentifier: orderer
"""


def _pem_cert(cert) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def _pem_key(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def _make_ca(cn: str, org: str):
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([
        x509.NameAttribute(NameOID.COMMON_NAME, cn),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
    ])
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_NOT_BEFORE).not_valid_after(_NOT_AFTER)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .add_extension(
            x509.KeyUsage(digital_signature=True, content_commitment=False,
                          key_encipherment=False, data_encipherment=False,
                          key_agreement=False, key_cert_sign=True,
                          crl_sign=True, encipher_only=False,
                          decipher_only=False),
            critical=True)
        .sign(key, hashes.SHA256())
    )
    return cert, key


def _issue(cn: str, org: str, ou: str, ca_cert, ca_key):
    key = ec.generate_private_key(ec.SECP256R1())
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([
            x509.NameAttribute(NameOID.COMMON_NAME, cn),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
            x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, ou),
        ]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_NOT_BEFORE).not_valid_after(_NOT_AFTER)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    return cert, key


def _issue_tls(cn: str, org: str, ca_cert, ca_key,
               sans: list[str] = ()):
    """TLS server/client cert with SANs (gRPC verifies the hostname —
    dev networks dial 127.0.0.1/localhost)."""
    import ipaddress
    key = ec.generate_private_key(ec.SECP256R1())
    alt_names = [x509.DNSName(cn), x509.DNSName("localhost")]
    alt_names.append(x509.IPAddress(
        ipaddress.IPv4Address("127.0.0.1")))
    for san in sans:
        alt_names.append(x509.DNSName(san))
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([
            x509.NameAttribute(NameOID.COMMON_NAME, cn),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
        ]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_NOT_BEFORE).not_valid_after(_NOT_AFTER)
        .add_extension(x509.BasicConstraints(ca=False,
                                             path_length=None),
                       critical=True)
        .add_extension(x509.SubjectAlternativeName(alt_names),
                       critical=False)
        .add_extension(x509.ExtendedKeyUsage(
            [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH,
             x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]),
            critical=False)
        .sign(ca_key, hashes.SHA256())
    )
    return cert, key


def _write_tls_dir(node_dir: str, cn: str, domain: str, tlsca_cert,
                   tlsca_key) -> None:
    """The reference layout: <node>/tls/{ca.crt,server.crt,server.key}."""
    cert, key = _issue_tls(cn, domain, tlsca_cert, tlsca_key)
    _write(os.path.join(node_dir, "tls", "ca.crt"),
           _pem_cert(tlsca_cert))
    _write(os.path.join(node_dir, "tls", "server.crt"),
           _pem_cert(cert))
    _write(os.path.join(node_dir, "tls", "server.key"), _pem_key(key))


def _write_local_msp(msp_dir: str, ca_cert, cert, key) -> None:
    """A node/user MSP dir: its own cert + key + the org's CA."""
    _write(os.path.join(msp_dir, "cacerts", "ca-cert.pem"),
           _pem_cert(ca_cert))
    _write(os.path.join(msp_dir, "signcerts", "cert.pem"), _pem_cert(cert))
    _write(os.path.join(msp_dir, "keystore", "key_sk"), _pem_key(key))
    _write(os.path.join(msp_dir, "config.yaml"),
           _NODE_OU_CONFIG.encode())


def generate_org(out_dir: str, domain: str, n_peers: int = 1,
                 n_users: int = 1, orderer_org: bool = False,
                 n_orderers: int = 1) -> str:
    """Generate one organization; returns its directory. Reference:
    cryptogen `generate` with one OrgSpec."""
    kind = "ordererOrganizations" if orderer_org else "peerOrganizations"
    org_dir = os.path.join(out_dir, kind, domain)
    ca_cert, ca_key = _make_ca(f"ca.{domain}", domain)

    _write(os.path.join(org_dir, "ca", f"ca.{domain}-cert.pem"),
           _pem_cert(ca_cert))
    _write(os.path.join(org_dir, "ca", "ca-key.pem"), _pem_key(ca_key))

    # dedicated TLS CA (reference: cryptogen emits tlsca/ + per-node tls/)
    tlsca_cert, tlsca_key = _make_ca(f"tlsca.{domain}", domain)
    _write(os.path.join(org_dir, "tlsca", f"tlsca.{domain}-cert.pem"),
           _pem_cert(tlsca_cert))

    # org-level (channel) MSP: verification material only
    _write(os.path.join(org_dir, "msp", "cacerts", "ca-cert.pem"),
           _pem_cert(ca_cert))
    _write(os.path.join(org_dir, "msp", "tlscacerts",
                        f"tlsca.{domain}-cert.pem"),
           _pem_cert(tlsca_cert))
    _write(os.path.join(org_dir, "msp", "config.yaml"),
           _NODE_OU_CONFIG.encode())

    if orderer_org:
        for i in range(n_orderers):
            cn = f"orderer{i}.{domain}"
            node_dir = os.path.join(org_dir, "orderers", cn)
            cert, key = _issue(cn, domain, "orderer", ca_cert, ca_key)
            _write_local_msp(os.path.join(node_dir, "msp"),
                             ca_cert, cert, key)
            _write_tls_dir(node_dir, cn, domain, tlsca_cert, tlsca_key)
    else:
        for i in range(n_peers):
            cn = f"peer{i}.{domain}"
            node_dir = os.path.join(org_dir, "peers", cn)
            cert, key = _issue(cn, domain, "peer", ca_cert, ca_key)
            _write_local_msp(os.path.join(node_dir, "msp"),
                             ca_cert, cert, key)
            _write_tls_dir(node_dir, cn, domain, tlsca_cert, tlsca_key)

    admin_cn = f"Admin@{domain}"
    cert, key = _issue(admin_cn, domain, "admin", ca_cert, ca_key)
    _write_local_msp(os.path.join(org_dir, "users", admin_cn, "msp"),
                     ca_cert, cert, key)
    # admins also listed explicitly for MSPs with NodeOUs off
    _write(os.path.join(org_dir, "msp", "admincerts", "admin-cert.pem"),
           _pem_cert(cert))
    _write(os.path.join(org_dir, "users", admin_cn, "msp",
                        "admincerts", "admin-cert.pem"), _pem_cert(cert))

    for i in range(1, n_users + 1):
        user_cn = f"User{i}@{domain}"
        cert, key = _issue(user_cn, domain, "client", ca_cert, ca_key)
        _write_local_msp(os.path.join(org_dir, "users", user_cn, "msp"),
                         ca_cert, cert, key)
    return org_dir
