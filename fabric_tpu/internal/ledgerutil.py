"""ledgerutil: offline block-store comparison and verification.

Rebuild of `internal/ledgerutil` + `cmd/ledgerutil` (SURVEY §2.5):
  verify   walk a channel's chain checking the hash links, data
           hashes and index consistency
  compare  diff two peers' copies of a channel; reports the first
           divergent block and per-tx validation-code differences
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.protos import common


def _open_store(ledger_root: str, channel: str):
    path = os.path.join(ledger_root, channel)
    if not os.path.isdir(path):
        raise ValueError(f"channel {channel!r} not found under "
                         f"{ledger_root}")
    kv = KVStore(os.path.join(path, "index.db"))
    return BlockStore(path, DBHandle(kv, "blkindex")), kv


@dataclass
class VerifyResult:
    height: int = 0
    errors: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


def verify(ledger_root: str, channel: str) -> VerifyResult:
    store, kv = _open_store(ledger_root, channel)
    res = VerifyResult(height=store.height)
    prev_hash = b""
    try:
        for num in range(store.first_block, store.height):
            block = store.get_block_by_number(num)
            if block is None:
                res.errors.append(f"block {num} missing")
                break
            if block.header.number != num:
                res.errors.append(
                    f"block {num}: header number "
                    f"{block.header.number}")
            if num > store.first_block and \
                    block.header.previous_hash != prev_hash:
                res.errors.append(f"block {num}: previous_hash broken")
            data_hash = pu.block_data_hash(block.data)
            if block.header.data_hash != data_hash:
                res.errors.append(f"block {num}: data hash mismatch")
            by_hash = store.get_block_by_hash(
                pu.block_header_hash(block.header))
            if by_hash is None or by_hash.header.number != num:
                res.errors.append(f"block {num}: hash index broken")
            prev_hash = pu.block_header_hash(block.header)
    finally:
        store.close()
        kv.close()
    return res


@dataclass
class CompareResult:
    common_height: int = 0
    heights: tuple = (0, 0)
    first_divergence: Optional[int] = None
    tx_filter_diffs: list[int] = field(default_factory=list)

    @property
    def identical_prefix(self) -> bool:
        return self.first_divergence is None and not self.tx_filter_diffs


def compare(root_a: str, root_b: str, channel: str) -> CompareResult:
    sa, ka = _open_store(root_a, channel)
    sb, kb = _open_store(root_b, channel)
    res = CompareResult(heights=(sa.height, sb.height))
    res.common_height = min(sa.height, sb.height)
    try:
        for num in range(max(sa.first_block, sb.first_block),
                         res.common_height):
            a = sa.get_block_by_number(num)
            b = sb.get_block_by_number(num)
            ha = pu.block_header_hash(a.header)
            hb = pu.block_header_hash(b.header)
            if ha != hb:
                res.first_divergence = num
                break
            fa = a.metadata.metadata[
                common.BlockMetadataIndex.TRANSACTIONS_FILTER]
            fb = b.metadata.metadata[
                common.BlockMetadataIndex.TRANSACTIONS_FILTER]
            if bytes(fa) != bytes(fb):
                res.tx_filter_diffs.append(num)
    finally:
        sa.close()
        ka.close()
        sb.close()
        kb.close()
    return res
