"""Channel-config tree encoder: profile → ConfigGroup tree.

Rebuild of `internal/configtxgen/encoder/encoder.go`: turns a
configtx.yaml-style profile (here: a plain dict, loadable from YAML)
into the `Config.channel_group` tree the Bundle parses. Org policies
default to the standard member/admin signature policies when the
profile omits them (the reference requires them spelled out; defaulting
keeps test profiles short).

Profile shape (all sections optional except one of Application/Orderer):

    {
      "Consortium": "SampleConsortium",
      "Capabilities": {"V2_0": True},            # channel level
      "Application": {
          "Organizations": [org, ...],
          "Capabilities": {"V2_0": True},
          "ACLs": {"event/Block": "/Channel/Application/Readers"},
          "Policies": {name: policy-spec, ...},
      },
      "Orderer": {
          "OrdererType": "solo" | "raft",
          "Addresses": ["host:port", ...],
          "BatchTimeout": "2s",
          "BatchSize": {"MaxMessageCount": 500, ...},
          "Organizations": [org, ...],
          "Raft": {"Consenters": [{"Host","Port","ClientTLSCert",
                    "ServerTLSCert"}, ...], "Options": {...}},
      },
    }

org shape: {"Name", "ID" (mspid), "MSPConfig" (ftpu.msp.MSPConfig) or
"MSPDir", "AnchorPeers": [("host", port)], "OrdererEndpoints": [...],
"Policies": {...}}.

policy-spec: either a policydsl string (signature policy) or
{"Type": "ImplicitMeta", "Rule": "MAJORITY Admins"}.
"""

from __future__ import annotations

from typing import Optional

from fabric_tpu.common.channelconfig import bundle as bkeys
from fabric_tpu.common.policies import from_string
from fabric_tpu.protos import configtx as ctxpb, policies as polpb

ADMINS_POLICY_KEY = "Admins"
READERS_POLICY_KEY = "Readers"
WRITERS_POLICY_KEY = "Writers"


def _set_value(group: ctxpb.ConfigGroup, key: str, msg,
               mod_policy: str = ADMINS_POLICY_KEY) -> None:
    cv = group.values[key]
    cv.value = msg.SerializeToString(deterministic=True)
    cv.mod_policy = mod_policy


def _set_policy(group: ctxpb.ConfigGroup, name: str, spec,
                mod_policy: str = ADMINS_POLICY_KEY) -> None:
    cp = group.policies[name]
    cp.mod_policy = mod_policy
    if isinstance(spec, str):
        env = from_string(spec)
        cp.policy.type = polpb.Policy.SIGNATURE
        cp.policy.value = env.SerializeToString(deterministic=True)
    elif isinstance(spec, dict) and spec.get("Type") == "ImplicitMeta":
        rule_s, sub = spec["Rule"].split(None, 1)
        meta = polpb.ImplicitMetaPolicy(
            sub_policy=sub,
            rule=polpb.ImplicitMetaPolicy.Rule.Value(rule_s.upper()))
        cp.policy.type = polpb.Policy.IMPLICIT_META
        cp.policy.value = meta.SerializeToString(deterministic=True)
    else:
        raise ValueError(f"bad policy spec for {name!r}: {spec!r}")


def default_org_policies(mspid: str) -> dict:
    return {
        READERS_POLICY_KEY: f"OR('{mspid}.member')",
        WRITERS_POLICY_KEY: f"OR('{mspid}.member')",
        ADMINS_POLICY_KEY: f"OR('{mspid}.admin')",
        "Endorsement": f"OR('{mspid}.member')",
    }


def _implicit(rule: str, sub: str) -> dict:
    return {"Type": "ImplicitMeta", "Rule": f"{rule} {sub}"}


def new_org_group(org: dict, orderer_org: bool = False) -> ctxpb.ConfigGroup:
    g = ctxpb.ConfigGroup()
    g.mod_policy = ADMINS_POLICY_KEY
    mspid = org["ID"]
    msp_config = org.get("MSPConfig")
    if msp_config is None:
        from fabric_tpu.msp import msp_config_from_dir
        msp_config = msp_config_from_dir(org["MSPDir"], mspid)
    _set_value(g, bkeys.MSP_KEY, ctxpb.MSPValue(
        config=msp_config.SerializeToString(deterministic=True)))
    policies = dict(default_org_policies(mspid))
    policies.update(org.get("Policies") or {})
    for name, spec in policies.items():
        _set_policy(g, name, spec)
    if not orderer_org and org.get("AnchorPeers"):
        anchors = ctxpb.AnchorPeers()
        for host, port in org["AnchorPeers"]:
            anchors.anchor_peers.add(host=host, port=port)
        _set_value(g, bkeys.ANCHOR_PEERS_KEY, anchors)
    if orderer_org and org.get("OrdererEndpoints"):
        _set_value(g, bkeys.ENDPOINTS_KEY, ctxpb.OrdererAddresses(
            addresses=org["OrdererEndpoints"]))
    return g


def _capabilities_value(group, spec: Optional[dict]) -> None:
    if not spec:
        return
    cap = ctxpb.Capabilities()
    for name, on in spec.items():
        if on:
            cap.capabilities[name] = True
    _set_value(group, bkeys.CAPABILITIES_KEY, cap)


def new_application_group(app: dict) -> ctxpb.ConfigGroup:
    g = ctxpb.ConfigGroup()
    g.mod_policy = ADMINS_POLICY_KEY
    for org in app.get("Organizations", []):
        g.groups[org["Name"]].CopyFrom(new_org_group(org))
    policies = {
        READERS_POLICY_KEY: _implicit("ANY", "Readers"),
        WRITERS_POLICY_KEY: _implicit("ANY", "Writers"),
        ADMINS_POLICY_KEY: _implicit("MAJORITY", "Admins"),
        "Endorsement": _implicit("MAJORITY", "Endorsement"),
        "LifecycleEndorsement": _implicit("MAJORITY", "Endorsement"),
    }
    policies.update(app.get("Policies") or {})
    for name, spec in policies.items():
        _set_policy(g, name, spec)
    _capabilities_value(g, app.get("Capabilities"))
    if app.get("ACLs"):
        acls = ctxpb.ACLs()
        for k, v in app["ACLs"].items():
            acls.acls[k] = v
        _set_value(g, bkeys.ACLS_KEY, acls)
    return g


def new_orderer_group(ord_cfg: dict) -> ctxpb.ConfigGroup:
    g = ctxpb.ConfigGroup()
    g.mod_policy = ADMINS_POLICY_KEY
    for org in ord_cfg.get("Organizations", []):
        g.groups[org["Name"]].CopyFrom(new_org_group(org, orderer_org=True))
    policies = {
        READERS_POLICY_KEY: _implicit("ANY", "Readers"),
        WRITERS_POLICY_KEY: _implicit("ANY", "Writers"),
        ADMINS_POLICY_KEY: _implicit("MAJORITY", "Admins"),
        "BlockValidation": _implicit("ANY", "Writers"),
    }
    policies.update(ord_cfg.get("Policies") or {})
    for name, spec in policies.items():
        _set_policy(g, name, spec)

    ctype = ord_cfg.get("OrdererType", "solo")
    consensus = ctxpb.ConsensusType(type=ctype)
    if ctype in ("raft", "etcdraft"):
        raft = ord_cfg.get("Raft") or {}
        meta = ctxpb.ConsensusMetadata()
        def _cert(c, key):
            """PEM bytes, or a file path as in the reference's
            configtx.yaml Consenters (ClientTLSCert: path)."""
            v = c.get(key, b"")
            if isinstance(v, str) and v:
                with open(v, "rb") as f:
                    return f.read()
            return v or b""

        for c in raft.get("Consenters", []):
            meta.consenters.add(
                host=c["Host"], port=c["Port"],
                client_tls_cert=_cert(c, "ClientTLSCert"),
                server_tls_cert=_cert(c, "ServerTLSCert"))
        opts = raft.get("Options") or {}
        meta.options.tick_interval_ms = opts.get("TickIntervalMs", 500)
        meta.options.election_tick = opts.get("ElectionTick", 10)
        meta.options.heartbeat_tick = opts.get("HeartbeatTick", 1)
        meta.options.max_inflight_blocks = opts.get("MaxInflightBlocks", 5)
        meta.options.snapshot_interval_size = opts.get(
            "SnapshotIntervalSize", 16 * 1024 * 1024)
        consensus.metadata = meta.SerializeToString(deterministic=True)
    _set_value(g, bkeys.CONSENSUS_TYPE_KEY, consensus)

    bs = ord_cfg.get("BatchSize") or {}
    _set_value(g, bkeys.BATCH_SIZE_KEY, ctxpb.BatchSize(
        max_message_count=bs.get("MaxMessageCount", 500),
        absolute_max_bytes=bs.get("AbsoluteMaxBytes", 10 * 1024 * 1024),
        preferred_max_bytes=bs.get("PreferredMaxBytes", 2 * 1024 * 1024)))
    _set_value(g, bkeys.BATCH_TIMEOUT_KEY, ctxpb.BatchTimeout(
        timeout=ord_cfg.get("BatchTimeout", "2s")))
    _capabilities_value(g, ord_cfg.get("Capabilities"))
    return g


def new_channel_group(profile: dict) -> ctxpb.ConfigGroup:
    """Reference: `encoder.go` NewChannelGroup."""
    root = ctxpb.ConfigGroup()
    root.mod_policy = ADMINS_POLICY_KEY
    for name, spec in {
        READERS_POLICY_KEY: _implicit("ANY", "Readers"),
        WRITERS_POLICY_KEY: _implicit("ANY", "Writers"),
        ADMINS_POLICY_KEY: _implicit("MAJORITY", "Admins"),
        **(profile.get("Policies") or {}),
    }.items():
        _set_policy(root, name, spec)

    _set_value(root, bkeys.HASHING_ALGORITHM_KEY,
               ctxpb.HashingAlgorithm(name="SHA256"))
    _set_value(root, bkeys.BLOCK_HASHING_KEY,
               ctxpb.BlockDataHashingStructure(width=0xFFFFFFFF))
    if profile.get("Orderer", {}).get("Addresses"):
        _set_value(root, bkeys.ORDERER_ADDRESSES_KEY,
                   ctxpb.OrdererAddresses(
                       addresses=profile["Orderer"]["Addresses"]),
                   mod_policy="/Channel/Orderer/Admins")
    if profile.get("Consortium"):
        _set_value(root, bkeys.CONSORTIUM_KEY,
                   ctxpb.Consortium(name=profile["Consortium"]))
    _capabilities_value(root, profile.get("Capabilities"))

    if "Orderer" in profile:
        root.groups[bkeys.ORDERER].CopyFrom(
            new_orderer_group(profile["Orderer"]))
    if "Application" in profile:
        root.groups[bkeys.APPLICATION].CopyFrom(
            new_application_group(profile["Application"]))
    return root
