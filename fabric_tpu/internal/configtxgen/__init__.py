from fabric_tpu.internal.configtxgen.encoder import (
    default_org_policies,
    new_application_group,
    new_channel_group,
    new_orderer_group,
    new_org_group,
)
from fabric_tpu.internal.configtxgen.genesis import (
    config_block_for_channel,
    config_envelope,
    config_from_block,
    genesis_block,
)

__all__ = [
    "default_org_policies", "new_application_group", "new_channel_group",
    "new_orderer_group", "new_org_group", "config_block_for_channel",
    "config_envelope", "config_from_block", "genesis_block",
]
