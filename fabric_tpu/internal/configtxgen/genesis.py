"""Genesis-block construction.

Rebuild of `common/genesis/genesis.go` + the configtxgen outputBlock
path: wrap a channel's Config in a CONFIG envelope inside block 0.
Orderers bootstrap channels from this block; peers join with it.
"""

from __future__ import annotations

from fabric_tpu import protoutil as pu
from fabric_tpu.protos import common, configtx as ctxpb


def config_envelope(channel_id: str, config: ctxpb.Config,
                    last_update: bytes = b"") -> common.Envelope:
    """An (unsigned) CONFIG envelope carrying the given config."""
    cenv = ctxpb.ConfigEnvelope()
    cenv.config.CopyFrom(config)
    cenv.last_update = last_update
    ch = pu.make_channel_header(common.HeaderType.CONFIG, channel_id)
    sh = pu.create_signature_header(b"")   # genesis has no creator
    payload = pu.make_payload(ch, sh, pu.marshal(cenv))
    env = common.Envelope()
    env.payload = pu.marshal(payload)
    return env


def genesis_block(channel_id: str,
                  channel_group: ctxpb.ConfigGroup) -> common.Block:
    """Block 0 for a new channel (reference: `common/genesis/genesis.go`
    Block)."""
    config = ctxpb.Config(sequence=0)
    config.channel_group.CopyFrom(channel_group)
    return config_block_for_channel(channel_id, config, seq=0,
                                    previous_hash=b"")


def config_block_for_channel(channel_id: str, config: ctxpb.Config,
                             seq: int,
                             previous_hash: bytes) -> common.Block:
    env = config_envelope(channel_id, config)
    block = pu.new_block(seq, previous_hash)
    block.data.data.append(pu.marshal(env))
    block.header.data_hash = pu.block_data_hash(block.data)
    md = common.Metadata()
    md.value = common.OrdererBlockMetadata(
        last_config_index=seq).SerializeToString(deterministic=True)
    block.metadata.metadata[common.BlockMetadataIndex.SIGNATURES] = \
        pu.marshal(md)
    return block


def config_from_block(block: common.Block) -> ctxpb.Config:
    """Extract the Config from a config block (reference:
    `protoutil/blockutils.go` GetConfigFromBlock)."""
    env = pu.extract_envelope(block, 0)
    payload = pu.get_payload(env)
    ch = pu.get_channel_header(payload)
    if ch.type != common.HeaderType.CONFIG:
        raise ValueError(f"block envelope is not CONFIG (type {ch.type})")
    cenv = ctxpb.ConfigEnvelope()
    cenv.ParseFromString(payload.data)
    return cenv.config
