"""Fixed-base comb ECDSA-P256 verification for key-grouped batches.

The reference verifies each signature independently on CPU
(`bccsp/sw/ecdsa.go:41-57`), so it cannot exploit the dominant structural
fact about a Fabric block: the same handful of org endorser/creator keys
signs thousands of transactions. This kernel does.

For a batch whose signatures use K distinct public keys (K small — block
reality is 2-8 orgs), R = u1*G + u2*Q is computed with the fixed-base comb
method on BOTH bases:

    R = sum_i  T_G[i][win_i(u1)]  +  sum_i  T_Q[key][i][win_i(u2)]

with 8-bit windows (NWIN = 32 per scalar):
  * T_G[i][j] = j * 2^(8i) * G  — host-precomputed constants (1.9 MB).
  * T_Q[k][i][j] = j * 2^(8i) * Q_k — built ON DEVICE once per batch with
    two lax.scans (~500 point ops at width NWIN*K), amortized over every
    signature that shares the key.
  * Per signature: 64 gathered points, tree-reduced with 6 vectorized
    complete-add levels (63 adds) — and ZERO doublings, vs the generic
    Shamir ladder's 256 doublings + 128 adds (fabric_tpu/ops/p256.py
    double_scalar_mul). ~4.8x fewer field ops.

Everything is branchless/fixed-shape; window j=0 gathers the point at
infinity and the complete addition law absorbs it, so zero scalars and
padded lanes need no special casing. Batches with many distinct keys fall
back to the generic ladder in the provider (fabric_tpu/bccsp/tpu.py).
"""

from __future__ import annotations

import functools
import logging

import numpy as np

import jax.numpy as jnp
from jax import lax

from fabric_tpu.ops import limb, p256
from fabric_tpu.ops.limb import L, W
from fabric_tpu.ops.p256 import FN, FP, cadd, cdbl

logger = logging.getLogger("ops.comb")

WBITS = 8                   # comb window width (bits)
NWIN = 256 // WBITS         # windows per 256-bit scalar
NENT = 1 << WBITS           # table entries per window


# ---------------------------------------------------------------------------
# Persisted-table integrity: every *.npy this framework writes to a
# warm/cache dir carries a sha256 sidecar (<path>.sha256). A table
# corrupted on disk (bit rot, torn write survived by rename, operator
# truncation) must fall back to a REBUILD, never feed the verify
# kernel wrong points — a wrong Q-table entry flips verdicts silently.
# ---------------------------------------------------------------------------

def file_sha256(path: str, blk: int = 1 << 20) -> str:
    import hashlib
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(blk), b""):
            h.update(chunk)
    return h.hexdigest()


def write_digest_sidecar(path: str, digest: str | None = None) -> None:
    """Record `path`'s sha256 beside it (tmp+rename; best-effort at
    call sites — a missing sidecar degrades to trust-the-bytes)."""
    import os
    if digest is None:
        digest = file_sha256(path)
    side = path + ".sha256"
    tmp = side + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(digest)
    os.replace(tmp, side)


def verify_digest_sidecar(path: str):
    """True = digest matches; False = MISMATCH (corrupt — caller must
    rebuild); None = no sidecar (legacy file, caller's choice)."""
    try:
        with open(path + ".sha256") as f:
            want = f.read().strip()
    except FileNotFoundError:
        return None
    except Exception:
        return None
    try:
        return file_sha256(path) == want
    except Exception:
        return False


def drop_digest_sidecar(path: str) -> None:
    import os
    try:
        os.remove(path + ".sha256")
    except OSError:
        pass


# ---------------------------------------------------------------------------
# G-side tables (host-precomputed constants)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def g_tables() -> np.ndarray:
    """(NWIN * NENT, 3, L) int32 — projective T_G[i*NENT + j] = j*2^(8i)*G.

    Entry j=0 is the point at infinity (0 : 1 : 0). Built once over
    Python ints (exact), lru-cached in process and persisted to
    $FABRIC_TPU_GTAB_CACHE (default ~/.cache/fabric_tpu/gtab8.npy,
    empty string disables) — the 8k host bigint point ops are a
    measurable slice of restart-to-first-validated-block, and G is a
    universal constant."""
    import os
    # ftpu-check: allow-retrace(compile-time config by design: the G
    # table cache path is pinned for the process and only gates a
    # host-side np.load, never a traced value)
    cache = os.environ.get(
        "FABRIC_TPU_GTAB_CACHE",
        os.path.expanduser("~/.cache/fabric_tpu/gtab8.npy"))
    if cache:
        try:
            if verify_digest_sidecar(cache) is not False:
                arr = np.load(cache)
                if (arr.dtype == np.int32
                        and arr.shape == (NWIN * NENT, 3, L)):
                    return arr
        except FileNotFoundError:
            pass
        except Exception as e:
            logger.warning("G-table cache %s unreadable (%s); "
                           "rebuilding", cache, e)
    out = np.zeros((NWIN * NENT, 3, L), dtype=np.int32)
    base = (p256.GX, p256.GY, 1)
    for i in range(NWIN):
        acc = (0, 1, 0)
        for j in range(NENT):
            for c in range(3):
                out[i * NENT + j, c] = limb.int_to_limbs(acc[c])
            acc = p256.cadd_int(acc, base)
        for _ in range(WBITS):
            base = p256.cdbl_int(base)
    if cache:
        try:
            os.makedirs(os.path.dirname(cache), exist_ok=True)
            tmp = cache + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                np.save(f, out)
            digest = file_sha256(tmp)
            os.replace(tmp, cache)
            write_digest_sidecar(cache, digest)
        except Exception as e:
            logger.warning("G-table cache persist to %s failed (%s); "
                           "next start rebuilds", cache, e)
    return out


# G-side 16-bit windows: halve the G points in the per-signature tree.
NWIN_G16 = 16
NENT_G16 = 1 << 16

_g16_cache: list = []
_g16_lock = __import__("threading").Lock()


def g16_tables():
    """(NWIN_G16 * NENT_G16, 3, L) device array —
    T16[i*65536 + j] = j * 2^(16i) * G.

    Too large to build with host ints (1M point ops); built ON DEVICE
    once per process from the 8-bit host tables with one vectorized
    complete add: T16_i[j] = T8_{2i}[j & 255] + T8_{2i+1}[j >> 8].
    ~252 MB resident in HBM for the life of the process — the G
    tables are universal constants, exactly the precompute a
    long-lived validating peer wants.
    """
    with _g16_lock:     # a prewarm thread must not race the first
        #                 block into building the ~252 MB table twice
        if _g16_cache:
            return _g16_cache[0]
        import jax

        g8 = jnp.asarray(g_tables())        # (32*256, 3, L)

        def build(g8):
            idx = jnp.arange(NENT_G16, dtype=jnp.int32)
            lo, hi = idx & 255, idx >> 8
            outs = []
            for i in range(NWIN_G16):
                a = jnp.take(g8, (2 * i) * NENT + lo, axis=0)
                b = jnp.take(g8, (2 * i + 1) * NENT + hi, axis=0)
                X, Y, Z = cadd((a[:, 0], a[:, 1], a[:, 2]),
                               (b[:, 0], b[:, 1], b[:, 2]))
                outs.append(jnp.stack([X, Y, Z], axis=1))
            return jnp.concatenate(outs, axis=0)

        _g16_cache.append(jax.jit(build)(g8))
        return _g16_cache[0]


# ---------------------------------------------------------------------------
# Q-side tables (device, per distinct key)
# ---------------------------------------------------------------------------

def build_q16_tables(q_flat, K: int):
    """8-bit Q tables -> 16-bit Q tables by pairwise window combining:
    T16_{i,k}[j] = T8_{2i,k}[j & 255] + T8_{2i+1,k}[j >> 8].

    ~1M*K point adds as ONE vectorized complete add — expensive per
    call (and ~252*K MB resident), so callers cache the result per key
    set: a validating peer sees the same org keys on every block, which
    makes this a once-per-channel-config cost, not a per-block one.
    Layout: flat16[(i * K + k) * 65536 + j].
    """
    idx = jnp.arange(NENT_G16, dtype=jnp.int32)
    lo, hi = idx & 255, idx >> 8
    outs = []
    for i in range(NWIN_G16):
        for k in range(K):
            a = jnp.take(q_flat, ((2 * i) * K + k) * NENT + lo, axis=0)
            b = jnp.take(q_flat, ((2 * i + 1) * K + k) * NENT + hi,
                         axis=0)
            X, Y, Z = cadd((a[:, 0], a[:, 1], a[:, 2]),
                           (b[:, 0], b[:, 1], b[:, 2]))
            outs.append(jnp.stack([X, Y, Z], axis=1))
    return jnp.concatenate(outs, axis=0)


def build_q_tables(qx, qy):
    """(K, L) affine key coords -> (NWIN * K * NENT, 3, L) projective table.

    flat[(i * K + k) * NENT + j] = j * 2^(8i) * Q_k.  Two scans:
      1. window bases b_i = 2^(8i) * Q (31 steps of 8 doublings, width K);
      2. running multiples j*b (NENT-2 adds, width NWIN*K).
    Entries are semi-reduced projective coordinates — gathers copy bits,
    and the complete add accepts semi-reduced inputs.
    """
    K = qx.shape[0]
    ones = jnp.broadcast_to(jnp.asarray(limb.int_to_limbs(1)), (K, L))
    zeros = jnp.zeros((K, L), dtype=jnp.int32)
    q1 = (qx, qy, ones)

    def dbl8(pt, _):
        for _ in range(WBITS):
            pt = cdbl(pt)
        return pt, pt

    _, shifted = lax.scan(dbl8, q1, None, length=NWIN - 1)
    # bases: (NWIN, K, L) per coordinate
    bases = tuple(
        jnp.concatenate([q1[c][None], shifted[c]], axis=0) for c in range(3)
    )

    def step(acc, _):
        nxt = cadd(acc, bases)
        return nxt, nxt

    _, multiples = lax.scan(step, bases, None, length=NENT - 2)
    inf = (jnp.zeros((NWIN, K, L), jnp.int32),
           jnp.broadcast_to(jnp.asarray(limb.int_to_limbs(1)), (NWIN, K, L)),
           jnp.zeros((NWIN, K, L), jnp.int32))
    # entries: (NENT, NWIN, K, L) per coord = [inf, base, 2*base, ...]
    flat = []
    for c in range(3):
        ent = jnp.concatenate(
            [inf[c][None], bases[c][None], multiples[c]], axis=0)
        flat.append(jnp.transpose(ent, (1, 2, 0, 3)))   # (NWIN, K, NENT, L)
    # (NWIN*K*NENT, 3, L)
    return jnp.stack(
        [f.reshape(NWIN * K * NENT, L) for f in flat], axis=1)


# ---------------------------------------------------------------------------
# Window extraction + combination
# ---------------------------------------------------------------------------

def _windows(u, wbits: int = WBITS):
    """Canonical (B, L) scalar -> (B, 256//wbits) int32 windows.

    Window bit positions are static, so limb indices/shifts resolve at
    trace time — no dynamic slicing. A window spans at most three
    13-bit limbs for wbits <= 16.
    """
    cols = []
    for i in range(256 // wbits):
        bit0 = i * wbits
        j0, off = bit0 // W, bit0 % W
        v = u[:, j0] >> off
        got = W - off
        j = j0 + 1
        while got < wbits and j < L:
            v = v | (u[:, j] << got)
            got += W
            j += 1
        cols.append(v & ((1 << wbits) - 1))
    return jnp.stack(cols, axis=1)


def _tree_reduce(X, Y, Z):
    """(B, M, L) point arrays -> (B, L) sum via log2(M) cadd levels."""
    while X.shape[1] > 1:
        if X.shape[1] % 2:          # pad with infinity
            pad = [(0, 0), (0, 1), (0, 0)]
            X = jnp.pad(X, pad)
            Y = jnp.pad(Y, pad, constant_values=0)
            Y = Y.at[:, -1, 0].set(1)
            Z = jnp.pad(Z, pad)
        X, Y, Z = cadd((X[:, 0::2], Y[:, 0::2], Z[:, 0::2]),
                       (X[:, 1::2], Y[:, 1::2], Z[:, 1::2]))
    return X[:, 0], Y[:, 0], Z[:, 0]


def comb_gather_points(u1, u2, key_idx, g_flat, q_flat, K: int,
                       g16=None, q16: bool = False):
    """Gather the per-signature comb points: (B, M, 3, L).

    M = (16 or 32 G-side) + (16 or 32 Q-side) depending on window
    widths. The subsequent tree sum is done either by `_tree_reduce`
    (XLA) or by the Pallas VMEM kernel (fabric_tpu/ops/ptree.py).
    """
    if g16 is not None:
        w1 = _windows(u1, 16)               # (B, 16)
        win = jnp.arange(NWIN_G16, dtype=jnp.int32)[None, :]
        pts_g = jnp.take(g16, win * NENT_G16 + w1, axis=0)
    else:
        w1 = _windows(u1)                   # (B, NWIN)
        win = jnp.arange(NWIN, dtype=jnp.int32)[None, :]
        pts_g = jnp.take(g_flat, win * NENT + w1, axis=0)
    if q16:                             # 16-bit Q tables (build_q16_tables)
        w2 = _windows(u2, 16)
        win = jnp.arange(NWIN_G16, dtype=jnp.int32)[None, :]
        q_idx = (win * K + key_idx[:, None]) * NENT_G16 + w2
    else:
        w2 = _windows(u2)
        win = jnp.arange(NWIN, dtype=jnp.int32)[None, :]
        q_idx = (win * K + key_idx[:, None]) * NENT + w2
    pts_q = jnp.take(q_flat, q_idx, axis=0)
    return jnp.concatenate([pts_g, pts_q], axis=1)


def comb_double_scalar_mul(u1, u2, key_idx, g_flat, q_flat, K: int,
                           g16=None, q16: bool = False):
    """R = u1*G + u2*Q_{key_idx} for a batch, via two combs.

    u1, u2: (B, L) canonical scalars; key_idx: (B,) int32 in [0, K);
    g_flat: (NWIN*NENT, 3, L); q_flat: (NWIN*K*NENT, 3, L).
    With g16 (the 16-bit G table), the G side contributes 16 points
    instead of 32 — a 48-point tree (25% fewer adds per signature).
    Returns projective (X, Y, Z) each (B, L).
    """
    pts = comb_gather_points(u1, u2, key_idx, g_flat, q_flat, K,
                             g16=g16, q16=q16)
    return _tree_reduce(pts[:, :, 0], pts[:, :, 1], pts[:, :, 2])


def comb_verify_with_tables(digest_words, key_idx, q_flat, r, rpn, w,
                            premask, g16=None, q16: bool = False,
                            tree: str = "xla"):
    """Batched ECDSA accept/reject against a prebuilt Q-table.

    q_flat: from build_q_tables (8-bit windows; q16=False) or
    build_q16_tables (16-bit; q16=True) — built once per key set and
    reused across blocks/chunks. g16: optional 16-bit G-window table
    (g16_tables()); with both 16-bit sides the per-signature tree has
    32 points. tree: "xla" (fusion-island graph) or "pallas" (the
    VMEM tree kernel, ops/ptree.py — the fast path on real TPUs).
    """
    ent = NWIN_G16 * NENT_G16 if q16 else NWIN * NENT
    K = q_flat.shape[0] // ent
    g_flat = jnp.asarray(g_tables()) if g16 is None else None
    e = limb.words_be_to_limbs(digest_words)
    u1 = FN.canonical(FN.mulmod(e, w))
    u2 = FN.canonical(FN.mulmod(r, w))
    if tree == "pallas":
        from fabric_tpu.ops import ptree
        pts = comb_gather_points(u1, u2, key_idx, g_flat, q_flat, K,
                                 g16=g16, q16=q16)
        return ptree.tree_verify_points(pts, r, rpn, premask)
    X, _, Z = comb_double_scalar_mul(u1, u2, key_idx, g_flat, q_flat, K,
                                     g16=g16, q16=q16)
    nonzero = jnp.any(FP.canonical(Z) != 0, axis=-1)
    x_canon = FP.canonical(X)
    ok1 = jnp.all(x_canon == FP.canonical(FP.mulmod(r, Z)), axis=-1)
    ok2 = jnp.all(x_canon == FP.canonical(FP.mulmod(rpn, Z)), axis=-1)
    return premask & nonzero & (ok1 | ok2)


def comb_verify_core(digest_words, key_idx, qx_k, qy_k, r, rpn, w, premask):
    """Batched ECDSA accept/reject over K distinct keys via comb tables.

    digest_words: (B, 8) uint32; key_idx: (B,) int32 in [0, K);
    qx_k, qy_k: (K, L) distinct-key affine limbs; r/rpn/w: (B, L)
    canonical limbs (same contract as p256.verify_core); premask: (B,).
    """
    q_flat = build_q_tables(qx_k, qy_k)
    return comb_verify_with_tables(
        digest_words, key_idx, q_flat, r, rpn, w, premask)
