"""BLS12-381 pairing + aggregate BLS signatures — exact Python-int
reference.

The twin of `ops/bn254_ref.py` for the pairing-friendly curve modern
consensus deployments actually standardize on (the EdDSA/BLS
committee-consensus measurement in PAPERS.md, arXiv:2302.00418). It is
the correctness oracle and the HOST-FIRST serving path for the
provider's `verify_aggregate`; `ops/bls12_381.py` stages the batched
Miller-loop / shared-final-exponentiation structure over this module
so ROADMAP item 4 can lift the loop on-device (the 381-bit field
exceeds the 256-bit limb machinery — a wider limb layout is that
item's work, not this one's).

Deliberately the SIMPLEST correct formulation (the bn254_ref
discipline):

  * tower Fp -> Fp2 = Fp[u]/(u^2+1) -> Fp6 = Fp2[v]/(v^3 - (1+u))
    -> Fp12 = Fp6[w]/(w^2 - v);
  * G2 points untwist into E(Fp12) — the M-type twist divides by w^2 /
    w^3 where BN254's D-type multiplied — so the Miller loop is plain
    affine chord-and-tangent lines, no twist constants to get wrong;
  * BLS12 ate pairing: f_{|x|,Q}(P) over the curve parameter
    x = -0xd201000000010000, NO Frobenius correction steps (that is a
    BN-curve artifact), final exponentiation a single pow by
    (p^12-1)/r. With x negative this computes e(P,Q)^{-1} — still
    bilinear and non-degenerate, which is all a product-equals-one
    check consumes, exactly as used consistently below.

Signatures are min-sig BLS (the consensus-aggregation shape): sk in
Zr, pk = sk*G2 on the twist, sig = sk*H(m) in G1 — a whole committee's
block signatures aggregate to ONE 96-byte G1 point. Verify:
e(sig, -G2) * prod_i e(H(m_i), pk_i) == 1.

Group arithmetic for keygen/sign/hash runs on plain Fp / Fp2 Jacobian
ladders (the 636-bit G2 cofactor clear through the Fp12 embedding
would cost minutes); the embedded ops pin them differentially in
tests.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_BLS = 0xD201000000010000          # |x|; the BLS parameter is -|x|

# cofactors: h1 clears G1 hash outputs into the order-r subgroup; h2
# is only documented here (subgroup membership is CHECKED, not forced)
H1 = 0x396C8C005555E1568C00AAAB0000AAAB

G1 = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

B_G1 = 4                             # E:  y^2 = x^3 + 4
XI = (1, 1)                          # v^3 = 1 + u; twist b' = 4*XI


# ---------------------------------------------------------------------------
# Tower arithmetic over Python ints (the bn254_ref shapes, XI = 1+u)
# ---------------------------------------------------------------------------

def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_mul(a, b):
    return ((a[0] * b[0] - a[1] * b[1]) % P,
            (a[0] * b[1] + a[1] * b[0]) % P)


def f2_inv(a):
    d = pow(a[0] * a[0] + a[1] * a[1], -1, P)
    return (a[0] * d % P, -a[1] * d % P)


def f2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


F2_ZERO = (0, 0)
F2_ONE = (1, 0)


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_mul(a, b):
    c0, c1, c2 = a
    d0, d1, d2 = b
    t0, t1, t2 = f2_mul(c0, d0), f2_mul(c1, d1), f2_mul(c2, d2)
    r0 = f2_add(t0, f2_mul(XI, f2_add(f2_mul(c1, d2), f2_mul(c2, d1))))
    r1 = f2_add(f2_add(f2_mul(c0, d1), f2_mul(c1, d0)),
                f2_mul(XI, t2))
    r2 = f2_add(f2_add(f2_mul(c0, d2), f2_mul(c2, d0)), t1)
    return (r0, r1, r2)


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_inv(a):
    c0, c1, c2 = a
    t0 = f2_sub(f2_mul(c0, c0), f2_mul(XI, f2_mul(c1, c2)))
    t1 = f2_sub(f2_mul(XI, f2_mul(c2, c2)), f2_mul(c0, c1))
    t2 = f2_sub(f2_mul(c1, c1), f2_mul(c0, c2))
    norm = f2_add(f2_mul(c0, t0),
                  f2_mul(XI, f2_add(f2_mul(c2, t1), f2_mul(c1, t2))))
    ninv = f2_inv(norm)
    return (f2_mul(t0, ninv), f2_mul(t1, ninv), f2_mul(t2, ninv))


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_sub(a, b):
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def _f6_mul_v(t):
    """Multiply an Fp6 element by v (w^2 = v, v^3 = XI)."""
    return (f2_mul(XI, t[2]), t[0], t[1])


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    r0 = f6_add(t0, _f6_mul_v(t1))
    r1 = f6_add(f6_mul(a0, b1), f6_mul(a1, b0))
    return (r0, r1)


F12_ZERO = (F6_ZERO, F6_ZERO)
F12_ONE = (F6_ONE, F6_ZERO)


def f12_inv(a):
    a0, a1 = a
    t1 = f6_mul(a1, a1)
    norm = f6_sub(f6_mul(a0, a0), _f6_mul_v(t1))
    ninv = f6_inv(norm)
    return (f6_mul(a0, ninv),
            f6_sub(F6_ZERO, f6_mul(a1, ninv)))


def f12_pow(a, e: int):
    out = F12_ONE
    base = a
    while e:
        if e & 1:
            out = f12_mul(out, base)
        base = f12_mul(base, base)
        e >>= 1
    return out


def f12_conj(a):
    """x -> x^(p^6): conjugation over Fp6 (negate the w half)."""
    return (a[0], f6_sub(F6_ZERO, a[1]))


def f12_eq(a, b) -> bool:
    return a == b


def f12_scalar(x: int):
    return (((x % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


F12_W = (F6_ZERO, F6_ONE)
F12_W2 = f12_mul(F12_W, F12_W)
F12_W3 = f12_mul(F12_W2, F12_W)
F12_W2_INV = f12_inv(F12_W2)
F12_W3_INV = f12_inv(F12_W3)


# ---------------------------------------------------------------------------
# Curve over Fp12 (affine; None = infinity) — the certain-but-slow ops
# ---------------------------------------------------------------------------

def ec_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if f12_eq(x1, x2):
        if f12_eq(y1, y2):
            if f12_eq(y1, F12_ZERO):
                return None
            lam = f12_mul(f12_mul(f12_scalar(3), f12_mul(x1, x1)),
                          f12_inv(f12_mul(f12_scalar(2), y1)))
        else:
            return None
    else:
        lam = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
    x3 = f12_sub(f12_sub(f12_mul(lam, lam), x1), x2)
    y3 = f12_sub(f12_mul(lam, f12_sub(x1, x3)), y1)
    return (x3, y3)


def ec_mul(k: int, p):
    out = None
    for bit in bin(k)[2:] if k else "":
        out = ec_add(out, out)
        if bit == "1":
            out = ec_add(out, p)
    return out


def ec_neg(p):
    if p is None:
        return None
    return (p[0], f12_sub(F12_ZERO, p[1]))


def untwist(q):
    """E'(Fp2) affine (x, y) -> E(Fp12): the M-type map
    (x/w^2, y/w^3) — check: (y/w^3)^2 = (x/w^2)^3 + 4 pulls back to
    y^2 = x^3 + 4*XI, the twist equation."""
    if q is None:
        return None
    (x, y) = q
    ex = (((x[0], x[1]), F2_ZERO, F2_ZERO), F6_ZERO)
    ey = (((y[0], y[1]), F2_ZERO, F2_ZERO), F6_ZERO)
    return (f12_mul(ex, F12_W2_INV), f12_mul(ey, F12_W3_INV))


def _retwist(p12):
    x = f12_mul(p12[0], F12_W2)
    y = f12_mul(p12[1], F12_W3)
    return ((x[0][0][0], x[0][0][1]), (y[0][0][0], y[0][0][1]))


def g1_embed(p):
    if p is None:
        return None
    return (f12_scalar(p[0]), f12_scalar(p[1]))


def on_curve_g1(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - x * x * x - B_G1) % P == 0


def on_curve_g2(q) -> bool:
    if q is None:
        return True
    x, y = q
    lhs = f2_mul(y, y)
    rhs = f2_add(f2_mul(x, f2_mul(x, x)), f2_mul((B_G1, 0), XI))
    return lhs == rhs


# ---------------------------------------------------------------------------
# Miller loop + pairing (BLS12 shape: no correction steps)
# ---------------------------------------------------------------------------

def _line(t, q, p):
    """l_{T,Q}(P) for affine T, Q, P on E(Fp12)."""
    xt, yt = t
    xq, yq = q
    xp, yp = p
    if f12_eq(xt, xq) and not f12_eq(yt, yq):
        return f12_sub(xp, xt)            # vertical
    if f12_eq(xt, xq):
        lam = f12_mul(f12_mul(f12_scalar(3), f12_mul(xt, xt)),
                      f12_inv(f12_mul(f12_scalar(2), yt)))
    else:
        lam = f12_mul(f12_sub(yq, yt), f12_inv(f12_sub(xq, xt)))
    return f12_sub(f12_sub(yp, yt), f12_mul(lam, f12_sub(xp, xt)))


def miller_loop(q_tw, p, loop: int = X_BLS) -> tuple:
    """f_{loop, Q}(P): q_tw affine E'(Fp2) (or None), p affine G1 (or
    None). Plain double-and-add over the loop bits — BLS12 curves need
    none of the BN optimal-ate Frobenius corrections. Returns an Fp12
    element (ONE for infinity inputs)."""
    if q_tw is None or p is None:
        return F12_ONE
    q = untwist(q_tw)
    pe = g1_embed(p)
    f = F12_ONE
    t = q
    for bit in bin(loop)[3:]:
        f = f12_mul(f12_mul(f, f), _line(t, t, pe))
        t = ec_add(t, t)
        if bit == "1":
            f = f12_mul(f, _line(t, q, pe))
            t = ec_add(t, q)
    return f


@lru_cache(maxsize=None)
def _final_exp_exponent() -> int:
    return (P ** 12 - 1) // R


def final_exponentiation(f) -> tuple:
    """One pow by (p^12-1)/r — slow and certain. The easy-part
    shortcut (conj * inv, then the hard exponent) is ~3x cheaper and
    pinned against this in tests; aggregate verify uses it."""
    return f12_pow(f, _final_exp_exponent())


@lru_cache(maxsize=None)
def _hard_exponent() -> int:
    # after the easy part f^((p^6-1)(p^2+1)), what remains of
    # (p^12-1)/r is (p^4 - p^2 + 1)/r
    return (P ** 4 - P ** 2 + 1) // R


def final_exponentiation_fast(f) -> tuple:
    """Easy part via conjugate/inverse and x^(p^2) (coefficient-wise
    Frobenius^2), then a single pow by the ~1270-bit hard exponent —
    the structure the batched aggregate check shares across its ONE
    final exp per call."""
    m = f12_mul(f12_conj(f), f12_inv(f))          # f^(p^6-1)
    m = f12_mul(_frob2(m), m)                     # ^(p^2+1)
    return f12_pow(m, _hard_exponent())


@lru_cache(maxsize=None)
def _frob2_gammas() -> tuple:
    """gamma_i = (w^i)^(p^2-1) for i = 0..5, each an Fp scalar (the
    p^2-Frobenius fixes Fp2 elementwise, so x^(p^2) multiplies the
    w^i basis coefficient by gamma_i)."""
    g = pow_xi((P * P - 1) // 6)
    assert g[1] == 0, "gamma must be an Fp scalar"
    out = []
    for i in range(6):
        out.append(pow(g[0], i, P))
    return tuple(out)


def f2_conj(a):
    return (a[0], (-a[1]) % P)


@lru_cache(maxsize=None)
def _frob_gammas() -> tuple:
    """gamma_k = xi^(k*(p-1)/6) in Fp2: the p-power Frobenius sends
    the coefficient c of w^k to conj(c) * gamma_k."""
    return tuple(pow_xi(k * (P - 1) // 6) for k in range(6))


def f12_frob(a):
    """x -> x^p on Fp12 (coefficient-wise Fp2 conjugation times the
    gamma constants; w-exponents 0,2,4 / 1,3,5 across the halves)."""
    g = _frob_gammas()
    (c0, c1, c2), (c3, c4, c5) = a
    return ((f2_conj(c0),
             f2_mul(f2_conj(c1), g[2]),
             f2_mul(f2_conj(c2), g[4])),
            (f2_mul(f2_conj(c3), g[1]),
             f2_mul(f2_conj(c4), g[3]),
             f2_mul(f2_conj(c5), g[5])))


def final_exponentiation_chain(f) -> tuple:
    """The DEVICE-SHAPED final exponentiation: easy part, then the
    Hayashida-Hayasaka-Teruya addition chain for the BLS12 family,

        3*(p^4 - p^2 + 1)/r = (x-1)^2 * (x+p) * (x^2 + p^2 - 1) + 3

    with x = -|x| (so pow-by-|x| plus cyclotomic conjugations — every
    step is a static square-and-multiply, a Frobenius or a conjugate,
    exactly the op set of the tower's register machine). Returns
    final_exponentiation_fast(f)**3; since Phi_12(p) = p^4 - p^2 + 1
    is ~1 mod 3, gcd(3, r) = 1 and the cube is 1 iff the fast result
    is 1 — equivalent for every product-equals-one check. Pinned
    against the single-pow oracle in tests; the device final-exp
    program mirrors this chain instruction for instruction."""
    m = f12_mul(f12_conj(f), f12_inv(f))          # f^(p^6-1)
    m = f12_mul(_frob2(m), m)                     # ^(p^2+1)
    u = X_BLS
    t0 = f12_mul(f12_pow(m, u), m)                # m^(u+1) = m^-(x-1)
    y1 = f12_mul(f12_pow(t0, u), t0)              # m^((x-1)^2)
    y2 = f12_mul(f12_conj(f12_pow(y1, u)),
                 f12_frob(y1))                    # y1^(x+p)
    y3 = f12_mul(f12_mul(f12_pow(f12_pow(y2, u), u),
                         _frob2(y2)),
                 f12_conj(y2))                    # y2^(x^2+p^2-1)
    m3 = f12_mul(f12_mul(m, m), m)
    return f12_mul(y3, m3)


def pow_xi(e: int) -> tuple:
    out = F2_ONE
    base = XI
    while e:
        if e & 1:
            out = f2_mul(out, base)
        base = f2_mul(base, base)
        e >>= 1
    return out


def _frob2(a):
    """x -> x^(p^2) on Fp12: Fp2 coefficients are fixed; the basis
    element w^(2i) (resp. w^(2i+1)) picks up gamma_(2i) (gamma_(2i+1))
    with gamma_i = xi^(i*(p^2-1)/6) in Fp."""
    g = _frob2_gammas()
    (c0, c1, c2), (c3, c4, c5) = a
    scale = lambda c, s: (c[0] * s % P, c[1] * s % P)  # noqa: E731
    return ((scale(c0, g[0]), scale(c1, g[2]), scale(c2, g[4])),
            (scale(c3, g[1]), scale(c4, g[3]), scale(c5, g[5])))


def pairing(q_tw, p) -> tuple:
    return final_exponentiation(miller_loop(q_tw, p))


# ---------------------------------------------------------------------------
# Fast Jacobian group arithmetic (plain Fp / Fp2 — keygen, signing,
# hashing, subgroup checks; differential-tested vs the embedded ops)
# ---------------------------------------------------------------------------

def _jac_ops(two):
    """(add, sub, mul, zero) for Fp (two=False) or Fp2 (two=True)."""
    if two:
        return (f2_add, f2_sub, f2_mul, F2_ZERO)
    return (lambda a, b: (a + b) % P, lambda a, b: (a - b) % P,
            lambda a, b: a * b % P, 0)


def _jac_dbl(pt, two):
    fadd, fsub, fmul, fzero = _jac_ops(two)
    X, Y, Z = pt
    if Z == fzero or Y == fzero:
        return None
    A = fmul(X, X)
    B = fmul(Y, Y)
    C = fmul(B, B)
    D = fsub(fmul(fadd(X, B), fadd(X, B)), fadd(A, C))
    D = fadd(D, D)
    E = fadd(fadd(A, A), A)
    F = fmul(E, E)
    X3 = fsub(F, fadd(D, D))
    c8 = fadd(fadd(fadd(C, C), fadd(C, C)), fadd(fadd(C, C),
                                                 fadd(C, C)))
    Y3 = fsub(fmul(E, fsub(D, X3)), c8)
    Z3 = fmul(fadd(Y, Y), Z)
    return (X3, Y3, Z3)


def _jac_add(p1, p2, two):
    fadd, fsub, fmul, fzero = _jac_ops(two)
    if p1 is None or p1[2] == fzero:
        return p2
    if p2 is None or p2[2] == fzero:
        return p1
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = fmul(Z1, Z1)
    Z2Z2 = fmul(Z2, Z2)
    U1 = fmul(X1, Z2Z2)
    U2 = fmul(X2, Z1Z1)
    S1 = fmul(fmul(Y1, Z2), Z2Z2)
    S2 = fmul(fmul(Y2, Z1), Z1Z1)
    if U1 == U2:
        if S1 != S2:
            return None
        return _jac_dbl(p1, two)
    H = fsub(U2, U1)
    I = fmul(fadd(H, H), fadd(H, H))
    J = fmul(H, I)
    r = fadd(fsub(S2, S1), fsub(S2, S1))
    V = fmul(U1, I)
    X3 = fsub(fsub(fmul(r, r), J), fadd(V, V))
    S1J = fmul(S1, J)
    Y3 = fsub(fmul(r, fsub(V, X3)), fadd(S1J, S1J))
    Z3 = fmul(fmul(fsub(fmul(fadd(Z1, Z2), fadd(Z1, Z2)),
                        fadd(Z1Z1, Z2Z2)), H),
              (1 if not two else F2_ONE))
    return (X3, Y3, Z3)


def _jac_to_affine(pt, two):
    if pt is None:
        return None
    _, _, fmul, fzero = _jac_ops(two)
    X, Y, Z = pt
    if Z == fzero:
        return None
    zi = f2_inv(Z) if two else pow(Z, -1, P)
    zi2 = fmul(zi, zi)
    return (fmul(X, zi2), fmul(fmul(Y, zi2), zi))


def _jac_mul(k: int, aff, two):
    if aff is None or k == 0:
        return None
    one = F2_ONE if two else 1
    base = (aff[0], aff[1], one)
    acc = None
    for bit in bin(k)[2:]:
        acc = _jac_dbl(acc, two) if acc is not None else acc
        if bit == "1":
            acc = _jac_add(acc, base, two) if acc is not None else base
    return _jac_to_affine(acc, two)


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    one = 1
    return _jac_to_affine(
        _jac_add((p1[0], p1[1], one), (p2[0], p2[1], one), False),
        False)


def g1_mul(k: int, p):
    return _jac_mul(k, p, False)


def g1_neg(p):
    if p is None:
        return None
    return (p[0], (-p[1]) % P)


def g2_add(q1, q2):
    if q1 is None:
        return q2
    if q2 is None:
        return q1
    return _jac_to_affine(
        _jac_add((q1[0], q1[1], F2_ONE), (q2[0], q2[1], F2_ONE), True),
        True)


def g2_mul(k: int, q):
    return _jac_mul(k, q, True)


def g2_neg(q):
    if q is None:
        return None
    return (q[0], f2_neg(q[1]))


def g1_in_subgroup(p) -> bool:
    return p is None or (on_curve_g1(p) and g1_mul(R, p) is None)


def g2_in_subgroup(q) -> bool:
    # memoized: the full-order scalar mult is ~255 Fp2 point ops of
    # host math, and the points reaching this gate per aggregate call
    # are a committee's handful of long-lived public keys (already
    # subgroup-checked once at key import) — cache the verdict so the
    # orderer's per-span aggregate check doesn't re-pay it. G2 points
    # are nested int tuples, hence hashable; the bound keeps an
    # adversarial stream of fresh untrusted points from growing it.
    return q is None or _g2_in_subgroup_memo(q)


@lru_cache(maxsize=4096)
def _g2_in_subgroup_memo(q) -> bool:
    return on_curve_g2(q) and g2_mul(R, q) is None


# ---------------------------------------------------------------------------
# min-sig BLS: sk in Zr, pk = sk*G2 (twist), sig = sk*H(m) in G1
# ---------------------------------------------------------------------------

def hash_to_g1(msg: bytes):
    """Try-and-increment onto E(Fp) (p = 3 mod 4 so sqrt is one pow),
    then clear the G1 cofactor so the output lands in the order-r
    subgroup. Deterministic; NOT the RFC 9380 SSWU encoding — this
    reference defines the scheme's message map, and both the host and
    (future) device paths share it."""
    ctr = 0
    while True:
        x = int.from_bytes(
            hashlib.sha256(b"ftpu-bls12381-g1|" + msg + b"|" +
                           ctr.to_bytes(4, "big")).digest(),
            "big") % P
        rhs = (x * x % P * x + B_G1) % P
        y = pow(rhs, (P + 1) // 4, P)
        if y * y % P == rhs:
            if y & 1:
                y = P - y
            out = g1_mul(H1, (x, y))
            if out is not None:
                return out
        ctr += 1


def bls_keygen(seed: bytes):
    """(sk, pk): pk = sk*G2 affine on E'(Fp2)."""
    sk = int.from_bytes(
        hashlib.sha512(b"ftpu-bls12381-sk|" + seed).digest(),
        "big") % R
    sk = sk or 1
    return sk, g2_mul(sk, (G2_X, G2_Y))


def bls_sign(sk: int, msg: bytes):
    return g1_mul(sk, hash_to_g1(msg))


def bls_aggregate(sigs):
    """Sum of G1 signature points (None entries poison to None)."""
    acc = None
    for s in sigs:
        if s is None:
            return None
        acc = g1_add(acc, s)
    return acc


def bls_verify(pk, msg: bytes, sig) -> bool:
    """Single-signature oracle: e(sig, -G2) * e(H(m), pk) == 1."""
    return aggregate_verify([pk], [msg], sig)


def aggregate_verify(pks, msgs, agg_sig) -> bool:
    """prod_i e(H(m_i), pk_i) == e(agg_sig, G2): one Miller loop per
    pair, ONE shared final exponentiation — the batched structure the
    device path inherits. Subgroup-checks every input (a pk outside
    the order-r subgroup breaks aggregation soundness)."""
    if agg_sig is None or len(pks) != len(msgs) or not pks:
        return False
    if not g1_in_subgroup(agg_sig):
        return False
    f = miller_loop(g2_neg((G2_X, G2_Y)), agg_sig)
    for pk, msg in zip(pks, msgs):
        if pk is None or not g2_in_subgroup(pk):
            return False
        f = f12_mul(f, miller_loop(pk, hash_to_g1(msg)))
    return final_exponentiation_fast(f) == F12_ONE


# ---------------------------------------------------------------------------
# Serialization (uncompressed; infinity = all-zero)
# ---------------------------------------------------------------------------

def g1_to_bytes(p) -> bytes:
    if p is None:
        return b"\x00" * 96
    return p[0].to_bytes(48, "big") + p[1].to_bytes(48, "big")


def g1_from_bytes(raw: bytes, subgroup_check: bool = True):
    if len(raw) != 96:
        raise ValueError("G1 point must be 96 bytes (uncompressed)")
    if raw == b"\x00" * 96:
        return None
    p = (int.from_bytes(raw[:48], "big"),
         int.from_bytes(raw[48:], "big"))
    if p[0] >= P or p[1] >= P or not on_curve_g1(p):
        raise ValueError("not a BLS12-381 G1 point")
    if subgroup_check and not g1_in_subgroup(p):
        raise ValueError("G1 point outside the order-r subgroup")
    return p


def g2_to_bytes(q) -> bytes:
    if q is None:
        return b"\x00" * 192
    (x0, x1), (y0, y1) = q
    return b"".join(v.to_bytes(48, "big") for v in (x0, x1, y0, y1))


def g2_from_bytes(raw: bytes, subgroup_check: bool = True):
    if len(raw) != 192:
        raise ValueError("G2 point must be 192 bytes (uncompressed)")
    if raw == b"\x00" * 192:
        return None
    v = [int.from_bytes(raw[i * 48:(i + 1) * 48], "big")
         for i in range(4)]
    if any(c >= P for c in v):
        raise ValueError("G2 coordinate out of range")
    q = ((v[0], v[1]), (v[2], v[3]))
    if not on_curve_g2(q):
        raise ValueError("not a BLS12-381 G2 point")
    if subgroup_check and not g2_in_subgroup(q):
        raise ValueError("G2 point outside the order-r subgroup")
    return q
