"""BN254 optimal-ate pairing — exact Python-int reference.

This is the correctness oracle for the batched TPU pairing kernels
(the idemix stretch: the reference's identity mixer signs with BBS+
over this curve — vendored `IBM/idemix` under `msp/idemix.go`). It is
deliberately the SIMPLEST correct formulation, not a fast one:

  * tower Fp -> Fp2 = Fp[u]/(u^2+1) -> Fp6 = Fp2[v]/(v^3 - (9+u))
    -> Fp12 = Fp6[w]/(w^2 - v);
  * G2 points are untwisted into E(Fp12) (x*w^2, y*w^3), so the Miller
    loop uses plain affine chord-and-tangent lines with field division
    and plain coordinate-wise Frobenius x -> x^p — no twist constants
    to get subtly wrong;
  * the final exponentiation is a single pow by (p^12-1)/r.

Correctness is pinned by algebraic laws (bilinearity, non-degeneracy,
unit output for infinity inputs) in tests/test_bn254.py; the TPU
kernels are then differentially tested against THIS module.

Curve: y^2 = x^3 + 3 over Fp; twist E': y^2 = x^3 + 3/(9+u) over Fp2
(the alt_bn128 / EIP-197 parameter set — public domain parameters).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617
T_BN = 4965661367192848881               # the BN parameter t
ATE_LOOP = 6 * T_BN + 2

G1 = (1, 2)
# standard generator of the order-r subgroup of E'(Fp2)
G2_X = (10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634)
G2_Y = (8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531)


# ---------------------------------------------------------------------------
# Tower arithmetic over Python ints
# ---------------------------------------------------------------------------

def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_mul(a, b):
    return ((a[0] * b[0] - a[1] * b[1]) % P,
            (a[0] * b[1] + a[1] * b[0]) % P)


def f2_inv(a):
    d = pow(a[0] * a[0] + a[1] * a[1], -1, P)
    return (a[0] * d % P, -a[1] * d % P)


XI = (9, 1)                              # v^3 = 9 + u

F2_ZERO = (0, 0)
F2_ONE = (1, 0)


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_mul(a, b):
    c0, c1, c2 = a
    d0, d1, d2 = b
    t0, t1, t2 = f2_mul(c0, d0), f2_mul(c1, d1), f2_mul(c2, d2)
    # schoolbook with v^3 = XI
    r0 = f2_add(t0, f2_mul(XI, f2_add(f2_mul(c1, d2), f2_mul(c2, d1))))
    r1 = f2_add(f2_add(f2_mul(c0, d1), f2_mul(c1, d0)),
                f2_mul(XI, t2))
    r2 = f2_add(f2_add(f2_mul(c0, d2), f2_mul(c2, d0)), t1)
    return (r0, r1, r2)


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_inv(a):
    """Inverse via the adjoint/norm method over Fp2."""
    c0, c1, c2 = a
    t0 = f2_sub(f2_mul(c0, c0), f2_mul(XI, f2_mul(c1, c2)))
    t1 = f2_sub(f2_mul(XI, f2_mul(c2, c2)), f2_mul(c0, c1))
    t2 = f2_sub(f2_mul(c1, c1), f2_mul(c0, c2))
    norm = f2_add(f2_mul(c0, t0),
                  f2_mul(XI, f2_add(f2_mul(c2, t1), f2_mul(c1, t2))))
    ninv = f2_inv(norm)
    return (f2_mul(t0, ninv), f2_mul(t1, ninv), f2_mul(t2, ninv))


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_sub(a, b):
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    # w^2 = v: multiply an Fp6 element by v
    t1v = (f2_mul(XI, t1[2]), t1[0], t1[1])
    r0 = f6_add(t0, t1v)
    r1 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)),
                f6_add(t0, t1))
    return (r0, r1)


F12_ZERO = (F6_ZERO, F6_ZERO)
F12_ONE = (F6_ONE, F6_ZERO)


def f12_inv(a):
    a0, a1 = a
    t1 = f6_mul(a1, a1)
    t1v = (f2_mul(XI, t1[2]), t1[0], t1[1])
    norm = f6_sub(f6_mul(a0, a0), t1v)
    ninv = f6_inv(norm)
    return (f6_mul(a0, ninv),
            f6_sub(F6_ZERO, f6_mul(a1, ninv)))


def f12_pow(a, e: int):
    out = F12_ONE
    base = a
    while e:
        if e & 1:
            out = f12_mul(out, base)
        base = f12_mul(base, base)
        e >>= 1
    return out


def f12_frob(a):
    """x -> x^p, computed the slow certain way."""
    return f12_pow(a, P)


def f12_eq(a, b) -> bool:
    return a == b


def f12_scalar(x: int):
    """Embed Fp into Fp12."""
    return (((x % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


# w and its powers as Fp12 elements: w = (0, 1) in the Fp6[w] basis
F12_W = (F6_ZERO, F6_ONE)
F12_W2 = f12_mul(F12_W, F12_W)
F12_W3 = f12_mul(F12_W2, F12_W)


# ---------------------------------------------------------------------------
# Curve over Fp12 (affine; None = point at infinity)
# ---------------------------------------------------------------------------

def ec_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if f12_eq(x1, x2):
        if f12_eq(y1, y2):
            if f12_eq(y1, F12_ZERO):
                return None
            lam = f12_mul(f12_mul(f12_scalar(3), f12_mul(x1, x1)),
                          f12_inv(f12_mul(f12_scalar(2), y1)))
        else:
            return None
    else:
        lam = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
    x3 = f12_sub(f12_sub(f12_mul(lam, lam), x1), x2)
    y3 = f12_sub(f12_mul(lam, f12_sub(x1, x3)), y1)
    return (x3, y3)


def ec_mul(k: int, p):
    out = None
    for bit in bin(k)[2:] if k else "":
        out = ec_add(out, out)
        if bit == "1":
            out = ec_add(out, p)
    return out


def ec_neg(p):
    if p is None:
        return None
    return (p[0], f12_sub(F12_ZERO, p[1]))


def untwist(q):
    """E'(Fp2) affine (x, y) -> E(Fp12)."""
    if q is None:
        return None
    (x, y) = q
    ex = (((x[0], x[1]), F2_ZERO, F2_ZERO), F6_ZERO)
    ey = (((y[0], y[1]), F2_ZERO, F2_ZERO), F6_ZERO)
    return (f12_mul(ex, F12_W2), f12_mul(ey, F12_W3))


def g1_embed(p):
    if p is None:
        return None
    return (f12_scalar(p[0]), f12_scalar(p[1]))


def on_curve_g1(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - x * x * x - 3) % P == 0


def on_curve_g2(q) -> bool:
    if q is None:
        return True
    x, y = untwist(q)
    lhs = f12_mul(y, y)
    rhs = f12_add(f12_mul(x, f12_mul(x, x)), f12_scalar(3))
    return f12_eq(lhs, rhs)


# ---------------------------------------------------------------------------
# Miller loop + pairing
# ---------------------------------------------------------------------------

def _line(t, q, p):
    """l_{T,Q}(P) for affine T, Q, P on E(Fp12); handles T == Q
    (tangent) and vertical lines."""
    xt, yt = t
    xq, yq = q
    xp, yp = p
    if f12_eq(xt, xq) and not f12_eq(yt, yq):
        return f12_sub(xp, xt)            # vertical
    if f12_eq(xt, xq):
        lam = f12_mul(f12_mul(f12_scalar(3), f12_mul(xt, xt)),
                      f12_inv(f12_mul(f12_scalar(2), yt)))
    else:
        lam = f12_mul(f12_sub(yq, yt), f12_inv(f12_sub(xq, xt)))
    return f12_sub(f12_sub(yp, yt), f12_mul(lam, f12_sub(xp, xt)))


def miller_loop(q_tw, p, loop: int = ATE_LOOP) -> tuple:
    """f_{loop, Q}(P) with the optimal-ate Frobenius corrections.

    q_tw: affine E'(Fp2) point (or None); p: affine G1 (or None).
    Returns an Fp12 element (ONE for infinity inputs).
    """
    if q_tw is None or p is None:
        return F12_ONE
    q = untwist(q_tw)
    pe = g1_embed(p)
    f = F12_ONE
    t = q
    for bit in bin(loop)[3:]:
        f = f12_mul(f12_mul(f, f), _line(t, t, pe))
        t = ec_add(t, t)
        if bit == "1":
            f = f12_mul(f, _line(t, q, pe))
            t = ec_add(t, q)
    # optimal-ate corrections: Q1 = pi_p(Q), Q2 = pi_{p^2}(Q)
    q1 = (f12_frob(q[0]), f12_frob(q[1]))
    q2 = (f12_frob(q1[0]), f12_frob(q1[1]))
    nq2 = ec_neg(q2)
    f = f12_mul(f, _line(t, q1, pe))
    t = ec_add(t, q1)
    f = f12_mul(f, _line(t, nq2, pe))
    return f


@lru_cache(maxsize=None)
def _final_exp_exponent() -> int:
    return (P ** 12 - 1) // R


def final_exponentiation(f) -> tuple:
    return f12_pow(f, _final_exp_exponent())


def f12_conj(a):
    """x -> x^(p^6): conjugation over Fp6 (negate the w half). In the
    cyclotomic subgroup (post easy part) this IS the inverse."""
    return (a[0], f6_sub(F6_ZERO, a[1]))


def final_exponentiation_chain(f) -> tuple:
    """The structured final exp: easy part (p^6-1)(p^2+1), then the
    BN hard part (p^4-p^2+1)/r via the Scott-et-al addition chain in
    the curve parameter t ("On the Final Exponentiation for
    Calculating Pairings on Ordinary Elliptic Curves", 2008 — public
    method). ~300 f12 ops instead of a 2800-bit pow; the shape the
    DEVICE final exp transcribes (fabric_tpu/ops/bn254.py), pinned
    here against the single-pow oracle."""
    # easy: f^((p^6-1)(p^2+1))
    m = f12_mul(f12_conj(f), f12_inv(f))          # f^(p^6-1)
    m = f12_mul(f12_frob(f12_frob(m)), m)         # ^(p^2+1)
    # hard: m^((p^4-p^2+1)/r) via powers of t and Frobenius
    mx = f12_pow(m, T_BN)
    mx2 = f12_pow(mx, T_BN)
    mx3 = f12_pow(mx2, T_BN)
    mp = f12_frob(m)
    mp2 = f12_frob(mp)
    mp3 = f12_frob(mp2)
    mxp = f12_frob(mx)
    mx2p = f12_frob(mx2)
    mx3p = f12_frob(mx3)
    mx2p2 = f12_frob(f12_frob(mx2))
    y0 = f12_mul(f12_mul(mp, mp2), mp3)
    y1 = f12_conj(m)
    y2 = mx2p2
    y3 = f12_conj(mxp)
    y4 = f12_conj(f12_mul(mx, mx2p))
    y5 = f12_conj(mx2)
    y6 = f12_conj(f12_mul(mx3, mx3p))
    t0 = f12_mul(f12_mul(f12_mul(y6, y6), y4), y5)
    t1 = f12_mul(f12_mul(y3, y5), t0)
    t0 = f12_mul(t0, y2)
    t1 = f12_mul(f12_mul(t1, t1), t0)
    t1 = f12_mul(t1, t1)
    t0 = f12_mul(t1, y1)
    t1 = f12_mul(t1, y0)
    t0 = f12_mul(t0, t0)
    return f12_mul(t0, t1)


def pairing(q_tw, p) -> tuple:
    """e(P, Q) — the full optimal-ate pairing into GT."""
    return final_exponentiation(miller_loop(q_tw, p))


def _retwist(p12):
    """E(Fp12) point in the image of the untwist -> E'(Fp2) coords."""
    x = f12_mul(p12[0], f12_inv(F12_W2))
    y = f12_mul(p12[1], f12_inv(F12_W3))
    return ((x[0][0][0], x[0][0][1]), (y[0][0][0], y[0][0][1]))


def g2_mul(k: int, q):
    """Scalar mul on the twist (through the untwist, for tests)."""
    if q is None or k % R == 0:
        return None
    out = ec_mul(k % R, untwist(q))
    if out is None:
        return None
    return _retwist(out)


def g2_frobenius(q):
    """The twisted Frobenius endomorphism psi^{-1} o pi_p o psi on
    E'(Fp2) — exact int computation through the untwist (the device
    Miller loop takes these correction points precomputed)."""
    if q is None:
        return None
    u = untwist(q)
    return _retwist((f12_frob(u[0]), f12_frob(u[1])))


def g2_neg_tw(q):
    if q is None:
        return None
    return (q[0], ((-q[1][0]) % P, (-q[1][1]) % P))


# ---------------------------------------------------------------------------
# BLS signatures over BN254 (the pairing CONSUMER: idemix issuer
# credentials — sig = sk*H(m) in G1, pk = sk*G2 on the twist;
# verify: e(sig, G2) * e(H(m), -pk) == 1)
# ---------------------------------------------------------------------------

def hash_to_g1(msg: bytes):
    """Try-and-increment hash onto E(Fp): x = H(msg||ctr), y = sqrt of
    x^3+3 (p = 3 mod 4 so sqrt = pow((p+1)/4)); cofactor 1 on BN
    curves, so any curve point is in the order-r group. Returns affine
    int coords."""
    import hashlib as _h
    ctr = 0
    while True:
        x = int.from_bytes(
            _h.sha256(b"ftpu-bn254-g1|" + msg + b"|" +
                      ctr.to_bytes(4, "big")).digest(), "big") % P
        rhs = (x * x * x + 3) % P
        y = pow(rhs, (P + 1) // 4, P)
        if y * y % P == rhs:
            # deterministic sign choice: even y
            if y & 1:
                y = P - y
            return (x, y)
        ctr += 1


def g1_mul(k: int, p):
    """Affine int G1 scalar mul (through the Fp12 embedding)."""
    out = ec_mul(k % R, g1_embed(p))
    if out is None:
        return None
    return (out[0][0][0][0], out[1][0][0][0])


def g1_add(p1, p2):
    """Affine int G1 addition (through the Fp12 embedding)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    out = ec_add(g1_embed(p1), g1_embed(p2))
    if out is None:
        return None
    return (out[0][0][0][0], out[1][0][0][0])


def g1_neg(p):
    if p is None:
        return None
    return (p[0], (-p[1]) % P)


def g2_add(q1, q2):
    """Point addition on the twist (through the untwist)."""
    if q1 is None:
        return q2
    if q2 is None:
        return q1
    out = ec_add(untwist(q1), untwist(q2))
    if out is None:
        return None
    return _retwist(out)


def bls_keygen(seed: bytes):
    """(sk, pk_twist): pk = sk*G2 on E'(Fp2)."""
    import hashlib as _h
    sk = int.from_bytes(_h.sha512(b"ftpu-bls-sk|" + seed).digest(),
                        "big") % R
    sk = sk or 1
    return sk, g2_mul(sk, (G2_X, G2_Y))


def bls_sign(sk: int, msg: bytes):
    return g1_mul(sk, hash_to_g1(msg))


def bls_verify(pk_tw, msg: bytes, sig) -> bool:
    """Host oracle: e(sig, G2) == e(H(m), pk)."""
    if sig is None or pk_tw is None:
        return False
    f1 = miller_loop((G2_X, G2_Y), sig)
    f2 = miller_loop(g2_neg_tw(pk_tw), hash_to_g1(msg))
    return final_exponentiation(f12_mul(f1, f2)) == F12_ONE


# ---------------------------------------------------------------------------
# Fast host group arithmetic (Jacobian, no Fp12 embedding): the PS
# credential layer (msp/idemix_ps.py) does dozens of scalar muls per
# presentation — through the embedding each costs ~f12 work; these are
# plain Fp / Fp2 Jacobian ladders. Differential-tested against the
# embedded ops (tests/test_idemix_ps.py).
# ---------------------------------------------------------------------------

def _jac_dbl(X, Y, Z, fadd, fsub, fmul, fzero):
    if Z == fzero or Y == fzero:
        return None
    A = fmul(X, X)
    B = fmul(Y, Y)
    C = fmul(B, B)
    D = fsub(fmul(fadd(X, B), fadd(X, B)), fadd(A, C))
    D = fadd(D, D)
    E = fadd(fadd(A, A), A)
    F = fmul(E, E)
    X3 = fsub(F, fadd(D, D))
    C8 = C
    for _ in range(3):
        C8 = fadd(C8, C8)
    Y3 = fsub(fmul(E, fsub(D, X3)), C8)
    Z3 = fmul(fadd(Y, Y), Z)
    return X3, Y3, Z3


def _fp_ops():
    fadd = lambda a, c: (a + c) % P
    fsub = lambda a, c: (a - c) % P
    fmul = lambda a, c: (a * c) % P
    return fadd, fsub, fmul, 0


def _fp2_ops():
    return f2_add, f2_sub, f2_mul, (0, 0)


def _jac_scalar(k, aff, fadd, fsub, fmul, fzero, fone):
    """k * affine point, generic Jacobian double-and-add; returns
    Jacobian or None (infinity)."""
    k %= R
    if k == 0 or aff is None:
        return None
    acc = None
    base = (aff[0], aff[1], fone)
    for bit in bin(k)[2:]:
        if acc is not None:
            acc = _jac_dbl(*acc, fadd, fsub, fmul, fzero)
        if bit == "1":
            acc = _jac_add_full(acc, base, fadd, fsub, fmul, fzero)
    return acc


def _jac_add_full(P1, P2, fadd, fsub, fmul, fzero):
    if P1 is None:
        return P2
    if P2 is None:
        return P1
    X1, Y1, Z1 = P1
    X2, Y2, Z2 = P2
    Z1Z1 = fmul(Z1, Z1)
    Z2Z2 = fmul(Z2, Z2)
    U1 = fmul(X1, Z2Z2)
    U2 = fmul(X2, Z1Z1)
    S1 = fmul(fmul(Y1, Z2), Z2Z2)
    S2 = fmul(fmul(Y2, Z1), Z1Z1)
    if U1 == U2:
        if S1 != S2:
            return None
        return _jac_dbl(X1, Y1, Z1, fadd, fsub, fmul, fzero)
    H = fsub(U2, U1)
    HH = fmul(H, H)
    HHH = fmul(H, HH)
    rr = fsub(S2, S1)
    V = fmul(U1, HH)
    X3 = fsub(fsub(fmul(rr, rr), HHH), fadd(V, V))
    Y3 = fsub(fmul(rr, fsub(V, X3)), fmul(S1, HHH))
    Z3 = fmul(fmul(Z1, Z2), H)
    return X3, Y3, Z3


def _fp_jac_to_affine(J):
    if J is None:
        return None
    X, Y, Z = J
    zi = pow(Z, P - 2, P)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 % P * zi % P)


def _fp2_jac_to_affine(J):
    if J is None:
        return None
    X, Y, Z = J
    zi = f2_inv(Z)
    zi2 = f2_mul(zi, zi)
    return (f2_mul(X, zi2), f2_mul(f2_mul(Y, zi2), zi))


def g1_mul_fast(k: int, p):
    fadd, fsub, fmul, z = _fp_ops()
    return _fp_jac_to_affine(_jac_scalar(k, p, fadd, fsub, fmul, z, 1))


def g1_add_fast(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    fadd, fsub, fmul, z = _fp_ops()
    return _fp_jac_to_affine(_jac_add_full(
        (p1[0], p1[1], 1), (p2[0], p2[1], 1), fadd, fsub, fmul, z))


def g2_mul_fast(k: int, q):
    fadd, fsub, fmul, z = _fp2_ops()
    return _fp2_jac_to_affine(
        _jac_scalar(k, q, fadd, fsub, fmul, z, (1, 0)))


def g2_add_fast(q1, q2):
    if q1 is None:
        return q2
    if q2 is None:
        return q1
    fadd, fsub, fmul, z = _fp2_ops()
    return _fp2_jac_to_affine(_jac_add_full(
        (q1[0], q1[1], (1, 0)), (q2[0], q2[1], (1, 0)),
        fadd, fsub, fmul, z))


# -- wire encodings (64-byte G1, 128-byte G2 twist, big-endian) --

def g1_to_bytes(p) -> bytes:
    return p[0].to_bytes(32, "big") + p[1].to_bytes(32, "big")


def g1_from_bytes(raw: bytes):
    if len(raw) != 64:
        raise ValueError("G1 point must be 64 bytes")
    p = (int.from_bytes(raw[:32], "big"), int.from_bytes(raw[32:], "big"))
    if not on_curve_g1(p):
        raise ValueError("G1 point not on curve")
    return p


def g2_to_bytes(q) -> bytes:
    return b"".join(c.to_bytes(32, "big")
                    for c in (q[0][0], q[0][1], q[1][0], q[1][1]))


def g2_msm(pairs, window: int = 4):
    """Host Strauss/interleaved multi-scalar multiplication on the
    twist: sum_i k_i * Q_i with SHARED doublings and per-base
    2^window-entry tables — ~2.5x fewer field ops than independent
    ladders for the 3-term Schnorr verification combination
    (idemix_ps.verify_schnorr). pairs: [(k, Q_affine|None), ...]."""
    fadd, fsub, fmul, z = _fp2_ops()
    one = (1, 0)
    tabs = []
    for k, q in pairs:
        k %= R
        if k == 0 or q is None:
            tabs.append(None)
            continue
        # table[j] = j*Q in Jacobian, j in 1..2^w-1
        tab = [None] * (1 << window)
        tab[1] = (q[0], q[1], one)
        for j in range(2, 1 << window):
            tab[j] = _jac_add_full(tab[j - 1], tab[1], fadd, fsub,
                                   fmul, z)
        tabs.append((k, tab))
    acc = None
    nwin = (256 + window - 1) // window
    for w in reversed(range(nwin)):
        if acc is not None:
            for _ in range(window):
                acc = _jac_dbl(*acc, fadd, fsub, fmul, z)
        for entry in tabs:
            if entry is None:
                continue
            k, tab = entry
            d = (k >> (w * window)) & ((1 << window) - 1)
            if d:
                acc = _jac_add_full(acc, tab[d], fadd, fsub, fmul, z) \
                    if acc is not None else tab[d]
    return _fp2_jac_to_affine(acc)


def f2_conj(a):
    return (a[0], (-a[1]) % P)


_PSI_COEF: list = []


def g2_frobenius_fast(q):
    """psi on the twist via the standard coordinate form
    psi(x, y) = (c_x * conj(x), c_y * conj(y)) — two Fp2 muls instead
    of the untwist round trip through Fp12. The coefficients are
    SELF-CALIBRATED against the exact `g2_frobenius` on two
    independent points at first use (falls back to the exact map if
    the curve convention ever changes)."""
    if q is None:
        return None
    if not _PSI_COEF:
        g2 = (G2_X, G2_Y)
        exact = g2_frobenius(g2)
        cx = f2_mul(exact[0], f2_inv(f2_conj(G2_X)))
        cy = f2_mul(exact[1], f2_inv(f2_conj(G2_Y)))
        probe = g2_mul_fast(123457, g2)
        ok = (f2_mul(cx, f2_conj(probe[0])),
              f2_mul(cy, f2_conj(probe[1]))) == g2_frobenius(probe)
        _PSI_COEF.append((cx, cy) if ok else None)
    coef = _PSI_COEF[0]
    if coef is None:
        return g2_frobenius(q)
    return (f2_mul(coef[0], f2_conj(q[0])),
            f2_mul(coef[1], f2_conj(q[1])))


def g2_in_subgroup(q) -> bool:
    """Prime-order subgroup membership on the twist.

    E'(Fp2) has a large cofactor, so on-curve alone admits
    small-subgroup/invalid points — the classic verifier-facing
    footgun on attacker-supplied G2 inputs (idemix PS presentations
    deserialize commitment points). The reference's idemix pairing
    stacks (amcl / gurvy) reject non-subgroup points at
    deserialization; so does this one. Fast test (Galbraith–Scott):
    psi(Q) == [6x^2]Q — the G2 eigenvalue of the twisted Frobenius is
    t - 1 = 6x^2 for BN curves — a half-length scalar mul instead of
    the full [r]Q == inf check (equivalence asserted in
    tests/test_bn254.py)."""
    if q is None:
        return True
    return g2_frobenius_fast(q) == g2_msm([(6 * T_BN * T_BN, q)])


def g2_from_bytes(raw: bytes, subgroup_check: bool = True):
    """subgroup_check=False defers the prime-order membership test to
    the caller (the idemix MSP batches it on device with the Schnorr
    recombinations) — the on-curve check always runs."""
    if len(raw) != 128:
        raise ValueError("G2 point must be 128 bytes")
    vals = [int.from_bytes(raw[i:i + 32], "big") for i in range(0, 128, 32)]
    q = ((vals[0], vals[1]), (vals[2], vals[3]))
    if not on_curve_g2(q):
        raise ValueError("G2 point not on twist curve")
    if subgroup_check and not g2_in_subgroup(q):
        raise ValueError("G2 point not in the prime-order subgroup")
    return q
