"""Fixed-limb big-integer modular arithmetic on TPU (int32 tensors).

This is the arithmetic core of the TPU BCCSP provider — the rebuild of the
reference's hot verify path (`bccsp/sw/ecdsa.go:41-57` does one
`crypto/ecdsa.Verify` per signature on CPU; here thousands of verifications
run as one fixed-shape XLA program).

Design (TPU-first, no int64):
  * A 256-bit integer is a little-endian vector of ``L = 20`` limbs of
    ``W = 13`` bits each, dtype int32, shape ``(..., 20)``.
  * 13-bit limbs make schoolbook products safe in int32: a product column
    accumulates at most 20 terms of (2^13)^2, and 20 * 2^26 < 2^31.
  * Values are kept **semi-reduced** (< 2^256 + eps, limbs in [0, 2^13])
    rather than canonical; a cheap "fold at 2^256" (v = hi*2^256 + lo ≡
    hi*C + lo mod m, with C = 2^256 mod m precomputed) follows every op.
    Canonical form ([0, m), strict 13-bit limbs) is computed once at the
    end for equality checks.
  * Subtraction adds a precomputed multiple of m redistributed so every
    limb offset is ≥ 2*2^13, keeping all intermediate limbs non-negative —
    carries never have to propagate borrows, so three vectorized
    carry-rounds always settle.
  * Everything is branchless and fixed-shape: `vmap`-able over the batch
    axis and shardable with `shard_map` over a device mesh.

All bounds asserted below were derived for 256-bit moduli (P-256 field
prime and group order); `Mod.__init__` checks its preconditions.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

W = 13                      # bits per limb
L = 20                      # limbs per 256-bit value (13*20 = 260 bits)
MASK = (1 << W) - 1
PROD = 2 * L                # limbs in a schoolbook product


# ---------------------------------------------------------------------------
# Limb layout (parameterized limb count; 13-bit limbs stay)
# ---------------------------------------------------------------------------

class LimbLayout:
    """Limb geometry for one modulus width.

    The module constants above describe the historical 20-limb/256-bit
    layout every existing kernel (P-256, Ed25519, BN254) was built on;
    this object is the same geometry with the limb COUNT a parameter so
    wider primes (BLS12-381's 381-bit field needs 30 limbs) ride the
    identical carry/multiply machinery. 13-bit limbs are load-bearing
    and stay fixed: every int32 bound below is a function of W and L.

    int32 safety, re-derived per layout (ValueError, not assert — a
    silently overflowing column would corrupt field arithmetic):
      * a schoolbook product column accumulates at most L terms of
        (2^W)^2 (redundant limbs reach 2^W inclusive), so the column
        bound is L * 2^(2W);
      * Montgomery REDC adds up to L more terms of u_i * m_limb
        (< 2^(2W) each) into a column that already holds a carried
        (<= 2^W) limb, plus a propagated carry < 2^(31-W);
    both are covered by requiring
        L * 2^(2W) + 2^(31-W) + 2^W  <  2^31
    which admits L <= 31 at W = 13 (L = 32 overflows exactly).
    """

    def __init__(self, nlimbs: int, w: int = W):
        if nlimbs < 1:
            raise ValueError("LimbLayout needs at least one limb")
        worst = nlimbs * (1 << (2 * w)) + (1 << (31 - w)) + (1 << w)
        if worst >= 1 << 31:
            raise ValueError(
                f"limb layout L={nlimbs} W={w} overflows int32 column "
                f"accumulation ({worst} >= 2^31); the schoolbook/REDC "
                f"bound admits at most L={(((1 << 31) - (1 << (31 - w)) - (1 << w)) >> (2 * w))} limbs at W={w}")
        self.W = w
        self.MASK = (1 << w) - 1
        self.L = nlimbs
        self.PROD = 2 * nlimbs

    @property
    def bits(self) -> int:
        """Total representable bits (W * L)."""
        return self.W * self.L

    def max_modulus_bits(self) -> int:
        """Largest modulus width this layout's Montgomery R covers:
        REDC needs 4m < R = 2^(W*L), i.e. bit_length(m) <= W*L - 2."""
        return self.W * self.L - 2

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"LimbLayout(L={self.L}, W={self.W})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, LimbLayout)
                and (self.L, self.W) == (other.L, other.W))

    def __hash__(self) -> int:
        return hash((self.L, self.W))


# the historical layout, as THE default instance: every <=256-bit
# kernel stages through this exact geometry, so existing paths are
# bit-identical by construction
DEFAULT_LAYOUT = LimbLayout(L)


def layout_for_bits(bits: int) -> LimbLayout:
    """Smallest layout whose Montgomery R covers a `bits`-wide odd
    modulus (4m < 2^(W*L) => W*L >= bits + 2). Yields exactly the
    historical 20-limb layout for every 251..258-bit modulus and 30
    limbs for BLS12-381's 381-bit field; widths past ~401 bits fail
    loudly in LimbLayout's int32 column bound."""
    if bits < 1:
        raise ValueError("modulus width must be positive")
    n = -(-(bits + 2) // W)          # ceil((bits + 2) / W)
    if n <= DEFAULT_LAYOUT.L:
        return DEFAULT_LAYOUT
    return LimbLayout(n)


# ---------------------------------------------------------------------------
# Host-side converters (numpy; used to stage inputs/constants)
# ---------------------------------------------------------------------------

def int_to_limbs(x: int, n: int = L) -> np.ndarray:
    """Python int -> little-endian canonical limb vector (numpy int32)."""
    if x < 0:
        raise ValueError("negative")
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= W
    if x:
        raise ValueError("value does not fit in limbs")
    return out


def limbs_to_int(a) -> int:
    """Limb vector (any redundant form) -> Python int."""
    a = np.asarray(a)
    return sum(int(v) << (W * i) for i, v in enumerate(a.tolist()))


def ints_to_limbs(xs, n: int = L) -> np.ndarray:
    """Batch of Python ints -> (B, n) int32 limb array."""
    return np.stack([int_to_limbs(x, n) for x in xs])


def be_bytes_to_limbs(raw: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 big-endian 256-bit values -> (B, L) canonical limbs.

    Fully vectorized (no per-element Python) — this is the host-side
    packing path for whole-block signature batches, where a Python loop
    over 30k values x 20 limbs would dominate the pipeline.
    """
    raw = np.ascontiguousarray(raw, dtype=np.uint8)
    B = raw.shape[0]
    # bit k of the value at bits[:, k] (value little-endian bit order)
    bits = np.unpackbits(raw[:, ::-1], axis=1, bitorder="little")
    bits = np.pad(bits, ((0, 0), (0, L * W - 256)))
    weights = (1 << np.arange(W, dtype=np.int32))
    return (bits.reshape(B, L, W) * weights).sum(axis=2, dtype=np.int32)


def be_bytes_to_limbs_jnp(raw):
    """Device-side (B, 32) uint8 big-endian -> (B, L) limbs.

    Same output as `be_bytes_to_limbs`, expressed in jnp so the
    conversion runs ON DEVICE: the host then ships 32 B/scalar instead
    of 80 B of int32 limbs — the difference matters on tunnel/NIC
    attached accelerators where the verify path is transfer-bound.
    """
    raw = raw.astype(jnp.int32)             # (B, 32), big-endian bytes
    B = raw.shape[0]
    # value bit k (little-endian) = byte (31 - k//8), bit (k % 8)
    k = jnp.arange(L * W)                   # 260 bits; top 4 are zero
    byte_idx = 31 - (k // 8)
    bit_idx = k % 8
    valid = k < 256
    bytes_k = jnp.where(valid, raw[:, jnp.clip(byte_idx, 0, 31)], 0)
    bits = (bytes_k >> bit_idx) & 1         # (B, L*W)
    weights = (1 << jnp.arange(W, dtype=jnp.int32))
    return (bits.reshape(B, L, W) * weights).sum(
        axis=2, dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Carry propagation
# ---------------------------------------------------------------------------

def carry3(x: jnp.ndarray) -> jnp.ndarray:
    """Three vectorized carry rounds: limbs < 2^31 -> limbs in [0, 2^13].

    Requires all input limbs non-negative. Round 1 leaves limbs
    ≤ mask + 2^18, round 2 ≤ mask + 2^5, round 3 ≤ 2^13. The output is a
    valid *redundant* representation (limb value 2^13 = mask+1 allowed),
    safe as multiplication input.
    """
    for _ in range(3):
        lo = x & MASK
        c = x >> W
        x = lo + jnp.pad(c[..., :-1], [(0, 0)] * (c.ndim - 1) + [(1, 0)])
    return x


def full_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Exact sequential carry: non-negative limbs -> strict 13-bit limbs.

    Unrolled over the (static) limb count; each step is a vectorized op
    over the batch, so under `vmap` this costs O(limbs) cheap ops.
    Any carry out of the top limb is dropped (callers guarantee the value
    fits, which holds for all semi-reduced values here).
    """
    n = x.shape[-1]
    outs = []
    c = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
    for i in range(n):
        t = x[..., i] + c
        outs.append(t & MASK)
        c = t >> W
    return jnp.stack(outs, axis=-1)


# ---------------------------------------------------------------------------
# Schoolbook multiply
# ---------------------------------------------------------------------------

def mul_columns(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(…, L) x (…, L) -> (…, 2L) product columns (no carry).

    Inputs must have limbs ≤ 2^13 (redundant ok): max column is
    L * (2^13)^2 = 20 * 2^26 < 2^31.
    """
    na, nb = a.shape[-1], b.shape[-1]
    cols = jnp.zeros(a.shape[:-1] + (na + nb,), dtype=jnp.int32)
    for i in range(na):
        cols = cols.at[..., i : i + nb].add(a[..., i : i + 1] * b)
    return cols


# ---------------------------------------------------------------------------
# Modulus context
# ---------------------------------------------------------------------------

class Mod:
    """Precomputed tables for arithmetic mod a 256-bit modulus ``m``.

    Holds (as numpy constants, closed over by jitted code):
      * ``fold_hi``  — (L, L) rows: canonical limbs of 2^(13*(L+k)) mod m,
        for folding product limbs L..2L-1 in one pass;
      * ``c256``     — canonical limbs of 2^256 mod m (fold-at-256);
      * ``sub_off``  — limbs of 4m redistributed so every limb ≥ 2*2^13
        (non-negative subtraction, see module docstring);
      * ``m_limbs``  — canonical limbs of m.
    """

    def __init__(self, m: int):
        if not (1 << 255) < m < (1 << 256):
            raise ValueError("Mod supports 256-bit moduli")
        self.m = m
        self.m_limbs = int_to_limbs(m)
        self.c256 = int_to_limbs((1 << 256) % m)
        self.fold_hi = np.stack(
            [int_to_limbs(pow(2, W * (L + k), m)) for k in range(L)]
        )
        # 4m redistributed: add 2 units of limb i+1 into limb i (2*2^13 at
        # weight 13i == 2 at weight 13(i+1)), so limbs 0..L-2 gain 16384
        # and limbs 1..L-1 lose 2. Top limb of 4m is ~2^11, safely ≥ 2.
        off = int_to_limbs(4 * m).astype(np.int64)
        off[: L - 1] += 2 << W
        off[1:] -= 2
        # Non-negativity of (a + off - b) per limb: a semi-reduced b has
        # limbs ≤ 2^13 except the top limb ≤ 2^10 (since its value
        # < 2^256 + 2^243 < 2^257 and limb 19 has weight 2^247).
        # ValueError, not assert: wrong-shaped moduli must fail loudly
        # even under python -O — silent wrong residues would corrupt
        # signature verification.
        if not ((off[: L - 1] >= 1 << W).all() and off[L - 1] >= 1 << 10):
            raise ValueError("modulus shape unsupported (sub offsets)")
        if limbs_to_int(off) != 4 * m:
            raise ValueError("internal: sub_off redistribution broken")
        self.sub_off = off.astype(np.int32)
        # _fold256 places a limb-shifted copy of c256 and requires its top
        # two limbs to be zero (c256 < 2^234). True for the P-256 field
        # prime and group order (both have 2^256 mod m < 2^225).
        if (1 << 256) % m >= (1 << 225):
            raise ValueError("modulus shape unsupported (2^256 mod m too big)")

    # -- semi-reduction helpers (all jnp, fixed shape) --

    def _fold256(self, x: jnp.ndarray) -> jnp.ndarray:
        """Fold bits ≥ 256 back in: x ≡ hi*2^256 + lo, and 2^256 ≡ c256
        (mod m), so x ≡ hi*c256 + lo. Input: carried limbs (≤ 2^13) of
        width L, L+1 or L+2 (2^256 sits 9 bits into limb 19); total value
        < 2^(13*width). Output: width L, value < 2^256 + 2^243.
        """
        k = x.shape[-1]
        lo = x[..., :L]
        lo = lo.at[..., L - 1].set(x[..., L - 1] & 0x1FF)
        # hi = x >> 256, reassembled into 13-bit limbs h0, h1.
        h0 = x[..., L - 1] >> 9
        h1 = None
        if k > L:
            h0 = h0 + ((x[..., L] & 0x1FF) << 4)
            h1 = x[..., L] >> 9
            if k > L + 1:
                h1 = h1 + ((x[..., L + 1] & 0x1FF) << 4)
                # any higher bits of limb L+1 would be lost; callers keep
                # total value < 2^274 so h1 < 2^18 and this is exact
        c256 = jnp.asarray(self.c256, dtype=jnp.int32)
        acc = lo + h0[..., None] * c256       # limbs ≤ 2^13 + 2^26
        if h1 is not None:
            # h1 has weight 2^13 relative to h0: add c256 shifted one limb
            # (its top two limbs are zero — asserted in __init__).
            acc = acc.at[..., 1:].add(h1[..., None] * c256[: L - 1])
        return carry3(acc)

    def mulmod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Semi-reduced modular multiply: inputs/output limbs ≤ 2^13,
        output value < 2^256 + 2^243."""
        cols = mul_columns(a, b)              # width 2L
        x = carry3(cols)                      # limbs ≤ 2^13
        lo, hi = x[..., :L], x[..., L:]
        fold = jnp.asarray(self.fold_hi, dtype=jnp.int32)
        acc = jnp.pad(lo, [(0, 0)] * (lo.ndim - 1) + [(0, 2)])
        acc = acc.at[..., :L].add(
            sum(hi[..., k : k + 1] * fold[k] for k in range(L))
        )
        x = carry3(acc)                       # width L+2, value < 2^274
        x = self._fold256(x)                  # width L, value < 2^256+2^243
        x = self._fold256(x)                  # settle to < 2^256 + 2^226
        return x

    def addmod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Semi-reduced add: output < 2^256 + small."""
        s = a + b                             # limbs ≤ 2^14
        s = carry3(jnp.pad(s, [(0, 0)] * (s.ndim - 1) + [(0, 1)]))
        return self._fold256(s)

    def submod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Semi-reduced subtract: a - b + 4m, all limbs stay ≥ 0."""
        off = jnp.asarray(self.sub_off, dtype=jnp.int32)
        s = a + off - b                       # limbs in [0, 2^13+2^14+2^13]
        s = carry3(jnp.pad(s, [(0, 0)] * (s.ndim - 1) + [(0, 1)]))
        return self._fold256(s)

    def canonical(self, a: jnp.ndarray) -> jnp.ndarray:
        """Semi-reduced -> canonical [0, m), strict 13-bit limbs."""
        x = full_carry(a)
        # value < 2^256 + 2^243 < 2m (m > 2^255), so at most two
        # conditional subtractions reach [0, m).
        for _ in range(2):
            x = self._cond_sub_m(x)
        return x

    def _cond_sub_m(self, x: jnp.ndarray) -> jnp.ndarray:
        m_l = jnp.asarray(self.m_limbs, dtype=jnp.int32)
        d = x - m_l
        # sequential signed borrow propagation
        outs = []
        c = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
        for i in range(L):
            t = d[..., i] + c
            outs.append(t & MASK)
            c = t >> W                        # arithmetic shift: borrow=-1
        sub = jnp.stack(outs, axis=-1)
        ge = (c >= 0)[..., None]              # no final borrow -> x >= m
        return jnp.where(ge, sub, x)

    def eq(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Exact equality of two semi-reduced values mod m -> (...,) bool."""
        return jnp.all(self.canonical(a) == self.canonical(b), axis=-1)

    def to_semi(self, x: int) -> np.ndarray:
        """Host: Python int (already < m) -> canonical limbs (valid
        semi-reduced input)."""
        return int_to_limbs(x % self.m)


# ---------------------------------------------------------------------------
# Bit repacking (SHA-256 words -> limbs)
# ---------------------------------------------------------------------------

def words_be_to_limbs(words: jnp.ndarray) -> jnp.ndarray:
    """(…, 8) big-endian uint32 words (a SHA-256 digest) -> (…, L) limbs.

    The digest is interpreted as a 256-bit big-endian integer, exactly as
    the reference's ECDSA verify treats the hash (hashValue -> big.Int).
    """
    w = words.astype(jnp.uint32)
    # value = sum_{j} word[7-j] * 2^(32j)  (big-endian)
    le = w[..., ::-1]
    limbs = []
    for i in range(L):
        bit0 = W * i
        j0, s0 = bit0 // 32, bit0 % 32
        limb = (le[..., j0] >> jnp.uint32(s0)).astype(jnp.uint32)
        if s0 + W > 32 and j0 + 1 < 8:
            limb = limb | (le[..., j0 + 1] << jnp.uint32(32 - s0))
        limbs.append((limb & jnp.uint32(MASK)).astype(jnp.int32))
    return jnp.stack(limbs, axis=-1)
