"""Generic Montgomery modular arithmetic on 13-bit limb tensors.

fabric_tpu.ops.limb.Mod exploits the P-256 prime's sparse form (cheap
fold at 2^256); BN254 — the idemix pairing curve — has a dense 254-bit
prime where that fold diverges. This module provides modulus-generic
arithmetic via word-level Montgomery reduction (REDC) over a
parameterized limb layout (fabric_tpu.ops.limb.LimbLayout): W=13-bit
int32 limbs with the limb COUNT derived from the modulus width, so the
same vmap/shard_map batching serves 251..256-bit primes (the
historical 20-limb layout, bit-identical) and BLS12-381's 381-bit
field (30 limbs) alike.

Value discipline (all bounds proven per layout; R = 2^(W*L)):
  * Every value is kept < 2m with limbs in [0, 2^13] (redundant top ok).
  * mul: T = a*b < 4m^2 < m*R (the layout guarantees 4m < R), so one
    REDC pass returns < 2m. Column accumulators stay < 2^31: the
    product is carried to 13-bit limbs first, then each of the L
    reduction steps adds u_i*m (u_i < 2^13) — a column receives at
    most L such terms plus propagated carries, which is exactly the
    bound LimbLayout re-derives (and rejects) per limb count.
  * add: a + b < 4m, one conditional subtract of 2m -> < 2m.
  * sub: a + off4m - b with off4m = 4m redistributed so every limb
    covers the corresponding limb of any carried value < 2m; result
    < 6m, two conditional subtracts of 2m -> < 2m.

Everything is branchless and fixed-shape (conditional subtraction is a
lane-wise select), exactly like the P-256 path.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from fabric_tpu.ops import limb
from fabric_tpu.ops.limb import MASK, W, carry3, mul_columns


class MontMod:
    """Montgomery context for an odd modulus m.

    `layout` pins the limb geometry; None derives the smallest layout
    covering the modulus width (which is the historical 20-limb layout
    for every 251..258-bit modulus — no numerical change for the
    P-256/Ed25519/BN254 kernels). A layout too narrow for 4m < R, or
    wide enough to overflow int32 column accumulation, fails loudly.

    `unroll=False` emits the REDC sweep as one lax.fori_loop body with
    dynamic slices instead of L unrolled update steps — ~20x smaller
    HLO per multiply, which keeps deep towers (the pairing curves'
    hundreds of muls per Miller step) compilable in minutes instead of
    hours; the unrolled form optimizes better for shallow kernels.
    """

    def __init__(self, m: int, unroll: bool = True,
                 layout: Optional[limb.LimbLayout] = None):
        if m < 3:
            raise ValueError("MontMod needs an odd modulus >= 3")
        if m % 2 == 0:
            raise ValueError("modulus must be odd")
        if layout is None:
            layout = limb.layout_for_bits(m.bit_length())
        if 4 * m >= 1 << (layout.W * layout.L):
            raise ValueError(
                f"modulus is too wide for {layout!r}: REDC needs 4m < R")
        self.layout = layout
        self.L = layout.L
        self.m = m
        self.unroll = unroll
        self.R = 1 << (W * self.L)
        self.m_limbs = limb.int_to_limbs(m, self.L)
        self.two_m_limbs = limb.int_to_limbs(2 * m, self.L)
        self.mprime = (-pow(m, -1, 1 << W)) % (1 << W)
        self.r_mod_m = self.R % m               # mont(1)
        self.r2_mod_m = (self.R * self.R) % m   # to-mont factor
        # 4m redistributed: limbs 0..L-2 gain 2<<W, limbs 1..L-1 lose 2,
        # so every limb dominates the corresponding limb of any carried
        # subtrahend < 2m (limbs <= 2^13; the top limb of a value < 2m
        # is < 2m >> W*(L-1), and off's top limb is ~2x that).
        off = limb.int_to_limbs(4 * m, self.L).astype(np.int64)
        off[: self.L - 1] += 2 << W
        off[1:] -= 2
        if not ((off[: self.L - 1] >= 1 << W).all()
                and off[self.L - 1] > (2 * m) >> (W * (self.L - 1))):
            raise ValueError("modulus shape unsupported (sub offsets)")
        if limb.limbs_to_int(off) != 4 * m:
            raise ValueError("internal: sub_off redistribution broken")
        self.sub_off = off.astype(np.int32)

    # -- host converters --

    def to_mont(self, x: int) -> np.ndarray:
        """Python int -> canonical limbs of x*R mod m."""
        return limb.int_to_limbs((x % self.m) * self.R % self.m, self.L)

    def from_limbs(self, a) -> int:
        """Montgomery-domain limbs -> plain Python int (for tests)."""
        return limb.limbs_to_int(np.asarray(a)) * pow(self.R, -1, self.m) \
            % self.m

    # -- device ops --

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """mont(a*b): inputs < 2m with 13-bit limbs; output likewise."""
        L = self.L
        cols = mul_columns(a, b)                      # width 2L
        pad = [(0, 0)] * (cols.ndim - 1) + [(0, 2)]
        acc = carry3(jnp.pad(cols, pad))              # width 2L+2, <=2^13
        m_l = jnp.asarray(self.m_limbs)
        if self.unroll:
            for i in range(L):
                u = (acc[..., i] * self.mprime) & MASK
                acc = acc.at[..., i:i + L].add(u[..., None] * m_l)
                acc = acc.at[..., i + 1].add(acc[..., i] >> W)
        else:
            from jax import lax

            def step(i, acc):
                col = lax.dynamic_slice_in_dim(
                    acc, i, 1, axis=-1)[..., 0]
                u = (col * self.mprime) & MASK
                window = lax.dynamic_slice_in_dim(acc, i, L, axis=-1)
                window = window + u[..., None] * m_l
                acc = lax.dynamic_update_slice_in_dim(
                    acc, window, i, axis=-1)
                col = lax.dynamic_slice_in_dim(
                    acc, i, 2, axis=-1)
                col = col.at[..., 1].add(col[..., 0] >> W)
                return lax.dynamic_update_slice_in_dim(
                    acc, col, i, axis=-1)

            acc = lax.fori_loop(0, L, step, acc)
        out = carry3(acc[..., L:])                    # width L+2
        # value = T/R + (correction) < m + T/R; callers guarantee
        # T < m*R so out < 2m and its limbs L..L+1 are zero after the
        # conditional subtract below
        out = self._cond_sub_2m(out)
        return out[..., :L]

    def add(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        s = a + b
        s = carry3(jnp.pad(s, [(0, 0)] * (s.ndim - 1) + [(0, 1)]))
        return self._cond_sub_2m(s)[..., :self.L]

    def sub(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        off = jnp.asarray(self.sub_off)
        s = a + off - b
        s = carry3(jnp.pad(s, [(0, 0)] * (s.ndim - 1) + [(0, 1)]))
        s = self._cond_sub_2m(self._cond_sub_2m(s))
        return s[..., :self.L]

    def neg(self, a: jnp.ndarray) -> jnp.ndarray:
        zero = jnp.zeros_like(a)
        return self.sub(zero, a)

    def _cond_sub_2m(self, x: jnp.ndarray) -> jnp.ndarray:
        """x < 4m (any width >= L, carried limbs) -> subtract 2m when
        x >= 2m. Sequential signed borrow, lane-wise select."""
        n = x.shape[-1]
        tm = np.zeros(n, dtype=np.int32)
        tm[:self.L] = self.two_m_limbs
        d = x - jnp.asarray(tm)
        outs = []
        c = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
        for i in range(n):
            t = d[..., i] + c
            outs.append(t & MASK)
            c = t >> W                                # borrow = -1
        sub = jnp.stack(outs, axis=-1)
        ge = (c >= 0)[..., None]
        return jnp.where(ge, sub, x)

    def canonical(self, a: jnp.ndarray) -> jnp.ndarray:
        """< 2m value -> [0, m) strict limbs (equality checks)."""
        x = limb.full_carry(a)
        m_l = jnp.asarray(self.m_limbs)
        d = x - m_l
        outs = []
        c = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
        for i in range(self.L):
            t = d[..., i] + c
            outs.append(t & MASK)
            c = t >> W
        sub = jnp.stack(outs, axis=-1)
        ge = (c >= 0)[..., None]
        return jnp.where(ge, sub, x)
