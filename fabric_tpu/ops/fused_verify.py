"""Fused Pallas verify kernel family: on-device SHA-256 feeding the comb.

Round-20. The staged verify path (bccsp/tpu.py `_dispatch_comb_digest`)
still pays a HOST hash per message lane — BENCH_r03 measured 276k
`host_hashed_lanes` per run, and every one of them is host SHA-256 plus
a 32-byte digest transfer before the device sees work. The
FPGA-ECDSA-engine paper (arXiv:2112.02229, PAPERS.md) shows the winning
shape: a fully pipelined engine where the hash, scalar-mul and compare
stages overlap on the accelerator. This module is that shape for the
TPU:

  stage A (this file's Pallas program): raw SHA-padded message blocks
    stream HBM->VMEM double-buffered (`pltpu.make_async_copy`, two
    slots, one DMA in flight ahead of compute), the scan-structured
    SHA-256 compression from ops/sha256.py runs per lane, the digest
    feeds the mod-n scalar derivation (u1 = e*w, u2 = r*w via the
    limb-leading KMod arithmetic of ops/ptree.py) and the comb WINDOW
    extraction — so what leaves the kernel is not a digest round-trip
    but the (B, nwin) table indices the comb needs;
  stage B: the existing gather + ops/ptree.py VMEM complete-add tree
    (or the XLA tree for q8 dispatches), unchanged and bit-identical;
  resident variant: for key sets whose 8-bit comb tables fit the VMEM
    budget, ONE program runs SHA + scalars + windows + an in-kernel
    table gather + the complete-add tree with the tables pinned in
    VMEM across grid steps (constant index_map) — nothing but the
    verdict bitmap comes back.

Layout matches ops/ptree.py: batch = trailing (sublane, lane) tile,
limb/word index = leading compile-time axis, so every op is an
elementwise VPU op over (rows, BLOCK_B) tiles. The SHA compression
keeps ops/sha256.py's lax.scan structure on purpose: unrolling the 64
rounds makes XLA's fusion search blow up exponentially (measured: 24
unrolled rounds trace in ~0.4 s, 32 rounds take minutes), while the
scan traces one round body.

Differentially tested against sha256.sha256_host / the sw oracle and
pinned bit-identical to the comb_digest path in
tests/test_fused_verify.py.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from fabric_tpu.ops import comb, limb, p256, ptree, sha256
from fabric_tpu.ops.limb import L, MASK, W

BLOCK_B = 512               # batch lanes per kernel program
LANE_ALIGN = ptree.LANE_ALIGN

# VMEM byte budget for the resident variant's pinned tables: the g8 +
# q8 comb tables cost ~1.97 MB per key slot, so 64 MB holds ~31 keys
# with working-set headroom inside the 100 MB compiler limit below.
RESIDENT_TABLE_BUDGET = 64 * 1024 * 1024

_VMEM_LIMIT = 100 * 1024 * 1024


@functools.lru_cache(maxsize=None)
def _fnk() -> ptree.KMod:
    """Limb-leading mod-n arithmetic (scalar field) for in-kernel
    u1/u2 derivation — the KMod twin of comb's `FN` usage."""
    return ptree.KMod(p256.FN)


def _sha_consts() -> np.ndarray:
    """(72, 1) uint32: the 64 SHA-256 round constants followed by the
    8 initial state words. Pallas kernels may not close over array
    constants, so these ride a pinned input (same pattern as
    KMod.pack_consts)."""
    return np.concatenate([np.asarray(sha256._K).reshape(64, 1),
                           np.asarray(sha256._H0).reshape(8, 1)])


# ---------------------------------------------------------------------------
# Kernel body pieces (plain jnp over leading-axis tiles — testable
# outside a kernel, traced inside one)
# ---------------------------------------------------------------------------

def _rotr(x, n: int):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress_rows(state, block, kc):
    """One SHA-256 compression over a lane tile, limb-leading layout.

    state: (8, *t) uint32 rows; block: (16, *t) uint32 message words;
    kc: (64, 1) uint32 round constants (a kernel input — see
    _sha_consts). Mirrors sha256._compress exactly (same scan
    structure — see the module docstring for why the rounds must NOT
    unroll), but keeps every register as a (1, *t) row so the VPU
    sees 2-D tiles.
    """

    def sched_step(win, _):
        wm15 = win[1:2]
        wm2 = win[14:15]
        s0 = _rotr(wm15, 7) ^ _rotr(wm15, 18) ^ (wm15 >> jnp.uint32(3))
        s1 = _rotr(wm2, 17) ^ _rotr(wm2, 19) ^ (wm2 >> jnp.uint32(10))
        wt = win[0:1] + s0 + win[9:10] + s1
        nxt = jnp.concatenate([win[1:], wt], axis=0)
        return nxt, win[0:1]

    win, w_early = lax.scan(sched_step, block, None, length=48)
    w_all = jnp.concatenate([w_early, win[:, None]], axis=0)  # (64,1,*t)

    def round_step(regs, inp):
        a, b, c, d, e, f, g, h = regs
        wt, kt = inp
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    regs0 = tuple(state[i:i + 1] for i in range(8))
    regs, _ = lax.scan(round_step, regs0, (w_all, kc))
    return state + jnp.concatenate(regs, axis=0)


def _words_to_limbs_rows(words):
    """(8, *t) big-endian uint32 digest rows -> (L, *t) int32 limbs.

    The leading-axis twin of limb.words_be_to_limbs — same static
    bit-position bookkeeping, word index on axis 0.
    """
    le = words[::-1]
    rows = []
    for i in range(L):
        bit0 = W * i
        j0, s0 = bit0 // 32, bit0 % 32
        v = le[j0] >> jnp.uint32(s0)
        if s0 + W > 32 and j0 + 1 < 8:
            v = v | (le[j0 + 1] << jnp.uint32(32 - s0))
        rows.append((v & jnp.uint32(MASK)).astype(jnp.int32))
    return jnp.stack(rows, axis=0)


def _windows_rows(u, wbits: int):
    """(L, *t) canonical scalar rows -> (256//wbits, *t) int32 windows.

    The leading-axis twin of comb._windows: window bit positions are
    static, limb indices/shifts resolve at trace time."""
    rows = []
    for i in range(256 // wbits):
        bit0 = i * wbits
        j0, off = bit0 // W, bit0 % W
        v = u[j0] >> off
        got = W - off
        j = j0 + 1
        while got < wbits and j < L:
            v = v | (u[j] << got)
            got += W
            j += 1
        rows.append(v & ((1 << wbits) - 1))
    return jnp.stack(rows, axis=0)


def _sha_scalar_rows(F, shc, blk, nb_live, digests, has_digest, r, w,
                     nb: int):
    """SHA + mod-n scalar derivation for one lane tile.

    shc: the (72, 1) _sha_consts value read from a kernel input; blk:
    (nb*16, bb) uint32 padded message blocks; nb_live: (1, bb)
    int32 per-lane block count (0 for digest-only lanes); digests:
    (8, bb) uint32 precomputed digest words; has_digest: (1, bb) int32;
    r, w: (L, bb) int32 canonical limbs. Returns (words, u1, u2).

    The block loop is a STATIC Python loop with a masked state update
    (exactly sha256.sha256_blocks' fori_loop semantics) — Mosaic has
    no dynamic leading-axis slicing, and nb is tiny (messages bucket
    to a handful of 64-byte blocks).
    """
    bb = blk.shape[-1]
    kc, h0 = shc[:64], shc[64:]
    state = jnp.broadcast_to(h0, (8, bb))
    for j in range(nb):
        nxt = _compress_rows(state, blk[16 * j:16 * (j + 1)], kc)
        live = jnp.int32(j) < nb_live
        state = jnp.where(live, nxt, state)
    words = jnp.where(has_digest != 0, digests, state)
    e = _words_to_limbs_rows(words)
    u1 = F.canonical(F.mulmod(e, w))
    u2 = F.canonical(F.mulmod(r, w))
    return words, u1, u2


# ---------------------------------------------------------------------------
# Stage-A kernels: SHA-256 + scalar derivation + window extraction
# ---------------------------------------------------------------------------

def _sha_kernel(nb, wbits_g, wbits_q, consts, shc, blocks, nblocks,
                digests, has_digest, r, w, w1_out, w2_out, d_out):
    F = _fnk().bind(consts[:])
    words, u1, u2 = _sha_scalar_rows(
        F, shc[:], blocks[0], nblocks[0], digests[0], has_digest[0],
        r[0], w[0], nb)
    d_out[0] = words
    w1_out[0] = _windows_rows(u1, wbits_g)
    w2_out[0] = _windows_rows(u2, wbits_q)


def _sha_kernel_dma(nb, wbits_g, wbits_q, consts, shc, blocks_hbm,
                    nblocks, digests, has_digest, r, w, w1_out, w2_out,
                    d_out, blk_vmem, dma_sem):
    """The streaming variant: `blocks` stays in HBM (memory_space=ANY)
    and each grid step's message tile is DMA'd into one of two VMEM
    slots, with the NEXT step's copy started before this step's
    compute — transfer rides under the SHA rounds instead of
    serializing with them. Only the verdict-feeding windows/digest
    rows come back through blocked outputs."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    ng = pl.num_programs(0)
    slot = lax.rem(i, 2)
    nxt_slot = lax.rem(i + 1, 2)

    @pl.when(i == 0)
    def _start_first():
        pltpu.make_async_copy(blocks_hbm.at[0], blk_vmem.at[0],
                              dma_sem.at[0]).start()

    @pl.when(i + 1 < ng)
    def _prefetch_next():
        pltpu.make_async_copy(blocks_hbm.at[i + 1],
                              blk_vmem.at[nxt_slot],
                              dma_sem.at[nxt_slot]).start()

    pltpu.make_async_copy(blocks_hbm.at[i], blk_vmem.at[slot],
                          dma_sem.at[slot]).wait()

    F = _fnk().bind(consts[:])
    words, u1, u2 = _sha_scalar_rows(
        F, shc[:], blk_vmem[slot], nblocks[0], digests[0],
        has_digest[0], r[0], w[0], nb)
    d_out[0] = words
    w1_out[0] = _windows_rows(u1, wbits_g)
    w2_out[0] = _windows_rows(u2, wbits_q)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _lead(v, g: int, bb: int):
    """(B, rows) -> (g, rows, bb): batch-major flat order per block
    (lane b of grid block i is batch index i*bb + b) — the scal
    staging pattern of ptree.tree_verify_points."""
    rows = v.shape[1]
    return jnp.transpose(v, (1, 0)).reshape(rows, g, bb) \
              .transpose(1, 0, 2)


def _unlead(v, Bp: int, B: int):
    """(g, rows, bb) -> (B, rows): inverse of _lead."""
    rows = v.shape[1]
    return jnp.transpose(v, (1, 0, 2)).reshape(rows, Bp) \
              .transpose(1, 0)[:B]


def sha_windows(blocks, nblocks, digests, has_digest, r_l, w_l, *,
                wbits_g: int = comb.WBITS, wbits_q: int = comb.WBITS,
                interpret=None, block_b: int = BLOCK_B, dma=None):
    """Batched on-device SHA-256 + scalar derivation + comb windows.

    blocks: (B, NB, 16) uint32 SHA-padded message words
    (sha256.pack_messages); nblocks: (B,) int32 live block counts (0
    for digest-only lanes); digests: (B, 8) uint32 precomputed digest
    words; has_digest: (B,) bool; r_l, w_l: (B, L) canonical limbs.

    Returns (w1 (B, 256//wbits_g), w2 (B, 256//wbits_q), words (B, 8))
    — the G-side and Q-side comb table windows of u1 = e*w and
    u2 = r*w (mod n), plus the digest words (for parity checks).

    dma=True (default) streams the message blocks HBM->VMEM through a
    two-slot double buffer; dma=False uses plain blocked VMEM inputs
    (the shape-confirmation path). interpret=None autodetects via
    jaxenv.pallas_interpret().
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        from fabric_tpu.common import jaxenv

        interpret = jaxenv.pallas_interpret()
    if dma is None:
        dma = True

    B, NB = blocks.shape[0], blocks.shape[1]
    NB16 = NB * 16
    bb = min(block_b, _round_up(B, LANE_ALIGN))
    Bp = _round_up(B, bb)
    g = Bp // bb
    if Bp != B:
        pad = [(0, Bp - B)]
        blocks = jnp.pad(blocks, pad + [(0, 0), (0, 0)])
        nblocks = jnp.pad(nblocks, pad)
        digests = jnp.pad(digests, pad + [(0, 0)])
        has_digest = jnp.pad(has_digest, pad)
        r_l = jnp.pad(r_l, pad + [(0, 0)])
        w_l = jnp.pad(w_l, pad + [(0, 0)])

    blk_t = _lead(blocks.astype(jnp.uint32).reshape(Bp, NB16), g, bb)
    nb_t = _lead(nblocks.astype(jnp.int32).reshape(Bp, 1), g, bb)
    dig_t = _lead(digests.astype(jnp.uint32), g, bb)
    hd_t = _lead(has_digest.astype(jnp.int32).reshape(Bp, 1), g, bb)
    r_t = _lead(r_l, g, bb)
    w_t = _lead(w_l, g, bb)

    consts = jnp.asarray(_fnk().pack_consts()).reshape(
        ptree.KMod.NCONST, L, 1)
    shc = jnp.asarray(_sha_consts())
    n1, n2 = 256 // wbits_g, 256 // wbits_q

    def spec(rows):
        return pl.BlockSpec((1, rows, bb), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    cspec = pl.BlockSpec((ptree.KMod.NCONST, L, 1),
                         lambda i: (0, 0, 0), memory_space=pltpu.VMEM)
    shspec = pl.BlockSpec((72, 1), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    if dma:
        kernel = functools.partial(_sha_kernel_dma, NB, wbits_g,
                                   wbits_q)
        blk_spec = pl.BlockSpec(memory_space=pltpu.ANY)
        scratch = [pltpu.VMEM((2, NB16, bb), jnp.uint32),
                   pltpu.SemaphoreType.DMA((2,))]
    else:
        kernel = functools.partial(_sha_kernel, NB, wbits_g, wbits_q)
        blk_spec = spec(NB16)
        scratch = []

    w1, w2, dwords = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[cspec, shspec, blk_spec, spec(1), spec(8), spec(1),
                  spec(L), spec(L)],
        out_specs=[spec(n1), spec(n2), spec(8)],
        out_shape=[jax.ShapeDtypeStruct((g, n1, bb), jnp.int32),
                   jax.ShapeDtypeStruct((g, n2, bb), jnp.int32),
                   jax.ShapeDtypeStruct((g, 8, bb), jnp.uint32)],
        scratch_shapes=scratch,
        compiler_params=ptree.compiler_params(
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(consts, shc, blk_t, nb_t, dig_t, hd_t, r_t, w_t)
    return (_unlead(w1, Bp, B), _unlead(w2, Bp, B),
            _unlead(dwords, Bp, B))


# ---------------------------------------------------------------------------
# Stage B: gather from precomputed windows + the existing tree
# ---------------------------------------------------------------------------

def gather_from_windows(w1, w2, key_idx, g_flat, q_flat, K: int,
                        g16=None, q16: bool = False):
    """comb.comb_gather_points with the window extraction already done
    on device (stage A): (B, M, 3, L) gathered comb points."""
    if g16 is not None:
        win = jnp.arange(comb.NWIN_G16, dtype=jnp.int32)[None, :]
        pts_g = jnp.take(g16, win * comb.NENT_G16 + w1, axis=0)
    else:
        win = jnp.arange(comb.NWIN, dtype=jnp.int32)[None, :]
        pts_g = jnp.take(g_flat, win * comb.NENT + w1, axis=0)
    if q16:
        win = jnp.arange(comb.NWIN_G16, dtype=jnp.int32)[None, :]
        q_idx = (win * K + key_idx[:, None]) * comb.NENT_G16 + w2
    else:
        win = jnp.arange(comb.NWIN, dtype=jnp.int32)[None, :]
        q_idx = (win * K + key_idx[:, None]) * comb.NENT + w2
    pts_q = jnp.take(q_flat, q_idx, axis=0)
    return jnp.concatenate([pts_g, pts_q], axis=1)


def fused_verify_with_tables(blocks, nblocks, key_idx, q_flat, r8, rpn8,
                             w8, premask, digests, has_digest, g16=None,
                             q16: bool = False, tree: str = "pallas",
                             interpret=None, dma=None,
                             block_b: int = BLOCK_B):
    """The fused verify pipeline: device SHA + windows (stage A
    kernel), table gather, complete-add tree — bit-identical verdicts
    to comb.comb_verify_with_tables over host-hashed digests.

    blocks/nblocks: SHA-padded message words + live counts
    (sha256.pack_messages; nblocks 0 on digest-only lanes);
    r8/rpn8/w8: (B, 32) big-endian u8 scalar rows (limb conversion on
    device, same transfer-minimal shape as the comb_digest path);
    digests/has_digest: precomputed digest words for digest-only
    lanes. Table args exactly as comb_verify_with_tables.
    """
    ent = (comb.NWIN_G16 * comb.NENT_G16 if q16
           else comb.NWIN * comb.NENT)
    K = q_flat.shape[0] // ent
    g_flat = jnp.asarray(comb.g_tables()) if g16 is None else None
    r_l = limb.be_bytes_to_limbs_jnp(r8)
    rpn_l = limb.be_bytes_to_limbs_jnp(rpn8)
    w_l = limb.be_bytes_to_limbs_jnp(w8)
    wbits_g = 16 if g16 is not None else comb.WBITS
    wbits_q = 16 if q16 else comb.WBITS
    w1, w2, _ = sha_windows(blocks, nblocks, digests, has_digest, r_l,
                            w_l, wbits_g=wbits_g, wbits_q=wbits_q,
                            interpret=interpret, dma=dma,
                            block_b=block_b)
    pts = gather_from_windows(w1, w2, key_idx, g_flat, q_flat, K,
                              g16=g16, q16=q16)
    if tree == "pallas":
        return ptree.tree_verify_points(pts, r_l, rpn_l, premask,
                                        interpret=interpret)
    X, _, Z = comb._tree_reduce(pts[:, :, 0], pts[:, :, 1],
                                pts[:, :, 2])
    FP = p256.FP
    nonzero = jnp.any(FP.canonical(Z) != 0, axis=-1)
    x_canon = FP.canonical(X)
    ok1 = jnp.all(x_canon == FP.canonical(FP.mulmod(r_l, Z)), axis=-1)
    ok2 = jnp.all(x_canon == FP.canonical(FP.mulmod(rpn_l, Z)),
                  axis=-1)
    return premask & nonzero & (ok1 | ok2)


# ---------------------------------------------------------------------------
# The resident variant: ONE program, tables pinned in VMEM
# ---------------------------------------------------------------------------

def resident_table_bytes(K: int) -> int:
    """VMEM bytes the resident variant pins: the 8-bit G table plus K
    key slots of 8-bit Q table, (NWIN*NENT, 3, L) int32 each."""
    return comb.NWIN * comb.NENT * (1 + K) * 3 * L * 4


def _resident_kernel(nb, K, consts_n, consts_p, shc, g_tab, q_tab,
                     blocks, nblocks, digests, has_digest, key_idx,
                     r, rpn, w, pm, out):
    Fn = _fnk().bind(consts_n[:])
    Fp = ptree._fpk().bind(consts_p[:])
    _, u1, u2 = _sha_scalar_rows(
        Fn, shc[:], blocks[0], nblocks[0], digests[0], has_digest[0],
        r[0], w[0], nb)
    bb = r.shape[-1]
    w1 = _windows_rows(u1, comb.WBITS)          # (NWIN, bb)
    w2 = _windows_rows(u2, comb.WBITS)
    win = lax.broadcasted_iota(jnp.int32, (comb.NWIN, bb), 0)
    g_pts = jnp.take(g_tab[:], win * comb.NENT + w1, axis=0)
    q_idx = (win * K + key_idx[0]) * comb.NENT + w2
    q_pts = jnp.take(q_tab[:], q_idx, axis=0)
    pts = jnp.concatenate([g_pts, q_pts], axis=0)  # (M, bb, 3L)
    M = 2 * comb.NWIN
    pts = pts.reshape(M, bb, 3, L).transpose(2, 3, 0, 1)
    ts, tr = out.shape[1], out.shape[2]
    r_t = r[0].reshape(L, ts, tr)
    rpn_t = rpn[0].reshape(L, ts, tr)
    pm_t = pm[0].reshape(ts, tr)
    res = ptree.tree_body(pts[0], pts[1], pts[2], r_t, rpn_t, pm_t, Fp)
    out[0] = res.astype(jnp.int32)


def fused_verify_resident(blocks, nblocks, key_idx, q_flat, r8, rpn8,
                          w8, premask, digests, has_digest, g_flat=None,
                          *, interpret=None, block_b: int = BLOCK_B):
    """The single-program variant: SHA + scalars + windows + table
    gather + complete-add tree in ONE Pallas program, with the 8-bit
    g/q comb tables pinned in VMEM across grid steps via a constant
    index_map — only the verdict bitmap leaves the device.

    q_flat must be an 8-bit table (comb.build_q_tables) whose
    resident_table_bytes(K) fits the budget; callers gate on that.
    Verdicts are bit-identical to fused_verify_with_tables(tree=
    either). NOTE the in-kernel gather + 64-point tree lower cleanly
    under interpret; on real Mosaic this variant is gated behind the
    same `_tree_impl` guard as the q8 tree (unimplemented lowerings).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        from fabric_tpu.common import jaxenv

        interpret = jaxenv.pallas_interpret()

    K = q_flat.shape[0] // (comb.NWIN * comb.NENT)
    if g_flat is None:
        g_flat = jnp.asarray(comb.g_tables())
    g_tab = jnp.asarray(g_flat).reshape(-1, 3 * L)
    q_tab = jnp.asarray(q_flat).reshape(-1, 3 * L)

    r_l = limb.be_bytes_to_limbs_jnp(r8)
    rpn_l = limb.be_bytes_to_limbs_jnp(rpn8)
    w_l = limb.be_bytes_to_limbs_jnp(w8)

    B, NB = blocks.shape[0], blocks.shape[1]
    NB16 = NB * 16
    bb = min(block_b, _round_up(B, LANE_ALIGN))
    Bp = _round_up(B, bb)
    g = Bp // bb
    if Bp != B:
        pad = [(0, Bp - B)]
        blocks = jnp.pad(blocks, pad + [(0, 0), (0, 0)])
        nblocks = jnp.pad(nblocks, pad)
        key_idx = jnp.pad(key_idx, pad)
        digests = jnp.pad(digests, pad + [(0, 0)])
        has_digest = jnp.pad(has_digest, pad)
        r_l = jnp.pad(r_l, pad + [(0, 0)])
        rpn_l = jnp.pad(rpn_l, pad + [(0, 0)])
        w_l = jnp.pad(w_l, pad + [(0, 0)])
        premask = jnp.pad(premask, pad)

    blk_t = _lead(blocks.astype(jnp.uint32).reshape(Bp, NB16), g, bb)
    nb_t = _lead(nblocks.astype(jnp.int32).reshape(Bp, 1), g, bb)
    dig_t = _lead(digests.astype(jnp.uint32), g, bb)
    hd_t = _lead(has_digest.astype(jnp.int32).reshape(Bp, 1), g, bb)
    ki_t = _lead(key_idx.astype(jnp.int32).reshape(Bp, 1), g, bb)
    r_t = _lead(r_l, g, bb)
    rpn_t = _lead(rpn_l, g, bb)
    w_t = _lead(w_l, g, bb)
    pm_t = premask.astype(jnp.int32).reshape(g, 1, bb)

    consts_n = jnp.asarray(_fnk().pack_consts()).reshape(
        ptree.KMod.NCONST, L, 1)
    consts_p = jnp.asarray(ptree._fpk().pack_consts()).reshape(
        ptree.KMod.NCONST, L, 1, 1)
    M = 2 * comb.NWIN
    ts, tr = ptree._collapse_tile(M, bb)

    def spec(rows):
        return pl.BlockSpec((1, rows, bb), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    def pinned(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, lambda i: (0,) * nd,
                            memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        functools.partial(_resident_kernel, NB, K),
        grid=(g,),
        in_specs=[pinned((ptree.KMod.NCONST, L, 1)),
                  pinned((ptree.KMod.NCONST, L, 1, 1)),
                  pinned((72, 1)),
                  pinned(tuple(g_tab.shape)),
                  pinned(tuple(q_tab.shape)),
                  spec(NB16), spec(1), spec(8), spec(1), spec(1),
                  spec(L), spec(L), spec(L), spec(1)],
        out_specs=pl.BlockSpec((1, ts, tr), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((g, ts, tr), jnp.int32),
        compiler_params=ptree.compiler_params(
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(consts_n, consts_p, jnp.asarray(_sha_consts()), g_tab, q_tab,
      blk_t, nb_t, dig_t, hd_t, ki_t, r_t, rpn_t, w_t, pm_t)
    return out.reshape(Bp)[:B] != 0
