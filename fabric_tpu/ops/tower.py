"""Generic Fp2/Fp6/Fp12 pairing tower over Montgomery limb tensors.

One tower, two curves: the arithmetic that used to live inline in
`ops/bn254.py` (Karatsuba Fp2, xi-folded Fp6, quadratic Fp12, the
complete RCB15 a=0 projective point/line steps, Frobenius maps, the
Fermat inversion scans and the register-machine final-exponentiation
runner) is parameterized here by

  * ``F``       — a `mont.MontMod` context (ANY limb layout: BN254's
                  20-limb/254-bit field and BLS12-381's 30-limb/381-bit
                  field ride the identical code);
  * ``xi``      — the sextic-twist non-residue as an exact small-int
                  Fp2 pair (BN254: 9+u; BLS12-381: 1+u), expanded into
                  branch-free add chains;
  * ``b3_tw``   — 3*b' on the twist, exact Fp2 ints;
  * ``gammas``  — xi^(k*(p-1)/6) for k = 0..5, the p-power Frobenius
                  constants (host-exact ints);
  * ``mtwist``  — the sparse-line placement: a D-type twist's line
                  A + B*w + C*w^3 lands on Fp12 slots (w^0, w, w^3);
                  an M-type twist's scaled line lands on
                  (w^0, w^2, w^3). The Fp2 COEFFICIENT formulas are
                  identical either way (both scalings are killed by
                  the final exponentiation) — only the placement moves.

The tower layout is fixed: Fp2 = Fp[u]/(u^2+1) as (a0, a1);
Fp6 = Fp2[v]/(v^3 - xi) as (c0, c1, c2); Fp12 = Fp6[w]/(w^2 - v) as
(d0, d1). Everything is branchless, fixed-shape, vmap/shard_map-safe —
the ops are plain jnp over the MontMod limb primitives.

`ops/bn254.py` instantiates this with its historical constants and
rebinds its public names onto the instance, so every existing consumer
(and the kernel-parity suites) sees bit-identical arithmetic;
`ops/bls12_381_kernel.py` is the second instantiation.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax


# ---------------------------------------------------------------------------
# Shape-only helpers (no field context)
# ---------------------------------------------------------------------------

def select_pt(mask, a, b):
    """Lane select between two Fp2 point triples; mask: (B,) bool."""
    m = mask[:, None]
    return tuple(
        (jnp.where(m, x[0], y[0]), jnp.where(m, x[1], y[1]))
        for x, y in zip(a, b))


def select_f12(mask, a, b):
    m = mask[:, None]

    def sel(x, y):
        return jnp.where(m, x, y)

    return tuple(
        tuple((sel(x[0], y[0]), sel(x[1], y[1]))
              for x, y in zip(c6a, c6b))
        for c6a, c6b in zip(a, b))


def flat_from_f12(f):
    """Nested-tuple f12 -> (12, ...) stacked coeff tensor."""
    coeffs = [c for half in f for fp2 in half for c in fp2]
    return jnp.stack(coeffs, axis=0)


def f12_from_flat(x):
    return tuple(
        tuple((x[h * 6 + j * 2], x[h * 6 + j * 2 + 1])
              for j in range(3))
        for h in range(2))


def pow_scan(x, e: int, mul, sqr, select):
    """Square-and-multiply by a STATIC positive exponent as a lax.scan
    (keeps the HLO one-body-sized for multi-thousand-bit chains)."""
    bits = [int(b) for b in bin(e)[3:]]          # skip the leading 1
    if not bits:
        return x
    bit_arr = jnp.asarray(np.array(bits, dtype=bool))

    def body(acc, bit):
        acc = sqr(acc)
        acc = select(bit, mul(acc, x), acc)
        return acc, None

    out, _ = lax.scan(body, x, bit_arr)
    return out


# -- the final-exp REGISTER MACHINE --
#
# A monolithic unrolled exponentiation chain (several pow-by-parameter
# scans + dozens of Fp12 muls, each >= 54 Montgomery muls) produces an
# HLO the compilers refuse: the tunnel's remote TPU compiler SIGKILLs
# and the CPU jit OOMs. Instead the whole post-inversion chain runs as
# ONE lax.scan whose body is a tiny f12-op interpreter (MUL/CONJ/FROB
# over a register file), driven by a static instruction program. HLO
# cost: one multiply body, regardless of chain length. The PROGRAM is
# per-curve (BN254's t-chain, BLS12-381's x-chain); the interpreter is
# this tower's.

OP_MUL, OP_CONJ, OP_FROB = 0, 1, 2
NREG = 8


class Asm:
    """Assembles a final-exp chain into (op, dst, a, b) rows."""

    def __init__(self):
        self.rows = []

    def emit(self, op, dst, a, b=0):
        self.rows.append((op, dst, a, b))

    def mul(self, dst, a, b):
        self.emit(OP_MUL, dst, a, b)

    def sqr(self, dst, a):
        self.emit(OP_MUL, dst, a, a)

    def conj(self, dst, a):
        self.emit(OP_CONJ, dst, a)

    def frob(self, dst, a):
        self.emit(OP_FROB, dst, a)

    def copy(self, dst, a):
        self.conj(dst, a)            # conj . conj = identity
        self.conj(dst, dst)

    def pow_static(self, dst, src, tmp, e: int):
        """dst = src^e for a STATIC positive e: square-and-multiply
        over e's bits (src, tmp, dst must be distinct registers)."""
        assert len({dst, src, tmp}) == 3 and e > 0
        self.copy(tmp, src)          # acc <- src (leading bit)
        for b in bin(e)[3:]:
            self.sqr(tmp, tmp)
            if b == "1":
                self.mul(tmp, tmp, src)
        self.copy(dst, tmp)

    def program(self) -> np.ndarray:
        return np.asarray(self.rows, dtype=np.int32)


class Tower:
    """One pairing curve's Fp12 tower over a MontMod limb context."""

    def __init__(self, F, xi, b3_tw, gammas, mtwist: bool = False):
        assert len(xi) == 2 and min(xi) >= 0
        self.F = F
        self.xi = tuple(int(c) for c in xi)
        self.b3_tw = tuple(int(c) % F.m for c in b3_tw)
        self.gammas = [tuple(int(c) % F.m for c in g) for g in gammas]
        assert len(self.gammas) == 6
        self.mtwist = bool(mtwist)

    # -- host constant staging --

    def const_fp2(self, c):
        """Exact Fp2 int pair -> broadcastable Montgomery limb
        constants."""
        F = self.F
        return (jnp.asarray(F.to_mont(c[0])), jnp.asarray(F.to_mont(c[1])))

    def _b3(self, shape):
        return tuple(jnp.broadcast_to(c, shape)
                     for c in self.const_fp2(self.b3_tw))

    def _one(self, shape):
        return jnp.broadcast_to(jnp.asarray(self.F.to_mont(1)), shape)

    # -- Fp small-scalar add chains --

    def fp_small(self, x, k: int):
        """x * k for a small positive static int, via a binary add
        chain (no Montgomery multiply)."""
        F = self.F
        acc = None
        base = x
        while k:
            if k & 1:
                acc = base if acc is None else F.add(acc, base)
            k >>= 1
            if k:
                base = F.add(base, base)
        if acc is None:
            return jnp.zeros_like(x)
        return acc

    # -- Fp2 --

    def f2_add(self, a, b):
        F = self.F
        return (F.add(a[0], b[0]), F.add(a[1], b[1]))

    def f2_sub(self, a, b):
        F = self.F
        return (F.sub(a[0], b[0]), F.sub(a[1], b[1]))

    def f2_mul(self, a, b):
        """Karatsuba: 3 base multiplications."""
        F = self.F
        m0 = F.mul(a[0], b[0])
        m1 = F.mul(a[1], b[1])
        m2 = F.mul(F.add(a[0], a[1]), F.add(b[0], b[1]))
        return (F.sub(m0, m1), F.sub(F.sub(m2, m0), m1))

    def f2_sqr(self, a):
        return self.f2_mul(a, a)

    def f2_scale(self, a, s):
        """Fp2 times an Fp element."""
        F = self.F
        return (F.mul(a[0], s), F.mul(a[1], s))

    def f2_neg(self, a):
        F = self.F
        return (F.neg(a[0]), F.neg(a[1]))

    def f2_conj(self, a):
        return (a[0], self.F.neg(a[1]))

    def f2_mul_xi(self, a):
        """Multiply by xi = x0 + x1*u:
        ((x0*a0 - x1*a1), (x1*a0 + x0*a1)), small-int add chains."""
        x0, x1 = self.xi
        F = self.F
        t0 = F.sub(self.fp_small(a[0], x0), self.fp_small(a[1], x1))
        t1 = F.add(self.fp_small(a[0], x1), self.fp_small(a[1], x0))
        return (t0, t1)

    def f2_small(self, a, k: int):
        """Multiply by a small positive int via a binary add chain."""
        acc = None
        base = a
        while k:
            if k & 1:
                acc = base if acc is None else self.f2_add(acc, base)
            k >>= 1
            if k:
                base = self.f2_add(base, base)
        return acc

    # -- Fp6 --

    def f6_add(self, a, b):
        return tuple(self.f2_add(x, y) for x, y in zip(a, b))

    def f6_sub(self, a, b):
        return tuple(self.f2_sub(x, y) for x, y in zip(a, b))

    def f6_mul(self, a, b):
        f2_mul, f2_add = self.f2_mul, self.f2_add
        c0, c1, c2 = a
        d0, d1, d2 = b
        t0, t1, t2 = f2_mul(c0, d0), f2_mul(c1, d1), f2_mul(c2, d2)
        r0 = f2_add(t0, self.f2_mul_xi(
            f2_add(f2_mul(c1, d2), f2_mul(c2, d1))))
        r1 = f2_add(f2_add(f2_mul(c0, d1), f2_mul(c1, d0)),
                    self.f2_mul_xi(t2))
        r2 = f2_add(f2_add(f2_mul(c0, d2), f2_mul(c2, d0)), t1)
        return (r0, r1, r2)

    def f6_mul_v(self, a):
        """Multiply an Fp6 element by v (v^3 = xi)."""
        return (self.f2_mul_xi(a[2]), a[0], a[1])

    # -- Fp12 --

    def f12_mul(self, a, b):
        a0, a1 = a
        b0, b1 = b
        t0 = self.f6_mul(a0, b0)
        t1 = self.f6_mul(a1, b1)
        r0 = self.f6_add(t0, self.f6_mul_v(t1))
        r1 = self.f6_sub(
            self.f6_mul(self.f6_add(a0, a1), self.f6_add(b0, b1)),
            self.f6_add(t0, t1))
        return (r0, r1)

    def f12_sqr(self, a):
        return self.f12_mul(a, a)

    def f12_conj(self, f):
        """x -> x^(p^6): negate the w half. Inverse inside the
        cyclotomic subgroup (post easy part)."""
        d0, d1 = f
        return (d0, tuple(self.f2_neg(c) for c in d1))

    def f12_one_like(self, x):
        """Fp12 one, broadcast to the batch shape of Fp element x."""
        one = self._one(x.shape)
        z = jnp.zeros_like(x)
        return (((one, z), (z, z), (z, z)), ((z, z), (z, z), (z, z)))

    def f12_frob(self, f):
        """x -> x^p: coefficient-wise Fp2 conjugation times the gamma
        constants (host-exact, differentially pinned vs the curve's
        int reference)."""
        d0, d1 = f

        def g(k, c):
            const = tuple(jnp.broadcast_to(v, c[0].shape)
                          for v in self.const_fp2(self.gammas[k]))
            return self.f2_mul(self.f2_conj(c), const)

        return ((self.f2_conj(d0[0]), g(2, d0[1]), g(4, d0[2])),
                (g(1, d1[0]), g(3, d1[1]), g(5, d1[2])))

    # -- inversion (Fermat scans) --

    def fp_inv(self, x):
        """Montgomery Fermat inverse: x^(p-2) via a static bit scan."""
        F = self.F

        def select(bit, a, b):
            return jnp.where(bit, a, b)

        return pow_scan(x, F.m - 2, F.mul, lambda a: F.mul(a, a),
                        select)

    def f2_inv(self, a):
        F = self.F
        d = self.fp_inv(F.add(F.mul(a[0], a[0]), F.mul(a[1], a[1])))
        return (F.mul(a[0], d), F.mul(F.neg(a[1]), d))

    def f6_inv(self, a):
        """Adjoint/norm method (mirrors the int references)."""
        f2_mul, f2_sub, f2_add = self.f2_mul, self.f2_sub, self.f2_add
        f2_sqr, f2_mul_xi = self.f2_sqr, self.f2_mul_xi
        c0, c1, c2 = a
        t0 = f2_sub(f2_sqr(c0), f2_mul_xi(f2_mul(c1, c2)))
        t1 = f2_sub(f2_mul_xi(f2_sqr(c2)), f2_mul(c0, c1))
        t2 = f2_sub(f2_sqr(c1), f2_mul(c0, c2))
        norm = f2_add(f2_mul(c0, t0),
                      f2_mul_xi(f2_add(f2_mul(c2, t1),
                                       f2_mul(c1, t2))))
        ninv = self.f2_inv(norm)
        return (f2_mul(t0, ninv), f2_mul(t1, ninv), f2_mul(t2, ninv))

    def f12_inv(self, a):
        a0, a1 = a
        t1 = self.f6_mul(a1, a1)
        norm = self.f6_sub(self.f6_mul(a0, a0), self.f6_mul_v(t1))
        ninv = self.f6_inv(norm)
        return (self.f6_mul(a0, ninv),
                tuple(self.f2_neg(c) for c in self.f6_mul(a1, ninv)))

    def f12_select(self, bit, a, b):
        mask = jnp.broadcast_to(bit, a[0][0][0].shape[:1])
        return select_f12(mask, a, b)

    # -- sparse line placement --

    def line_to_f12(self, A, B, C):
        """Sparse line as a full Fp12 element.

        D-type (BN254): the line is A + B*w + C*w^3 with A the
        yP-scaled, B the xP-scaled and C the constant coefficient —
        slots ((A, 0, 0), (B, C, 0)) since w^3 = v*w.

        M-type (BLS12-381): scaling the untwisted line by w^3 and the
        Fp2 denominators (both annihilated by the final exponentiation
        — w^3 lies in Fp4, and (p^12-1)/r contains the factor p^4-1)
        lands the SAME three coefficients on C + B*w^2 + A*w^3, i.e.
        slots ((C, B, 0), (0, A, 0)) with w^2 = v.
        """
        z = (jnp.zeros_like(A[0]), jnp.zeros_like(A[0]))
        if self.mtwist:
            return ((C, B, z), (z, A, z))
        return ((A, z, z), (B, C, z))

    # -- complete twist-curve steps (RCB15 a=0) --

    def g2_dbl_line(self, T, xP, yP):
        """Complete a=0 doubling (RCB15 Alg 9 with b3 on the twist)
        plus the tangent line at T evaluated at P = (xP, yP) in G1.

        T: ((X0,X1),(Y0,Y1),(Z0,Z1)) Fp2 limb tensors. Coefficients
        (scaled by Z^3 — killed by the final exponentiation):
          A = 2*Y*Z^2 * yP,  B = -3*X^2*Z * xP,  C = 3*X^3 - 2*Y^2*Z.
        """
        f2_mul, f2_sqr = self.f2_mul, self.f2_sqr
        f2_add, f2_sub = self.f2_add, self.f2_sub
        f2_small, f2_scale = self.f2_small, self.f2_scale
        X, Y, Z = T
        b3 = self._b3(X[0].shape)
        # line first (uses the pre-doubling T)
        Z2 = f2_sqr(Z)
        X2 = f2_sqr(X)
        YZ = f2_mul(Y, Z)
        A = f2_scale(f2_small(f2_mul(Y, Z2), 2), yP)
        B = f2_scale(self.f2_neg(f2_small(f2_mul(X2, Z), 3)), xP)
        C = f2_sub(f2_small(f2_mul(X2, X), 3),
                   f2_small(f2_mul(Y, YZ), 2))
        # RCB15 Alg 9 doubling
        t0 = f2_sqr(Y)
        Z3 = f2_small(t0, 8)
        t1 = YZ
        t2 = f2_sqr(Z)
        t2 = f2_mul(b3, t2)
        X3 = f2_mul(t2, Z3)
        Y3 = f2_add(t0, t2)
        Z3 = f2_mul(t1, Z3)
        t1 = f2_small(t2, 2)
        t2 = f2_add(t1, t2)
        t0 = f2_sub(t0, t2)
        Y3 = f2_mul(t0, Y3)
        Y3 = f2_add(X3, Y3)
        t1 = f2_mul(X, Y)
        X3 = f2_mul(t0, t1)
        X3 = f2_small(X3, 2)
        return (X3, Y3, Z3), self.line_to_f12(A, B, C)

    def g2_add_line(self, T, Q, xP, yP):
        """Complete a=0 mixed addition T + Q (RCB15 Alg 7 with Z2=1)
        plus the chord line through T, Q evaluated at P.

        Chord coefficients scaled by Z (and the twist scaling):
          A = (X - xQ*Z) * yP,  B = -(Y - yQ*Z) * xP,
          C = (Y - yQ*Z)*xQ - (X - xQ*Z)*yQ.
        """
        f2_mul, f2_add, f2_sub = self.f2_mul, self.f2_add, self.f2_sub
        f2_small, f2_scale = self.f2_small, self.f2_scale
        X1, Y1, Z1 = T
        xQ, yQ = Q
        b3 = self._b3(X1[0].shape)
        # line
        dX = f2_sub(X1, f2_mul(xQ, Z1))
        dY = f2_sub(Y1, f2_mul(yQ, Z1))
        A = f2_scale(dX, yP)
        B = f2_scale(self.f2_neg(dY), xP)
        C = f2_sub(f2_mul(dY, xQ), f2_mul(dX, yQ))
        # RCB15 Alg 7, complete addition for a=0 (general Z2; the
        # twist point Q is affine so Z2 = mont(1))
        one = self._one(X1[0].shape)
        zero = jnp.zeros_like(one)
        X2, Y2, Z2 = xQ, yQ, (one, zero)
        t0 = f2_mul(X1, X2)
        t1 = f2_mul(Y1, Y2)
        t2 = f2_mul(Z1, Z2)
        t3 = f2_mul(f2_add(X1, Y1), f2_add(X2, Y2))
        t3 = f2_sub(t3, f2_add(t0, t1))
        t4 = f2_mul(f2_add(Y1, Z1), f2_add(Y2, Z2))
        t4 = f2_sub(t4, f2_add(t1, t2))
        X3 = f2_mul(f2_add(X1, Z1), f2_add(X2, Z2))
        Y3 = f2_sub(X3, f2_add(t0, t2))      # Y3 = X1*Z2 + X2*Z1
        t0 = f2_small(t0, 3)                 # 3*X1*X2
        t2 = f2_mul(b3, t2)
        Z3 = f2_add(t1, t2)
        t1 = f2_sub(t1, t2)
        Y3 = f2_mul(b3, Y3)
        X3 = f2_mul(t4, Y3)
        X3 = f2_sub(f2_mul(t3, t1), X3)
        Y3 = f2_mul(Y3, t0)
        Y3 = f2_add(f2_mul(t1, Z3), Y3)
        Z3 = f2_mul(Z3, t4)
        Z3 = f2_add(Z3, f2_mul(t0, t3))
        return (X3, Y3, Z3), self.line_to_f12(A, B, C)

    def g2_dbl(self, T):
        """RCB15 Alg 9 complete doubling on the twist (no line)."""
        f2_mul, f2_sqr = self.f2_mul, self.f2_sqr
        f2_add, f2_sub, f2_small = self.f2_add, self.f2_sub, self.f2_small
        X, Y, Z = T
        b3 = self._b3(X[0].shape)
        t0 = f2_sqr(Y)
        Z3 = f2_small(t0, 8)
        t1 = f2_mul(Y, Z)
        t2 = f2_mul(b3, f2_sqr(Z))
        X3 = f2_mul(t2, Z3)
        Y3 = f2_add(t0, t2)
        Z3 = f2_mul(t1, Z3)
        t1 = f2_small(t2, 2)
        t2 = f2_add(t1, t2)
        t0 = f2_sub(t0, t2)
        Y3 = f2_mul(t0, Y3)
        Y3 = f2_add(X3, Y3)
        t1 = f2_mul(X, Y)
        X3 = f2_mul(t0, t1)
        X3 = f2_small(X3, 2)
        return X3, Y3, Z3

    def g2_add_mixed(self, T, Q):
        """RCB15 Alg 7 complete mixed addition T + (affine Q), no
        line."""
        f2_mul, f2_add, f2_sub = self.f2_mul, self.f2_add, self.f2_sub
        f2_small = self.f2_small
        X1, Y1, Z1 = T
        xQ, yQ = Q
        b3 = self._b3(X1[0].shape)
        one = self._one(X1[0].shape)
        zero = jnp.zeros_like(one)
        X2, Y2, Z2 = xQ, yQ, (one, zero)
        t0 = f2_mul(X1, X2)
        t1 = f2_mul(Y1, Y2)
        t2 = f2_mul(Z1, Z2)
        t3 = f2_mul(f2_add(X1, Y1), f2_add(X2, Y2))
        t3 = f2_sub(t3, f2_add(t0, t1))
        t4 = f2_mul(f2_add(Y1, Z1), f2_add(Y2, Z2))
        t4 = f2_sub(t4, f2_add(t1, t2))
        X3 = f2_mul(f2_add(X1, Z1), f2_add(X2, Z2))
        Y3 = f2_sub(X3, f2_add(t0, t2))
        t0 = f2_small(t0, 3)
        t2 = f2_mul(b3, t2)
        Z3 = f2_add(t1, t2)
        t1 = f2_sub(t1, t2)
        Y3 = f2_mul(b3, Y3)
        X3 = f2_mul(t4, Y3)
        X3 = f2_sub(f2_mul(t3, t1), X3)
        Y3 = f2_mul(Y3, t0)
        Y3 = f2_add(f2_mul(t1, Z3), Y3)
        Z3 = f2_mul(Z3, t4)
        Z3 = f2_add(Z3, f2_mul(t0, t3))
        return X3, Y3, Z3

    # -- verdict + final exponentiation --

    def gt_is_one(self, f):
        """(B,) bool: is the GT element the identity? Canonical-compare
        every coefficient (mont(1) for c000, zero elsewhere)."""
        F = self.F
        one = jnp.asarray(F.to_mont(1))
        coeffs = [c for d in f for fp2 in d for c in fp2]
        first = coeffs[0]
        ok = jnp.all(F.canonical(first) ==
                     F.canonical(jnp.broadcast_to(one, first.shape)),
                     axis=-1)
        for c in coeffs[1:]:
            ok = ok & jnp.all(F.canonical(c) == 0, axis=-1)
        return ok

    def run_final_exp(self, f, program):
        """The full final exponentiation on device: seeds the register
        file with (f, 1/f), then executes the curve's static final-exp
        program (registers 0/1 are inputs; the result lands in
        register 0) as the register-machine scan described above."""
        inv = self.f12_inv(f)
        regs0 = jnp.stack(
            [flat_from_f12(f), flat_from_f12(inv)] +
            [jnp.zeros_like(flat_from_f12(f))] * (NREG - 2),
            axis=0)                    # (NREG, 12, ...)
        program = jnp.asarray(program)

        def body(regs, instr):
            op, dst, a, b = instr[0], instr[1], instr[2], instr[3]
            A = f12_from_flat(jnp.take(regs, a, axis=0))
            Bv = f12_from_flat(jnp.take(regs, b, axis=0))
            res = lax.switch(op, [
                lambda: flat_from_f12(self.f12_mul(A, Bv)),
                lambda: flat_from_f12(self.f12_conj(A)),
                lambda: flat_from_f12(self.f12_frob(A)),
            ])
            regs = lax.dynamic_update_index_in_dim(regs, res, dst,
                                                   axis=0)
            return regs, None

        regs, _ = lax.scan(body, regs0, program)
        return f12_from_flat(regs[0])
