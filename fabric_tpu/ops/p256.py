"""Batched ECDSA-P256 verification core on TPU.

Rebuild of `bccsp/sw/ecdsa.go:41-57` (reference: one `crypto/ecdsa.Verify`
per signature on CPU) as a single fixed-shape XLA program over a batch:

    R = u1*G + u2*Q;  accept ⇔ R != ∞ and x(R) mod n == r

TPU-first design decisions:
  * **Complete projective addition** (Renes–Costello–Batina 2015,
    Algorithm 1, homogeneous (X:Y:Z)): one branchless formula handles
    P+Q, P+P, P+∞, ∞+P and P+(−P) for prime-order curves — no
    data-dependent control flow, which XLA requires and GPUs/CPUs fake
    with constant-time selects anyway.
  * **Shamir's trick**: one 256-iteration `lax.fori_loop`, each step one
    doubling plus one addition of table[bit(u1), bit(u2)] ∈
    {∞, G, Q, G+Q} — branchless 4-way select.
  * **No field inversion**: the affine check x(R) == r becomes the
    projective check X == r*Z (and X == (r+n)*Z when r+n < p, covering
    the x mod n wrap), so the whole verify is mul/add/sub mod p.
  * Scalar recombination u1 = e*s⁻¹, u2 = r*s⁻¹ happens on-device mod n;
    only s⁻¹ (one tiny Fermat inverse per signature) is computed on the
    host, keeping the big scalar muls on the MXU-fed VPU.

Host-side pre-validation (DER shape, r/s range, low-S policy, on-curve
pubkeys) lives in fabric_tpu/bccsp — mirroring where the reference
rejects them — so accept/reject here is bit-identical to the `sw` oracle.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from fabric_tpu.ops import limb
from fabric_tpu.ops.limb import L, Mod, W

# NIST P-256 (FIPS 186-4) domain parameters
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
B3 = (3 * B) % P
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5

FP = Mod(P)
FN = Mod(N)

_A_LIMBS = limb.int_to_limbs(A)
_B_LIMBS = limb.int_to_limbs(B)
_B3_LIMBS = limb.int_to_limbs(B3)
_GX_LIMBS = limb.int_to_limbs(GX)
_GY_LIMBS = limb.int_to_limbs(GY)
_ONE_LIMBS = limb.int_to_limbs(1)


# ---------------------------------------------------------------------------
# Reference implementation over Python ints (spec for the limb version;
# also used by tests and host-side table building)
# ---------------------------------------------------------------------------

def cadd_int(p1, p2):
    """Complete projective addition over Python ints (RCB15 Alg. 1)."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    t0 = X1 * X2 % P
    t1 = Y1 * Y2 % P
    t2 = Z1 * Z2 % P
    t3 = (X1 + Y1) * (X2 + Y2) % P
    t3 = (t3 - t0 - t1) % P
    t4 = (X1 + Z1) * (X2 + Z2) % P
    t4 = (t4 - t0 - t2) % P
    t5 = (Y1 + Z1) * (Y2 + Z2) % P
    t5 = (t5 - t1 - t2) % P
    Z3 = (A * t4 + B3 * t2) % P
    X3 = (t1 - Z3) % P
    Z3 = (t1 + Z3) % P
    Y3 = X3 * Z3 % P
    t1 = (t0 + t0 + t0 + A * t2) % P
    t2 = (t0 - A * t2) % P * A % P
    t4 = (B3 * t4 + t2) % P
    Y3 = (Y3 + t1 * t4) % P
    X3 = (t3 * X3 - t5 * t4) % P
    Z3 = (t5 * Z3 + t3 * t1) % P
    return (X3, Y3, Z3)


def cdbl_int(p1):
    """Exception-free projective doubling over ints (RCB15 Alg. 6,
    a = -3). Handles the point at infinity and 2-torsion correctly."""
    X, Y, Z = p1
    t0 = X * X % P
    t1 = Y * Y % P
    t2 = Z * Z % P
    t3 = X * Y % P
    t3 = (t3 + t3) % P
    Z3 = X * Z % P
    Z3 = (Z3 + Z3) % P
    Y3 = B * t2 % P
    Y3 = (Y3 - Z3) % P
    X3 = (Y3 + Y3) % P
    Y3 = (X3 + Y3) % P
    X3 = (t1 - Y3) % P
    Y3 = (t1 + Y3) % P
    Y3 = X3 * Y3 % P
    X3 = X3 * t3 % P
    t3 = (t2 + t2) % P
    t2 = (t2 + t3) % P
    Z3 = B * Z3 % P
    Z3 = (Z3 - t2) % P
    Z3 = (Z3 - t0) % P
    t3 = (Z3 + Z3) % P
    Z3 = (Z3 + t3) % P
    t3 = (t0 + t0) % P
    t0 = (t3 + t0) % P
    t0 = (t0 - t2) % P
    t0 = t0 * Z3 % P
    Y3 = (Y3 + t0) % P
    t0 = Y * Z % P
    t0 = (t0 + t0) % P
    Z3 = t0 * Z3 % P
    X3 = (X3 - Z3) % P
    Z3 = t0 * t1 % P
    Z3 = (Z3 + Z3) % P
    Z3 = (Z3 + Z3) % P
    return (X3, Y3, Z3)


def scalar_mul_int(k, pt):
    """Double-and-add over ints using cadd_int (host/test helper)."""
    acc = (0, 1, 0)
    for bit in bin(k)[2:] if k else "":
        acc = cadd_int(acc, acc)
        if bit == "1":
            acc = cadd_int(acc, pt)
    return acc


def to_affine_int(pt):
    X, Y, Z = pt
    if Z == 0:
        return None
    zi = pow(Z, -1, P)
    return (X * zi % P, Y * zi % P)


# ---------------------------------------------------------------------------
# Limb-tensor implementation
# ---------------------------------------------------------------------------

def _bar(*xs):
    """Optimization barrier: stops XLA elementwise fusion from duplicating
    multi-consumer temporaries (exponential recompute — see sha256.py)."""
    return lax.optimization_barrier(xs)


def cadd(p1, p2):
    """Complete projective addition over limb tensors.

    p1, p2: tuples of (…, L) int32 semi-reduced coordinates.
    Mirrors cadd_int exactly (same RCB15 Alg. 1 sequence).
    """
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    a = jnp.broadcast_to(jnp.asarray(_A_LIMBS), X1.shape)
    b3 = jnp.broadcast_to(jnp.asarray(_B3_LIMBS), X1.shape)
    t0 = FP.mulmod(X1, X2)
    t1 = FP.mulmod(Y1, Y2)
    t2 = FP.mulmod(Z1, Z2)
    t0, t1, t2 = _bar(t0, t1, t2)
    t3 = FP.mulmod(FP.addmod(X1, Y1), FP.addmod(X2, Y2))
    t3 = FP.submod(FP.submod(t3, t0), t1)
    t4 = FP.mulmod(FP.addmod(X1, Z1), FP.addmod(X2, Z2))
    t4 = FP.submod(FP.submod(t4, t0), t2)
    t5 = FP.mulmod(FP.addmod(Y1, Z1), FP.addmod(Y2, Z2))
    t5 = FP.submod(FP.submod(t5, t1), t2)
    t3, t4, t5 = _bar(t3, t4, t5)
    Z3 = FP.addmod(FP.mulmod(a, t4), FP.mulmod(b3, t2))
    X3 = FP.submod(t1, Z3)
    Z3 = FP.addmod(t1, Z3)
    X3, Z3 = _bar(X3, Z3)
    Y3 = FP.mulmod(X3, Z3)
    at2 = FP.mulmod(a, t2)
    n_t1 = FP.addmod(FP.addmod(t0, t0), FP.addmod(t0, at2))
    n_t2 = FP.mulmod(FP.submod(t0, at2), a)
    n_t4 = FP.addmod(FP.mulmod(b3, t4), n_t2)
    n_t1, n_t4, Y3 = _bar(n_t1, n_t4, Y3)
    Y3 = FP.addmod(Y3, FP.mulmod(n_t1, n_t4))
    X3 = FP.submod(FP.mulmod(t3, X3), FP.mulmod(t5, n_t4))
    Z3 = FP.addmod(FP.mulmod(t5, Z3), FP.mulmod(t3, n_t1))
    return _bar(X3, Y3, Z3)


def cdbl(p1):
    """Exception-free projective doubling over limb tensors (RCB15
    Alg. 6, a = -3) — ~35% cheaper than cadd(p, p): 8 muls of which 3
    are squares, vs the complete add's 12. Mirrors cdbl_int exactly."""
    X, Y, Z = p1
    b = jnp.broadcast_to(jnp.asarray(_B_LIMBS), X.shape)
    t0 = FP.mulmod(X, X)
    t1 = FP.mulmod(Y, Y)
    t2 = FP.mulmod(Z, Z)
    t3 = FP.mulmod(X, Y)
    t3 = FP.addmod(t3, t3)
    Z3 = FP.mulmod(X, Z)
    Z3 = FP.addmod(Z3, Z3)
    t0, t1, t2, t3, Z3 = _bar(t0, t1, t2, t3, Z3)
    Y3 = FP.mulmod(b, t2)
    Y3 = FP.submod(Y3, Z3)
    X3 = FP.addmod(Y3, Y3)
    Y3 = FP.addmod(X3, Y3)
    X3 = FP.submod(t1, Y3)
    Y3 = FP.addmod(t1, Y3)
    X3, Y3 = _bar(X3, Y3)
    Y3 = FP.mulmod(X3, Y3)
    X3 = FP.mulmod(X3, t3)
    t3 = FP.addmod(t2, t2)
    t2 = FP.addmod(t2, t3)
    Z3 = FP.mulmod(b, Z3)
    Z3 = FP.submod(Z3, t2)
    Z3 = FP.submod(Z3, t0)
    Z3, t2 = _bar(Z3, t2)
    t3 = FP.addmod(Z3, Z3)
    Z3 = FP.addmod(Z3, t3)
    t3 = FP.addmod(t0, t0)
    t0 = FP.addmod(t3, t0)
    t0 = FP.submod(t0, t2)
    t0, Z3 = _bar(t0, Z3)
    t0 = FP.mulmod(t0, Z3)
    Y3 = FP.addmod(Y3, t0)
    t0 = FP.mulmod(Y, Z)
    t0 = FP.addmod(t0, t0)
    t0, Y3 = _bar(t0, Y3)
    Z3 = FP.mulmod(t0, Z3)
    X3 = FP.submod(X3, Z3)
    Z3 = FP.mulmod(t0, t1)
    Z3 = FP.addmod(Z3, Z3)
    Z3 = FP.addmod(Z3, Z3)
    return _bar(X3, Y3, Z3)


def _select_point(idx, table):
    """Branchless 2^k-way select: idx (B,) in [0, len(table)); table =
    points as tuples of (B, L) or (L,) coordinate arrays. Balanced
    select tree (log2 depth) instead of a linear where-chain."""
    w = idx[:, None]

    def tree(entries, coords):
        if len(entries) == 1:
            return coords[0]
        half = len(entries) // 2
        lo = tree(entries[:half], coords[:half])
        hi = tree(entries[half:], coords[half:])
        return jnp.where(w < entries[half], lo, hi)

    out = []
    for c in range(3):
        coords = [jnp.broadcast_to(t[c], idx.shape + (L,))
                  for t in table]
        out.append(tree(list(range(len(table))), coords))
    return tuple(out)


def double_scalar_mul(u1, u2, qx, qy):
    """R = u1*G + u2*Q for a batch: u1, u2 canonical (B, L) scalars,
    (qx, qy) affine points (B, L). Returns projective (X, Y, Z).

    2-bit Shamir windows: a 16-entry table of i*G + j*Q (i, j in 0..3;
    the G multiples are host-precomputed constants, the Q side costs 11
    adds once per batch), then 128 unrolled steps of two cheap
    doublings plus one table add — ~40% fewer field ops than the
    1-bit/complete-add ladder."""
    Bsz = u1.shape[0]
    ones = jnp.broadcast_to(jnp.asarray(_ONE_LIMBS), (Bsz, L))
    zeros = jnp.zeros((Bsz, L), dtype=jnp.int32)

    def const_pt(k):
        x, y = to_affine_int(scalar_mul_int(k, (GX, GY, 1)))
        return (jnp.asarray(limb.int_to_limbs(x)),
                jnp.asarray(limb.int_to_limbs(y)),
                jnp.asarray(_ONE_LIMBS))

    inf = (zeros, ones, zeros)
    g_pts = [None, const_pt(1), const_pt(2), const_pt(3)]
    q1 = (qx, qy, ones)
    q2 = cdbl(q1)
    q3 = cadd(q2, q1)
    q_pts = [None, q1, q2, q3]

    table = [inf]
    for i in range(1, 4):           # j = 0 column: pure G multiples
        table.append(tuple(jnp.broadcast_to(c, (Bsz, L))
                           for c in g_pts[i]))
    for j in range(1, 4):
        table.append(q_pts[j])      # i = 0 row: pure Q multiples
        for i in range(1, 4):
            gb = tuple(jnp.broadcast_to(c, (Bsz, L))
                       for c in g_pts[i])
            table.append(cadd(gb, q_pts[j]))
    # table[i + 4*j] = i*G + j*Q

    def body(i, acc):
        acc = cdbl(cdbl(acc))
        k = 254 - 2 * i

        def at(scalar):
            # static bit positions per unrolled limb index are not
            # available inside fori_loop; recover both bits with a
            # gather over the limb axis
            j_lo = k // W
            off_lo = k % W
            j_hi = (k + 1) // W
            off_hi = (k + 1) % W
            lo = (lax.dynamic_slice_in_dim(scalar, j_lo, 1,
                                           axis=1)[:, 0] >> off_lo) & 1
            hi = (lax.dynamic_slice_in_dim(scalar, j_hi, 1,
                                           axis=1)[:, 0] >> off_hi) & 1
            return lo + 2 * hi

        sel = _select_point(at(u1) + 4 * at(u2), table)
        return cadd(acc, sel)

    return lax.fori_loop(0, 128, body, inf)


def verify_core(digest_words, qx, qy, r, rpn, w, premask):
    """Batched ECDSA-P256 accept/reject.

    digest_words: (B, 8) uint32 big-endian SHA-256 digest words.
    qx, qy: (B, L) canonical limbs — pubkey affine coordinates (host
        guarantees on-curve, as the reference does via key import).
    r:   (B, L) canonical limbs of the signature r (1 <= r < n).
    rpn: (B, L) canonical limbs of r + n if r + n < p else r (the
        second candidate for x mod n == r).
    w:   (B, L) canonical limbs of s^{-1} mod n (host-computed).
    premask: (B,) bool — host-side DER/range/low-S validity.
    Returns (B,) bool accept mask.
    """
    e = limb.words_be_to_limbs(digest_words)
    u1 = FN.canonical(FN.mulmod(e, w))
    u2 = FN.canonical(FN.mulmod(r, w))
    X, Y, Z = double_scalar_mul(u1, u2, qx, qy)
    z_canon = FP.canonical(Z)
    nonzero = jnp.any(z_canon != 0, axis=-1)
    x_canon = FP.canonical(X)
    ok1 = jnp.all(x_canon == FP.canonical(FP.mulmod(r, Z)), axis=-1)
    ok2 = jnp.all(x_canon == FP.canonical(FP.mulmod(rpn, Z)), axis=-1)
    return premask & nonzero & (ok1 | ok2)
