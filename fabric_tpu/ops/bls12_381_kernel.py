"""Batched BLS12-381 Miller products on device — ROADMAP item 4's lift.

`ops/bls12_381.py` cut the seams (stage_pairs / miller_products /
check_products); this module is the device transcription over the
30-limb/381-bit instance of the parameterized Montgomery core
(fabric_tpu/ops/mont.py + fabric_tpu/ops/limb.LimbLayout): the SAME
generic Fp2/Fp6/Fp12 tower, complete RCB15 twist steps and
register-machine final-exp runner that serve BN254
(fabric_tpu/ops/tower.py), instantiated for the M-type twist over
xi = 1 + u.

Shape (mirrors ops/bn254.py):
  * All staged pairs of one `verify_aggregate` call run as ONE
    fixed-shape batched program: a single lax.scan over the static
    bits of |x| = 0xD201000000010000 computes every pair's Miller
    value in parallel (plain double-and-add — BLS12 curves need none
    of the BN optimal-ate Frobenius corrections; with x negative this
    is e(P,Q)^-1 per pair, exactly mirroring
    bls12_381_ref.miller_loop, which is all a product-equals-one
    check consumes).
  * Lines are evaluated sparsely on the twist. The M-type untwist
    divides by w^2/w^3 where BN254's D-type multiplied; scaling the
    line by w^3 and the projective Fp2 denominators (both killed by
    the final exponentiation: w^3 lies in the Fp4 subfield and
    (p^12-1)/r contains p^4-1) lands the SAME three Fp2 coefficients
    the D-type uses on slots (w^0, w^2, w^3) — `tower.Tower`'s
    mtwist placement.
  * Padded lanes are masked to Fp12 one after the Miller scan, the
    per-pair values tree-reduce into a single product lane, and ONE
    final exponentiation per call — the Hayashida-Hayasaka-Teruya
    chain (3*(p^4-p^2+1)/r = (x-1)^2*(x+p)*(x^2+p^2-1) + 3, pinned as
    bls12_381_ref.final_exponentiation_chain == fast^3, equivalent
    for every ==1 verdict since gcd(3, r) = 1) runs as the tower's
    register-machine scan on that ONE lane.

Differential oracle: bls12_381_ref.miller_loop at matching loop
counts (device/ref ratio stays a single Fp2 * w^(3k) monomial) and
final_exponentiation_chain for the exp program; accept/reject
verdicts are bit-identical to bls12_381_ref.aggregate_verify.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from fabric_tpu.ops import bls12_381_ref as ref
from fabric_tpu.ops import tower
from fabric_tpu.ops.mont import MontMod

# compact-HLO Montgomery over the 381-bit field: layout_for_bits
# derives the 30-limb layout (and re-proves its int32 column bounds)
F = MontMod(ref.P, unroll=False)
L = F.L

# b3 = 3*b' = 12*(1+u) on the M-type twist, as exact Fp2 ints
_B3_TW = ref.f2_mul((3 * ref.B_G1, 0), ref.XI)

# gamma = xi^((p-1)/6): p-power Frobenius constants (host-exact,
# differentially pinned vs ref.f12_frob)
_GAMMA = [ref.pow_xi(k * (ref.P - 1) // 6) for k in range(6)]

_T = tower.Tower(F, xi=ref.XI, b3_tw=_B3_TW, gammas=_GAMMA,
                 mtwist=True)

f2_mul = _T.f2_mul
f6_mul = _T.f6_mul
f12_mul = _T.f12_mul
f12_sqr = _T.f12_sqr
f12_conj = _T.f12_conj
f12_frob = _T.f12_frob
f12_inv = _T.f12_inv
f12_one_like = _T.f12_one_like
g2_dbl_line = _T.g2_dbl_line
g2_add_line = _T.g2_add_line
gt_is_one = _T.gt_is_one
_select_pt = tower.select_pt
_select_f12 = tower.select_f12


# ---------------------------------------------------------------------------
# Batched Miller loop (BLS12 shape: no correction steps)
# ---------------------------------------------------------------------------

def miller_loop_batch(xP, yP, Q, loop: int = ref.X_BLS):
    """f_{loop,Q}(P) for a batch — plain double-and-add.

    xP, yP: (B, L) Montgomery limbs of the G1 points. Q: affine twist
    point ((x0,x1),(y0,y1)) of (B, L) Montgomery limbs. Returns the
    Fp12 Miller value as nested tuples of (B, L) tensors.
    """
    bits = [int(b) for b in bin(loop)[3:]]
    bit_arr = jnp.asarray(np.array(bits, dtype=bool))
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), xP.shape)
    zero = jnp.zeros_like(one)
    T0 = (Q[0], Q[1], (one, zero))
    f0 = f12_one_like(xP)

    def body(carry, bit):
        T, f = carry
        f = f12_sqr(f)
        T, l = g2_dbl_line(T, xP, yP)
        f = f12_mul(f, l)
        Ta, la = g2_add_line(T, Q, xP, yP)
        fa = f12_mul(f, la)
        mask = jnp.broadcast_to(bit, xP.shape[:1])
        T = _select_pt(mask, Ta, T)
        f = _select_f12(mask, fa, f)
        return (T, f), None

    (_, f), _ = lax.scan(body, (T0, f0), bit_arr)
    return f


# ---------------------------------------------------------------------------
# Final exponentiation (device, ONE lane per call)
# ---------------------------------------------------------------------------

def final_exp_program(u: int = ref.X_BLS) -> np.ndarray:
    """Registers: 0=f (input), 1=inv_f (input), 2=m, 3=t0, 4=y1,
    5=y2, 6/7=scratch. Mirrors ref.final_exponentiation_chain
    instruction for instruction (oracle-pinned); `u` is overridable so
    tests can exercise the register machine with tiny chains."""
    A = tower.Asm()
    # easy part: m = frob^2(f^(p^6-1)) * f^(p^6-1)
    A.conj(2, 0)                 # m <- conj(f)
    A.mul(2, 2, 1)               # m <- conj(f)*inv(f) = f^(p^6-1)
    A.frob(6, 2)
    A.frob(6, 6)                 # t <- m^(p^2)
    A.mul(2, 6, 2)               # m <- m^(p^2+1)
    # hard part (HHT chain, x = -u)
    A.pow_static(3, 2, 6, u)
    A.mul(3, 3, 2)               # t0 = m^u * m         = m^-(x-1)
    A.pow_static(4, 3, 6, u)
    A.mul(4, 4, 3)               # y1 = t0^u * t0       = m^((x-1)^2)
    A.pow_static(5, 4, 6, u)
    A.conj(5, 5)                 # conj(y1^u)           = y1^x
    A.frob(6, 4)
    A.mul(5, 5, 6)               # y2 = y1^x * frob(y1) = y1^(x+p)
    A.pow_static(0, 5, 6, u)     # y2^u  (f no longer needed)
    A.pow_static(1, 0, 6, u)     # y2^(u^2) = y2^(x^2)  (inv_f done)
    A.frob(6, 5)
    A.frob(6, 6)                 # frob^2(y2)
    A.mul(1, 1, 6)
    A.conj(6, 5)                 # y2^-1
    A.mul(1, 1, 6)               # y3 = y2^(x^2+p^2-1)
    A.sqr(6, 2)
    A.mul(6, 6, 2)               # m^3
    A.mul(0, 1, 6)               # result = y3 * m^3
    return A.program()


_FINAL_EXP_PROGRAM = final_exp_program()


def final_exp_batch(f, program: np.ndarray | None = None):
    """The full final exponentiation on device as the tower's
    register-machine scan; the default program computes
    ref.final_exponentiation_chain (== fast^3 — verdict-equivalent
    and pinned)."""
    if program is None:
        program = _FINAL_EXP_PROGRAM
    return _T.run_final_exp(f, program)


# ---------------------------------------------------------------------------
# Pair products: Miller -> mask -> tree reduce -> ONE final exp
# ---------------------------------------------------------------------------

def _product_reduce(f):
    """Tree-reduce the batch axis (power-of-two lanes) into lane 0 by
    pairwise Fp12 multiplies — log2(B) sequential f12_muls instead of
    B."""
    import jax

    n = f[0][0][0].shape[0]
    assert n & (n - 1) == 0, "product reduce needs power-of-two lanes"
    while n > 1:
        half = n // 2
        lo = jax.tree_util.tree_map(lambda x: x[:half], f)
        hi = jax.tree_util.tree_map(lambda x: x[half:], f)
        f = f12_mul(lo, hi)
        n = half
    return f


def pairs_product_is_one(xP, yP, qx0, qx1, qy0, qy1, mask,
                         loop: int = ref.X_BLS):
    """prod_i e(P_i, Q_i)^-1 == 1 for ONE aggregate-verify call.

    All tensors (B, L) Montgomery limbs (B a power of two; padded
    lanes carry any valid points with mask=False and contribute the
    identity); mask (B,) bool. Returns a (1,) bool: one Miller scan
    over every pair, one product reduce, ONE final exponentiation.
    """
    f = miller_loop_batch(xP, yP, ((qx0, qx1), (qy0, qy1)), loop=loop)
    f = _select_f12(mask, f, f12_one_like(xP))
    f = _product_reduce(f)
    return gt_is_one(final_exp_batch(f))


# ---------------------------------------------------------------------------
# Host staging + readback
# ---------------------------------------------------------------------------

def stage_pairs(pairs, pad_to: int | None = None):
    """[(g1_point, g2_twist_point) ints] (the bls12_381.stage_pairs
    output) -> (xP, yP, qx0, qx1, qy0, qy1, mask) numpy limb arrays,
    padded to `pad_to` lanes (next power of two when None) with
    masked generator pairs."""
    n = len(pairs)
    assert n >= 1
    if pad_to is None:
        pad_to = 1 << (n - 1).bit_length()
    assert pad_to >= n and pad_to & (pad_to - 1) == 0
    filler = (ref.G1, (ref.G2_X, ref.G2_Y))
    padded = list(pairs) + [filler] * (pad_to - n)
    xP = np.stack([F.to_mont(p[0]) for p, _ in padded])
    yP = np.stack([F.to_mont(p[1]) for p, _ in padded])
    qx0 = np.stack([F.to_mont(q[0][0]) for _, q in padded])
    qx1 = np.stack([F.to_mont(q[0][1]) for _, q in padded])
    qy0 = np.stack([F.to_mont(q[1][0]) for _, q in padded])
    qy1 = np.stack([F.to_mont(q[1][1]) for _, q in padded])
    mask = np.zeros(pad_to, dtype=bool)
    mask[:n] = True
    return xP, yP, qx0, qx1, qy0, qy1, mask


def f12_from_device(f) -> list:
    """Device Fp12 (nested tuples of (B, L) mont limbs) -> list of
    int-reference Fp12 elements, for differential comparison."""
    d0, d1 = f
    B = d0[0][0].shape[0]
    out = []
    for i in range(B):
        def cvt_f2(c):
            return (F.from_limbs(np.asarray(c[0][i])),
                    F.from_limbs(np.asarray(c[1][i])))
        out.append((tuple(cvt_f2(c) for c in d0),
                    tuple(cvt_f2(c) for c in d1)))
    return out


def verify_pairs(pairs, loop: int = ref.X_BLS) -> bool:
    """Host convenience (tests/bench): stage -> device pipeline ->
    scalar verdict. The provider wires the same kernel through its
    _jit/breaker/fault seams instead of calling this."""
    staged = stage_pairs(pairs)
    out = pairs_product_is_one(*[jnp.asarray(a) for a in staged],
                               loop=loop)
    return bool(np.asarray(out)[0])
