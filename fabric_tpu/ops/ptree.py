"""Pallas TPU kernel: the per-signature complete-add tree in VMEM.

Round-2 profiling (ARCHITECTURE.md "Round-2 kernel") showed the comb
pipeline is HBM-bound under plain XLA: one complete add at
(30720, 64, 20) costs ~670 ms because every temporary of the RCB15
formula (~30 of them, ~150 MB each at that shape) materializes to HBM
between fusion islands, while the raw multiply+carry compute is ~100 ms.
This kernel runs the WHOLE 31-add tree (plus the projective verify
check) for a tile of signatures inside one Pallas program, so the
20-limb working set never leaves VMEM.

Layout (the whole point of the kernel):
  * limb index = LEADING axis — a pure compile-time dimension, so limb
    shifts/carries/folds are register renames, never data movement;
  * batch = the (sublane, lane) tile: every arithmetic op is a clean
    elementwise VPU op over (M, BLOCK_B) int32 tiles;
  * the tree pairs points by contiguous halves of the sublane axis
    (point sums are commutative, so halving is as good as
    odd/even interleave and needs no shuffles), re-packing to 8
    sublanes as M shrinks so deep tree levels keep full vregs.

The arithmetic mirrors fabric_tpu/ops/limb.py (13-bit limbs, carry3,
fold-at-2^256, offset subtraction) and fabric_tpu/ops/p256.py cadd
(RCB15 Alg. 1) exactly — same bounds, same semantics, differentially
tested against the Python-int reference. Reference semantics being
accelerated: `bccsp/sw/ecdsa.go:41-57` under the validator pool
(`core/committer/txvalidator/v20/validator.go:180-237`).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from fabric_tpu.ops import limb, p256
from fabric_tpu.ops.limb import L, MASK, W

BLOCK_B = 512               # batch lanes per kernel program

# Lane-count granule for callers that slice a batch into dispatch
# chunks (the provider's overlapped verify pipeline): chunks aligned
# to this never force `tree_verify_points` to pad a partial Mosaic
# tile per chunk, so every pipeline span reuses one compiled shape.
LANE_ALIGN = 128


def aligned_span(lanes: int, mesh_size: int = 1) -> int:
    """Round a requested pipeline-chunk lane count to the kernel/mesh
    granule: a multiple of LANE_ALIGN * mesh_size (floor, min one
    granule) — the chunk shim between the provider's PipelineChunk
    config and the Pallas tree's tile constraints."""
    granule = LANE_ALIGN * max(1, mesh_size)
    return max(granule, (lanes // granule) * granule)


# ---------------------------------------------------------------------------
# Limb-leading modular arithmetic (mirrors limb.Mod, axis 0 = limbs)
# ---------------------------------------------------------------------------

class KMod:
    """limb.Mod twin with the limb axis LEADING instead of trailing.

    Shapes are (nlimbs, *tile); all tile ops are elementwise. Constants
    are reused from the proven limb.Mod instance so the two
    implementations cannot drift.

    Pallas kernels may not close over array constants, so the constant
    vectors are packed into one (NCONST, L) int32 array passed as a
    kernel input and re-bound inside the kernel via `bind()`; outside
    a kernel the numpy closures work directly (plain XLA).
    """

    # packed-constant row layout (see pack_consts)
    _ROWS = ("c256", "sub_off", "m_limbs", "curve_a", "curve_b3")
    NCONST = len(_ROWS) + L                 # + fold_hi rows

    def __init__(self, mod: limb.Mod):
        self.mod = mod
        self.fold_hi = mod.fold_hi          # (L, L) numpy int32
        self.c256 = mod.c256                # (L,)
        self.sub_off = mod.sub_off          # (L,)
        self.m_limbs = mod.m_limbs          # (L,)
        self._bound = None                  # jnp (NCONST, L) when bound

    def pack_consts(self) -> np.ndarray:
        """(NCONST, L) int32: rows [c256, sub_off, m_limbs, A, B3,
        fold_hi[0..L-1]] — the kernel-input twin of the closures."""
        rows = [self.c256, self.sub_off, self.m_limbs, _A_K, _B3_K]
        return np.concatenate(
            [np.stack(rows), self.fold_hi]).astype(np.int32)

    def bind(self, carr) -> "KMod":
        """Shallow copy whose constants come from the packed array
        `carr` (a value read from a kernel input ref)."""
        import copy
        b = copy.copy(self)
        b._bound = carr
        return b

    def _row(self, name: str, like):
        """Constant row -> (L, 1, ...) broadcastable against like."""
        if self._bound is not None:
            # bound array is pre-shaped (NCONST, L, 1, 1): slicing gives
            # a broadcast-ready (L, 1, 1) with no shape cast (Mosaic
            # does not support 1D->3D vector reshapes)
            if name.startswith("fold_hi"):
                idx = len(self._ROWS) + int(name.split(":")[1])
            else:
                idx = self._ROWS.index(name)
            return self._bound[idx]
        else:
            src = {"c256": self.c256, "sub_off": self.sub_off,
                   "m_limbs": self.m_limbs, "curve_a": _A_K,
                   "curve_b3": _B3_K}
            if name.startswith("fold_hi"):
                arr = self.fold_hi[int(name.split(":")[1])]
            else:
                arr = src[name]
            v = jnp.asarray(np.asarray(arr, dtype=np.int32))
        return v.reshape(v.shape + (1,) * (like.ndim - 1))

    # -- carries --

    @staticmethod
    def carry3(x):
        for _ in range(3):
            lo = x & MASK
            c = x >> W
            x = lo + jnp.concatenate(
                [jnp.zeros_like(c[:1]), c[:-1]], axis=0)
        return x

    @staticmethod
    def full_carry(x):
        n = x.shape[0]
        outs = []
        c = jnp.zeros_like(x[0])
        for i in range(n):
            t = x[i] + c
            outs.append(t & MASK)
            c = t >> W
        return jnp.stack(outs, axis=0)

    # -- schoolbook product, limb-leading --

    @staticmethod
    def mul_columns(a, b):
        """(L, *t) x (L, *t) -> (2L, *t) product columns (no carry)."""
        pad_tail = [(0, 0)] * (b.ndim - 1)
        acc = None
        for i in range(L):
            p = a[i][None] * b                          # (L, *t)
            p = jnp.pad(p, [(i, L - i)] + pad_tail)     # place at column i
            acc = p if acc is None else acc + p
        return acc

    def _fold256(self, x):
        """Same contract as limb.Mod._fold256, limb-leading."""
        k = x.shape[0]
        pad_tail = [(0, 0)] * (x.ndim - 1)
        lo = jnp.concatenate([x[:L - 1], (x[L - 1] & 0x1FF)[None]], axis=0)
        h0 = x[L - 1] >> 9
        h1 = None
        if k > L:
            h0 = h0 + ((x[L] & 0x1FF) << 4)
            h1 = x[L] >> 9
            if k > L + 1:
                h1 = h1 + ((x[L + 1] & 0x1FF) << 4)
        c256 = self._row("c256", x)
        acc = lo + h0[None] * c256
        if h1 is not None:
            shifted = h1[None] * c256[:L - 1]
            acc = acc + jnp.pad(shifted, [(1, 0)] + pad_tail)
        return self.carry3(acc)

    def mulmod(self, a, b):
        pad_tail = [(0, 0)] * (a.ndim - 1)
        x = self.carry3(self.mul_columns(a, b))         # (2L, *t)
        lo, hi = x[:L], x[L:]
        folded = None
        for k in range(L):
            t = hi[k][None] * self._row(f"fold_hi:{k}", x)
            folded = t if folded is None else folded + t
        acc = jnp.pad(lo + folded, [(0, 2)] + pad_tail)
        x = self.carry3(acc)
        x = self._fold256(x)
        return self._fold256(x)

    def addmod(self, a, b):
        pad_tail = [(0, 0)] * (a.ndim - 1)
        s = self.carry3(jnp.pad(a + b, [(0, 1)] + pad_tail))
        return self._fold256(s)

    def submod(self, a, b):
        pad_tail = [(0, 0)] * (a.ndim - 1)
        off = self._row("sub_off", a)
        s = self.carry3(jnp.pad(a + off - b, [(0, 1)] + pad_tail))
        return self._fold256(s)

    def _cond_sub_m(self, x):
        d = x - self._row("m_limbs", x)
        outs = []
        c = jnp.zeros_like(x[0])
        for i in range(L):
            t = d[i] + c
            outs.append(t & MASK)
            c = t >> W                      # arithmetic shift: borrow=-1
        sub = jnp.stack(outs, axis=0)
        ge = (c >= 0)[None]
        return jnp.where(ge, sub, x)

    def canonical(self, a):
        x = self.full_carry(a)
        for _ in range(2):
            x = self._cond_sub_m(x)
        return x


@functools.lru_cache(maxsize=None)
def _fpk() -> KMod:
    return KMod(p256.FP)


_A_K = limb.int_to_limbs(p256.A)
_B3_K = limb.int_to_limbs(p256.B3)


def cadd_k(p1, p2, F: KMod | None = None):
    """Complete projective addition, limb-leading (RCB15 Alg. 1).

    p1, p2: tuples of (L, *tile) int32 semi-reduced coordinates.
    Mirrors p256.cadd / p256.cadd_int exactly, minus the XLA
    optimization barriers (Mosaic schedules the kernel itself).
    """
    if F is None:
        F = _fpk()
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    a = F._row("curve_a", X1)
    b3 = F._row("curve_b3", X1)
    t0 = F.mulmod(X1, X2)
    t1 = F.mulmod(Y1, Y2)
    t2 = F.mulmod(Z1, Z2)
    t3 = F.mulmod(F.addmod(X1, Y1), F.addmod(X2, Y2))
    t3 = F.submod(F.submod(t3, t0), t1)
    t4 = F.mulmod(F.addmod(X1, Z1), F.addmod(X2, Z2))
    t4 = F.submod(F.submod(t4, t0), t2)
    t5 = F.mulmod(F.addmod(Y1, Z1), F.addmod(Y2, Z2))
    t5 = F.submod(F.submod(t5, t1), t2)
    Z3 = F.addmod(F.mulmod(a, t4), F.mulmod(b3, t2))
    X3 = F.submod(t1, Z3)
    Z3 = F.addmod(t1, Z3)
    Y3 = F.mulmod(X3, Z3)
    at2 = F.mulmod(a, t2)
    n_t1 = F.addmod(F.addmod(t0, t0), F.addmod(t0, at2))
    n_t2 = F.mulmod(F.submod(t0, at2), a)
    n_t4 = F.addmod(F.mulmod(b3, t4), n_t2)
    Y3 = F.addmod(Y3, F.mulmod(n_t1, n_t4))
    X3 = F.submod(F.mulmod(t3, X3), F.mulmod(t5, n_t4))
    Z3 = F.addmod(F.mulmod(t5, Z3), F.mulmod(t3, n_t1))
    return X3, Y3, Z3


# ---------------------------------------------------------------------------
# The tree body (plain jnp — runs inside the kernel, testable outside)
# ---------------------------------------------------------------------------

def _pack_operand(x, pts: int):
    """(L, S, R) -> (L, 8, S*R//8) when it tightens sublane use.

    Deep tree levels shrink the sublane axis below the vreg height of
    8; merging lanes back into sublanes keeps the VPU full. Only legal
    when the operand's point count is a power of two (so point
    boundaries stay row-aligned — rows are sliced into point halves at
    the NEXT level) and the element count fills whole vregs. Both
    cadd operands are reshaped identically, so elementwise pairing is
    preserved.
    """
    _, S, R = x.shape
    if S >= 8 or pts & (pts - 1) or (S * R) % (8 * 128):
        return x
    return x.reshape(x.shape[0], 8, S * R // 8)


def _inf_rows(x, rows: int):
    """(L, rows, R) point-at-infinity (0 : 1 : 0) coordinate triple."""
    zeros = jnp.zeros_like(x[:, :rows])
    y = zeros.at[0].set(jnp.ones_like(zeros[0]))
    return zeros, y, zeros


def tree_body(X, Y, Z, r, rpn, premask, F: KMod | None = None):
    """(L, M, B) gathered points -> verify mask, all in one trace.

    M is the per-signature point count (32 for 16/16-bit windows).
    Invariant through the loop: the sublane axis holds `pts`
    point-major point slots of equal row span, so slicing the top/bottom
    half of rows pairs every point exactly once (point addition is
    commutative — pairing order is free). The output tile shape equals
    r's tail shape; `_collapse_tile` computes it for callers.
    """
    if F is None:
        F = _fpk()
    pts = X.shape[1]
    while pts > 1:
        if pts % 2:
            rpp = X.shape[1] // pts          # rows per point slot
            ix, iy, iz = _inf_rows(X, rpp)
            X = jnp.concatenate([X, ix], axis=1)
            Y = jnp.concatenate([Y, iy], axis=1)
            Z = jnp.concatenate([Z, iz], axis=1)
            pts += 1
        h = X.shape[1] // 2
        hp = pts // 2
        A = tuple(_pack_operand(v[:, :h], hp) for v in (X, Y, Z))
        Bo = tuple(_pack_operand(v[:, h:], hp) for v in (X, Y, Z))
        X, Y, Z = cadd_k(A, Bo, F)
        pts = hp
    zc = F.canonical(Z)
    nonzero = jnp.any(zc != 0, axis=0)
    xc = F.canonical(X)
    ok1 = jnp.all(xc == F.canonical(F.mulmod(r, Z)), axis=0)
    ok2 = jnp.all(xc == F.canonical(F.mulmod(rpn, Z)), axis=0)
    return (premask != 0) & nonzero & (ok1 | ok2)


# ---------------------------------------------------------------------------
# pallas_call wrapper
# ---------------------------------------------------------------------------

def _kernel(consts, px, py, pz, r, rpn, pm, out):
    F = _fpk().bind(consts[:])
    ts, tr = out.shape[1], out.shape[2]
    r_t = r[0].reshape(L, ts, tr)
    rpn_t = rpn[0].reshape(L, ts, tr)
    pm_t = pm[0].reshape(ts, tr)
    res = tree_body(px[:], py[:], pz[:], r_t, rpn_t, pm_t, F)
    out[0] = res.astype(jnp.int32)


def tree_verify_points(pts, r_l, rpn_l, premask, *, interpret=None,
                       block_b: int = BLOCK_B):
    """Batched R = sum(points); accept iff x(R) ≡ r (mod n).

    pts: (B, M, 3, L) int32 gathered comb points (semi-reduced).
    r_l, rpn_l: (B, L) canonical limbs; premask: (B,) bool.
    Returns (B,) bool. The tree + projective check run as ONE Pallas
    program per `block_b` signatures, entirely in VMEM.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        from fabric_tpu.common import jaxenv

        interpret = jaxenv.pallas_interpret()

    B, M = pts.shape[0], pts.shape[1]
    bb = min(block_b, _round_up(B, 128))
    Bp = _round_up(B, bb)
    if Bp != B:
        pad = [(0, Bp - B)]
        pts = jnp.pad(pts, pad + [(0, 0)] * (pts.ndim - 1))
        r_l = jnp.pad(r_l, pad + [(0, 0)])
        rpn_l = jnp.pad(rpn_l, pad + [(0, 0)])
        premask = jnp.pad(premask, pad)

    # (B, M, 3, L) -> per-coordinate (L, M, B)
    pt = jnp.transpose(pts, (2, 3, 1, 0))
    px, py, pz = pt[0], pt[1], pt[2]

    # scalars get a leading grid axis: Mosaic requires block tails to
    # be (8, 128)-divisible OR equal to the array dims — (1, L, bb)
    # blocks of a (g, L, bb) array satisfy the "equal" clause; the
    # kernel reshapes to the collapsed tile internally
    ts, tr = _collapse_tile(M, bb)
    g = Bp // bb

    def scal(v):
        # (B, L) -> (g, L, bb): batch-major flat order per block
        return jnp.transpose(v, (1, 0)).reshape(L, g, bb) \
                  .transpose(1, 0, 2)

    r_t = scal(r_l)
    rpn_t = scal(rpn_l)
    pm_t = premask.astype(jnp.int32).reshape(g, 1, bb)

    consts = jnp.asarray(_fpk().pack_consts()).reshape(
        KMod.NCONST, L, 1, 1)
    grid = (g,)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((KMod.NCONST, L, 1, 1), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((L, M, bb), lambda i: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((L, M, bb), lambda i: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((L, M, bb), lambda i: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, L, bb), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, L, bb), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bb), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, ts, tr), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((g, ts, tr), jnp.int32),
        compiler_params=compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(consts, px, py, pz, r_t, rpn_t, pm_t)
    return out.reshape(Bp)[:B] != 0


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def compiler_params(**kw):
    """Version-portable Mosaic compiler params: jax >= 0.5 renamed
    `TPUCompilerParams` to `CompilerParams`; the 0.4.x line in the
    wheel-free container only has the old name."""
    from jax.experimental.pallas import tpu as pltpu

    cp = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cp(**kw)


def _collapse_tile(M: int, B: int):
    """The (S, R) tile shape tree_body collapses an (M, B) block to.

    Mirrors tree_body's row/pack bookkeeping exactly (shapes only).
    """
    S, R, pts = M, B, M
    while pts > 1:
        if pts % 2:
            S += S // pts
            pts += 1
        h, hp = S // 2, pts // 2
        if h < 8 and not (hp & (hp - 1)) and (h * R) % (8 * 128) == 0:
            h, R = 8, h * R // 8
        S, pts = h, hp
    return S, R
