"""Batched Ed25519 verification core on TPU.

The device half of the multi-scheme dispatch: where `ops/p256.py`
rebuilds `crypto/ecdsa.Verify` as one fixed-shape XLA program, this
module does the same for Ed25519 — the signature scheme Fabric's
modern-MSP and smart-BFT-style identity scenarios use (the
committee-consensus measurement in PAPERS.md, arXiv:2302.00418, shows
exactly this cost dominating at scale).

Per lane the kernel decides the cofactorless equation

    [S]B + [k](-A) == R

with every policy gate (canonical encodings, S < L, small-order
rejection, challenge k = SHA-512(R‖A‖M) mod L) already applied on the
host by `bccsp/ed25519_host.prep_verify` — mirroring where the P-256
path applies DER/low-S/range gates, so device and host accept/reject
sets are structurally identical.

TPU-first design:
  * Field arithmetic is `ops/mont.MontMod(2^255 - 19)` on the shared
    13-bit/20-limb int32 layout (`ops/limb.py`): the sparse-prime fold
    in `limb.Mod` needs m > 2^255, which 2^255 - 19 misses by a hair —
    Montgomery REDC (the BN254 discipline) covers it with the same
    vmap/shard_map batching. The compact fori_loop REDC form keeps the
    ladder's ~3k multiplies compilable.
  * Extended twisted Edwards coordinates with the COMPLETE a = -1
    addition law (add-2008-hwcd-3): one branchless formula for P+Q,
    P+P and P+∞ — ed25519's d is a non-square and a = -1 a square, so
    completeness holds unconditionally and padded/identity lanes need
    no special casing.
  * [S]B rides a fixed-base 8-bit comb over B (ZERO doublings — 32
    gathered points, 5 tree levels), through the SAME table
    build/persist/sidecar seam as `ops/comb.py` (B is a universal
    constant like G; the table persists beside gtab8.npy).
  * [k](-A) is a per-lane 2-bit Shamir-style ladder (the proven
    `p256.double_scalar_mul` shape): a 4-entry multiples table, then
    128 steps of two doublings plus one branchless table add.
"""

from __future__ import annotations

import functools
import logging

import numpy as np

import jax.numpy as jnp
from jax import lax

from fabric_tpu.bccsp import ed25519_host as edh
from fabric_tpu.ops import comb, limb, mont
from fabric_tpu.ops.limb import L
from fabric_tpu.ops.p256 import _bar

logger = logging.getLogger("ops.ed25519")

P_ED = edh.P
L_ED = edh.L

# compact-REDC Montgomery context: the ladder's multiply count (~3k
# per lane) with unrolled REDC would blow the HLO past what this
# container compiles in minutes (the BN254 tower lesson)
FED = mont.MontMod(P_ED, unroll=False)

WBITS = comb.WBITS              # 8-bit comb windows, as the G/Q tables
NWIN = comb.NWIN
NENT = comb.NENT

_R2 = FED.r2_mod_m              # to-Montgomery factor (int)
_R2_LIMBS = limb.int_to_limbs(_R2)
_ONE_M = limb.int_to_limbs(FED.r_mod_m)          # mont(1)
_D2_M = limb.int_to_limbs(edh.D2 * FED.R % P_ED)  # mont(2d)


def _to_mont(v):
    """Plain canonical limbs -> Montgomery domain (one REDC mul)."""
    return FED.mul(v, jnp.asarray(_R2_LIMBS))


# ---------------------------------------------------------------------------
# Extended twisted Edwards arithmetic over limb tensors (a = -1)
# ---------------------------------------------------------------------------

def ed_add(p, q):
    """Complete addition (add-2008-hwcd-3): tuples of (…, L) int32
    Montgomery-domain coordinates (X, Y, Z, T). Mirrors
    `ed25519_host.pt_add` exactly."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    d2 = jnp.broadcast_to(jnp.asarray(_D2_M), X1.shape)
    a = FED.mul(FED.sub(Y1, X1), FED.sub(Y2, X2))
    b = FED.mul(FED.add(Y1, X1), FED.add(Y2, X2))
    c = FED.mul(FED.mul(T1, d2), T2)
    dd = FED.mul(Z1, Z2)
    dd = FED.add(dd, dd)
    a, b, c, dd = _bar(a, b, c, dd)
    e, f, g, h = FED.sub(b, a), FED.sub(dd, c), FED.add(dd, c), \
        FED.add(b, a)
    e, f, g, h = _bar(e, f, g, h)
    return _bar(FED.mul(e, f), FED.mul(g, h), FED.mul(f, g),
                FED.mul(e, h))


def ed_double(p):
    """a = -1 doubling (dbl-2008-hwcd); complete, ~2 muls cheaper than
    ed_add(p, p). Mirrors `ed25519_host.pt_double` exactly."""
    X1, Y1, Z1, _ = p
    a = FED.mul(X1, X1)
    b = FED.mul(Y1, Y1)
    c = FED.mul(Z1, Z1)
    c = FED.add(c, c)
    xy = FED.add(X1, Y1)
    a, b, c, xy = _bar(a, b, c, xy)
    h = FED.add(a, b)
    e = FED.sub(h, FED.mul(xy, xy))
    g = FED.sub(a, b)
    f = FED.add(c, g)
    e, f, g, h = _bar(e, f, g, h)
    return _bar(FED.mul(e, f), FED.mul(g, h), FED.mul(f, g),
                FED.mul(e, h))


def _identity(shape):
    one = jnp.broadcast_to(jnp.asarray(_ONE_M), shape)
    zero = jnp.zeros(shape, dtype=jnp.int32)
    return (zero, one, one, zero)


# ---------------------------------------------------------------------------
# Fixed-base comb table for B (host-precomputed constants, persisted
# through the comb.py sidecar seam — B is a universal constant like G)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def b_tables() -> np.ndarray:
    """(NWIN * NENT, 4, L) int32 — T_B[i*NENT + j] = j * 2^(8i) * B in
    Montgomery-domain extended coordinates (Z = mont(1), T = X*Y).
    Entry j=0 is the identity. Built once over Python ints (exact),
    persisted beside the G tables ($FABRIC_TPU_EDTAB_CACHE, default
    ~/.cache/fabric_tpu/edtab8.npy, empty string disables) with the
    same sha256 sidecar/verify-on-load/rebuild contract as
    `comb.g_tables` — a corrupt table must rebuild, never feed the
    kernel wrong points."""
    import os
    cache = os.environ.get(
        "FABRIC_TPU_EDTAB_CACHE",
        os.path.expanduser("~/.cache/fabric_tpu/edtab8.npy"))
    if cache:
        try:
            if comb.verify_digest_sidecar(cache) is not False:
                arr = np.load(cache)
                if (arr.dtype == np.int32
                        and arr.shape == (NWIN * NENT, 4, L)):
                    return arr
        except FileNotFoundError:
            pass
        except Exception as e:
            logger.warning("Ed25519 B-table cache %s unreadable (%s); "
                           "rebuilding", cache, e)
    out = np.zeros((NWIN * NENT, 4, L), dtype=np.int32)
    base = edh.from_affine(edh.BX, edh.BY)
    for i in range(NWIN):
        acc = edh._IDENT
        for j in range(NENT):
            if j == 0:
                x, y = 0, 1
            else:
                x, y = edh.to_affine(acc)
            coords = (x, y, 1, x * y % P_ED)
            for c in range(4):
                out[i * NENT + j, c] = limb.int_to_limbs(
                    coords[c] * FED.R % P_ED)
            acc = edh.pt_add(acc, base)
        for _ in range(WBITS):
            base = edh.pt_double(base)
    if cache:
        try:
            os.makedirs(os.path.dirname(cache), exist_ok=True)
            tmp = cache + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                np.save(f, out)
            digest = comb.file_sha256(tmp)
            os.replace(tmp, cache)
            comb.write_digest_sidecar(cache, digest)
        except Exception as e:
            logger.warning("Ed25519 B-table cache persist to %s failed "
                           "(%s); next start rebuilds", cache, e)
    return out


def _tree_reduce4(X, Y, Z, T):
    """(B, M, L) extended-point arrays -> (B, L) sum via log2(M)
    complete-add levels (the comb._tree_reduce shape, 4 coords)."""
    while X.shape[1] > 1:
        if X.shape[1] % 2:          # pad with the identity
            pad = [(0, 0), (0, 1), (0, 0)]
            X = jnp.pad(X, pad)
            T = jnp.pad(T, pad)
            Y = jnp.pad(Y, pad)
            Y = Y.at[:, -1, :].set(jnp.asarray(_ONE_M))
            Z = jnp.pad(Z, pad)
            Z = Z.at[:, -1, :].set(jnp.asarray(_ONE_M))
        X, Y, Z, T = ed_add(
            (X[:, 0::2], Y[:, 0::2], Z[:, 0::2], T[:, 0::2]),
            (X[:, 1::2], Y[:, 1::2], Z[:, 1::2], T[:, 1::2]))
    return X[:, 0], Y[:, 0], Z[:, 0], T[:, 0]


def comb_mul_base(s, tab):
    """[S]B via the fixed-base comb: s (B, L) canonical scalar limbs,
    tab the b_tables() device array. 32 gathered points, zero
    doublings."""
    w = comb._windows(s)                        # (B, NWIN)
    win = jnp.arange(NWIN, dtype=jnp.int32)[None, :]
    pts = jnp.take(tab, win * NENT + w, axis=0)  # (B, NWIN, 4, L)
    return _tree_reduce4(pts[:, :, 0], pts[:, :, 1], pts[:, :, 2],
                         pts[:, :, 3])


def _select4(idx, table):
    """Branchless 4-way point select: idx (B,), table a list of four
    extended points as tuples of (B, L) coords."""
    w = idx[:, None]
    out = []
    for c in range(4):
        lo = jnp.where(w < 1, table[0][c], table[1][c])
        hi = jnp.where(w < 3, table[2][c], table[3][c])
        out.append(jnp.where(w < 2, lo, hi))
    return tuple(out)


def ladder_mul(k, pt):
    """[k]pt for a batch: k (B, L) canonical scalar limbs, pt an
    extended point of (B, L) coords. 2-bit windows, 128 fori_loop
    steps of two doublings + one complete table add (the
    p256.double_scalar_mul shape)."""
    Bsz = k.shape[0]
    ident = _identity((Bsz, L))
    p2 = ed_double(pt)
    p3 = ed_add(p2, pt)
    table = [ident, pt, p2, p3]

    def body(i, acc):
        acc = ed_double(ed_double(acc))
        pos = 254 - 2 * i

        def bit(b):
            j = b // limb.W
            off = b % limb.W
            return (lax.dynamic_slice_in_dim(k, j, 1,
                                             axis=1)[:, 0] >> off) & 1

        sel = _select4(bit(pos) + 2 * bit(pos + 1), table)
        return ed_add(acc, sel)

    return lax.fori_loop(0, 128, body, ident)


# ---------------------------------------------------------------------------
# The batched verify kernel
# ---------------------------------------------------------------------------

def verify_core(tab, s8, k8, anx8, ay8, rx8, ry8, premask):
    """Batched Ed25519 accept/reject.

    tab: b_tables() as a device array (passed in, like q_flat, so the
        provider controls placement/replication under a mesh).
    s8, k8: (B, 32) uint8 big-endian rows — S and the SHA-512
        challenge k (host-reduced mod L; window extraction only, no
        scalar arithmetic on device).
    anx8, ay8: (B, 32) uint8 big-endian affine coordinates of -A.
    rx8, ry8: (B, 32) uint8 big-endian affine coordinates of R.
    premask: (B,) bool — host gate verdicts (encoding canonicality,
        S range, small-order policy); dead lanes carry the identity
        for A/R so the complete formulas stay on curve points.
    Returns (B,) bool accept mask: premask & ([S]B + [k](-A) == R).
    """
    s = limb.be_bytes_to_limbs_jnp(s8)
    k = limb.be_bytes_to_limbs_jnp(k8)
    anx = _to_mont(limb.be_bytes_to_limbs_jnp(anx8))
    ay = _to_mont(limb.be_bytes_to_limbs_jnp(ay8))
    rx = _to_mont(limb.be_bytes_to_limbs_jnp(rx8))
    ry = _to_mont(limb.be_bytes_to_limbs_jnp(ry8))

    sb = comb_mul_base(s, tab)
    neg_a = (anx, ay, jnp.broadcast_to(jnp.asarray(_ONE_M), anx.shape),
             FED.mul(anx, ay))
    ka = ladder_mul(k, neg_a)
    X3, Y3, Z3, _ = ed_add(sb, ka)

    def eq(a, b):
        return jnp.all(FED.canonical(a) == FED.canonical(b), axis=-1)

    okx = eq(X3, FED.mul(rx, Z3))
    oky = eq(Y3, FED.mul(ry, Z3))
    return premask & okx & oky


# -- host staging helper (numpy; the provider's prep path) --

def stage_rows(prep, bucket: int):
    """Pack `prep` — a list of per-lane `ed25519_host.prep_verify`
    results (None = host-rejected) — into the kernel's operand rows.
    Dead/padded lanes carry zero scalars and identity points, so every
    lane's math stays on the curve. Returns (s8, k8, anx8, ay8, rx8,
    ry8, premask)."""
    s8 = np.zeros((bucket, 32), dtype=np.uint8)
    k8 = np.zeros((bucket, 32), dtype=np.uint8)
    anx8 = np.zeros((bucket, 32), dtype=np.uint8)
    ay8 = np.zeros((bucket, 32), dtype=np.uint8)
    rx8 = np.zeros((bucket, 32), dtype=np.uint8)
    ry8 = np.zeros((bucket, 32), dtype=np.uint8)
    premask = np.zeros(bucket, dtype=bool)
    # identity (0, 1) for every dead lane
    one = (1).to_bytes(32, "big")
    ay8[:] = np.frombuffer(one, np.uint8)
    ry8[:] = np.frombuffer(one, np.uint8)
    for i, p in enumerate(prep):
        if p is None:
            continue
        s, k, neg_ax, ay, rx, ry = p
        premask[i] = True
        for row, v in ((s8, s), (k8, k), (anx8, neg_ax), (ay8, ay),
                       (rx8, rx), (ry8, ry)):
            row[i] = np.frombuffer(v.to_bytes(32, "big"), np.uint8)
    return s8, k8, anx8, ay8, rx8, ry8, premask
