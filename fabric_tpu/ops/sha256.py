"""Batched SHA-256 on TPU (uint32 tensor ops, fixed-shape buckets).

Rebuild of the reference's hashing hot path: every signature verification
hashes its message first (`msp/identities.go:179` → `bccsp.Hash` →
`bccsp/sw/hash.go`, SHA-256). Here a whole batch of messages is hashed as
one XLA program: messages are SHA-padded host-side, packed into a fixed
number of 64-byte blocks per bucket, and the compression function runs as a
`lax.fori_loop` over blocks with all lanes advancing in lockstep; lanes
whose message has fewer blocks mask out the extra state updates.

All arithmetic is uint32 (native TPU int32 units; wrap-around add is the
SHA-256 semantics).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

_K = np.array([
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
], dtype=np.uint32)

_H0 = np.array([
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
], dtype=np.uint32)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One SHA-256 compression: state (B, 8), block (B, 16) -> (B, 8).

    Both the message schedule and the 64 rounds run as `lax.scan`s. This
    is not just graph-size hygiene: fully unrolled, XLA's elementwise
    fusion duplicates multi-consumer round values, and the rotating
    8-register dependency makes the recomputation exponential in the
    round count (measured: 24 unrolled rounds ≈ 0.4 s on CPU, 32 rounds
    > 100 s). scan bodies materialize per step, bounding the fusion.
    """
    # message schedule: carry a rolling window of the last 16 words
    def sched_step(win, _):
        # win: (B, 16) = W[t-16..t-1]; emit W[t-16], produce W[t]
        wm15, wm2 = win[..., 1], win[..., 14]
        s0 = _rotr(wm15, 7) ^ _rotr(wm15, 18) ^ (wm15 >> jnp.uint32(3))
        s1 = _rotr(wm2, 17) ^ _rotr(wm2, 19) ^ (wm2 >> jnp.uint32(10))
        wt = win[..., 0] + s0 + win[..., 9] + s1
        new_win = jnp.concatenate([win[..., 1:], wt[..., None]], axis=-1)
        return new_win, win[..., 0]

    win, w_early = lax.scan(sched_step, block, None, length=48)
    # w_early: (48, B) = W[0..47]; win holds W[48..63]
    w_all = jnp.concatenate([w_early, jnp.moveaxis(win, -1, 0)], axis=0)

    def round_step(regs, inp):
        a, b, c, d, e, f, g, h = regs
        wt, kt = inp
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + kt + wt
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    regs0 = tuple(state[..., i] for i in range(8))
    regs, _ = lax.scan(round_step, regs0, (w_all, jnp.asarray(_K)))
    return state + jnp.stack(regs, axis=-1)


def sha256_blocks(blocks: jnp.ndarray, nblocks: jnp.ndarray) -> jnp.ndarray:
    """Hash pre-padded messages.

    blocks: (B, NB, 16) uint32 big-endian words (SHA padding already
        applied host-side; trailing blocks beyond a message's own padded
        length are ignored).
    nblocks: (B,) int32 — number of real (padded) blocks per message.
    Returns (B, 8) uint32 digest words.
    """
    B, NB, _ = blocks.shape
    init = jnp.broadcast_to(jnp.asarray(_H0), (B, 8))

    def body(j, state):
        new = _compress(state, blocks[:, j, :])
        live = (j < nblocks)[:, None]
        return jnp.where(live, new, state)

    return lax.fori_loop(0, NB, body, init)


# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------

def max_message_len(nb: int) -> int:
    """Largest message (bytes) that fits nb SHA-256 blocks after padding."""
    return nb * 64 - 9


def pack_messages(msgs: list[bytes], nb: int) -> tuple[np.ndarray, np.ndarray]:
    """SHA-pad each message and pack into (B, nb, 16) uint32 words + block
    counts. Every message must satisfy len(msg) <= max_message_len(nb).

    Vectorized: one flat-byte scatter plus numpy word assembly instead
    of a per-message Python loop — at 30k lanes the loop was itself a
    measurable slice of host_prep_s (round-20 fused-kernel bench).
    Byte-identical to the per-message reference; pinned by
    tests/test_fused_verify.py::TestPackMessages.
    """
    B = len(msgs)
    out = np.zeros((B, nb, 16), dtype=np.uint32)
    counts = np.zeros((B,), dtype=np.int32)
    if B == 0:
        return out, counts
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=B)
    if lens.max() > max_message_len(nb):
        i = int(np.argmax(lens > max_message_len(nb)))
        raise ValueError(f"message {i} too long for {nb} blocks")
    counts[:] = (lens + 9 + 63) // 64

    # one (B, nb*64) byte plane: message bytes scattered flat (a single
    # flat-index store — the destination of byte j of the join is its
    # row offset plus its position within the message), then the 0x80
    # terminator and the 8-byte big-endian bit length per row
    rowlen = nb * 64
    buf = np.zeros((B, rowlen), dtype=np.uint8)
    total = int(lens.sum())
    if total:
        flat = np.frombuffer(b"".join(msgs), dtype=np.uint8)
        starts = np.concatenate(([0], np.cumsum(lens)[:-1]))
        shift = np.repeat(np.arange(B, dtype=np.int64) * rowlen - starts,
                          lens)
        buf.reshape(-1)[np.arange(total, dtype=np.int64) + shift] = flat
    rows_all = np.arange(B)
    buf[rows_all, lens] = 0x80
    bitlen = 8 * lens.astype(np.uint64)
    tail0 = counts.astype(np.int64) * 64 - 8
    for j in range(8):
        buf[rows_all, tail0 + j] = \
            ((bitlen >> np.uint64(8 * (7 - j))) & np.uint64(0xFF))

    # big-endian 32-bit words in one byteswap pass; blocks past each
    # row's count stay zero because their bytes in buf were never
    # written, matching the per-message reference
    out = buf.view(">u4").astype(np.uint32).reshape(B, nb, 16)
    return np.ascontiguousarray(out), counts


def sha256_host(msgs: list[bytes], nb: int | None = None) -> np.ndarray:
    """Convenience: hash a batch, returning (B, 8) uint32 digest words."""
    if nb is None:
        nb = max((len(m) + 9 + 63) // 64 for m in msgs) if msgs else 1
    blocks, counts = pack_messages(msgs, nb)
    return np.asarray(
        jax.jit(sha256_blocks)(jnp.asarray(blocks), jnp.asarray(counts))
    )
