"""Batched BN254 optimal-ate Miller loop on TPU (idemix stretch).

The reference's identity mixer verifies BBS+ credentials with pairings
over BN254 (vendored `IBM/idemix`, wired at `msp/idemix.go`). Its hot
verify path computes a pairing product PER credential on CPU; here the
Miller loop — the data-dependent bulk of the pairing — runs for a whole
batch of (P, Q) pairs as one fixed-shape XLA program over the
Montgomery limb engine (fabric_tpu/ops/mont.py).

TPU-first shape:
  * G2 state stays on the twist E'(Fp2): y^2 = x^3 + 3/(9+u), in
    HOMOGENEOUS projective coordinates with the complete a=0
    add/double formulas (Renes-Costello-Batina Algs 7/9) — branchless,
    fixed-shape, safe at every edge case.
  * Line functions are evaluated sparsely: l = A + B*w + C*w^3 with
    A,B,C in Fp2 (coefficients scaled by Fp2 denominators, which the
    final exponentiation kills).
  * The loop is one lax.scan over the STATIC bit array of 6t+2; the
    addition step is always computed and folded in with a lane-wide
    select (bits are compile-time constants but a scan keeps the HLO
    one-body-sized).
  * The optimal-ate Frobenius correction points pi_p(Q), -pi_{p^2}(Q)
    live on the twist, so the host precomputes them with exact int
    arithmetic (fabric_tpu/ops/bn254_ref.g2_frobenius) and the device
    runs two more add+line steps.

The final exponentiation stays on the host for now (one f12_pow per
batch element over the int reference) — the Miller loop is ~99% of the
per-credential field work once the exponent bits are fixed.

Differential oracle: fabric_tpu/ops/bn254_ref.miller_loop at matching
loop counts (tests run truncated loops on CPU; the full 6t+2 loop is
exercised on real hardware via bench paths).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from fabric_tpu.ops import bn254_ref as ref
from fabric_tpu.ops import limb
from fabric_tpu.ops.limb import L
from fabric_tpu.ops.mont import MontMod

# compact-HLO Montgomery: the Miller scan body holds hundreds of muls
F = MontMod(ref.P, unroll=False)

# b3 = 3 * b' = 9/(9+u) on the twist, as exact Fp2 ints
_XI_INV = ref.f2_inv(ref.XI)
_B_TW = ref.f2_mul((3, 0), _XI_INV)
_B3_TW = ref.f2_mul((3, 0), ref.f2_mul((3, 0), _XI_INV))


def _const_fp2(c):
    """Exact Fp2 int pair -> broadcastable Montgomery limb constants."""
    return (jnp.asarray(F.to_mont(c[0])), jnp.asarray(F.to_mont(c[1])))


# ---------------------------------------------------------------------------
# Tower arithmetic over Montgomery limb tensors
# Fp2 = (a0, a1); Fp6 = (c0, c1, c2) of Fp2; Fp12 = (d0, d1) of Fp6
# ---------------------------------------------------------------------------

def f2_add(a, b):
    return (F.add(a[0], b[0]), F.add(a[1], b[1]))


def f2_sub(a, b):
    return (F.sub(a[0], b[0]), F.sub(a[1], b[1]))


def f2_mul(a, b):
    """Karatsuba: 3 base multiplications."""
    m0 = F.mul(a[0], b[0])
    m1 = F.mul(a[1], b[1])
    m2 = F.mul(F.add(a[0], a[1]), F.add(b[0], b[1]))
    return (F.sub(m0, m1), F.sub(F.sub(m2, m0), m1))


def f2_sqr(a):
    return f2_mul(a, a)


def f2_scale(a, s):
    """Fp2 times an Fp element."""
    return (F.mul(a[0], s), F.mul(a[1], s))


def f2_neg(a):
    return (F.neg(a[0]), F.neg(a[1]))


def f2_mul_xi(a):
    """Multiply by xi = 9 + u: (9a0 - a1, a0 + 9a1)."""
    def x9(x):
        x2 = F.add(x, x)
        x4 = F.add(x2, x2)
        x8 = F.add(x4, x4)
        return F.add(x8, x)
    return (F.sub(x9(a[0]), a[1]), F.add(a[0], x9(a[1])))


def f2_small(a, k: int):
    """Multiply by a small positive int via a binary add chain."""
    acc = None
    base = a
    while k:
        if k & 1:
            acc = base if acc is None else f2_add(acc, base)
        k >>= 1
        if k:
            base = f2_add(base, base)
    return acc


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_mul(a, b):
    c0, c1, c2 = a
    d0, d1, d2 = b
    t0, t1, t2 = f2_mul(c0, d0), f2_mul(c1, d1), f2_mul(c2, d2)
    r0 = f2_add(t0, f2_mul_xi(f2_add(f2_mul(c1, d2), f2_mul(c2, d1))))
    r1 = f2_add(f2_add(f2_mul(c0, d1), f2_mul(c1, d0)), f2_mul_xi(t2))
    r2 = f2_add(f2_add(f2_mul(c0, d2), f2_mul(c2, d0)), t1)
    return (r0, r1, r2)


def f6_mul_v(a):
    """Multiply an Fp6 element by v (v^3 = xi)."""
    return (f2_mul_xi(a[2]), a[0], a[1])


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    r0 = f6_add(t0, f6_mul_v(t1))
    r1 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)),
                f6_add(t0, t1))
    return (r0, r1)


def f12_sqr(a):
    return f12_mul(a, a)


def _f2_zero_like(x):
    z = jnp.zeros_like(x[0])
    return (z, z)


def f12_one_like(x):
    """Fp12 one, broadcast to the batch shape of Fp element x."""
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), x.shape)
    z = jnp.zeros_like(x)
    return (((one, z), (z, z), (z, z)), ((z, z), (z, z), (z, z)))


def line_to_f12(A, B, C):
    """Sparse line A + B*w + C*w^3 as a full Fp12 element
    (w^3 = v*w -> coefficient c1 of the second Fp6 component)."""
    z = _f2_zero_like(A)
    return ((A, z, z), (B, C, z))


# ---------------------------------------------------------------------------
# Twist-curve steps with line evaluation
# ---------------------------------------------------------------------------

def g2_dbl_line(T, xP, yP):
    """Complete a=0 doubling (RCB15 Alg 9 with b3 on the twist) plus
    the tangent line at T evaluated at P = (xP, yP) in G1.

    T: ((X0,X1),(Y0,Y1),(Z0,Z1)) Fp2 limb tensors. Line coefficients
    (see module docstring): scaled by Z^3,
      A = 2*Y*Z^2 * yP,  B = -3*X^2*Z * xP,  C = 3*X^3 - 2*Y^2*Z.
    """
    X, Y, Z = T
    b3 = tuple(jnp.broadcast_to(c, X[0].shape)
               for c in _const_fp2(_B3_TW))
    # line first (uses the pre-doubling T)
    Z2 = f2_sqr(Z)
    X2 = f2_sqr(X)
    YZ = f2_mul(Y, Z)
    A = f2_scale(f2_small(f2_mul(Y, Z2), 2), yP)
    B = f2_scale(f2_neg(f2_small(f2_mul(X2, Z), 3)), xP)
    C = f2_sub(f2_small(f2_mul(X2, X), 3), f2_small(f2_mul(Y, YZ), 2))
    # RCB15 Alg 9 doubling
    t0 = f2_sqr(Y)
    Z3 = f2_small(t0, 8)
    t1 = YZ
    t2 = f2_sqr(Z)
    t2 = f2_mul(b3, t2)
    X3 = f2_mul(t2, Z3)
    Y3 = f2_add(t0, t2)
    Z3 = f2_mul(t1, Z3)
    t1 = f2_small(t2, 2)
    t2 = f2_add(t1, t2)
    t0 = f2_sub(t0, t2)
    Y3 = f2_mul(t0, Y3)
    Y3 = f2_add(X3, Y3)
    t1 = f2_mul(X, Y)
    X3 = f2_mul(t0, t1)
    X3 = f2_small(X3, 2)
    return (X3, Y3, Z3), line_to_f12(A, B, C)


def g2_add_line(T, Q, xP, yP):
    """Complete a=0 mixed addition T + Q (RCB15 Alg 7 with Z2=1) plus
    the chord line through T, Q evaluated at P.

    Chord coefficients scaled by Z:
      A = (X - xQ*Z) * yP,  B = -(Y - yQ*Z) * xP,
      C = (Y - yQ*Z)*xQ - (X - xQ*Z)*yQ.
    """
    X1, Y1, Z1 = T
    xQ, yQ = Q
    b3 = tuple(jnp.broadcast_to(c, X1[0].shape)
               for c in _const_fp2(_B3_TW))
    # line
    dX = f2_sub(X1, f2_mul(xQ, Z1))
    dY = f2_sub(Y1, f2_mul(yQ, Z1))
    A = f2_scale(dX, yP)
    B = f2_scale(f2_neg(dY), xP)
    C = f2_sub(f2_mul(dY, xQ), f2_mul(dX, yQ))
    # RCB15 Alg 7, complete addition for a=0 (general Z2; the twist
    # point Q is affine so Z2 = mont(1))
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), X1[0].shape)
    zero = jnp.zeros_like(one)
    X2, Y2, Z2 = xQ, yQ, (one, zero)
    t0 = f2_mul(X1, X2)
    t1 = f2_mul(Y1, Y2)
    t2 = f2_mul(Z1, Z2)
    t3 = f2_mul(f2_add(X1, Y1), f2_add(X2, Y2))
    t3 = f2_sub(t3, f2_add(t0, t1))
    t4 = f2_mul(f2_add(Y1, Z1), f2_add(Y2, Z2))
    t4 = f2_sub(t4, f2_add(t1, t2))
    X3 = f2_mul(f2_add(X1, Z1), f2_add(X2, Z2))
    Y3 = f2_sub(X3, f2_add(t0, t2))      # Y3 = X1*Z2 + X2*Z1
    t0 = f2_small(t0, 3)                 # 3*X1*X2
    t2 = f2_mul(b3, t2)
    Z3 = f2_add(t1, t2)
    t1 = f2_sub(t1, t2)
    Y3 = f2_mul(b3, Y3)
    X3 = f2_mul(t4, Y3)
    X3 = f2_sub(f2_mul(t3, t1), X3)
    Y3 = f2_mul(Y3, t0)
    Y3 = f2_add(f2_mul(t1, Z3), Y3)
    Z3 = f2_mul(Z3, t4)
    Z3 = f2_add(Z3, f2_mul(t0, t3))
    return (X3, Y3, Z3), line_to_f12(A, B, C)


# ---------------------------------------------------------------------------
# Batched Miller loop
# ---------------------------------------------------------------------------

def _select_pt(mask, a, b):
    """Lane select between two Fp2 point triples; mask: (B,) bool."""
    m = mask[:, None]
    return tuple(
        (jnp.where(m, x[0], y[0]), jnp.where(m, x[1], y[1]))
        for x, y in zip(a, b))


def _select_f12(mask, a, b):
    m = mask[:, None]

    def sel(x, y):
        return jnp.where(m, x, y)

    return tuple(
        tuple((sel(x[0], y[0]), sel(x[1], y[1]))
              for x, y in zip(c6a, c6b))
        for c6a, c6b in zip(a, b))


def miller_loop_batch(xP, yP, Q, Q1, nQ2, loop: int = ref.ATE_LOOP):
    """f_{loop,Q}(P) for a batch, with optimal-ate corrections.

    xP, yP: (B, L) Montgomery limbs of the G1 points.
    Q, Q1, nQ2: affine twist points as ((x0,x1),(y0,y1)) of (B, L)
    Montgomery limbs — Q1 = pi_p(Q) and nQ2 = -pi_{p^2}(Q) are
    host-precomputed (exact ints, ref.g2_frobenius).
    Returns the Fp12 Miller value as nested tuples of (B, L) tensors.
    """
    bits = [int(b) for b in bin(loop)[3:]]
    bit_arr = jnp.asarray(np.array(bits, dtype=bool))
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), xP.shape)
    zero = jnp.zeros_like(one)
    T0 = (Q[0], Q[1], ((one, zero)))
    f0 = f12_one_like(xP)

    def body(carry, bit):
        T, f = carry
        f = f12_sqr(f)
        T, l = g2_dbl_line(T, xP, yP)
        f = f12_mul(f, l)
        Ta, la = g2_add_line(T, Q, xP, yP)
        fa = f12_mul(f, la)
        mask = jnp.broadcast_to(bit, xP.shape[:1])
        T = _select_pt(mask, Ta, T)
        f = _select_f12(mask, fa, f)
        return (T, f), None

    (T, f), _ = lax.scan(body, (T0, f0), bit_arr)
    # optimal-ate corrections
    T, l1 = g2_add_line(T, Q1, xP, yP)
    f = f12_mul(f, l1)
    _, l2 = g2_add_line(T, nQ2, xP, yP)
    f = f12_mul(f, l2)
    return f


# ---------------------------------------------------------------------------
# Host staging + verification helpers
# ---------------------------------------------------------------------------

def stage_g1(points) -> tuple[np.ndarray, np.ndarray]:
    """[(x, y) ints] -> (B, L) Montgomery limb arrays."""
    xs = np.stack([F.to_mont(p[0]) for p in points])
    ys = np.stack([F.to_mont(p[1]) for p in points])
    return xs, ys


def stage_g2(points):
    """[((x0,x1),(y0,y1)) ints] -> twist-point limb tuples + the
    host-precomputed Frobenius correction points."""
    def pack(pts):
        return ((np.stack([F.to_mont(p[0][0]) for p in pts]),
                 np.stack([F.to_mont(p[0][1]) for p in pts])),
                (np.stack([F.to_mont(p[1][0]) for p in pts]),
                 np.stack([F.to_mont(p[1][1]) for p in pts])))

    q1s = [ref.g2_frobenius(q) for q in points]
    nq2s = [ref.g2_neg_tw(ref.g2_frobenius(q1)) for q1 in q1s]
    return pack(points), pack(q1s), pack(nq2s)


def f12_from_device(f) -> list:
    """Device Fp12 (nested tuples of (B, L) mont limbs) -> list of
    int-reference Fp12 elements, for differential comparison."""
    d0, d1 = f
    B = d0[0][0].shape[0]
    out = []
    for i in range(B):
        def cvt_f2(c):
            return (F.from_limbs(np.asarray(c[0][i])),
                    F.from_limbs(np.asarray(c[1][i])))
        out.append((tuple(cvt_f2(c) for c in d0),
                    tuple(cvt_f2(c) for c in d1)))
    return out
