"""Batched BN254 optimal-ate Miller loop on TPU (idemix stretch).

The reference's identity mixer verifies BBS+ credentials with pairings
over BN254 (vendored `IBM/idemix`, wired at `msp/idemix.go`). Its hot
verify path computes a pairing product PER credential on CPU; here the
Miller loop — the data-dependent bulk of the pairing — runs for a whole
batch of (P, Q) pairs as one fixed-shape XLA program over the
Montgomery limb engine (fabric_tpu/ops/mont.py).

TPU-first shape:
  * G2 state stays on the twist E'(Fp2): y^2 = x^3 + 3/(9+u), in
    HOMOGENEOUS projective coordinates with the complete a=0
    add/double formulas (Renes-Costello-Batina Algs 7/9) — branchless,
    fixed-shape, safe at every edge case.
  * Line functions are evaluated sparsely: l = A + B*w + C*w^3 with
    A,B,C in Fp2 (coefficients scaled by Fp2 denominators, which the
    final exponentiation kills).
  * The loop is one lax.scan over the STATIC bit array of 6t+2; the
    addition step is always computed and folded in with a lane-wide
    select (bits are compile-time constants but a scan keeps the HLO
    one-body-sized).
  * The optimal-ate Frobenius correction points pi_p(Q), -pi_{p^2}(Q)
    live on the twist, so the host precomputes them with exact int
    arithmetic (fabric_tpu/ops/bn254_ref.g2_frobenius) and the device
    runs two more add+line steps.

The Fp2/Fp6/Fp12 tower arithmetic, the complete twist steps and the
register-machine final-exponentiation runner are the generic
`fabric_tpu.ops.tower.Tower` parameterized with BN254's constants
(D-type twist over xi = 9+u on the default 20-limb layout); this
module keeps the BN-specific pieces — the 6t+2 Miller loop with its
optimal-ate Frobenius correction adds, the parameter-t final-exp
PROGRAM, the G2 MSM scan and the host staging helpers. The final
exponentiation runs fully on device, amortized: pairing products
multiply their Miller values and pay `final_exp_batch` once.

Differential oracle: fabric_tpu/ops/bn254_ref.miller_loop at matching
loop counts (tests run truncated loops on CPU; the full 6t+2 loop is
exercised on real hardware via bench paths).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from fabric_tpu.ops import bn254_ref as ref
from fabric_tpu.ops import limb
from fabric_tpu.ops import tower
from fabric_tpu.ops.limb import L
from fabric_tpu.ops.mont import MontMod

# compact-HLO Montgomery: the Miller scan body holds hundreds of muls
F = MontMod(ref.P, unroll=False)

# b3 = 3 * b' = 9/(9+u) on the twist, as exact Fp2 ints
_XI_INV = ref.f2_inv(ref.XI)
_B_TW = ref.f2_mul((3, 0), _XI_INV)
_B3_TW = ref.f2_mul((3, 0), ref.f2_mul((3, 0), _XI_INV))


def _f2_pow_int(a, e: int):
    """Host: exact Fp2 pow (for Frobenius constants)."""
    out = (1, 0)
    base = a
    while e:
        if e & 1:
            out = ref.f2_mul(out, base)
        base = ref.f2_mul(base, base)
        e >>= 1
    return out


# gamma = xi^((p-1)/6); (v^j w^i)^p = conj-coeffs * gamma^(2j+i)
_GAMMA = [_f2_pow_int(ref.XI, k * (ref.P - 1) // 6) for k in range(6)]


# ---------------------------------------------------------------------------
# Tower instance — BN254's D-type twist over xi = 9 + u. Every bound
# method below is bit-identical to the arithmetic that used to live
# inline here (proven by the kernel-parity suites).
# ---------------------------------------------------------------------------

_T = tower.Tower(F, xi=ref.XI, b3_tw=_B3_TW, gammas=_GAMMA,
                 mtwist=False)

_const_fp2 = _T.const_fp2
f2_add = _T.f2_add
f2_sub = _T.f2_sub
f2_mul = _T.f2_mul
f2_sqr = _T.f2_sqr
f2_scale = _T.f2_scale
f2_neg = _T.f2_neg
f2_conj = _T.f2_conj
f2_mul_xi = _T.f2_mul_xi
f2_small = _T.f2_small
f6_add = _T.f6_add
f6_sub = _T.f6_sub
f6_mul = _T.f6_mul
f6_mul_v = _T.f6_mul_v
f12_mul = _T.f12_mul
f12_sqr = _T.f12_sqr
f12_conj = _T.f12_conj
f12_frob = _T.f12_frob
f12_one_like = _T.f12_one_like
line_to_f12 = _T.line_to_f12
g2_dbl_line = _T.g2_dbl_line
g2_add_line = _T.g2_add_line
g2_dbl = _T.g2_dbl
g2_add_mixed = _T.g2_add_mixed
fp_inv = _T.fp_inv
f2_inv = _T.f2_inv
f6_inv = _T.f6_inv
f12_inv = _T.f12_inv
gt_is_one = _T.gt_is_one
_f12_select = _T.f12_select
_select_pt = tower.select_pt
_select_f12 = tower.select_f12
_flat_from_f12 = tower.flat_from_f12
_f12_from_flat = tower.f12_from_flat
_pow_scan = tower.pow_scan
_OP_MUL, _OP_CONJ, _OP_FROB = tower.OP_MUL, tower.OP_CONJ, tower.OP_FROB
_NREG = tower.NREG


def _f2_zero_like(x):
    z = jnp.zeros_like(x[0])
    return (z, z)


# ---------------------------------------------------------------------------
# Batched Miller loop
# ---------------------------------------------------------------------------

def miller_loop_batch(xP, yP, Q, Q1, nQ2, loop: int = ref.ATE_LOOP):
    """f_{loop,Q}(P) for a batch, with optimal-ate corrections.

    xP, yP: (B, L) Montgomery limbs of the G1 points.
    Q, Q1, nQ2: affine twist points as ((x0,x1),(y0,y1)) of (B, L)
    Montgomery limbs — Q1 = pi_p(Q) and nQ2 = -pi_{p^2}(Q) are
    host-precomputed (exact ints, ref.g2_frobenius).
    Returns the Fp12 Miller value as nested tuples of (B, L) tensors.
    """
    bits = [int(b) for b in bin(loop)[3:]]
    bit_arr = jnp.asarray(np.array(bits, dtype=bool))
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), xP.shape)
    zero = jnp.zeros_like(one)
    T0 = (Q[0], Q[1], ((one, zero)))
    f0 = f12_one_like(xP)

    def body(carry, bit):
        T, f = carry
        f = f12_sqr(f)
        T, l = g2_dbl_line(T, xP, yP)
        f = f12_mul(f, l)
        Ta, la = g2_add_line(T, Q, xP, yP)
        fa = f12_mul(f, la)
        mask = jnp.broadcast_to(bit, xP.shape[:1])
        T = _select_pt(mask, Ta, T)
        f = _select_f12(mask, fa, f)
        return (T, f), None

    (T, f), _ = lax.scan(body, (T0, f0), bit_arr)
    # optimal-ate corrections
    T, l1 = g2_add_line(T, Q1, xP, yP)
    f = f12_mul(f, l1)
    _, l2 = g2_add_line(T, nQ2, xP, yP)
    f = f12_mul(f, l2)
    return f


# ---------------------------------------------------------------------------
# Final exponentiation (device)
# ---------------------------------------------------------------------------

def f12_pow_t(m):
    """m^t for the BN parameter t (63-bit static scan)."""
    return _pow_scan(m, ref.T_BN, f12_mul, f12_sqr, _f12_select)


class _Asm(tower.Asm):
    """BN-flavored assembler: pow_t is pow by the static parameter t."""

    def pow_t(self, dst, src, tmp):
        self.pow_static(dst, src, tmp, ref.T_BN)


def _final_exp_program() -> np.ndarray:
    """Registers: 0=f (input), 1=inv_f (input), 2=m, 3=mx, 4=mx2,
    5=mx3, 6=t0/scratch, 7=t1/scratch. Mirrors
    ref.final_exponentiation_chain exactly (oracle-pinned)."""
    A = _Asm()
    # easy part: m = frob^2(f^(p^6-1)) * f^(p^6-1)
    A.conj(2, 0)                 # m <- conj(f)
    A.mul(2, 2, 1)               # m <- conj(f)*inv(f) = f^(p^6-1)
    A.frob(6, 2)
    A.frob(6, 6)                 # t0 <- m^(p^2)
    A.mul(2, 6, 2)               # m <- m^(p^2+1)
    # hard part powers of t
    A.pow_t(3, 2, 6)             # mx  = m^t
    A.pow_t(4, 3, 6)             # mx2 = mx^t
    A.pow_t(5, 4, 6)             # mx3 = mx2^t
    # y0 = mp*mp2*mp3 -> reg 6
    A.frob(6, 2)                 # mp
    A.frob(7, 6)                 # mp2
    A.mul(6, 6, 7)               # mp*mp2
    A.frob(7, 7)                 # mp3
    A.mul(6, 6, 7)               # y0
    # y4 = conj(mx * frob(mx2)) -> reg 7 ... build T0 incrementally:
    # T0 = y6^2 * y4 * y5;  y6 = conj(mx3 * frob(mx3))
    # use reg 0 (f no longer needed) and reg 1 (inv_f done) as scratch
    A.frob(0, 5)                 # frob(mx3)
    A.mul(0, 5, 0)               # mx3*mx3p
    A.conj(0, 0)                 # y6
    A.sqr(0, 0)                  # y6^2
    A.frob(1, 4)                 # mx2p
    A.mul(1, 3, 1)               # mx*mx2p
    A.conj(1, 1)                 # y4
    A.mul(0, 0, 1)               # y6^2*y4
    A.conj(1, 4)                 # y5
    A.mul(0, 0, 1)               # T0 = y6^2*y4*y5
    # T1 = y3*y5*T0; y3 = conj(frob(mx))
    A.frob(7, 3)
    A.conj(7, 7)                 # y3
    A.mul(7, 7, 1)               # y3*y5
    A.mul(7, 7, 0)               # T1
    # T0 = T0 * y2; y2 = frob^2(mx2)
    A.frob(1, 4)
    A.frob(1, 1)                 # y2
    A.mul(0, 0, 1)               # T0*y2
    # T1 = T1^2 * T0; T1 = T1^2
    A.sqr(7, 7)
    A.mul(7, 7, 0)
    A.sqr(7, 7)
    # T0 = T1 * y1; y1 = conj(m)
    A.conj(1, 2)                 # y1
    A.mul(0, 7, 1)               # T0 = T1*y1
    # T1 = T1 * y0 (y0 in reg 6)
    A.mul(7, 7, 6)
    # result = T0^2 * T1 -> reg 0
    A.sqr(0, 0)
    A.mul(0, 0, 7)
    return np.asarray(A.rows, dtype=np.int32)


_FINAL_EXP_PROGRAM = _final_exp_program()


def final_exp_batch(f):
    """The full final exponentiation on device: easy part
    (p^6-1)(p^2+1) then the BN hard part via the parameter-t addition
    chain (mirrors ref.final_exponentiation_chain, which is pinned
    against the single-pow oracle). Runs as the tower's
    register-machine scan — see fabric_tpu.ops.tower."""
    return _T.run_final_exp(f, _FINAL_EXP_PROGRAM)


def pairing_product_is_one(xPs, yPs, Qs, Q1s, nQ2s,
                           loop: int = ref.ATE_LOOP):
    """prod_i e(P_i, Q_i) == 1 for a batch of pairing PRODUCTS.

    Each argument is a list over the product terms; list element i
    carries the (B, L) staged tensors of that term. One shared final
    exponentiation over the multiplied Miller values — the standard
    product-of-pairings trick (and why the BBS+ verify equation
    e(A, X) = e(B, Y) is checked as e(A, X)·e(B, -Y) == 1).
    """
    import jax

    # ONE shared Miller scan with the product terms STACKED into the
    # batch axis: T terms of B lanes run as one (T*B)-lane loop, so
    # the (large) scan body appears once in the HLO instead of T
    # times — without this the tunnel's remote TPU compiler is killed
    # on program size.
    nterms = len(xPs)
    B = xPs[0].shape[0]
    cat = lambda ts: jax.tree_util.tree_map(  # noqa: E731
        lambda *xs: jnp.concatenate(xs, axis=0), *ts)
    f_all = miller_loop_batch(cat(xPs), cat(yPs), cat(Qs), cat(Q1s),
                              cat(nQ2s), loop=loop)
    acc = None
    for t in range(nterms):
        fi = jax.tree_util.tree_map(
            lambda x: x[t * B:(t + 1) * B], f_all)
        acc = fi if acc is None else f12_mul(acc, fi)
    return gt_is_one(final_exp_batch(acc))


def stage_pairing_products(products):
    """[[(P_int, Q_tw_int), ...] per lane] (uniform term count) ->
    the staged tensor lists pairing_product_is_one consumes."""
    nterms = len(products[0])
    assert all(len(p) == nterms for p in products)
    xPs, yPs, Qs, Q1s, nQ2s = [], [], [], [], []
    for t in range(nterms):
        g1s = [p[t][0] for p in products]
        g2s = [p[t][1] for p in products]
        xP, yP = stage_g1(g1s)
        Q, Q1, nQ2 = stage_g2(g2s)
        xPs.append(jnp.asarray(xP))
        yPs.append(jnp.asarray(yP))
        Qs.append(jax_tree(Q))
        Q1s.append(jax_tree(Q1))
        nQ2s.append(jax_tree(nQ2))
    return xPs, yPs, Qs, Q1s, nQ2s


def jax_tree(t):
    import jax
    return jax.tree_util.tree_map(jnp.asarray, t)


# ---------------------------------------------------------------------------
# Batched G2 multi-scalar multiplication (idemix PS Schnorr on device)
# ---------------------------------------------------------------------------
#
# The PS presentation verifier recomputes K~ = s_sk*Y~ + s_r*G~ - c*T~
# per credential (msp/idemix_ps.verify_schnorr) — three G2 scalar muls
# of host bigint work per lane. Here the whole batch runs as ONE
# lax.scan of complete RCB15 double/add steps over the scalar bit
# columns: per bit, one doubling + T masked mixed additions, all lanes
# in parallel on the Montgomery limb engine. The subgroup membership
# test ([6x^2]T~ == psi(T~), ops/bn254_ref.g2_in_subgroup) batches
# through the same kernel as 1-term lanes. The reference verifies each
# credential's proof serially on CPU (vendored IBM/idemix).

NBITS_R = 254                       # ref.R.bit_length()


def g2_msm_scan(bit_cols, *Q_flat):
    """sum_t k_t * Q_t per lane. bit_cols: (NBITS, B, T) bool, msb
    first; Q_flat: 4*T tensors (x0, x1, y0, y1 per term), (B, L)
    Montgomery limbs. Returns the projective result (X, Y, Z) Fp2."""
    nterms = len(Q_flat) // 4
    Qs = [((Q_flat[4 * t], Q_flat[4 * t + 1]),
           (Q_flat[4 * t + 2], Q_flat[4 * t + 3]))
          for t in range(nterms)]
    shape = Q_flat[0].shape
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), shape)
    zero = jnp.zeros_like(one)
    acc0 = ((zero, zero), (one, zero), (zero, zero))   # infinity

    def body(acc, bits):
        acc = g2_dbl(acc)
        for t, Q in enumerate(Qs):
            added = g2_add_mixed(acc, Q)
            acc = _select_pt(bits[:, t], added, acc)
        return acc, None

    acc, _ = lax.scan(body, acc0, bit_cols)
    return acc


def stage_g2_msm(lanes, nbits: int = NBITS_R):
    """[[(k, Q_affine_int | None), ...] x T per lane] -> (bit_cols,
    q_flat list). None/zero terms get an all-zero bit column (the
    point is never added; any valid placeholder works)."""
    nterms = len(lanes[0])
    assert all(len(lane) == nterms for lane in lanes)
    B = len(lanes)
    bit_cols = np.zeros((nbits, B, nterms), dtype=bool)
    g2 = (ref.G2_X, ref.G2_Y)
    q_flat = []
    for t in range(nterms):
        xs0, xs1, ys0, ys1 = [], [], [], []
        for i, lane in enumerate(lanes):
            k, q = lane[t]
            k %= ref.R
            if q is None:
                k = 0
            if k:
                kb = bin(k)[2:].zfill(nbits)
                bit_cols[:, i, t] = np.frombuffer(
                    kb.encode(), dtype=np.uint8) == 0x31
            p = q if (q is not None and k) else g2
            xs0.append(F.to_mont(p[0][0]))
            xs1.append(F.to_mont(p[0][1]))
            ys0.append(F.to_mont(p[1][0]))
            ys1.append(F.to_mont(p[1][1]))
        q_flat.extend([np.stack(xs0), np.stack(xs1),
                       np.stack(ys0), np.stack(ys1)])
    return bit_cols, q_flat


def read_g2_msm(out) -> list:
    """Projective mont limb result -> affine int points (None for
    infinity), via host Fp2 inversion per lane."""
    (X0, X1), (Y0, Y1), (Z0, Z1) = out
    X0, X1, Y0, Y1, Z0, Z1 = (np.asarray(a)
                              for a in (X0, X1, Y0, Y1, Z0, Z1))
    res = []
    for i in range(X0.shape[0]):
        z = (F.from_limbs(Z0[i]), F.from_limbs(Z1[i]))
        if z == (0, 0):
            res.append(None)
            continue
        zi = ref.f2_inv(z)
        x = ref.f2_mul((F.from_limbs(X0[i]), F.from_limbs(X1[i])), zi)
        y = ref.f2_mul((F.from_limbs(Y0[i]), F.from_limbs(Y1[i])), zi)
        res.append((x, y))
    return res


def bls_products(pk_tw, msgs, sig_points):
    """Per-lane BLS verify as a 2-term pairing product:
    e(sig, G2) * e(H(m), -pk) == 1."""
    g2 = (ref.G2_X, ref.G2_Y)
    npk = ref.g2_neg_tw(pk_tw)
    return [[(sig, g2), (ref.hash_to_g1(m), npk)]
            for m, sig in zip(msgs, sig_points)]


# ---------------------------------------------------------------------------
# Host staging + verification helpers
# ---------------------------------------------------------------------------

def stage_g1(points) -> tuple[np.ndarray, np.ndarray]:
    """[(x, y) ints] -> (B, L) Montgomery limb arrays."""
    xs = np.stack([F.to_mont(p[0]) for p in points])
    ys = np.stack([F.to_mont(p[1]) for p in points])
    return xs, ys


def stage_g2(points):
    """[((x0,x1),(y0,y1)) ints] -> twist-point limb tuples + the
    host-precomputed Frobenius correction points."""
    def pack(pts):
        return ((np.stack([F.to_mont(p[0][0]) for p in pts]),
                 np.stack([F.to_mont(p[0][1]) for p in pts])),
                (np.stack([F.to_mont(p[1][0]) for p in pts]),
                 np.stack([F.to_mont(p[1][1]) for p in pts])))

    q1s = [ref.g2_frobenius(q) for q in points]
    nq2s = [ref.g2_neg_tw(ref.g2_frobenius(q1)) for q1 in q1s]
    return pack(points), pack(q1s), pack(nq2s)


def f12_from_device(f) -> list:
    """Device Fp12 (nested tuples of (B, L) mont limbs) -> list of
    int-reference Fp12 elements, for differential comparison."""
    d0, d1 = f
    B = d0[0][0].shape[0]
    out = []
    for i in range(B):
        def cvt_f2(c):
            return (F.from_limbs(np.asarray(c[0][i])),
                    F.from_limbs(np.asarray(c[1][i])))
        out.append((tuple(cvt_f2(c) for c in d0),
                    tuple(cvt_f2(c) for c in d1)))
    return out
