"""Batched BN254 optimal-ate Miller loop on TPU (idemix stretch).

The reference's identity mixer verifies BBS+ credentials with pairings
over BN254 (vendored `IBM/idemix`, wired at `msp/idemix.go`). Its hot
verify path computes a pairing product PER credential on CPU; here the
Miller loop — the data-dependent bulk of the pairing — runs for a whole
batch of (P, Q) pairs as one fixed-shape XLA program over the
Montgomery limb engine (fabric_tpu/ops/mont.py).

TPU-first shape:
  * G2 state stays on the twist E'(Fp2): y^2 = x^3 + 3/(9+u), in
    HOMOGENEOUS projective coordinates with the complete a=0
    add/double formulas (Renes-Costello-Batina Algs 7/9) — branchless,
    fixed-shape, safe at every edge case.
  * Line functions are evaluated sparsely: l = A + B*w + C*w^3 with
    A,B,C in Fp2 (coefficients scaled by Fp2 denominators, which the
    final exponentiation kills).
  * The loop is one lax.scan over the STATIC bit array of 6t+2; the
    addition step is always computed and folded in with a lane-wide
    select (bits are compile-time constants but a scan keeps the HLO
    one-body-sized).
  * The optimal-ate Frobenius correction points pi_p(Q), -pi_{p^2}(Q)
    live on the twist, so the host precomputes them with exact int
    arithmetic (fabric_tpu/ops/bn254_ref.g2_frobenius) and the device
    runs two more add+line steps.

The final exponentiation stays on the host for now (one f12_pow per
batch element over the int reference) — the Miller loop is ~99% of the
per-credential field work once the exponent bits are fixed.

Differential oracle: fabric_tpu/ops/bn254_ref.miller_loop at matching
loop counts (tests run truncated loops on CPU; the full 6t+2 loop is
exercised on real hardware via bench paths).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from fabric_tpu.ops import bn254_ref as ref
from fabric_tpu.ops import limb
from fabric_tpu.ops.limb import L
from fabric_tpu.ops.mont import MontMod

# compact-HLO Montgomery: the Miller scan body holds hundreds of muls
F = MontMod(ref.P, unroll=False)

# b3 = 3 * b' = 9/(9+u) on the twist, as exact Fp2 ints
_XI_INV = ref.f2_inv(ref.XI)
_B_TW = ref.f2_mul((3, 0), _XI_INV)
_B3_TW = ref.f2_mul((3, 0), ref.f2_mul((3, 0), _XI_INV))


def _const_fp2(c):
    """Exact Fp2 int pair -> broadcastable Montgomery limb constants."""
    return (jnp.asarray(F.to_mont(c[0])), jnp.asarray(F.to_mont(c[1])))


# ---------------------------------------------------------------------------
# Tower arithmetic over Montgomery limb tensors
# Fp2 = (a0, a1); Fp6 = (c0, c1, c2) of Fp2; Fp12 = (d0, d1) of Fp6
# ---------------------------------------------------------------------------

def f2_add(a, b):
    return (F.add(a[0], b[0]), F.add(a[1], b[1]))


def f2_sub(a, b):
    return (F.sub(a[0], b[0]), F.sub(a[1], b[1]))


def f2_mul(a, b):
    """Karatsuba: 3 base multiplications."""
    m0 = F.mul(a[0], b[0])
    m1 = F.mul(a[1], b[1])
    m2 = F.mul(F.add(a[0], a[1]), F.add(b[0], b[1]))
    return (F.sub(m0, m1), F.sub(F.sub(m2, m0), m1))


def f2_sqr(a):
    return f2_mul(a, a)


def f2_scale(a, s):
    """Fp2 times an Fp element."""
    return (F.mul(a[0], s), F.mul(a[1], s))


def f2_neg(a):
    return (F.neg(a[0]), F.neg(a[1]))


def f2_mul_xi(a):
    """Multiply by xi = 9 + u: (9a0 - a1, a0 + 9a1)."""
    def x9(x):
        x2 = F.add(x, x)
        x4 = F.add(x2, x2)
        x8 = F.add(x4, x4)
        return F.add(x8, x)
    return (F.sub(x9(a[0]), a[1]), F.add(a[0], x9(a[1])))


def f2_small(a, k: int):
    """Multiply by a small positive int via a binary add chain."""
    acc = None
    base = a
    while k:
        if k & 1:
            acc = base if acc is None else f2_add(acc, base)
        k >>= 1
        if k:
            base = f2_add(base, base)
    return acc


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_mul(a, b):
    c0, c1, c2 = a
    d0, d1, d2 = b
    t0, t1, t2 = f2_mul(c0, d0), f2_mul(c1, d1), f2_mul(c2, d2)
    r0 = f2_add(t0, f2_mul_xi(f2_add(f2_mul(c1, d2), f2_mul(c2, d1))))
    r1 = f2_add(f2_add(f2_mul(c0, d1), f2_mul(c1, d0)), f2_mul_xi(t2))
    r2 = f2_add(f2_add(f2_mul(c0, d2), f2_mul(c2, d0)), t1)
    return (r0, r1, r2)


def f6_mul_v(a):
    """Multiply an Fp6 element by v (v^3 = xi)."""
    return (f2_mul_xi(a[2]), a[0], a[1])


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    r0 = f6_add(t0, f6_mul_v(t1))
    r1 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)),
                f6_add(t0, t1))
    return (r0, r1)


def f12_sqr(a):
    return f12_mul(a, a)


def _f2_zero_like(x):
    z = jnp.zeros_like(x[0])
    return (z, z)


def f12_one_like(x):
    """Fp12 one, broadcast to the batch shape of Fp element x."""
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), x.shape)
    z = jnp.zeros_like(x)
    return (((one, z), (z, z), (z, z)), ((z, z), (z, z), (z, z)))


def line_to_f12(A, B, C):
    """Sparse line A + B*w + C*w^3 as a full Fp12 element
    (w^3 = v*w -> coefficient c1 of the second Fp6 component)."""
    z = _f2_zero_like(A)
    return ((A, z, z), (B, C, z))


# ---------------------------------------------------------------------------
# Twist-curve steps with line evaluation
# ---------------------------------------------------------------------------

def g2_dbl_line(T, xP, yP):
    """Complete a=0 doubling (RCB15 Alg 9 with b3 on the twist) plus
    the tangent line at T evaluated at P = (xP, yP) in G1.

    T: ((X0,X1),(Y0,Y1),(Z0,Z1)) Fp2 limb tensors. Line coefficients
    (see module docstring): scaled by Z^3,
      A = 2*Y*Z^2 * yP,  B = -3*X^2*Z * xP,  C = 3*X^3 - 2*Y^2*Z.
    """
    X, Y, Z = T
    b3 = tuple(jnp.broadcast_to(c, X[0].shape)
               for c in _const_fp2(_B3_TW))
    # line first (uses the pre-doubling T)
    Z2 = f2_sqr(Z)
    X2 = f2_sqr(X)
    YZ = f2_mul(Y, Z)
    A = f2_scale(f2_small(f2_mul(Y, Z2), 2), yP)
    B = f2_scale(f2_neg(f2_small(f2_mul(X2, Z), 3)), xP)
    C = f2_sub(f2_small(f2_mul(X2, X), 3), f2_small(f2_mul(Y, YZ), 2))
    # RCB15 Alg 9 doubling
    t0 = f2_sqr(Y)
    Z3 = f2_small(t0, 8)
    t1 = YZ
    t2 = f2_sqr(Z)
    t2 = f2_mul(b3, t2)
    X3 = f2_mul(t2, Z3)
    Y3 = f2_add(t0, t2)
    Z3 = f2_mul(t1, Z3)
    t1 = f2_small(t2, 2)
    t2 = f2_add(t1, t2)
    t0 = f2_sub(t0, t2)
    Y3 = f2_mul(t0, Y3)
    Y3 = f2_add(X3, Y3)
    t1 = f2_mul(X, Y)
    X3 = f2_mul(t0, t1)
    X3 = f2_small(X3, 2)
    return (X3, Y3, Z3), line_to_f12(A, B, C)


def g2_add_line(T, Q, xP, yP):
    """Complete a=0 mixed addition T + Q (RCB15 Alg 7 with Z2=1) plus
    the chord line through T, Q evaluated at P.

    Chord coefficients scaled by Z:
      A = (X - xQ*Z) * yP,  B = -(Y - yQ*Z) * xP,
      C = (Y - yQ*Z)*xQ - (X - xQ*Z)*yQ.
    """
    X1, Y1, Z1 = T
    xQ, yQ = Q
    b3 = tuple(jnp.broadcast_to(c, X1[0].shape)
               for c in _const_fp2(_B3_TW))
    # line
    dX = f2_sub(X1, f2_mul(xQ, Z1))
    dY = f2_sub(Y1, f2_mul(yQ, Z1))
    A = f2_scale(dX, yP)
    B = f2_scale(f2_neg(dY), xP)
    C = f2_sub(f2_mul(dY, xQ), f2_mul(dX, yQ))
    # RCB15 Alg 7, complete addition for a=0 (general Z2; the twist
    # point Q is affine so Z2 = mont(1))
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), X1[0].shape)
    zero = jnp.zeros_like(one)
    X2, Y2, Z2 = xQ, yQ, (one, zero)
    t0 = f2_mul(X1, X2)
    t1 = f2_mul(Y1, Y2)
    t2 = f2_mul(Z1, Z2)
    t3 = f2_mul(f2_add(X1, Y1), f2_add(X2, Y2))
    t3 = f2_sub(t3, f2_add(t0, t1))
    t4 = f2_mul(f2_add(Y1, Z1), f2_add(Y2, Z2))
    t4 = f2_sub(t4, f2_add(t1, t2))
    X3 = f2_mul(f2_add(X1, Z1), f2_add(X2, Z2))
    Y3 = f2_sub(X3, f2_add(t0, t2))      # Y3 = X1*Z2 + X2*Z1
    t0 = f2_small(t0, 3)                 # 3*X1*X2
    t2 = f2_mul(b3, t2)
    Z3 = f2_add(t1, t2)
    t1 = f2_sub(t1, t2)
    Y3 = f2_mul(b3, Y3)
    X3 = f2_mul(t4, Y3)
    X3 = f2_sub(f2_mul(t3, t1), X3)
    Y3 = f2_mul(Y3, t0)
    Y3 = f2_add(f2_mul(t1, Z3), Y3)
    Z3 = f2_mul(Z3, t4)
    Z3 = f2_add(Z3, f2_mul(t0, t3))
    return (X3, Y3, Z3), line_to_f12(A, B, C)


# ---------------------------------------------------------------------------
# Batched Miller loop
# ---------------------------------------------------------------------------

def _select_pt(mask, a, b):
    """Lane select between two Fp2 point triples; mask: (B,) bool."""
    m = mask[:, None]
    return tuple(
        (jnp.where(m, x[0], y[0]), jnp.where(m, x[1], y[1]))
        for x, y in zip(a, b))


def _select_f12(mask, a, b):
    m = mask[:, None]

    def sel(x, y):
        return jnp.where(m, x, y)

    return tuple(
        tuple((sel(x[0], y[0]), sel(x[1], y[1]))
              for x, y in zip(c6a, c6b))
        for c6a, c6b in zip(a, b))


def miller_loop_batch(xP, yP, Q, Q1, nQ2, loop: int = ref.ATE_LOOP):
    """f_{loop,Q}(P) for a batch, with optimal-ate corrections.

    xP, yP: (B, L) Montgomery limbs of the G1 points.
    Q, Q1, nQ2: affine twist points as ((x0,x1),(y0,y1)) of (B, L)
    Montgomery limbs — Q1 = pi_p(Q) and nQ2 = -pi_{p^2}(Q) are
    host-precomputed (exact ints, ref.g2_frobenius).
    Returns the Fp12 Miller value as nested tuples of (B, L) tensors.
    """
    bits = [int(b) for b in bin(loop)[3:]]
    bit_arr = jnp.asarray(np.array(bits, dtype=bool))
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), xP.shape)
    zero = jnp.zeros_like(one)
    T0 = (Q[0], Q[1], ((one, zero)))
    f0 = f12_one_like(xP)

    def body(carry, bit):
        T, f = carry
        f = f12_sqr(f)
        T, l = g2_dbl_line(T, xP, yP)
        f = f12_mul(f, l)
        Ta, la = g2_add_line(T, Q, xP, yP)
        fa = f12_mul(f, la)
        mask = jnp.broadcast_to(bit, xP.shape[:1])
        T = _select_pt(mask, Ta, T)
        f = _select_f12(mask, fa, f)
        return (T, f), None

    (T, f), _ = lax.scan(body, (T0, f0), bit_arr)
    # optimal-ate corrections
    T, l1 = g2_add_line(T, Q1, xP, yP)
    f = f12_mul(f, l1)
    _, l2 = g2_add_line(T, nQ2, xP, yP)
    f = f12_mul(f, l2)
    return f


# ---------------------------------------------------------------------------
# Final exponentiation (device)
# ---------------------------------------------------------------------------

def _f2_pow_int(a, e: int):
    """Host: exact Fp2 pow (for Frobenius constants)."""
    out = (1, 0)
    base = a
    while e:
        if e & 1:
            out = ref.f2_mul(out, base)
        base = ref.f2_mul(base, base)
        e >>= 1
    return out


# gamma = xi^((p-1)/6); (v^j w^i)^p = conj-coeffs * gamma^(2j+i)
_GAMMA = [_f2_pow_int(ref.XI, k * (ref.P - 1) // 6) for k in range(6)]


def f2_conj(a):
    return (a[0], F.neg(a[1]))


def f12_conj(f):
    """x -> x^(p^6): negate the w half. Inverse inside the cyclotomic
    subgroup (post easy part)."""
    d0, d1 = f
    return (d0, tuple(f2_neg(c) for c in d1))


def f12_frob(f):
    """x -> x^p: coefficient-wise Fp2 conjugation times the gamma
    constants (host-exact, differentially pinned vs ref.f12_frob)."""
    d0, d1 = f

    def g(k, c):
        const = tuple(jnp.broadcast_to(v, c[0].shape)
                      for v in _const_fp2(_GAMMA[k]))
        return f2_mul(f2_conj(c), const)

    return ((f2_conj(d0[0]), g(2, d0[1]), g(4, d0[2])),
            (g(1, d1[0]), g(3, d1[1]), g(5, d1[2])))


def _pow_scan(x, e: int, mul, sqr, select):
    """Square-and-multiply by a STATIC positive exponent as a lax.scan
    (keeps the HLO one-body-sized for multi-thousand-bit chains)."""
    bits = [int(b) for b in bin(e)[3:]]          # skip the leading 1
    if not bits:
        return x
    bit_arr = jnp.asarray(np.array(bits, dtype=bool))

    def body(acc, bit):
        acc = sqr(acc)
        acc = select(bit, mul(acc, x), acc)
        return acc, None

    out, _ = lax.scan(body, x, bit_arr)
    return out


def fp_inv(x):
    """Montgomery Fermat inverse: x^(p-2) via a 254-bit scan."""
    def select(bit, a, b):
        return jnp.where(bit, a, b)

    return _pow_scan(x, ref.P - 2, F.mul, lambda a: F.mul(a, a), select)


def f2_inv(a):
    d = fp_inv(F.add(F.mul(a[0], a[0]), F.mul(a[1], a[1])))
    return (F.mul(a[0], d), F.mul(F.neg(a[1]), d))


def f6_inv(a):
    """Adjoint/norm method (mirrors ref.f6_inv)."""
    c0, c1, c2 = a
    t0 = f2_sub(f2_sqr(c0), f2_mul_xi(f2_mul(c1, c2)))
    t1 = f2_sub(f2_mul_xi(f2_sqr(c2)), f2_mul(c0, c1))
    t2 = f2_sub(f2_sqr(c1), f2_mul(c0, c2))
    norm = f2_add(f2_mul(c0, t0),
                  f2_mul_xi(f2_add(f2_mul(c2, t1), f2_mul(c1, t2))))
    ninv = f2_inv(norm)
    return (f2_mul(t0, ninv), f2_mul(t1, ninv), f2_mul(t2, ninv))


def f12_inv(a):
    a0, a1 = a
    t1 = f6_mul(a1, a1)
    norm = f6_sub(f6_mul(a0, a0), f6_mul_v(t1))
    ninv = f6_inv(norm)
    return (f6_mul(a0, ninv),
            tuple(f2_neg(c) for c in f6_mul(a1, ninv)))


def _f12_select(bit, a, b):
    mask = jnp.broadcast_to(bit, a[0][0][0].shape[:1])
    return _select_f12(mask, a, b)


def f12_pow_t(m):
    """m^t for the BN parameter t (63-bit static scan)."""
    return _pow_scan(m, ref.T_BN, f12_mul, f12_sqr, _f12_select)


# -- the final-exp REGISTER MACHINE --
#
# A monolithic unrolled chain (3 pow-by-t + ~25 Fp12 muls, each 54
# Montgomery muls) produces an HLO the compilers refuse: the tunnel's
# remote TPU compiler SIGKILLs and the CPU jit OOMs. Instead the whole
# post-inversion exponentiation runs as ONE lax.scan whose body is a
# tiny f12-op interpreter (MUL/CONJ/FROB over a register file), driven
# by a static ~310-instruction program assembled from the SAME chain
# that ref.final_exponentiation_chain pins against the single-pow
# oracle. HLO cost: one multiply body, regardless of chain length.

_OP_MUL, _OP_CONJ, _OP_FROB = 0, 1, 2
_NREG = 8


def _flat_from_f12(f):
    """Nested-tuple f12 -> (12, ...) stacked coeff tensor."""
    coeffs = [c for half in f for fp2 in half for c in fp2]
    return jnp.stack(coeffs, axis=0)


def _f12_from_flat(x):
    return tuple(
        tuple((x[h * 6 + j * 2], x[h * 6 + j * 2 + 1])
              for j in range(3))
        for h in range(2))


class _Asm:
    """Assembles the final-exp chain into (op, dst, a, b) rows."""

    def __init__(self):
        self.rows = []

    def emit(self, op, dst, a, b=0):
        self.rows.append((op, dst, a, b))

    def mul(self, dst, a, b):
        self.emit(_OP_MUL, dst, a, b)

    def sqr(self, dst, a):
        self.emit(_OP_MUL, dst, a, a)

    def conj(self, dst, a):
        self.emit(_OP_CONJ, dst, a)

    def frob(self, dst, a):
        self.emit(_OP_FROB, dst, a)

    def copy(self, dst, a):
        self.conj(dst, a)            # conj . conj = identity
        self.conj(dst, dst)

    def pow_t(self, dst, src, tmp):
        """dst = src^t: square-and-multiply over t's static bits
        (src, tmp, dst must be distinct registers)."""
        assert len({dst, src, tmp}) == 3
        self.copy(tmp, src)          # acc <- src (leading bit)
        for b in bin(ref.T_BN)[3:]:
            self.sqr(tmp, tmp)
            if b == "1":
                self.mul(tmp, tmp, src)
        self.copy(dst, tmp)


def _final_exp_program() -> np.ndarray:
    """Registers: 0=f (input), 1=inv_f (input), 2=m, 3=mx, 4=mx2,
    5=mx3, 6=t0/scratch, 7=t1/scratch. Mirrors
    ref.final_exponentiation_chain exactly (oracle-pinned)."""
    A = _Asm()
    # easy part: m = frob^2(f^(p^6-1)) * f^(p^6-1)
    A.conj(2, 0)                 # m <- conj(f)
    A.mul(2, 2, 1)               # m <- conj(f)*inv(f) = f^(p^6-1)
    A.frob(6, 2)
    A.frob(6, 6)                 # t0 <- m^(p^2)
    A.mul(2, 6, 2)               # m <- m^(p^2+1)
    # hard part powers of t
    A.pow_t(3, 2, 6)             # mx  = m^t
    A.pow_t(4, 3, 6)             # mx2 = mx^t
    A.pow_t(5, 4, 6)             # mx3 = mx2^t
    # y0 = mp*mp2*mp3 -> reg 6
    A.frob(6, 2)                 # mp
    A.frob(7, 6)                 # mp2
    A.mul(6, 6, 7)               # mp*mp2
    A.frob(7, 7)                 # mp3
    A.mul(6, 6, 7)               # y0
    # y4 = conj(mx * frob(mx2)) -> reg 7 ... build T0 incrementally:
    # T0 = y6^2 * y4 * y5;  y6 = conj(mx3 * frob(mx3))
    # use reg 0 (f no longer needed) and reg 1 (inv_f done) as scratch
    A.frob(0, 5)                 # frob(mx3)
    A.mul(0, 5, 0)               # mx3*mx3p
    A.conj(0, 0)                 # y6
    A.sqr(0, 0)                  # y6^2
    A.frob(1, 4)                 # mx2p
    A.mul(1, 3, 1)               # mx*mx2p
    A.conj(1, 1)                 # y4
    A.mul(0, 0, 1)               # y6^2*y4
    A.conj(1, 4)                 # y5
    A.mul(0, 0, 1)               # T0 = y6^2*y4*y5
    # T1 = y3*y5*T0; y3 = conj(frob(mx))
    A.frob(7, 3)
    A.conj(7, 7)                 # y3
    A.mul(7, 7, 1)               # y3*y5
    A.mul(7, 7, 0)               # T1
    # T0 = T0 * y2; y2 = frob^2(mx2)
    A.frob(1, 4)
    A.frob(1, 1)                 # y2
    A.mul(0, 0, 1)               # T0*y2
    # T1 = T1^2 * T0; T1 = T1^2
    A.sqr(7, 7)
    A.mul(7, 7, 0)
    A.sqr(7, 7)
    # T0 = T1 * y1; y1 = conj(m)
    A.conj(1, 2)                 # y1
    A.mul(0, 7, 1)               # T0 = T1*y1
    # T1 = T1 * y0 (y0 in reg 6)
    A.mul(7, 7, 6)
    # result = T0^2 * T1 -> reg 0
    A.sqr(0, 0)
    A.mul(0, 0, 7)
    return np.asarray(A.rows, dtype=np.int32)


def final_exp_batch(f):
    """The full final exponentiation on device: easy part
    (p^6-1)(p^2+1) then the BN hard part via the parameter-t addition
    chain (mirrors ref.final_exponentiation_chain, which is pinned
    against the single-pow oracle). Runs as a register-machine scan —
    see the note above the assembler."""
    inv = f12_inv(f)
    regs0 = jnp.stack([_flat_from_f12(f), _flat_from_f12(inv)] +
                      [jnp.zeros_like(_flat_from_f12(f))] * (_NREG - 2),
                      axis=0)                    # (NREG, 12, ...)
    program = jnp.asarray(_final_exp_program())

    def body(regs, instr):
        op, dst, a, b = instr[0], instr[1], instr[2], instr[3]
        A = _f12_from_flat(jnp.take(regs, a, axis=0))
        Bv = _f12_from_flat(jnp.take(regs, b, axis=0))
        res = lax.switch(op, [
            lambda: _flat_from_f12(f12_mul(A, Bv)),
            lambda: _flat_from_f12(f12_conj(A)),
            lambda: _flat_from_f12(f12_frob(A)),
        ])
        regs = lax.dynamic_update_index_in_dim(regs, res, dst, axis=0)
        return regs, None

    regs, _ = lax.scan(body, regs0, program)
    return _f12_from_flat(regs[0])


def gt_is_one(f):
    """(B,) bool: is the GT element the identity? Canonical-compare
    every coefficient (mont(1) for c000, zero elsewhere)."""
    one = jnp.asarray(F.to_mont(1))
    coeffs = [c for d in f for fp2 in d for c in fp2]
    first = coeffs[0]
    ok = jnp.all(F.canonical(first) ==
                 F.canonical(jnp.broadcast_to(one, first.shape)),
                 axis=-1)
    for c in coeffs[1:]:
        ok = ok & jnp.all(F.canonical(c) == 0, axis=-1)
    return ok


def pairing_product_is_one(xPs, yPs, Qs, Q1s, nQ2s,
                           loop: int = ref.ATE_LOOP):
    """prod_i e(P_i, Q_i) == 1 for a batch of pairing PRODUCTS.

    Each argument is a list over the product terms; list element i
    carries the (B, L) staged tensors of that term. One shared final
    exponentiation over the multiplied Miller values — the standard
    product-of-pairings trick (and why the BBS+ verify equation
    e(A, X) = e(B, Y) is checked as e(A, X)·e(B, -Y) == 1).
    """
    import jax

    # ONE shared Miller scan with the product terms STACKED into the
    # batch axis: T terms of B lanes run as one (T*B)-lane loop, so
    # the (large) scan body appears once in the HLO instead of T
    # times — without this the tunnel's remote TPU compiler is killed
    # on program size.
    nterms = len(xPs)
    B = xPs[0].shape[0]
    cat = lambda ts: jax.tree_util.tree_map(  # noqa: E731
        lambda *xs: jnp.concatenate(xs, axis=0), *ts)
    f_all = miller_loop_batch(cat(xPs), cat(yPs), cat(Qs), cat(Q1s),
                              cat(nQ2s), loop=loop)
    acc = None
    for t in range(nterms):
        fi = jax.tree_util.tree_map(
            lambda x: x[t * B:(t + 1) * B], f_all)
        acc = fi if acc is None else f12_mul(acc, fi)
    return gt_is_one(final_exp_batch(acc))


def stage_pairing_products(products):
    """[[(P_int, Q_tw_int), ...] per lane] (uniform term count) ->
    the staged tensor lists pairing_product_is_one consumes."""
    nterms = len(products[0])
    assert all(len(p) == nterms for p in products)
    xPs, yPs, Qs, Q1s, nQ2s = [], [], [], [], []
    for t in range(nterms):
        g1s = [p[t][0] for p in products]
        g2s = [p[t][1] for p in products]
        xP, yP = stage_g1(g1s)
        Q, Q1, nQ2 = stage_g2(g2s)
        xPs.append(jnp.asarray(xP))
        yPs.append(jnp.asarray(yP))
        Qs.append(jax_tree(Q))
        Q1s.append(jax_tree(Q1))
        nQ2s.append(jax_tree(nQ2))
    return xPs, yPs, Qs, Q1s, nQ2s


def jax_tree(t):
    import jax
    return jax.tree_util.tree_map(jnp.asarray, t)


# ---------------------------------------------------------------------------
# Batched G2 multi-scalar multiplication (idemix PS Schnorr on device)
# ---------------------------------------------------------------------------
#
# The PS presentation verifier recomputes K~ = s_sk*Y~ + s_r*G~ - c*T~
# per credential (msp/idemix_ps.verify_schnorr) — three G2 scalar muls
# of host bigint work per lane. Here the whole batch runs as ONE
# lax.scan of complete RCB15 double/add steps over the scalar bit
# columns: per bit, one doubling + T masked mixed additions, all lanes
# in parallel on the Montgomery limb engine. The subgroup membership
# test ([6x^2]T~ == psi(T~), ops/bn254_ref.g2_in_subgroup) batches
# through the same kernel as 1-term lanes. The reference verifies each
# credential's proof serially on CPU (vendored IBM/idemix).

NBITS_R = 254                       # ref.R.bit_length()


def g2_dbl(T):
    """RCB15 Alg 9 complete doubling on the twist (no line)."""
    X, Y, Z = T
    b3 = tuple(jnp.broadcast_to(c, X[0].shape)
               for c in _const_fp2(_B3_TW))
    t0 = f2_sqr(Y)
    Z3 = f2_small(t0, 8)
    t1 = f2_mul(Y, Z)
    t2 = f2_mul(b3, f2_sqr(Z))
    X3 = f2_mul(t2, Z3)
    Y3 = f2_add(t0, t2)
    Z3 = f2_mul(t1, Z3)
    t1 = f2_small(t2, 2)
    t2 = f2_add(t1, t2)
    t0 = f2_sub(t0, t2)
    Y3 = f2_mul(t0, Y3)
    Y3 = f2_add(X3, Y3)
    t1 = f2_mul(X, Y)
    X3 = f2_mul(t0, t1)
    X3 = f2_small(X3, 2)
    return X3, Y3, Z3


def g2_add_mixed(T, Q):
    """RCB15 Alg 7 complete mixed addition T + (affine Q), no line."""
    X1, Y1, Z1 = T
    xQ, yQ = Q
    b3 = tuple(jnp.broadcast_to(c, X1[0].shape)
               for c in _const_fp2(_B3_TW))
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), X1[0].shape)
    zero = jnp.zeros_like(one)
    X2, Y2, Z2 = xQ, yQ, (one, zero)
    t0 = f2_mul(X1, X2)
    t1 = f2_mul(Y1, Y2)
    t2 = f2_mul(Z1, Z2)
    t3 = f2_mul(f2_add(X1, Y1), f2_add(X2, Y2))
    t3 = f2_sub(t3, f2_add(t0, t1))
    t4 = f2_mul(f2_add(Y1, Z1), f2_add(Y2, Z2))
    t4 = f2_sub(t4, f2_add(t1, t2))
    X3 = f2_mul(f2_add(X1, Z1), f2_add(X2, Z2))
    Y3 = f2_sub(X3, f2_add(t0, t2))
    t0 = f2_small(t0, 3)
    t2 = f2_mul(b3, t2)
    Z3 = f2_add(t1, t2)
    t1 = f2_sub(t1, t2)
    Y3 = f2_mul(b3, Y3)
    X3 = f2_mul(t4, Y3)
    X3 = f2_sub(f2_mul(t3, t1), X3)
    Y3 = f2_mul(Y3, t0)
    Y3 = f2_add(f2_mul(t1, Z3), Y3)
    Z3 = f2_mul(Z3, t4)
    Z3 = f2_add(Z3, f2_mul(t0, t3))
    return X3, Y3, Z3


def g2_msm_scan(bit_cols, *Q_flat):
    """sum_t k_t * Q_t per lane. bit_cols: (NBITS, B, T) bool, msb
    first; Q_flat: 4*T tensors (x0, x1, y0, y1 per term), (B, L)
    Montgomery limbs. Returns the projective result (X, Y, Z) Fp2."""
    nterms = len(Q_flat) // 4
    Qs = [((Q_flat[4 * t], Q_flat[4 * t + 1]),
           (Q_flat[4 * t + 2], Q_flat[4 * t + 3]))
          for t in range(nterms)]
    shape = Q_flat[0].shape
    one = jnp.broadcast_to(jnp.asarray(F.to_mont(1)), shape)
    zero = jnp.zeros_like(one)
    acc0 = ((zero, zero), (one, zero), (zero, zero))   # infinity

    def body(acc, bits):
        acc = g2_dbl(acc)
        for t, Q in enumerate(Qs):
            added = g2_add_mixed(acc, Q)
            acc = _select_pt(bits[:, t], added, acc)
        return acc, None

    acc, _ = lax.scan(body, acc0, bit_cols)
    return acc


def stage_g2_msm(lanes, nbits: int = NBITS_R):
    """[[(k, Q_affine_int | None), ...] x T per lane] -> (bit_cols,
    q_flat list). None/zero terms get an all-zero bit column (the
    point is never added; any valid placeholder works)."""
    nterms = len(lanes[0])
    assert all(len(lane) == nterms for lane in lanes)
    B = len(lanes)
    bit_cols = np.zeros((nbits, B, nterms), dtype=bool)
    g2 = (ref.G2_X, ref.G2_Y)
    q_flat = []
    for t in range(nterms):
        xs0, xs1, ys0, ys1 = [], [], [], []
        for i, lane in enumerate(lanes):
            k, q = lane[t]
            k %= ref.R
            if q is None:
                k = 0
            if k:
                kb = bin(k)[2:].zfill(nbits)
                bit_cols[:, i, t] = np.frombuffer(
                    kb.encode(), dtype=np.uint8) == 0x31
            p = q if (q is not None and k) else g2
            xs0.append(F.to_mont(p[0][0]))
            xs1.append(F.to_mont(p[0][1]))
            ys0.append(F.to_mont(p[1][0]))
            ys1.append(F.to_mont(p[1][1]))
        q_flat.extend([np.stack(xs0), np.stack(xs1),
                       np.stack(ys0), np.stack(ys1)])
    return bit_cols, q_flat


def read_g2_msm(out) -> list:
    """Projective mont limb result -> affine int points (None for
    infinity), via host Fp2 inversion per lane."""
    (X0, X1), (Y0, Y1), (Z0, Z1) = out
    X0, X1, Y0, Y1, Z0, Z1 = (np.asarray(a)
                              for a in (X0, X1, Y0, Y1, Z0, Z1))
    res = []
    for i in range(X0.shape[0]):
        z = (F.from_limbs(Z0[i]), F.from_limbs(Z1[i]))
        if z == (0, 0):
            res.append(None)
            continue
        zi = ref.f2_inv(z)
        x = ref.f2_mul((F.from_limbs(X0[i]), F.from_limbs(X1[i])), zi)
        y = ref.f2_mul((F.from_limbs(Y0[i]), F.from_limbs(Y1[i])), zi)
        res.append((x, y))
    return res


def bls_products(pk_tw, msgs, sig_points):
    """Per-lane BLS verify as a 2-term pairing product:
    e(sig, G2) * e(H(m), -pk) == 1."""
    g2 = (ref.G2_X, ref.G2_Y)
    npk = ref.g2_neg_tw(pk_tw)
    return [[(sig, g2), (ref.hash_to_g1(m), npk)]
            for m, sig in zip(msgs, sig_points)]


# ---------------------------------------------------------------------------
# Host staging + verification helpers
# ---------------------------------------------------------------------------

def stage_g1(points) -> tuple[np.ndarray, np.ndarray]:
    """[(x, y) ints] -> (B, L) Montgomery limb arrays."""
    xs = np.stack([F.to_mont(p[0]) for p in points])
    ys = np.stack([F.to_mont(p[1]) for p in points])
    return xs, ys


def stage_g2(points):
    """[((x0,x1),(y0,y1)) ints] -> twist-point limb tuples + the
    host-precomputed Frobenius correction points."""
    def pack(pts):
        return ((np.stack([F.to_mont(p[0][0]) for p in pts]),
                 np.stack([F.to_mont(p[0][1]) for p in pts])),
                (np.stack([F.to_mont(p[1][0]) for p in pts]),
                 np.stack([F.to_mont(p[1][1]) for p in pts])))

    q1s = [ref.g2_frobenius(q) for q in points]
    nq2s = [ref.g2_neg_tw(ref.g2_frobenius(q1)) for q1 in q1s]
    return pack(points), pack(q1s), pack(nq2s)


def f12_from_device(f) -> list:
    """Device Fp12 (nested tuples of (B, L) mont limbs) -> list of
    int-reference Fp12 elements, for differential comparison."""
    d0, d1 = f
    B = d0[0][0].shape[0]
    out = []
    for i in range(B):
        def cvt_f2(c):
            return (F.from_limbs(np.asarray(c[0][i])),
                    F.from_limbs(np.asarray(c[1][i])))
        out.append((tuple(cvt_f2(c) for c in d0),
                    tuple(cvt_f2(c) for c in d1)))
    return out
