"""BLS12-381 aggregate verification — the batched, device-staged path.

The provider's `verify_aggregate` serves from HERE, not from
`bls12_381_ref` directly: this module owns the batch structure —
per-pair Miller loops accumulated into one product, ONE shared final
exponentiation per call — which is precisely the shape ROADMAP item 4
lifts on-device (the 2G2T MSM-outsourcing / ACE-runtime amortization
from PAPERS.md: the loop iterations batch across pairs; the expensive
final exp is paid once per call whatever the batch size).

As of round-21 the hot stages run ON DEVICE: `ops/limb.LimbLayout`
parameterizes the Montgomery core by modulus width (the 381-bit field
gets a 30-limb layout with re-proven int32 bounds) and
`ops/bls12_381_kernel` transcribes `miller_products` /
`check_products` into one fixed-shape batched program — a single
lax.scan Miller loop over every staged pair, a tree product-reduce,
ONE register-machine final exponentiation per call. The provider
routes there through `TPUProvider._bls_pairing_check`; THIS module
remains the staged host twin those seams demote to (small batches,
CPU rigs, breaker-open, device faults) with bit-identical verdicts:
`stage_pairs` produces the flat (G1, G2) pair list both consumers
share, `miller_products` is the only host function that iterates
pairs, and `check_products` is the single host final-exp site.

The host fallback twin (`bls12_381_ref.aggregate_verify`) computes
the same predicate through its own code path — the chaos contract
(armed `tpu.bls_aggregate` fault -> fallback) compares the two.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from fabric_tpu.ops import bls12_381_ref as bref

logger = logging.getLogger("ops.bls12_381")


def stage_pairs(pks: Sequence, msgs: Sequence[bytes], agg_sig
                ) -> Optional[list]:
    """Flatten one aggregate-verify call into the pairing-product pair
    list [(g1_point, g2_twist_point), ...] whose product must be ONE:
    e(agg_sig, -G2) * prod_i e(H(m_i), pk_i). Returns None when an
    input fails the structural/subgroup gates (the verdict is False
    without touching any pairing)."""
    if agg_sig is None or len(pks) != len(msgs) or not len(pks):
        return None
    if not bref.g1_in_subgroup(agg_sig):
        return None
    pairs = [(agg_sig, bref.g2_neg((bref.G2_X, bref.G2_Y)))]
    for pk, msg in zip(pks, msgs):
        if pk is None or not bref.g2_in_subgroup(pk):
            return None
        pairs.append((bref.hash_to_g1(msg), pk))
    return pairs


def miller_products(pairs) -> tuple:
    """The batched Miller stage: one loop per pair, accumulated into a
    single Fp12 product. THIS is the function item 4 replaces with a
    vmapped device kernel over wide limbs (same signature: pairs in,
    one Fp12 element out)."""
    f = bref.F12_ONE
    for p, q in pairs:
        f = bref.f12_mul(f, bref.miller_loop(q, p))
    return f


def check_products(f) -> bool:
    """ONE shared final exponentiation for the whole batch — the cost
    that amortizes across however many pairs the call aggregated."""
    return bref.final_exponentiation_fast(f) == bref.F12_ONE


def aggregate_verify(pks, msgs, agg_sig) -> bool:
    """The staged pipeline end to end: gate/stage -> batched Miller ->
    one final exp. Verdict-identical to
    `bls12_381_ref.aggregate_verify` (differential-tested)."""
    pairs = stage_pairs(pks, msgs, agg_sig)
    if pairs is None:
        return False
    return check_products(miller_products(pairs))
