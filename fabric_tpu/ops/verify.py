"""Fused batched verify pipeline: SHA-256 + ECDSA-P256 in one XLA program.

This is the flagship kernel of the framework — the TPU rebuild of the
reference's per-signature verify micro-stack (`msp/identities.go:170-199`:
hash the message, then `bccsp.Verify` the digest). A whole block's worth of
signatures is hashed and verified as one fixed-shape program, shardable over
the batch axis across a device mesh (ICI collectives only at the final
all-gather of result bits — the problem is embarrassingly batch-parallel).
"""

from __future__ import annotations

import numpy as np

from fabric_tpu.ops import p256, sha256
from fabric_tpu.ops.limb import L  # noqa: F401  (re-exported shape constant)


def verify_pipeline(blocks, nblocks, qx, qy, r, rpn, w, premask):
    """Hash-and-verify a batch of (message, pubkey, signature) triples.

    blocks:  (B, NB, 16) uint32 — SHA-padded message blocks (host-packed).
    nblocks: (B,) int32 — real padded-block count per message.
    qx, qy:  (B, L) int32 — pubkey affine coordinates, canonical limbs.
    r:       (B, L) int32 — signature r, canonical limbs.
    rpn:     (B, L) int32 — r + n if r + n < p else r (x-mod-n wrap case).
    w:       (B, L) int32 — s^{-1} mod n, canonical limbs (host-computed).
    premask: (B,) bool — host-side DER/range/low-S validity gate.
    Returns (B,) bool accept mask.
    """
    digests = sha256.sha256_blocks(blocks, nblocks)
    return p256.verify_core(digests, qx, qy, r, rpn, w, premask)


def example_inputs(batch: int, nb: int = 2, seed: int = 7):
    """Deterministic, well-formed example inputs for compile checks and
    benchmarks (numpy host arrays; not valid signatures — premask is all
    True and the kernel will simply reject them, which exercises every op).
    """
    import random

    from fabric_tpu.ops import limb

    rng = random.Random(seed)
    msgs = [bytes([rng.randrange(256) for _ in range(40 + i % 50)])
            for i in range(batch)]
    blocks, nblocks = sha256.pack_messages(msgs, nb)
    qs = [p256.to_affine_int(
        p256.scalar_mul_int(rng.randrange(1, p256.N), (p256.GX, p256.GY, 1)))
        for _ in range(min(batch, 4))]
    qx = limb.ints_to_limbs([qs[i % len(qs)][0] for i in range(batch)])
    qy = limb.ints_to_limbs([qs[i % len(qs)][1] for i in range(batch)])
    rs = [rng.randrange(1, p256.N) for _ in range(batch)]
    ss = [rng.randrange(1, p256.N) for _ in range(batch)]
    r = limb.ints_to_limbs(rs)
    rpn = limb.ints_to_limbs(
        [x + p256.N if x + p256.N < p256.P else x for x in rs])
    w = limb.ints_to_limbs([pow(s, -1, p256.N) for s in ss])
    premask = np.ones((batch,), dtype=bool)
    return blocks, nblocks, qx, qy, r, rpn, w, premask
