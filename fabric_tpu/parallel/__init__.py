from fabric_tpu.parallel.mesh import (  # noqa: F401
    batch_mesh,
    shard_batch,
    sharded_verify_fn,
)
