from fabric_tpu.parallel.mesh import (  # noqa: F401
    BATCH_AXIS,
    batch_mesh,
    shard_batch,
    sharded_comb_fns,
    sharded_verify_fn,
    shardmap_comb_verify,
)
