"""Device-mesh sharding for the batched verify pipeline.

The reference scales validation with a goroutine pool bounded by
`validatorPoolSize` (`core/peer/peer.go:501`, default NumCPU); the TPU
rebuild scales by sharding the signature-batch axis of one XLA program over
a `jax.sharding.Mesh`. Verification is embarrassingly batch-parallel —
XLA's SPMD partitioner splits every op along the batch dim and the only
collective is the implicit all-gather of the (B,) result bits back to the
host. Multi-host sidecars would extend the same mesh over DCN; nothing in
the kernel changes.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fabric_tpu.ops import verify as verify_ops

BATCH_AXIS = "batch"


def batch_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first `n_devices` local devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def _shardings(mesh: Mesh):
    """Input shardings for verify_pipeline's 8 args (all batch-leading)."""
    s = NamedSharding(mesh, P(BATCH_AXIS))
    return (s,) * 8


def shard_batch(mesh: Mesh, *host_arrays):
    """Place batch-leading host arrays onto the mesh, split on dim 0.

    Batch size must be a multiple of the mesh size — callers pad to a
    fixed bucket first (fabric_tpu/bccsp handles bucketing).
    """
    s = NamedSharding(mesh, P(BATCH_AXIS))
    return tuple(jax.device_put(a, s) for a in host_arrays)


def sharded_verify_fn(mesh: Mesh):
    """jit-compiled verify_pipeline with batch-dim sharding over `mesh`."""
    return jax.jit(
        verify_ops.verify_pipeline,
        in_shardings=_shardings(mesh),
        out_shardings=NamedSharding(mesh, P(BATCH_AXIS)),
    )


def shardmap_comb_verify(mesh: Mesh, q16: bool, tree: str = "xla"):
    """The flagship comb pipeline as a per-shard program (shard_map).

    This is the SAME layout the TPU provider compiles under a mesh
    (bccsp/tpu.py _comb_pipeline_locked): batch-sharded operand lanes,
    replicated tables, no collectives — shard_map rather than GSPMD so
    the pallas VMEM tree (a custom call the partitioner cannot split)
    is legal per shard. With q16=True the 16-bit window configuration
    (the measured single-chip headline) is exercised; tree="xla" keeps
    the gate runnable on CPU meshes where pallas cannot lower.
    """
    from fabric_tpu.common import jaxenv
    from fabric_tpu.ops import comb

    def local(words, key_idx, q_flat, g16, r, rpn, w, premask):
        return comb.comb_verify_with_tables(
            words, key_idx, q_flat, r, rpn, w, premask,
            g16=g16 if q16 else None, q16=q16, tree=tree)

    s = P(BATCH_AXIS)
    rep = P()
    return jax.jit(jaxenv.shard_map(
        local, mesh=mesh,
        in_specs=(s, s, rep, rep, s, s, s, s), out_specs=s))


def sharded_comb_fns(mesh: Mesh):
    """(table_builder, verify_fn) for the comb kernel over `mesh`.

    The per-key tables are small and read by every lane, so they are
    REPLICATED to all devices; every per-signature operand is sharded on
    the batch axis. This is the flagship kernel's multi-chip layout: no
    collectives in the main path at all — each chip combs its own batch
    shard against its local table copy.
    """
    from fabric_tpu.ops import comb

    rep = NamedSharding(mesh, P())
    s = NamedSharding(mesh, P(BATCH_AXIS))
    build = jax.jit(comb.build_q_tables,
                    in_shardings=(rep, rep), out_shardings=rep)
    verify = jax.jit(
        comb.comb_verify_with_tables,
        in_shardings=(s, s, rep, s, s, s, s),
        out_shardings=s)
    return build, verify
