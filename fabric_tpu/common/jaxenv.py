"""JAX environment knobs shared by node startup and benchmarks.

The reference pays its crypto setup cost per-signature at runtime; this
framework pays it once at XLA compile time — which BENCH_r01 measured at
~2 minutes per batch shape on a v5e. A persistent compilation cache
makes that a once-per-binary cost instead of once-per-process: a peer
restart (crash recovery, upgrade) must not stall block validation for
minutes re-compiling a kernel that has not changed.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("common.jaxenv")

_ENV = "FABRIC_TPU_XLA_CACHE"
_DEFAULT = os.path.join(os.path.expanduser("~"), ".cache", "fabric_tpu_xla")
_done = False
_cache_dir: str | None = None


def cache_dir() -> str | None:
    """The enabled persistent-compile-cache directory, or None. The
    round-16 compile seam (common/devicecost.py) probes this dir's
    entry count around each compile: a cold compile WRITES an entry,
    a warm load only reads — the cache-hit-vs-miss signal."""
    return _cache_dir


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Point jax at a persistent on-disk compilation cache.

    Resolution order: explicit arg > $FABRIC_TPU_XLA_CACHE > ~/.cache.
    Setting the env var to an empty string disables the cache. Safe to
    call repeatedly; must run before the first jit compilation to help.
    """
    global _done, _cache_dir
    if _done:
        return None
    cache = path if path is not None else os.environ.get(_ENV, _DEFAULT)
    if not cache:
        return None
    try:
        import jax

        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        # cache every program regardless of compile time or size
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _done = True
        _cache_dir = cache
        logger.info("XLA compilation cache at %s", cache)
        return cache
    except Exception:
        logger.exception("could not enable the XLA compilation cache")
        return None


def shard_map(fn, mesh, in_specs, out_specs):
    """Version-portable `shard_map` wrapper for the sharded verify
    pipeline.

    jax >= 0.6 exposes `jax.shard_map(..., check_vma=)`; the 0.4.x
    line this container ships has only
    `jax.experimental.shard_map.shard_map(..., check_rep=)`. Either
    way replication checking is disabled: the flagship comb pipeline
    contains a pallas_call custom call the checker cannot see
    through, and the tables really are replicated by construction
    (`TPUProvider._resolve_tables` places them with an empty
    PartitionSpec)."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        except TypeError:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _esm
    return _esm(fn, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, check_rep=False)


def pallas_interpret() -> bool:
    """Whether Pallas programs should run under ``interpret=True``.

    The wheel-free CI environment has no Mosaic backend, so every
    Pallas kernel (ops/ptree.py, ops/fused_verify.py) runs interpreted
    there — same program, traced through XLA on CPU — and compiles for
    real only when a TPU backend is actually attached. FTPU_PALLAS_
    INTERPRET=0/1 overrides the autodetect for A/B runs on real chips.
    """
    # ftpu-check: allow-retrace(compile-time config by design: the
    # interpret flag is pinned for the process, read once per trace)
    env = os.environ.get("FTPU_PALLAS_INTERPRET")
    if env is not None:
        return env != "0"
    import jax

    return jax.default_backend() != "tpu"


def enable_cache_under(warm_dir: str | None) -> str | None:
    """Key the persistent compilation cache under a provider's warm
    state directory (``<warm_dir>/xla_cache``) so the ~minutes kernel
    compiles are paid once per MACHINE, not once per process — compiled
    programs live beside the warm Q-table bytes they serve.

    An explicit $FABRIC_TPU_XLA_CACHE (including the empty string,
    which disables caching) still wins; with no warm dir this falls
    back to the ~/.cache default."""
    if os.environ.get(_ENV) is not None or not warm_dir:
        return enable_compilation_cache()
    return enable_compilation_cache(os.path.join(warm_dir, "xla_cache"))
