"""Per-device health for the sharded verify mesh (elastic fail-in-place).

The provider-wide breaker (common/breaker.py) answers "is the
accelerator path serving at all" — one chip failing mid-`shard_map`
would trip it and drop an 8-chip box to 0-chip (host-path) throughput.
Large accelerator fleets instead fail IN PLACE: bench the one bad
chip, keep serving on the survivors, re-admit after it recovers
(the committee-consensus measurement in arXiv:2302.00418 makes the
same point for consensus crypto — throughput claims are meaningless
without the degraded-mode curve).

This module is the accounting half of that: a ring of per-device
`CircuitBreaker`s (the SAME trip/cooldown/probe discipline as the
provider breaker, one per chip) fed by three signals the sharded
dispatch already produces —

  * device-attributed dispatch failures (`DeviceLostError` from the
    span feeder, or a runtime error whose message names a device);
  * per-chip transfer timings from `TPUProvider._shard_put`;
  * per-chip ready-lag skew from `_record_shard_stats`.

A device whose breaker opens is QUARANTINED: `healthy()` drops it and
the provider rebuilds a smaller mesh over the survivors
(bccsp/tpu.py `_rebuild_mesh`). After `cooldown_s` the breaker
half-opens and `probe_candidates()` offers the chip for ONE bounded
probe dispatch; success re-admits it and the mesh grows back.

States per device (the `bccsp_device_state` gauge):
    0 healthy      in the serving mesh
    1 probing      cooldown elapsed, awaiting its probe's outcome
    2 quarantined  out of the mesh, cooling down
"""

from __future__ import annotations

import logging
import re
import threading
import time
from dataclasses import dataclass
from typing import Optional

from fabric_tpu.common.breaker import (
    BreakerConfig,
    CircuitBreaker,
    CircuitOpen,
    DEGRADED,
    DEVICE,
)

logger = logging.getLogger("common.devicehealth")


class DeviceLostError(RuntimeError):
    """A dispatch failure attributable to ONE device (raised by the
    sharded span feeder when a chip's transfer stream fails or an
    armed `tpu.device_lost` fault fires). The provider breaker
    IGNORES this type — losing one chip must quarantine that chip,
    never bench the whole accelerator path."""

    def __init__(self, device: int, cause: BaseException):
        super().__init__(f"device {device} lost: "
                         f"{type(cause).__name__}: {cause}")
        self.device = device
        self.cause = cause


class DeviceStragglerError(RuntimeError):
    """Synthetic failure fed to a device's breaker when its straggler
    strikes exceed the budget — the chip answers, but so slowly it
    paces the whole mesh."""


# runtime errors that name a device: "device 3", "TPU_3", "TPU:3",
# "device=3" — the patterns real XLA/PJRT errors use
_DEVICE_RE = re.compile(
    r"(?:\bdevice[\s=:#]+|\bTPU[_:]|\bchip[\s=:#]+)(\d+)",
    re.IGNORECASE)


def device_from_error(message: str, n_devices: int) -> Optional[int]:
    """Best-effort device attribution for a runtime error string:
    the first in-range device index the message names, else None."""
    for m in _DEVICE_RE.finditer(message or ""):
        d = int(m.group(1))
        if 0 <= d < n_devices:
            return d
    return None


@dataclass
class DeviceHealthConfig:
    """`BCCSP.TPU.DeviceHealth` in core.yaml (parsed by
    bccsp/factory.py)."""
    # device-attributed faults before quarantine. 1 by default: a
    # transfer stream failing on a named chip is strong evidence
    # (noisy timing signals gate through straggler_strikes instead)
    trip_threshold: int = 1
    cooldown_s: float = 30.0
    # a chip whose per-batch transfer time (or ready-lag jump) exceeds
    # the mesh median by this many seconds earns a straggler strike;
    # <= 0 disables straggler quarantine entirely
    straggler_skew_s: float = 2.0
    # consecutive struck batches before the chip is quarantined
    straggler_strikes: int = 3
    # wall bound on one re-admission probe dispatch
    probe_timeout_s: float = 5.0


class DeviceHealth:
    """Per-device fault/straggler accounting + quarantine ring.

    Device indices are FULL-mesh positions (the factory-built mesh),
    stable across rebuilds — chaos targets "chip 3" whatever the
    serving mesh currently looks like. Thread-safe: dispatch paths,
    the admission-time rebuild hook and the stats poller all read it.
    """

    def __init__(self, n_devices: int,
                 config: Optional[DeviceHealthConfig] = None,
                 clock=time.monotonic, name: str = "bccsp.device"):
        self.config = config or DeviceHealthConfig()
        self.n_devices = n_devices
        self._lock = threading.Lock()
        self._breakers = [
            CircuitBreaker(
                BreakerConfig(
                    trip_threshold=max(1, self.config.trip_threshold),
                    cooldown_s=self.config.cooldown_s),
                name=f"{name}{d}", clock=clock)
            for d in range(n_devices)
        ]
        self._strikes = [0] * n_devices
        self._quarantines = [0] * n_devices
        self._readmits = [0] * n_devices
        self._straggler_strikes_total = 0

    def set_clock(self, clock) -> None:
        """Test seam: drive every per-device breaker's cooldown from
        an injectable monotonic clock instead of wall sleeps."""
        for br in self._breakers:
            br._clock = clock

    # -- state --

    def state(self, d: int) -> str:
        return self._breakers[d].state

    def state_codes(self) -> list[int]:
        return [br.state_code for br in self._breakers]

    def healthy(self) -> list[int]:
        """Full-mesh indices fit to serve (breaker closed). Probing
        devices stay OUT until their probe succeeds — the serving
        mesh only ever contains chips currently believed good."""
        return [d for d, br in enumerate(self._breakers)
                if br.state == DEVICE]

    def quarantined(self) -> list[int]:
        return [d for d, br in enumerate(self._breakers)
                if br.state != DEVICE]

    # -- fault accounting --

    def record_fault(self, d: int, exc: BaseException | None = None
                     ) -> bool:
        """Count one device-attributed failure against chip `d`.
        Returns True when this failure newly quarantined it. A chip
        that is ALREADY benched is left alone: CircuitBreaker.failure
        on an open breaker re-arms its cooldown, so re-attributed
        failures from stale/doomed dispatches would keep a dead chip
        from ever reaching its re-admission probe. The whole
        check-fail-count sequence runs under the ring lock — a chip
        dying with several dispatches in flight attributes
        CONCURRENTLY, and racers past a bare pre-check would each
        re-arm the cooldown and each count a quarantine."""
        br = self._breakers[d]
        with self._lock:
            if br.state != DEVICE:
                return False
            br.failure(exc)
            newly = br.state != DEVICE
            if newly:
                self._quarantines[d] += 1
                self._strikes[d] = 0
        if newly:
            logger.warning(
                "device %d QUARANTINED after %s (cooldown %.1fs; the "
                "mesh rebuilds over the survivors)", d,
                type(exc).__name__ if exc else "failure",
                self.config.cooldown_s)
            # flight-recorder landmark + automatic postmortem dump
            # (outside the ring lock: the dump does file I/O)
            from fabric_tpu.common import tracing
            tracing.note_quarantine(d)
        return newly

    def attribute(self, exc: BaseException) -> Optional[int]:
        """Map a dispatch exception to a device and record the fault:
        a `DeviceLostError` carries its device; any other error is
        matched against the device-naming patterns. Returns the
        struck device index, or None when unattributable."""
        if isinstance(exc, DeviceLostError):
            d = exc.device
        else:
            d = device_from_error(str(exc), self.n_devices)
        if d is None:
            return None
        self.record_fault(d, exc)
        return d

    # -- straggler accounting --

    def observe_shard(self, full_idx: list[int],
                      transfer_s: list[float],
                      ready_s: list[float]) -> list[int]:
        """Feed one sharded batch's per-chip readings (positions map
        to `full_idx`). A chip whose transfer time exceeds the mesh
        median — or whose ready-lag JUMP over its mesh predecessor
        exceeds it (ready_s is sampled in mesh order, so a straggler
        shows as a step) — by `straggler_skew_s` earns a strike; a
        clean batch clears its strikes (consecutive, not lifetime).
        `straggler_strikes` strikes quarantine it. Returns EVERY
        newly quarantined device (correlated failures — two chips on
        one degrading link — cross the threshold in the same batch)."""
        skew = self.config.straggler_skew_s
        if skew <= 0 or len(full_idx) < 2:
            return []
        struck: set[int] = set()
        if transfer_s and len(transfer_s) == len(full_idx):
            # LOWER median: with an even mesh and half the chips slow
            # (one degrading switch feeding two chips), the upper
            # median IS the slow value and nothing ever reads as over
            # budget — a straggler is "slower than a typical chip",
            # and the typical chip is the faster half's boundary
            med = sorted(transfer_s)[(len(transfer_s) - 1) // 2]
            for pos, t in enumerate(transfer_s):
                if t - med > skew:
                    struck.add(pos)
        if ready_s and len(ready_s) == len(full_idx):
            for pos in range(1, len(ready_s)):
                if ready_s[pos] - ready_s[pos - 1] > skew:
                    struck.add(pos)
        over: list[tuple[int, int]] = []
        with self._lock:
            for pos in range(len(full_idx)):
                d = full_idx[pos]
                if pos not in struck:
                    self._strikes[d] = 0
                    continue
                self._strikes[d] += 1
                self._straggler_strikes_total += 1
                logger.warning(
                    "device %d straggler strike %d/%d (skew budget "
                    "%.3fs)", d, self._strikes[d],
                    self.config.straggler_strikes, skew)
                if self._strikes[d] >= self.config.straggler_strikes:
                    over.append((d, self._strikes[d]))
        quarantined: list[int] = []
        for d, trip in over:
            exc = DeviceStragglerError(
                f"device {d} struck {trip} consecutive batches")
            # drive the chip's breaker OPEN through its own
            # discipline (record_fault counts the quarantine
            # transition; the loop is bounded by the chip's trip
            # threshold), so cooldown/probe re-entry is exactly the
            # fault path's
            br = self._breakers[d]
            for _ in range(max(1, self.config.trip_threshold)):
                if br.state != DEVICE:
                    break
                self.record_fault(d, exc)
            if br.state != DEVICE:
                quarantined.append(d)
        return quarantined

    # -- probe / re-admission --

    def probe_candidates(self) -> list[int]:
        """Quarantined devices whose cooldown elapsed AND whose
        half-open probe slot this caller just acquired — the caller
        MUST report each one via probe_result()."""
        out = []
        for d, br in enumerate(self._breakers):
            if br.state == DEVICE:
                continue
            try:
                if br.admit():
                    out.append(d)
            except CircuitOpen:
                continue
        return out

    def probe_execution(self, d: int):
        """Context manager marking chip `d`'s probe as LIVE while it
        executes (the breaker's execution window): probe wall time is
        bounded by `probe_timeout_s`, which may exceed the breaker's
        stale-probe reclaim window (max(cooldown_s, 1s)) — without
        this, an admission's state poll would reclaim the slot under
        a merely-slow probe."""
        return self._breakers[d].execution()

    def probe_result(self, d: int, ok: bool,
                     exc: BaseException | None = None) -> None:
        """Report a probe outcome. A successful probe counts as a
        re-admission ONLY if the breaker actually closed —
        `success()` is deliberately a no-op on a breaker the
        stale-probe reclaim already re-opened, and counting/logging a
        readmit then would report a recovered chip that never
        rejoined the mesh."""
        br = self._breakers[d]
        readmitted = False
        with self._lock:
            if ok:
                br.success()
                readmitted = br.state == DEVICE
                if readmitted:
                    self._readmits[d] += 1
                    self._strikes[d] = 0
            else:
                br.failure(exc or DeviceLostError(
                    d, RuntimeError("probe failed")))
        if readmitted:
            logger.info("device %d probe succeeded; re-admitted to "
                        "the mesh", d)
            from fabric_tpu.common import tracing
            tracing.note_readmit(d)
        elif ok:
            logger.warning(
                "device %d probe answered, but its slot was already "
                "stale-reclaimed (probe outlived the breaker's "
                "reclaim window); staying quarantined until the next "
                "probe", d)

    # -- observability (bccsp_device_* gauges) --

    def snapshot(self) -> dict:
        """Per-device gauge rows, one slot per FULL-mesh device:
        published device-labeled by profiling.publish_provider_stats."""
        with self._lock:
            return {
                "state": self.state_codes(),
                "trips": [br.stats["trips"]
                          for br in self._breakers],
                "quarantines": list(self._quarantines),
                "readmits": list(self._readmits),
            }

    def totals(self) -> dict:
        with self._lock:
            return {
                "device_quarantines": sum(self._quarantines),
                "device_readmits": sum(self._readmits),
                "device_straggler_strikes":
                    self._straggler_strikes_total,
            }

    def any_unhealthy(self) -> bool:
        return any(br.state != DEVICE for br in self._breakers)

    def force_state(self, d: int, state: str) -> None:
        """Test seam: pin a device's breaker state directly."""
        br = self._breakers[d]
        with br._lock:
            br._state = state
            if state == DEGRADED:
                br._open_until = br._clock() + self.config.cooldown_s
