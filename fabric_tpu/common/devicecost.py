"""Device-cost observability: XLA compile & cache telemetry, per-chip
memory accounting and device-busy ratios (round 16).

The device side of the pipeline was blind before this layer: a cold
XLA compile is the 1436s-vs-88s restart cliff PR 6 measured, a
persistent-cache miss in steady state means an unplanned shape slipped
into serving, and HBM occupancy decides whether the next oversized
span OOMs — none of which was observable. Three instruments fix that:

  * ``CompileRecorder`` — the ONE seam every compiled-path build in
    `bccsp/tpu.py` goes through (``TPUProvider._jit``). Each first
    dispatch of a new argument shape (and each AOT
    ``lower(...).compile()`` from prewarm) is timed, classified
    cache-hit vs cold (persistent-cache-dir delta + a wall-time
    threshold: a cold compile WRITES a new cache entry and takes
    seconds-to-minutes; a warm load does neither), annotated with
    XLA's lowering cost analysis (flops / bytes accessed, where the
    jax version exposes it), and recorded as a ``tpu.compile`` tracing
    span. A cold compile emits a ``compile.cold`` instant, and in
    steady state (after the first successful dispatch) auto-dumps the
    flight recorder — a steady-state cold compile is exactly the
    latency cliff an operator needs the timeline for.
  * ``device_memory()`` — per-device ``memory_stats()`` rows
    (bytes_in_use / peak / limit; devices without the API — CPU test
    meshes — simply report nothing), polled by
    ``profiling.publish_devicecost_stats`` into the
    ``bccsp_device_mem_{used,peak,limit}_bytes`` gauges, and read by
    the provider's `/healthz` HBM-headroom sub-state.
  * ``DeviceBusy`` — cumulative per-chip device-time fed from the
    same per-chip ready readings that feed the ``device.ready.d<k>``
    tracing stages; ``ratios()`` converts the window's accumulation
    into ``bccsp_device_busy_ratio`` (device-time over wall-time).

Everything here is wheel-free and clock-seamed for tests: the
recorder takes an injectable clock and cache-dir resolver, and the
whole layer imports jax lazily (a host without a device plugin still
imports and serves zeros).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from fabric_tpu.common import tracing

logger = logging.getLogger("common.devicecost")

# a first-shape dispatch slower than this is a compile even when the
# cache-dir probe is unavailable (threshold rule); a persistent-cache
# HIT is an mmap-and-load, far under a second even for the big comb
# programs (PR-6: cached 88s total vs cold 1436s across ~a dozen
# shapes)
COLD_COMPILE_THRESHOLD_S = float(
    os.environ.get("FTPU_DEVICECOST_COLD_S", "5.0"))

# minimum free fraction of any device's memory limit before /healthz
# components.bccsp grows the hbm_low sub-state — the "an oversized
# span is about to OOM" warning light
HBM_HEADROOM_FRAC = float(
    os.environ.get("FTPU_HBM_HEADROOM_FRAC", "0.10"))

# lowering cost analysis traces the program a second time (seconds on
# the big comb pipelines) — a once-per-shape cost, but disable-able
# for deadline-critical rigs
ANALYSIS_ENABLED = os.environ.get("FTPU_DEVICECOST_ANALYSIS",
                                  "1") == "1"

_EVENT_CAP = 256        # bounded per-compile event history


def _shape_key(args) -> tuple:
    """A compiled-program shape key: (shape, dtype) per argument —
    the same data XLA keys its own dispatch cache by. Non-array
    arguments degrade to their type name."""
    return tuple(
        (getattr(a, "shape", None),
         getattr(a, "dtype", None) if getattr(a, "dtype", None)
         is not None else type(a).__name__)
        for a in args)


def _normalize_cost(ca) -> Optional[dict]:
    """One normalization of XLA's cost_analysis return shapes (dict
    in current jax, list-of-dict historically) into the two numbers
    the events carry — shared by the first-dispatch and AOT paths so
    they can never classify the same compile differently."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for k in ("flops", "bytes accessed"):
        if k in ca:
            out[k.replace(" ", "_")] = float(ca[k])
    return out or None


class DeviceBusy:
    """Cumulative per-device busy seconds -> windowed busy ratios.

    ``note(device, seconds)`` accumulates device-time (the per-chip
    ready lag of a sharded dispatch, or the whole-batch device stage
    on a single-chip provider); ``ratios()`` returns each device's
    busy-time share of the wall window since the previous ``ratios()``
    call, clamped to [0, 1] — the poller's cadence IS the window."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._busy: dict = {}       # device -> cumulative seconds
        self._last: dict = {}       # snapshot at the last ratios()
        self._last_t = clock()

    def note(self, device: int, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._lock:
            self._busy[device] = self._busy.get(device, 0.0) + \
                float(seconds)

    def totals(self) -> dict:
        with self._lock:
            return dict(self._busy)

    def ratios(self) -> dict:
        """{device: busy_fraction} over the window since the last
        call. A device with no dispatches in the window reads 0.0 —
        idle, not absent."""
        with self._lock:
            now = self._clock()
            wall = now - self._last_t
            out: dict = {}
            if wall > 0:
                for d, total in self._busy.items():
                    delta = total - self._last.get(d, 0.0)
                    out[d] = round(min(1.0, max(0.0, delta / wall)), 4)
            self._last = dict(self._busy)
            self._last_t = now
            return out


class CompileRecorder:
    """The compile-seam bookkeeper (one per provider).

    Mirrors its counters into the provider's ``stats`` dict so they
    publish through the existing stats poller as the canonical
    ``bccsp_compile_{total,cache_hits,seconds}`` gauges:

      compile_total       programs compiled/loaded through the seam
      compile_cache_hits  persistent-compile-cache hits among them
      compile_cold_total  cold compiles (the expensive complement)
      compile_failures    builds/compiles that raised (armed
                          ``tpu.compile`` faults land here)
      compile_seconds     cumulative wall seconds inside the seam

    ``cache_dir`` may be a path, a zero-arg callable resolving one
    (``jaxenv.cache_dir`` — the persistent cache may be enabled after
    the provider is built), or None (threshold-only classification).
    """

    def __init__(self, stats: Optional[dict] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 cache_dir=None,
                 cold_threshold_s: Optional[float] = None,
                 analysis: Optional[bool] = None):
        self.stats = stats if stats is not None else {}
        self.stats.setdefault("compile_total", 0)
        self.stats.setdefault("compile_cache_hits", 0)
        self.stats.setdefault("compile_cold_total", 0)
        self.stats.setdefault("compile_failures", 0)
        self.stats.setdefault("compile_seconds", 0.0)
        self._clock = clock
        self._cache_dir = cache_dir
        self.cold_threshold_s = (COLD_COMPILE_THRESHOLD_S
                                 if cold_threshold_s is None
                                 else float(cold_threshold_s))
        self.analysis = (ANALYSIS_ENABLED if analysis is None
                         else bool(analysis))
        self.events: list = []      # bounded per-compile records
        self._lock = threading.Lock()
        self._steady = False
        self.busy = DeviceBusy()

    # -- steady-state marker (set after the first successful
    #    dispatch: later cold compiles are serving-path cliffs) --

    @property
    def steady(self) -> bool:
        return self._steady

    def mark_steady(self) -> None:
        self._steady = True

    # -- persistent-cache probe --

    def _cache_dir_path(self) -> Optional[str]:
        d = self._cache_dir
        if callable(d):
            try:
                d = d()
            except Exception:       # noqa: BLE001
                return None
        return d if isinstance(d, str) and d else None

    def cache_entries(self) -> int:
        """Entry count of the persistent compile cache dir, or -1
        when there is none to probe. A cold compile WRITES an entry;
        a warm load only reads — the before/after delta is the
        hit-vs-miss signal the wall-time threshold backstops."""
        d = self._cache_dir_path()
        if not d:
            return -1
        try:
            with os.scandir(d) as it:
                return sum(1 for e in it if e.is_file())
        except OSError:
            return -1

    # -- recording --

    def note(self, kind: str, seconds: float, *, cache_hit: bool,
             key=None, cost: Optional[dict] = None,
             error: Optional[BaseException] = None,
             aot: bool = False) -> None:
        """Book one pass through the seam. ``error`` records a failed
        build/compile (counter only — the caller re-raises and the
        enclosing ``tpu.compile`` span stamps error status)."""
        ev = {"kind": kind, "seconds": round(float(seconds), 6),
              "cache_hit": bool(cache_hit) and error is None,
              "cold": error is None and not cache_hit,
              "aot": aot, "steady": self._steady,
              "key": repr(key) if key is not None else None,
              "cost": cost or None,
              "error": repr(error) if error is not None else None}
        with self._lock:
            if error is not None:
                self.stats["compile_failures"] += 1
            else:
                self.stats["compile_total"] += 1
                self.stats["compile_seconds"] = round(
                    self.stats["compile_seconds"] + float(seconds), 6)
                if cache_hit:
                    self.stats["compile_cache_hits"] += 1
                else:
                    self.stats["compile_cold_total"] += 1
            self.events.append(ev)
            if len(self.events) > _EVENT_CAP:
                del self.events[:len(self.events) - _EVENT_CAP]
        if error is None and not cache_hit:
            tracing.instant("compile.cold", kind=kind,
                            seconds=round(float(seconds), 3),
                            steady=self._steady)
            if self._steady:
                # the 1436s-vs-88s cliff, live: a cold compile AFTER
                # the provider reached steady state means an
                # unplanned shape entered serving — dump the
                # timeline around it
                tracing.auto_dump("cold_compile")
            logger.info(
                "cold XLA compile: kind=%s %.1fs%s", kind,
                float(seconds),
                " (STEADY STATE — unplanned shape?)"
                if self._steady else "")

    def run_compile(self, kind: str, key, thunk, *,
                    cost: Optional[dict] = None, aot: bool = False):
        """THE classification path: run `thunk` (a first-shape
        dispatch or an AOT ``lower().compile()``) inside a
        ``tpu.compile`` span, time it, classify hit-vs-cold
        (cache-dir entry delta + wall threshold) and book the event.
        A raising thunk books a failure and re-raises."""
        before = self.cache_entries()
        t0 = self._clock()
        try:
            with tracing.span("tpu.compile", kind=kind, aot=aot):
                out = thunk()
        except BaseException as e:
            self.note(kind, self._clock() - t0, cache_hit=False,
                      key=key, cost=cost, error=e, aot=aot)
            raise
        dt = self._clock() - t0
        wrote = before >= 0 and self.cache_entries() > before
        hit = (not wrote) and dt < self.cold_threshold_s
        self.note(kind, dt, cache_hit=hit, key=key, cost=cost,
                  aot=aot)
        return out

    def wrap(self, kind: str, jitted) -> "InstrumentedJit":
        """Instrument one jitted program — the return value of the
        provider's ``_jit`` seam."""
        return InstrumentedJit(self, kind, jitted)


class InstrumentedJit:
    """A jitted callable whose first dispatch per argument shape (and
    AOT ``lower().compile()``) runs inside the compile seam. Steady
    dispatches of a seen shape pay one set lookup."""

    __slots__ = ("_rec", "_kind", "_fn", "_seen", "_seen_lock")

    def __init__(self, recorder: CompileRecorder, kind: str, jitted):
        self._rec = recorder
        self._kind = kind
        self._fn = jitted
        self._seen: set = set()
        self._seen_lock = threading.Lock()

    def __call__(self, *args):
        key = _shape_key(args)
        if key in self._seen:
            return self._fn(*args)
        return self._compile_call(key, args)

    def _compile_call(self, key, args):
        """The instrumented first-dispatch path. The shape is CLAIMED
        before the call (concurrent first dispatches of one shape
        record once — jit serializes the actual compile anyway) and
        unclaimed on failure so a retry records again; measurement +
        hit/cold classification is the recorder's shared
        ``run_compile`` path, inside its ``tpu.compile`` span."""
        rec = self._rec
        with self._seen_lock:
            first = key not in self._seen
            if first:
                self._seen.add(key)
        if not first:
            return self._fn(*args)
        cost = self._cost_analysis(args)
        try:
            return rec.run_compile(self._kind, key,
                                   lambda: self._fn(*args),
                                   cost=cost)
        except BaseException:
            with self._seen_lock:
                self._seen.discard(key)
            raise

    def _cost_analysis(self, args) -> Optional[dict]:
        """XLA's lowering cost analysis for this shape (flops /
        bytes accessed), where the jax version exposes it. Traces the
        program once more — a once-per-shape cost on the (already
        seconds-to-minutes) compile path, never the dispatch path."""
        if not self._rec.analysis:
            return None
        try:
            return _normalize_cost(self._fn.lower(*args)
                                   .cost_analysis())
        except Exception:           # noqa: BLE001
            return None

    def lower(self, *args):
        """AOT seam: prewarm's ``fn.lower(shapes).compile()`` records
        through the same bookkeeping (``aot=True``). The shape is NOT
        marked seen — jit keeps its own dispatch cache, so the first
        real call still pays (and records) a persistent-cache hit."""
        return _InstrumentedLowered(self, _shape_key(args),
                                    self._fn.lower(*args))

    def __getattr__(self, name):
        return getattr(self._fn, name)


class _InstrumentedLowered:
    __slots__ = ("_ijit", "_key", "_lowered")

    def __init__(self, ijit: InstrumentedJit, key, lowered):
        self._ijit = ijit
        self._key = key
        self._lowered = lowered

    def compile(self, *args, **kwargs):
        ijit, rec = self._ijit, self._ijit._rec
        cost = None
        if rec.analysis:
            try:
                cost = _normalize_cost(self._lowered.cost_analysis())
            except Exception:       # noqa: BLE001
                cost = None
        return rec.run_compile(
            ijit._kind, self._key,
            lambda: self._lowered.compile(*args, **kwargs),
            cost=cost, aot=True)

    def __getattr__(self, name):
        return getattr(self._lowered, name)


# ---------------------------------------------------------------------------
# per-device memory accounting
# ---------------------------------------------------------------------------

# device-index -> "answers memory_stats()" capability, learned on the
# first poll: a CPU mesh answers None for every device, and a polling
# thread must not keep crossing into the runtime (including during
# interpreter shutdown) for devices that will never report
_mem_capable: dict = {}


def device_memory() -> list:
    """One row per local device exposing ``memory_stats()``:
    ``{"device", "kind", "bytes_in_use", "peak_bytes_in_use",
    "bytes_limit"}``. Devices without the API (CPU meshes) and hosts
    without jax report nothing — the gauges simply stay unset — and
    are not re-probed on later polls."""
    if _mem_capable and not any(_mem_capable.values()):
        return []           # fleet-wide no-stats-API: learned once
    try:
        import jax
        devs = jax.local_devices()
    except Exception:               # noqa: BLE001
        return []
    rows = []
    for i, d in enumerate(devs):
        if _mem_capable.get(i) is False:
            continue
        try:
            ms = d.memory_stats()
            # capability latches only on a CLEAN "no stats API"
            # answer (None on CPU meshes); a transient exception
            # (mesh rebuild, busy runtime) must not permanently
            # silence this chip's mem gauges and hbm_low warning
            _mem_capable[i] = bool(ms)
        except Exception:           # noqa: BLE001
            ms = None
        if not ms:
            continue
        in_use = int(ms.get("bytes_in_use", 0))
        rows.append({
            "device": i,
            "kind": getattr(d, "device_kind", str(d)),
            "bytes_in_use": in_use,
            "peak_bytes_in_use": int(
                ms.get("peak_bytes_in_use", in_use)),
            "bytes_limit": int(ms.get("bytes_limit", 0)),
        })
    return rows


def peak_memory_bytes(rows: Optional[list] = None) -> int:
    """The fleet's worst per-device peak occupancy (bench stage-line
    ``mem_peak_bytes``); 0 when no device reports memory stats."""
    rows = device_memory() if rows is None else rows
    return max((r.get("peak_bytes_in_use", 0) for r in rows),
               default=0)


def hbm_substate(rows: Optional[list] = None,
                 headroom_frac: Optional[float] = None
                 ) -> Optional[str]:
    """`hbm_low:d<k>:<free>%free` naming the tightest device when any
    device's free fraction drops under the headroom threshold, else
    None — the `/healthz components.bccsp` sub-state that shows an
    oversized span BEFORE it OOMs."""
    frac = HBM_HEADROOM_FRAC if headroom_frac is None \
        else float(headroom_frac)
    rows = device_memory() if rows is None else rows
    worst = None
    for r in rows:
        limit = r.get("bytes_limit") or 0
        if limit <= 0:
            continue
        free = 1.0 - (r.get("bytes_in_use", 0) / limit)
        if worst is None or free < worst[1]:
            worst = (r.get("device"), free)
    if worst is not None and worst[1] < frac:
        return f"hbm_low:d{worst[0]}:{max(0, int(worst[1] * 100))}%free"
    return None
