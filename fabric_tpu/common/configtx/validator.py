"""Config-transaction validation and delta computation.

Rebuild of `common/configtx/{validator,update,compare}.go`: a channel
reconfig is a ConfigUpdate (read_set: version assertions; write_set:
the new content) signed by enough principals to satisfy the mod_policy
of everything it touches.

Semantics:
- read_set versions must match the current config exactly;
- a write_set element with the current version is context (merged
  member-wise for groups);
- an element with version+1 is a modification → its CURRENT
  mod_policy must be satisfied by the update's signatures, and for
  groups the new membership is exactly the write_set's members;
- a new element must carry version 0 and satisfies the policy check
  via its parent group's mod_policy (reference: validator.go
  policyForItem walks up for new items).
"""

from __future__ import annotations

from typing import Optional

from fabric_tpu.protos import common, configtx as ctxpb
from fabric_tpu import protoutil as pu
from fabric_tpu.common.policies import PolicyError

class ConfigTxError(Exception):
    pass


_SINGULAR = {"groups": "group", "values": "value",
             "policies": "policy"}


def _singular(kind: str) -> str:
    return _SINGULAR[kind]


def _members(group: ctxpb.ConfigGroup):
    """(kind, name, element) triples for all members of a group."""
    for name, g in group.groups.items():
        yield "groups", name, g
    for name, v in group.values.items():
        yield "values", name, v
    for name, p in group.policies.items():
        yield "policies", name, p


class Validator:
    """Per-channel config state machine (reference:
    `common/configtx/validator.go` ValidatorImpl)."""

    def __init__(self, channel_id: str, config: ctxpb.Config,
                 policy_manager):
        self.channel_id = channel_id
        self.config = config
        self._pm = policy_manager

    def sequence(self) -> int:
        return self.config.sequence

    # -- entry point --

    def propose_config_update(self, update_env: ctxpb.ConfigUpdateEnvelope
                              ) -> ctxpb.Config:
        """Validate + apply; returns the NEW Config (sequence+1).
        Reference: `validator.go` ProposeConfigUpdate."""
        update = ctxpb.ConfigUpdate()
        update.ParseFromString(update_env.config_update)
        if update.channel_id != self.channel_id:
            raise ConfigTxError(
                f"update for channel {update.channel_id!r}, "
                f"validator is {self.channel_id!r}")

        signed_data = [
            pu.SignedData(
                data=bytes(sig.signature_header) +
                bytes(update_env.config_update),
                identity=pu.get_signature_header(
                    sig.signature_header).creator,
                signature=bytes(sig.signature),
            )
            for sig in update_env.signatures
        ]

        current = self.config.channel_group
        self._verify_read_set(current, update.read_set)
        self._verify_write_structure(current, update.write_set,
                                     ["Channel"])
        new_group = self._apply_group(
            current, update.write_set, path=["Channel"],
            signed_data=signed_data,
            parent_mod_policy=current.mod_policy or "Admins")

        new_config = ctxpb.Config(sequence=self.config.sequence + 1)
        new_config.channel_group.CopyFrom(new_group)
        return new_config

    # -- read set --

    def _verify_read_set(self, current: Optional[ctxpb.ConfigGroup],
                         read: ctxpb.ConfigGroup, path: str = "Channel"
                         ) -> None:
        if current is None:
            raise ConfigTxError(f"read_set references missing group {path}")
        if read.version != current.version:
            raise ConfigTxError(
                f"read_set version mismatch at {path}: "
                f"asserted {read.version}, current {current.version}")
        for kind, name, elem in _members(read):
            cur = getattr(current, kind).get(name)
            if kind == "groups":
                self._verify_read_set(cur, elem, f"{path}/{name}")
            else:
                if cur is None:
                    raise ConfigTxError(
                        f"read_set references missing {_singular(kind)} "
                        f"{path}/{name}")
                if elem.version != cur.version:
                    raise ConfigTxError(
                        f"read_set version mismatch at {path}/{name}")

    # -- write set --

    def _verify_write_structure(self, current: ctxpb.ConfigGroup,
                                write: ctxpb.ConfigGroup,
                                path: list[str]) -> None:
        """Structural pre-pass over the whole write_set, run BEFORE any
        signature-policy evaluation, covering every signature-independent
        rule: version windows, brand-new subtrees at version 0,
        same-version elements being byte-identical, and mod_policy swaps
        without a version bump. Violations are therefore reported
        deterministically regardless of which mod_policies the update's
        signatures happen to satisfy (reference: the version checks of
        `common/configtx/update.go` verifyDeltaSet). `_apply_group`
        trusts this pass — the version rules live only here."""
        if write.version not in (current.version, current.version + 1):
            raise ConfigTxError(
                f"group {'/'.join(path)} version {write.version} is "
                f"neither current ({current.version}) nor current+1")
        if write.version == current.version:
            if (write.mod_policy
                    and write.mod_policy != current.mod_policy):
                # swapping the gate without bumping (and so without
                # passing the CURRENT policy) would be a silent
                # privilege downgrade
                raise ConfigTxError(
                    f"group {'/'.join(path)} changes mod_policy "
                    f"without a version bump")
        elif not write.mod_policy:
            # every modified item must carry a usable mod_policy
            # (reference: update.go validateModPolicy rejects empty);
            # silently retaining the old one would make a requested
            # clear a non-converging no-op
            raise ConfigTxError(
                f"group {'/'.join(path)} is modified but has an empty "
                f"mod_policy")
        for kind, name, elem in _members(write):
            cur = getattr(current, kind).get(name)
            sub = path + [name]
            if kind == "groups":
                if cur is None:
                    self._require_all_version_zero(elem, sub)
                else:
                    self._verify_write_structure(cur, elem, sub)
            elif cur is None:
                if elem.version != 0:
                    raise ConfigTxError(
                        f"new {_singular(kind)} {'/'.join(sub)} must have "
                        f"version 0, has {elem.version}")
                if not elem.mod_policy:
                    raise ConfigTxError(
                        f"new {_singular(kind)} {'/'.join(sub)} has an "
                        f"empty mod_policy")
            elif elem.version == cur.version:
                if pu.marshal(elem) != pu.marshal(cur):
                    raise ConfigTxError(
                        f"{_singular(kind)} {'/'.join(sub)} changed "
                        f"without version bump")
            elif elem.version != cur.version + 1:
                raise ConfigTxError(
                    f"{_singular(kind)} {'/'.join(sub)} version "
                    f"{elem.version} invalid (current {cur.version})")
            elif not elem.mod_policy:
                raise ConfigTxError(
                    f"{_singular(kind)} {'/'.join(sub)} is modified but "
                    f"has an empty mod_policy")

    def _check_policy(self, mod_policy: str, path: list[str],
                      signed_data) -> None:
        if not mod_policy:
            raise ConfigTxError(
                f"element at {'/'.join(path)} has empty mod_policy — "
                f"unmodifiable")
        if mod_policy.startswith("/"):
            policy_path = mod_policy
        else:
            policy_path = "/" + "/".join(path + [mod_policy])
        try:
            pol = self._pm.get_policy(policy_path)
        except PolicyError as e:
            raise ConfigTxError(
                f"mod_policy {policy_path!r} cannot be resolved: {e}"
            ) from e
        try:
            pol.evaluate_signed_data(signed_data)
        except PolicyError as e:
            raise ConfigTxError(
                f"signature set does not satisfy mod_policy "
                f"{policy_path!r}: {e}") from e

    def _apply_group(self, current: ctxpb.ConfigGroup,
                     write: ctxpb.ConfigGroup, path: list[str],
                     signed_data, parent_mod_policy: str
                     ) -> ctxpb.ConfigGroup:
        # structure (version windows, mod_policy swaps, new-subtree
        # zeros, same-version immutability) is pre-verified by
        # _verify_write_structure; this pass only evaluates policies
        # and builds the merged group
        modified = write.version == current.version + 1
        if modified:
            self._check_policy(current.mod_policy or parent_mod_policy,
                               path, signed_data)

        out = ctxpb.ConfigGroup()
        out.version = write.version
        out.mod_policy = (write.mod_policy or current.mod_policy) \
            if modified else current.mod_policy

        if modified:
            # membership is exactly the write set's members
            keep = {(k, n) for k, n, _ in _members(write)}
        else:
            keep = None   # merge: unmentioned members are retained

        # start from current members that survive
        for kind, name, elem in _members(current):
            if keep is not None and (kind, name) not in keep:
                continue
            getattr(out, kind)[name].CopyFrom(elem)

        # apply write members
        for kind, name, elem in _members(write):
            cur = getattr(current, kind).get(name)
            sub_path = path + [name]
            if kind == "groups":
                if cur is None:
                    self._check_new_group(elem, sub_path, signed_data,
                                          out.mod_policy)
                    out.groups[name].CopyFrom(elem)
                else:
                    out.groups[name].CopyFrom(self._apply_group(
                        cur, elem, sub_path, signed_data,
                        out.mod_policy))
            else:
                if cur is None:
                    self._check_policy(out.mod_policy, path, signed_data)
                    getattr(out, kind)[name].CopyFrom(elem)
                elif elem.version == cur.version + 1:
                    self._check_policy(cur.mod_policy or out.mod_policy,
                                       path, signed_data)
                    getattr(out, kind)[name].CopyFrom(elem)
                # same version: pre-verified byte-identical — context only
        return out

    def _check_new_group(self, group: ctxpb.ConfigGroup, path: list[str],
                         signed_data, parent_mod_policy: str) -> None:
        self._check_policy(parent_mod_policy, path[:-1], signed_data)

    @staticmethod
    def _require_all_version_zero(group: ctxpb.ConfigGroup,
                                  path: list[str]) -> None:
        """Every element of a brand-new subtree starts at version 0 and
        carries a non-empty mod_policy (reference: validator.go
        verifyDeltaSet + update.go validateModPolicy)."""
        if group.version != 0:
            raise ConfigTxError(
                f"new group {'/'.join(path)} must have version 0")
        if not group.mod_policy:
            raise ConfigTxError(
                f"new group {'/'.join(path)} has an empty mod_policy")
        for kind, name, elem in _members(group):
            sub = path + [name]
            if kind == "groups":
                Validator._require_all_version_zero(elem, sub)
            else:
                if elem.version != 0:
                    raise ConfigTxError(
                        f"new {_singular(kind)} {'/'.join(sub)} must "
                        f"have version 0, has {elem.version}")
                if not elem.mod_policy:
                    raise ConfigTxError(
                        f"new {_singular(kind)} {'/'.join(sub)} has an "
                        f"empty mod_policy")


# ---- client-side delta computation (reference: update.go) ----

def compute_update(channel_id: str, original: ctxpb.Config,
                   updated: ctxpb.Config) -> ctxpb.ConfigUpdate:
    """Compute the ConfigUpdate transforming `original` into `updated`
    (reference: `common/configtx/update.go` Compute). Unchanged members
    of modified groups are carried in the write_set at their current
    version so membership stays exact."""
    read = ctxpb.ConfigGroup()
    write = ctxpb.ConfigGroup()
    changed = _compute_group(original.channel_group,
                             updated.channel_group, read, write)
    if not changed:
        raise ConfigTxError("no differences between configs")
    update = ctxpb.ConfigUpdate(channel_id=channel_id)
    update.read_set.CopyFrom(read)
    update.write_set.CopyFrom(write)
    return update


def _compute_group(orig: ctxpb.ConfigGroup, new: ctxpb.ConfigGroup,
                   read: ctxpb.ConfigGroup,
                   write: ctxpb.ConfigGroup) -> bool:
    """Returns True iff this subtree differs. The group's own version
    bumps only for DIRECT changes (membership, values, policies at this
    level) — a change buried in a subgroup leaves this group at its
    current version as pure context (matching the validator's merge
    rule for unbumped groups)."""
    membership_changed = (
        set(orig.groups) != set(new.groups)
        or set(orig.values) != set(new.values)
        or set(orig.policies) != set(new.policies)
    )
    direct_changed = membership_changed or \
        new.mod_policy != orig.mod_policy
    nested_changed = False

    for kind in ("values", "policies"):
        for name, elem in getattr(new, kind).items():
            cur = getattr(orig, kind).get(name)
            if cur is None:
                target = getattr(write, kind)[name]
                target.CopyFrom(elem)
                target.version = 0
                direct_changed = True
            elif pu.marshal(_strip_version(elem)) != \
                    pu.marshal(_strip_version(cur)):
                target = getattr(write, kind)[name]
                target.CopyFrom(elem)
                target.version = cur.version + 1
                direct_changed = True

    for name, elem in new.groups.items():
        cur = orig.groups.get(name)
        if cur is None:
            write.groups[name].CopyFrom(elem)
            direct_changed = True
            continue
        sub_read = ctxpb.ConfigGroup()
        sub_write = ctxpb.ConfigGroup()
        if _compute_group(cur, elem, sub_read, sub_write):
            nested_changed = True
            read.groups[name].CopyFrom(sub_read)
            write.groups[name].CopyFrom(sub_write)

    read.version = orig.version
    if direct_changed:
        write.version = orig.version + 1
        write.mod_policy = new.mod_policy
        # a bumped group's membership is exact: carry unchanged members
        for kind in ("groups", "values", "policies"):
            for name in getattr(new, kind):
                if name not in getattr(write, kind):
                    getattr(write, kind)[name].CopyFrom(
                        getattr(orig, kind)[name])
    else:
        write.version = orig.version
    return direct_changed or nested_changed


def _strip_version(elem):
    clone = type(elem)()
    clone.CopyFrom(elem)
    clone.version = 0
    return clone
