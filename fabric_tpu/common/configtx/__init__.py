from fabric_tpu.common.configtx.validator import (
    ConfigTxError,
    Validator,
    compute_update,
)

__all__ = ["ConfigTxError", "Validator", "compute_update"]
