"""Leveled, per-logger-configurable logging.

Equivalent of the reference's ``common/flogging`` (zap-based; see reference
``common/flogging/{global,loggerlevels}.go``): named loggers, a runtime
re-parseable *logging spec* of the form ``default-level:logger=level:...``
(e.g. ``info:gossip=debug:ledger.statedb=error``), env var override
``FABRIC_LOGGING_SPEC``, and an ActivateSpec admin hook (the reference exposes
this over HTTP at /logspec — ours is wired in fabric_tpu/operations).

Logger names are dot-separated; a spec entry applies to the named logger and
all its children, longest prefix wins (matches the reference's
``loggerlevels.go`` behavior).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "panic": logging.CRITICAL,
    "fatal": logging.CRITICAL,
}
_LEVEL_NAMES = {
    logging.DEBUG: "DEBU",
    logging.INFO: "INFO",
    logging.WARNING: "WARN",
    logging.ERROR: "ERRO",
    logging.CRITICAL: "FATA",
}
# Canonical spellings for spec() output — every value must parse back
# through _LEVELS so activate_spec(spec()) round-trips.
_CANONICAL = {
    logging.DEBUG: "debug",
    logging.INFO: "info",
    logging.WARNING: "warn",
    logging.ERROR: "error",
    logging.CRITICAL: "fatal",
}


class _Formatter(logging.Formatter):
    """Compact fabric-style line format: time [logger] LEVL message."""

    def format(self, record: logging.LogRecord) -> str:
        t = time.strftime("%H:%M:%S", time.localtime(record.created))
        lvl = _LEVEL_NAMES.get(record.levelno, "INFO")
        msg = record.getMessage()
        if record.exc_info:
            msg += "\n" + self.formatException(record.exc_info)
        return f"{t}.{int(record.msecs):03d} [{record.name}] {lvl} {msg}"


class LoggerLevels:
    """Per-logger level table with longest-prefix matching."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._default = logging.INFO
        self._specs: dict[str, int] = {}

    def activate_spec(self, spec: str) -> None:
        """Parse and apply a logging spec. Invalid entries raise ValueError."""
        default = logging.INFO
        table: dict[str, int] = {}
        for field in (spec or "").split(":"):
            if not field:
                continue
            if "=" in field:
                names, _, lvl = field.rpartition("=")
                level = _parse_level(lvl)
                for name in names.split(","):
                    if not name:
                        raise ValueError(f"invalid logging spec field: {field!r}")
                    table[name] = level
            else:
                default = _parse_level(field)
        with self._lock:
            self._default = default
            self._specs = table
        _reapply_all()

    def spec(self) -> str:
        with self._lock:
            parts = [
                f"{name}={_CANONICAL[lvl]}"
                for name, lvl in sorted(self._specs.items())
            ]
            parts.append(_CANONICAL[self._default])
        return ":".join(parts)

    def level_for(self, name: str) -> int:
        with self._lock:
            best, best_len = self._default, -1
            for prefix, lvl in self._specs.items():
                if name == prefix or name.startswith(prefix + "."):
                    if len(prefix) > best_len:
                        best, best_len = lvl, len(prefix)
            return best


def _parse_level(s: str) -> int:
    try:
        return _LEVELS[s.strip().lower()]
    except KeyError:
        raise ValueError(f"invalid logging level: {s!r}") from None


_levels = LoggerLevels()
_registry: dict[str, logging.Logger] = {}
_registry_lock = threading.Lock()
_handler: logging.Handler | None = None


class _CountingFilter(logging.Filter):
    """logging_entries_written: one count per record the flogging
    handler emits, labeled by level. The companion entries_checked
    counter hooks `Logger.isEnabledFor` (see wire_logging_metrics) so
    it counts every log CALL evaluated against the active level —
    including the suppressed ones — matching the reference's
    check/write observer split."""

    def __init__(self):
        super().__init__()
        self.written = 0
        self._counters: dict | None = None   # levelname -> counter
        self._checked: dict | None = None    # hook-off approximation

    @staticmethod
    def _bump(cs: dict, levelname: str) -> None:
        c = cs.get(levelname)
        if c is None:
            c = cs["_base"].with_labels("level", levelname)
            cs[levelname] = c
        c.add(1)

    def filter(self, record: logging.LogRecord) -> bool:
        self.written += 1
        if self._counters is not None:
            self._bump(self._counters, record.levelname)
        if self._checked is not None:
            self._bump(self._checked, record.levelname)
        return True


_log_counts = _CountingFilter()
_checked_counters: dict | None = None
_checked_patched = False


def wire_logging_metrics(provider, count_checked=None) -> None:
    """Attach a metrics provider to the flogging observer (called by
    node assembly once the operations metrics exist). entries_written
    counts records actually emitted by the flogging handler.

    entries_checked (every log call evaluated against the active
    level, including suppressed ones) needs a process-wide
    `Logger.isEnabledFor` hook that taxes every suppressed debug call
    in hot loops and leaks into third-party loggers — so it is OFF by
    default and opt-in via count_checked=True or
    FABRIC_TPU_LOG_CHECKED_METRIC=1 (round-4 advisor). When off, the
    checked counter still registers (doc parity) and counts emitted
    records only."""
    global _checked_counters, _checked_patched
    from fabric_tpu.common import metrics as _m
    checked = provider.new_counter(_m.CounterOpts(
        namespace="logging", name="entries_checked",
        help="The number of log calls checked against the active "
             "logging level, by level.", label_names=("level",)))
    written = provider.new_counter(_m.CounterOpts(
        namespace="logging", name="entries_written",
        help="The number of log records written out, by level.",
        label_names=("level",)))
    _log_counts._counters = {"_base": written}
    _checked_counters = {"_base": checked}
    if count_checked is None:
        count_checked = os.environ.get(
            "FABRIC_TPU_LOG_CHECKED_METRIC", "0") == "1"
    if not count_checked:
        # cheap approximation without the global hook: a record that
        # reaches the flogging handler was necessarily checked
        _log_counts._checked = _checked_counters
        return
    if not _checked_patched:
        _checked_patched = True
        _orig_is_enabled_for[0] = logging.Logger.isEnabledFor
        _names = {}                      # level int -> cached name

        def counting_is_enabled_for(self, level):
            cs = _checked_counters
            if cs is not None:
                name = _names.get(level)
                if name is None:
                    name = _names[level] = logging.getLevelName(level)
                c = cs.get(name)
                if c is None:
                    c = cs["_base"].with_labels("level", name)
                    cs[name] = c
                c.add(1)
            return _orig_is_enabled_for[0](self, level)

        logging.Logger.isEnabledFor = counting_is_enabled_for


_orig_is_enabled_for: list = [None]


def unwire_checked_hook() -> None:
    """Restore the stock Logger.isEnabledFor (tests/shutdown)."""
    global _checked_patched
    if _checked_patched and _orig_is_enabled_for[0] is not None:
        logging.Logger.isEnabledFor = _orig_is_enabled_for[0]
        _checked_patched = False


def _ensure_handler() -> logging.Handler:
    global _handler
    if _handler is None:
        _handler = logging.StreamHandler(sys.stderr)
        _handler.setFormatter(_Formatter())
        _handler.addFilter(_log_counts)
    return _handler


def must_get_logger(name: str) -> logging.Logger:
    """Return the named logger, registered for spec-driven level control.

    Mirror of the reference's ``flogging.MustGetLogger``.
    """
    with _registry_lock:
        logger = _registry.get(name)
        if logger is None:
            logger = logging.getLogger("fabric." + name)
            logger.propagate = False
            h = _ensure_handler()
            if h not in logger.handlers:
                logger.addHandler(h)
            logger.setLevel(_levels.level_for(name))
            _registry[name] = logger
    return logger


def _reapply_all() -> None:
    with _registry_lock:
        for name, logger in _registry.items():
            logger.setLevel(_levels.level_for(name))


def activate_spec(spec: str) -> None:
    """Apply a logging spec globally (the /logspec admin operation)."""
    _levels.activate_spec(spec)


def spec() -> str:
    return _levels.spec()


# Initialize from the environment, like the reference's flogging init
# (FABRIC_LOGGING_SPEC — reference common/flogging/global.go).
_env_spec = os.environ.get("FABRIC_LOGGING_SPEC", "")
if _env_spec:
    try:
        _levels.activate_spec(_env_spec)
    except ValueError:
        pass
