"""Capability gates — feature flags agreed in channel config.

Rebuild of `common/capabilities/` (`application.go:28-57`,
`channel.go`, `orderer.go`): each level (channel/application/orderer)
declares named capabilities in its config; nodes refuse to process a
channel whose required capabilities they don't implement.
"""

from __future__ import annotations

from fabric_tpu.protos import configtx as ctxpb

# capabilities this implementation understands
CHANNEL_V2_0 = "V2_0"
APPLICATION_V2_0 = "V2_0"
ORDERER_V2_0 = "V2_0"

_SUPPORTED_CHANNEL = {CHANNEL_V2_0}
_SUPPORTED_APPLICATION = {APPLICATION_V2_0}
_SUPPORTED_ORDERER = {ORDERER_V2_0}


class CapabilityError(Exception):
    pass


class _Capabilities:
    def __init__(self, cap_value: ctxpb.Capabilities | None,
                 supported: set[str], level: str):
        self._caps = set(cap_value.capabilities.keys()) if cap_value else set()
        self._supported = supported
        self._level = level

    def declared(self) -> set[str]:
        return set(self._caps)

    def supported(self) -> None:
        """Raise unless every declared capability is implemented
        (reference: `common/capabilities/registry.go` Supported)."""
        missing = self._caps - self._supported
        if missing:
            raise CapabilityError(
                f"{self._level} capabilities {sorted(missing)} are "
                f"required but not supported by this node")


class ChannelCapabilities(_Capabilities):
    def __init__(self, cap_value=None):
        super().__init__(cap_value, _SUPPORTED_CHANNEL, "channel")


class ApplicationCapabilities(_Capabilities):
    def __init__(self, cap_value=None):
        super().__init__(cap_value, _SUPPORTED_APPLICATION, "application")

    def v20_validation(self) -> bool:
        """Gate for the v2 tx-validation/lifecycle path (reference:
        `common/capabilities/application.go:28-57` V2_0Validation)."""
        return APPLICATION_V2_0 in self._caps


class OrdererCapabilities(_Capabilities):
    def __init__(self, cap_value=None):
        super().__init__(cap_value, _SUPPORTED_ORDERER, "orderer")
