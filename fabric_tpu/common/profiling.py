"""Profiling surfaces for the operations endpoint.

Rebuild of the reference's pprof wiring (`cmd/peer/main.go:10` imports
net/http/pprof; served on the operations listener when
`peer.profile.enabled`, `internal/peer/node/start.go:842-850`) —
adapted to this runtime:

  * `sample_profile(seconds)` — a sampling CPU profiler over
    `sys._current_frames()` (the pprof "profile" analog for Python:
    no instrumentation, safe on a live node);
  * `capture_jax_trace(out_dir, seconds)` — a JAX profiler capture
    producing an xplane trace of whatever runs on the devices during
    the window (SURVEY §5: the rebuild adds xplane capture on the
    compute path). View with TensorBoard / xprof.
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import sys
import threading
import time

logger = logging.getLogger("common.profiling")

SAMPLE_HZ = 100

_poller_seq = itertools.count()


def _spawn_poller(name: str, poll_s: float, tick) -> threading.Thread:
    """One daemon poll loop, with a process-unique thread name and a
    deterministic per-poller interval jitter. Every publisher used to
    spawn with the same bare name and the same 5s period, so stacked
    pollers woke in phase — the sampling profiler (/debug/profile)
    read the synchronized sleep stacks as one aliased hot frame, and
    two providers' pollers were indistinguishable in a thread dump.
    The jitter staggers the periods (+3% per poller sequence —
    strictly DISTINCT periods, so no two pollers ever re-align; a
    modulo scheme would hand the 6th poller the 1st one's exact
    period back) and the `-<seq>` suffix makes each poller
    attributable."""
    seq = next(_poller_seq)
    interval = poll_s * (1.0 + 0.03 * seq)

    def loop():
        while True:
            tick()
            time.sleep(interval)

    t = threading.Thread(target=loop, name=f"{name}-{seq}",
                         daemon=True)
    t.start()
    return t


def sample_profile(seconds: float = 5.0, hz: int = SAMPLE_HZ) -> str:
    """Sample every thread's stack for `seconds`; returns a text
    report of the hottest stacks (collapsed, most-sampled first)."""
    interval = 1.0 / hz
    counts: collections.Counter = collections.Counter()
    nsamples = 0
    me = threading.get_ident()
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < 48:
                code = f.f_code
                stack.append(f"{os.path.basename(code.co_filename)}:"
                             f"{f.f_lineno}:{code.co_name}")
                f = f.f_back
            counts["; ".join(reversed(stack))] += 1
        nsamples += 1
        time.sleep(interval)
    lines = [f"# {nsamples} samples over {seconds:.1f}s at {hz} Hz"]
    for stack, n in counts.most_common(40):
        pct = 100.0 * n / max(1, nsamples)
        lines.append(f"{pct:5.1f}%  {n:6d}  {stack}")
    return "\n".join(lines) + "\n"


_trace_lock = threading.Lock()

# bounded /debug/jax/trace output: captures beyond this many are
# pruned oldest-first from the managed parent directory
JAX_TRACE_KEEP = int(os.environ.get("FTPU_JAX_TRACE_KEEP", "5"))


class ProfilerBusyError(RuntimeError):
    """A jax-trace capture is already running. The JAX profiler
    supports one live session per process; a second request must be
    REFUSED immediately (the ops endpoint maps this to 409) — the old
    behavior parked the second HTTP worker on the lock for the whole
    capture window."""


def capture_jax_trace(out_dir: str, seconds: float = 3.0) -> str:
    """Capture a JAX/xplane profiler trace of device activity for
    `seconds`; returns the trace directory. One live session per
    process: a concurrent call raises ProfilerBusyError immediately
    instead of queueing behind the full capture window."""
    import jax

    if not _trace_lock.acquire(blocking=False):
        raise ProfilerBusyError(
            "a jax trace capture is already running; retry after its "
            "window ends")
    try:
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    finally:
        _trace_lock.release()
    return out_dir


def capture_jax_trace_bounded(seconds: float = 3.0,
                              parent_dir: str | None = None,
                              keep: int | None = None) -> str:
    """The ops-endpoint capture: a fresh per-capture directory under
    ONE managed parent, pruned to the newest `keep` captures after
    each run — /debug/jax/trace used to mkdtemp a new orphan
    directory per request, growing tmp without bound. Raises
    ProfilerBusyError like capture_jax_trace."""
    import tempfile

    parent = parent_dir or os.path.join(tempfile.gettempdir(),
                                        "ftpu_jax_trace")
    os.makedirs(parent, exist_ok=True)
    out = tempfile.mkdtemp(prefix="jax_trace_", dir=parent)
    try:
        capture_jax_trace(out, seconds)
    except ProfilerBusyError:
        try:
            os.rmdir(out)           # never leak the unused dir
        except OSError as e:
            logger.debug("could not remove unused trace dir %s: %s",
                         out, e)
        raise
    _prune_trace_dirs(parent, JAX_TRACE_KEEP if keep is None
                      else max(1, int(keep)))
    return out


def _prune_trace_dirs(parent: str, keep: int) -> None:
    """Delete all but the newest `keep` capture directories under
    `parent` (best-effort — a prune failure never fails the capture
    that triggered it)."""
    import shutil

    try:
        entries = [e for e in os.scandir(parent) if e.is_dir()]
    except OSError:
        return
    entries.sort(key=lambda e: e.stat().st_mtime, reverse=True)
    for e in entries[max(1, keep):]:
        shutil.rmtree(e.path, ignore_errors=True)


def publish_provider_stats(metrics_provider, csp, poll_s: float = 5.0):
    """Expose a BCCSP provider's `stats` counters as gauges
    (`bccsp_<name>`), refreshed by a daemon poller — the TPU
    path's perf-cliff counters (comb vs ladder dispatches, sw
    fallbacks, table cache bytes/evictions) become scrapeable instead
    of debugger-only. Returns the poller thread (daemon, running)."""
    from fabric_tpu.common import metrics as metrics_mod

    stats = getattr(csp, "stats", None)
    if not isinstance(stats, dict):
        return None
    # the pipeline stage timers have canonical declarations (help text,
    # gendoc rows) in common/metrics.py; every other stats key gets a
    # generic gauge named after it
    canonical = {
        "pipeline_host_s": metrics_mod.BCCSP_PIPELINE_HOST_SECONDS_OPTS,
        "pipeline_transfer_s":
            metrics_mod.BCCSP_PIPELINE_TRANSFER_SECONDS_OPTS,
        "pipeline_device_s":
            metrics_mod.BCCSP_PIPELINE_DEVICE_SECONDS_OPTS,
        "pipeline_overlap_ratio":
            metrics_mod.BCCSP_PIPELINE_OVERLAP_RATIO_OPTS,
        # sharded-dispatch scalars share their fqnames with the
        # canonical bccsp_shard_* declarations — the generic fallback
        # opts would collide in the registry with different help text
        "shard_devices": metrics_mod.BCCSP_SHARD_DEVICES_OPTS,
        "shard_dispatches": metrics_mod.BCCSP_SHARD_DISPATCHES_OPTS,
        "shard_skew_s": metrics_mod.BCCSP_SHARD_SKEW_SECONDS_OPTS,
        # the scalar quarantine/readmit aggregates share their STATS
        # key with the device-labeled bccsp_device_* series; their
        # canonical *_total names keep the registry fqnames disjoint
        # (the round-13 exclusion left these aggregates unpublished)
        "device_quarantines":
            metrics_mod.BCCSP_DEVICE_QUARANTINES_TOTAL_OPTS,
        "device_readmits":
            metrics_mod.BCCSP_DEVICE_READMITS_TOTAL_OPTS,
        # round-16 compile/cache telemetry (common/devicecost.py):
        # the canonical names operators alert on — cold compiles in
        # steady state are the minutes-long latency cliff
        "compile_total": metrics_mod.BCCSP_COMPILE_TOTAL_OPTS,
        "compile_cache_hits":
            metrics_mod.BCCSP_COMPILE_CACHE_HITS_OPTS,
        "compile_seconds": metrics_mod.BCCSP_COMPILE_SECONDS_OPTS,
        # round-20 fused tier: the serving/demotion counters operators
        # watch to confirm the flagship fused path is the one serving
        "fused_batches": metrics_mod.BCCSP_FUSED_BATCHES_OPTS,
        "fused_lanes": metrics_mod.BCCSP_FUSED_LANES_OPTS,
        "fused_fallbacks": metrics_mod.BCCSP_FUSED_FALLBACKS_OPTS,
        # round-21 pairing engine: serving/demotion counters spanning
        # both device pairing paths (BLS12-381 aggregates, BN254
        # idemix products)
        "pairing_pairs": metrics_mod.BCCSP_PAIRING_PAIRS_OPTS,
        "pairing_batches": metrics_mod.BCCSP_PAIRING_BATCHES_OPTS,
        "pairing_fallbacks": metrics_mod.BCCSP_PAIRING_FALLBACKS_OPTS,
    }
    gauges = {
        name: metrics_provider.new_gauge(canonical.get(
            name, metrics_mod.GaugeOpts(
                namespace="bccsp", name=name,
                help="BCCSP provider runtime counter "
                     "(TPUProvider.stats)"))).with_labels()
        for name in stats
    }
    # the canonical degradation instruments (the names operators
    # alert on): breaker state gauge + trip counter, fed from the
    # provider's breaker rather than the stats dict so they track
    # state changes even between dispatches
    # per-device sharded-dispatch gauges (device label = mesh slot):
    # fed from the provider's shard_stats lists, refreshed per poll
    shard_stats = getattr(csp, "shard_stats", None)
    shard_gauges = None
    if isinstance(shard_stats, dict):
        try:
            shard_gauges = {
                "transfer_s": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_SHARD_TRANSFER_SECONDS_OPTS),
                "ready_s": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_SHARD_READY_SECONDS_OPTS),
                "lanes": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_SHARD_LANES_OPTS),
            }
        except Exception:
            shard_gauges = None
    # per-device health gauges (device label = FULL-mesh index): fed
    # from the provider's device_stats property — read fresh per poll
    # so cooldown-driven state changes (quarantined -> probing) show
    # without a dispatch
    device_stats = getattr(csp, "device_stats", None)
    device_gauges = None
    if isinstance(device_stats, dict):
        try:
            device_gauges = {
                "state": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_DEVICE_STATE_OPTS),
                "trips": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_DEVICE_TRIPS_OPTS),
                "quarantines": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_DEVICE_QUARANTINES_OPTS),
                "readmits": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_DEVICE_READMITS_OPTS),
            }
        except Exception:
            device_gauges = None
    # scheme-router gauges (scheme label = router partition key):
    # fed from the provider's scheme_stats dicts, refreshed per poll
    scheme_stats = getattr(csp, "scheme_stats", None)
    scheme_gauges = None
    if isinstance(scheme_stats, dict):
        try:
            scheme_gauges = {
                "lanes": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_SCHEME_LANES_OPTS),
                "sw_lanes": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_SCHEME_SW_LANES_OPTS),
                "dispatches": metrics_provider.new_gauge(
                    metrics_mod.BCCSP_SCHEME_DISPATCHES_OPTS),
            }
        except Exception:
            scheme_gauges = None
    breaker = getattr(csp, "_breaker", None)
    fallback_state = fallback_trips = None
    if breaker is not None:
        try:
            fallback_state = metrics_provider.new_gauge(
                metrics_mod.BCCSP_FALLBACK_STATE_OPTS).with_labels()
            fallback_trips = metrics_provider.new_counter(
                metrics_mod.BCCSP_FALLBACK_TRIPS_OPTS).with_labels()
        except Exception:
            fallback_state = fallback_trips = None
    # the admission window (bccsp/admission.py) attaches itself to the
    # provider; its convoy wait becomes bccsp_admission_wait_s (the
    # window may appear AFTER this poller starts — re-probed per poll)
    try:
        admission_wait = metrics_provider.new_gauge(
            metrics_mod.BCCSP_ADMISSION_WAIT_SECONDS_OPTS).with_labels()
    except Exception:
        admission_wait = None

    state = {"last_trips": 0}
    warned: set = set()         # once per gauge, not once per poll

    def tick():
        if admission_wait is not None:
            win = getattr(csp, "__ftpu_admission_window__", None)
            if win is not None:
                try:
                    admission_wait.set(float(
                        win.stats.get("window_last_wait_s", 0.0)))
                except Exception as e:
                    if "admission" not in warned:
                        warned.add("admission")
                        logger.warning(
                            "bccsp admission gauge publish failed "
                            "(suppressing repeats): %s", e)
        for name, g in gauges.items():
            try:
                g.set(float(stats.get(name, 0)))
            except Exception as e:
                if name not in warned:
                    warned.add(name)
                    logger.warning("bccsp stats gauge %r publish "
                                   "failed (suppressing repeats): "
                                   "%s", name, e)
        if shard_gauges is not None:
            # re-read per poll: the provider replaces the dict
            # wholesale on each sharded batch
            cur = getattr(csp, "shard_stats", None)
            if isinstance(cur, dict):
                for name, g in shard_gauges.items():
                    try:
                        for d, v in enumerate(cur.get(name) or ()):
                            g.with_labels("device",
                                          str(d)).set(float(v))
                    except Exception as e:
                        if ("shard_" + name) not in warned:
                            warned.add("shard_" + name)
                            logger.warning(
                                "bccsp shard gauge %r publish "
                                "failed (suppressing repeats): %s",
                                name, e)
        if device_gauges is not None:
            cur = getattr(csp, "device_stats", None)
            if isinstance(cur, dict):
                for name, g in device_gauges.items():
                    try:
                        for d, v in enumerate(cur.get(name) or ()):
                            g.with_labels("device",
                                          str(d)).set(float(v))
                    except Exception as e:
                        if ("device_" + name) not in warned:
                            warned.add("device_" + name)
                            logger.warning(
                                "bccsp device gauge %r publish "
                                "failed (suppressing repeats): %s",
                                name, e)
        if scheme_gauges is not None:
            cur = getattr(csp, "scheme_stats", None)
            if isinstance(cur, dict):
                for name, g in scheme_gauges.items():
                    try:
                        for scheme, v in dict(
                                cur.get(name) or {}).items():
                            g.with_labels(
                                "scheme", str(scheme)).set(
                                    float(v))
                    except Exception as e:
                        if ("scheme_" + name) not in warned:
                            warned.add("scheme_" + name)
                            logger.warning(
                                "bccsp scheme gauge %r publish "
                                "failed (suppressing repeats): %s",
                                name, e)
        if fallback_state is not None:
            try:
                fallback_state.set(float(breaker.state_code))
                trips = breaker.stats["trips"]
                if trips > state["last_trips"]:
                    fallback_trips.add(trips - state["last_trips"])
                    state["last_trips"] = trips
            except Exception as e:
                if "breaker" not in warned:
                    warned.add("breaker")
                    logger.warning("bccsp breaker gauge publish "
                                   "failed (suppressing repeats): "
                                   "%s", e)

    return _spawn_poller("bccsp-stats", poll_s, tick)


def publish_overload_stats(metrics_provider, poll_s: float = 5.0):
    """Expose every registered overload stage (common/overload.py:
    shedding queues, the admission window, the write stage, the commit
    pipeline) as the canonical `overload_queue_{depth,capacity,
    max_depth,wait_s}` gauges and the `overload_sheds_total` counter,
    stage-labeled, refreshed by a daemon poller — the round-12
    overload surfaces an operator alerts on (sheds_total growing =
    load past capacity, shed cleanly). Returns the poller thread."""
    from fabric_tpu.common import metrics as metrics_mod
    from fabric_tpu.common import overload

    depth_g = metrics_provider.new_gauge(
        metrics_mod.OVERLOAD_QUEUE_DEPTH_OPTS)
    cap_g = metrics_provider.new_gauge(
        metrics_mod.OVERLOAD_QUEUE_CAPACITY_OPTS)
    max_g = metrics_provider.new_gauge(
        metrics_mod.OVERLOAD_QUEUE_MAX_DEPTH_OPTS)
    wait_g = metrics_provider.new_gauge(
        metrics_mod.OVERLOAD_PUT_WAIT_SECONDS_OPTS)
    sheds_c = metrics_provider.new_counter(
        metrics_mod.OVERLOAD_SHEDS_TOTAL_OPTS)
    rate_g = metrics_provider.new_gauge(
        metrics_mod.OVERLOAD_SHED_RATE_OPTS)

    last_sheds: dict = {}
    warned: set = set()

    def tick():
        for stage, s in overload.stage_stats().items():
            try:
                lbl = ("stage", stage)
                depth_g.with_labels(*lbl).set(
                    float(s.get("depth", 0)))
                cap_g.with_labels(*lbl).set(
                    float(s.get("capacity", 0)))
                if "max_depth" in s:
                    max_g.with_labels(*lbl).set(
                        float(s["max_depth"]))
                if "last_wait_s" in s:
                    wait_g.with_labels(*lbl).set(
                        float(s["last_wait_s"]))
                if "shed_rate" in s:
                    rate_g.with_labels(*lbl).set(
                        float(s["shed_rate"]))
                sheds = int(s.get("sheds", 0))
                if sheds > last_sheds.get(stage, 0):
                    sheds_c.with_labels(*lbl).add(
                        sheds - last_sheds.get(stage, 0))
                    last_sheds[stage] = sheds
            except Exception as e:
                if stage not in warned:
                    warned.add(stage)
                    logger.warning(
                        "overload gauge publish for %r failed "
                        "(suppressing repeats): %s", stage, e)

    return _spawn_poller("overload-stats", poll_s, tick)


def publish_order_stats(metrics_provider, registrar, poll_s: float = 5.0):
    """Expose every raft chain's ordering-pipeline readings as the
    canonical `orderer_batch_{fill,propose_s,consensus_s,write_s,
    overlap_ratio}` gauges (channel-labeled), refreshed by a daemon
    poller — the batched-ordering perf counters (admission-window
    fill, propose/consensus/write stage seconds, write-overlap ratio)
    become scrapeable beside the `bccsp_*` gauges. `registrar` must
    expose `channel_list()` + `get_chain(id)` (whose `.chain` may
    implement `order_pipeline_stats()`; chains that don't — solo,
    followers — are skipped). Returns the poller thread."""
    from fabric_tpu.common import metrics as metrics_mod

    if not hasattr(registrar, "channel_list"):
        return None
    gauges = {
        "fill": metrics_provider.new_gauge(
            metrics_mod.ORDERER_BATCH_FILL_OPTS),
        "propose_s": metrics_provider.new_gauge(
            metrics_mod.ORDERER_BATCH_PROPOSE_SECONDS_OPTS),
        "consensus_s": metrics_provider.new_gauge(
            metrics_mod.ORDERER_BATCH_CONSENSUS_SECONDS_OPTS),
        "write_s": metrics_provider.new_gauge(
            metrics_mod.ORDERER_BATCH_WRITE_SECONDS_OPTS),
        "overlap_ratio": metrics_provider.new_gauge(
            metrics_mod.ORDERER_BATCH_OVERLAP_RATIO_OPTS),
    }

    warned: set = set()         # once per channel, not once per poll

    def tick():
        for cid in registrar.channel_list():
            support = registrar.get_chain(cid)
            stats_fn = getattr(
                getattr(support, "chain", None),
                "order_pipeline_stats", None)
            if stats_fn is None:
                continue
            try:
                stats = stats_fn()
                for name, g in gauges.items():
                    g.with_labels("channel", cid).set(
                        float(stats.get(name, 0)))
            except Exception as e:
                if cid not in warned:
                    warned.add(cid)
                    logger.warning(
                        "orderer batch gauge publish for %r "
                        "failed (suppressing repeats): %s", cid, e)

    return _spawn_poller("orderer-batch-stats", poll_s, tick)


def publish_devicecost_stats(metrics_provider, csp,
                             poll_s: float = 5.0):
    """Expose the round-16 device-cost readings as gauges, refreshed
    by a daemon poller: per-device memory occupancy
    (`bccsp_device_mem_{used,peak,limit}_bytes`, from each device's
    memory_stats — devices without the API publish nothing) and
    per-device busy ratios (`bccsp_device_busy_ratio`, device-time
    over wall-time in the poll window, fed by the provider's
    CompileRecorder.busy accumulator). The compile/cache counters
    themselves ride publish_provider_stats (they live in the
    provider's stats dict). Returns the poller thread, or None when
    the gauges cannot be declared."""
    tick = devicecost_tick(metrics_provider, csp)
    if tick is None:
        return None
    return _spawn_poller("devicecost-stats", poll_s, tick)


def devicecost_tick(metrics_provider, csp):
    """Build the devicecost gauges and return the refresh callable
    (None when the gauges cannot be declared) — split from
    publish_devicecost_stats so tests drive one deterministic tick
    instead of leaking a fast poller that keeps crossing into the
    jax runtime for the rest of the session."""
    from fabric_tpu.common import devicecost as dc
    from fabric_tpu.common import metrics as metrics_mod

    try:
        mem_used = metrics_provider.new_gauge(
            metrics_mod.BCCSP_DEVICE_MEM_USED_BYTES_OPTS)
        mem_peak = metrics_provider.new_gauge(
            metrics_mod.BCCSP_DEVICE_MEM_PEAK_BYTES_OPTS)
        mem_limit = metrics_provider.new_gauge(
            metrics_mod.BCCSP_DEVICE_MEM_LIMIT_BYTES_OPTS)
        busy_g = metrics_provider.new_gauge(
            metrics_mod.BCCSP_DEVICE_BUSY_RATIO_OPTS)
    except Exception:
        logger.warning("devicecost gauges unavailable", exc_info=True)
        return None

    warned: set = set()

    def tick():
        try:
            rows = dc.device_memory()
        except Exception as e:      # noqa: BLE001
            rows = []
            if "mem" not in warned:
                warned.add("mem")
                logger.warning("device memory probe failed "
                               "(suppressing repeats): %s", e)
        for r in rows:
            try:
                lbl = ("device", str(r["device"]))
                mem_used.with_labels(*lbl).set(
                    float(r["bytes_in_use"]))
                mem_peak.with_labels(*lbl).set(
                    float(r["peak_bytes_in_use"]))
                mem_limit.with_labels(*lbl).set(
                    float(r["bytes_limit"]))
            except Exception as e:  # noqa: BLE001
                if "mem_gauge" not in warned:
                    warned.add("mem_gauge")
                    logger.warning("device memory gauge publish "
                                   "failed (suppressing repeats): "
                                   "%s", e)
        rec = getattr(csp, "device_cost", None)
        if rec is not None:
            try:
                for d, ratio in rec.busy.ratios().items():
                    busy_g.with_labels("device", str(d)).set(
                        float(ratio))
            except Exception as e:  # noqa: BLE001
                if "busy" not in warned:
                    warned.add("busy")
                    logger.warning("device busy-ratio publish failed "
                                   "(suppressing repeats): %s", e)

    return tick
