"""Viper-style config loading: YAML file + env-var overrides.

Equivalent of the reference's viper usage plus ``common/viperutil``
(enhanced unmarshal): nested YAML trees addressed by dotted, case-insensitive
paths; environment overrides of the form ``<PREFIX>_SECTION_SUBKEY=value``
(reference: ``CORE_*`` for the peer — ``cmd/peer/main.go:33-36`` — and
``ORDERER_*`` for the orderer); duration strings ("5s", "250ms"); byte-size
ints; and relative-path resolution against the config file's directory.
"""

from __future__ import annotations

import os
import re
from typing import Any

import yaml

_DURATION_UNITS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
}


def parse_duration(value: Any) -> float:
    """Parse a Go-style duration string (possibly composite, '1m30s') to seconds."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    total, pos = 0.0, 0
    for m in re.finditer(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)", s):
        if m.start() != pos:
            raise ValueError(f"invalid duration: {value!r}")
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise ValueError(f"invalid duration: {value!r}")
    return total


class Config:
    """A loaded config tree with env overrides, addressed by dotted path."""

    def __init__(self, tree: dict | None = None, env_prefix: str = "",
                 config_dir: str = ""):
        self._tree = tree or {}
        self._env_prefix = env_prefix
        self.config_dir = config_dir

    @classmethod
    def load(cls, path: str, env_prefix: str = "") -> "Config":
        with open(path) as f:
            tree = yaml.safe_load(f) or {}
        return cls(tree, env_prefix, os.path.dirname(os.path.abspath(path)))

    def _env_lookup(self, dotted: str) -> str | None:
        if not self._env_prefix:
            return None
        key = self._env_prefix + "_" + dotted.upper().replace(".", "_")
        return os.environ.get(key)

    def get(self, dotted: str, default: Any = None) -> Any:
        env = self._env_lookup(dotted)
        if env is not None:
            return _coerce(env)
        node: Any = self._tree
        for part in dotted.split("."):
            if not isinstance(node, dict):
                return default
            found = None
            for k in node:
                if str(k).lower() == part.lower():
                    found = node[k]
                    break
            else:
                return default
            node = found
        return node if node is not None else default

    def get_bool(self, dotted: str, default: bool = False) -> bool:
        v = self.get(dotted, default)
        if isinstance(v, str):
            return v.strip().lower() in ("1", "true", "yes", "on")
        return bool(v)

    def get_int(self, dotted: str, default: int = 0) -> int:
        v = self.get(dotted, default)
        return int(v)

    def get_duration(self, dotted: str, default: float = 0.0) -> float:
        v = self.get(dotted, None)
        if v is None:
            return default
        return parse_duration(v)

    def resolve_path(self, value: str) -> str:
        """Resolve a possibly-relative path value against the config
        file's dir (reference viperutil path translation)."""
        v = str(value)
        if os.path.isabs(v):
            return v
        return os.path.join(self.config_dir, v)

    def get_path(self, dotted: str, default: str = "") -> str:
        v = self.get(dotted, default)
        if not v:
            return default
        return self.resolve_path(v)

    def sub(self, dotted: str) -> "Config":
        node = self.get(dotted, {})
        prefix = (
            self._env_prefix + "_" + dotted.upper().replace(".", "_")
            if self._env_prefix else ""
        )
        sub = Config(node if isinstance(node, dict) else {}, prefix, self.config_dir)
        return sub


def _coerce(s: str) -> Any:
    low = s.strip().lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s
