"""Circuit breaker for liveness-critical accelerator dispatch.

`BCCSP.Default: TPU` promises bit-identical accept/reject with the sw
provider as the ONLY observable difference being speed — which means a
flaky, stalled, or absent accelerator must degrade to the host path,
never wedge the peer/orderer or change verdicts. FPGA verify engines
ship a CPU fallback for the same reason (arXiv:2112.02229); committee-
consensus deployments treat verification as liveness-critical
(arXiv:2302.00418).

States (the strings surfaced on /healthz and the breaker_state gauge):

    device    (closed)    dispatches go to the accelerator
    degraded  (open)      every dispatch is refused; callers serve the
                          bit-identical sw path; entered after
                          `trip_threshold` consecutive device failures
    probing   (half-open) cooldown elapsed: ONE probe dispatch is
                          admitted; success re-admits the device,
                          failure re-opens for another cooldown

A `deadline_ms` guard runs the dispatch on a watchdog thread: a stalled
device (wedged PCIe/tunnel, a compile that never returns) counts as a
failure after the deadline instead of blocking validation forever. The
abandoned call keeps running on its daemon thread and its eventual
result is discarded. One thread is spawned per guarded dispatch —
dispatches are BLOCK-granular (tens per second, not per-signature), so
the churn is noise next to the dispatch itself, and deadline_ms=0 (the
default) spawns none; revisit with a worker pool only if profiles ever
say otherwise.

Error classification: any Exception counts as a device failure except
types listed in `BreakerConfig.ignore` (caller bugs — e.g. TypeError
from malformed arguments — should surface, not trip the breaker).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger("common.breaker")

DEVICE, DEGRADED, PROBING = "device", "degraded", "probing"

_STATE_CODES = {DEVICE: 0, PROBING: 1, DEGRADED: 2}


class CircuitOpen(RuntimeError):
    """Dispatch refused: the breaker is open (or the probe slot is
    taken). The caller serves its host fallback."""


class DeadlineExceeded(RuntimeError):
    """The guarded call outlived `deadline_ms`."""


@dataclass
class BreakerConfig:
    """`BCCSP.TPU.Fallback` in core.yaml (parsed by bccsp/factory.py)."""
    deadline_ms: float = 0.0      # 0 = no watchdog
    trip_threshold: int = 5       # consecutive failures before opening
    cooldown_s: float = 30.0      # open -> probing after this long
    probe_batch: int = 1024       # max lanes risked on a probe dispatch
    ignore: tuple = field(default_factory=tuple)  # exception types that
    #                                               never count


class CircuitBreaker:
    def __init__(self, config: BreakerConfig | None = None,
                 name: str = "tpu", clock=time.monotonic):
        self.config = config or BreakerConfig()
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = DEVICE
        self._failures = 0           # consecutive
        self._open_until = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        self._guards_inflight = 0    # guarded executions running now
        self.stats = {"trips": 0, "probes": 0, "deadline_timeouts": 0,
                      "failures": 0, "rejected": 0, "stale_probes": 0}

    # -- state --

    @property
    def state(self) -> str:
        """Current state; resolves cooldown expiry (degraded →
        probing) at observation time."""
        with self._lock:
            return self._state_locked()

    def _probe_timeout_s(self) -> float:
        return max(self.config.cooldown_s,
                   2 * self.config.deadline_ms / 1000.0, 1.0)

    def _state_locked(self) -> str:
        now = self._clock()
        if self._state == DEGRADED and now >= self._open_until:
            self._state = PROBING
            self._probe_inflight = False
            logger.info("%s breaker cooldown elapsed; probing the "
                        "device", self.name)
        elif self._state == PROBING and self._probe_inflight and \
                self._guards_inflight == 0 and \
                now - self._probe_started >= self._probe_timeout_s():
            # the probe's outcome was never reported (a caller dropped
            # its resolver): reclaim the slot by treating it as a
            # failed probe, otherwise the breaker wedges in `probing`
            # with the device benched forever. A probe still EXECUTING
            # inside guard() — e.g. paying a long first-dispatch
            # compile with no deadline configured — is not stale and
            # keeps the slot.
            self.stats["stale_probes"] += 1
            self._state = DEGRADED
            self._open_until = now + self.config.cooldown_s
            self._probe_inflight = False
            logger.warning(
                "%s breaker: probe outcome never reported after "
                "%.1fs; re-opening for %.1fs", self.name,
                self._probe_timeout_s(), self.config.cooldown_s)
        return self._state

    @property
    def state_code(self) -> int:
        return _STATE_CODES[self.state]

    # -- accounting --

    def admit(self) -> bool:
        """Raise CircuitOpen unless a dispatch may be tried now.
        Returns True when this dispatch IS the probe (the single
        half-open slot was acquired — released by the following
        success()/failure()), False for a normal closed-state
        dispatch. The probe decision is made HERE, atomically with the
        state resolution, so callers can bound the probe's size
        without racing the cooldown clock."""
        with self._lock:
            st = self._state_locked()
            if st == DEVICE:
                return False
            if st == PROBING and not self._probe_inflight:
                self._probe_inflight = True
                self._probe_started = self._clock()
                self.stats["probes"] += 1
                return True
            self.stats["rejected"] += 1
        raise CircuitOpen(f"{self.name} breaker {st}")

    def success(self) -> None:
        with self._lock:
            st = self._state_locked()
            if st == DEGRADED:
                # a stale in-flight dispatch (admitted before the
                # trip) resolving now must not force-close an OPEN
                # breaker — re-entry goes through cooldown + a bounded
                # probe, not through a straggler's luck
                return
            if st != DEVICE:
                logger.info("%s breaker: probe succeeded; device "
                            "re-admitted", self.name)
            self._state = DEVICE
            self._failures = 0
            self._probe_inflight = False

    def failure(self, exc: BaseException | None = None) -> None:
        if exc is not None and isinstance(exc, self.config.ignore):
            with self._lock:
                # the error doesn't count against the device, but a
                # held probe slot must not leak
                self._probe_inflight = False
            return
        tripped = 0
        with self._lock:
            self.stats["failures"] += 1
            st = self._state_locked()
            self._failures += 1
            if st == PROBING or \
                    self._failures >= self.config.trip_threshold:
                if st != DEGRADED:
                    self.stats["trips"] += 1
                    tripped = self._failures
                    logger.warning(
                        "%s breaker OPEN after %d consecutive device "
                        "failure(s) (%s); serving the sw path for "
                        "%.1fs", self.name, self._failures,
                        type(exc).__name__ if exc else "failure",
                        self.config.cooldown_s)
                self._state = DEGRADED
                self._open_until = (self._clock()
                                    + self.config.cooldown_s)
                self._probe_inflight = False
        if tripped:
            # flight-recorder landmark + automatic postmortem dump
            # (rate-limited, never raises) — OUTSIDE the breaker lock:
            # the dump does file I/O
            from fabric_tpu.common import tracing
            tracing.note_breaker_trip(self.name, failures=tripped)

    # -- guarded execution --

    @contextlib.contextmanager
    def execution(self):
        """Mark a device execution as live WITHOUT recording an
        outcome — for work done between admit() and a later guarded
        resolve (the prepared path's staging/compile window), so the
        stale-probe reclaim doesn't preempt it. A probe whose resolver
        is merely HELD (not executing) past the probe timeout is still
        treated as dropped; a late success()/failure() then
        self-corrects the state."""
        with self._lock:
            self._guards_inflight += 1
        try:
            yield
        finally:
            with self._lock:
                self._guards_inflight -= 1

    def guard(self, fn):
        """Run `fn()` under the deadline watchdog and record the
        outcome. No admission check — see run()."""
        deadline_s = self.config.deadline_ms / 1000.0
        # while a guarded execution runs, the probe slot is live (not
        # stale-reclaimable): a slow probe paying a first-dispatch
        # compile with no deadline configured must not be preempted
        with self._lock:
            self._guards_inflight += 1
        try:
            try:
                if deadline_s > 0:
                    box: dict = {}
                    done = threading.Event()

                    def work():
                        try:
                            box["result"] = fn()
                        except BaseException as e:  # noqa: BLE001
                            box["error"] = e
                        finally:
                            done.set()

                    t = threading.Thread(
                        target=work, daemon=True,
                        name=f"{self.name}-breaker-dispatch")
                    t.start()
                    if not done.wait(deadline_s):
                        self.stats["deadline_timeouts"] += 1
                        exc = DeadlineExceeded(
                            f"{self.name} dispatch exceeded "
                            f"{self.config.deadline_ms:.0f}ms deadline")
                        self.failure(exc)
                        raise exc
                    if "error" in box:
                        raise box["error"]
                    result = box["result"]
                else:
                    result = fn()
            except DeadlineExceeded:
                raise
            except Exception as e:
                self.failure(e)
                raise
            self.success()
            return result
        finally:
            with self._lock:
                self._guards_inflight -= 1

    def run(self, fn):
        """Admission + guarded execution: raises CircuitOpen when the
        device must not be tried, otherwise runs fn() under the
        deadline and records the outcome."""
        self.admit()
        return self.guard(fn)
