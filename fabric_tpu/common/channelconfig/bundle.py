"""Channel config Bundle: one immutable parse of a channel's Config.

Rebuild of `common/channelconfig/` (`bundle.go:182` NewBundle,
`channel.go`, `application.go`, `orderer.go`): given the channel's
`Config` tree, build — once — the MSP manager for all orgs, the policy
manager tree (signature + implicit-meta policies at every level), and
typed views over the standard config values. Everything downstream
(endorser, validator, orderer, gossip) reads THIS object; a config
block replaces the bundle wholesale (no mutation).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from fabric_tpu.common import capabilities as caps
from fabric_tpu.common.policies import (
    ImplicitMetaPolicy,
    Manager,
    SignaturePolicy,
)
from fabric_tpu.msp import CachedMSP, Manager as MSPManager, X509MSP
from fabric_tpu.protos import configtx as ctxpb, msp as msppb
from fabric_tpu.protos import policies as polpb

# canonical group names (reference: channelconfig consts)
APPLICATION = "Application"
ORDERER = "Orderer"
CONSORTIUMS = "Consortiums"

MSP_KEY = "MSP"
CAPABILITIES_KEY = "Capabilities"
HASHING_ALGORITHM_KEY = "HashingAlgorithm"
BLOCK_HASHING_KEY = "BlockDataHashingStructure"
ORDERER_ADDRESSES_KEY = "OrdererAddresses"
CONSORTIUM_KEY = "Consortium"
BATCH_SIZE_KEY = "BatchSize"
BATCH_TIMEOUT_KEY = "BatchTimeout"
CONSENSUS_TYPE_KEY = "ConsensusType"
CHANNEL_RESTRICTIONS_KEY = "ChannelRestrictions"
ANCHOR_PEERS_KEY = "AnchorPeers"
ACLS_KEY = "ACLs"
ENDPOINTS_KEY = "Endpoints"


class ConfigError(Exception):
    pass


def _value(group: ctxpb.ConfigGroup, key: str, msg_type):
    cv = group.values.get(key)
    if cv is None:
        return None
    out = msg_type()
    out.ParseFromString(cv.value)
    return out


@dataclass
class ApplicationOrg:
    name: str
    mspid: str
    anchor_peers: list = field(default_factory=list)


@dataclass
class OrdererOrg:
    name: str
    mspid: str
    endpoints: list = field(default_factory=list)


@dataclass
class ApplicationConfig:
    orgs: dict[str, ApplicationOrg]
    capabilities: caps.ApplicationCapabilities
    acls: dict[str, str]


@dataclass
class OrdererConfig:
    orgs: dict[str, OrdererOrg]
    consensus_type: str
    consensus_metadata: bytes
    consensus_state: int
    batch_size: ctxpb.BatchSize
    batch_timeout_s: float
    max_channels: int
    capabilities: caps.OrdererCapabilities


@dataclass
class ChannelConfig:
    hashing_algorithm: str
    orderer_addresses: list[str]
    capabilities: caps.ChannelCapabilities
    consortium: str


class Bundle:
    """Reference: `common/channelconfig/bundle.go:182` NewBundle(channel
    id, config, bccsp) — takes the crypto provider explicitly, like the
    reference, so MSPs verify through the batched path."""

    def __init__(self, channel_id: str, config: ctxpb.Config, csp):
        self.channel_id = channel_id
        self.config = config
        self.csp = csp
        root = config.channel_group
        self._msps: list = []
        self._mspid_by_org: dict[str, str] = {}

        self.channel = self._parse_channel(root)
        self.application: Optional[ApplicationConfig] = None
        self.orderer: Optional[OrdererConfig] = None
        app_group = root.groups.get(APPLICATION)
        ord_group = root.groups.get(ORDERER)

        # MSPs first: policies reference principals by mspid
        for section in (app_group, ord_group):
            if section is None:
                continue
            for org_name, org_group in section.groups.items():
                self._load_msp(org_group, org_name)
        self.msp_manager = MSPManager()
        self.msp_manager.setup(self._msps)

        # policy managers bottom-up (orgs -> section -> channel)
        subs: dict[str, Manager] = {}
        for section_name, section in ((APPLICATION, app_group),
                                      (ORDERER, ord_group)):
            if section is None:
                continue
            org_mgrs = {}
            for org_name, org_group in section.groups.items():
                org_mgrs[org_name] = Manager(
                    name=org_name,
                    policies=self._compile_policies(org_group, []))
            section_policies = self._compile_policies(
                section, list(org_mgrs.values()))
            subs[section_name] = Manager(name=section_name,
                                         policies=section_policies,
                                         sub_managers=org_mgrs)
        channel_policies = self._compile_policies(
            root, list(subs.values()))
        self.policy_manager = Manager(name="Channel",
                                      policies=channel_policies,
                                      sub_managers=subs)

        if app_group is not None:
            self.application = self._parse_application(app_group)
        if ord_group is not None:
            self.orderer = self._parse_orderer(ord_group)

        # refuse to run with capabilities we don't implement
        self.channel.capabilities.supported()
        if self.application:
            self.application.capabilities.supported()
        if self.orderer:
            self.orderer.capabilities.supported()

    # -- sections --

    def _parse_channel(self, root: ctxpb.ConfigGroup) -> ChannelConfig:
        ha = _value(root, HASHING_ALGORITHM_KEY, ctxpb.HashingAlgorithm)
        if ha is not None and ha.name not in ("", "SHA256"):
            raise ConfigError(f"unsupported hashing algorithm {ha.name!r}")
        addrs = _value(root, ORDERER_ADDRESSES_KEY, ctxpb.OrdererAddresses)
        cap = _value(root, CAPABILITIES_KEY, ctxpb.Capabilities)
        consortium = _value(root, CONSORTIUM_KEY, ctxpb.Consortium)
        return ChannelConfig(
            hashing_algorithm=(ha.name if ha and ha.name else "SHA256"),
            orderer_addresses=list(addrs.addresses) if addrs else [],
            capabilities=caps.ChannelCapabilities(cap),
            consortium=consortium.name if consortium else "",
        )

    def _parse_application(self, group) -> ApplicationConfig:
        orgs = {}
        for name, og in group.groups.items():
            anchors = _value(og, ANCHOR_PEERS_KEY, ctxpb.AnchorPeers)
            orgs[name] = ApplicationOrg(
                name=name, mspid=self._mspid_by_org[name],
                anchor_peers=[(a.host, a.port) for a in
                              anchors.anchor_peers] if anchors else [])
        acls = _value(group, ACLS_KEY, ctxpb.ACLs)
        cap = _value(group, CAPABILITIES_KEY, ctxpb.Capabilities)
        return ApplicationConfig(
            orgs=orgs,
            capabilities=caps.ApplicationCapabilities(cap),
            acls=dict(acls.acls) if acls else {},
        )

    def _parse_orderer(self, group) -> OrdererConfig:
        orgs = {}
        for name, og in group.groups.items():
            endpoints = _value(og, ENDPOINTS_KEY, ctxpb.OrdererAddresses)
            orgs[name] = OrdererOrg(
                name=name, mspid=self._mspid_by_org[name],
                endpoints=list(endpoints.addresses) if endpoints else [])
        ct = _value(group, CONSENSUS_TYPE_KEY, ctxpb.ConsensusType)
        if ct is None:
            raise ConfigError("Orderer group lacks ConsensusType")
        bs = _value(group, BATCH_SIZE_KEY, ctxpb.BatchSize)
        bt = _value(group, BATCH_TIMEOUT_KEY, ctxpb.BatchTimeout)
        cr = _value(group, CHANNEL_RESTRICTIONS_KEY,
                    ctxpb.ChannelRestrictions)
        cap = _value(group, CAPABILITIES_KEY, ctxpb.Capabilities)
        from fabric_tpu.common.viperutil import parse_duration
        return OrdererConfig(
            orgs=orgs,
            consensus_type=ct.type,
            consensus_metadata=bytes(ct.metadata),
            consensus_state=ct.state,
            batch_size=bs or ctxpb.BatchSize(
                max_message_count=500,
                absolute_max_bytes=10 * 1024 * 1024,
                preferred_max_bytes=2 * 1024 * 1024),
            batch_timeout_s=parse_duration(bt.timeout) if bt and bt.timeout
            else 2.0,
            max_channels=cr.max_count if cr else 0,
            capabilities=caps.OrdererCapabilities(cap),
        )

    # -- msp / policy plumbing --

    def _load_msp(self, org_group, org_name: str) -> None:
        msp_value = _value(org_group, MSP_KEY, ctxpb.MSPValue)
        if msp_value is None:
            raise ConfigError(f"org {org_name!r} lacks MSP value")
        mc = msppb.MSPConfig()
        mc.ParseFromString(msp_value.config)
        if mc.type == 1:
            from fabric_tpu.msp.idemix import IdemixMSP
            msp = IdemixMSP(self.csp)
        else:
            msp = X509MSP(self.csp)
        msp.setup(mc)
        self._msps.append(CachedMSP(msp))
        self._mspid_by_org[org_name] = msp.identifier()

    def _compile_policies(self, group: ctxpb.ConfigGroup,
                          child_managers: list[Manager]) -> dict:
        out = {}
        for name, cp in group.policies.items():
            pol = cp.policy
            if pol.type == polpb.Policy.SIGNATURE:
                out[name] = SignaturePolicy.from_bytes(
                    pol.value, self._deserializer_proxy(), self.csp)
            elif pol.type == polpb.Policy.IMPLICIT_META:
                meta = polpb.ImplicitMetaPolicy()
                meta.ParseFromString(pol.value)
                out[name] = ImplicitMetaPolicy.from_managers(
                    meta, child_managers,
                    converter=(self._deserializer_proxy(), self.csp))
            else:
                raise ConfigError(
                    f"policy {name!r} has unknown type {pol.type}")
        return out

    def _deserializer_proxy(self):
        """Policies are compiled before the MSP manager is final; the
        proxy defers the lookup to evaluation time."""
        bundle = self

        class _Proxy:
            def deserialize_identity(self, serialized):
                return bundle.msp_manager.deserialize_identity(serialized)
        return _Proxy()
