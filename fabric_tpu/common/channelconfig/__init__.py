from fabric_tpu.common.channelconfig.bundle import (
    ApplicationConfig,
    ApplicationOrg,
    Bundle,
    ChannelConfig,
    ConfigError,
    OrdererConfig,
    OrdererOrg,
)

__all__ = [
    "ApplicationConfig", "ApplicationOrg", "Bundle", "ChannelConfig",
    "ConfigError", "OrdererConfig", "OrdererOrg",
]
