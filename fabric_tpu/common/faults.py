"""Process-wide fault-injection registry for chaos testing.

Liveness-critical paths carry NAMED fault points — hooks that are
no-ops in production (an unarmed `check()` is one dict lookup) but can
be armed by tests and chaos runs to raise, stall, or fail-N-times.
The well-known points:

    tpu.dispatch       every device batch dispatch (bccsp/tpu.py)
    tpu.compile        jit pipeline builds / AOT compiles
    tpu.table_persist  warm-table byte writers
    tpu.fused_verify   the round-20 fused Pallas dispatch (device
                       SHA-256 + comb in one program) — a fault
                       demotes the batch to the host-hash
                       comb-digest path, bit-identical verdicts
                       (bccsp/tpu.py _dispatch_fused_verify)
    tpu.ed25519        the scheme router's Ed25519 device dispatch —
                       a fault serves the sub-batch on the host
                       reference path, bit-identical (bccsp/tpu.py)
    tpu.bls_aggregate  the staged BLS aggregate-verify path — a fault
                       serves the host reference pairing product
                       (bccsp/tpu.py verify_aggregate)
    tpu.device_lost    per-device point inside the sharded span feeder
                       and the quarantine probe (bccsp/tpu.py
                       _shard_put/_probe_device): checked with
                       arg=<full-mesh device index>, so chaos targets
                       chip k — an error there quarantines THAT chip
                       and the provider rebuilds a smaller mesh over
                       the survivors (common/devicehealth.py)
    tpu.device_straggler
                       same per-device seam, delay mode: the targeted
                       chip's transfer stream stalls, feeding the
                       straggler accounting that quarantines a chip
                       pacing the whole mesh (bccsp/tpu.py)
    raft.step          inbound raft messages (orderer raft chain loop)
    raft.wal_append    the raft WAL append seam (orderer/raft/
                       storage.py) — error mode drops the batch (the
                       chain demotes/retries), crash mode is the
                       crash-matrix kill point BEFORE the durable
                       write
    order.propose      the batched propose span of the ordering
                       admission window — a fault demotes the window
                       to per-block sequential proposes
                       (orderer/raft/chain.py)
    order.block_write  the block-write worker's span write
                       (orderer/raft/pipeline.py) — error mode is a
                       sticky stage failure (the chain demotes and
                       replays from the WAL), crash mode kills the
                       consenter between raft commit and the durable
                       block append
    net.drop           one matched message dropped by the network-
                       chaos layer (common/netchaos.py) — the arg
                       targets a link: an endpoint (either side),
                       `a>b` (directed) or `a|b|c` (either side in
                       the set)
    net.delay          one matched message held back delay_s seconds
                       (scheduled — the sender never blocks); arm
                       with mode `delay`
    net.dup            one matched message delivered twice
    net.reorder        one matched message held until <delay-field>
                       (default 4) later messages on its link passed
                       it — bounded reordering
    net.partition      installs a partition once per fire: the arg
                       names the cut group (`node2|node3` isolates
                       exactly that set from everyone else, both
                       directions); the delay field, when set, heals
                       it that many seconds later. Effects are
                       applied by any live NetChaos engine at its
                       next transport activity.
    deliver.stream     the peer's block-deliver stream
    cluster.pull       onboarding/catch-up block pulls from consenters
    cluster.verify     pulled-span verification (orderer/onboarding.py)
    onboarding.commit  committing a verified pulled block
    commit.validate_ahead  stage A of the commit pipeline — a fault
                       demotes the block to the sequential path
                       (core/commitpipeline.py)
    commit.barrier     the pipeline's drain-before-validate barrier
                       (config blocks, validation-parameter updates)

A new subsystem adds a `check()` call AND declares the point in
`KNOWN_POINTS` below — the canonical registry `tools/ftpu_lint.py`
checks every call-site literal against, and `arm()` warns on unknown
names so a typo'd FTPU_FAULTS entry is loud instead of inert (the
chaos suite would otherwise pass vacuously). Arbitrary names still
ARM (tests of the registry itself use made-up points); they just
warn.

Arming:
  - code:  `faults.arm("tpu.dispatch", mode="error", count=3)`
  - env:   FTPU_FAULTS="tpu.dispatch=error:3;deliver.stream=delay::0.2"
           parsed at import and re-applied by `reset()`, so a chaos CI
           pass (tools/chaos_check.sh) arms a whole pytest run while
           each test still starts from the same armed baseline.

Spec grammar: `point=mode[:count][:delay_s][:arg]`, `mode` in
{error, delay, crash}; empty count = unlimited. A `delay` fault sleeps
then proceeds (a stall, for deadline/breaker testing); an `error`
fault raises FaultInjected; a `crash` fault hard-kills the process
(`os._exit(137)`) at the k-th check, where k is the delay field
(`raft.wal_append=crash:1:3` dies at the 3rd WAL append) — the
crash-point recovery matrix arms these in subprocess children and
asserts bit-identical replay after restart. The optional 4th field
targets an ARGUMENT: the fault fires only when the call site's
`check(point, arg=...)` matches it (the per-device points pass the
full-mesh device index, so `tpu.device_lost=error:1::3` kills exactly
chip 3); a check without an arg never matches an arg-targeted arming.
Everything after the 3rd `:` is the arg verbatim, so endpoint args may
contain colons (`net.drop=error:5::orderer0.example.com:7050`).

Counts are consumed per fire; `fires(point)` reports how often a point
actually fired (armed or not, a check on an unarmed point counts
nothing — firing means the fault acted). Subsystems that implement a
fault's EFFECT themselves (the net.* points: common/netchaos.py turns
them into drops/delays/duplicates/reorders/partitions on its delivery
schedule) read the arming with `arming(point)` and book the fire with
`consume(point, arg=)` instead of `check()` — same count/fires
accounting, no raise, no sleep.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger("common.faults")

ENV_VAR = "FTPU_FAULTS"


class FaultInjected(RuntimeError):
    """Raised by an armed `error` fault point."""


# The canonical fault-point registry: every `faults.check("...")`
# call-site literal in the tree must appear here (enforced by
# tools/ftpu_lint.py's fault-point rule), and `arm()` warns when an
# unknown name is armed. Keep the docstring table above in sync.
KNOWN_POINTS = frozenset({
    "tpu.dispatch",
    "tpu.compile",
    "tpu.fused_verify",
    "tpu.table_persist",
    "tpu.ed25519",
    "tpu.bls_aggregate",
    "tpu.device_lost",
    "tpu.device_straggler",
    "raft.step",
    "raft.wal_append",
    "order.propose",
    "order.block_write",
    "net.drop",
    "net.delay",
    "net.dup",
    "net.reorder",
    "net.partition",
    "deliver.stream",
    "cluster.pull",
    "cluster.verify",
    "onboarding.commit",
    "commit.validate_ahead",
    "commit.barrier",
})


@dataclass
class _Arming:
    mode: str                      # "error" | "delay" | "crash"
    count: Optional[int] = None    # remaining fires; None = unlimited
    delay_s: float = 0.0
    message: str = ""
    arg: Optional[str] = None      # fire only when check(arg=) matches
    skip: int = 0                  # crash mode: checks left before dying

    def snapshot(self) -> dict:
        return {"mode": self.mode, "count": self.count,
                "delay_s": self.delay_s, "arg": self.arg,
                "message": self.message}


class FaultRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, _Arming] = {}
        self._fires: dict[str, int] = {}

    # -- arming --

    def arm(self, point: str, mode: str = "error",
            count: Optional[int] = None, delay_s: float = 0.0,
            message: str = "", arg=None) -> None:
        if mode not in ("error", "delay", "crash"):
            raise ValueError(f"unknown fault mode {mode!r}")
        if point not in KNOWN_POINTS:
            logger.warning(
                "arming UNKNOWN fault point %r — no check() site "
                "declares it in KNOWN_POINTS (common/faults.py); a "
                "typo'd %s entry injects nothing", point, ENV_VAR)
        with self._lock:
            self._armed[point] = _Arming(
                mode=mode, count=count, delay_s=delay_s,
                message=message,
                arg=None if arg is None else str(arg),
                # crash mode: the delay field selects WHICH check dies
                # (k-th, 1-based; 0/1 = the first one)
                skip=max(0, int(delay_s) - 1) if mode == "crash"
                else 0)
        logger.info("fault point %s armed: mode=%s count=%s "
                    "delay=%.3fs arg=%s", point, mode, count, delay_s,
                    arg)

    def disarm(self, point: str) -> None:
        with self._lock:
            self._armed.pop(point, None)

    def clear(self) -> None:
        """Disarm everything, including env-armed faults."""
        with self._lock:
            self._armed.clear()
            self._fires.clear()

    def reset(self) -> None:
        """Back to the process baseline: clear, then re-apply the
        FTPU_FAULTS env arming (per-test isolation for chaos runs)."""
        self.clear()
        self.arm_from_env()

    def arm_from_env(self, spec: Optional[str] = None) -> None:
        spec = os.environ.get(ENV_VAR, "") if spec is None else spec
        if not spec:
            return
        for part in spec.replace(",", ";").split(";"):
            part = part.strip()
            if not part:
                continue
            try:
                point, _, rhs = part.partition("=")
                fields = rhs.split(":")
                mode = fields[0] or "error"
                count = (int(fields[1])
                         if len(fields) > 1 and fields[1] else None)
                delay = (float(fields[2])
                         if len(fields) > 2 and fields[2] else 0.0)
                # everything past the 3rd ':' is the arg verbatim —
                # endpoint args ("host:port") may contain colons
                arg = (":".join(fields[3:])
                       if len(fields) > 3 and fields[3] else None)
                self.arm(point.strip(), mode=mode, count=count,
                         delay_s=delay, message=f"env:{ENV_VAR}",
                         arg=arg)
            except (ValueError, IndexError):
                logger.warning("ignoring malformed %s entry %r",
                               ENV_VAR, part)

    # -- observation --

    def fires(self, point: str) -> int:
        with self._lock:
            return self._fires.get(point, 0)

    def armed(self, point: str) -> bool:
        with self._lock:
            return point in self._armed

    def arming(self, point: str) -> Optional[dict]:
        """Read-only snapshot of the current arming at `point` (mode,
        count, delay_s, arg, message), or None. For subsystems that
        interpret a fault's spec themselves (netchaos) — reading never
        consumes a fire."""
        with self._lock:
            a = self._armed.get(point)
            return None if a is None else a.snapshot()

    def consume(self, point: str, arg=None) -> Optional[dict]:
        """Book one fire at `point` WITHOUT acting (no raise, no
        sleep, no exit) and return the arming snapshot, or None when
        nothing armed / the arg doesn't match (same matching rule as
        `check`). The netchaos engine uses this to keep count/fires
        accounting canonical while applying the fault's effect on its
        own delivery schedule."""
        with self._lock:
            a = self._armed.get(point)
            if a is None:
                return None
            if a.arg is not None and (arg is None
                                      or str(arg) != a.arg):
                return None
            snap = a.snapshot()
            if a.count is not None:
                a.count -= 1
                if a.count <= 0:
                    del self._armed[point]
            self._fires[point] = self._fires.get(point, 0) + 1
            return snap

    # -- the hot-path hook --

    def check(self, point: str, arg=None) -> None:
        """Fire the fault armed at `point`, if any. Near-free when
        nothing is armed (the production state). `arg` is the call
        site's targeting argument (the per-device points pass the
        full-mesh device index); an arming with an arg fires ONLY on
        a matching check, and never on an arg-less one."""
        if not self._armed:
            return
        with self._lock:
            a = self._armed.get(point)
            if a is None:
                return
            if a.arg is not None and (arg is None
                                      or str(arg) != a.arg):
                return
            if a.mode == "crash" and a.skip > 0:
                a.skip -= 1    # not a fire: the k-th check dies
                return
            if a.count is not None:
                a.count -= 1
                if a.count <= 0:
                    del self._armed[point]
            self._fires[point] = self._fires.get(point, 0) + 1
            mode, delay_s, msg = a.mode, a.delay_s, a.message
            if a.arg is not None:
                msg = f"{msg};arg={a.arg}" if msg else f"arg={a.arg}"
        # act OUTSIDE the lock: a delay fault must not serialize every
        # other fault point behind its sleep
        if mode == "crash":
            # the crash-matrix kill: no cleanup, no atexit — the point
            # is to die exactly like a power loss at this seam
            logger.critical("injected CRASH at %s%s", point,
                            f" ({msg})" if msg else "")
            os._exit(137)
        if mode == "delay":
            # the sanitizer treats an injected stall like a device
            # dispatch: holding any tracked lock across it is a finding
            from fabric_tpu.common import lockcheck
            lockcheck.note_blocking(f"fault-delay:{point}")
            time.sleep(delay_s)
            return
        raise FaultInjected(
            f"injected fault at {point}" + (f" ({msg})" if msg else ""))


_registry = FaultRegistry()

# module-level convenience API (the registry is process-wide state,
# like the bccsp factory singleton)
arm = _registry.arm
disarm = _registry.disarm
clear = _registry.clear
reset = _registry.reset
arm_from_env = _registry.arm_from_env
fires = _registry.fires
armed = _registry.armed
arming = _registry.arming
consume = _registry.consume
check = _registry.check

# chaos runs arm the whole process via env before interpreter start
_registry.arm_from_env()
