"""Process-wide fault-injection registry for chaos testing.

Liveness-critical paths carry NAMED fault points — hooks that are
no-ops in production (an unarmed `check()` is one dict lookup) but can
be armed by tests and chaos runs to raise, stall, or fail-N-times.
The well-known points:

    tpu.dispatch       every device batch dispatch (bccsp/tpu.py)
    tpu.compile        jit pipeline builds / AOT compiles
    tpu.table_persist  warm-table byte writers
    tpu.ed25519        the scheme router's Ed25519 device dispatch —
                       a fault serves the sub-batch on the host
                       reference path, bit-identical (bccsp/tpu.py)
    tpu.bls_aggregate  the staged BLS aggregate-verify path — a fault
                       serves the host reference pairing product
                       (bccsp/tpu.py verify_aggregate)
    tpu.device_lost    per-device point inside the sharded span feeder
                       and the quarantine probe (bccsp/tpu.py
                       _shard_put/_probe_device): checked with
                       arg=<full-mesh device index>, so chaos targets
                       chip k — an error there quarantines THAT chip
                       and the provider rebuilds a smaller mesh over
                       the survivors (common/devicehealth.py)
    tpu.device_straggler
                       same per-device seam, delay mode: the targeted
                       chip's transfer stream stalls, feeding the
                       straggler accounting that quarantines a chip
                       pacing the whole mesh (bccsp/tpu.py)
    raft.step          inbound raft messages (orderer raft chain loop)
    order.propose      the batched propose span of the ordering
                       admission window — a fault demotes the window
                       to per-block sequential proposes
                       (orderer/raft/chain.py)
    deliver.stream     the peer's block-deliver stream
    cluster.pull       onboarding/catch-up block pulls from consenters
    cluster.verify     pulled-span verification (orderer/onboarding.py)
    onboarding.commit  committing a verified pulled block
    commit.validate_ahead  stage A of the commit pipeline — a fault
                       demotes the block to the sequential path
                       (core/commitpipeline.py)
    commit.barrier     the pipeline's drain-before-validate barrier
                       (config blocks, validation-parameter updates)

A new subsystem adds a `check()` call AND declares the point in
`KNOWN_POINTS` below — the canonical registry `tools/ftpu_lint.py`
checks every call-site literal against, and `arm()` warns on unknown
names so a typo'd FTPU_FAULTS entry is loud instead of inert (the
chaos suite would otherwise pass vacuously). Arbitrary names still
ARM (tests of the registry itself use made-up points); they just
warn.

Arming:
  - code:  `faults.arm("tpu.dispatch", mode="error", count=3)`
  - env:   FTPU_FAULTS="tpu.dispatch=error:3;deliver.stream=delay::0.2"
           parsed at import and re-applied by `reset()`, so a chaos CI
           pass (tools/chaos_check.sh) arms a whole pytest run while
           each test still starts from the same armed baseline.

Spec grammar: `point=mode[:count][:delay_s][:arg]`, `mode` in
{error, delay}; empty count = unlimited. A `delay` fault sleeps then
proceeds (a stall, for deadline/breaker testing); an `error` fault
raises FaultInjected. The optional 4th field targets an ARGUMENT: the
fault fires only when the call site's `check(point, arg=...)` matches
it (the per-device points pass the full-mesh device index, so
`tpu.device_lost=error:1::3` kills exactly chip 3); a check without an
arg never matches an arg-targeted arming.

Counts are consumed per fire; `fires(point)` reports how often a point
actually fired (armed or not, a check on an unarmed point counts
nothing — firing means the fault acted).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger("common.faults")

ENV_VAR = "FTPU_FAULTS"


class FaultInjected(RuntimeError):
    """Raised by an armed `error` fault point."""


# The canonical fault-point registry: every `faults.check("...")`
# call-site literal in the tree must appear here (enforced by
# tools/ftpu_lint.py's fault-point rule), and `arm()` warns when an
# unknown name is armed. Keep the docstring table above in sync.
KNOWN_POINTS = frozenset({
    "tpu.dispatch",
    "tpu.compile",
    "tpu.table_persist",
    "tpu.ed25519",
    "tpu.bls_aggregate",
    "tpu.device_lost",
    "tpu.device_straggler",
    "raft.step",
    "order.propose",
    "deliver.stream",
    "cluster.pull",
    "cluster.verify",
    "onboarding.commit",
    "commit.validate_ahead",
    "commit.barrier",
})


@dataclass
class _Arming:
    mode: str                      # "error" | "delay"
    count: Optional[int] = None    # remaining fires; None = unlimited
    delay_s: float = 0.0
    message: str = ""
    arg: Optional[str] = None      # fire only when check(arg=) matches


class FaultRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, _Arming] = {}
        self._fires: dict[str, int] = {}

    # -- arming --

    def arm(self, point: str, mode: str = "error",
            count: Optional[int] = None, delay_s: float = 0.0,
            message: str = "", arg=None) -> None:
        if mode not in ("error", "delay"):
            raise ValueError(f"unknown fault mode {mode!r}")
        if point not in KNOWN_POINTS:
            logger.warning(
                "arming UNKNOWN fault point %r — no check() site "
                "declares it in KNOWN_POINTS (common/faults.py); a "
                "typo'd %s entry injects nothing", point, ENV_VAR)
        with self._lock:
            self._armed[point] = _Arming(
                mode=mode, count=count, delay_s=delay_s,
                message=message,
                arg=None if arg is None else str(arg))
        logger.info("fault point %s armed: mode=%s count=%s "
                    "delay=%.3fs arg=%s", point, mode, count, delay_s,
                    arg)

    def disarm(self, point: str) -> None:
        with self._lock:
            self._armed.pop(point, None)

    def clear(self) -> None:
        """Disarm everything, including env-armed faults."""
        with self._lock:
            self._armed.clear()
            self._fires.clear()

    def reset(self) -> None:
        """Back to the process baseline: clear, then re-apply the
        FTPU_FAULTS env arming (per-test isolation for chaos runs)."""
        self.clear()
        self.arm_from_env()

    def arm_from_env(self, spec: Optional[str] = None) -> None:
        spec = os.environ.get(ENV_VAR, "") if spec is None else spec
        if not spec:
            return
        for part in spec.replace(",", ";").split(";"):
            part = part.strip()
            if not part:
                continue
            try:
                point, _, rhs = part.partition("=")
                fields = rhs.split(":")
                mode = fields[0] or "error"
                count = (int(fields[1])
                         if len(fields) > 1 and fields[1] else None)
                delay = (float(fields[2])
                         if len(fields) > 2 and fields[2] else 0.0)
                arg = (fields[3]
                       if len(fields) > 3 and fields[3] else None)
                self.arm(point.strip(), mode=mode, count=count,
                         delay_s=delay, message=f"env:{ENV_VAR}",
                         arg=arg)
            except (ValueError, IndexError):
                logger.warning("ignoring malformed %s entry %r",
                               ENV_VAR, part)

    # -- observation --

    def fires(self, point: str) -> int:
        with self._lock:
            return self._fires.get(point, 0)

    def armed(self, point: str) -> bool:
        with self._lock:
            return point in self._armed

    # -- the hot-path hook --

    def check(self, point: str, arg=None) -> None:
        """Fire the fault armed at `point`, if any. Near-free when
        nothing is armed (the production state). `arg` is the call
        site's targeting argument (the per-device points pass the
        full-mesh device index); an arming with an arg fires ONLY on
        a matching check, and never on an arg-less one."""
        if not self._armed:
            return
        with self._lock:
            a = self._armed.get(point)
            if a is None:
                return
            if a.arg is not None and (arg is None
                                      or str(arg) != a.arg):
                return
            if a.count is not None:
                a.count -= 1
                if a.count <= 0:
                    del self._armed[point]
            self._fires[point] = self._fires.get(point, 0) + 1
            mode, delay_s, msg = a.mode, a.delay_s, a.message
            if a.arg is not None:
                msg = f"{msg};arg={a.arg}" if msg else f"arg={a.arg}"
        # act OUTSIDE the lock: a delay fault must not serialize every
        # other fault point behind its sleep
        if mode == "delay":
            # the sanitizer treats an injected stall like a device
            # dispatch: holding any tracked lock across it is a finding
            from fabric_tpu.common import lockcheck
            lockcheck.note_blocking(f"fault-delay:{point}")
            time.sleep(delay_s)
            return
        raise FaultInjected(
            f"injected fault at {point}" + (f" ({msg})" if msg else ""))


_registry = FaultRegistry()

# module-level convenience API (the registry is process-wide state,
# like the bccsp factory singleton)
arm = _registry.arm
disarm = _registry.disarm
clear = _registry.clear
reset = _registry.reset
arm_from_env = _registry.arm_from_env
fires = _registry.fires
armed = _registry.armed
check = _registry.check

# chaos runs arm the whole process via env before interpreter start
_registry.arm_from_env()
