"""Overload protection: propagated deadlines + bounded shedding queues.

Round 12. Every hot path in this tree is batched and overlapped
(rounds 6-11), but the stages those rounds chain together — broadcast
ingress → AdmissionWindow → raft event loop → BlockWriteStage →
CommitPipeline — had no shared notion of a deadline, a queue bound, or
a shed policy: sustained over-capacity load meant an indefinitely
blocking `queue.put(...)` in the middle of the pipeline (the broadcast
handler hung forever on a full raft event queue) or unbounded memory.
The committee-consensus measurement in PAPERS.md (arXiv:2302.00418)
shows throughput COLLAPSE at saturation is a consensus-layer failure
mode; a serving system must shed cleanly at the admission edge, not
stall in the middle. This module is that edge, in two pieces:

`Deadline` — a remaining-budget context established once at ingress
(the broadcast stream stamps each envelope with
`Deadline.after(ingress_budget_s())`) and propagated AMBIENTLY down
the calling thread (`with deadline.applied(): ...`): every downstream
wait — the admission-window convoy wait, the raft event enqueue, the
commit-pipeline backpressure wait — bounds itself by
`Deadline.current()` without threading a parameter through every
signature. Nesting takes the minimum (an inner stage can only shrink
the budget, never extend the caller's).

`SheddingQueue` — a bounded inter-stage queue whose blocking `put`
is ALWAYS deadline-aware: it waits for space until the caller's
deadline (or the process-wide `default_enqueue_budget_s()` when the
caller carries none — there is no infinite wait), then SHEDS by
raising `OverloadError`. A shed is a clean, retryable, client-visible
refusal: nothing was enqueued, nothing half-applied; the broadcast
layer maps it to `SERVICE_UNAVAILABLE` (reference Fabric's
overloaded-orderer contract) so well-behaved clients back off and
retry. Every queue self-registers in a process-wide registry so depth
/ shed / wait-time surface as the `overload_*` gauges
(`profiling.publish_overload_stats`) and as the `/healthz`
`components.overload` state.

The policy in one line: BLOCK while the budget lasts (backpressure),
then SHED at the admission edge (graceful degradation) — and never,
ever stall a middle stage forever.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time
import weakref
from typing import Optional

from fabric_tpu.common import tracing

_INGRESS_ENV = "FTPU_INGRESS_BUDGET_S"
_ENQUEUE_ENV = "FTPU_ENQUEUE_BUDGET_S"
_EVENTS_CAP_ENV = "FTPU_RAFT_EVENTS_CAP"

_DEF_INGRESS_S = 30.0
_DEF_ENQUEUE_S = 10.0
_DEF_EVENTS_CAP = 4096

# /healthz reports "shedding" while any queue shed within this window
SHED_HEALTH_WINDOW_S = 30.0

# rolling shed-RATE window (round 19): the controller and /healthz
# need burst-vs-steady, which a lifetime counter cannot give
SHED_RATE_WINDOW_S = 30.0

# round 19: the serving knobs resolve through three layers —
#   dynamic (set by the adaptive controller, bounded by its knob
#   floors/ceilings) > env (the operator's explicit override, which
#   also anchors the controller's bounds) > Operations.Overload.*
#   config > built-in default.
_cfg_lock = threading.Lock()
_config: dict = {"ingress_budget_s": None, "enqueue_budget_s": None,
                 "raft_events_cap": None}
_dynamic: dict = {"ingress_budget_s": None, "enqueue_budget_s": None}


def configure_from_config(cfg) -> None:
    """Lift the env-only serving knobs into `Operations.Overload.*`
    config keys (round 19): `IngressBudgetS`, `EnqueueBudgetS`
    (durations) and `RaftEventsCap` (int). Env remains the override —
    operators and the adaptive controller tune through one seam."""
    ing = cfg.get_duration("Operations.Overload.IngressBudgetS", 0.0)
    enq = cfg.get_duration("Operations.Overload.EnqueueBudgetS", 0.0)
    cap = cfg.get_int("Operations.Overload.RaftEventsCap", 0)
    with _cfg_lock:
        _config["ingress_budget_s"] = ing if ing > 0 else None
        _config["enqueue_budget_s"] = enq if enq > 0 else None
        _config["raft_events_cap"] = cap if cap > 0 else None


def set_dynamic_budget(name: str, value) -> None:
    """The adaptive controller's seam: install (or with None, clear) a
    runtime override for `ingress_budget_s` / `enqueue_budget_s`. The
    controller's knob floor/ceiling — anchored at the statically
    resolved base — bounds what lands here."""
    key = f"{name}_budget_s"
    if key not in _dynamic:
        raise KeyError(f"unknown dynamic budget {name!r}")
    with _cfg_lock:
        _dynamic[key] = float(value) if value is not None else None


def clear_dynamic_budgets() -> None:
    with _cfg_lock:
        for k in _dynamic:
            _dynamic[k] = None


def _env_float(name: str):
    try:
        v = float(os.environ.get(name, ""))
    except ValueError:
        return None
    return v if v > 0 else None


def static_ingress_budget_s() -> float:
    """The configured (pre-controller) ingress budget: env >
    config > default. The adaptive controller anchors its ingress
    knob's ceiling here."""
    v = _env_float(_INGRESS_ENV)
    if v is not None:
        return v
    with _cfg_lock:
        c = _config["ingress_budget_s"]
    return c if c is not None else _DEF_INGRESS_S


def static_enqueue_budget_s() -> float:
    v = _env_float(_ENQUEUE_ENV)
    if v is not None:
        return v
    with _cfg_lock:
        c = _config["enqueue_budget_s"]
    return c if c is not None else _DEF_ENQUEUE_S


def ingress_budget_s() -> float:
    """The per-envelope deadline budget established at broadcast
    ingress (default 30s): the total wall an envelope may spend queued
    across ALL stages before it is shed. Resolution: the adaptive
    controller's dynamic override, else FTPU_INGRESS_BUDGET_S, else
    `Operations.Overload.IngressBudgetS`, else the default."""
    with _cfg_lock:
        d = _dynamic["ingress_budget_s"]
    return d if d is not None else static_ingress_budget_s()


def default_enqueue_budget_s() -> float:
    """The bound for a blocking inter-stage put whose caller carries
    no deadline (default 10s). This is the backstop that closes the
    unbounded-blocking-put class: a put with neither an explicit nor
    an ambient deadline still cannot wait forever. Resolution mirrors
    `ingress_budget_s` (dynamic > FTPU_ENQUEUE_BUDGET_S >
    `Operations.Overload.EnqueueBudgetS` > default)."""
    with _cfg_lock:
        d = _dynamic["enqueue_budget_s"]
    return d if d is not None else static_enqueue_budget_s()


def raft_events_cap() -> int:
    """The per-channel raft event-queue bound (FTPU_RAFT_EVENTS_CAP >
    `Operations.Overload.RaftEventsCap` > 4096). The live queue's
    capacity is additionally a registered adaptive knob — this helper
    only resolves the STARTING bound."""
    try:
        v = int(os.environ.get(_EVENTS_CAP_ENV, "") or 0)
    except ValueError:
        v = 0
    if v > 0:
        return v
    with _cfg_lock:
        c = _config["raft_events_cap"]
    return c if c is not None else _DEF_EVENTS_CAP


class ShedRateWindow:
    """Rolling shed-rate reading: sheds per second over the trailing
    `window_s`. The lifetime `sheds` counter answers "has this stage
    EVER shed"; the controller and /healthz need "is it shedding NOW"
    — burst vs steady. Clock-injectable for deterministic tests."""

    __slots__ = ("window_s", "_clock", "_stamps", "_lock")

    def __init__(self, window_s: float = SHED_RATE_WINDOW_S,
                 clock=time.monotonic):
        self.window_s = float(window_s)
        self._clock = clock
        self._stamps: "list[float]" = []
        self._lock = threading.Lock()

    def note(self) -> None:
        now = self._clock()
        with self._lock:
            self._stamps.append(now)
            self._trim(now)

    def rate(self) -> float:
        now = self._clock()
        with self._lock:
            self._trim(now)
            return len(self._stamps) / self.window_s

    def _trim(self, now: float) -> None:
        horizon = now - self.window_s
        stamps = self._stamps
        i = 0
        while i < len(stamps) and stamps[i] < horizon:
            i += 1
        if i:
            del stamps[:i]


class OverloadError(Exception):
    """A stage could not accept work within the deadline budget and
    shed it. Retryable by contract: nothing was enqueued or applied —
    the broadcast layer surfaces it as SERVICE_UNAVAILABLE, cluster
    RPC as a SERVICE_UNAVAILABLE SubmitResponse, and internal feeders
    simply retry the same item."""

    def __init__(self, stage: str, info: str = ""):
        super().__init__(
            f"overloaded at {stage}: work shed"
            + (f" ({info})" if info else "")
            + " — retry with backoff")
        self.stage = stage


_tls = threading.local()


class Deadline:
    """An absolute expiry on the monotonic clock, carried down the
    calling thread. Immutable; `applied()` installs it as the ambient
    deadline (nesting takes the min) for the duration of a block."""

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float):
        self.expires_at = float(expires_at)

    @classmethod
    def after(cls, budget_s: float) -> "Deadline":
        return cls(time.monotonic() + float(budget_s))

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def applied(self):
        """Context manager: make this the calling thread's ambient
        deadline. An already-tighter ambient deadline wins (a nested
        stage can shrink the caller's budget, never extend it)."""
        return _Applied(self)

    @classmethod
    def current(cls) -> Optional["Deadline"]:
        return getattr(_tls, "deadline", None)

    @classmethod
    def remaining_or(cls, default: Optional[float]) -> Optional[float]:
        """The ambient deadline's remaining budget, or `default` when
        the thread carries none. A caller bounding a wait writes
        `timeout = Deadline.remaining_or(fallback_budget)`."""
        d = cls.current()
        return default if d is None else d.remaining()

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


class _Applied:
    __slots__ = ("_deadline", "_prior")

    def __init__(self, deadline: Deadline):
        self._deadline = deadline
        self._prior = None

    def __enter__(self) -> Deadline:
        self._prior = Deadline.current()
        eff = self._deadline
        if self._prior is not None and \
                self._prior.expires_at < eff.expires_at:
            eff = self._prior
        _tls.deadline = eff
        return eff

    def __exit__(self, *exc) -> None:
        _tls.deadline = self._prior


# ---------------------------------------------------------------------------
# the process-wide queue registry (gauges + /healthz read through it)
# ---------------------------------------------------------------------------

_reg_lock = threading.Lock()
_stages: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def register_stage(name: str, obj) -> None:
    """Register any object exposing `overload_stats() -> dict` (depth,
    capacity, sheds, puts, wait_s, last_wait_s, last_shed_t) under a
    stage name. SheddingQueue self-registers; BlockWriteStage and
    CommitPipeline register adapters. Weakly held: a halted channel's
    queues drop out of the gauges on collection; a re-created stage of
    the same name simply replaces the entry."""
    with _reg_lock:
        _stages[name] = obj


def unregister_stage(name: str, obj=None) -> None:
    with _reg_lock:
        if obj is None or _stages.get(name) is obj:
            _stages.pop(name, None)


def stage_stats() -> dict:
    """Snapshot of every live stage's overload readings, keyed by
    stage name — the source for `overload_*` gauges, /healthz and the
    soak rig's bounded-depth assertions."""
    with _reg_lock:
        items = list(_stages.items())
    out = {}
    for name, obj in items:
        try:
            out[name] = dict(obj.overload_stats())
        except Exception:   # noqa: BLE001 — one dead stage must not hide the rest
            continue
    return out


def total_sheds() -> int:
    return sum(int(s.get("sheds", 0)) for s in stage_stats().values())


def health() -> str:
    """/healthz `components.overload` state: `ok`, or
    `shedding:<stage,...>` while any stage shed work within the last
    SHED_HEALTH_WINDOW_S — degraded-but-serving, like the bccsp
    breaker (a shedding orderer is doing its job, not failing)."""
    now = time.monotonic()
    shedding = sorted(
        name for name, s in stage_stats().items()
        if s.get("last_shed_t") is not None
        and now - s["last_shed_t"] <= SHED_HEALTH_WINDOW_S)
    if shedding:
        return "shedding:" + ",".join(shedding)
    return "ok"


# ---------------------------------------------------------------------------
# the bounded inter-stage queue
# ---------------------------------------------------------------------------

class SheddingQueue:
    """Bounded queue whose blocking `put` is deadline-aware and whose
    overflow policy is SHED, not stall.

    Consumer-side API is `queue.Queue`-compatible (`get(timeout=)`,
    `get_nowait()` raising `queue.Empty`) so a drain loop swaps in
    without changes. Producer-side:

      put(item)            wait for space until the caller's deadline
                           (ambient `Deadline.current()` unless an
                           explicit one is passed), else the queue's
                           `default_budget_s`; on expiry count a shed
                           and raise OverloadError. There is NO
                           unbounded mode.
      put_forced(item)     bypass the bound (control items only:
                           shutdown sentinels, shed markers that must
                           hold a response slot). Never sheds, never
                           blocks.
      put_drop_oldest(item) gossip's loss-tolerant policy: on Full,
                           drop the OLDEST entry (counted as a shed)
                           to admit the new one.
    """

    def __init__(self, name: str, maxsize: int,
                 default_budget_s: Optional[float] = None,
                 register: bool = True):
        if maxsize <= 0:
            raise ValueError("SheddingQueue needs a positive bound "
                             "(unbounded queues are the failure mode "
                             "this class exists to remove)")
        self.name = name
        self.maxsize = maxsize
        self._default_budget_s = default_budget_s
        # ftpu-lint: allow-unbounded-queue(the bound is enforced by
        # put()/offer()/put_drop_oldest above the inner queue, because
        # put_forced — control sentinels and shed markers — must be
        # able to exceed it; this class IS the bounded replacement the
        # rule points everyone else at)
        self._q: _queue.Queue = _queue.Queue()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self.stats = {
            "puts": 0, "sheds": 0, "drops": 0, "forced": 0,
            "max_depth": 0, "wait_s": 0.0, "last_wait_s": 0.0,
        }
        self._last_shed_t: Optional[float] = None
        self._shed_rate = ShedRateWindow()
        if register:
            register_stage(name, self)

    def _account_shed(self) -> None:
        # callers hold self._not_full
        self.stats["sheds"] += 1
        self._last_shed_t = time.monotonic()
        self._shed_rate.note()
        tracing.note_shed(self.name)

    # -- producer side --

    def _budget_s(self, budget_s: Optional[float]) -> float:
        if budget_s is not None:
            return budget_s
        d = Deadline.current()
        if d is not None:
            return d.remaining()
        if self._default_budget_s is not None:
            return self._default_budget_s
        return default_enqueue_budget_s()

    def put(self, item, deadline: Optional[Deadline] = None,
            budget_s: Optional[float] = None) -> None:
        """Deadline-aware admission. Priority: explicit `deadline`,
        then explicit `budget_s`, then the ambient `Deadline.current()`,
        then the queue's default budget, then the process-wide
        `default_enqueue_budget_s()` — the wait is ALWAYS finite."""
        if deadline is not None:
            budget = deadline.remaining()
        else:
            budget = self._budget_s(budget_s)
        t0 = time.monotonic()
        expires = t0 + max(0.0, budget)
        with self._not_full:
            while self._q.qsize() >= self.maxsize:
                remaining = expires - time.monotonic()
                if remaining <= 0:
                    self._account_shed()
                    raise OverloadError(
                        self.name,
                        f"queue full at {self.maxsize} for "
                        f"{max(0.0, budget):.3f}s")
                self._not_full.wait(timeout=remaining)
            self._q.put_nowait(item)
            self._account_put(t0)

    def offer(self, item, count_shed: bool = True) -> bool:
        """Non-blocking, non-raising admission: True if enqueued,
        False if full. A refusal counts as a shed unless the caller
        says otherwise (`count_shed=False` for INTERNAL traffic like
        raft step messages, whose loss is a protocol concern —
        retransmission recovers it — not a client-visible refusal;
        those land in the `drops` stat instead so sheds_total keeps
        meaning what its help text says)."""
        with self._not_full:
            if self._q.qsize() >= self.maxsize:
                if count_shed:
                    self._account_shed()
                else:
                    self.stats["drops"] += 1
                return False
            self._q.put_nowait(item)
            self._account_put(time.monotonic())
            return True

    def note_drop(self) -> None:
        """Account an INTERNAL message dropped by the caller without
        entering the queue (e.g. a flooded control-plane lane) — lands
        in `drops`, never `sheds`."""
        with self._not_full:
            self.stats["drops"] += 1

    def put_nowait(self, item) -> None:
        """queue.Queue-compatible spelling: raises `queue.Full` when
        at the bound (counted as a shed) — for call sites that already
        carry a Full handler."""
        if not self.offer(item):
            raise _queue.Full

    def put_forced(self, item) -> None:
        """Bound-exempt enqueue for CONTROL items: shutdown sentinels
        and shed markers (which replace a real item and must hold its
        response slot). Using this for payload would defeat the queue;
        the `forced` stat keeps that visible."""
        with self._not_full:
            self._q.put_nowait(item)
            self.stats["forced"] += 1
            depth = self._q.qsize()
            if depth > self.stats["max_depth"]:
                self.stats["max_depth"] = depth

    def put_drop_oldest(self, item) -> int:
        """Admit `item`, evicting the oldest entry if full (the evicted
        entry counts as a shed). Returns how many entries were dropped
        (0 normally, 1 on eviction). Gossip's policy: stale gossip is
        worthless, fresh is not."""
        dropped = 0
        with self._not_full:
            while self._q.qsize() >= self.maxsize:
                try:
                    self._q.get_nowait()
                except _queue.Empty:
                    break
                dropped += 1
                self._account_shed()
            self._q.put_nowait(item)
            self._account_put(time.monotonic())
        return dropped

    def _account_put(self, t0: float) -> None:
        wait = time.monotonic() - t0
        self.stats["puts"] += 1
        self.stats["wait_s"] += wait
        self.stats["last_wait_s"] = wait
        depth = self._q.qsize()
        if depth > self.stats["max_depth"]:
            self.stats["max_depth"] = depth

    # -- consumer side (queue.Queue-compatible) --

    def get(self, block: bool = True, timeout: Optional[float] = None):
        item = self._q.get(block=block, timeout=timeout)
        with self._not_full:
            self._not_full.notify()
        return item

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    # -- observability --

    def overload_stats(self) -> dict:
        out = dict(self.stats)
        out["depth"] = self._q.qsize()
        out["capacity"] = self.maxsize
        out["last_shed_t"] = self._last_shed_t
        out["shed_rate"] = self._shed_rate.rate()
        return out
