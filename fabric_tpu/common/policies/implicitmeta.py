"""ImplicitMeta policies: ANY/ALL/MAJORITY over named sub-policies.

Rebuild of `common/policies/implicitmeta.go:69,107`: the policy holds a
sub-policy NAME; at evaluation it fetches that policy from each child
manager and requires the threshold number of children to pass. Used for
the standard channel policies (Readers/Writers/Admins at every level).
"""

from __future__ import annotations

import logging
from typing import Sequence

from fabric_tpu.protos import policies as polpb
from fabric_tpu.common.policies import policy as papi

logger = logging.getLogger("policies.implicitmeta")


class ImplicitMetaPolicy(papi.Policy):
    def __init__(self, meta: polpb.ImplicitMetaPolicy,
                 sub_policies: Sequence[papi.Policy],
                 converter=None):
        """`converter` = (identity_deserializer, csp); when given,
        signature sets are turned into valid identities once, with one
        batched verify, before fan-out to the children."""
        self._sub_policy_name = meta.sub_policy
        self._subs = list(sub_policies)
        self._converter = converter
        n = len(self._subs)
        if meta.rule == polpb.ImplicitMetaPolicy.ANY:
            # threshold stays 1 even with zero children: an ANY over
            # nothing must fail closed (reference implicitmeta.go:69)
            self._threshold = 1
        elif meta.rule == polpb.ImplicitMetaPolicy.ALL:
            self._threshold = n
        elif meta.rule == polpb.ImplicitMetaPolicy.MAJORITY:
            self._threshold = n // 2 + 1
        else:
            raise ValueError(f"unknown implicit-meta rule {meta.rule}")

    @classmethod
    def from_managers(cls, meta: polpb.ImplicitMetaPolicy,
                      managers: Sequence[papi.Manager],
                      converter=None) -> "ImplicitMetaPolicy":
        """Collect `meta.sub_policy` from each org manager that defines
        it (reference: NewPolicy gathers from all child managers)."""
        subs = []
        for m in managers:
            try:
                subs.append(m.get_policy(meta.sub_policy))
            except papi.PolicyError:
                logger.debug("manager %s lacks sub-policy %s",
                             m.name, meta.sub_policy)
        return cls(meta, subs, converter=converter)

    def _evaluate(self, fn_name: str, arg) -> None:
        satisfied = 0
        errors = []
        for sub in self._subs:
            try:
                getattr(sub, fn_name)(arg)
                satisfied += 1
            except papi.PolicyError as e:
                errors.append(str(e))
            if satisfied >= self._threshold:
                return
        if satisfied >= self._threshold:
            # e.g. ALL over zero children passes vacuously (reference
            # implicitmeta.go returns nil when remaining == 0)
            return
        raise papi.PolicyError(
            f"implicit-meta {self._sub_policy_name!r}: {satisfied} of "
            f"{len(self._subs)} sub-policies satisfied, "
            f"needed {self._threshold}: {errors[:3]}")

    def evaluate_signed_data(self, signed_data) -> None:
        if self._converter is not None:
            # convert the signature set to valid identities ONCE — one
            # batched verify dispatch — instead of once per child
            deserializer, csp = self._converter
            identities = papi.signature_set_to_valid_identities(
                signed_data, deserializer, csp)
            self._evaluate("evaluate_identities", identities)
        else:
            self._evaluate("evaluate_signed_data", signed_data)

    def evaluate_identities(self, identities) -> None:
        self._evaluate("evaluate_identities", identities)

    def prepare(self, signed_data):
        """Two-phase evaluation (see `SignaturePolicy.prepare`): the
        signature set is converted to identities once; `finish(ok)`
        fans the surviving identities out to the children. Requires the
        converter (bundle-compiled policies always have one)."""
        if self._converter is None:
            raise papi.PolicyError(
                "implicit-meta policy lacks identity converter; "
                "two-phase evaluation unavailable")
        deserializer, _csp = self._converter
        prepared = papi.prepare_signature_set(signed_data, deserializer)
        policy = self

        class _Prepared:
            items = prepared.items

            @staticmethod
            def finish(ok) -> None:
                policy.evaluate_identities(prepared.finish(ok))

        return _Prepared()
