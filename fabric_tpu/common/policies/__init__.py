from fabric_tpu.common.policies.policy import (
    Manager,
    Policy,
    PolicyError,
    signature_set_to_valid_identities,
)
from fabric_tpu.common.policies.cauthdsl import SignaturePolicy
from fabric_tpu.common.policies.implicitmeta import ImplicitMetaPolicy
from fabric_tpu.common.policies.policydsl import from_string

__all__ = [
    "Manager", "Policy", "PolicyError",
    "signature_set_to_valid_identities", "SignaturePolicy",
    "ImplicitMetaPolicy", "from_string",
]
