"""Signature-policy compiler and evaluator.

Rebuild of `common/cauthdsl/{cauthdsl.go,policy.go}`: compile a
SignaturePolicyEnvelope (NOutOf/SignedBy tree over MSPPrincipals) into
a closure over a list of identities, and wrap it as a `policies.Policy`
that first turns a signature set into valid identities — via the
batched verifier — then runs pure principal matching (no crypto in the
tree walk, exactly like the reference's compiled evaluators).
"""

from __future__ import annotations

import logging
from typing import Callable, Sequence

from fabric_tpu.protos import policies as polpb
from fabric_tpu.common.policies import policy as papi

logger = logging.getLogger("cauthdsl")


def compile_rule(rule: polpb.SignaturePolicy,
                 principals: Sequence[polpb.MSPPrincipal]
                 ) -> Callable[[Sequence, list[bool]], bool]:
    """Reference: `common/cauthdsl/cauthdsl.go:24-92` compile — returns
    evaluator(identities, used) -> bool. `used` prevents one identity
    from satisfying two SignedBy leaves (same semantics as the
    reference's `used` vector)."""
    which = rule.WhichOneof("type")
    if which == "signed_by":
        idx = rule.signed_by
        if idx < 0 or idx >= len(principals):
            raise ValueError(f"signed_by index {idx} out of range")
        principal = principals[idx]

        def eval_signed_by(identities, used):
            for i, ident in enumerate(identities):
                if used[i]:
                    continue
                try:
                    ident.satisfies_principal(principal)
                except Exception:
                    continue
                used[i] = True
                return True
            return False
        return eval_signed_by

    if which == "n_out_of":
        n = rule.n_out_of.n
        children = [compile_rule(r, principals)
                    for r in rule.n_out_of.rules]
        if n < 1 or n > len(children):
            # n == 0 would be always-satisfied (fail-open); reject it at
            # compile time even though the reference compiles it silently
            raise ValueError(f"asked for {n} of {len(children)} sub-rules")

        def eval_n_out_of(identities, used):
            # like the reference, children snapshot `used` so a failed
            # child doesn't consume identities
            satisfied = 0
            for child in children:
                snapshot = list(used)
                if child(identities, used):
                    satisfied += 1
                else:
                    used[:] = snapshot
                if satisfied >= n:
                    return True
            return satisfied >= n
        return eval_n_out_of

    raise ValueError(f"unknown signature policy node {which!r}")


class SignaturePolicy(papi.Policy):
    """An evaluatable signature policy (reference:
    `common/cauthdsl/policy.go:86-108`)."""

    def __init__(self, envelope: polpb.SignaturePolicyEnvelope,
                 deserializer, csp):
        if envelope.version != 0:
            raise ValueError(
                f"unsupported policy version {envelope.version}")
        self._envelope = envelope
        self._eval = compile_rule(envelope.rule, list(envelope.identities))
        self._deserializer = deserializer
        self._csp = csp

    @classmethod
    def from_bytes(cls, raw: bytes, deserializer, csp) -> "SignaturePolicy":
        env = polpb.SignaturePolicyEnvelope()
        env.ParseFromString(raw)
        return cls(env, deserializer, csp)

    def evaluate_signed_data(self, signed_data) -> None:
        identities = papi.signature_set_to_valid_identities(
            signed_data, self._deserializer, self._csp)
        self.evaluate_identities(identities)

    def prepare(self, signed_data) -> "PreparedPolicyEval":
        """Two-phase evaluation for block-scope batching: returns the
        pending VerifyItems; the caller batches them (typically together
        with every other signature set in the block), then calls
        `.finish(ok_flags)` which raises PolicyError exactly as
        `evaluate_signed_data` would."""
        prepared = papi.prepare_signature_set(
            signed_data, self._deserializer)
        return PreparedPolicyEval(self, prepared)

    def evaluate_identities(self, identities) -> None:
        used = [False] * len(identities)
        if not self._eval(identities, used):
            raise papi.PolicyError(
                "signature set did not satisfy policy")


class PreparedPolicyEval:
    """Deferred `SignaturePolicy.evaluate_signed_data`: identities are
    deserialized, signatures not yet verified."""

    def __init__(self, policy: SignaturePolicy,
                 prepared: papi.PreparedSignatureSet):
        self._policy = policy
        self._prepared = prepared

    @property
    def items(self):
        return self._prepared.items

    def finish(self, ok) -> None:
        self._policy.evaluate_identities(self._prepared.finish(ok))
